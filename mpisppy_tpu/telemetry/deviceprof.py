###############################################################################
# Device-trace ingestion (ISSUE 7 tentpole, part 1; docs/telemetry.md).
#
# ProfilerSession (profiler.py) and bench.py's jax.profiler.trace write
# TensorBoard-layout captures:
#
#   <profile_dir>/plugins/profile/<YYYY_MM_DD_HH_MM_SS>/
#       <host>.trace.json.gz      chrome-trace event list
#       <host>.xplane.pb          raw XSpace protobuf (richer stats)
#
# This module turns a capture into a typed DEVICE timeline — per-kernel
# device durations, DMA (HBM<->VMEM / host copy) in-flight spans,
# step/host annotations — with two stdlib-only readers:
#
#   * the chrome trace.json.gz (gzip+json) is the primary input: every
#     device op arrives with ts/dur and XLA's `bytes_accessed` /
#     `hlo_category` args;
#   * the sibling .xplane.pb, WHEN PRESENT, is read by a hand-rolled
#     protobuf wire-format walker (no tensorflow, no protobuf runtime —
#     varint/length-delimited decoding is ~40 lines) to recover what
#     the json converter drops: the per-op `memory_access_breakdown`
#     (bytes split by memory space — space 1 is HBM, space 3 on-chip
#     VMEM on the v5e captures this repo commits), per-op `flops`, and
#     the device's own `peak_hbm_bw_gigabytes_per_second` /
#     `peak_teraflops_per_second` plane stats.
#
# Why both: `bytes_accessed` alone counts VMEM-resident reuse (ops at
# S=10k appear to "stream" 2+ TB/s, far over the 819 GB/s HBM roofline),
# so honest roofline attribution (telemetry/roofline.py) needs the
# HBM-space split whenever the xplane sidecar survives.  Captures are
# committed with both files; the json-only path stays supported for
# trimmed fixtures and foreign traces.
###############################################################################
from __future__ import annotations

import dataclasses
import gzip
import json
import os
import re
import struct

DEVICE_PROCESS_PREFIX = "/device:"
HOST_PROCESS_PREFIX = "/host:"

#: chrome-trace thread names the profiler gives device lines
OPS_LINE = "XLA Ops"
MODULES_LINE = "XLA Modules"
STEPS_LINE = "Steps"
ASYNC_LINE = "Async XLA Ops"

#: hlo_category values that are CONTAINER shells: their interval spans
#: their children (also listed), so byte/time sums must exclude them
CONTAINER_CATEGORIES = frozenset({"while", "conditional", "call"})

#: async-DMA bookkeeping categories: the -start op queues the transfer
#: (~ns duration), the -done op is the completion fence; the transfer
#: itself is IN FLIGHT between them, concurrent with whatever executes
DMA_START_CATEGORIES = frozenset({"copy-start", "async-start",
                                  "send", "collective-permute-start"})
DMA_DONE_CATEGORIES = frozenset({"copy-done", "async-done",
                                 "recv-done", "collective-permute-done"})
DMA_CATEGORIES = DMA_START_CATEGORIES | DMA_DONE_CATEGORIES

_DMA_START_RE = re.compile(r"^(.*)-start(\.\d+)?$")


@dataclasses.dataclass(frozen=True)
class DeviceOp:
    """One executed device op (one chrome-trace X event)."""

    name: str
    category: str
    start_us: float
    dur_us: float
    bytes_accessed: int = 0        # all memory spaces (XLA cost model)
    hbm_bytes: int | None = None   # space-1 bytes (xplane sidecar only)
    onchip_bytes: int | None = None
    flops: int | None = None

    @property
    def end_us(self) -> float:
        return self.start_us + self.dur_us


@dataclasses.dataclass(frozen=True)
class DmaSpan:
    """One async transfer, from its -start op to its -done fence."""

    name: str
    start_us: float
    end_us: float
    bytes: int = 0
    hbm_bytes: int | None = None

    @property
    def dur_us(self) -> float:
        return self.end_us - self.start_us


@dataclasses.dataclass(frozen=True)
class StepMarker:
    """A StepTraceAnnotation span (profiler.step): one wheel iteration
    as seen by the device.  `step_num` is the hub_iter the wheel passed
    in — the join key back to the JSONL host trace."""

    name: str
    start_us: float
    dur_us: float
    step_num: int | None = None


@dataclasses.dataclass(frozen=True)
class HostSpan:
    """A named host-thread span (TraceAnnotation / python tracer)."""

    name: str
    start_us: float
    dur_us: float


@dataclasses.dataclass
class DeviceTimeline:
    """Typed model of one capture."""

    trace_path: str
    xplane_path: str | None = None
    device_name: str = ""
    modules: list = dataclasses.field(default_factory=list)
    ops: list = dataclasses.field(default_factory=list)
    dma: list = dataclasses.field(default_factory=list)
    steps: list = dataclasses.field(default_factory=list)
    host_spans: list = dataclasses.field(default_factory=list)
    peak_hbm_gbps: float | None = None
    peak_tflops: float | None = None

    @property
    def has_memory_spaces(self) -> bool:
        return any(op.hbm_bytes is not None for op in self.ops)


# ---------------------------------------------------------------------------
# capture discovery
# ---------------------------------------------------------------------------
def discover_captures(profile_dir: str) -> list[dict]:
    """All captures under a --profile-dir, oldest -> newest.  Each entry
    is {"dir", "trace", "xplane"(or None)}.  Accepts the profile root,
    a single capture dir, or a trace.json.gz path directly."""
    if os.path.isfile(profile_dir):
        d = os.path.dirname(profile_dir)
        return [{"dir": d, "trace": profile_dir,
                 "xplane": _sibling_xplane(profile_dir)}]
    roots = [os.path.join(profile_dir, "plugins", "profile"), profile_dir]
    caps: list[dict] = []
    for root in roots:
        if not os.path.isdir(root):
            continue
        for sub in sorted(os.listdir(root)):
            d = os.path.join(root, sub)
            if not os.path.isdir(d):
                continue
            traces = sorted(f for f in os.listdir(d)
                            if f.endswith(".trace.json.gz")
                            or f.endswith(".trace.json"))
            if traces:
                t = os.path.join(d, traces[0])
                caps.append({"dir": d, "trace": t,
                             "xplane": _sibling_xplane(t)})
        if caps:
            break
        # the profile root may itself hold a capture's files
        traces = sorted(f for f in os.listdir(root)
                        if f.endswith(".trace.json.gz")
                        or f.endswith(".trace.json"))
        if traces:
            t = os.path.join(root, traces[0])
            caps.append({"dir": root, "trace": t,
                         "xplane": _sibling_xplane(t)})
            break
    # timestamped dir names (YYYY_MM_DD_HH_MM_SS) sort chronologically
    return caps


def newest_capture(profile_dir: str) -> dict | None:
    caps = discover_captures(profile_dir)
    return caps[-1] if caps else None


def _sibling_xplane(trace_path: str) -> str | None:
    base = trace_path
    for suf in (".trace.json.gz", ".trace.json"):
        if base.endswith(suf):
            base = base[:-len(suf)]
            break
    xp = base + ".xplane.pb"
    return xp if os.path.isfile(xp) else None


# ---------------------------------------------------------------------------
# chrome trace reader (primary)
# ---------------------------------------------------------------------------
def load_chrome_trace(path: str) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def build_timeline(capture, xplane: str | None = None) -> DeviceTimeline:
    """Capture -> DeviceTimeline.  `capture` is a discover_captures()
    entry, a capture dir / profile root, or a trace path."""
    if isinstance(capture, str):
        cap = newest_capture(capture)
        if cap is None:
            raise ValueError(f"no trace.json.gz capture under {capture!r}")
        capture = cap
    trace_path = capture["trace"]
    xplane = xplane if xplane is not None else capture.get("xplane")
    raw = load_chrome_trace(trace_path)
    events = raw.get("traceEvents", raw if isinstance(raw, list) else [])
    pnames: dict = {}
    tnames: dict = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pnames[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tnames[(e.get("pid"), e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    tl = DeviceTimeline(trace_path=trace_path, xplane_path=xplane)
    dev_pids = {p for p, n in pnames.items()
                if n.startswith(DEVICE_PROCESS_PREFIX)}
    if dev_pids:
        tl.device_name = pnames[sorted(dev_pids)[0]]
    side = _read_xplane_sidecar(xplane) if xplane else None
    if side:
        tl.peak_hbm_gbps = side.get("peak_hbm_gbps")
        tl.peak_tflops = side.get("peak_tflops")
    stats = (side or {}).get("ops", {})
    raw_ops: list[DeviceOp] = []
    for e in events:
        if e.get("ph") != "X":
            continue
        pid, tid = e.get("pid"), e.get("tid")
        line = tnames.get((pid, tid), "")
        name = e.get("name", "")
        ts = float(e.get("ts", 0.0))
        dur = float(e.get("dur", 0.0))
        if pid in dev_pids:
            if line == MODULES_LINE:
                tl.modules.append(DeviceOp(
                    name=name, category="module", start_us=ts,
                    dur_us=dur))
            elif line == STEPS_LINE:
                tl.steps.append(StepMarker(
                    name=name, start_us=ts, dur_us=dur,
                    step_num=_step_num(e)))
            elif line == ASYNC_LINE:
                # in-flight transfer spans straight from the profiler
                a = e.get("args", {})
                st = stats.get(name)
                tl.dma.append(DmaSpan(
                    name=name, start_us=ts, end_us=ts + dur,
                    bytes=_int_arg(a, "bytes_accessed"),
                    hbm_bytes=st.hbm_bytes if st else None))
            elif line == OPS_LINE or not line:
                a = e.get("args", {})
                st = stats.get(name)
                raw_ops.append(DeviceOp(
                    name=name,
                    category=a.get("hlo_category", "?"),
                    start_us=ts, dur_us=dur,
                    bytes_accessed=_int_arg(a, "bytes_accessed"),
                    hbm_bytes=st.hbm_bytes if st else None,
                    onchip_bytes=st.onchip_bytes if st else None,
                    flops=st.flops if st else None))
        elif pnames.get(pid, "").startswith(HOST_PROCESS_PREFIX):
            tl.host_spans.append(HostSpan(name=name, start_us=ts,
                                          dur_us=dur))
    tl.ops = sorted(raw_ops, key=lambda o: o.start_us)
    if not tl.dma:
        tl.dma = _pair_dma(tl.ops)
    tl.dma.sort(key=lambda d: d.start_us)
    tl.modules.sort(key=lambda m: m.start_us)
    tl.steps.sort(key=lambda s: s.start_us)
    return tl


def _int_arg(args: dict, key: str) -> int:
    try:
        return int(args.get(key, 0) or 0)
    except (TypeError, ValueError):
        return 0


def _step_num(e: dict) -> int | None:
    a = e.get("args", {})
    for key in ("step_num", "group_id"):
        if key in a:
            try:
                return int(a[key])
            except (TypeError, ValueError):
                pass
    m = re.search(r"(\d+)$", e.get("name", ""))
    return int(m.group(1)) if m else None


def _pair_dma(ops: list) -> list:
    """Fallback DMA spans from the ops line: match each `X-done.N`
    fence to its `X-start.N` queue op (FIFO per name when an op
    executes repeatedly inside a loop)."""
    starts: dict[str, list] = {}
    for op in ops:
        if op.category in DMA_START_CATEGORIES \
                and _DMA_START_RE.match(op.name):
            starts.setdefault(op.name, []).append(op)
    spans = []
    for op in ops:
        if op.category not in DMA_DONE_CATEGORIES:
            continue
        sname = op.name.replace("-done", "-start")
        queue = starts.get(sname)
        if not queue:
            continue
        cand = [s for s in queue if s.start_us <= op.start_us]
        if not cand:
            continue
        s = cand[0]     # FIFO: transfers complete in issue order
        queue.remove(s)
        spans.append(DmaSpan(name=sname, start_us=s.start_us,
                             end_us=op.end_us,
                             bytes=s.bytes_accessed,
                             hbm_bytes=s.hbm_bytes))
    return spans


# ---------------------------------------------------------------------------
# xplane sidecar reader — stdlib protobuf wire-format walker
# ---------------------------------------------------------------------------
# Message shapes used (tensorflow/profiler xplane.proto, stable since
# 2020; decoded schemalessly so a missing field degrades to None):
#   XSpace.planes = 1
#   XPlane: id=1 name=2 lines=3 event_metadata=4(map) stat_metadata=5(map)
#           stats=6
#   XLine: id=1 name=2 events=4 (timestamps also at 6/7 — unused here)
#   XEvent: metadata_id=1 offset_ps=2 duration_ps=3 stats=4
#   XEventMetadata: id=1 name=2 display_name=4 stats=5
#   XStatMetadata: id=1 name=2
#   XStat: metadata_id=1 double=2 uint64=3 int64=4 str=5 bytes=6 ref=7
#   memory_access_breakdown bytes payload: repeated MemoryAccessed=1
#     {operation_type=1 memory_space=2 bytes_accessed=3}

#: memory_access_breakdown space id observed to be HBM on v5e captures
#: (space 3 is on-chip; see module docstring)
HBM_MEMORY_SPACE = 1


@dataclasses.dataclass(frozen=True)
class _OpStats:
    hbm_bytes: int | None = None
    onchip_bytes: int | None = None
    flops: int | None = None


def _read_varint(buf: bytes, i: int) -> tuple[int, int]:
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not (b & 0x80):
            return r, i
        s += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) triples of one message.
    Raises on malformed input — callers treat that as 'no sidecar'."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            if i + ln > n:
                raise ValueError("truncated length-delimited field")
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            if i + 4 > n:
                raise ValueError("truncated fixed32 field")
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            if i + 8 > n:
                raise ValueError("truncated fixed64 field")
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


def _first(fs, fn, default=None):
    for f, _, v in fs:
        if f == fn:
            return v
    return default


def _stat_value(sf):
    """XStat -> python value (double/uint64/int64/str/bytes)."""
    for f, wt, v in sf:
        if f == 2 and wt == 1:
            return struct.unpack("<d", v)[0]
        if f == 3 and wt == 0:
            return v
        if f == 4 and wt == 0:
            # int64 varints are two's-complement over 64 bits
            return v - (1 << 64) if v >= (1 << 63) else v
        if f == 5 and wt == 2:
            return v.decode("utf-8", "replace")
        if f == 6 and wt == 2:
            return v
    return None


def _short_name(em_fields) -> str:
    disp = _first(em_fields, 4)
    if isinstance(disp, bytes) and disp:
        return disp.decode("utf-8", "replace")
    nm = _first(em_fields, 2, b"")
    nm = nm.decode("utf-8", "replace") if isinstance(nm, bytes) else ""
    m = re.match(r"%?(\S+)\s*=", nm)
    return m.group(1) if m else nm


def _read_xplane_sidecar(path: str) -> dict | None:
    """xplane.pb -> {"ops": {name: _OpStats}, "peak_hbm_gbps",
    "peak_tflops"} for the first device plane, or None when the file is
    unreadable/malformed (the json-only path takes over)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
        planes = [v for fn, wt, v in _fields(data) if fn == 1 and wt == 2]
        for plane in planes:
            pf = list(_fields(plane))
            name = _first(pf, 2, b"").decode("utf-8", "replace")
            if not name.startswith(DEVICE_PROCESS_PREFIX):
                continue
            return _parse_device_plane(pf)
        return None
    except (OSError, ValueError, IndexError):
        return None


def _parse_device_plane(pf) -> dict:
    stat_names: dict[int, str] = {}
    for fn, wt, v in pf:
        if fn == 5 and wt == 2:
            ent = list(_fields(v))
            val = _first(ent, 2)
            if isinstance(val, bytes):
                sm = list(_fields(val))
                sid = _first(ent, 1, _first(sm, 1))
                nm = _first(sm, 2, b"?")
                stat_names[sid] = nm.decode("utf-8", "replace") \
                    if isinstance(nm, bytes) else str(nm)
    out: dict = {"ops": {}, "peak_hbm_gbps": None, "peak_tflops": None}
    for fn, wt, v in pf:   # plane-level stats: the device's own peaks
        if fn == 6 and wt == 2:
            sf = list(_fields(v))
            sn = stat_names.get(_first(sf, 1))
            if sn == "peak_hbm_bw_gigabytes_per_second":
                out["peak_hbm_gbps"] = _as_float(_stat_value(sf))
            elif sn == "peak_teraflops_per_second":
                out["peak_tflops"] = _as_float(_stat_value(sf))
    for fn, wt, v in pf:   # per-op invariant stats live on the metadata
        if fn != 4 or wt != 2:
            continue
        ent = list(_fields(v))
        val = _first(ent, 2)
        if not isinstance(val, bytes):
            continue
        em = list(_fields(val))
        hbm = onchip = None
        flops = None
        for f, w, x in em:
            if f != 5 or w != 2:
                continue
            sf = list(_fields(x))
            sn = stat_names.get(_first(sf, 1))
            if sn == "flops":
                sv = _stat_value(sf)
                if isinstance(sv, (int, float)):
                    flops = int(sv)
            elif sn == "memory_access_breakdown":
                raw = _stat_value(sf)
                if isinstance(raw, bytes):
                    hbm = hbm or 0
                    onchip = onchip or 0
                    for bf, bw, bv in _fields(raw):
                        if bf == 1 and bw == 2:
                            mf = list(_fields(bv))
                            space = _first(mf, 2, 0)
                            nbytes = _first(mf, 3, 0) or 0
                            if space == HBM_MEMORY_SPACE:
                                hbm += nbytes
                            else:
                                onchip += nbytes
        if hbm is None and flops is None:
            continue
        out["ops"][_short_name(em)] = _OpStats(
            hbm_bytes=hbm, onchip_bytes=onchip, flops=flops)
    return out


def _as_float(v):
    return float(v) if isinstance(v, (int, float)) else None
