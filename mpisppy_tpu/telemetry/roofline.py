###############################################################################
# Roofline attribution over a device timeline (ISSUE 7 tentpole,
# part 2; docs/telemetry.md).
#
# Turns deviceprof.DeviceTimeline into the gateable device-side report
# the perf era argues from ("Large Scale Distributed Linear Algebra
# With TPUs" / MPAX discipline, PAPERS.md): achieved HBM GB/s against
# the device's own published peak, measured MFU, per-category
# byte/time attribution, and the DMA/compute overlap fraction that is
# the acceptance metric for the Pallas double-buffer work (ROADMAP
# item 2).
#
# Metric definitions (all derived, none hand-timed):
#
#   device_sec_per_iter   median StepTraceAnnotation step duration when
#                         the capture has step markers (wheel runs via
#                         --profile-dir), else device module time per
#                         module execution (bench one-iteration traces).
#   measured_stream_gbps  duration-weighted HBM bandwidth of the PURE
#                         data-movement ops (hlo_category "data
#                         formatting" / "non-fusion elementwise" /
#                         "broadcast"), restricted — when memory spaces
#                         are known — to HBM-DOMINATED ops (>= half
#                         their traffic in HBM).  The trace analog of a
#                         stream (saxpy) microbenchmark: what the
#                         device actually sustains when an op does
#                         nothing but move HBM.  Replaces bench.py's
#                         hand-rolled two-op estimate (ISSUE 7).
#   achieved_hbm_gbps     total leaf-op HBM bytes / device module time:
#                         the true streaming rate of the WHOLE step
#                         (the roofline y-axis).
#   overlap_frac          |union(DMA in-flight) ∩ union(compute busy)|
#                         / |union(DMA in-flight)| — the fraction of
#                         async-transfer time hidden behind compute.
#                         Exposed (un-overlapped) DMA time is the
#                         double-buffer target.
#   mfu                   XLA-visible flops / module time / peak
#                         TFLOP/s.  Pallas custom-call flops are NOT
#                         attributed by the profiler, so this is a
#                         lower bound on true MFU (noted in the report).
#
# Byte accounting uses the xplane HBM-space split when the sidecar is
# present (deviceprof.py); the json-only fallback uses bytes_accessed
# (all spaces) and flags itself, because bytes_accessed counts
# VMEM-resident reuse and can exceed the physical HBM roofline.
###############################################################################
from __future__ import annotations

from mpisppy_tpu.telemetry import deviceprof as dp

DEVPROF_SCHEMA = "mpisppy-tpu-deviceprof/1"

#: hlo_category values whose ops are pure memory movement — the
#: streaming-bandwidth sample set
STREAM_CATEGORIES = frozenset({"data formatting",
                               "non-fusion elementwise", "broadcast"})

#: v5e single-chip public-spec fallbacks when the capture carries no
#: plane stats (json-only fixtures)
V5E_PEAK_HBM_GBPS = 819.0
V5E_PEAK_BF16_TFLOPS = 197.0


def _union(intervals):
    """Total length (and merged list) of a set of [a, b) intervals."""
    merged = []
    for a, b in sorted(intervals):
        if b <= a:
            continue
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    return sum(b - a for a, b in merged), merged


def _intersect_len(mg1, mg2) -> float:
    i = j = 0
    tot = 0.0
    while i < len(mg1) and j < len(mg2):
        a = max(mg1[i][0], mg2[j][0])
        b = min(mg1[i][1], mg2[j][1])
        if b > a:
            tot += b - a
        if mg1[i][1] <= mg2[j][1]:
            i += 1
        else:
            j += 1
    return tot


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else None


def roofline(tl: dp.DeviceTimeline) -> dict:
    """DeviceTimeline -> the machine report (JSON-able dict)."""
    hbm_exact = tl.has_memory_spaces

    def op_bytes(op):
        return op.hbm_bytes if hbm_exact else op.bytes_accessed

    leaves = [op for op in tl.ops
              if op.category not in dp.CONTAINER_CATEGORIES]
    module_s = sum(m.dur_us for m in tl.modules) * 1e-6
    n_modules = len(tl.modules)
    if module_s <= 0.0:
        # no module line (heavily trimmed fixture): the op envelope is
        # the best available denominator
        if leaves:
            module_s = (max(o.end_us for o in leaves)
                        - min(o.start_us for o in leaves)) * 1e-6
        n_modules = n_modules or 1

    # -- per-category attribution ---------------------------------------
    cats: dict[str, dict] = {}
    for op in leaves:
        c = cats.setdefault(op.category, {
            "ops": 0, "busy_s": 0.0, "hbm_gb": 0.0, "flops": 0})
        c["ops"] += 1
        c["busy_s"] += op.dur_us * 1e-6
        c["hbm_gb"] += (op_bytes(op) or 0) / 1e9
        c["flops"] += op.flops or 0
    for c in cats.values():
        c["gbps"] = (round(c["hbm_gb"] / c["busy_s"], 1)
                     if c["busy_s"] > 0 else None)
        c["busy_s"] = round(c["busy_s"], 6)
        c["hbm_gb"] = round(c["hbm_gb"], 3)
    cats = dict(sorted(cats.items(), key=lambda kv: -kv[1]["hbm_gb"]))

    # -- whole-step achieved HBM rate ------------------------------------
    total_gb = sum(c["hbm_gb"] for c in cats.values())
    achieved = total_gb / module_s if module_s > 0 else None
    peak_hbm = tl.peak_hbm_gbps or V5E_PEAK_HBM_GBPS
    peak_tf = tl.peak_tflops or V5E_PEAK_BF16_TFLOPS

    # -- stream (pure-data-movement) bandwidth ---------------------------
    # with exact memory spaces the sample keeps only HBM-DOMINATED
    # movement ops (>= half their traffic in HBM): a VMEM-resident copy
    # tells you about VMEM, not about what the HBM bus sustains
    stream_gb = stream_s = 0.0
    for op in leaves:
        if op.category not in STREAM_CATEGORIES or op.dur_us <= 0:
            continue
        if hbm_exact and (op.hbm_bytes or 0) < max(1, op.bytes_accessed // 2):
            continue
        stream_gb += (op_bytes(op) or 0) / 1e9
        stream_s += op.dur_us * 1e-6
    stream_gbps = stream_gb / stream_s if stream_s > 0 else None

    # -- MFU (XLA-visible flops only) ------------------------------------
    flops_total = sum(c["flops"] for c in cats.values())
    mfu = (flops_total / module_s / (peak_tf * 1e12)
           if module_s > 0 and flops_total else None)
    # opaque time: leaf execution with no byte attribution in ANY
    # memory space — almost entirely Pallas custom-calls (run_window)
    # whose internal DMA/flops the profiler cannot see.  An op that is
    # merely all-VMEM (hbm 0, on-chip > 0) is attributed, not opaque.
    opaque_s = sum(op.dur_us for op in leaves
                   if not (op.bytes_accessed or op.hbm_bytes
                           or op.onchip_bytes)
                   and op.category not in dp.DMA_CATEGORIES) * 1e-6

    # -- DMA/compute overlap ---------------------------------------------
    dma_iv = [(d.start_us, d.end_us) for d in tl.dma]
    comp_iv = [(op.start_us, op.end_us) for op in leaves
               if op.category not in dp.DMA_CATEGORIES]
    dma_len, dma_merged = _union(dma_iv)
    comp_len, comp_merged = _union(comp_iv)
    overlap_us = _intersect_len(dma_merged, comp_merged)
    overlap_frac = (overlap_us / dma_len) if dma_len > 0 else None
    dma_gb = sum(d.bytes for d in tl.dma) / 1e9

    # -- per-iteration device time ---------------------------------------
    step_durs = [s.dur_us * 1e-6 for s in tl.steps]
    by_iter = sorted((s.step_num, round(s.dur_us * 1e-6, 6))
                     for s in tl.steps if s.step_num is not None)
    if step_durs:
        dev_sec_per_iter = _median(step_durs)
        iter_source = "steps"
    elif n_modules and module_s > 0:
        dev_sec_per_iter = module_s / n_modules
        iter_source = "modules"
    else:
        dev_sec_per_iter, iter_source = None, "none"

    rep = {
        "schema": DEVPROF_SCHEMA,
        "trace": tl.trace_path,
        "device": tl.device_name,
        "byte_source": ("xplane-memory-spaces" if hbm_exact
                        else "bytes-accessed-all-spaces"),
        "device_sec_per_iter": _round(dev_sec_per_iter, 6),
        "iter_source": iter_source,
        "modules": {"count": n_modules,
                    "total_s": round(module_s, 6)},
        "measured_stream_gbps": _round(stream_gbps, 1),
        "stream_sample": {"gb": round(stream_gb, 3),
                          "busy_s": round(stream_s, 6)},
        "achieved_hbm_gbps": _round(achieved, 1),
        "peak_hbm_gbps": round(peak_hbm, 1),
        "hbm_roofline_frac": _round(
            achieved / peak_hbm if achieved is not None else None, 4),
        "mfu": _round(mfu, 5),
        "flops_total": flops_total,
        "peak_tflops": round(peak_tf, 1),
        "opaque_s": round(opaque_s, 6),
        "opaque_frac": _round(
            opaque_s / module_s if module_s > 0 else None, 4),
        "overlap_frac": _round(overlap_frac, 4),
        "dma": {
            "spans": len(tl.dma),
            "gb": round(dma_gb, 3),
            "inflight_s": round(dma_len * 1e-6, 6),
            "exposed_s": round((dma_len - overlap_us) * 1e-6, 6),
        },
        "steps": {"count": len(tl.steps),
                  "sec_per_iter_median": _round(_median(step_durs), 6),
                  "by_iter_tail": by_iter[-8:]},
        "categories": cats,
    }
    notes = []
    if not leaves:
        notes.append("capture has no device-plane ops (host-only "
                     "trace — CPU backend?): device metrics are empty")
    if not hbm_exact:
        notes.append("no xplane sidecar: bytes are XLA bytes_accessed "
                     "(all memory spaces, counts VMEM reuse) — rates "
                     "can exceed the physical HBM roofline")
    if opaque_s > 0.05 * module_s:
        notes.append(f"{100 * opaque_s / module_s:.0f}% of device time "
                     "is byte-opaque custom-calls (Pallas kernels): "
                     "their internal HBM traffic and flops are "
                     "invisible to the profiler, so achieved_hbm_gbps "
                     "and mfu are lower bounds")
    if opaque_s > 0.5 * module_s and overlap_frac is not None \
            and overlap_frac > 0.9:
        # ISSUE 8 caveat, measured on the committed S=100k capture:
        # profiler-VISIBLE DMA was already 98.9% hidden while the
        # window kernel's internal tile DMA (the double-buffer target)
        # is inside the opaque custom-call — a high overlap_frac here
        # does NOT certify the kernel pipeline
        notes.append("overlap_frac covers only profiler-visible DMA; "
                     "most device time is opaque Pallas custom-calls "
                     "whose internal tile DMA the profiler cannot see "
                     "— judge the kernel double-buffer by "
                     "device_sec_per_iter / iters_per_sec, not by "
                     "overlap_frac alone")
    rep["notes"] = notes
    return rep


def _round(v, nd):
    return None if v is None else round(v, nd)


def roofline_path(profile_dir: str) -> dict:
    """Newest capture under `profile_dir` -> roofline report."""
    return roofline(dp.build_timeline(profile_dir))


# ---------------------------------------------------------------------------
# the human rendering
# ---------------------------------------------------------------------------
def _fmt(v, spec=".6g"):
    return "-" if v is None else format(v, spec)


def render_device(rep: dict) -> str:
    L: list[str] = []
    L.append(f"device {rep.get('device') or '?'}  "
             f"[{rep.get('byte_source')}]  trace {rep.get('trace')}")
    m = rep["modules"]
    L.append(f"modules: {m['count']}  device time {m['total_s']:.6g}s  "
             f"device_sec_per_iter {_fmt(rep['device_sec_per_iter'])} "
             f"({rep['iter_source']})")
    L.append(f"measured_stream_gbps {_fmt(rep['measured_stream_gbps'])} "
             f"  (pure data-movement ops: "
             f"{rep['stream_sample']['gb']:.6g} GB over "
             f"{rep['stream_sample']['busy_s']:.6g}s)")
    L.append(f"achieved_hbm_gbps {_fmt(rep['achieved_hbm_gbps'])} of "
             f"peak {rep['peak_hbm_gbps']} "
             f"(roofline_frac {_fmt(rep['hbm_roofline_frac'])})")
    L.append(f"mfu {_fmt(rep['mfu'])}  (xla-visible flops "
             f"{rep['flops_total']:.6g} vs peak "
             f"{rep['peak_tflops']} TFLOP/s)")
    d = rep["dma"]
    L.append(f"overlap_frac {_fmt(rep['overlap_frac'])}  (dma "
             f"{d['spans']} spans, {d['gb']:.6g} GB, in-flight "
             f"{d['inflight_s']:.6g}s, exposed {d['exposed_s']:.6g}s)")
    if rep["steps"]["count"]:
        s = rep["steps"]
        L.append(f"steps: {s['count']}  sec/iter median "
                 f"{_fmt(s['sec_per_iter_median'])}  tail "
                 f"{s['by_iter_tail']}")
    L.append("categories (device busy, HBM bytes):")
    for name, c in rep["categories"].items():
        L.append(f"  {name:<24} x{c['ops']:<5d} {c['busy_s']:9.5f}s"
                 f"  {c['hbm_gb']:9.3f} GB"
                 f"  {_fmt(c['gbps'], '8.1f') if c['gbps'] is not None else '       -'} GB/s")
    for n in rep.get("notes", []):
        L.append(f"  ! {n}")
    return "\n".join(L)
