###############################################################################
# Typed telemetry events — the vocabulary of the wheel's one reporting
# spine (docs/telemetry.md).
#
# Every observable thing the wheel does maps to exactly one event kind;
# sinks (telemetry/sinks.py) and back-compat views (the hub's `trace`
# list, a spoke's `(iter, bound)` trace) are all subscribers of the same
# EventBus stream.  An Event is a frozen host-side record: wall-clock
# AND monotonic timestamps (wall for correlating across machines,
# monotonic for durations — wall clocks step), a per-bus sequence
# number (total order even when two events land in the same clock
# tick), the run id, and the producing cylinder.
###############################################################################
from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Any

# -- event taxonomy (docs/telemetry.md) -------------------------------------
HUB_ITERATION = "hub-iteration"        # one hub sync: bounds, gaps, conv
SPOKE_HARVEST = "spoke-harvest"        # a spoke produced a (raw) bound
BOUND_ACCEPT = "bound-accept"          # harvested bound passed validation
BOUND_REJECT = "bound-reject"          # non-finite / sense-violating bound
SPOKE_STRIKE = "spoke-strike"          # unambiguous garbage charged a strike
SPOKE_DISABLE = "spoke-disable"        # strike budget exhausted
BOUND_EVICT = "bound-evict"            # contradicted incumbent evicted
CHECKPOINT_WRITE = "checkpoint-write"  # a snapshot landed on disk
CHECKPOINT_RESTORE = "checkpoint-restore"
FAULT_INJECTED = "fault-injected"      # a FaultPlan seam fired
LANE_QUARANTINE = "lane-quarantine"    # PDHG lane guard reset lanes
DISPATCH = "dispatch"                  # one coalesced megabatch dispatched
DISPATCH_RETRY = "dispatch-retry"      # a failed/hung dispatch re-tried
DISPATCH_QUARANTINE = "dispatch-quarantine"  # a poisoned request isolated
                                       # by bisection; its ticket resolves
                                       # with a typed SolveFailed
WATCHDOG = "watchdog"                  # a supervisor tripped / acted
                                       # (hub progress stall, dispatcher
                                       # thread death)
PLANE_WRITE = "plane-write"            # async hub: host wrote an
                                       # exchange-plane slot (slot,
                                       # generation, staleness)
EXCHANGE_OVERLAP = "exchange-overlap"  # async hub: per-sync host
                                       # exchange attribution (issue_s,
                                       # complete_s, staleness, theta)
SESSION_STATE = "session-state"        # serve layer: a session moved
                                       # through its lifecycle (QUEUED/
                                       # ADMITTED/RUNNING/DEGRADED/
                                       # DONE/FAILED/REJECTED)
ADMISSION_REJECTED = "admission-rejected"  # serve layer: backpressure
                                       # refused a submit with a typed
                                       # reason (queue-full / quota /
                                       # draining) — never a hang
FLEET_PLACEMENT = "fleet-placement"    # fleet router: a session placed
                                       # on a replica (policy: affinity
                                       # on the interner routing key,
                                       # else least-loaded)
SESSION_MIGRATED = "session-migrated"  # fleet router: a session moved
                                       # replicas (emergency checkpoint
                                       # -> requeue -> restore on the
                                       # destination; non-terminal)
REPLICA_STATE = "replica-state"        # fleet health plane: a replica
                                       # moved UP/SUSPECT/DEAD/DRAINED
MESH_STATE = "mesh-state"              # elastic mesh membership: a host
                                       # moved UP/SUSPECT/DEAD, with the
                                       # epoch that observed the move
                                       # (parallel/elastic.py)
MESH_HOST_LOST = "mesh-host-lost"      # elastic mesh: a host went
                                       # sticky-DEAD and its shard is
                                       # orphaned — a reshard follows
MESH_RESHARD = "mesh-reshard"          # elastic mesh: the wheel was
                                       # re-partitioned across the
                                       # survivor set (old/new device
                                       # counts, epoch, hub_iter)
MESH_STRAGGLER = "mesh-straggler"      # elastic mesh: a hub-harvest
                                       # fetch missed its deadline or
                                       # tore; typed MeshDegraded (or a
                                       # clean re-fetch), never a hang
MPC_STEP = "mpc-step"                  # rolling-horizon stream: one
                                       # window solved (step, rel_gap,
                                       # warm/cold, latency_s) —
                                       # mirrors the client's `step`
                                       # line (mpc/stream.py)
MPC_DEGRADED = "mpc-degraded"          # a window missed its gap target
                                       # warm AND cold (typed
                                       # StepDegraded; the stream
                                       # continues on the best iterate)
SCENGEN = "scengen"                    # a VirtualBatch was built: the
                                       # program, scenario count, base
                                       # seed, and the resident-vs-
                                       # materialized byte accounting
                                       # (docs/scengen.md)
KERNEL_COUNTERS = "kernel-counters"    # on-device counter harvest
CONSOLE = "console"                    # a human-readable log line
PROFILE = "profile"                    # profiler lifecycle: "start", or
                                       # "captured" + trace_dir once a
                                       # capture is VERIFIED on disk
                                       # (analyze auto-discovery key)
SPAN = "span"                          # one timed wheel phase (host wall)
SPAN_START = "span-start"              # causal tracing (ISSUE 20): a new
                                       # named span opened under the
                                       # row's trace context — segments
                                       # (one per run attempt/replica),
                                       # mesh reshard rebuilds, MPC
                                       # windows.  Spans need no close
                                       # record: their extent is the
                                       # [min, max] wall clock of the
                                       # rows carrying their span_id
                                       # (torn-tail safe)
SLO_OBSERVATION = "slo-observation"    # one terminal SLO sample for a
                                       # session: SLA class, outcome,
                                       # client-observed total wall,
                                       # migrations/preemptions, step
                                       # deadline misses (slo.py folds
                                       # these into error budgets)
RUN_START = "run-start"
RUN_END = "run-end"                    # exit reason + final gap

ALL_KINDS = frozenset(v for k, v in list(globals().items())
                      if k.isupper() and isinstance(v, str))


def new_run_id() -> str:
    """Short unique id correlating every event of one wheel run."""
    return uuid.uuid4().hex[:12]


def _jsonable(v: Any) -> Any:
    """Best-effort conversion to something json.dumps accepts.  Device
    scalars/arrays become Python numbers/lists; anything exotic falls
    back to repr — a trace line must never raise."""
    if isinstance(v, float):
        # strict JSON: json.dumps would emit bare Infinity/NaN tokens
        # that non-Python parsers reject — a bound that never landed
        # serializes as null (the generic_cylinders _finite convention)
        import math
        return v if math.isfinite(v) else None
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    try:  # numpy / jax scalars and arrays
        import numpy as np
        if isinstance(v, np.ndarray):
            return _jsonable(v.tolist())
        if isinstance(v, np.generic):
            return _jsonable(v.item())
        if hasattr(v, "tolist"):  # jax.Array
            return _jsonable(v.tolist())
    except Exception:
        pass
    return repr(v)


@dataclasses.dataclass(frozen=True)
class Event:
    """One telemetry record.  `data` holds the kind-specific payload."""

    kind: str
    seq: int                 # per-bus monotone sequence number
    t_wall: float            # time.time()
    t_mono: float            # time.perf_counter()
    run: str = ""            # run id (new_run_id())
    cyl: str = ""            # producing cylinder ("hub", "spoke0:...", ...)
    hub_iter: int | None = None
    level: int | None = None  # console verbosity level (CONSOLE only)
    # causal trace context (ISSUE 20; telemetry/tracecontext.py) —
    # empty on pre-trace rows, stamped by the bus otherwise
    trace_id: str = ""
    span_id: str = ""
    parent_span_id: str = ""
    data: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "seq": self.seq,
             "t_wall": self.t_wall, "t_mono": self.t_mono,
             "run": self.run, "cyl": self.cyl}
        if self.hub_iter is not None:
            d["iter"] = self.hub_iter
        if self.level is not None:
            d["level"] = self.level
        if self.trace_id:
            d["trace_id"] = self.trace_id
            d["span_id"] = self.span_id
            if self.parent_span_id:
                d["parent_span_id"] = self.parent_span_id
        d["data"] = _jsonable(self.data)
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())


def make_event(kind: str, seq: int, *, run: str = "", cyl: str = "",
               hub_iter: int | None = None, level: int | None = None,
               trace=None, data: dict | None = None) -> Event:
    """`trace` is a TraceContext (or any object carrying
    trace_id/span_id/parent_span_id) — None leaves the row unstamped."""
    return Event(kind=kind, seq=seq, t_wall=time.time(),
                 t_mono=time.perf_counter(), run=run, cyl=cyl,
                 hub_iter=hub_iter, level=level,
                 trace_id=getattr(trace, "trace_id", "") or "",
                 span_id=getattr(trace, "span_id", "") or "",
                 parent_span_id=getattr(trace, "parent_span_id", "") or "",
                 data=data or {})
