###############################################################################
# Trace analyzer (ISSUE 5 tentpole, part 1; docs/telemetry.md).
#
# Consumes the JSONL event stream the wheel emits (--trace-jsonl, or a
# flight-recorder dump) and answers the first questions of every run of
# a hub-and-spoke wheel (Knueven et al., MPC 2023): where did the wall
# time go, which spoke produced the binding bounds, is the gap moving
# or stalled, and is the dispatch tunnel healthy?
#
#   rows  = load_trace("trace.jsonl")
#   model = build_run_model(rows)           # typed run -> iters -> events
#   rep   = analyze(model)                  # the machine report (JSON)
#   text  = render_report(rep)              # the human report
#
# Pure stdlib on purpose: a host without jax (a laptop holding a trace
# scp'd off a TPU pool) can run `python -m mpisppy_tpu.telemetry
# analyze` on any trace or black box.  Joins are exact: events carry
# run ids, hub_iter stamps (ISSUE 5 satellite — dispatch / fault /
# quarantine events are stamped at emit time, -1 pre-wheel), and the
# per-bus seq total order; no seq-window heuristics.
###############################################################################
from __future__ import annotations

import dataclasses
import json
import math

from mpisppy_tpu.telemetry import events as ev
from mpisppy_tpu.telemetry import flightrec

ANALYZE_SCHEMA = "mpisppy-tpu-analyze/1"

#: rel-gap thresholds the time-to-gap table reports (the 1% target is
#: the BENCH_METHODOLOGY headline)
GAP_TARGETS = (0.05, 0.02, 0.01)


def load_trace(path: str) -> list[dict]:
    """Parse a JSONL trace (or flight dump) into row dicts.  A torn
    final line — the signature of a crashed writer — is skipped, not
    fatal: a crash trace must stay analyzable by construction."""
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
    return rows


def join_segments(rows: list[dict],
                  run: str | None = None) -> tuple[list, list]:
    """Join a trace DIRECTORY's rows into ONE session's stream (ISSUE
    20 satellite).  A fleet-migrated session leaves one segment file
    per replica it ran on (same sid under each replica's subdir) — the
    segments join on the CAUSAL TRACE ID every row carries, with the
    (run, session) heuristic only as the fallback for pre-trace rows.
    Returns (rows of the chosen session sorted by wall clock, the
    segment files they came from); `run` selects a session, default is
    the newest."""
    groups: dict = {}
    order: list = []
    for r in rows:
        key = r.get("trace_id")
        if not key:
            sid = (r.get("data") or {}).get("session")
            key = (r.get("run"), sid) if sid else r.get("_file")
        if key not in groups:
            groups[key] = []
            order.append(key)
        groups[key].append(r)
    if not groups:
        raise ValueError("no rows in the trace directory")
    if run:
        target = next((k for k in order
                       if any(g.get("run") == run for g in groups[k])),
                      None)
        if target is None:
            raise ValueError(f"run {run!r} not in the trace directory")
    else:
        target = max(order, key=lambda k: max(
            (g.get("t_wall") or 0.0) for g in groups[k]))
    segs = sorted(groups[target],
                  key=lambda r: (r.get("t_wall") or 0.0,
                                 r.get("seq") or 0))
    files: list = []
    for r in segs:
        f = r.get("_file")
        if f and f not in files:
            files.append(f)
    return segs, files


def runs_in(rows: list[dict]) -> list[str]:
    """Distinct run ids in stream order (a restarted run appends a new
    segment to the same file; ids delimit the segments)."""
    seen: list[str] = []
    for r in rows:
        run = r.get("run")
        if run and run not in seen:
            seen.append(run)
    return seen


@dataclasses.dataclass
class HubIter:
    """One hub iteration joined from its events."""

    it: int
    t_wall: float | None = None
    t_mono: float | None = None
    data: dict = dataclasses.field(default_factory=dict)
    harvests: list = dataclasses.field(default_factory=list)
    accepts: list = dataclasses.field(default_factory=list)
    rejects: list = dataclasses.field(default_factory=list)
    spans: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RunModel:
    """Typed model of one run: run -> hub iterations -> joined events,
    plus the cross-iteration streams (dispatch, faults, checkpoints)."""

    run: str
    rows: list = dataclasses.field(default_factory=list)
    header: dict | None = None        # flight-recorder dump header
    start: dict | None = None         # run-start row
    end: dict | None = None           # run-end row
    iters: dict = dataclasses.field(default_factory=dict)  # it -> HubIter
    spans: list = dataclasses.field(default_factory=list)
    strikes: list = dataclasses.field(default_factory=list)
    disables: list = dataclasses.field(default_factory=list)
    evicts: list = dataclasses.field(default_factory=list)
    quarantines: list = dataclasses.field(default_factory=list)
    faults: list = dataclasses.field(default_factory=list)
    ckpt_writes: list = dataclasses.field(default_factory=list)
    ckpt_restores: list = dataclasses.field(default_factory=list)
    megabatches: list = dataclasses.field(default_factory=list)
    dispatch_stats: list = dataclasses.field(default_factory=list)
    dispatch_retries: list = dataclasses.field(default_factory=list)
    dispatch_quarantines: list = dataclasses.field(default_factory=list)
    watchdogs: list = dataclasses.field(default_factory=list)
    kernel: dict = dataclasses.field(default_factory=dict)  # cyl -> last
    spoke_classes: dict = dataclasses.field(default_factory=dict)
    profiles: list = dataclasses.field(default_factory=list)  # profile evs
    plane_writes: list = dataclasses.field(default_factory=list)
    overlaps: list = dataclasses.field(default_factory=list)  # async rows
    placements: list = dataclasses.field(default_factory=list)  # fleet
    migrations: list = dataclasses.field(default_factory=list)  # fleet
    mesh_states: list = dataclasses.field(default_factory=list)  # elastic
    mesh_losses: list = dataclasses.field(default_factory=list)  # elastic
    mesh_reshards: list = dataclasses.field(default_factory=list)
    mesh_stragglers: list = dataclasses.field(default_factory=list)
    mpc_steps: list = dataclasses.field(default_factory=list)  # stream
    mpc_degrades: list = dataclasses.field(default_factory=list)

    def iter_of(self, it: int) -> HubIter:
        if it not in self.iters:
            self.iters[it] = HubIter(it)
        return self.iters[it]

    @property
    def t0_mono(self) -> float | None:
        monos = [r["t_mono"] for r in self.rows if "t_mono" in r]
        return min(monos) if monos else None

    @property
    def t1_mono(self) -> float | None:
        monos = [r["t_mono"] for r in self.rows if "t_mono" in r]
        return max(monos) if monos else None


def build_run_model(rows: list[dict], run: str | None = None) -> RunModel:
    """Join one run's events into a RunModel.  `run=None` picks the
    LAST run id in the stream — with segment-appending traces (a
    preempted run restarted onto the same --trace-jsonl path) the
    newest segment is the one being diagnosed."""
    runs = runs_in(rows)
    if run is None:
        if not runs:
            raise ValueError("no run ids in the trace "
                             "(empty or console-only stream)")
        run = runs[-1]
    elif run not in runs:
        raise ValueError(f"run {run!r} not in trace (have: {runs})")
    m = RunModel(run=run)
    for r in rows:
        if r.get("kind") == flightrec.HEADER_KIND:
            if r.get("run") in (run, "unknown"):
                m.header = r
            continue
        if r.get("run") != run:
            # a scheduler configured before the hub minted its run id
            # emits dispatch rows with run="" — keep them (single-wheel
            # processes; the hub adopts the scheduler afterwards).  A
            # MIXED cross-session megabatch (serve layer) carries the
            # scheduler's run with a per-session breakdown: keep the
            # row when this run rode in it, joined by its own token —
            # no seq heuristics (ISSUE 12 satellite)
            if r.get("kind") != ev.DISPATCH:
                continue
            sessions = (r.get("data") or {}).get("sessions") or []
            mine = [s for s in sessions if s.get("run") == run]
            if r.get("run") and not mine:
                continue
            if mine:
                # join at THIS session's iteration (its own token),
                # not the foreign top-level stamp
                r = dict(r)
                r["iter"] = mine[0].get("iter", r.get("iter"))
        m.rows.append(r)
        kind, data, it = r.get("kind"), r.get("data", {}), r.get("iter")
        if kind == ev.RUN_START:
            m.start = r
        elif kind == ev.RUN_END:
            m.end = r
        elif kind == ev.HUB_ITERATION:
            hi = m.iter_of(data.get("iter", it))
            hi.t_wall, hi.t_mono = r.get("t_wall"), r.get("t_mono")
            hi.data = data
        elif kind == ev.SPOKE_HARVEST:
            m.iter_of(it).harvests.append(data)
            if "spoke" in data and "spoke_class" in data:
                m.spoke_classes[data["spoke"]] = data["spoke_class"]
        elif kind == ev.BOUND_ACCEPT:
            m.iter_of(it).accepts.append(data)
        elif kind == ev.BOUND_REJECT:
            m.iter_of(it).rejects.append(data)
        elif kind == ev.SPAN:
            m.spans.append({"iter": it, **data})
            if it is not None:
                spans = m.iter_of(it).spans
                name = data.get("name", "?")
                spans[name] = spans.get(name, 0.0) + data.get("dur_s", 0.0)
        elif kind == ev.SPOKE_STRIKE:
            m.strikes.append({"iter": it, **data})
        elif kind == ev.SPOKE_DISABLE:
            m.disables.append({"iter": it, **data})
        elif kind == ev.BOUND_EVICT:
            m.evicts.append({"iter": it, **data})
        elif kind == ev.LANE_QUARANTINE:
            m.quarantines.append({"iter": it, **data})
        elif kind == ev.FAULT_INJECTED:
            m.faults.append({"iter": it, **data})
        elif kind == ev.CHECKPOINT_WRITE:
            m.ckpt_writes.append({"iter": it, **data})
        elif kind == ev.CHECKPOINT_RESTORE:
            m.ckpt_restores.append({"iter": it, **data})
        elif kind == ev.DISPATCH:
            # two producers share the kind (docs/telemetry.md): the
            # scheduler's per-megabatch row (cyl "dispatch") and the
            # hub's cumulative per-sync stats row (cyl "hub")
            if r.get("cyl") == "dispatch":
                m.megabatches.append({"iter": it, **data})
            else:
                m.dispatch_stats.append({"iter": it, **data})
        elif kind == ev.DISPATCH_RETRY:
            m.dispatch_retries.append({"iter": it, **data})
        elif kind == ev.DISPATCH_QUARANTINE:
            m.dispatch_quarantines.append({"iter": it, **data})
        elif kind == ev.WATCHDOG:
            m.watchdogs.append({"iter": it, **data})
        elif kind == ev.KERNEL_COUNTERS:
            m.kernel["hub" if r.get("cyl") in (None, "", "hub")
                     else r["cyl"]] = data
        elif kind == ev.PLANE_WRITE:
            m.plane_writes.append({"iter": it, **data})
        elif kind == ev.EXCHANGE_OVERLAP:
            m.overlaps.append({"iter": it, **data})
        elif kind == ev.PROFILE:
            m.profiles.append({"iter": it, **data})
        elif kind == ev.FLEET_PLACEMENT:
            m.placements.append({"iter": it, **data})
        elif kind == ev.SESSION_MIGRATED:
            m.migrations.append({"iter": it, **data})
        elif kind == ev.MESH_STATE:
            m.mesh_states.append({"iter": it, **data})
        elif kind == ev.MESH_HOST_LOST:
            m.mesh_losses.append({"iter": it, **data})
        elif kind == ev.MESH_RESHARD:
            m.mesh_reshards.append({"iter": it, **data})
        elif kind == ev.MESH_STRAGGLER:
            m.mesh_stragglers.append({"iter": it, **data})
        elif kind == ev.MPC_STEP:
            m.mpc_steps.append({"iter": it, **data})
        elif kind == ev.MPC_DEGRADED:
            m.mpc_degrades.append({"iter": it, **data})
    return m


# ---------------------------------------------------------------------------
# the report
# ---------------------------------------------------------------------------
def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else None


def _finite(v):
    return v if isinstance(v, (int, float)) and math.isfinite(v) else None


def _phase_breakdown(model: RunModel) -> dict:
    agg: dict[str, dict] = {}
    for s in model.spans:
        a = agg.setdefault(s.get("name", "?"),
                           {"calls": 0, "total_s": 0.0, "max_s": 0.0})
        d = float(s.get("dur_s") or 0.0)
        a["calls"] += 1
        a["total_s"] += d
        a["max_s"] = max(a["max_s"], d)
    grand = sum(a["total_s"] for a in agg.values()) or 1.0
    for a in agg.values():
        a["mean_s"] = a["total_s"] / max(1, a["calls"])
        a["share"] = a["total_s"] / grand
        for k in ("total_s", "mean_s", "max_s", "share"):
            a[k] = round(a[k], 6)
    return dict(sorted(agg.items(), key=lambda kv: -kv[1]["total_s"]))


def _iteration_stats(model: RunModel) -> dict:
    hs = sorted((h for h in model.iters.values() if h.t_mono is not None),
                key=lambda h: h.it)
    deltas = [b.t_mono - a.t_mono for a, b in zip(hs, hs[1:])
              if b.it == a.it + 1]
    steady = deltas[2:] if len(deltas) > 4 else deltas
    out = {"count": len(hs),
           "wall_s": (round(hs[-1].t_mono - hs[0].t_mono, 6)
                      if len(hs) > 1 else 0.0),
           "sec_per_iter_median": None, "sec_per_iter_p90": None}
    if steady:
        out["sec_per_iter_median"] = round(_median(steady), 6)
        out["sec_per_iter_p90"] = round(
            sorted(steady)[min(len(steady) - 1,
                               int(0.9 * len(steady)))], 6)
    return out


def _bound_progress(model: RunModel) -> dict:
    hs = sorted(model.iters.values(), key=lambda h: h.it)
    traj = [(h.it, _finite(h.data.get("outer")),
             _finite(h.data.get("inner")), _finite(h.data.get("rel_gap")))
            for h in hs if h.data]
    last_move = {"outer": None, "inner": None}
    prev = {"outer": None, "inner": None}
    for it, ob, ib, _ in traj:
        for side, v in (("outer", ob), ("inner", ib)):
            if v is not None and v != prev[side]:
                last_move[side] = it
                prev[side] = v
    last_iter = traj[-1][0] if traj else 0
    gaps = [(it, g) for it, _, _, g in traj if g is not None]
    t0 = model.t0_mono
    time_to_gap = {}
    for target in GAP_TARGETS:
        hit = next((h for h in hs
                    if _finite(h.data.get("rel_gap")) is not None
                    and h.data["rel_gap"] <= target), None)
        time_to_gap[f"{target:g}"] = None if hit is None else {
            "iter": hit.it,
            "seconds": (round(hit.t_mono - t0, 6)
                        if hit.t_mono is not None and t0 is not None
                        else None)}
    return {
        "final_outer": prev["outer"],
        "final_inner": prev["inner"],
        "final_rel_gap": gaps[-1][1] if gaps else None,
        "min_rel_gap": min((g for _, g in gaps), default=None),
        "first_rel_gap": gaps[0][1] if gaps else None,
        "iters_since_outer_moved": (None if last_move["outer"] is None
                                    else last_iter - last_move["outer"]),
        "iters_since_inner_moved": (None if last_move["inner"] is None
                                    else last_iter - last_move["inner"]),
        "time_to_gap": time_to_gap,
        "gap_trajectory_tail": [[it, g] for it, g in gaps[-8:]],
    }


def _spoke_attribution(model: RunModel) -> dict:
    spokes: dict = {}

    def rec(j):
        return spokes.setdefault(j, {
            "class": model.spoke_classes.get(j),
            "harvests": 0, "accepts": 0, "binding_accepts": 0,
            "rejects": 0, "strikes": 0, "disabled": False,
            "senses": [], "last_bound": None})

    for hi in model.iters.values():
        for h in hi.harvests:
            r = rec(h.get("spoke"))
            r["harvests"] += 1
            if h.get("sense") not in r["senses"]:
                r["senses"].append(h.get("sense"))
        for a in hi.accepts:
            r = rec(a.get("spoke"))
            r["accepts"] += 1
            r["last_bound"] = a.get("bound")
            if a.get("improved"):
                r["binding_accepts"] += 1
        for x in hi.rejects:
            rec(x.get("spoke"))["rejects"] += 1
    for s in model.strikes:
        rec(s.get("spoke"))["strikes"] = max(
            rec(s.get("spoke"))["strikes"], s.get("strikes", 0))
    for d in model.disables:
        rec(d.get("spoke"))["disabled"] = True
    # who holds the final incumbent of each side: the LAST improving
    # accept per sense in the stream
    binding = {}
    for hi in sorted(model.iters.values(), key=lambda h: h.it):
        for a in hi.accepts:
            if a.get("improved"):
                binding[a.get("sense")] = {
                    "spoke": a.get("spoke"),
                    "class": model.spoke_classes.get(a.get("spoke")),
                    "bound": a.get("bound"), "iter": hi.it}
    return {"spokes": {str(k): v for k, v in sorted(spokes.items())},
            "final_bound_producer": binding}


def _dispatch_audit(model: RunModel) -> dict | None:
    if not model.megabatches and not model.dispatch_stats:
        return None
    out: dict = {}
    mbs = model.megabatches
    if mbs:
        lanes = sum(b.get("lanes", 0) for b in mbs)
        padded = sum(b.get("padded_to", 0) for b in mbs)
        out.update({
            "megabatches": len(mbs),
            "lanes": lanes,
            "occupancy_mean": round(lanes / padded, 4) if padded else None,
            "wait_ms_med": round(_median(
                [b.get("wait_ms", 0.0) for b in mbs]), 3),
            "wait_ms_max": round(max(b.get("wait_ms", 0.0)
                                     for b in mbs), 3),
            "queue_depth_max": max(b.get("queue_depth", 0) for b in mbs),
            "coalesced": sum(1 for b in mbs if b.get("requests", 1) > 1),
            "pre_wheel": sum(1 for b in mbs if (b.get("iter") or 0) < 0),
        })
        # occupancy attribution by dispatch cause (ISSUE 9 satellite):
        # a timer-heavy mix means windows expire before filling — the
        # occupancy loss is admission-deadline driven, not size driven
        by_cause: dict[str, dict] = {}
        for b in mbs:
            c = b.get("cause")
            if c is None:
                continue
            a = by_cause.setdefault(c, {"batches": 0, "lanes": 0,
                                        "padded": 0})
            a["batches"] += 1
            a["lanes"] += b.get("lanes", 0)
            a["padded"] += b.get("padded_to", 0)
        for a in by_cause.values():
            a["occupancy"] = (round(a["lanes"] / a["padded"], 4)
                              if a["padded"] else None)
        if by_cause:
            out["by_cause"] = by_cause
    if model.dispatch_stats:
        last = model.dispatch_stats[-1]
        out.update({
            "batches_total": last.get("batches"),
            "buckets": last.get("buckets"),
            "backend_compiles": last.get("backend_compiles"),
            "unexpected_recompiles": last.get("unexpected_recompiles"),
            "inflight_max": last.get("inflight_max"),
            "retries_total": last.get("retries_total"),
            "quarantined_lanes": last.get("quarantined_lanes"),
            "degraded": last.get("degraded"),
        })
        # compile-cache discipline: in steady state each shape bucket
        # compiles once; more compiles than buckets means the ladder is
        # leaking (docs/dispatch.md)
        b, c = last.get("buckets"), last.get("backend_compiles")
        if b and c is not None:
            out["compiles_per_bucket"] = round(c / b, 3)
        # per-coalesce-key occupancy (ISSUE 12 satellite): which
        # mergeable identities shared megabatches, across how many
        # sessions — megabatch sharing across tenants made attributable
        if last.get("by_key"):
            out["by_key"] = last["by_key"]
    return out


def _resilience_summary(model: RunModel) -> dict:
    by_seam: dict[str, int] = {}
    for f in model.faults:
        by_seam[f.get("seam", "?")] = by_seam.get(f.get("seam", "?"), 0) + 1
    return {
        "faults_injected": by_seam,
        "spoke_strikes": len(model.strikes),
        "spokes_disabled": len({d.get("spoke") for d in model.disables}),
        "bound_evictions": len(model.evicts),
        "lane_quarantine_resets": sum(q.get("resets", 0)
                                      for q in model.quarantines),
        "checkpoint_writes": len(model.ckpt_writes),
        "checkpoint_restores": len(model.ckpt_restores),
        "restore_fallbacks": sum(1 for c in model.ckpt_restores
                                 if c.get("fallback")),
        # dispatch fault domain (ISSUE 9; docs/dispatch.md)
        "dispatch_retries": len(model.dispatch_retries),
        "dispatch_quarantined_lanes": sum(
            q.get("lanes", 0) for q in model.dispatch_quarantines),
        "dispatch_quarantined_requests": len(model.dispatch_quarantines),
        "watchdog_trips": sum(1 for w in model.watchdogs
                              if w.get("action") in ("abort", "degrade")),
        "dispatcher_deaths": sum(1 for w in model.watchdogs
                                 if w.get("component") == "dispatcher"),
    }


def _fleet_summary(model: RunModel) -> dict | None:
    """Fleet rows for a session's run (ISSUE 16): where it was placed,
    how it moved.  None for non-fleet runs (no fleet events rode the
    trace)."""
    if not model.placements and not model.migrations:
        return None
    chain: list = []
    for p in model.placements:
        rep = p.get("replica")
        if rep and (not chain or chain[-1] != rep):
            chain.append(rep)
    policies: dict[str, int] = {}
    for p in model.placements:
        pol = p.get("policy", "?")
        policies[pol] = policies.get(pol, 0) + 1
    return {
        "placements": len(model.placements),
        "policies": policies,
        "replica_chain": chain,
        "migrations": len(model.migrations),
        "migrated_at_iters": [mg.get("iter") for mg in model.migrations
                              if mg.get("iter") is not None],
    }


def _mesh_summary(model: RunModel) -> dict | None:
    """Elastic-mesh rows (ISSUE 17): membership churn, host losses,
    reshards, and harvest degradations.  None when no mesh fault-domain
    events rode the trace (every pre-elastic run)."""
    if not (model.mesh_states or model.mesh_losses
            or model.mesh_reshards or model.mesh_stragglers):
        return None
    return {
        "transitions": len(model.mesh_states),
        "final_epoch": max(
            [int(s.get("epoch", 0)) for s in model.mesh_states],
            default=0),
        "hosts_lost": sorted({loss.get("host")
                              for loss in model.mesh_losses
                              if loss.get("host") is not None}),
        "reshards": [{"hub_iter": rs.get("hub_iter"),
                      "old_devices": rs.get("old_devices"),
                      "new_devices": rs.get("new_devices")}
                     for rs in model.mesh_reshards],
        "stragglers": sum(1 for s in model.mesh_stragglers
                          if s.get("mode") == "deadline"),
        "torn_harvests": sum(1 for s in model.mesh_stragglers
                             if s.get("mode") == "torn"),
    }


def _mpc_summary(model: RunModel) -> dict | None:
    """Rolling-horizon stream rows (ISSUE 19): one mpc-step event per
    solved window (docs/mpc.md), plus mpc-degraded for windows that
    missed the gap target warm AND cold.  None when the run is not an
    MPC stream."""
    if not model.mpc_steps and not model.mpc_degrades:
        return None
    lat = [s.get("latency_s") for s in model.mpc_steps
           if isinstance(s.get("latency_s"), (int, float))]
    gaps = [s.get("rel_gap") for s in model.mpc_steps
            if isinstance(s.get("rel_gap"), (int, float))]
    return {
        "steps": len(model.mpc_steps),
        "last_step": max([s.get("step") for s in model.mpc_steps
                          if s.get("step") is not None], default=None),
        "warm": sum(1 for s in model.mpc_steps if s.get("warm")),
        "cold_fallbacks": sum(1 for s in model.mpc_steps
                              if s.get("cold_fallback")),
        "degraded": sum(1 for s in model.mpc_steps if s.get("degraded")),
        "step_latency_p50_s": (round(_median(lat), 6) if lat else None),
        "step_latency_max_s": (round(max(lat), 6) if lat else None),
        "last_rel_gap": gaps[-1] if gaps else None,
        "degraded_at_steps": [d.get("step") for d in model.mpc_degrades
                              if d.get("step") is not None],
    }


def _async_wheel(model: RunModel) -> dict | None:
    """Plane-staleness + host/device overlap attribution for an async
    wheel run (ISSUE 11): how stale the exchange plane actually ran,
    how the per-sync host wall split between the issue and complete
    halves, and what fraction of the host exchange was absorbed on the
    stale side of the pipeline."""
    if not model.plane_writes and not model.overlaps:
        return None
    out: dict = {"plane_writes": len(model.plane_writes)}
    stal = [w.get("staleness") for w in model.plane_writes
            if isinstance(w.get("staleness"), (int, float))]
    if stal:
        out["staleness_mean"] = round(sum(stal) / len(stal), 3)
        out["staleness_max"] = max(stal)
    if model.overlaps:
        issue = [o.get("issue_s", 0.0) or 0.0 for o in model.overlaps]
        comp = [o.get("complete_s", 0.0) or 0.0 for o in model.overlaps]
        thetas = [o.get("theta") for o in model.overlaps
                  if isinstance(o.get("theta"), (int, float))]
        total = sum(issue) + sum(comp)
        out.update({
            "syncs": len(model.overlaps),
            "issue_s_total": round(sum(issue), 6),
            "complete_s_total": round(sum(comp), 6),
            "complete_s_med": round(_median(comp), 6),
            # share of the host exchange running on the stale side —
            # host work overlapping the in-flight device step
            "overlapped_host_frac": (round(sum(comp) / total, 4)
                                     if total > 0 else None),
        })
        if thetas:
            out["theta_last"] = thetas[-1]
            out["theta_min"] = min(thetas)
    return out


def _exit_info(model: RunModel) -> dict:
    if model.end is not None:
        d = dict(model.end.get("data", {}))
        d.setdefault("reason", "unknown")
        return d
    if model.header is not None:
        return {"reason": "truncated",
                "flight_reason": model.header.get("reason")}
    return {"reason": "truncated"}


def analyze(model: RunModel) -> dict:
    """The machine report: one JSON-able dict per run."""
    it_stats = _iteration_stats(model)
    bounds = _bound_progress(model)
    exit_info = _exit_info(model)
    # run-end carries the truly-final bounds (finalize's last harvest
    # can improve on the last hub-iteration row); prefer them
    for k in ("outer", "inner", "rel_gap"):
        v = _finite(exit_info.get(k))
        if v is not None:
            bounds[f"final_{k}"] = v
    rep = {
        "schema": ANALYZE_SCHEMA,
        "run": {
            "id": model.run,
            "hub_class": (model.start or {}).get("data", {})
            .get("hub_class"),
            "num_spokes": (model.start or {}).get("data", {})
            .get("num_spokes"),
            "events": len(model.rows),
            "exit": exit_info,
        },
        "iteration": it_stats,
        "phases": _phase_breakdown(model),
        "bounds": bounds,
        "attribution": _spoke_attribution(model),
        "dispatch": _dispatch_audit(model),
        "resilience": _resilience_summary(model),
        "kernel": model.kernel,
        "async_wheel": _async_wheel(model),
        "fleet": _fleet_summary(model),
        "mesh": _mesh_summary(model),
        "mpc": _mpc_summary(model),
    }
    flags = []
    stall = bounds.get("iters_since_outer_moved")
    n = max(1, it_stats["count"])
    if stall is not None and stall >= max(5, n // 2):
        flags.append(f"outer bound stalled for {stall} iterations")
    stall_i = bounds.get("iters_since_inner_moved")
    if stall_i is not None and stall_i >= max(5, n // 2):
        flags.append(f"inner bound stalled for {stall_i} iterations")
    if exit_info.get("reason") == "truncated":
        flags.append("stream truncated: no run-end event "
                     "(crash, kill, or tracing stopped mid-run)")
    disp = rep["dispatch"]
    if disp and (disp.get("unexpected_recompiles") or 0) > 0:
        flags.append(f"{disp['unexpected_recompiles']} unexpected "
                     "warm-bucket recompile(s)")
    if rep["resilience"]["spokes_disabled"]:
        flags.append(f"{rep['resilience']['spokes_disabled']} spoke(s) "
                     "auto-disabled")
    if rep["resilience"]["bound_evictions"]:
        flags.append(f"{rep['resilience']['bound_evictions']} incumbent "
                     "bound eviction(s)")
    if rep["resilience"]["dispatch_quarantined_lanes"]:
        flags.append(
            f"{rep['resilience']['dispatch_quarantined_lanes']} dispatch "
            f"lane(s) quarantined "
            f"({rep['resilience']['dispatch_quarantined_requests']} "
            "request(s) resolved SolveFailed)")
    if rep["resilience"]["watchdog_trips"]:
        flags.append(f"watchdog tripped "
                     f"{rep['resilience']['watchdog_trips']} time(s)")
    if rep["resilience"]["dispatcher_deaths"]:
        flags.append(f"{rep['resilience']['dispatcher_deaths']} "
                     "dispatcher-thread death(s) (tickets failed fast)")
    rep["flags"] = flags
    return rep


def profiled_window(model: RunModel) -> dict | None:
    """The hub-iteration window a ProfilerSession captured, from its
    profile events (start / stop-with-capture), plus the profile dir —
    the join between the host-span timeline and the device trace."""
    if not model.profiles:
        return None
    out: dict = {"profile_dir": None, "start_iter": None,
                 "stop_iter": None, "captured": False}
    for p in model.profiles:
        if p.get("profile_dir"):
            out["profile_dir"] = p["profile_dir"]
        if p.get("action") == "start":
            out["start_iter"] = p.get("iter")
        elif p.get("action") in ("stop", "captured"):
            # a close()-time capture (wheel finalized early) carries
            # iter=None — keep the last known boundary instead
            if p.get("iter") is not None:
                out["stop_iter"] = p["iter"]
        if p.get("action") == "captured" or p.get("trace_dir"):
            out["captured"] = True
            out["capture_dir"] = p.get("trace_dir")
    return out


def attach_device(rep: dict, profile_dir: str) -> dict:
    """Join a device-trace roofline report (telemetry/roofline.py) onto
    an analyzer report under rep['device'].  Parse problems become a
    flag, not a crash — a host report must survive a torn capture."""
    from mpisppy_tpu.telemetry import roofline
    try:
        dev = roofline.roofline_path(profile_dir)
    except (OSError, ValueError) as e:
        rep.setdefault("flags", []).append(
            f"device trace unreadable under {profile_dir!r}: {e}")
        return rep
    rep["device"] = dev
    host_spi = (rep.get("iteration") or {}).get("sec_per_iter_median")
    dev_spi = dev.get("device_sec_per_iter")
    if host_spi and dev_spi:
        # host sec/iter covers dispatch + python; the gap to device
        # time is the wheel's host-side overhead during the profiled
        # window
        rep["device"]["host_device_ratio"] = round(host_spi / dev_spi, 3)
    return rep


def analyze_path(path: str, run: str | None = None,
                 profile_dir: str | None = None) -> dict:
    """Analyze a JSONL trace; `profile_dir` (or a profile event in the
    trace pointing at a directory that exists here) joins the device
    section on.  `path` may be a trace DIRECTORY (the serve layer's
    per-session / per-replica layout): the newest session's segments
    are joined across files on their trace id, so a migrated session
    analyzes as ONE run instead of losing its pre-migration segment."""
    import os
    seg_files: list = []
    if os.path.isdir(path):
        from mpisppy_tpu.telemetry import spans as _spans
        rows, seg_files = join_segments(_spans.load_rows(path), run=run)
    else:
        rows = load_trace(path)
    model = build_run_model(rows, run=run)
    rep = analyze(model)
    if seg_files:
        rep["run"]["segment_files"] = seg_files
        rep["run"]["migrated_segments"] = max(0, len(seg_files) - 1)
    window = profiled_window(model)
    if window:
        rep["profiled_window"] = window
    if profile_dir is None and window and window.get("captured"):
        # auto-discovery trusts only a VERIFIED capture advertisement
        # (action "captured"): a bare profile_dir may hold a STALE
        # capture from an earlier run whose device numbers would be
        # silently joined to this one.  Prefer the exact capture dir
        # the event recorded over "newest under the root".
        import os
        for cand in (window.get("capture_dir"),
                     window.get("profile_dir")):
            if cand and os.path.isdir(cand):
                profile_dir = cand
                break
    if profile_dir:
        attach_device(rep, profile_dir)
    return rep


# ---------------------------------------------------------------------------
# the human rendering
# ---------------------------------------------------------------------------
def _fmt(v, spec=".6g"):
    return "-" if v is None else format(v, spec)


def render_report(rep: dict) -> str:
    L: list[str] = []
    r, ex = rep["run"], rep["run"]["exit"]
    L.append(f"run {r['id']}  hub={r.get('hub_class') or '?'}  "
             f"spokes={r.get('num_spokes', '?')}  events={r['events']}"
             + (f"  migrated segments {r['migrated_segments']} "
                f"({' + '.join(r.get('segment_files') or [])})"
                if r.get("migrated_segments") else ""))
    L.append(f"exit: {ex.get('reason')}"
             + (f"  rel_gap={_fmt(ex.get('rel_gap'), '.3e')}"
                if ex.get("rel_gap") is not None else "")
             + (f"  ({ex.get('flight_reason')})"
                if ex.get("flight_reason") else ""))
    it = rep["iteration"]
    L.append(f"iterations: {it['count']}  wall {_fmt(it['wall_s'], '.3f')}s"
             f"  sec/iter median {_fmt(it['sec_per_iter_median'], '.4g')}"
             f"  p90 {_fmt(it['sec_per_iter_p90'], '.4g')}")
    if rep["phases"]:
        L.append("phases (host wall):")
        for name, a in rep["phases"].items():
            L.append(f"  {name:<18} {a['total_s']:9.3f}s"
                     f"  {100 * a['share']:5.1f}%"
                     f"  x{a['calls']}  mean {a['mean_s']:.4g}s")
    b = rep["bounds"]
    L.append(f"bounds: outer {_fmt(b['final_outer'])}  "
             f"inner {_fmt(b['final_inner'])}  "
             f"rel_gap {_fmt(b['final_rel_gap'], '.3e')} "
             f"(min {_fmt(b['min_rel_gap'], '.3e')})")
    L.append(f"  stall: outer moved {_fmt(b['iters_since_outer_moved'])} "
             f"iters ago, inner {_fmt(b['iters_since_inner_moved'])}")
    for tgt, hit in b["time_to_gap"].items():
        if hit is not None:
            L.append(f"  gap<={tgt}: iter {hit['iter']}"
                     f" @ {_fmt(hit['seconds'], '.3f')}s")
    at = rep["attribution"]
    for sense, w in at["final_bound_producer"].items():
        L.append(f"  binding {sense}: spoke {w['spoke']}"
                 f" ({w.get('class') or '?'}) = {_fmt(w['bound'])}"
                 f" at iter {w['iter']}")
    if at["spokes"]:
        L.append("spokes:")
        for j, s in at["spokes"].items():
            L.append(f"  [{j}] {s.get('class') or '?':<28}"
                     f" harvests {s['harvests']:4d}  accepts"
                     f" {s['accepts']:4d} ({s['binding_accepts']} binding)"
                     f"  rejects {s['rejects']}  strikes {s['strikes']}"
                     + ("  DISABLED" if s["disabled"] else ""))
    d = rep["dispatch"]
    if d:
        L.append("dispatch:"
                 + (f" megabatches {d.get('megabatches')}"
                    f"  lanes {d.get('lanes')}"
                    f"  occupancy {_fmt(d.get('occupancy_mean'))}"
                    f"  wait_ms med {_fmt(d.get('wait_ms_med'))}"
                    f"/max {_fmt(d.get('wait_ms_max'))}"
                    if d.get("megabatches") else "")
                 + (f"  buckets {d.get('buckets')}"
                    f"  compiles {d.get('backend_compiles')}"
                    f" ({_fmt(d.get('compiles_per_bucket'))}/bucket)"
                    f"  unexpected {d.get('unexpected_recompiles')}"
                    if d.get("buckets") is not None else ""))
    aw = rep.get("async_wheel")
    if aw:
        L.append(f"async wheel: plane writes {aw.get('plane_writes')}"
                 f"  staleness mean {_fmt(aw.get('staleness_mean'))}"
                 f"/max {_fmt(aw.get('staleness_max'))}"
                 + (f"  host-complete {_fmt(aw.get('complete_s_total'), '.3f')}s"
                    f" ({_fmt(aw.get('overlapped_host_frac'))}"
                    f" of exchange wall on the stale side)"
                    if aw.get("syncs") else "")
                 + (f"  theta last {_fmt(aw.get('theta_last'), '.3g')}"
                    f"/min {_fmt(aw.get('theta_min'), '.3g')}"
                    if aw.get("theta_last") is not None else ""))
    fl = rep.get("fleet")
    if fl:
        L.append(f"fleet: placements {fl['placements']} "
                 f"{fl['policies']}  migrations {fl['migrations']}"
                 + (f"  path {'>'.join(fl['replica_chain'])}"
                    if fl["replica_chain"] else "")
                 + (f"  at iters {fl['migrated_at_iters']}"
                    if fl["migrated_at_iters"] else ""))
    msh = rep.get("mesh")
    if msh:
        L.append(f"mesh: epoch {msh['final_epoch']}  "
                 f"hosts lost {msh['hosts_lost'] or '[]'}  "
                 f"reshards {len(msh['reshards'])}"
                 + ("".join(f"  [{r['old_devices']}->"
                            f"{r['new_devices']}dev@iter"
                            f"{r['hub_iter']}]"
                            for r in msh["reshards"]))
                 + (f"  stragglers {msh['stragglers']}"
                    if msh["stragglers"] else "")
                 + (f"  torn harvests {msh['torn_harvests']}"
                    if msh["torn_harvests"] else ""))
    mpc = rep.get("mpc")
    if mpc:
        L.append(f"mpc stream: steps {mpc['steps']}"
                 f" (last {_fmt(mpc['last_step'], 'd')})"
                 f"  warm {mpc['warm']}"
                 f"  cold fallbacks {mpc['cold_fallbacks']}"
                 f"  degraded {mpc['degraded']}"
                 f"  step p50 {_fmt(mpc['step_latency_p50_s'], '.3g')}s"
                 f"/max {_fmt(mpc['step_latency_max_s'], '.3g')}s"
                 + (f"  last rel_gap {_fmt(mpc['last_rel_gap'], '.3e')}"
                    if mpc["last_rel_gap"] is not None else "")
                 + (f"  degraded at {mpc['degraded_at_steps']}"
                    if mpc["degraded_at_steps"] else ""))
    res = rep["resilience"]
    if any(v for v in res.values()):
        L.append(f"resilience: faults {res['faults_injected'] or '{}'}  "
                 f"strikes {res['spoke_strikes']}  "
                 f"disabled {res['spokes_disabled']}  "
                 f"evictions {res['bound_evictions']}  "
                 f"quarantine resets {res['lane_quarantine_resets']}  "
                 f"ckpt writes/restores {res['checkpoint_writes']}"
                 f"/{res['checkpoint_restores']}")
        if res.get("dispatch_retries") or res.get(
                "dispatch_quarantined_lanes") or res.get(
                "watchdog_trips") or res.get("dispatcher_deaths"):
            L.append(f"  dispatch fault domain: retries "
                     f"{res['dispatch_retries']}  quarantined lanes "
                     f"{res['dispatch_quarantined_lanes']} "
                     f"({res['dispatch_quarantined_requests']} requests)"
                     f"  watchdog trips {res['watchdog_trips']}"
                     f"  dispatcher deaths {res['dispatcher_deaths']}")
    for cyl, k in rep["kernel"].items():
        tot = k.get("pdhg_iterations_total")
        if tot is not None:
            L.append(f"kernel[{cyl}]: pdhg iters {tot}  restarts "
                     f"{k.get('pdhg_restarts_total')}  guard resets "
                     f"{k.get('pdhg_guard_resets_total')}")
    if rep.get("device"):
        from mpisppy_tpu.telemetry import roofline
        L.append("device (trace-derived; docs/telemetry.md):")
        w = rep.get("profiled_window") or {}
        if w.get("start_iter") is not None:
            L.append(f"  profiled hub iters [{w.get('start_iter')}, "
                     f"{w.get('stop_iter')})")
        L.extend("  " + ln
                 for ln in roofline.render_device(rep["device"])
                 .splitlines())
        ratio = rep["device"].get("host_device_ratio")
        if ratio is not None:
            L.append(f"  host/device sec-per-iter ratio {ratio}")
    if rep["flags"]:
        L.append("flags:")
        L.extend(f"  ! {f}" for f in rep["flags"])
    return "\n".join(L)
