###############################################################################
# Flight recorder: the wheel's black box (ISSUE 5 tentpole, part 2;
# docs/telemetry.md).
#
# A FlightRecorder is a bounded in-memory ring sink holding the LAST
# `capacity` (default 512) events of the stream.  It is registered by
# generic_cylinders on every decomposition run — including runs with
# --trace-jsonl OFF — and costs one slot store per event in steady
# state: the ring is preallocated at construction and only holds
# references to Event objects the bus already built, so a full ring
# never allocates (the deque-with-maxlen semantics without the node
# churn).
#
# When the wheel dies — PreemptionError (real signal or a FaultPlan
# trip), or any unhandled exception unwinding WheelSpinner.spin — the
# recorder dumps its window ATOMICALLY to `flight-<runid>.jsonl`: a
# `flight-recorder` header line (reason, drop count), then the buffered
# events as ordinary trace lines, oldest first.  The analyzer
# (telemetry/analyze.py) reads a flight dump exactly like a full
# --trace-jsonl stream, so "what were the last 512 things the wheel
# did" is one `python -m mpisppy_tpu.telemetry analyze` away even when
# nobody thought to turn tracing on before the crash.
###############################################################################
from __future__ import annotations

import json
import os
import threading
import time

from mpisppy_tpu.telemetry import events as ev
from mpisppy_tpu.telemetry.sinks import Sink

DEFAULT_CAPACITY = 512

#: header line kind (NOT a bus event kind: it exists only in dump files)
HEADER_KIND = "flight-recorder"


class FlightRecorder(Sink):
    """Bounded ring of the last `capacity` events, dumpable on crash."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: str = "."):
        self.capacity = max(1, int(capacity))
        self.dump_dir = dump_dir
        # handle() can run on the background checkpoint-writer daemon
        # (bus.emit is called from it) while dump() runs on the crash
        # path of the main thread — without this lock a dump racing an
        # emit could tear the ring snapshot (duplicate the newest
        # event into the oldest slot, drop the true oldest).  Lint-
        # enforced: tools/graftlint lock-discipline.
        self._lock = threading.Lock()
        self._ring: list = [None] * self.capacity   # guarded-by: _lock
        self._count = 0          # total events seen  # guarded-by: _lock
        self._run = ""           # last non-empty run  # guarded-by: _lock
        self.dumped_to: str | None = None  # last dump path (crash-path
                                           # thread only; read by tests
                                           # after the dump)

    # -- sink interface ---------------------------------------------------
    def handle(self, event: ev.Event) -> None:
        with self._lock:
            self._ring[self._count % self.capacity] = event
            self._count += 1
            if event.run:
                self._run = event.run

    # -- inspection -------------------------------------------------------
    def events(self) -> list:
        """Buffered events, oldest first (a consistent snapshot)."""
        with self._lock:
            n = min(self._count, self.capacity)
            start = self._count - n
            return [self._ring[i % self.capacity]
                    for i in range(start, self._count)]

    @property
    def run(self) -> str:
        with self._lock:
            return self._run or "unknown"

    @property
    def dropped(self) -> int:
        """Events that fell off the ring (seen minus buffered)."""
        with self._lock:
            return max(0, self._count - self.capacity)

    # -- the black-box dump -----------------------------------------------
    def dump(self, reason: str = "", path: str | None = None) -> str:
        """Write `flight-<runid>.jsonl` atomically (tmp + rename) and
        return its path.  Never raises: a crash handler is the worst
        place to add a second failure — on any error the best-effort
        path (or "") comes back and the original exception keeps
        propagating in the caller."""
        try:
            from mpisppy_tpu.utils.atomic_io import atomic_write_text
            if path is None:
                path = os.path.join(self.dump_dir,
                                    f"flight-{self.run}.jsonl")
            buffered = self.events()
            header = json.dumps({
                "kind": HEADER_KIND, "run": self.run, "reason": reason,
                "t_wall": time.time(), "capacity": self.capacity,
                "dumped_events": len(buffered), "dropped": self.dropped,
            })
            lines = [header] + [e.to_json() for e in buffered]
            atomic_write_text(path, "\n".join(lines) + "\n")
            self.dumped_to = path
            return path
        except Exception:
            return self.dumped_to or ""


def recorders_on(bus) -> list[FlightRecorder]:
    """The FlightRecorder sinks subscribed to `bus` ([] for None)."""
    if bus is None:
        return []
    return [s for s in bus.sinks if isinstance(s, FlightRecorder)]


def dump_all(bus, reason: str = "") -> list[str]:
    """Dump every recorder on `bus`; returns the written paths."""
    return [r.dump(reason=reason) for r in recorders_on(bus)]
