###############################################################################
# EventBus: the wheel's one reporting spine (docs/telemetry.md).
#
# Emitters (hub, fault plan, kernel harvest, console) publish typed
# events; subscribers (JSONL trace, console, metrics snapshot, the
# back-compat trace-list views) each see the full ordered stream.
# Design points:
#
#   * Thread-safe: checkpoint completions are reported from the
#     background writer daemon while the hub loop emits on the main
#     thread; a lock serializes sequence numbering and sink fan-out.
#   * Failure-isolated: a sink that raises is detached after
#     MAX_SINK_ERRORS consecutive failures — telemetry must never kill
#     (or wedge) the wheel it observes.
#   * Cheap when idle: a bus with no subscribers never constructs an
#     Event object, so library code can emit unconditionally.
###############################################################################
from __future__ import annotations

import threading

from mpisppy_tpu.telemetry import events as ev

MAX_SINK_ERRORS = 3


class EventBus:
    def __init__(self):
        # lint-enforced discipline (tools/graftlint lock-discipline):
        # sequence numbering and sink fan-out are serialized by _lock
        self._lock = threading.Lock()
        self._sinks: list = []             # guarded-by: _lock
        self._errors: dict[int, int] = {}  # guarded-by: _lock
        self._seq = 0                      # guarded-by: _lock
        self._trace = None                 # guarded-by: _lock
        self.closed = False                # guarded-by: _lock

    # -- trace scoping (ISSUE 20; telemetry/tracecontext.py) --------------
    def set_trace(self, ctx) -> None:
        """Scope the bus to a TraceContext: every subsequent emit is
        stamped with its (trace_id, span_id, parent_span_id) unless the
        emit passes an explicit `trace=`.  None clears the scope.  A
        per-session bus is scoped to the session's current segment
        span; a shared server/router bus stays unscoped and stamps
        per-emit."""
        with self._lock:
            self._trace = ctx

    @property
    def trace(self):
        """The current default TraceContext (None when unscoped)."""
        with self._lock:
            return self._trace

    # -- subscription -----------------------------------------------------
    def subscribe(self, sink) -> None:
        with self._lock:
            self._sinks.append(sink)

    def unsubscribe(self, sink) -> None:
        with self._lock:
            if sink in self._sinks:
                self._sinks.remove(sink)
            self._errors.pop(id(sink), None)

    @property
    def sinks(self) -> tuple:
        with self._lock:
            return tuple(self._sinks)

    # -- emission ---------------------------------------------------------
    def emit(self, kind: str, *, run: str = "", cyl: str = "",
             hub_iter: int | None = None, level: int | None = None,
             trace=None, **data) -> ev.Event | None:
        """Publish one event to every subscriber.  Returns the Event (or
        None when nobody is listening — the no-telemetry fast path).
        `trace=` overrides the bus-scoped TraceContext for this one
        event (the shared-bus attribution path)."""
        with self._lock:
            if not self._sinks or self.closed:
                return None
            self._seq += 1
            event = ev.make_event(kind, self._seq, run=run, cyl=cyl,
                                  hub_iter=hub_iter, level=level,
                                  trace=(trace if trace is not None
                                         else self._trace),
                                  data=data)
            dead = []
            last_err: dict[int, BaseException] = {}
            for sink in self._sinks:
                try:
                    sink.handle(event)
                    self._errors.pop(id(sink), None)
                except Exception as e:
                    n = self._errors.get(id(sink), 0) + 1
                    self._errors[id(sink)] = n
                    last_err[id(sink)] = e
                    if n >= MAX_SINK_ERRORS:
                        dead.append(sink)
            for sink in dead:
                self._sinks.remove(sink)
                # drop the stale count: a later sink object can reuse
                # this id (CPython address reuse) and must start at 0
                self._errors.pop(id(sink), None)
        # warn OUTSIDE the lock, and never through console.log (an
        # attached bus would re-enter emit on this non-reentrant lock):
        # a silently vanishing --trace-jsonl artifact is worse than a
        # stderr line
        for sink in dead:
            import sys
            e = last_err.get(id(sink))
            sys.stderr.write(
                f"[telemetry] detached sink {type(sink).__name__} after "
                f"{MAX_SINK_ERRORS} consecutive failures "
                f"({type(e).__name__ if e else '?'}: {e})\n")
        return event

    def close(self) -> None:
        """Flush + detach every sink; the bus then drops all events."""
        with self._lock:
            sinks, self._sinks = self._sinks, []
            self.closed = True
        for sink in sinks:
            try:
                sink.close()
            except Exception:
                pass
