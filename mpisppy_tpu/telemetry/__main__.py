###############################################################################
# `python -m mpisppy_tpu.telemetry <analyze|compare|gate>` — the trace
# toolbox CLI (ISSUE 5; docs/telemetry.md).  Pure host-side stdlib: runs
# on any machine holding a trace, no jax required.
#
#   analyze --trace-jsonl T [--run ID] [--json]
#       per-phase wall-time breakdown, bound progress + stalls,
#       per-spoke bound attribution, dispatch audit, crash forensics —
#       T may be a --trace-jsonl stream OR a flight-<runid>.jsonl dump.
#   compare OLD NEW [--json]
#       diff the perf metrics of two artifacts (analyzer --json
#       reports, BENCH_DETAIL.json, or BENCH_r0N.json wrappers).
#   gate OLD NEW [--threshold KEY=FRAC ...] [--json]
#       compare + direction-aware thresholds; exit 2 on a regression.
###############################################################################
from __future__ import annotations

import argparse
import json
import sys


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpisppy_tpu.telemetry",
        description="wheel trace analyzer / perf-regression gate")
    sub = p.add_subparsers(dest="cmd", required=True)

    pa = sub.add_parser("analyze", help="analyze a JSONL wheel trace")
    pa.add_argument("--trace-jsonl", required=True,
                    help="trace file (--trace-jsonl output or a "
                         "flight-<runid>.jsonl black box)")
    pa.add_argument("--run", default=None,
                    help="run id to analyze (default: last in stream)")
    pa.add_argument("--json", action="store_true",
                    help="machine report instead of the human rendering")

    for name, hlp in (("compare", "diff two perf artifacts"),
                      ("gate", "compare + thresholds; exit 2 on "
                               "regression")):
        pc = sub.add_parser(name, help=hlp)
        pc.add_argument("old")
        pc.add_argument("new")
        pc.add_argument("--json", action="store_true")
        if name == "gate":
            pc.add_argument("--threshold", action="append", default=[],
                            metavar="KEY=FRAC",
                            help="override: metric-key substring = "
                                 "relative threshold (repeatable)")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "analyze":
        from mpisppy_tpu.telemetry import analyze as an
        try:
            rep = an.analyze_path(args.trace_jsonl, run=args.run)
        except (OSError, ValueError) as e:
            print(f"analyze: {e}", file=sys.stderr)
            return 1
        print(json.dumps(rep) if args.json else an.render_report(rep))
        return 0

    from mpisppy_tpu.telemetry import regress
    overrides = {}
    for spec in getattr(args, "threshold", []):
        try:
            key, frac = spec.split("=", 1)
            overrides[key] = float(frac)
        except ValueError:
            print(f"bad --threshold {spec!r} (want KEY=FRAC)",
                  file=sys.stderr)
            return 1
    try:
        if args.cmd == "gate":
            rep = regress.gate_paths(args.old, args.new, overrides)
        else:
            rep = regress.compare_paths(args.old, args.new)
    except (OSError, ValueError) as e:
        print(f"{args.cmd}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(rep) if args.json
          else regress.render_compare(rep, only_gated=False))
    if args.cmd == "gate" and not rep["ok"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
