###############################################################################
# `python -m mpisppy_tpu.telemetry <analyze|compare|gate>` — the trace
# toolbox CLI (ISSUE 5; docs/telemetry.md).  Pure host-side stdlib: runs
# on any machine holding a trace, no jax required.
#
#   analyze [--trace-jsonl T] [--profile-dir D] [--run ID] [--json]
#       per-phase wall-time breakdown, bound progress + stalls,
#       per-spoke bound attribution, dispatch audit, crash forensics —
#       T may be a --trace-jsonl stream OR a flight-<runid>.jsonl dump.
#       --profile-dir joins the DEVICE section (trace-derived roofline:
#       measured_stream_gbps, achieved HBM GB/s, MFU, DMA/compute
#       overlap_frac) from a jax.profiler capture; with --trace-jsonl
#       alone, a capture advertised by the run's `profile` events is
#       auto-discovered.  --profile-dir alone renders the device-only
#       report.
#   watch --trace-jsonl T [--metrics-snapshot M] [--interval S] [--once]
#       live-tail a RUNNING wheel: bound/gap, sec/iter, dispatch
#       occupancy, quarantine counts; --once prints one snapshot.
#   watch --trace-dir D [--interval S] [--once]
#       live-tail a DIRECTORY of per-session traces (the serve layer
#       writes one per session) as a per-tenant session table.
#   trace [ID] (--trace-dir D | --trace-jsonl T) [--json]
#       assemble one causal span tree per trace id across per-session /
#       per-replica / fleet JSONL segments (ISSUE 20): span hierarchy,
#       migration/reshard spans, critical-path latency buckets; exit 2
#       on orphan spans (a dropped propagation hop).
#   slo (--trace-dir D | --trace-jsonl T | --bench B) [--json]
#       evaluate the declarative SLOs (slo.DEFAULT_SLOS) into error
#       budgets + burn rates from slo-observation rows or a committed
#       BENCH artifact; exit 2 on a violated budget.
#   compare OLD NEW [--json]
#       diff the perf metrics of two artifacts (analyzer --json
#       reports, device roofline reports, BENCH_DETAIL.json, or
#       BENCH_r0N.json wrappers).
#   gate OLD NEW [--threshold KEY=FRAC ...] [--milestones] [--json]
#       compare + direction-aware thresholds + absolute milestone
#       floors (ratchet by default, strict with --milestones); exit 2
#       on a regression.
###############################################################################
from __future__ import annotations

import argparse
import json
import sys


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpisppy_tpu.telemetry",
        description="wheel trace analyzer / perf-regression gate")
    sub = p.add_subparsers(dest="cmd", required=True)

    pa = sub.add_parser("analyze", help="analyze a JSONL wheel trace "
                                        "and/or a device capture")
    pa.add_argument("--trace-jsonl", default=None,
                    help="trace file (--trace-jsonl output or a "
                         "flight-<runid>.jsonl black box)")
    pa.add_argument("--profile-dir", default=None,
                    help="jax.profiler capture dir (--profile-dir of "
                         "the run, or bench.py's profile_trace_S*): "
                         "adds the trace-derived device section")
    pa.add_argument("--run", default=None,
                    help="run id to analyze (default: last in stream)")
    pa.add_argument("--json", action="store_true",
                    help="machine report instead of the human rendering")

    pw = sub.add_parser("watch", help="live-tail a running wheel's "
                                      "trace + metrics snapshot, or a "
                                      "serve trace directory")
    pw.add_argument("--trace-jsonl", default=None,
                    help="the running wheel's --trace-jsonl path")
    pw.add_argument("--trace-dir", default=None,
                    help="a directory of per-session JSONL traces "
                         "(the serve layer writes one per session; "
                         "docs/serving.md) — renders the per-tenant "
                         "session table instead of the single-run "
                         "status block")
    pw.add_argument("--metrics-snapshot", default=None,
                    help="the wheel's --metrics-snapshot file "
                         "(Prometheus text) to fold into the display")
    pw.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds (default 2)")
    pw.add_argument("--once", action="store_true",
                    help="print one status snapshot and exit")

    pt = sub.add_parser("trace", help="assemble one causal span tree "
                                      "from per-session/fleet JSONL "
                                      "segments (ISSUE 20)")
    pt.add_argument("trace_id", nargs="?", default=None,
                    help="full trace id, a unique prefix, or 'last' "
                         "(default: the only trace present)")
    pt.add_argument("--trace-dir", default=None,
                    help="directory of JSONL segments (per-session, "
                         "per-replica subdirs, router stream) to join")
    pt.add_argument("--trace-jsonl", default=None,
                    help="a single JSONL trace file")
    pt.add_argument("--json", action="store_true",
                    help="machine report (schema mpisppy-tpu-trace/1)")

    ps = sub.add_parser("slo", help="evaluate SLO error budgets / "
                                    "burn rates from traces or a "
                                    "committed bench artifact")
    ps.add_argument("--trace-dir", default=None,
                    help="trace dir: fold its slo-observation rows")
    ps.add_argument("--trace-jsonl", default=None,
                    help="a single JSONL trace file")
    ps.add_argument("--bench", default=None,
                    help="a BENCH_r*.json artifact: evaluate its "
                         "serve/fleet/MPC sections")
    ps.add_argument("--json", action="store_true")

    for name, hlp in (("compare", "diff two perf artifacts"),
                      ("gate", "compare + thresholds; exit 2 on "
                               "regression")):
        pc = sub.add_parser(name, help=hlp)
        pc.add_argument("old")
        pc.add_argument("new")
        pc.add_argument("--json", action="store_true")
        if name == "gate":
            pc.add_argument("--threshold", action="append", default=[],
                            metavar="KEY=FRAC",
                            help="override: metric-key substring = "
                                 "relative threshold (repeatable)")
            pc.add_argument("--milestones", action="store_true",
                            help="make the absolute MILESTONE bounds "
                                 "(S=10k sec_per_iter <= 0.045, S=100k "
                                 "iters/s >= 2) bind even when the old "
                                 "artifact predates the win; default "
                                 "is ratchet semantics (bind once "
                                 "landed)")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    if args.cmd == "analyze":
        from mpisppy_tpu.telemetry import analyze as an
        if not args.trace_jsonl and not args.profile_dir:
            print("analyze: need --trace-jsonl and/or --profile-dir",
                  file=sys.stderr)
            return 1
        try:
            if args.trace_jsonl:
                rep = an.analyze_path(args.trace_jsonl, run=args.run,
                                      profile_dir=args.profile_dir)
                text = an.render_report(rep)
            else:
                # device-only: the roofline report straight from the
                # capture (the ISSUE 7 acceptance path)
                from mpisppy_tpu.telemetry import roofline
                rep = roofline.roofline_path(args.profile_dir)
                text = roofline.render_device(rep)
        except (OSError, ValueError) as e:
            print(f"analyze: {e}", file=sys.stderr)
            return 1
        print(json.dumps(rep) if args.json else text)
        return 0

    if args.cmd == "watch":
        from mpisppy_tpu.telemetry import watch as w
        if bool(args.trace_jsonl) == bool(args.trace_dir):
            print("watch: need exactly one of --trace-jsonl / "
                  "--trace-dir", file=sys.stderr)
            return 1
        if args.trace_dir:
            return w.watch_dir(args.trace_dir, interval=args.interval,
                               once=args.once)
        return w.watch(args.trace_jsonl,
                       metrics_path=args.metrics_snapshot,
                       interval=args.interval, once=args.once)

    if args.cmd == "trace":
        from mpisppy_tpu.telemetry import spans
        path = args.trace_dir or args.trace_jsonl
        if not path:
            print("trace: need --trace-dir or --trace-jsonl",
                  file=sys.stderr)
            return 1
        try:
            rep = spans.assemble_path(path, trace=args.trace_id)
        except (OSError, ValueError) as e:
            print(f"trace: {e}", file=sys.stderr)
            return 1
        print(json.dumps(rep) if args.json
              else spans.render_trace(rep))
        return 0 if not rep["orphans"] else 2

    if args.cmd == "slo":
        from mpisppy_tpu.telemetry import regress, slo
        path = args.trace_dir or args.trace_jsonl
        if bool(path) == bool(args.bench):
            print("slo: need exactly one of --trace-dir/--trace-jsonl "
                  "or --bench", file=sys.stderr)
            return 1
        try:
            if args.bench:
                rep = slo.evaluate_bench(
                    regress.load_artifact(args.bench))
            else:
                rep = slo.evaluate_path(path)
        except (OSError, ValueError) as e:
            print(f"slo: {e}", file=sys.stderr)
            return 1
        slo.export_metrics(rep)
        print(json.dumps(rep) if args.json else slo.render_slo(rep))
        return 0 if all(r["ok"] for r in rep["slo"].values()) else 2

    from mpisppy_tpu.telemetry import regress
    overrides = {}
    for spec in getattr(args, "threshold", []):
        try:
            key, frac = spec.split("=", 1)
            overrides[key] = float(frac)
        except ValueError:
            print(f"bad --threshold {spec!r} (want KEY=FRAC)",
                  file=sys.stderr)
            return 1
    try:
        if args.cmd == "gate":
            rep = regress.gate_paths(
                args.old, args.new, overrides,
                milestones=getattr(args, "milestones", False))
        else:
            rep = regress.compare_paths(args.old, args.new)
    except (OSError, ValueError) as e:
        print(f"{args.cmd}: {e}", file=sys.stderr)
        return 1
    print(json.dumps(rep) if args.json
          else regress.render_compare(rep, only_gated=False))
    if args.cmd == "gate" and not rep["ok"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
