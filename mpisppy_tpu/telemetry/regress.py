###############################################################################
# Perf-regression compare/gate (ISSUE 5 tentpole, part 3;
# docs/telemetry.md).
#
# `compare` diffs the perf metrics of two artifacts; `gate` applies
# direction-aware thresholds and fails (exit 2 from the CLI) on a
# regression — the mechanical guard the ROADMAP north star needs so
# sec/iter, backend-compile, and time-to-certified-gap regressions are
# caught by CI instead of by eyeballing BENCH_*.json diffs.
#
# Accepted artifact forms (auto-detected per file):
#   * an analyzer report (telemetry/analyze.py --json output;
#     schema mpisppy-tpu-analyze/1), including its `device` section;
#   * a device roofline report (telemetry/roofline.py; schema
#     mpisppy-tpu-deviceprof/1) — stream/achieved GB/s, overlap_frac,
#     MFU and device_sec_per_iter gate direction-aware (ISSUE 7);
#   * a BENCH_DETAIL.json-style dict (bench.py output: *_to_1pct_gap
#     sections, wheel_overhead, measured_mfu, sweep_iters_per_sec,
#     embedded metrics_snapshot / dispatch stats);
#   * a BENCH_r0N.json driver wrapper whose `tail` holds the (possibly
#     front-TRUNCATED) bench stdout: named sections are salvaged by
#     balanced-brace extraction, so the committed r04/r05 fixtures gate
#     on their recoverable overlap instead of failing to parse.
#
# Metrics are flattened to dotted keys; GATES maps key patterns to
# (direction, relative threshold).  Only keys present in BOTH artifacts
# are gated — a metric that disappeared is reported, not failed (bench
# sections legitimately come and go across rounds).
###############################################################################
from __future__ import annotations

import json
import re

ANALYZE_SCHEMA_PREFIX = "mpisppy-tpu-analyze/"
DEVPROF_SCHEMA_PREFIX = "mpisppy-tpu-deviceprof/"

#: (key regex, direction, relative threshold).  direction "up" = larger
#: is worse, "down" = smaller is worse.  First match wins; keys that
#: match nothing are compared but never gated.
GATES: tuple[tuple[str, str, float], ...] = (
    (r"(^|\.)sec_per_iter", "up", 0.10),
    (r"(^|\.)seconds_to_gap$", "up", 0.15),
    (r"(^|\.)time_to_gap\.", "up", 0.15),
    (r"(^|\.)iters_per_sec$", "down", 0.10),
    (r"(^|\.)overhead_factor$", "up", 0.15),
    (r"backend_compiles", "up", 0.10),
    (r"unexpected_recompiles", "up", 0.0),
    (r"guard_resets", "up", 0.0),
    (r"(^|\.)final_rel_gap$", "up", 0.25),
    # dispatch fault domain (ISSUE 9): on the committed bench fixtures
    # any retry growth or quarantined lane is a regression — the bench
    # workloads are fault-free by construction, so these counters only
    # move when the dispatch layer itself started failing
    (r"(retries_total|dispatch_retries)$", "up", 0.0),
    (r"quarantined_lanes", "up", 0.0),
    (r"quarantined_requests", "up", 0.0),
    (r"(watchdog_trips|dispatcher_deaths)", "up", 0.0),
    # device-trace roofline metrics (telemetry/roofline.py, ISSUE 7):
    # bandwidth, DMA/compute overlap and MFU falling is a regression;
    # device time per iteration rising is one.  Together with the
    # MILESTONES below these guard the ROADMAP item-2 / ISSUE-8 wins
    # (bf16x3 iteration precision, Pallas double-buffer).
    (r"measured_stream_gbps", "down", 0.10),
    (r"achieved_hbm_gbps", "down", 0.10),
    (r"hbm_roofline_frac", "down", 0.10),
    (r"overlap_frac", "down", 0.10),
    (r"(^|\.)mfu$", "down", 0.10),
    (r"device_sec_per_iter", "up", 0.10),
    (r"dma\.exposed_s$", "up", 0.25),
    # multi-tenant serve layer (ISSUE 12; BENCH serve_load phase):
    # client-observed latency under load and the tenant-isolation
    # ratio regressing is a serving regression (docs/serving.md)
    (r"serve_load\..*time_to_gap_p(50|99)_s$", "up", 0.25),
    (r"(^|\.)isolation_ratio$", "up", 0.25),
    # IR-level kernel facts (ISSUE 15; KERNEL_IR.json, docs/
    # static_analysis.md "IR layer"): bytes of concrete array
    # constants baked into a kernel's jaxpr may NEVER grow (any growth
    # is a new baked value — the per-value recompile-leak class), and
    # the compiled temp-byte high-water per kernel ratchets at +10%
    # (a materialized S-major temporary in a VirtualBatch-fed kernel
    # multiplies it)
    (r"kernels\..*\.const_bytes$", "up", 0.0),
    (r"kernels\..*\.temp_bytes$", "up", 0.10),
    # replicated serve fleet (ISSUE 16; BENCH fleet_serve_load phase):
    # a migration that loses its session is ALWAYS a regression — the
    # counter must stay 0 (any increase fails).  Latency/isolation on
    # the fleet phase ride the serve_load\..* and isolation_ratio
    # patterns above unchanged (the phase is named fleet_serve_load,
    # and the gates' searches are unanchored).
    (r"migrations_lost", "up", 0.0),
    # elastic mesh (ISSUE 17; BENCH mesh_chaos phase): a re-shard that
    # loses its run is ALWAYS a regression — the counter stays 0.
    # watchdog_trips rides the any-increase gate above unchanged.
    (r"mesh_reshards_lost", "up", 0.0),
    # rolling-horizon MPC streams (ISSUE 19; BENCH mpc_stream phase):
    # client-observed per-step latency on the committed uc / ccopf
    # horizons regressing past 25% is a serving regression — the warm
    # path's whole point is the per-window latency class (docs/mpc.md)
    (r"mpc_stream\..*step_latency_p(50|99)_s$", "up", 0.25),
    # SLO plane (ISSUE 20; telemetry/slo.py): committed artifacts carry
    # per-class `slo` sections (burn_rate = violating fraction over the
    # error budget).  Budget consumption growing past 25% relative is a
    # serving regression even while still inside the budget; the
    # absolute <= 1.0 ceiling lives in MILESTONES below.
    (r"slo\..*\.burn_rate$", "up", 0.25),
    (r"slo\..*\.budget_remaining$", "down", 0.25),
)

#: absolute slack added on top of the relative threshold, so integer
#: counters (compiles, guard resets) tolerate tiny absolute wiggle
ABS_SLACK = {"backend_compiles": 2.0, "guard_resets": 2.0,
             "unexpected_recompiles": 0.0}

#: Absolute MILESTONE bounds (ISSUE 8 acceptance / ROADMAP item 2):
#: (key regex, direction, bound).  direction "up": the value must stay
#: <= bound; "down": >= bound.  Unlike the relative GATES these are
#: floors/ceilings on the NEW artifact, with RATCHET semantics: a
#: milestone only BINDS once the old artifact already meets it (the
#: win has landed on hardware) — before that it is reported "pending",
#: so pre-win fixture pairs keep gating green while a landed win can
#: never silently regress past its acceptance line.  `gate(...,
#: milestones=True)` / CLI --milestones forces every milestone to bind
#: regardless (the strict mode CI runs on post-win artifacts).
MILESTONES: tuple[tuple[str, str, float], ...] = (
    # bf16x3 on the S=10k PH iteration: 0.0601 s/iter measured at full
    # precision (BENCH_r05 / BENCH_DETAIL measured_mfu.S10000)
    (r"measured_mfu\.S10000\.sec_per_iter$", "up", 0.045),
    # double-buffered Pallas window at S=100k: 1.46 iters/s measured
    # with the single-buffer kernel (sweep entries key by scenario
    # count — extract_metrics rewrites list indices to S<count>)
    (r"sweep_iters_per_sec\.S100000\.iters_per_sec$", "down", 2.0),
    # async wheel (ISSUE 11; ROADMAP item 4): wheel overhead over bare
    # PH at staleness 1 must reach <= 1.3x (2.41x measured synchronous,
    # BENCH_DETAIL wheel_overhead).  Ratchet: pending until witnessed
    # on hardware, binding forever after.
    (r"wheel_overhead_async\.overhead_factor$", "up", 1.3),
    # multi-tenant serve (ISSUE 12 acceptance): healthy-tenant p99
    # time-to-gap under one adversarial tenant within 25% of the
    # no-adversary baseline — the tenant-isolation line the serve_load
    # bench phase measures (docs/serving.md)
    (r"serve_load\.isolation\.isolation_ratio$", "up", 1.25),
    # seeded scenario synthesis (ISSUE 14 acceptance; docs/scengen.md):
    # recompute-instead-of-store must cost <= 10% PH throughput at the
    # max common scale both paths hold resident...
    (r"wheel_scengen\.synth_vs_materialized_ratio$", "down", 0.9),
    # ...and the S=1M synthesized sweep entry must EXIST (bound 0 is a
    # presence ratchet: any measured throughput meets it, but dropping
    # the S=1M phase — the "as many scenarios as you can imagine"
    # witness — fails as MISSING once an artifact has carried it)
    (r"wheel_scengen\.sweep\.S1000000\.iters_per_sec$", "down", 0.0),
    # wheel fleet (ISSUE 16 acceptance; docs/serving.md fleet
    # section): every session a replica death forced to migrate must
    # still certify to the SAME gap target as the fault-free run —
    # the migrated-reached-gap fraction is 1.0 or the live-migration
    # story is fiction
    (r"fleet_serve_load\.migration\.migrated_reached_gap_frac$",
     "down", 1.0),
    # elastic mesh (ISSUE 17 acceptance; docs/resilience.md): a run
    # that survives a mid-wheel host loss must re-shard across the
    # survivors and STILL certify the same gap target as the
    # fault-free baseline — anything under 1.0 means a reshard lost
    # certified progress
    (r"mesh_chaos\..*reshard_reached_gap_frac$", "down", 1.0),
    # rolling-horizon MPC (ISSUE 19 acceptance; docs/mpc.md): mean
    # warm step latency pooled over the committed uc + ccopf --soc
    # horizons must stay <= 0.6x the matching cold re-solves — below
    # that the receding-horizon product is just repeated cold solves
    # (the phase's per-model detail records each horizon's own ratio)
    (r"mpc_stream\.warm_over_cold_ratio$", "up", 0.6),
    # ...and a stream preempted mid-flight must resume and reproduce
    # the fault-free stream's per-step bounds exactly (bit-identical
    # window data + the checkpointed shifted plane): the matched
    # fraction is 1.0 or the resume story is fiction
    (r"mpc_stream\..*resumed_matched_frac$", "down", 1.0),
    # SLO plane (ISSUE 20 acceptance; docs/telemetry.md SLO table): a
    # committed artifact's per-class burn rate must never exceed 1.0 —
    # an exhausted error budget IS the violated SLO, regardless of how
    # gently it got there (the relative gate above catches the drift)
    (r"slo\..*\.burn_rate$", "up", 1.0),
)


# ---------------------------------------------------------------------------
# artifact loading + metric extraction
# ---------------------------------------------------------------------------
def _salvage_tail(tail: str) -> dict:
    """Recover named JSON sections from a (front-truncated) bench
    stdout tail: for every `"name": {` seen, try a balanced-brace parse;
    also pick up top-level scalars like `"bench_total_sec": 1012.3`.
    Sections cut off by the truncation simply don't parse and are
    skipped — salvage is best-effort by design."""
    out: dict = {}
    spans: list[tuple[int, int]] = []  # captured section extents
    for mt in re.finditer(r'"(\w+)":\s*(\{|\[)', tail):
        if any(a <= mt.start() < b for a, b in spans):
            continue  # nested inside an already-salvaged section
        name = mt.group(1)
        depth, i = 0, mt.end(2) - 1
        in_str = esc = False
        for i in range(mt.end(2) - 1, len(tail)):
            ch = tail[i]
            if in_str:
                if esc:
                    esc = False
                elif ch == "\\":
                    esc = True
                elif ch == '"':
                    in_str = False
                continue
            if ch == '"':
                in_str = True
            elif ch in "{[":
                depth += 1
            elif ch in "}]":
                depth -= 1
                if depth == 0:
                    break
        if depth != 0:
            continue
        try:
            val = json.loads(tail[mt.end(2) - 1:i + 1])
        except ValueError:
            continue
        if name not in out:
            out[name] = val
            spans.append((mt.start(), i + 1))
    # top-level scalars: whitelist only — a bare regex would hoist
    # NESTED scalars ("seconds_to_gap": ... inside whichever section
    # survived the truncation) to top level and diff unrelated sections
    # against each other
    for key in ("bench_total_sec",):
        ms = re.search(rf'"{key}":\s*(-?\d+(?:\.\d+)?)', tail)
        if ms and key not in out:
            out[key] = float(ms.group(1))
    return out


def load_artifact(path: str) -> dict:
    """Load + normalize one artifact file into a bench-style dict (or
    an analyzer report, passed through).  Driver wrappers carry the
    bench stdout in `tail` (salvaged); assembled wrappers (e.g. the
    committed BENCH_r06.json, built from prior on-TPU captures in a
    round whose container had no chip) carry the sections directly in
    `parsed`."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, dict) and isinstance(obj.get("parsed"), dict):
        return obj["parsed"]
    if isinstance(obj, dict) and isinstance(obj.get("tail"), str) \
            and "cmd" in obj:
        return _salvage_tail(obj["tail"])
    return obj


def _flatten(prefix: str, obj, out: dict) -> None:
    if isinstance(obj, dict):
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(obj, list):
        # bench lists key by scenario count when the entries carry one
        # (the sweep) — "sweep_iters_per_sec.S100000.iters_per_sec"
        # stays comparable across rounds even when the sweep grid
        # changes, and is what MILESTONES anchors on; other lists keep
        # stable positional keys
        for i, v in enumerate(obj):
            key = i
            if isinstance(v, dict) \
                    and isinstance(v.get("scenarios"), (int, float)) \
                    and not isinstance(v.get("scenarios"), bool):
                key = f"S{int(v['scenarios'])}"
            _flatten(f"{prefix}.{key}", v, out)
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix] = float(obj)


def _device_metrics(dev: dict, out: dict, prefix: str = "device"):
    """Gate-relevant keys of a roofline report (telemetry/roofline.py),
    shared by standalone device reports and analyzer rep['device']."""
    for k in ("device_sec_per_iter", "measured_stream_gbps",
              "achieved_hbm_gbps", "hbm_roofline_frac", "mfu",
              "overlap_frac", "opaque_frac"):
        if isinstance(dev.get(k), (int, float)) \
                and not isinstance(dev.get(k), bool):
            out[f"{prefix}.{k}"] = float(dev[k])
    dma = dev.get("dma") or {}
    for k in ("exposed_s", "inflight_s"):
        if isinstance(dma.get(k), (int, float)):
            out[f"{prefix}.dma.{k}"] = float(dma[k])
    med = (dev.get("steps") or {}).get("sec_per_iter_median")
    if isinstance(med, (int, float)):
        out[f"{prefix}.steps.sec_per_iter_median"] = float(med)


def extract_metrics(obj: dict) -> dict[str, float]:
    """Flatten an artifact into {dotted_key: number}.  Analyzer reports
    keep only the gate-relevant sections (timings, bounds, dispatch,
    guard totals) so two reports of different runs stay comparable."""
    out: dict[str, float] = {}
    schema = obj.get("schema", "") if isinstance(obj, dict) else ""
    if isinstance(schema, str) and schema.startswith(
            DEVPROF_SCHEMA_PREFIX):
        _device_metrics(obj, out, prefix="device")
        return out
    if isinstance(schema, str) and schema.startswith(
            ANALYZE_SCHEMA_PREFIX):
        _flatten("iteration", obj.get("iteration") or {}, out)
        b = obj.get("bounds") or {}
        for k in ("final_rel_gap", "min_rel_gap"):
            if isinstance(b.get(k), (int, float)):
                out[f"bounds.{k}"] = float(b[k])
        for tgt, hit in (b.get("time_to_gap") or {}).items():
            if isinstance(hit, dict) and hit.get("seconds") is not None:
                out[f"time_to_gap.{tgt}"] = float(hit["seconds"])
        disp = dict(obj.get("dispatch") or {})
        # per-coalesce-key rows are labeled with a per-process digest
        # (dispatch/scheduler._key_label) — never comparable across
        # runs, so they inform the audit but not the gate
        disp.pop("by_key", None)
        _flatten("dispatch", disp, out)
        res = obj.get("resilience") or {}
        for k in ("dispatch_retries", "dispatch_quarantined_lanes",
                  "dispatch_quarantined_requests", "watchdog_trips",
                  "dispatcher_deaths", "lane_quarantine_resets"):
            if isinstance(res.get(k), (int, float)) \
                    and not isinstance(res.get(k), bool):
                out[f"resilience.{k}"] = float(res[k])
        for cyl, k in (obj.get("kernel") or {}).items():
            if isinstance(k, dict) \
                    and k.get("pdhg_guard_resets_total") is not None:
                out[f"kernel.{cyl}.guard_resets"] = float(
                    k["pdhg_guard_resets_total"])
        if isinstance(obj.get("device"), dict):
            _device_metrics(obj["device"], out, prefix="device")
        out.pop("iteration.count", None)
        return out
    _flatten("", obj, out)
    # noise keys that vary run to run without meaning anything (by_key
    # rows carry a per-process coalesce-key digest in their name)
    drop = re.compile(r"(t_wall|timestamp|seed|\.n$|\.rc$|\.by_key\.)")
    return {k: v for k, v in out.items() if not drop.search(k)}


# ---------------------------------------------------------------------------
# compare + gate
# ---------------------------------------------------------------------------
def _gate_for(key: str):
    for pat, direction, thr in GATES:
        if re.search(pat, key):
            return direction, thr
    return None, None


def compare(old: dict, new: dict,
            _metrics: tuple[dict, dict] | None = None) -> dict:
    """Diff two artifacts.  Returns rows for common keys plus the
    appeared/disappeared key lists.  `_metrics` lets gate() pass
    already-extracted metric maps so each artifact is flattened once
    per invocation."""
    mo, mn = _metrics or (extract_metrics(old), extract_metrics(new))
    rows = []
    for k in sorted(set(mo) & set(mn)):
        a, b = mo[k], mn[k]
        delta = b - a
        rel = delta / abs(a) if a else (0.0 if not delta else float("inf"))
        direction, thr = _gate_for(k)
        regressed = False
        if direction is not None:
            slack = next((s for pat, s in ABS_SLACK.items()
                          if pat in k), 0.0)
            worse = delta if direction == "up" else -delta
            regressed = worse > thr * abs(a) + slack
        rows.append({"metric": k, "old": a, "new": b,
                     "delta": delta, "rel": rel,
                     "gated": direction is not None,
                     "direction": direction, "threshold": thr,
                     "regressed": regressed})
    return {
        "schema": "mpisppy-tpu-regress/1",
        "rows": rows,
        "common": len(rows),
        "appeared": sorted(set(mn) - set(mo)),
        "disappeared": sorted(set(mo) - set(mn)),
        "regressions": [r for r in rows if r["regressed"]],
        "ok": not any(r["regressed"] for r in rows),
    }


def _meets(value: float, direction: str, bound: float) -> bool:
    return value <= bound if direction == "up" else value >= bound


def _milestone_rows(mo: dict, mn: dict, strict: bool) -> list[dict]:
    """Evaluate MILESTONES over the new artifact's metrics (ratchet
    semantics — see the table's comment).

    A milestone key ABSENT from the new artifact is itself a failure
    whenever the bound would have bound: dropping the phase from the
    bench (or renaming the key) must not become a silent regression
    path.  Ratchet mode fails a landed key that disappeared; strict
    mode additionally fails a pattern with no match anywhere (strict
    is the post-win bench CI mode — an artifact without the milestone
    phases has no business passing it)."""
    rows = []
    for pat, direction, bound in MILESTONES:
        matched_new = False
        for k in sorted(mn):
            if not re.search(pat, k):
                continue
            matched_new = True
            new, old = mn[k], mo.get(k)
            landed = old is not None and _meets(old, direction, bound)
            binding = strict or landed
            met = _meets(new, direction, bound)
            rows.append({
                "metric": k, "milestone": bound,
                "direction": direction, "old": old, "new": new,
                "binding": binding,
                "regressed": binding and not met,
                "status": ("met" if met
                           else ("REGRESSED" if binding else "pending")),
            })
        if matched_new:
            continue
        old_hits = [k for k in sorted(mo) if re.search(pat, k)]
        landed_old = [k for k in old_hits
                      if _meets(mo[k], direction, bound)]
        if strict or landed_old:
            # readable stand-in when neither artifact carries the key
            # (a raw regex is not a metric name)
            fallback = pat.replace("\\.", ".").rstrip("$")
            for k in (landed_old or old_hits or [fallback]):
                rows.append({
                    "metric": k, "milestone": bound,
                    "direction": direction,
                    "old": mo.get(k), "new": None,
                    "binding": True, "regressed": True,
                    "status": "MISSING"})
    return rows


def gate(old: dict, new: dict,
         overrides: dict[str, float] | None = None,
         milestones: bool = False) -> dict:
    """compare() with per-call threshold overrides ({key substring:
    relative threshold}) plus the MILESTONE floors/ceilings.  `ok` is
    the pass/fail verdict; the CLI maps it to the exit code.
    `milestones=True` makes every milestone bind even when the old
    artifact predates the win (strict mode)."""
    mo, mn = extract_metrics(old), extract_metrics(new)
    rep = compare(old, new, _metrics=(mo, mn))
    if overrides:
        for r in rep["rows"]:
            for sub, thr in overrides.items():
                if sub in r["metric"]:
                    direction = r["direction"] or "up"
                    a, delta = r["old"], r["delta"]
                    worse = delta if direction == "up" else -delta
                    r["gated"] = True
                    r["direction"] = direction
                    r["threshold"] = thr
                    r["regressed"] = worse > thr * abs(a)
        rep["regressions"] = [r for r in rep["rows"] if r["regressed"]]
        rep["ok"] = not rep["regressions"]
    ms = _milestone_rows(mo, mn, milestones)
    rep["milestones"] = ms
    failed_ms = [r for r in ms if r["regressed"]]
    if failed_ms:
        # fold milestone failures into `regressions` in the compare-row
        # schema (consumers iterate one list), deduped against metrics
        # the relative gates already failed
        already = {r["metric"] for r in rep["regressions"]}
        for r in failed_ms:
            if r["metric"] in already:
                continue
            delta = (None if r["old"] is None or r["new"] is None
                     else r["new"] - r["old"])
            rel = (delta / abs(r["old"])
                   if delta is not None and r["old"] else None)
            rep["regressions"].append({
                "metric": r["metric"], "old": r["old"], "new": r["new"],
                "delta": delta, "rel": rel, "gated": True,
                "direction": r["direction"],
                "threshold": r["milestone"], "regressed": True,
                "milestone": r["milestone"], "status": r["status"]})
        rep["ok"] = False
    if not rep["rows"]:
        # two artifacts with NO overlapping metrics cannot certify
        # anything — fail loudly rather than green-light a vacuous diff
        rep["ok"] = False
        rep["error"] = "no common metrics between the two artifacts"
    return rep


def compare_paths(old_path: str, new_path: str) -> dict:
    return compare(load_artifact(old_path), load_artifact(new_path))


def gate_paths(old_path: str, new_path: str,
               overrides: dict[str, float] | None = None,
               milestones: bool = False) -> dict:
    return gate(load_artifact(old_path), load_artifact(new_path),
                overrides, milestones=milestones)


def render_compare(rep: dict, only_gated: bool = False) -> str:
    L = []
    for r in rep["rows"]:
        if only_gated and not r["gated"]:
            continue
        mark = "REGRESSED" if r["regressed"] else (
            "gated" if r["gated"] else "")
        L.append(f"{r['metric']:<52} {r['old']:>12.6g} -> "
                 f"{r['new']:>12.6g}  ({r['rel']:+7.2%})  {mark}".rstrip())
    for r in rep.get("milestones") or []:
        cmp_c = "<=" if r["direction"] == "up" else ">="
        shown = "absent" if r["new"] is None else format(r["new"], ".6g")
        L.append(f"milestone {r['metric']:<42} {shown:>12} "
                 f"{cmp_c} {r['milestone']:g}  [{r['status']}]")
    if rep["disappeared"]:
        L.append(f"disappeared: {', '.join(rep['disappeared'][:8])}"
                 + (" ..." if len(rep["disappeared"]) > 8 else ""))
    if rep["appeared"]:
        L.append(f"appeared: {', '.join(rep['appeared'][:8])}"
                 + (" ..." if len(rep["appeared"]) > 8 else ""))
    if rep.get("error"):
        L.append(f"ERROR: {rep['error']}")
    verdict = "PASS" if rep["ok"] else \
        f"FAIL ({len(rep['regressions'])} regression(s))"
    L.append(f"{rep['common']} common metrics; gate: {verdict}")
    return "\n".join(L)
