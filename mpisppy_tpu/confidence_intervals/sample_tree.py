###############################################################################
# Sampled subtrees for multistage evaluation
# (ref:mpisppy/confidence_intervals/sample_tree.py:23-318).
#
# SampleSubtree builds a sampled multistage batch (module must expose
# make_tree(branching_factors) and a seedable scenario_creator — e.g.
# models.aircond's start_seed) and solves its EF, optionally with the
# first `fixed_stages` stages pinned at given xhats.
#
# walking_tree_xhats (ref:sample_tree.py:191-260): a feasible,
# nonanticipative policy for EVERY non-leaf node.  The reference
# resolves one subtree per node recursively; here ONE EF solve of the
# sampled tree with the root fixed already produces nonanticipative
# per-node values — we read the per-node averages of the EF solution
# (exact consensus by the EF's nonant rows) as the node xhats.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.ops import pdhg


class SampleSubtree:
    """ref:sample_tree.py:23."""

    def __init__(self, module, xhats, branching_factors, seed: int,
                 cfg, opts: pdhg.PDHGOptions | None = None):
        self.module = module
        self.xhats = None if xhats is None or len(xhats) == 0 \
            else np.asarray(xhats, np.float64)
        self.branching_factors = tuple(int(b) for b in branching_factors)
        self.seed = seed
        self.cfg = cfg
        self.opts = opts or pdhg.PDHGOptions(tol=1e-7, max_iters=200_000)
        self.EF_obj = None
        self.ef = None
        self.seed_provenance = None

    def _scengen_program(self, num: int, kw: dict):
        """The sampled tree's ScenarioProgram when the module ships one
        and the cfg opts in; None falls back to the legacy node-seeded
        RandomState path (scengen.program_from_cfg owns the gate +
        audible fallback).  The tree's branching factors and base seed
        come from THIS subtree, not the cfg."""
        from mpisppy_tpu.scengen.program import program_from_cfg
        return program_from_cfg(
            self.module, self.cfg, num, seed=self.seed,
            drop=("start_seed", "branching_factors"),
            branching_factors=self.branching_factors)

    def run(self):
        from mpisppy_tpu.algos.ef import ExtensiveForm
        import math
        kw = dict(self.module.kw_creator(self.cfg))
        kw["branching_factors"] = self.branching_factors
        if _accepts_start_seed(self.module):
            kw["start_seed"] = self.seed
        num = math.prod(self.branching_factors)
        names = self.module.scenario_names_creator(num)
        tree = self.module.make_tree(self.branching_factors)
        creator = self.module.scenario_creator
        prog = self._scengen_program(num, kw)
        if prog is not None:
            # draw the subtree through scengen keys: node draws fold
            # the tree-node id into PRNGKey(self.seed) instead of
            # seeding a RandomState per node — same node-sharing
            # structure, layout-invariant draws, and a provenance
            # record (docs/scengen.md)
            from mpisppy_tpu.utils.sputils import extract_num
            self.seed_provenance = prog.provenance()

            def creator(name, **_kw):
                return prog.spec_at(extract_num(name))
        self.ef = ExtensiveForm({"tol": self.opts.tol,
                                 "max_iters": self.opts.max_iters},
                                names, creator, kw,
                                tree=tree)
        if self.xhats is not None:
            # pin the leading stage slots at the given xhats
            self.ef.fix_root_nonants(self.xhats)
        st = self.ef.solve_extensive_form()
        self.EF_obj = self.ef.get_objective_value()
        self._state = st
        return self.EF_obj


def _accepts_start_seed(module) -> bool:
    """True if scenario_creator can receive start_seed — either as an
    explicit named parameter or through a **kw VAR_KEYWORD catch-all
    (aircond takes it via **kw; dropping it there would make every
    sampled subtree identical, ref:sample_tree.py:137-138)."""
    import inspect
    params = inspect.signature(module.scenario_creator).parameters
    if "start_seed" in params:
        return True
    return any(p.kind is inspect.Parameter.VAR_KEYWORD
               for p in params.values())


def walking_tree_xhats(module, xhat_one, branching_factors, seed, cfg,
                       opts: pdhg.PDHGOptions | None = None):
    """Per-node xhats for a sampled tree with the root pinned at
    xhat_one (ref:sample_tree.py:191-260).  Returns
    (xhats (num_nodes, N), next_seed)."""
    st = SampleSubtree(module, xhat_one, branching_factors, seed, cfg,
                       opts)
    st.run()
    batch_tree = st.ef.ef.tree
    sol = st.ef.x                             # (S, n) original space
    nonant_idx = np.asarray(st.ef.ef.nonant_idx)
    x_non = sol[:, nonant_idx]
    # pin the root block to xhat_one, average the rest per node
    node_of_slot = np.asarray(batch_tree.node_of_slot())
    N = x_non.shape[1]
    num_nodes = batch_tree.num_nodes
    xhats = np.zeros((num_nodes, N))
    counts = np.zeros((num_nodes, N))
    cols = np.broadcast_to(np.arange(N), node_of_slot.shape)
    np.add.at(xhats, (node_of_slot, cols), x_non)
    np.add.at(counts, (node_of_slot, cols), 1.0)
    xhats = np.divide(xhats, np.maximum(counts, 1.0))
    n_root = int(np.asarray(xhat_one).shape[-1])
    xhats[0, :n_root] = np.asarray(xhat_one)
    next_seed = seed + _number_of_nodes(branching_factors)
    return xhats, next_seed


def _number_of_nodes(branching_factors) -> int:
    """TOTAL node-id count consumed by node-seeded samplers (aircond
    keys its RandomState by node_idx over ALL stages including the
    leaves, ref:aircond.py:44-75) — advancing by less would overlap the
    seed streams of consecutive sampled trees and correlate the
    'independent' samples."""
    total, acc = 1, 1
    for b in branching_factors:
        acc *= b
        total += acc
    return total
