###############################################################################
# zhat4xhat: estimate the objective-value distribution of a fixed
# candidate x̂ over sampled trees
# (ref:mpisppy/confidence_intervals/zhat4xhat.py:22-207).
#
# Two-stage: each "tree" is a batch of sampled scenarios; z_hat_j =
# E_batch[f(x̂, xi)] via one batched fixed-nonant evaluation.
# Multistage: each tree is a SampleSubtree solved with the root pinned
# at x̂ (a feasible nonanticipative policy, sample_tree).
###############################################################################
from __future__ import annotations

import math

import numpy as np
import scipy.stats

from mpisppy_tpu import global_toc
from mpisppy_tpu.ops import pdhg


def evaluate_sample_trees(xhat_one, num_samples: int, cfg,
                          module, InitSeed: int = 0,
                          branching_factors=None,
                          opts: pdhg.PDHGOptions | None = None):
    """(zhats array, next_seed) (ref:zhat4xhat.py:22-110)."""
    opts = opts or pdhg.PDHGOptions(tol=1e-7, max_iters=200_000)
    seed = InitSeed
    zhats = []
    if branching_factors is None:
        branching_factors = cfg.get("branching_factors")
    if branching_factors:  # multistage
        from mpisppy_tpu.confidence_intervals.sample_tree import (
            SampleSubtree, _number_of_nodes,
        )
        for _ in range(num_samples):
            st = SampleSubtree(module, xhat_one, branching_factors,
                               seed, cfg, opts)
            zhats.append(st.run())
            seed += _number_of_nodes(branching_factors)
    else:
        from mpisppy_tpu.algos import xhat as xhat_mod
        from mpisppy_tpu.core import batch as batch_mod
        import jax.numpy as jnp
        batch_size = int(cfg["num_scens"])
        kw = module.kw_creator(cfg)
        for _ in range(num_samples):
            names = module.scenario_names_creator(batch_size,
                                                  start=seed)
            specs = [module.scenario_creator(nm, **kw) for nm in names]
            b = batch_mod.from_specs(specs)
            res = xhat_mod.evaluate(
                b, jnp.asarray(np.asarray(xhat_one)), opts)
            zhats.append(float(res.value))
            seed += batch_size
    return np.array(zhats), seed


def run_samples(cfg, module, xhat_one=None, num_samples: int = 10,
                confidence_level: float = 0.95):
    """The zhat4xhat driver (ref:zhat4xhat.py:107-180): t-interval on
    E[f(x̂)] from the sampled zhats."""
    if xhat_one is None:
        from mpisppy_tpu.confidence_intervals.ciutils import read_xhat
        xhat_one = read_xhat(cfg["xhatpath"])
    zhats, seed = evaluate_sample_trees(xhat_one, num_samples, cfg,
                                        module)
    zhatbar = float(np.mean(zhats))
    s_zhat = float(np.std(zhats, ddof=1)) if len(zhats) > 1 else 0.0
    t = scipy.stats.t.ppf(0.5 + confidence_level / 2.0,
                          max(len(zhats) - 1, 1))
    eps_z = t * s_zhat / math.sqrt(max(len(zhats), 1))
    global_toc(f"zhatbar = {zhatbar:.6g} +/- {eps_z:.6g} "
               f"({confidence_level:.0%} CI)", True)
    return zhatbar, eps_z
