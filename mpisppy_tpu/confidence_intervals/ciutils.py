###############################################################################
# CI utilities (ref:mpisppy/confidence_intervals/ciutils.py:141-445).
#
# gap_estimators is the statistical core: sample n scenarios, solve the
# induced approximate problem (EF) for (z_n*, x*), evaluate the
# candidate x̂ AND x* on every sampled scenario, and form the
# Mak-Morton-Wood gap estimator
#   G = E_n[f(x̂, xi) - f(x*, xi)],  s^2 = (E[g^2] - G^2)/(1 - ||p||^2)
# (ref:ciutils.py:404-427).  On TPU both evaluations are ONE batched
# fixed-nonant solve each over the sampled batch, and the EF is the
# batched EF kernel — no Gurobi, no Amalgamator process machinery.
###############################################################################
from __future__ import annotations

import math

import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.ops import pdhg


def write_xhat(xhat, path: str = "xhat.npy"):
    """ref:ciutils.py:156-161 — flat npy of the root xhat."""
    np.save(path, np.asarray(xhat, np.float64))


def read_xhat(path: str = "xhat.npy", delete_file: bool = False):
    """ref:ciutils.py:163-173."""
    xhat = np.load(path)
    if delete_file:
        import os
        os.remove(path)
    return xhat


def branching_factors_from_numscens(numscens: int,
                                    num_stages: int) -> list[int]:
    """Even branching factors whose product is >= numscens
    (ref:ciutils.py:126-139)."""
    if num_stages == 2:
        return [numscens]
    stages = num_stages - 1
    b = max(2, int(math.ceil(numscens ** (1.0 / stages))))
    return [b] * stages


def scalable_branching_factors(numscens: int,
                               ref_bfs) -> list[int]:
    """Scale the model's branching factors so the product is close to
    (>=) numscens while keeping the shape (ref:ciutils.py:104-124)."""
    ref_bfs = list(ref_bfs)
    prod = int(np.prod(ref_bfs))
    if prod >= numscens:
        return ref_bfs
    fac = (numscens / prod) ** (1.0 / len(ref_bfs))
    return [max(b, int(math.ceil(b * fac))) for b in ref_bfs]


def correcting_numeric(G: float, objfct: float,
                       relative_error: bool = True,
                       threshold: float = 1e-4) -> float:
    """Clip small negative G from numerical error (ref:ciutils.py:191-211,
    minimization)."""
    crit = threshold * abs(objfct) if relative_error else threshold
    if G <= -crit:
        global_toc(f"WARNING: gap estimator has the wrong sign: {G}",
                   True)
        return G
    return max(0.0, G)


def _sample_specs(module, scenario_names, cfg):
    kw = module.kw_creator(cfg)
    return [module.scenario_creator(nm, **kw) for nm in scenario_names]


def _scengen_program(module, cfg, num: int, start: int):
    """The scengen replication program for this sample (draws from
    fold_in(PRNGKey(scengen_seed), start + s) — layout-invariant and
    exactly reproducible from the seed_provenance record alone), or
    None for the legacy stream; scengen.program_from_cfg owns the
    opt-in gate, the model-kwarg forwarding, and the audible
    fallback (docs/scengen.md)."""
    from mpisppy_tpu.scengen.program import program_from_cfg
    return program_from_cfg(module, cfg, num, start=start)


def gap_estimators(xhat_one, module, scenario_names, cfg,
                   ArRP: int = 1,
                   opts: pdhg.PDHGOptions | None = None,
                   verbose: bool = False) -> dict:
    """G and s at x̂ from one sampled batch (ref:ciutils.py:214-433;
    two-stage — the multistage path lives in sample_tree).

    Returns {"G", "s", "seed", "zn_star", "xstar"}; the pooled ArRP>1
    path returns only {"G", "s", "seed"} (matching the reference,
    ref:ciutils.py:291-319)."""
    from mpisppy_tpu.algos import xhat as xhat_mod
    from mpisppy_tpu.algos.ef import build_ef
    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.utils.sputils import extract_num
    import jax.numpy as jnp

    opts = opts or pdhg.PDHGOptions(tol=1e-7, max_iters=200_000)
    start = extract_num(scenario_names[0])

    if ArRP > 1:
        # pooled estimators (ref:ciutils.py:291-319); the recursive
        # ArRP=1 call pins each pool's probabilities itself
        n = len(scenario_names)
        if n % ArRP != 0:
            raise ValueError(
                f"{n} scenarios is not a multiple of ArRP={ArRP}; "
                "silently dropping the tail would desynchronize "
                "seed accounting (the reference raises too)")
        Gs, ss = [], []
        for k in range(ArRP):
            part = scenario_names[k * (n // ArRP):(k + 1) * (n // ArRP)]
            est = gap_estimators(xhat_one, module, part, cfg,
                                 ArRP=1, opts=opts)
            Gs.append(est["G"])
            ss.append(est["s"])
        return {"G": float(np.mean(Gs)),
                "s": float(np.linalg.norm(ss) / np.sqrt(n // ArRP)),
                "seed": start + n}

    # the sample IS the distribution: uniform probabilities over the
    # sampled scenarios (ref:ciutils.py:344-349 quick_assign num_scens
    # and _mpisppy_probability on an ephemeral cfg)
    import copy
    cfg = copy.deepcopy(cfg)
    cfg.quick_assign("num_scens", int, len(scenario_names))
    prog = _scengen_program(module, cfg, len(scenario_names), start)
    if prog is not None:
        specs = prog.to_specs()
    else:
        specs = _sample_specs(module, scenario_names, cfg)
    b = batch_mod.from_specs(specs)

    # solve the sampled EF for (zn_star, x*)
    efp = build_ef(specs)
    st = pdhg.solve(efp.qp, opts, pdhg.init_state(efp.qp, opts))
    n0 = specs[0].c.shape[0]
    nonant_idx = np.asarray(specs[0].nonant_idx)
    d0 = np.asarray(efp.scaling.d_col)[:n0] \
        if getattr(efp, "scaling", None) is not None else np.ones(n0)
    xstar = (np.asarray(st.x)[:n0] * d0)[nonant_idx]

    # evaluate xhat and xstar on every sampled scenario (batched)
    ev_xhat = xhat_mod.evaluate(b, jnp.asarray(np.asarray(xhat_one)),
                                opts)
    ev_xstar = xhat_mod.evaluate(b, jnp.asarray(xstar), opts)
    # an infeasible candidate has NO defined gap: per_scenario would
    # hold the arbitrary objective of a frozen iterate
    if not bool(ev_xhat.feasible):
        raise RuntimeError(
            "gap_estimators: xhat is infeasible for some sampled "
            "scenario (recourse evaluation failed); the gap is "
            "undefined for this candidate")
    if not bool(ev_xstar.feasible):
        raise RuntimeError(
            "gap_estimators: the sampled-EF solution failed its own "
            "recourse evaluation (solver tolerance issue)")
    f_hat = np.asarray(ev_xhat.per_scenario, np.float64)
    f_star = np.asarray(ev_xstar.per_scenario, np.float64)
    p = np.asarray(b.p, np.float64)

    gaps = f_hat - f_star
    G = float(np.dot(gaps, p))
    ssq = float(np.dot(gaps * gaps, p))
    prob_sqnorm = float(np.dot(p, p))
    sample_var = max((ssq - G * G) / max(1.0 - prob_sqnorm, 1e-12), 0.0)
    s = math.sqrt(sample_var)

    obj_at_xhat = float(np.dot(f_hat, p))
    G = correcting_numeric(G, objfct=obj_at_xhat,
                           relative_error=abs(obj_at_xhat) > 1)
    if verbose:
        global_toc(f"gap estimator: G={G:.6g} s={s:.6g}", True)
    out = {"G": G, "s": s, "seed": start + len(scenario_names),
           "zn_star": float(np.dot(f_star, p)), "xstar": xstar}
    if prog is not None:
        out["seed_provenance"] = prog.provenance()
    return out


def gap_estimators_mstage(xhat_one, module, n_trees: int, cfg,
                          start_seed: int, branching_factors,
                          opts: pdhg.PDHGOptions | None = None) -> dict:
    """Multistage gap estimators over independently sampled scenario
    TREES (ref:mpisppy/confidence_intervals/multi_seqsampling.py:31-340
    and ciutils gap_estimators' EF_mstage branch): each i.i.d. sample i
    is a seeded subtree; z*_i is its free EF optimum, z_xhat_i the EF
    with the root pinned at xhat (a feasible nonanticipative policy via
    sample_tree.SampleSubtree).  Both use the SAME seed — common random
    numbers, the reference's variance-reduction choice.

    Returns {"G", "s", "seed"} with seed advanced by the node-id count
    of every sampled tree."""
    from mpisppy_tpu.confidence_intervals.sample_tree import (
        SampleSubtree, _number_of_nodes,
    )

    gaps = []
    zhats = []
    seed = start_seed
    for _ in range(n_trees):
        free = SampleSubtree(module, None, branching_factors, seed, cfg,
                             opts)
        zstar = free.run()
        fixed = SampleSubtree(module, xhat_one, branching_factors, seed,
                              cfg, opts)
        zxhat = fixed.run()
        gaps.append(zxhat - zstar)
        zhats.append(zxhat)
        seed += _number_of_nodes(branching_factors)
    gaps = np.asarray(gaps, np.float64)
    G = float(np.mean(gaps))
    s = float(np.std(gaps, ddof=1)) if len(gaps) > 1 else 0.0
    obj = float(np.mean(zhats))
    G = correcting_numeric(G, objfct=obj, relative_error=abs(obj) > 1)
    return {"G": G, "s": s, "seed": seed}
