###############################################################################
# Sequential sampling to a target optimality-gap CI
# (ref:mpisppy/confidence_intervals/seqsampling.py:114-520).
#
# Bayraksan-Morton (BM, fixed-width) and Bayraksan-Pierre-Louis (BPL,
# fully sequential / stochastic) procedures: grow the sample until the
# gap estimate at the current candidate x̂ clears the stopping rule,
# with the reference's exact sample-size recursions
# (ref:seqsampling.py:269-333).
###############################################################################
from __future__ import annotations

import math

import numpy as np
import scipy.stats

from mpisppy_tpu import global_toc
from mpisppy_tpu.confidence_intervals import ciutils


class SeqSampling:
    """ref:seqsampling.py:114.  `module` is a model module;
    `xhat_generator(scenario_names, **kw) -> root xhat array`."""

    def __init__(self, module, xhat_generator, cfg,
                 stochastic_sampling: bool = False,
                 stopping_criterion: str = "BM",
                 solving_type: str = "EF_2stage"):
        if solving_type != "EF_2stage":
            raise RuntimeError("only EF_2stage sequential sampling is "
                               "supported (ref parity: EF only)")
        self.module = module
        self.xhat_generator = xhat_generator
        self.cfg = cfg
        self.stochastic_sampling = stochastic_sampling
        self.stopping_criterion = stopping_criterion
        self.sample_size_ratio = cfg.get("sample_size_ratio", 1)
        self.xhat_gen_kwargs = cfg.get("xhat_gen_kwargs", {}) or {}
        self.confidence_level = cfg.get("confidence_level", 0.95)
        self.ArRP = cfg.get("ArRP", 1)
        self.kf_Gs = cfg.get("kf_Gs", 1)
        self.kf_xhat = cfg.get("kf_xhat", 1)
        # BM parameters (ref:seqsampling.py defaults)
        self.BM_h = cfg.get("BM_h", 1.75)
        self.BM_hprime = cfg.get("BM_hprime", 0.5)
        self.BM_eps = cfg.get("BM_eps", 0.2)
        self.BM_eps_prime = cfg.get("BM_eps_prime", 0.1)
        self.BM_p = cfg.get("BM_p", 0.191)
        self.BM_q = cfg.get("BM_q", 1.2)
        # BPL parameters
        self.BPL_eps = cfg.get("BPL_eps", 0.5)
        self.BPL_c0 = cfg.get("BPL_c0", 50)
        self.BPL_c1 = cfg.get("BPL_c1", 10)
        self.BPL_n0min = cfg.get("BPL_n0min", 50)
        # default growth_function is linear in k (ref:seqsampling.py
        # growth_function default = (k-1))
        self.growth_function = cfg.get("growth_function", None) \
            or (lambda k: k - 1)

        if stopping_criterion == "BM":
            self.stop_criterion = self.bm_stopping_criterion
        elif stopping_criterion == "BPL":
            self.stop_criterion = self.bpl_stopping_criterion
        else:
            raise RuntimeError("Only BM and BPL criteria are supported.")
        if self.stochastic_sampling:
            self.sample_size = self.stochastic_sampsize
        elif stopping_criterion == "BM":
            self.sample_size = self.bm_sampsize
        else:
            self.sample_size = self.bpl_fsp_sampsize
        self.ScenCount = 0

    # -- stopping rules (ref:seqsampling.py:269-278) ----------------------
    def bm_stopping_criterion(self, G, s, nk):
        return G > self.BM_hprime * s + self.BM_eps_prime

    def bpl_stopping_criterion(self, G, s, nk):
        t = scipy.stats.t.ppf(self.confidence_level, nk - 1)
        return G + t * s / math.sqrt(nk) + 1.0 / math.sqrt(nk) \
            > self.BPL_eps

    # -- sample sizes (ref:seqsampling.py:280-333) ------------------------
    def bm_sampsize(self, k, G, s, nk_m1, r=2):
        p, q = self.BM_p, self.BM_q
        h, hprime = self.BM_h, self.BM_hprime
        j = np.arange(1, 1000)
        if q is None:
            if not hasattr(self, "c"):
                ssum = float(np.sum(np.power(j.astype(float),
                                             -p * np.log(j))))
                self.c = max(1.0, 2 * math.log(
                    ssum / (math.sqrt(2 * math.pi)
                            * (1 - self.confidence_level))))
            lower = (self.c + 2 * p * math.log(k) ** 2) \
                / ((h - hprime) ** 2)
        else:
            if q < 1:
                raise RuntimeError("Parameter q should be greater "
                                   "than 1.")
            if not hasattr(self, "c"):
                ssum = float(np.sum(np.exp(-p * np.power(
                    j.astype(float), 2 * q / r))))
                self.c = max(1.0, 2 * math.log(
                    ssum / (math.sqrt(2 * math.pi)
                            * (1 - self.confidence_level))))
            lower = (self.c + 2 * p * k ** (2 * q / r)) \
                / ((h - hprime) ** 2)
        return int(math.ceil(lower))

    def bpl_fsp_sampsize(self, k, G, s, nk_m1):
        return int(math.ceil(self.BPL_c0
                             + self.BPL_c1 * self.growth_function(k)))

    def stochastic_sampsize(self, k, G, s, nk_m1):
        if k == 1:
            return int(math.ceil(max(self.BPL_n0min,
                                     math.log(1.0 / self.BPL_eps))))
        t = scipy.stats.t.ppf(self.confidence_level, nk_m1 - 1)
        a = -self.BPL_eps
        b = 1.0 + t * s
        c = nk_m1 * G
        disc = max(b * b - 4 * a * c, 0.0)
        maxroot = -(math.sqrt(disc) + b) / (2 * a)
        return int(math.ceil(maxroot ** 2))

    # -- the driver (ref:seqsampling.py:335-520) --------------------------
    def run(self, maxit: int = 200) -> dict:
        module = self.module
        mult = self.sample_size_ratio
        k = 1
        lower_bound_k = self.sample_size(k, None, None, None)

        mk = int(math.floor(mult * lower_bound_k))
        xhat_names = module.scenario_names_creator(mk,
                                                   start=self.ScenCount)
        self.ScenCount += mk
        xhat_k = self.xhat_generator(xhat_names, **self.xhat_gen_kwargs)

        nk = self.ArRP * int(math.ceil(lower_bound_k / self.ArRP))
        est_names = module.scenario_names_creator(nk,
                                                  start=self.ScenCount)
        self.ScenCount += nk
        est = ciutils.gap_estimators(xhat_k, module, est_names,
                                     self.cfg, ArRP=self.ArRP)
        Gk, sk = est["G"], est["s"]

        while self.stop_criterion(Gk, sk, nk) and k < maxit:
            k += 1
            nk_m1 = nk
            lower_bound_k = self.sample_size(k, Gk, sk, nk_m1)
            mk = int(math.floor(mult * lower_bound_k))
            # kf_xhat: resample the candidate only every kf_xhat
            # iterations; otherwise extend the previous sample
            # (ref:seqsampling.py:447-460 reuse branches)
            if k % self.kf_xhat == 0 or len(xhat_names) == 0:
                xhat_names = module.scenario_names_creator(
                    mk, start=self.ScenCount)
                self.ScenCount += mk
            elif mk > len(xhat_names):
                extra = mk - len(xhat_names)
                xhat_names = xhat_names + module.scenario_names_creator(
                    extra, start=self.ScenCount)
                self.ScenCount += extra
            xhat_k = self.xhat_generator(xhat_names,
                                         **self.xhat_gen_kwargs)
            nk = self.ArRP * int(math.ceil(lower_bound_k / self.ArRP))
            if k % self.kf_Gs == 0 or nk > nk_m1 * 2:
                est_names = module.scenario_names_creator(
                    nk, start=self.ScenCount)
                self.ScenCount += nk
            elif nk > len(est_names):
                extra = nk - len(est_names)
                est_names = est_names + module.scenario_names_creator(
                    extra, start=self.ScenCount)
                self.ScenCount += extra
            est = ciutils.gap_estimators(xhat_k, module, est_names,
                                         self.cfg, ArRP=self.ArRP)
            Gk, sk = est["G"], est["s"]
            global_toc(f"seq sampling iter {k}: n={nk} G={Gk:.5g} "
                       f"s={sk:.5g}", True)

        # The coverage guarantee only holds if the stopping rule was
        # actually met; at k == maxit the reference raises RuntimeError
        # (ref:seqsampling.py maxit guard).  We flag instead so callers
        # can still inspect the partial result, but loudly.
        converged = not self.stop_criterion(Gk, sk, nk)
        if not converged:
            global_toc(f"WARNING: sequential sampling hit maxit={maxit} "
                       "without satisfying the stopping criterion; the "
                       "returned CI has NO coverage guarantee", True)

        # CI on the gap at the final candidate (ref theory: width from
        # the stopping rule's parameters)
        if self.stopping_criterion == "BM":
            upper = self.BM_h * sk + self.BM_eps
        else:
            t = scipy.stats.t.ppf(self.confidence_level, nk - 1)
            upper = Gk + t * sk / math.sqrt(nk) + 1.0 / math.sqrt(nk)
        out = {"T": k, "Candidate_solution": xhat_k,
               "CI": [0.0, float(upper)], "G": Gk, "s": sk, "nk": nk,
               "converged": converged}
        if "seed_provenance" in est:
            # scengen draws (docs/scengen.md): the final estimator's
            # key window — with ScenCount, the whole sample sequence is
            # reproducible from counter-based keys alone
            out["seed_provenance"] = est["seed_provenance"]
        return out


class IndepScens_SeqSampling(SeqSampling):
    """Multistage sequential sampling over independently sampled
    scenario TREES (ref:mpisppy/confidence_intervals/
    multi_seqsampling.py:31-340).  Each i.i.d. sample is one seeded
    subtree with the configured branching factors; the stopping rules
    and sample-size recursions are inherited unchanged (they only see
    (G, s, nk), with nk counting trees).

    `xhat_generator(mk, start_seed, **kw) -> root xhat`: candidate from
    mk sampled scenarios; defaults to the root solution of a free
    sampled-tree EF whose branching factors are scaled so the leaf
    count is close to mk (ciutils.scalable_branching_factors — the
    reference's xhat_generator_aircond analog)."""

    def __init__(self, module, xhat_generator, cfg,
                 stochastic_sampling: bool = False,
                 stopping_criterion: str = "BM",
                 solving_type: str = "EF_mstage"):
        # bypass the parent's EF_2stage guard but reuse all its knobs
        super().__init__(module, xhat_generator, cfg,
                         stochastic_sampling=stochastic_sampling,
                         stopping_criterion=stopping_criterion,
                         solving_type="EF_2stage")
        self.solving_type = solving_type
        bfs = cfg.get("branching_factors")
        if not bfs:
            raise RuntimeError("IndepScens_SeqSampling needs "
                               "cfg['branching_factors']")
        self.branching_factors = [int(b) for b in bfs]
        self.numstages = len(self.branching_factors) + 1
        if self.xhat_generator is None:
            self.xhat_generator = self._default_xhat_gen

    def _candidate_seed_span(self, mk: int) -> int:
        """Seed ids a candidate generation consumes — advanced by run()
        for ANY generator, so a user-supplied xhat_generator can never
        leave ScenCount behind and have the gap estimator re-sample the
        very trees the candidate was fit to (which would bias G low and
        void the coverage guarantee)."""
        from mpisppy_tpu.confidence_intervals.sample_tree import (
            _number_of_nodes,
        )
        bfs = ciutils.scalable_branching_factors(
            max(mk, 2), self.branching_factors)
        return _number_of_nodes(bfs)

    def _default_xhat_gen(self, mk: int, start_seed: int, **_kw):
        """Root xhat from a free sampled-tree EF with ~mk leaves.
        Consumes exactly _candidate_seed_span(mk) seed ids; custom
        generators must do the same (run() advances ScenCount by it)."""
        from mpisppy_tpu.confidence_intervals.sample_tree import (
            SampleSubtree,
        )
        bfs = ciutils.scalable_branching_factors(
            max(mk, 2), self.branching_factors)
        st = SampleSubtree(self.module, None, bfs, start_seed, self.cfg)
        st.run()
        sol = st.ef.x                               # (S, n) original
        nonant_idx = np.asarray(st.ef.ef.nonant_idx)
        tree = st.ef.ef.tree
        root_slots = np.nonzero(tree.slot_stage == 1)[0]
        x_non = sol[:, nonant_idx]
        xhat = x_non.mean(axis=0)[root_slots]
        return xhat

    def run(self, maxit: int = 200) -> dict:
        mult = self.sample_size_ratio
        bfs = self.branching_factors
        k = 1
        lower_bound_k = self.sample_size(k, None, None, None)

        mk = int(math.floor(mult * lower_bound_k))
        xhat_k = self.xhat_generator(mk, self.ScenCount,
                                     **self.xhat_gen_kwargs)
        self.ScenCount += self._candidate_seed_span(mk)

        nk = int(math.ceil(lower_bound_k))
        est = ciutils.gap_estimators_mstage(
            xhat_k, self.module, nk, self.cfg, self.ScenCount, bfs)
        self.ScenCount = est["seed"]
        Gk, sk = est["G"], est["s"]

        while self.stop_criterion(Gk, sk, nk) and k < maxit:
            k += 1
            nk_m1 = nk
            lower_bound_k = self.sample_size(k, Gk, sk, nk_m1)
            mk = int(math.floor(mult * lower_bound_k))
            if k % self.kf_xhat == 0:
                xhat_k = self.xhat_generator(mk, self.ScenCount,
                                             **self.xhat_gen_kwargs)
                self.ScenCount += self._candidate_seed_span(mk)
            nk = int(math.ceil(lower_bound_k))
            est = ciutils.gap_estimators_mstage(
                xhat_k, self.module, nk, self.cfg, self.ScenCount, bfs)
            self.ScenCount = est["seed"]
            Gk, sk = est["G"], est["s"]
            global_toc(f"multistage seq sampling iter {k}: trees={nk} "
                       f"G={Gk:.5g} s={sk:.5g}", True)

        converged = not self.stop_criterion(Gk, sk, nk)
        if not converged:
            global_toc(f"WARNING: sequential sampling hit maxit={maxit} "
                       "without satisfying the stopping criterion; the "
                       "returned CI has NO coverage guarantee", True)
        if self.stopping_criterion == "BM":
            upper = self.BM_h * sk + self.BM_eps
        else:
            t = scipy.stats.t.ppf(self.confidence_level, max(nk - 1, 1))
            upper = Gk + t * sk / math.sqrt(nk) + 1.0 / math.sqrt(nk)
        return {"T": k, "Candidate_solution": xhat_k,
                "CI": [0.0, float(upper)], "G": Gk, "s": sk, "nk": nk,
                "converged": converged}
