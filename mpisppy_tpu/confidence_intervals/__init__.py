from mpisppy_tpu.confidence_intervals import ciutils  # noqa: F401
