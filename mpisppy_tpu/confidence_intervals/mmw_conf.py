###############################################################################
# mmw_conf: the MMW confidence-interval CLI
# (ref:mpisppy/confidence_intervals/mmw_conf.py:1-120).
#
#   python -m mpisppy_tpu.confidence_intervals.mmw_conf \
#       --module-name mpisppy_tpu.models.farmer --xhatpath xhat.npy \
#       --num-scens 3 --MMW-num-batches 5 --MMW-batch-size 10
#
# Loads a candidate x̂ from --xhatpath (written by
# ciutils.write_xhat or a solution writer), runs MMW batches of the gap
# estimator around it, and prints the gap CI as one JSON line.
###############################################################################
from __future__ import annotations

import importlib
import json
import sys

from mpisppy_tpu.confidence_intervals import ciutils
from mpisppy_tpu.confidence_intervals.confidence_config import (
    confidence_config,
)
from mpisppy_tpu.confidence_intervals.mmw_ci import MMWConfidenceIntervals
from mpisppy_tpu.utils.config import Config


def _parse_args(args=None):
    cfg = Config()
    cfg.add_to_config("module_name", "model module to import", str, None)
    cfg.num_scens_optional()
    confidence_config(cfg)
    cfg.add_to_config("MMW_num_batches", "number of MMW batches", int, 2)
    cfg.add_to_config("MMW_batch_size",
                      "scenarios per batch (default: num_scens)", int,
                      None)
    cfg.add_to_config("start_scen",
                      "first scenario index for sampling (default: after "
                      "the candidate's own scenarios)", int, None)
    cfg.parse_command_line("mpisppy_tpu.confidence_intervals.mmw_conf",
                           args)
    return cfg


def main(args=None):
    argv = list(sys.argv[1:] if args is None else args)
    cfg = _parse_args(argv)
    if cfg.get("module_name") is None:
        raise SystemExit("--module-name is required")
    if cfg.get("xhatpath") is None:
        raise SystemExit("--xhatpath is required (an .npy candidate, "
                         "e.g. from ciutils.write_xhat)")
    sys.path.insert(0, ".")
    module = importlib.import_module(cfg["module_name"])
    xhat_one = ciutils.read_xhat(cfg["xhatpath"])
    start = cfg.get("start_scen")
    if start is None:
        # sample fresh scenarios beyond the ones the candidate saw
        # (ref:mmw_conf.py start = num_scens of the xhat run)
        start = int(cfg.get("num_scens") or 0)
        if start == 0:
            # evaluating on the candidate's own training scenarios
            # biases the gap estimate LOW and voids the CI coverage
            # guarantee (cf. seqsampling._candidate_seed_span)
            print(  # telemetry: allow-print (stderr protocol note)
                "WARNING: neither --start-scen nor --num-scens given; "
                  "gap estimation starts at scenario 0, which likely "
                  "REUSES the scenarios the candidate xhat was fit to "
                  "— the resulting CI is optimistically biased",
                  file=sys.stderr)
    batch_size = cfg.get("MMW_batch_size") or cfg.get("num_scens")
    if batch_size is None:
        raise SystemExit("--MMW-batch-size (or --num-scens) is required")
    mmw = MMWConfidenceIntervals(
        module, cfg, xhat_one,
        num_batches=cfg.get("MMW_num_batches", 2),
        batch_size=int(batch_size),
        start=start)
    res = mmw.run(confidence_level=cfg.get("confidence_level", 0.95))
    print(json.dumps({k: v for k, v in res.items()}))  # telemetry: allow-print
    return res


if __name__ == "__main__":
    main()
