###############################################################################
# Config groups for CI runs
# (ref:mpisppy/confidence_intervals/confidence_config.py:42-93).
###############################################################################
from __future__ import annotations


def confidence_config(cfg):
    cfg.add_to_config("confidence_level", "CI confidence level", float,
                      0.95)
    cfg.add_to_config("xhatpath", "path of an xhat .npy file", str, None)
    # scengen replications (docs/scengen.md): when the model module
    # ships a ScenarioProgram, draw every estimator/replication sample
    # through counter-based scengen keys instead of per-scenario host
    # numpy streams — unlimited replications, layout-invariant draws,
    # and a seed_provenance record in the outputs.  Library default is
    # the legacy stream (cfg.get(..., False)); CI-configured runs get
    # scengen by default via this declaration.
    cfg.add_to_config("use_scengen",
                      "draw CI replications through scengen "
                      "counter-based keys when the model has a "
                      "ScenarioProgram", bool, True)
    cfg.add_to_config("scengen_seed",
                      "base seed of the scengen replication key "
                      "stream", int, 0)


def sequential_config(cfg):
    cfg.add_to_config("sample_size_ratio",
                      "xhat sample size / estimator sample size", float,
                      1.0)
    cfg.add_to_config("ArRP", "pooled estimator count", int, 1)
    cfg.add_to_config("kf_Gs", "resampling frequency for G and s", int, 1)
    cfg.add_to_config("kf_xhat", "resampling frequency for xhat", int, 1)
    # programmatic-only knobs (no CLI flag): seqsampling reads these
    # off the cfg when a driver quick_assigns them
    # (ref:seqsampling.py options plumbing)
    cfg.add_to_config("growth_function",
                      "BPL sample-growth callable g(k) (programmatic; "
                      "default linear k-1)", object, None,
                      argparse=False)
    cfg.add_to_config("xhat_gen_kwargs",
                      "extra kwargs for the xhat generator "
                      "(programmatic)", dict, None, argparse=False)


def BM_config(cfg):
    """ref:confidence_config.py:42-75."""
    cfg.add_to_config("BM_h", "BM h parameter", float, 1.75)
    cfg.add_to_config("BM_hprime", "BM h' parameter", float, 0.5)
    cfg.add_to_config("BM_eps", "BM epsilon", float, 0.2)
    cfg.add_to_config("BM_eps_prime", "BM epsilon'", float, 0.1)
    cfg.add_to_config("BM_p", "BM p parameter", float, 0.191)
    cfg.add_to_config("BM_q", "BM q parameter", float, 1.2)


def BPL_config(cfg):
    """ref:confidence_config.py:76-93."""
    cfg.add_to_config("BPL_eps", "BPL epsilon", float, 0.5)
    cfg.add_to_config("BPL_c0", "BPL c0 sample-size constant", int, 50)
    cfg.add_to_config("BPL_c1", "BPL c1 growth constant", int, 10)
    cfg.add_to_config("BPL_n0min", "BPL stochastic n0 minimum", int, 50)
