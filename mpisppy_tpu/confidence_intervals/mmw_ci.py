###############################################################################
# Mak-Morton-Wood confidence intervals
# (ref:mpisppy/confidence_intervals/mmw_ci.py:34-192).
#
# Batches of the gap estimator G around a fixed candidate x̂:
#   Gbar = mean(G_i),  eps_g = t_{alpha, B-1} std(G)/sqrt(B)
#   gap CI = [0, Gbar + eps_g]
###############################################################################
from __future__ import annotations

import numpy as np
import scipy.stats

from mpisppy_tpu import global_toc
from mpisppy_tpu.confidence_intervals import ciutils


class MMWConfidenceIntervals:
    """ref:mmw_ci.py:34.  `module` is a model module with the standard
    5-function API; `xhat_one` the candidate root solution."""

    def __init__(self, module, cfg, xhat_one, num_batches: int,
                 batch_size: int | None = None, start: int | None = None,
                 verbose: bool = True):
        self.module = module
        self.cfg = cfg
        self.xhat_one = np.asarray(xhat_one, np.float64)
        self.num_batches = num_batches
        self.batch_size = batch_size or int(cfg["num_scens"])
        if start is None:
            raise RuntimeError("Start must be specified "
                               "(ref:mmw_ci.py:77-80)")
        self.start = start
        self.verbose = verbose

    def run(self, confidence_level: float = 0.95) -> dict:
        """ref:mmw_ci.py:130-190."""
        start = self.start
        G = np.zeros(self.num_batches)
        provenance = []
        # gap_estimators pins num_scens to the sample size itself
        for i in range(self.num_batches):
            names = self.module.scenario_names_creator(self.batch_size,
                                                       start=start)
            est = ciutils.gap_estimators(self.xhat_one, self.module,
                                         names, self.cfg)
            start = est["seed"]
            G[i] = est["G"]
            if "seed_provenance" in est:
                provenance.append(est["seed_provenance"])
            if self.verbose:
                global_toc(f"Gn={G[i]:.6g} for batch {i}", True)

        s_g = float(np.std(G))
        Gbar = float(np.mean(G))
        t_g = scipy.stats.t.ppf(confidence_level, self.num_batches - 1)
        epsilon_g = t_g * s_g / np.sqrt(self.num_batches)
        self.result = {
            "gap_inner_bound": Gbar + epsilon_g,
            "gap_outer_bound": 0.0,
            "Gbar": Gbar,
            "std": s_g,
            "Glist": G.tolist(),
        }
        if provenance:
            # scengen replication batches (docs/scengen.md): the exact
            # key windows every G_i was drawn from — the CI is fully
            # reproducible from this record alone
            self.result["seed_provenance"] = provenance
        return self.result
