###############################################################################
# The multi-tenant wheel server (ISSUE 12 tentpole; docs/serving.md).
#
# A long-lived process multiplexing many concurrent problem instances
# — different tenants, different models — through one shared device
# wheel stack: `python -m mpisppy_tpu.serve --unix /tmp/wheel.sock`.
#
# Thread anatomy (every shared field lock-annotated; tools/graftlint
# lock-discipline):
#
#   acceptor ── one reader thread per client connection (parses JSON
#   lines, answers acks, routes submits into admission)
#   scheduler ── pops the FairQueue into session worker threads while
#   capacity (max_running) is free; doubles as the DEADLINE REAPER: a
#   session past its deadline gets a typed SolveFailed-style terminal
#   `failed` (reason deadline) and its quota back, the abandoned
#   worker drains in the background (the dispatch-timeout contract one
#   layer up)
#   worker ── runs the session engine; every exit path funnels into
#   Session.settle: done / failed(typed) / rejected — a client ALWAYS
#   observes a terminal outcome, never a hang.  A preempted session
#   (emergency checkpoint already on disk) re-enters the queue FRONT
#   with restore=True and resumes without client-visible state loss.
#
# Sessions sharing QP structure coalesce their oracle dispatches into
# shared megabatches through the process dispatch scheduler (structure
# interning, serve/multiplex.py), and with multiplexing on each wheel
# runs the PR-10 async hub under the server's ExchangeRing — one
# device stream advances several tenants between host exchanges.
###############################################################################
from __future__ import annotations

import dataclasses
import os
import socket
import threading
import time

from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.serve import admission as adm
from mpisppy_tpu.serve import multiplex, protocol
from mpisppy_tpu.serve import session as sess_mod
from mpisppy_tpu.telemetry import metrics as _metrics


@dataclasses.dataclass(frozen=True)
class ServeOptions:
    """Server knobs (CLI: python -m mpisppy_tpu.serve --help)."""

    unix_path: str | None = None     # unix socket path (preferred)
    host: str = "127.0.0.1"          # TCP fallback
    port: int = 0                    # 0 = ephemeral
    max_running: int = 2             # concurrent session workers
    max_queued: int = 64             # global queue cap (backpressure)
    max_queued_per_tenant: int = 32
    tenant_quota: int = 2            # per-tenant in-flight cap
    tenant_weights: dict | None = None
    latency_burst: int = 4           # SLA starvation guard
    trace_dir: str | None = None     # per-session JSONL traces
    spool_dir: str | None = None     # session checkpoints
    multiplex: bool = True           # async hub + exchange ring
    default_deadline_s: float | None = None
    step_miss_budget: int = 3        # consecutive per-step deadline
                                     # misses before a RUNNING MPC
                                     # stream is reaped (ISSUE 19)
    engine: object | None = None     # injectable (tests/chaos)
    fault_plan: object | None = None  # chaos seams (ServeFault et al.)
    bus: object | None = None        # server-level telemetry bus
    replica_id: str = ""             # fleet replica identity (ISSUE 16;
                                     # stamped on every session served)


class WheelServer:
    """See the module header."""

    def __init__(self, options: ServeOptions = ServeOptions()):
        self.options = options
        self.bus = options.bus or tel.EventBus()
        self.queue = adm.FairQueue(
            max_queued=options.max_queued,
            max_queued_per_tenant=options.max_queued_per_tenant,
            default_quota=options.tenant_quota,
            weights=options.tenant_weights,
            latency_burst=options.latency_burst)
        self.ring = multiplex.ExchangeRing() if options.multiplex \
            else None
        if options.engine is not None:
            self.engine = options.engine
        else:
            from mpisppy_tpu.serve.engine import WheelEngine
            self.engine = WheelEngine(multiplexed=options.multiplex)
        for d in (options.trace_dir, options.spool_dir):
            if d:
                os.makedirs(d, exist_ok=True)
        self._sock: socket.socket | None = None
        self.address = None           # bound address after start()
        # Lock discipline (tools/graftlint lock-discipline): the
        # session registry and lifecycle counters are shared by the
        # acceptor, reader, scheduler and worker threads.
        self._lock = threading.Lock()
        self._sessions: dict = {}         # guarded-by: _lock (live +
                                          # a bounded terminal tail —
                                          # see _prune_sessions)
        self._slots: set = set()          # guarded-by: _lock (sids
                                          # currently holding a worker
                                          # slot — one release per
                                          # admission, re-admittable)
        self._running = 0                 # guarded-by: _lock
        self._stopping = False            # guarded-by: _lock
        self._threads: list = []          # guarded-by: _lock
        self._submitted = 0               # guarded-by: _lock
        self._preemptions = 0             # guarded-by: _lock
        self._state_totals: dict = {}     # guarded-by: _lock (terminal
                                          # counts of PRUNED sessions)
        self._wake = threading.Condition(self._lock)
        #: terminal sessions kept for inspection before pruning — the
        #: registry must stay bounded in a long-lived server
        self.keep_terminal = 256

    # -- lifecycle --------------------------------------------------------
    def start(self):
        opts = self.options
        if opts.unix_path:
            try:
                os.unlink(opts.unix_path)
            except OSError:
                pass
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.bind(opts.unix_path)
            self.address = opts.unix_path
        else:
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind((opts.host, opts.port))
            self.address = s.getsockname()
        s.listen(64)
        s.settimeout(0.25)
        self._sock = s
        for name, target in (("serve-accept", self._accept_loop),
                             ("serve-sched", self._schedule_loop)):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._note_thread(t)
        tel.console.log(f"serve: listening on {self.address} "
                        f"(max_running={opts.max_running}, "
                        f"multiplex={opts.multiplex})")
        return self

    def stop(self, timeout: float = 10.0):
        """Drain: stop admitting (queued sessions get a typed
        rejection), wait for running sessions up to `timeout`, close."""
        with self._lock:
            self._stopping = True
            self._wake.notify_all()
        for s in self.queue.drain():
            self._reject(s, "draining")
        deadline = time.perf_counter() + timeout
        while time.perf_counter() < deadline:
            with self._lock:
                if self._running == 0:
                    break
            time.sleep(0.05)
        # second drain: a worker that observed a preemption WHILE the
        # first drain ran may have requeued its session concurrently —
        # it must still get its typed terminal outcome, never a hang
        for s in self.queue.drain():
            self._reject(s, "draining")
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self.options.unix_path:
            try:
                os.unlink(self.options.unix_path)
            except OSError:
                pass
        if self.options.bus is None:
            self.bus.close()

    def _reject(self, session, reason: str, detail: str = ""):
        """Typed terminal outcome for a queued session leaving the
        queue unserved (drain path).  Idempotent: a session that
        already settled (deadline-reaped while queued) is left alone.
        A DEGRADED session caught here (preempted during drain) fails
        typed instead — REJECTED is a from-QUEUED verdict."""
        if session.is_terminal():
            return
        if session.state == sess_mod.DEGRADED:
            session.settle("failed", reason=reason,
                           detail=detail or "preempted while the "
                           "server drained; checkpoint retained")
            return
        self.bus.emit(tel.ADMISSION_REJECTED, run=session.run_id,
                      cyl="serve", tenant=session.tenant,
                      reason=reason, detail=detail)
        _metrics.REGISTRY.inc("serve_admission_rejects_total")
        session.settle("rejected", reason=reason, detail=detail)

    def serve_forever(self):
        """Block until interrupted (the __main__ entry point)."""
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:
            self.stop()

    # -- client plumbing --------------------------------------------------
    def _accept_loop(self):
        while True:
            with self._lock:
                if self._stopping:
                    return
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._client_loop,
                                 args=(conn,), daemon=True,
                                 name="serve-client")
            t.start()
            self._note_thread(t)

    def _client_loop(self, conn: socket.socket):
        """One client's reader: parse lines, ack, route.  The outbox
        closure serializes writes per connection."""
        wlock = threading.Lock()
        my_sessions: list = []

        def outbox(msg: dict):
            data = protocol.encode(msg)
            with wlock:
                conn.sendall(data)

        try:
            rfile = conn.makefile("rb")
            for msg in protocol.iter_lines(rfile):
                if "_malformed" in msg:
                    self._safe_send(outbox, {
                        "ok": False, "error": "malformed-json",
                        "detail": msg["_malformed"][:200]})
                    continue
                op = msg.get("op")
                if op == "ping":
                    self._safe_send(outbox, {"ok": True, "op": "ping"})
                elif op == "stats":
                    self._safe_send(outbox, {"ok": True, "op": "stats",
                                             "stats": self.stats()})
                elif op == "status":
                    self._safe_send(outbox, {
                        "ok": True, "op": "status",
                        "status": self.status()})
                elif op == "submit":
                    try:
                        self._handle_submit(msg, outbox, my_sessions)
                    except Exception as e:  # noqa: BLE001 — typed ack:
                        # one bad submit must never kill the reader
                        # (every later submit on the connection would
                        # hang unanswered)
                        self._safe_send(outbox, {
                            "ok": False, "error": "internal",
                            "detail": f"{type(e).__name__}: "
                                      f"{e}"[:300]})
                else:
                    self._safe_send(outbox, {
                        "ok": False, "error": "unknown-op",
                        "op": op})
        except (OSError, ValueError):
            pass
        finally:
            for s in my_sessions:
                s.detach()
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _safe_send(outbox, msg: dict) -> bool:
        try:
            outbox(msg)
            return True
        except Exception:
            return False

    def _handle_submit(self, msg: dict, outbox, my_sessions: list):
        try:
            spec = protocol.SubmitRequest.from_dict(msg)
        except protocol.ProtocolError as e:
            self._safe_send(outbox, {"ok": False,
                                     "error": "bad-request",
                                     "detail": str(e)})
            return
        if spec.deadline_s is None \
                and self.options.default_deadline_s is not None:
            spec = dataclasses.replace(
                spec, deadline_s=self.options.default_deadline_s)
        session = sess_mod.Session(
            spec, outbox=outbox, server_bus=self.bus,
            trace_dir=self.options.trace_dir)
        try:
            self.submit_session(session)
        except adm.AdmissionRejected as e:
            # typed backpressure — the terminal outcome arrives in the
            # SAME ack so a flooding client can never mistake a reject
            # for a hang
            self.bus.emit(tel.ADMISSION_REJECTED, run=session.run_id,
                          cyl="serve", tenant=spec.tenant,
                          trace=session.trace,
                          reason=e.reason, detail=e.detail)
            _metrics.REGISTRY.inc("serve_admission_rejects_total")
            session.settle("rejected", reason=e.reason, detail=e.detail)
            self._safe_send(outbox, {"ok": False, "session": session.sid,
                                     "error": "rejected",
                                     "reason": e.reason})
            return
        my_sessions.append(session)
        self._safe_send(outbox, {"ok": True, "session": session.sid,
                                 "tenant": spec.tenant})

    def submit_session(self, session) -> None:
        """Admit an externally-constructed session — the socket submit
        path above and the fleet router's replica-assignment path
        (ISSUE 16) share it.  Stamps the replica identity, attaches the
        per-replica trace and checkpoint spool, and enters admission.
        Raises adm.AdmissionRejected on backpressure WITHOUT settling
        the session: the caller owns the typed terminal outcome (the
        router re-places a migrating session instead of rejecting)."""
        if self.options.replica_id:
            session.replica = self.options.replica_id
        if self.options.trace_dir and not session.trace_attached:
            session.attach_trace(self.options.trace_dir)
        if session.checkpoint_path is None and self.options.spool_dir:
            session.checkpoint_path = os.path.join(
                self.options.spool_dir, f"ckpt-{session.sid}.npz")
        if session.streaming and session.on_step is None:
            # per-step WFQ charge (ISSUE 19): each completed window
            # advances the stream's virtual finish time like a fresh
            # admission, so a long stream keeps paying for capacity
            # instead of riding one admission forever
            session.on_step = self.queue.charge_step
        self.queue.submit(session)
        with self._lock:
            self._sessions[session.sid] = session
            self._submitted += 1
            self._wake.notify_all()
        _metrics.REGISTRY.inc("serve_sessions_total")
        _metrics.REGISTRY.set_gauge("serve_queue_depth",
                                    self.queue.stats()["queued"])

    # -- scheduling -------------------------------------------------------
    def _schedule_loop(self):
        while True:
            with self._lock:
                if self._stopping and self._running == 0:
                    return
                free = self._running < self.options.max_running \
                    and not self._stopping
            popped = self.queue.pop() if free else None
            if popped is not None:
                with self._lock:
                    self._running += 1
                    self._slots.add(popped.sid)
                _metrics.REGISTRY.set_gauge("serve_sessions_active",
                                            self._running_snapshot())
                t = threading.Thread(target=self._run_session,
                                     args=(popped,), daemon=True,
                                     name=f"serve-{popped.sid}")
                t.start()
                self._note_thread(t)
                continue
            self._reap_deadlines()
            with self._lock:
                if self._stopping and self._running == 0:
                    return
                self._wake.wait(timeout=0.05)

    def _running_snapshot(self) -> int:
        with self._lock:
            return self._running

    # -- bounded registries (a long-lived server must not grow with
    # total sessions served) ----------------------------------------------
    def _note_thread(self, t) -> None:
        """Track a worker/reader thread, dropping finished ones — the
        list stays O(live threads), not O(lifetime threads)."""
        with self._lock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _prune_sessions(self) -> None:
        """Fold the oldest terminal sessions into the state-total
        counters once more than keep_terminal of them accumulate; live
        sessions are never touched.  stats() merges the counters, so
        accounting survives the prune."""
        with self._lock:
            terminal = [s for s in self._sessions.values()
                        if s.is_terminal()]
            excess = len(terminal) - max(0, int(self.keep_terminal))
            for s in terminal[:max(0, excess)]:
                self._state_totals[s.state] = \
                    self._state_totals.get(s.state, 0) + 1
                del self._sessions[s.sid]

    def _reap_deadlines(self):
        """Typed deadline enforcement (docs/serving.md failure
        semantics): a session past its deadline — queued OR running —
        settles `failed` (reason deadline) NOW; a hung worker is
        abandoned to drain in the background, its quota freed, exactly
        the dispatch-timeout contract one layer up.

        STREAMING sessions (ISSUE 19): a healthy MPC stream outlives
        any whole-session wall clock by design, so once it is RUNNING
        (or DEGRADED mid-resume) its liveness unit is the STEP —
        reaped only after step_miss_budget consecutive per-step
        deadlines (spec.step_deadline_s, re-armed by every completed
        window) pass without a step.  deadline_s still bounds its
        QUEUED wait like any other session."""
        now = time.perf_counter()
        budget = max(1, int(self.options.step_miss_budget))
        with self._lock:
            sessions = [s for s in self._sessions.values()
                        if not s.is_terminal()]
        for s in sessions:
            state = s.state
            live = state in (sess_mod.RUNNING, sess_mod.DEGRADED)
            if s.streaming and live:
                missed = s.steps_overdue(now)
                if missed < budget:
                    continue
                if s.settle("failed", reason="step-deadline",
                            detail=f"{missed} consecutive step "
                                   f"deadlines "
                                   f"({s.spec.step_deadline_s}s) "
                                   f"missed at step {s.mpc_step}"):
                    _metrics.REGISTRY.inc("serve_failures_total")
                self._release(s)
            elif s.deadline is not None and now >= s.deadline:
                if s.settle("failed", reason="deadline",
                            detail=f"session deadline "
                                   f"{s.spec.deadline_s}s expired in "
                                   f"{state}"):
                    _metrics.REGISTRY.inc("serve_failures_total")
                if live:
                    self._release(s)

    def _release(self, session):
        """Free the session's worker slot + tenant quota exactly once
        — the deadline reaper and the worker's own exit path can both
        reach here for the same admission (a reaped session's
        abandoned worker still unwinds through its finally)."""
        with self._lock:
            if session.sid not in self._slots:
                return
            self._slots.discard(session.sid)
            self._running = max(0, self._running - 1)
            self._wake.notify_all()
        self.queue.release(session)
        _metrics.REGISTRY.set_gauge("serve_sessions_active",
                                    self._running_snapshot())
        # the queue gauge moves on pops/drains too, not only submits —
        # a monitoring consumer must never read a drained queue as
        # still flood-deep
        _metrics.REGISTRY.set_gauge("serve_queue_depth",
                                    self.queue.stats()["queued"])

    # -- the session worker -----------------------------------------------
    def _run_session(self, session):
        plan = self.options.fault_plan
        released = False
        try:
            if session.is_terminal():
                return       # reaped while queued
            if session.state == sess_mod.QUEUED:
                session.transition(sess_mod.ADMITTED)
            # a re-admitted DEGRADED session goes straight back to
            # RUNNING (preemption-resume path)
            if plan is not None and plan.serve_drop_connection(
                    session.tenant, session.ordinal):
                # injected mid-run disconnect: the session keeps
                # running detached; accounting and the per-session
                # trace stay intact
                session.detach()
                _metrics.REGISTRY.inc("serve_disconnects_total")
            session.transition(sess_mod.RUNNING,
                               restore=session.restore)
            # one causal segment span per run attempt (ISSUE 20):
            # everything the engine/hub emits below rides this span;
            # a resumed attempt opens a sibling under the same root
            session.begin_segment()
            session.t_started = session.t_started \
                or time.perf_counter()
            if session.streaming:
                # queue/preemption time must not bill against the
                # first step's per-step deadline
                session.reset_step_anchor()
            verdict, payload = self.engine.run(
                session, ring=self.ring, fault_plan=plan)
            if verdict == "preempted":
                # free the slot BEFORE requeueing: the scheduler may
                # re-admit the session the moment it hits the queue
                released = True
                self._release(session)
                self._handle_preemption(session, payload)
                return
            session.settle("done", **payload)
        except Exception as e:  # noqa: BLE001 — typed for the client
            reason = getattr(e, "reason", None) or type(e).__name__
            if session.settle("failed", reason=str(reason),
                              detail=str(e)[:500]):
                # settle returns False when the deadline reaper got
                # here first — the failure then counted already
                _metrics.REGISTRY.inc("serve_failures_total")
        finally:
            if not released:
                self._release(session)
            self._prune_sessions()

    def _handle_preemption(self, session, payload: dict):
        """A preempted session re-enters the queue FRONT with
        restore=True — the emergency snapshot is already on disk, so
        the resumed run continues mid-loop with no client-visible
        state loss (the client sees a non-terminal 'preempted' line,
        then the stream resumes).  A server already draining settles
        the session typed instead: nothing would ever pop the requeue
        once the scheduler loop exits."""
        session.preemptions += 1
        with self._lock:
            self._preemptions += 1
            stopping = self._stopping
        _metrics.REGISTRY.inc("serve_preemptions_total")
        session.transition(sess_mod.DEGRADED, reason="preempted",
                           **payload)
        session.send({"event": "preempted", "session": session.sid,
                      **payload})
        # the preempted attempt's segment span detaches here; the
        # restore (local requeue or fleet migration) opens a sibling
        # under the same trace — the wall gap between them IS the
        # migration gap on the critical path (ISSUE 20)
        session.end_segment()
        session.restore = True
        if stopping:
            session.settle("failed", reason="draining",
                           detail="preempted while the server "
                                  "drained; checkpoint retained")
            return
        if self._preemption_handoff(session, payload):
            return          # the fleet router took ownership
        self.queue.requeue_front(session)
        with self._lock:
            stopping = self._stopping
            self._wake.notify_all()
        if stopping:
            # the server began draining BETWEEN our first check and
            # the requeue: the scheduler loop may already be gone, so
            # drain from here — every queued session (including this
            # one) still gets its typed terminal outcome
            for s in self.queue.drain():
                self._reject(s, "draining")

    def _preemption_handoff(self, session, payload: dict) -> bool:
        """Fleet seam (ISSUE 16): a replica server overrides this to
        hand a draining/migrating session back to its router instead
        of the local queue.  True = the router took ownership (the
        emergency checkpoint is on disk; the router re-places the
        session with restore=True on another replica)."""
        return False

    # -- health probes ----------------------------------------------------
    def load(self) -> tuple[int, int]:
        """(running, queued) — the router's cheap placement read."""
        with self._lock:
            running = self._running
        return running, self.queue.stats()["queued"]

    def status(self) -> dict:
        """Lightweight health probe (ISSUE 16 satellite): replica
        identity, session counts by state, queue depth, free slots,
        and the interner digests this replica's engine holds — the
        placement-affinity key the fleet router routes on.  Cheap
        enough to answer on every heartbeat probe."""
        with self._lock:
            running = self._running
            stopping = self._stopping
            states: dict = dict(self._state_totals)
            for s in self._sessions.values():
                states[s.state] = states.get(s.state, 0) + 1
        q = self.queue.stats()
        out = {
            "replica": self.options.replica_id,
            "running": running,
            "queued": q["queued"],
            "free_slots": max(0, self.options.max_running - running),
            "draining": stopping or bool(q.get("draining")),
            "states": states,
        }
        interner = getattr(self.engine, "interner", None)
        out["interner_digests"] = (
            list(interner.digests())
            if interner is not None and hasattr(interner, "digests")
            else [])
        return out

    # -- stats ------------------------------------------------------------
    def stats(self) -> dict:
        from mpisppy_tpu import dispatch as _dispatch
        with self._lock:
            counts = dict(self._state_totals)   # pruned terminal tail
            for s in self._sessions.values():
                counts[s.state] = counts.get(s.state, 0) + 1
            out = {
                "submitted": self._submitted,
                "running": self._running,
                "preemptions": self._preemptions,
                "states": counts,
            }
        out["admission"] = self.queue.stats()
        if self.ring is not None:
            out["exchange_ring"] = self.ring.stats()
        ds = _dispatch.scheduler_stats()
        if ds is not None:
            out["dispatch"] = {
                "batches": ds["batches"],
                "coalesced_lanes": ds["coalesced_lanes"],
                "occupancy": ds["occupancy"],
                "by_key": ds["by_key"],
            }
        return out
