###############################################################################
# mpisppy_tpu.serve — the multi-tenant wheel server (ISSUE 12;
# docs/serving.md; ROADMAP item "millions of users, heavy traffic").
#
#   protocol  — JSON-lines wire protocol (SubmitRequest, SLA classes,
#               terminal-outcome vocabulary)
#   session   — session lifecycle (QUEUED -> ADMITTED -> RUNNING ->
#               DEGRADED -> DONE/FAILED, REJECTED) with per-session
#               telemetry bus scoping (one JSONL trace per session)
#   admission — weighted fair queueing across tenants, SLA priority
#               classes, per-tenant quotas, typed backpressure
#               (AdmissionRejected — never a hang)
#   multiplex — cross-session megabatch coalescing (shared-structure
#               interning over the dispatch scheduler's mergeable
#               identities) + the ExchangeRing interleaving sessions'
#               host exchanges on the PR-10 async hub
#   engine    — WheelEngine (a session = one fused wheel built through
#               the generic_cylinders recipe) + SyntheticEngine (the
#               load/chaos test double)
#   server    — the long-lived WheelServer process
#   loadgen   — ServeClient + the p50/p99 / tenant-isolation load
#               harness behind bench.py's serve_load phase
#
# Start one:  python -m mpisppy_tpu.serve --unix /tmp/wheel.sock
###############################################################################
from mpisppy_tpu.serve.admission import (  # noqa: F401
    AdmissionRejected,
    FairQueue,
    FleetAdmission,
)
from mpisppy_tpu.serve.protocol import (  # noqa: F401
    MODELS,
    SLA_CLASSES,
    ProtocolError,
    SubmitRequest,
)
from mpisppy_tpu.serve.server import ServeOptions, WheelServer  # noqa: F401
from mpisppy_tpu.serve.session import Session  # noqa: F401
