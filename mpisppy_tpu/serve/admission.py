###############################################################################
# Admission control (ISSUE 12 tentpole, piece 2; docs/serving.md).
#
# Weighted fair queueing across tenants with SLA priority classes and
# typed backpressure — the PR-8 "a caller observes a result or a typed
# failure, never a hang" semantics extended one layer up:
#
#   * BACKPRESSURE — submit() never blocks.  A full global queue, a
#     full per-tenant queue, or a draining server raises a typed
#     AdmissionRejected (reason queue-full / tenant-queue-full /
#     draining) that the server answers as the client's terminal
#     `rejected` line.  Load converts to a bounded queue and typed
#     refusals, exactly like the dispatch layer converts storms to
#     batch occupancy.
#   * WEIGHTED FAIRNESS — pop() runs virtual-time WFQ (stride
#     scheduling): each tenant accumulates virtual service 1/weight
#     per admitted session, and the eligible tenant with the least
#     virtual finish time goes next.  A tenant flooding its queue
#     advances its own virtual clock and cannot starve the others —
#     the mechanism behind the tenant-isolation acceptance line.
#   * SLA CLASSES — `latency` sessions pop before `throughput` ones
#     (they also jump their own tenant's queue), bounded by a
#     starvation guard: after `latency_burst` consecutive latency
#     pops with throughput work waiting, one throughput session is
#     scheduled regardless.
#   * QUOTAS — a tenant with `quota` sessions already in flight is
#     ineligible until one finishes; quota never rejects (queued work
#     waits), only the queue caps do.
###############################################################################
from __future__ import annotations

import threading


class AdmissionRejected(RuntimeError):
    """Typed admission refusal (docs/serving.md failure semantics):
    reason 'queue-full' | 'tenant-queue-full' | 'draining'."""

    def __init__(self, reason: str, tenant: str = "", detail: str = ""):
        self.reason = reason
        self.tenant = tenant
        self.detail = detail
        super().__init__(
            f"admission rejected ({reason})"
            + (f" for tenant {tenant!r}" if tenant else "")
            + (f": {detail}" if detail else ""))


class _Tenant:
    __slots__ = ("name", "weight", "quota", "vfinish", "queue",
                 "inflight", "admitted", "rejected", "ordinals",
                 "steps_charged")

    def __init__(self, name: str, weight: float, quota: int):
        self.name = name
        self.weight = max(1e-6, float(weight))
        self.quota = int(quota)
        self.vfinish = 0.0     # virtual finish time (WFQ clock)
        self.queue: list = []  # FIFO of queued sessions (latency first)
        self.inflight = 0
        self.admitted = 0
        self.rejected = 0
        self.ordinals = 0      # per-tenant admission ordinal counter
        self.steps_charged = 0  # MPC stream windows billed (ISSUE 19)


class FairQueue:
    """The admission policy: bounded tenant queues + WFQ pop.

    Thread-safety: submit() rides client reader threads, pop() the
    scheduler loop, release() the session workers."""

    def __init__(self, max_queued: int = 64,
                 max_queued_per_tenant: int = 32,
                 default_quota: int = 2,
                 default_weight: float = 1.0,
                 latency_burst: int = 4,
                 weights: dict | None = None,
                 quotas: dict | None = None):
        self.max_queued = int(max_queued)
        self.max_queued_per_tenant = int(max_queued_per_tenant)
        self.default_quota = int(default_quota)
        self.default_weight = float(default_weight)
        self.latency_burst = int(latency_burst)
        self._weights = dict(weights or {})
        self._quotas = dict(quotas or {})
        self._lock = threading.Lock()
        self._tenants: dict = {}          # guarded-by: _lock
        self._queued = 0                  # guarded-by: _lock
        self._vtime = 0.0                 # guarded-by: _lock
        self._draining = False            # guarded-by: _lock
        self._latency_run = 0             # guarded-by: _lock
        self._rejects = 0                 # guarded-by: _lock

    def _tenant(self, name: str) -> _Tenant:   # holds-lock: _lock
        t = self._tenants.get(name)
        if t is None:
            t = _Tenant(name, self._weights.get(name,
                                                self.default_weight),
                        self._quotas.get(name, self.default_quota))
            self._tenants[name] = t
        return t

    # -- submit (client reader threads) -----------------------------------
    def submit(self, session) -> None:
        """Enqueue or raise a typed AdmissionRejected — never blocks."""
        with self._lock:
            t = self._tenant(session.tenant)
            if self._draining:
                t.rejected += 1
                self._rejects += 1
                raise AdmissionRejected("draining", session.tenant)
            if self._queued >= self.max_queued:
                t.rejected += 1
                self._rejects += 1
                raise AdmissionRejected(
                    "queue-full", session.tenant,
                    f"{self._queued} sessions queued (cap "
                    f"{self.max_queued})")
            if len(t.queue) >= self.max_queued_per_tenant:
                t.rejected += 1
                self._rejects += 1
                raise AdmissionRejected(
                    "tenant-queue-full", session.tenant,
                    f"{len(t.queue)} queued (cap "
                    f"{self.max_queued_per_tenant})")
            session.ordinal = t.ordinals
            t.ordinals += 1
            if session.sla == "latency":
                # jump the tenant's own throughput backlog, FIFO among
                # latency peers
                idx = sum(1 for s in t.queue if s.sla == "latency")
                t.queue.insert(idx, session)
            else:
                t.queue.append(session)
            self._queued += 1

    def requeue_front(self, session) -> None:
        """Put a preempted/degraded session back at the FRONT of its
        tenant queue (it already paid its virtual service; restoring it
        first minimizes client-visible disruption)."""
        with self._lock:
            t = self._tenant(session.tenant)
            t.queue.insert(0, session)
            self._queued += 1

    # -- pop (scheduler loop) ---------------------------------------------
    def _eligible(self):               # holds-lock: _lock
        return [t for t in self._tenants.values()
                if t.queue and t.inflight < t.quota]

    def _select(self):                 # holds-lock: _lock
        """The WFQ winner tenant (SLA-class priority + starvation
        guard + least virtual finish), or None when nothing is
        eligible."""
        elig = self._eligible()
        if not elig:
            return None
        lat = [t for t in elig if t.queue[0].sla == "latency"]
        thr = [t for t in elig if t.queue[0].sla != "latency"]
        pool = lat or thr
        if lat and thr and self._latency_run >= self.latency_burst:
            pool = thr                 # starvation guard: one through
        return min(pool, key=lambda x: (x.vfinish, x.name))

    def _charge(self, t, session) -> None:   # holds-lock: _lock
        """Commit an admission: quota slot, WFQ virtual clock, SLA
        burst counter."""
        t.inflight += 1
        t.admitted += 1
        # WFQ virtual clock: service cost 1 scaled by weight
        self._vtime = max(self._vtime, t.vfinish)
        t.vfinish = self._vtime + 1.0 / t.weight
        if session.sla == "latency":
            self._latency_run += 1
        else:
            self._latency_run = 0

    def pop(self):
        """The next session to admit, or None when nothing is eligible
        (empty queues or every queued tenant at quota).  SLA-class
        priority first (with the starvation guard), then least virtual
        finish time among eligible tenants.  Sessions that reached a
        terminal state while queued (deadline-reaped, rejected on
        drain) are dropped here without charging the tenant's virtual
        clock or quota — a dead session must not burn a worker slot
        or skew fairness."""
        with self._lock:
            while True:
                t = self._select()
                if t is None:
                    return None
                session = t.queue.pop(0)
                self._queued -= 1
                if session.is_terminal():
                    continue           # reaped while queued: discard
                self._charge(t, session)
                return session

    def release(self, session) -> None:
        """A session left the running set (terminal or preempted) —
        frees its tenant's quota slot."""
        with self._lock:
            t = self._tenant(session.tenant)
            t.inflight = max(0, t.inflight - 1)

    def charge_step(self, session) -> None:
        """Bill one completed MPC stream window against the tenant's
        WFQ clock (ISSUE 19): each step advances vfinish exactly like a
        fresh admission, so a long-lived stream keeps paying virtual
        service per window and can never starve throughput tenants off
        a single admission-time charge.  Quota and the SLA burst
        counter are NOT touched — the stream still holds its one
        admission slot."""
        with self._lock:
            t = self._tenant(session.tenant)
            self._vtime = max(self._vtime, t.vfinish)
            t.vfinish = self._vtime + 1.0 / t.weight
            t.steps_charged += 1

    # -- lifecycle / stats ------------------------------------------------
    def drain(self) -> list:
        """Stop admitting: every queued session is returned for typed
        rejection, later submits raise AdmissionRejected('draining')."""
        with self._lock:
            self._draining = True
            out = []
            for t in self._tenants.values():
                out.extend(t.queue)
                t.queue = []
            self._queued = 0
            return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "queued": self._queued,
                "rejected": self._rejects,
                "draining": self._draining,
                "tenants": {
                    t.name: {
                        "queued": len(t.queue),
                        "inflight": t.inflight,
                        "admitted": t.admitted,
                        "rejected": t.rejected,
                        "steps_charged": t.steps_charged,
                        "weight": t.weight,
                        "quota": t.quota,
                        "vfinish": round(t.vfinish, 4),
                    } for t in self._tenants.values()},
            }


class FleetAdmission(FairQueue):
    """Placement-aware WFQ for the fleet router (ISSUE 16 tentpole).

    The exact FairQueue policy — WFQ weights, quotas, SLA classes,
    bounded queues with typed rejection — hoisted ABOVE the replicas
    (global admission state lives here, each replica's local queue is
    just a hand-off buffer), plus a placement step fused into pop:
    the WFQ winner is only charged (quota + virtual clock) once a
    replica actually accepted it, so a fleet momentarily out of free
    slots leaves fairness untouched."""

    def pop_placed(self, place_fn):
        """Pop the WFQ-next session and place it.

        place_fn(session) -> replica-or-None runs OUTSIDE the queue
        lock (it reads replica load and affinity state).  Returns
        (session, replica), or (None, None) when nothing is eligible,
        placement declined (no live replica with a free slot — the
        session stays at the front of its queue, uncharged), or a
        concurrent drain raced the candidate away."""
        with self._lock:
            while True:
                t = self._select()
                if t is None:
                    return None, None
                session = t.queue[0]
                if session.is_terminal():
                    t.queue.pop(0)     # reaped while queued: discard
                    self._queued -= 1
                    continue
                break
        replica = place_fn(session)
        if replica is None:
            return None, None
        with self._lock:
            t2 = self._tenants.get(session.tenant)
            # commit only if the candidate is still at its queue front
            # (a drain may have emptied the queue while we placed)
            if t2 is None or not t2.queue or t2.queue[0] is not session:
                return None, None
            t2.queue.pop(0)
            self._queued -= 1
            self._charge(t2, session)
        return session, replica
