###############################################################################
# Session solve engines (ISSUE 12 tentpole; docs/serving.md).
#
# WheelEngine turns one admitted session into one cylinder wheel built
# through the SAME recipe surface the CLI uses (generic_cylinders
# build_wheel over a parsed Config) — a serve session is exactly a
# `python -m mpisppy_tpu --fused-wheel --lagrangian --xhatxbar` run
# with the session's model/scale/gap substituted, plus the serve-layer
# wiring:
#
#   * the session's scoped telemetry bus becomes the hub's bus (every
#     wheel event lands in session-<sid>.jsonl and streams to the
#     client);
#   * the session's run id becomes the hub run id (one run per trace);
#   * the batch's shared structure is INTERNED (serve/multiplex.py) so
#     equal-structure sessions coalesce their oracle dispatches into
#     shared megabatches;
#   * with multiplexing on, the wheel runs the PR-10 async hub with
#     the server's ExchangeRing gating the host-complete half — one
#     device stream advances several tenants between host exchanges;
#   * a checkpoint path under the server spool makes the session
#     preemption-safe: a SimulatedPreemption (or real SIGTERM relayed
#     as PreemptionError) returns a 'preempted' verdict after the
#     emergency save, and the re-admitted session restores and resumes
#     with no client-visible state loss.
#
# SyntheticEngine is the load/chaos test double: the same outcome
# surface and fault seams without device work, so admission fairness
# and storm invariants test in milliseconds.
###############################################################################
from __future__ import annotations

import importlib
import os
import time

from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.resilience.faults import PreemptionError
from mpisppy_tpu.serve import multiplex
from mpisppy_tpu.serve.protocol import MODELS, SubmitRequest


#: per-model argv defaults keeping serve sessions small enough for a
#: shared wheel (clients override via SubmitRequest.args, which parse
#: LAST and win)
_MODEL_ARGS = {
    "farmer": ("--default-rho", "1.0"),
    # the synthetic 5x25 instance, LP-relaxed: certifies 1% in ~130
    # fused-wheel iterations at rho 20 (the BASELINE sslp recipe scaled
    # to an interactive session)
    "sslp": ("--sslp-lp-relax", "--default-rho", "20.0"),
    "uc": ("--uc-n-gens", "3", "--uc-n-hours", "6",
           "--slammax", "--sensi-rho", "--subproblem-windows", "10"),
    # 3-stage OPF on the default (3, 3) tree; clients opt into the
    # conic branch-flow mode with --soc in their args
    "ccopf": (),
}


def session_argv(spec: SubmitRequest, multiplexed: bool = False) -> list:
    """The generic_cylinders argv a session's spec translates to."""
    argv = [
        "--module-name", MODELS[spec.model],
        "--num-scens", str(spec.num_scens),
        "--fused-wheel",
        "--lagrangian", "--xhatxbar",
        "--rel-gap", str(spec.gap_target),
        "--max-iterations", str(spec.max_iterations),
        "--flight-recorder", "false",
    ]
    if multiplexed:
        argv += ["--async-staleness", "1"]
    argv += list(_MODEL_ARGS.get(spec.model, ()))
    argv += list(spec.args)
    return argv


class WheelEngine:
    """The production engine: one fused wheel per session."""

    def __init__(self, multiplexed: bool = True,
                 interner: multiplex.StructureInterner | None = None,
                 checkpoint_every_s: float = 30.0):
        self.multiplexed = multiplexed
        self.interner = interner or multiplex.default_interner()
        self.checkpoint_every_s = checkpoint_every_s

    def _build(self, session, ring, fault_plan):
        from mpisppy_tpu import generic_cylinders as gc
        spec = session.spec
        module = importlib.import_module(MODELS[spec.model])
        try:
            cfg = gc._parse_args(module,
                                 session_argv(spec, self.multiplexed))
        except SystemExit as e:
            # argparse exits on unknown/malformed session args — that
            # is a BaseException, which would skip the worker's typed
            # settle and leave the client hanging; type it instead
            raise ValueError(
                f"bad session args {list(spec.args)!r}: {e}") from e
        hub, spokes, names, specs, batch = gc.build_wheel(cfg, module)
        hub = dict(hub)
        opt_kwargs = dict(hub.get("opt_kwargs", {}))
        if opt_kwargs.get("batch") is not None:
            # cross-session coalescing: equal shared structure interned
            # to one object so the scheduler's identity keys match
            opt_kwargs["batch"] = multiplex.intern_batch(
                opt_kwargs["batch"], self.interner)
        hub["opt_kwargs"] = opt_kwargs
        hub["hub_kwargs"] = dict(hub.get("hub_kwargs", {}))
        hub_opts = dict(hub["hub_kwargs"].get("options", {}))
        hub_opts["run_id"] = session.run_id
        hub_opts["telemetry_bus"] = session.bus
        if session.checkpoint_path:
            hub_opts["checkpoint_path"] = session.checkpoint_path
            hub_opts["checkpoint_every_s"] = self.checkpoint_every_s
        # live-migration drain (ISSUE 16): the hub checks this event at
        # every sync prologue and raises PreemptionError (emergency
        # checkpoint) when the fleet router asks the session to move
        hub_opts["preempt_event"] = session.preempt_event
        if fault_plan is not None:
            hub_opts["fault_plan"] = fault_plan
        if self.multiplexed:
            hub["hub_class"] = multiplex.make_multiplexed_hub_class()
            if ring is not None:
                hub_opts["exchange_ring"] = ring
        hub["hub_kwargs"]["options"] = hub_opts
        return hub, spokes

    def run(self, session, ring=None, fault_plan=None) -> tuple:
        """Solve one session.  Returns ('done', payload) or
        ('preempted', payload); raises on a failed solve (the server
        types it for the client)."""
        from mpisppy_tpu.spin_the_wheel import WheelSpinner
        if getattr(session, "streaming", False):
            # rolling-horizon MPC stream (ISSUE 19): one long-lived
            # session, one wheel per window, per-step protocol lines +
            # per-step WFQ charging + its own stream checkpoint
            from mpisppy_tpu.mpc.stream import run_stream
            return run_stream(session, fault_plan=fault_plan)
        if fault_plan is not None:
            # serve chaos seams: an injected hang consumes the session
            # deadline, an injected poison surfaces as a typed failure
            fault_plan.serve_before_solve(session.tenant,
                                          session.ordinal)
        hub, spokes = self._build(session, ring, fault_plan)
        wheel = WheelSpinner(hub, spokes)
        wheel.build()
        if session.restore and session.checkpoint_path \
                and wheel.spcomm._checkpoint_candidates(
                    session.checkpoint_path):
            wheel.spcomm.load_checkpoint(session.checkpoint_path)
        t0 = time.perf_counter()
        try:
            wheel.spin()
        except PreemptionError as e:
            # WheelSpinner.spin already wrote the emergency snapshot;
            # the server re-admits the session with restore=True
            return "preempted", {"iter": wheel.spcomm._iter,
                                 "detail": str(e)}
        abs_gap, rel_gap = wheel.spcomm.compute_gaps()
        if session.checkpoint_path:
            for cand in wheel.spcomm._checkpoint_candidates(
                    session.checkpoint_path):
                try:
                    os.remove(cand)
                except OSError:
                    pass
        return "done", {
            "outer": float(wheel.BestOuterBound),
            "inner": float(wheel.BestInnerBound),
            "rel_gap": float(rel_gap),
            "iterations": wheel.spcomm._iter,
            "solve_seconds": round(time.perf_counter() - t0, 4),
            "preemptions": session.preemptions,
        }


class SyntheticEngine:
    """Deterministic test double: emits the same event stream shape
    (run-start, hub-iteration rows with a closing gap, run-end) and
    honors the serve fault seams, in ~iters*step_s wall seconds.  A
    `preempt_at` map {(tenant, ordinal): iter} simulates preemption
    with checkpoint-free resume (the resumed session continues from
    the recorded iteration).  The resume cursor lives ON the session
    (session.resume_iter), so a fleet-migrated session resumes
    correctly even on a DIFFERENT engine instance — the synthetic
    analogue of the checkpoint travelling through the shared spool."""

    def __init__(self, iters: int = 6, step_s: float = 0.005,
                 preempt_at: dict | None = None):
        self.iters = iters
        self.step_s = step_s
        self.preempt_at = dict(preempt_at or {})

    def run(self, session, ring=None, fault_plan=None) -> tuple:
        if fault_plan is not None:
            fault_plan.serve_before_solve(session.tenant,
                                          session.ordinal)
        key = (session.tenant, session.ordinal)
        start = session.resume_iter
        if start == 0:
            session.bus.emit(tel.RUN_START, run=session.run_id,
                             cyl="hub", hub_class="SyntheticEngine",
                             num_spokes=0)
        gap0 = 0.20
        target = session.spec.gap_target
        for it in range(start + 1, self.iters + 1):
            if session.preempt_event.is_set():
                # migration drain: stop at the iteration boundary, the
                # synthetic stand-in for the emergency checkpoint
                session.resume_iter = it - 1
                return "preempted", {"iter": it - 1,
                                     "detail": "drain-requested"}
            time.sleep(self.step_s)
            frac = it / self.iters
            rel_gap = gap0 * (1.0 - frac) + target * 0.5 * frac
            session.bus.emit(
                tel.HUB_ITERATION, run=session.run_id, cyl="hub",
                hub_iter=it, iter=it, outer=-100.0 - rel_gap * 100.0,
                inner=-100.0, abs_gap=rel_gap * 100.0,
                rel_gap=rel_gap)
            if self.preempt_at.get(key) == it:
                del self.preempt_at[key]     # fire once
                session.resume_iter = it
                return "preempted", {"iter": it, "detail": "synthetic"}
        session.bus.emit(tel.RUN_END, run=session.run_id, cyl="hub",
                         hub_iter=self.iters, reason="converged",
                         outer=-100.05, inner=-100.0, abs_gap=0.05,
                         rel_gap=target * 0.5, iterations=self.iters)
        return "done", {
            "outer": -100.05, "inner": -100.0,
            "rel_gap": float(target * 0.5),
            "iterations": self.iters,
            "solve_seconds": round(
                (self.iters - start) * self.step_s, 4),
            "preemptions": session.preemptions,
        }
