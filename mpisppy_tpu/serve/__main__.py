###############################################################################
# `python -m mpisppy_tpu.serve` — run the multi-tenant wheel server
# (ISSUE 12; docs/serving.md).
#
#   python -m mpisppy_tpu.serve --unix /tmp/wheel.sock \
#       --max-running 2 --tenant-quota 2 --trace-dir ./serve-traces \
#       --spool-dir ./serve-spool
#
# The process serves until SIGINT/SIGTERM; clients speak the JSON-lines
# protocol (serve/protocol.py).  Watch it live with
#   python -m mpisppy_tpu.telemetry watch --trace-dir ./serve-traces
###############################################################################
from __future__ import annotations

import argparse
import sys


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m mpisppy_tpu.serve",
        description="multi-tenant stochastic-program wheel server")
    p.add_argument("--unix", default=None,
                   help="unix socket path to listen on (preferred)")
    p.add_argument("--host", default="127.0.0.1",
                   help="TCP bind host (used when --unix is not set)")
    p.add_argument("--port", type=int, default=7453,
                   help="TCP bind port")
    p.add_argument("--max-running", type=int, default=2,
                   help="concurrent session workers")
    p.add_argument("--max-queued", type=int, default=64,
                   help="global admission queue cap (backpressure)")
    p.add_argument("--tenant-quota", type=int, default=2,
                   help="per-tenant in-flight session cap")
    p.add_argument("--tenant-weight", action="append", default=[],
                   metavar="TENANT=W",
                   help="WFQ weight override (repeatable)")
    p.add_argument("--latency-burst", type=int, default=4,
                   help="consecutive latency-class admissions before "
                        "one throughput session is forced through")
    p.add_argument("--trace-dir", default=None,
                   help="write one JSONL trace per session here "
                        "(telemetry watch --trace-dir tails it)")
    p.add_argument("--spool-dir", default=None,
                   help="session checkpoint spool (preemption-safe "
                        "resume)")
    p.add_argument("--deadline-s", type=float, default=None,
                   help="default per-session deadline (typed failure "
                        "on expiry; sessions may override)")
    p.add_argument("--step-miss-budget", type=int, default=3,
                   help="consecutive per-step deadline misses before "
                        "a RUNNING MPC stream is reaped (ISSUE 19; "
                        "streams set step_deadline_s per session)")
    p.add_argument("--no-multiplex", action="store_true",
                   help="run sessions on the synchronous hub without "
                        "the exchange interleave ring")
    return p


def main(argv=None) -> int:
    args = _build_parser().parse_args(argv)
    weights = {}
    for spec in args.tenant_weight:
        try:
            name, w = spec.split("=", 1)
            weights[name] = float(w)
        except ValueError:
            print(f"bad --tenant-weight {spec!r} (want TENANT=W)",
                  file=sys.stderr)
            return 1
    from mpisppy_tpu.serve.server import ServeOptions, WheelServer
    opts = ServeOptions(
        unix_path=args.unix, host=args.host,
        port=args.port if not args.unix else 0,
        max_running=args.max_running, max_queued=args.max_queued,
        tenant_quota=args.tenant_quota,
        tenant_weights=weights or None,
        latency_burst=args.latency_burst,
        trace_dir=args.trace_dir, spool_dir=args.spool_dir,
        default_deadline_s=args.deadline_s,
        step_miss_budget=args.step_miss_budget,
        multiplex=not args.no_multiplex)
    server = WheelServer(opts).start()
    print(f"serving on {server.address}")  # telemetry: allow-print
    try:
        server.serve_forever()
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
