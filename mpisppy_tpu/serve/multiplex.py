###############################################################################
# Cross-session multiplexing (ISSUE 12 tentpole, piece 3;
# docs/serving.md).
#
# Two mechanisms make many tenants share one wheel efficiently:
#
# 1. STRUCTURE INTERNING — the dispatch scheduler's mergeable-identity
#    key (dispatch/scheduler._request_key) treats SHARED QP structure
#    (a broadcast A, the ELL column index array, a ConeSpec, shared
#    bound vectors) by OBJECT identity: exact and free within one
#    session, where every oracle call threads the same arrays, but
#    blind across sessions — two tenants solving the same model build
#    equal-but-distinct arrays and would never coalesce.  The interner
#    is a content-addressed pool (dtype, shape, byte digest): each
#    session's batch canonicalizes its shared structure ONCE at build
#    time, after which equal structure IS the same object and
#    cross-session requests land in one coalescing window — megabatch
#    sharing across tenants through the unchanged PR-4 scheduler.  A
#    digest miss only costs coalescence, never correctness (the key
#    still separates them).
#
# 2. EXCHANGE INTERLEAVING — the PR-10 async hub splits every sync
#    into a device-issue half and a host-complete half.  The
#    ExchangeRing is a token gate over the host-complete half shared
#    by every session in the server: one session at a time completes
#    its host exchange while the other sessions' issue halves keep
#    feeding the device queue — one wheel advances several tenants
#    between host exchanges.  MultiplexedAsyncHub is an AsyncPHHub
#    wired to the ring via options['exchange_ring'].
###############################################################################
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import threading

import numpy as np


# ---------------------------------------------------------------------------
# structure interning
# ---------------------------------------------------------------------------
class StructureInterner:
    """Content-addressed pool of shared-structure arrays.  The FIRST
    array seen for a digest becomes the canonical object every later
    equal array interns to.  The pool is BOUNDED (`max_entries`, FIFO
    eviction): clients control problem diversity, so an unbounded pool
    would pin every distinct constraint matrix ever served — and by
    design an evicted entry only costs coalescence for later equal
    structure, never correctness (the scheduler key still separates
    non-identical objects)."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        self._pool: dict = {}     # guarded-by: _lock
        self._hits = 0            # guarded-by: _lock
        self._misses = 0          # guarded-by: _lock
        self._evictions = 0       # guarded-by: _lock

    def _insert(self, key, value):     # holds-lock: _lock
        while len(self._pool) >= self.max_entries:
            self._pool.pop(next(iter(self._pool)))
            self._evictions += 1
        self._pool[key] = value
        self._misses += 1

    def intern(self, x):
        """Canonical object for `x` (any host/device array); non-array
        values pass through untouched."""
        if x is None or not hasattr(x, "shape"):
            return x
        host = np.asarray(x)
        key = (str(host.dtype), host.shape,
               hashlib.sha1(np.ascontiguousarray(host)
                            .tobytes()).hexdigest())
        with self._lock:
            hit = self._pool.get(key)
            if hit is not None:
                self._hits += 1
                return hit
            self._insert(key, x)
            return x

    def intern_object(self, obj):
        """ConeSpec-style frozen dataclasses: interned by their array
        fields' digests (the pool stores the first instance)."""
        if obj is None:
            return None
        parts = []
        for f in getattr(obj, "__dataclass_fields__", {}):
            v = getattr(obj, f)
            if hasattr(v, "shape"):
                host = np.asarray(v)
                parts.append((f, str(host.dtype), host.shape,
                              hashlib.sha1(np.ascontiguousarray(host)
                                           .tobytes()).hexdigest()))
            else:
                parts.append((f, repr(v)))
        key = ("obj", type(obj).__name__, tuple(parts))
        with self._lock:
            hit = self._pool.get(key)
            if hit is not None:
                self._hits += 1
                return hit
            self._insert(key, obj)
            return obj

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._pool), "hits": self._hits,
                    "misses": self._misses,
                    "evictions": self._evictions}

    def digests(self) -> tuple:
        """The content digests currently held (ISSUE 16): the fleet
        router's placement-affinity key — a session whose canonical
        structure is already in a replica's pool coalesces there for
        free, so placement prefers that replica.  Array entries report
        their sha1 digest, interned objects a stable object key."""
        with self._lock:
            out = []
            for key in self._pool:
                if key and key[0] == "obj":
                    out.append("obj:" + hashlib.sha1(
                        repr(key).encode()).hexdigest()[:16])
                else:
                    out.append(str(key[-1])[:16])
            return tuple(out)


#: the process-default interner every serve session shares
_default_interner = StructureInterner()


def default_interner() -> StructureInterner:
    return _default_interner


def intern_qp(qp, d_col=None, interner: StructureInterner | None = None):
    """Canonicalize a BoxQP's SHARED (unbatched) structure so the
    dispatch scheduler's identity-keyed coalescing fires across
    sessions: the constraint matrix (dense 2-D, or an EllMatrix's
    cols/vals), the cone spec, and any unbatched bound/cost vectors.
    Batched (per-lane) fields pass through untouched — they concatenate
    per request and carry no identity."""
    it = interner or _default_interner
    A = qp.A
    if hasattr(A, "vals"):          # EllMatrix
        repl = {"cols": it.intern(A.cols)}
        if getattr(A.vals, "ndim", 3) == 2:
            repl["vals"] = it.intern(A.vals)
        A = dataclasses.replace(A, **repl)
    elif getattr(A, "ndim", 0) == 2:
        A = it.intern(A)
    fields = {"A": A}
    for name in ("c", "q", "bl", "bu", "l", "u"):
        v = getattr(qp, name)
        if getattr(v, "ndim", 0) == 1:
            fields[name] = it.intern(v)
    cones = getattr(qp, "cones", None)
    if cones is not None:
        fields["cones"] = it.intern_object(cones)
    qp = dataclasses.replace(qp, **fields)
    if d_col is None:
        return qp
    if getattr(d_col, "ndim", 0) == 1:
        d_col = it.intern(d_col)
    return qp, d_col


def intern_batch(batch, interner: StructureInterner | None = None):
    """Canonicalize a ScenarioBatch's shared structure (the engine
    calls this once per session at build time), so every downstream
    oracle QP derived from it shares identity with equal-structure
    batches of OTHER sessions."""
    qp, d_col = intern_qp(batch.qp, batch.d_col, interner)
    return dataclasses.replace(batch, qp=qp, d_col=d_col)


# ---------------------------------------------------------------------------
# exchange interleaving
# ---------------------------------------------------------------------------
class ExchangeRing:
    """Token gate over the async hub's host-complete half: at most one
    session completes its host exchange at a time; everyone else's
    device-issue halves keep the wheel fed.  Contention is counted so
    the serve stats show how often tenants actually interleaved."""

    def __init__(self):
        self._sem = threading.Semaphore(1)
        self._lock = threading.Lock()
        self._grants = 0          # guarded-by: _lock
        self._waits = 0           # guarded-by: _lock

    @contextlib.contextmanager
    def exchange(self):
        contended = not self._sem.acquire(blocking=False)
        if contended:
            self._sem.acquire()
        with self._lock:
            self._grants += 1
            if contended:
                self._waits += 1
        try:
            yield
        finally:
            self._sem.release()

    def stats(self) -> dict:
        with self._lock:
            return {"grants": self._grants, "waits": self._waits}


def make_multiplexed_hub_class():
    """AsyncPHHub subclass whose host-complete half runs under the
    ExchangeRing in options['exchange_ring'] (absent -> plain async
    behavior).  Built lazily so importing serve.multiplex does not pull
    jax via the cylinders package on trace-only hosts."""
    from mpisppy_tpu.cylinders import hub as hub_mod

    class MultiplexedAsyncHub(hub_mod.AsyncPHHub):
        def _exchange_gate(self):
            ring = self.options.get("exchange_ring")
            if ring is None:
                return contextlib.nullcontext()
            return ring.exchange()

    return MultiplexedAsyncHub
