###############################################################################
# Load generator + client library (ISSUE 12 tentpole, piece 4;
# docs/serving.md).
#
# ServeClient is the minimal protocol client (connect / submit /
# stream-until-terminal); run_load drives N synthetic clients with a
# mixed farmer/sslp/uc workload against a running server and measures
# what the acceptance criteria name:
#
#   * per-session TIME-TO-TARGET-GAP: the wall clock from submit to the
#     first streamed progress line whose rel_gap <= the session's gap
#     target (falling back to the terminal line for sessions whose
#     engine reports only the final gap);
#   * p50/p99 across the HEALTHY tenants' sessions — the serve_load
#     bench phase's headline numbers;
#   * TENANT ISOLATION: run_load runs once clean and once with an
#     adversarial tenant (flood via the ServeFault seam + hang/
#     disconnect behaviors); healthy-tenant p99 in the adversarial run
#     within 25% of the clean baseline is the acceptance line
#     (BENCH_r08 serve_load.isolation.isolation_ratio, gated).
#
# Every record carries the terminal outcome kind, so the no-hang
# contract is asserted mechanically: a session with no terminal
# outcome is a harness failure, not a statistic.
###############################################################################
from __future__ import annotations

import json
import socket
import threading
import time

import numpy as np

from mpisppy_tpu.serve.protocol import SubmitRequest, TERMINAL_EVENTS
from mpisppy_tpu.telemetry.tracecontext import TraceContext


class ServeClient:
    """Blocking JSON-lines client for one connection."""

    def __init__(self, address, timeout: float = 300.0):
        if isinstance(address, str):
            self.sock = socket.socket(socket.AF_UNIX,
                                      socket.SOCK_STREAM)
            self.sock.connect(address)
        else:
            self.sock = socket.create_connection(tuple(address))
        self.sock.settimeout(timeout)
        self._rfile = self.sock.makefile("rb")
        self._stashed: list = []   # events read while waiting for acks

    def send(self, obj: dict) -> None:
        self.sock.sendall((json.dumps(obj) + "\n").encode())

    def recv(self) -> dict:
        line = self._rfile.readline()
        if not line:
            raise ConnectionError("server closed the connection")
        return json.loads(line)

    def submit(self, spec: SubmitRequest) -> dict:
        """Submit and read lines until THIS submit's ack arrives
        (streamed events for earlier sessions may interleave — they are
        returned to the caller via collect())."""
        self.send(spec.to_dict())
        while True:
            msg = self.recv()
            if "ok" in msg and msg.get("event") is None:
                return msg
            self._stashed.append(msg)

    def stream(self):
        """Yield stashed + live messages."""
        while self._stashed:
            yield self._stashed.pop(0)
        while True:
            yield self.recv()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def run_session(client: ServeClient, spec: SubmitRequest,
                wait_terminal: bool = True) -> dict:
    """Submit one session and stream it to its terminal outcome.
    Returns the record the load summary consumes."""
    # causal trace context (ISSUE 20): the CLIENT mints the root trace
    # at submit — the server adopts it, so the record's trace_id joins
    # the client-observed latency to the server-side span tree
    if getattr(spec, "traceparent", None) is None:
        import dataclasses as _dc
        spec = _dc.replace(
            spec, traceparent=TraceContext.mint().to_traceparent())
    ctx = TraceContext.from_traceparent(spec.traceparent)
    t0 = time.perf_counter()
    ack = client.submit(spec)
    rec = {"tenant": spec.tenant, "sla": spec.sla, "model": spec.model,
           "submit_t": t0, "session": ack.get("session"),
           "trace_id": ctx.trace_id if ctx else None,
           "outcome": None, "time_to_gap_s": None, "total_s": None,
           "preempted": 0}
    if not ack.get("ok"):
        rec["outcome"] = "rejected"
        rec["reason"] = ack.get("reason")
        rec["total_s"] = time.perf_counter() - t0
        return rec
    if not wait_terminal:
        return rec
    sid = ack["session"]
    for msg in client.stream():
        if msg.get("session") not in (None, sid):
            continue
        ev = msg.get("event")
        if ev == "progress" and rec["time_to_gap_s"] is None:
            g = msg.get("rel_gap")
            if g is not None and g <= spec.gap_target:
                rec["time_to_gap_s"] = time.perf_counter() - t0
        elif ev == "preempted":
            rec["preempted"] += 1
        elif ev in TERMINAL_EVENTS:
            rec["outcome"] = ev
            rec["reason"] = msg.get("reason")
            rec["total_s"] = time.perf_counter() - t0
            if rec["time_to_gap_s"] is None and ev == "done" \
                    and msg.get("rel_gap") is not None \
                    and msg["rel_gap"] <= spec.gap_target:
                rec["time_to_gap_s"] = rec["total_s"]
            return rec
    rec["outcome"] = "disconnected"
    rec["total_s"] = time.perf_counter() - t0
    return rec


def _pct(xs, q):
    return float(np.percentile(np.asarray(xs), q)) if xs else None


def summarize(records: list[dict],
              healthy_tenants=None) -> dict:
    """p50/p99 time-to-gap + outcome accounting over (optionally a
    tenant subset of) the records."""
    rel = [r for r in records
           if healthy_tenants is None or r["tenant"] in healthy_tenants]
    hits = [r["time_to_gap_s"] for r in rel
            if r["time_to_gap_s"] is not None]
    outcomes: dict = {}
    for r in rel:
        outcomes[r["outcome"] or "none"] = \
            outcomes.get(r["outcome"] or "none", 0) + 1
    return {
        "sessions": len(rel),
        "reached_gap": len(hits),
        "time_to_gap_p50_s": (round(_pct(hits, 50), 4)
                              if hits else None),
        "time_to_gap_p99_s": (round(_pct(hits, 99), 4)
                              if hits else None),
        "total_p50_s": round(_pct(
            [r["total_s"] for r in rel if r["total_s"] is not None],
            50) or 0.0, 4),
        "outcomes": outcomes,
        "preemptions": sum(r.get("preempted", 0) for r in rel),
    }


#: the default mixed workload (model, num_scens, sla) — cycled per
#: client so every tenant touches every model class
DEFAULT_MIX = (
    ("farmer", 3, "latency"),
    ("sslp", 4, "throughput"),
    ("farmer", 4, "throughput"),
    ("uc", 3, "throughput"),
)


def run_load(address, n_clients: int = 8, sessions_each: int = 2,
             tenants=("acme", "zeta"), mix=DEFAULT_MIX,
             gap_target: float = 0.01, max_iterations: int = 200,
             deadline_s: float | None = 120.0,
             adversary: str | None = None,
             adversary_sessions: int = 8,
             fault_plan=None, seed: int = 0) -> list[dict]:
    """N concurrent clients round-robined over `tenants`, each running
    `sessions_each` sessions drawn from `mix` sequentially.  With
    `adversary` set, one extra client floods that tenant (submit
    count scaled by the fault plan's flood factor when armed, never
    reading backpressure as failure) while hanging/disconnect seams
    ride the server's own FaultPlan."""
    records: list[dict] = []
    rec_lock = threading.Lock()

    def client_body(ci: int):
        tenant = tenants[ci % len(tenants)]
        cl = ServeClient(address)
        try:
            for k in range(sessions_each):
                model, scens, sla = mix[(ci + k) % len(mix)]
                spec = SubmitRequest(
                    tenant=tenant, sla=sla, model=model,
                    num_scens=scens, gap_target=gap_target,
                    max_iterations=max_iterations,
                    deadline_s=deadline_s)
                rec = run_session(cl, spec)
                with rec_lock:
                    records.append(rec)
        finally:
            cl.close()

    def adversary_body():
        n = adversary_sessions
        if fault_plan is not None:
            n *= fault_plan.serve_flood_factor(adversary)
        cl = ServeClient(address)
        acks = []
        try:
            # flood: fire-and-forget submits — backpressure answers
            # with typed rejects, which the harness records as such
            for k in range(n):
                model, scens, _ = mix[k % len(mix)]
                spec = SubmitRequest(
                    tenant=adversary, sla="latency", model=model,
                    num_scens=scens, gap_target=gap_target,
                    max_iterations=max_iterations,
                    deadline_s=deadline_s)
                acks.append(run_session(cl, spec,
                                        wait_terminal=False))
                time.sleep(0.002)
            # then stop reading entirely (a hanging consumer) and
            # finally drop the connection mid-stream
            time.sleep(0.2)
        finally:
            cl.close()
        with rec_lock:
            for a in acks:
                if not a.get("outcome"):
                    # submitted then never streamed: the flood client
                    # walked away — the SERVER still settles these
                    # (drain rejects or detached completion)
                    a["outcome"] = "abandoned"
                records.append(a)

    threads = [threading.Thread(target=client_body, args=(i,),
                                daemon=True, name=f"loadgen-{i}")
               for i in range(n_clients)]
    if adversary is not None:
        threads.append(threading.Thread(target=adversary_body,
                                        daemon=True,
                                        name="loadgen-adversary"))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return records
