###############################################################################
# Serve-layer wire protocol (ISSUE 12 tentpole; docs/serving.md).
#
# JSON lines over a Unix or TCP socket — stdlib only, one JSON object
# per newline-terminated line, both directions.  Client requests:
#
#   {"op": "submit", "tenant": "acme", "sla": "latency",
#    "model": "farmer", "num_scens": 3, "gap_target": 0.01,
#    "deadline_s": 120.0, "args": ["--crops-multiplier", "1"]}
#   {"op": "ping"}
#   {"op": "stats"}
#   {"op": "status"}     (ISSUE 16: lightweight health probe — replica
#                         id, session counts by state, queue depth,
#                         free slots, interner digests held; the fleet
#                         router's health checks ride this op)
#
# Server responses: one ack per request ({"ok": true, "session": sid}
# or {"ok": false, "error": ..., "reason": ...}), then a stream of
# per-session events scoped to THIS client's sessions:
#
#   {"event": "session-state", "session": sid, "state": "RUNNING", ...}
#   {"event": "progress", "session": sid, "iter": 7, "outer": ...,
#    "inner": ..., "rel_gap": ...}
#   {"event": "preempted", "session": sid}            (non-terminal)
#   {"event": "done", "session": sid, ...}            (terminal)
#   {"event": "failed", "session": sid, "reason": ...}(terminal)
#   {"event": "rejected", "reason": "tenant-quota", ...} (terminal)
#
# The terminal-outcome contract (docs/serving.md failure semantics):
# every submitted session produces EXACTLY ONE terminal event — done,
# failed (typed reason), or rejected — never a silent hang.  A
# preemption mid-run emits the non-terminal "preempted" and the session
# resumes from its checkpoint with no client-visible state loss.
###############################################################################
from __future__ import annotations

import dataclasses
import json

#: SLA classes (ROADMAP items 2+5: the same server, two service
#: classes).  latency = admission-priority interactive re-solves;
#: throughput = batch certification runs that fill remaining capacity.
SLA_CLASSES = ("latency", "throughput")

#: models a session may request; each maps to a model module the engine
#: builds through the generic_cylinders CLI recipe surface
MODELS = {
    "farmer": "mpisppy_tpu.models.farmer",
    "sslp": "mpisppy_tpu.models.sslp",
    "uc": "mpisppy_tpu.models.uc",
    "ccopf": "mpisppy_tpu.models.ccopf",
}

#: terminal client-visible events — exactly one per session
TERMINAL_EVENTS = ("done", "failed", "rejected")

#: request ops a server answers (anything else gets a typed error line)
REQUEST_OPS = ("submit", "ping", "stats", "status")


class ProtocolError(ValueError):
    """Malformed client request — answered with a typed error line,
    never a dropped connection."""


@dataclasses.dataclass(frozen=True)
class SubmitRequest:
    """One validated session submission."""

    tenant: str
    sla: str = "throughput"
    model: str = "farmer"
    num_scens: int = 3
    gap_target: float = 0.01
    deadline_s: float | None = None
    max_iterations: int = 200
    args: tuple[str, ...] = ()
    #: rolling-horizon stream (ISSUE 19, docs/mpc.md): > 0 makes this a
    #: long-lived MPC session streaming one `step` line per window;
    #: step_deadline_s arms the PER-STEP deadline the streaming reaper
    #: enforces (consecutive-miss budget) instead of deadline_s' wall
    #: clock
    mpc_steps: int = 0
    step_deadline_s: float | None = None
    #: causal trace context (ISSUE 20, docs/telemetry.md): the W3C
    #: traceparent string minted at client submit.  None/malformed =>
    #: the Session (or the fleet router) mints a fresh trace — a
    #: trace-less client still gets a fully traced request.
    traceparent: str | None = None

    @classmethod
    def from_dict(cls, d: dict) -> "SubmitRequest":
        if not isinstance(d, dict):
            raise ProtocolError("submit payload must be an object")
        tenant = d.get("tenant")
        if not tenant or not isinstance(tenant, str):
            raise ProtocolError("submit needs a non-empty 'tenant'")
        sla = d.get("sla", "throughput")
        if sla not in SLA_CLASSES:
            raise ProtocolError(
                f"unknown sla {sla!r} (want one of {SLA_CLASSES})")
        model = d.get("model", "farmer")
        if model not in MODELS:
            raise ProtocolError(
                f"unknown model {model!r} (want one of "
                f"{tuple(MODELS)})")
        try:
            num_scens = int(d.get("num_scens", 3))
            gap = float(d.get("gap_target", 0.01))
            max_iters = int(d.get("max_iterations", 200))
        except (TypeError, ValueError) as e:
            raise ProtocolError(f"bad numeric field: {e}") from e
        if num_scens < 1:
            raise ProtocolError("num_scens must be >= 1")
        if not (0.0 < gap < 1.0):
            raise ProtocolError("gap_target must be in (0, 1)")
        ddl = d.get("deadline_s")
        if ddl is not None:
            ddl = float(ddl)
            if ddl <= 0:
                raise ProtocolError("deadline_s must be positive")
        args = d.get("args", ())
        if not isinstance(args, (list, tuple)) \
                or not all(isinstance(a, str) for a in args):
            raise ProtocolError("'args' must be a list of strings")
        try:
            mpc_steps = int(d.get("mpc_steps", 0))
        except (TypeError, ValueError) as e:
            raise ProtocolError(f"bad mpc_steps: {e}") from e
        if mpc_steps < 0:
            raise ProtocolError("mpc_steps must be >= 0")
        sddl = d.get("step_deadline_s")
        if sddl is not None:
            sddl = float(sddl)
            if sddl <= 0:
                raise ProtocolError("step_deadline_s must be positive")
        if sddl is not None and not mpc_steps:
            raise ProtocolError(
                "step_deadline_s only applies to an MPC stream "
                "(mpc_steps > 0)")
        tp = d.get("traceparent")
        if tp is not None and not isinstance(tp, str):
            raise ProtocolError("'traceparent' must be a string")
        return cls(tenant=tenant, sla=sla, model=model,
                   num_scens=num_scens, gap_target=gap, deadline_s=ddl,
                   max_iterations=max_iters, args=tuple(args),
                   mpc_steps=mpc_steps, step_deadline_s=sddl,
                   traceparent=tp)

    def to_dict(self) -> dict:
        return {"op": "submit", "tenant": self.tenant, "sla": self.sla,
                "model": self.model, "num_scens": self.num_scens,
                "gap_target": self.gap_target,
                "deadline_s": self.deadline_s,
                "max_iterations": self.max_iterations,
                "args": list(self.args),
                "mpc_steps": self.mpc_steps,
                "step_deadline_s": self.step_deadline_s,
                "traceparent": self.traceparent}


def encode(obj: dict) -> bytes:
    """One wire line.  Strict JSON (non-finite floats would emit bare
    NaN/Infinity tokens non-Python peers reject) — the same convention
    as the JSONL trace (telemetry/events._jsonable)."""
    from mpisppy_tpu.telemetry.events import _jsonable
    return (json.dumps(_jsonable(obj)) + "\n").encode()


def iter_lines(sock_file):
    """Yield decoded JSON objects from a socket file object; a
    malformed line yields a ProtocolError-tagged dict instead of
    killing the reader."""
    for raw in sock_file:
        raw = raw.strip()
        if not raw:
            continue
        try:
            yield json.loads(raw)
        except ValueError:
            yield {"_malformed": raw.decode("utf-8", "replace")
                   if isinstance(raw, bytes) else str(raw)}
