###############################################################################
# Session lifecycle (ISSUE 12 tentpole, piece 1; docs/serving.md).
#
# One Session is one tenant problem instance moving through
#
#     QUEUED -> ADMITTED -> RUNNING -> DONE | FAILED
#                              ^  \
#                              |   v
#                           DEGRADED        (preemption-restore /
#                                            watchdog degrade; resumes
#                                            to RUNNING)
#     QUEUED -> REJECTED                    (admission backpressure)
#
# Transitions are validated against TRANSITIONS (an illegal move is a
# server bug and raises), and every transition is emitted as ONE
# `session-state` event on BOTH buses: the session's own scoped bus
# (below) and the server bus, so `telemetry watch --trace-dir` and the
# analyzer see the same lifecycle the client streamed.
#
# PER-SESSION TELEMETRY SCOPING: each session owns an EventBus with a
# JsonlSink writing trace_dir/session-<sid>.jsonl.  The session's hub
# gets THIS bus as options['telemetry_bus'], so the whole existing
# event taxonomy (hub-iteration / bound-accept / checkpoint-* /
# run-end, docs/telemetry.md) lands per session with no new plumbing —
# and a _ClientForwardSink subscriber converts the bound-progress
# stream into the client's `progress` lines.  One wheel vocabulary,
# three consumers (client stream, per-session trace, live watch).
###############################################################################
from __future__ import annotations

import itertools
import os
import threading
import time

from mpisppy_tpu import telemetry as tel
from mpisppy_tpu.serve.protocol import SubmitRequest
from mpisppy_tpu.telemetry import metrics as _metrics

QUEUED = "QUEUED"
ADMITTED = "ADMITTED"
RUNNING = "RUNNING"
DEGRADED = "DEGRADED"
DONE = "DONE"
FAILED = "FAILED"
REJECTED = "REJECTED"

#: legal lifecycle moves (docs/serving.md session-state table)
TRANSITIONS = {
    QUEUED: (ADMITTED, REJECTED, FAILED),
    ADMITTED: (RUNNING, FAILED),
    RUNNING: (DEGRADED, DONE, FAILED),
    DEGRADED: (RUNNING, DONE, FAILED),
    DONE: (),
    FAILED: (),
    REJECTED: (),
}

TERMINAL_STATES = (DONE, FAILED, REJECTED)

_sid_counter = itertools.count()


class _ClientForwardSink:
    """Bus subscriber forwarding the session's bound progress and
    terminal verdicts to its client as protocol lines.  Send failures
    (a disconnected client) detach the outbox — the session keeps
    running to its terminal state regardless (quota accounting and the
    per-session trace never depend on the client still listening)."""

    def __init__(self, session: "Session"):
        self.session = session

    def handle(self, event) -> None:
        kind = event.kind
        if kind == tel.HUB_ITERATION:
            d = event.data
            self.session.send({
                "event": "progress", "session": self.session.sid,
                "iter": d.get("iter"), "outer": d.get("outer"),
                "inner": d.get("inner"), "rel_gap": d.get("rel_gap")})
        elif kind == tel.CHECKPOINT_RESTORE:
            self.session.send({
                "event": "restored", "session": self.session.sid,
                "iter": event.hub_iter})

    def close(self) -> None:
        pass


class Session:
    """One tenant problem instance: the admission unit, the telemetry
    scope, and the terminal-outcome obligation."""

    def __init__(self, spec: SubmitRequest, outbox=None,
                 server_bus=None, trace_dir: str | None = None):
        self.sid = f"s{next(_sid_counter):04d}"
        self.spec = spec
        self.tenant = spec.tenant
        self.sla = spec.sla
        self.ordinal = -1          # per-tenant admission ordinal
                                   # (stamped by the admission queue)
        self.run_id = tel.new_run_id()   # the wheel run this session IS
        self.server_bus = server_bus
        self.t_submit = time.perf_counter()
        self.t_started: float | None = None
        self.t_finished: float | None = None
        self.deadline = None if spec.deadline_s is None \
            else self.t_submit + float(spec.deadline_s)
        self.restore = False       # resume from checkpoint (preemption)
        self.preemptions = 0
        self.checkpoint_path: str | None = None
        # -- fleet surface (ISSUE 16) -- a session routed through the
        # fleet router carries its placement identity with it: which
        # replica currently runs it, its content-addressed routing key,
        # and how many times it migrated.  preempt_event is the live-
        # migration drain signal: the hub checks it at every sync
        # prologue and raises PreemptionError (emergency checkpoint)
        # when set, so a drain lands at a consistent boundary.
        self.replica = ""
        self.structure_key = ""
        self.migrations = 0
        self.resume_iter = 0       # engine-agnostic resume cursor
        self.preempt_event = threading.Event()
        # invoked (with this session) after settle() delivers the one
        # terminal outcome — the fleet router's quota-release hook
        self.on_terminal = None
        # -- rolling-horizon stream surface (ISSUE 19) -- an MPC
        # session (spec.mpc_steps > 0) is long-lived by design: its
        # liveness unit is the STEP, not the session.  mpc_step is the
        # resume cursor (next window to solve — a preempted stream
        # restores here and re-derives the window bit-identically);
        # note_step advances it, re-arms the per-step deadline anchor,
        # and fires on_step (the admission queue's per-step WFQ charge,
        # server.submit_session wires it).
        self.mpc_step = 0
        self.on_step = None
        self._step_anchor = self.t_submit    # guarded-by: _lock
        self._trace_sink = None    # guarded-by: _lock
        # Lock discipline (tools/graftlint lock-discipline): lifecycle
        # state and the client outbox are touched from the reader
        # thread, the scheduler thread, the session worker, and the
        # deadline reaper.
        self._lock = threading.Lock()
        self._state = QUEUED              # guarded-by: _lock
        self._outbox = outbox             # guarded-by: _lock
        self._terminal_sent = False       # guarded-by: _lock
        self.outcome: dict | None = None  # guarded-by: _lock
        # per-session telemetry scope
        self.bus = tel.EventBus()
        self.trace_path = None
        # causal trace (ISSUE 20; telemetry/tracecontext.py): adopt the
        # client's traceparent or mint a fresh root — either way every
        # event this session (and its hub, dispatch attribution, MPC
        # windows) emits carries one trace id end to end.  The root
        # span IS the request; each run attempt opens a child segment
        # span (begin_segment), so a migration renders as two sibling
        # segments under one root and the gap between them is the
        # migration gap.
        self.trace = tel.TraceContext.from_traceparent(
            getattr(spec, "traceparent", None)) or tel.TraceContext.mint()
        self.segment = None        # current run-segment TraceContext
        self.bus.set_trace(self.trace)
        if trace_dir:
            self.attach_trace(trace_dir)
        self.bus.subscribe(_ClientForwardSink(self))
        # dual-emit like transition(): in the fleet path the session
        # bus has no sinks until the replica attaches its trace dir,
        # so the root span-start must also land on the server/router
        # stream or the assembled tree loses its root's name
        for bus in (self.bus, self.server_bus):
            if bus is not None:
                bus.emit(tel.SPAN_START, run=self.run_id, cyl="serve",
                         trace=self.trace, name="request",
                         session=self.sid, tenant=self.tenant,
                         sla=self.sla)

    # -- per-replica trace attachment (ISSUE 16) --------------------------
    def attach_trace(self, trace_dir: str) -> None:
        """Subscribe a JsonlSink under trace_dir for this session.  A
        migrating session detaches from the source replica's trace dir
        and re-attaches under the destination's, so each replica's
        trace shows exactly the lifecycle segment it hosted (watch
        joins the segments on sid + run id)."""
        self.detach_trace()
        sink = tel.JsonlSink(os.path.join(
            trace_dir, f"session-{self.sid}.jsonl"))
        with self._lock:
            self._trace_sink = sink
            self.trace_path = sink.path
        self.bus.subscribe(sink)

    def detach_trace(self) -> None:
        with self._lock:
            sink = self._trace_sink
            self._trace_sink = None
        if sink is not None:
            self.bus.unsubscribe(sink)
            sink.close()

    @property
    def trace_attached(self) -> bool:
        with self._lock:
            return self._trace_sink is not None

    # -- run segments (ISSUE 20) ------------------------------------------
    def begin_segment(self, name: str = "segment", **data):
        """Open a child span of the request trace for ONE run attempt
        (one replica hosting, one resume).  The session bus is scoped
        to it, so every hub/dispatch/MPC event of the attempt carries
        the segment span; a later attempt (after preemption/migration)
        opens a sibling segment under the same root."""
        seg = self.trace.child()
        self.segment = seg
        self.bus.set_trace(seg)
        self.bus.emit(tel.SPAN_START, run=self.run_id, cyl="serve",
                      name=name, session=self.sid,
                      replica=self.replica or None,
                      resume_iter=self.resume_iter,
                      restore=self.restore, **data)
        return seg

    def end_segment(self) -> None:
        """Detach the current segment span (preemption/migration
        hand-off): subsequent events fall back to the request root
        until the next begin_segment."""
        self.segment = None
        self.bus.set_trace(self.trace)

    # -- state machine ----------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def transition(self, new_state: str, **data) -> None:
        """One validated lifecycle move + its session-state event on
        both buses + the client's session-state line."""
        with self._lock:
            old = self._state
            if new_state not in TRANSITIONS[old]:
                raise RuntimeError(
                    f"illegal session transition {old} -> {new_state} "
                    f"({self.sid})")
            self._state = new_state
        payload = dict(data)
        payload.update(session=self.sid, tenant=self.tenant,
                       sla=self.sla, state=new_state, prev=old)
        if self.replica:
            payload.setdefault("replica", self.replica)
        trace = self.segment or self.trace
        for bus in (self.bus, self.server_bus):
            if bus is not None:
                bus.emit(tel.SESSION_STATE, run=self.run_id,
                         cyl="serve", trace=trace, **payload)
        self.send({"event": "session-state", **payload})

    def is_terminal(self) -> bool:
        with self._lock:
            return self._state in TERMINAL_STATES

    # -- client stream ----------------------------------------------------
    def send(self, msg: dict) -> bool:
        """Best-effort line to this session's client; a dead outbox is
        detached (the session is then 'detached' but still accounted)."""
        with self._lock:
            outbox = self._outbox
        if outbox is None:
            return False
        try:
            outbox(msg)
            return True
        except Exception:
            with self._lock:
                self._outbox = None
            _metrics.REGISTRY.inc("serve_disconnects_total")
            return False

    def detach(self) -> None:
        """Drop the client outbox (disconnect seam / closed reader)."""
        with self._lock:
            self._outbox = None

    @property
    def detached(self) -> bool:
        with self._lock:
            return self._outbox is None

    # -- terminal outcomes ------------------------------------------------
    def settle(self, event: str, **payload) -> bool:
        """Deliver the session's ONE terminal outcome: transition to
        the terminal state, record the outcome, send the terminal
        protocol line exactly once, and close the session bus.  The
        no-hang contract's last line of defense — every exit path of
        the server worker funnels through here.  Returns True when
        THIS call performed the delivery (False = already settled), so
        callers can account failures exactly once."""
        state = {"done": DONE, "failed": FAILED,
                 "rejected": REJECTED}[event]
        with self._lock:
            already = self._terminal_sent
            if not already:
                self._terminal_sent = True
                self.outcome = {"event": event, **payload}
        if already:
            return False
        self.t_finished = time.perf_counter()
        if self.state != state:       # REJECTED may come straight from
            self.transition(state, **payload)   # QUEUED; others move
        self.send({"event": event, "session": self.sid, **payload})
        # one terminal SLO sample per session (ISSUE 20; slo.py folds
        # these into error budgets) — stamped on the request ROOT span,
        # emitted before the bus closes so the per-session trace ends
        # on it
        total_s = self.t_finished - self.t_submit
        obs = dict(session=self.sid, tenant=self.tenant, sla=self.sla,
                   outcome=event, total_s=round(total_s, 6),
                   deadline_s=self.spec.deadline_s,
                   migrations=self.migrations,
                   preemptions=self.preemptions)
        if self.streaming:
            obs.update(steps=self.mpc_step,
                       steps_expected=self.spec.mpc_steps,
                       step_deadline_s=self.spec.step_deadline_s)
        for bus in (self.bus, self.server_bus):
            if bus is not None:
                bus.emit(tel.SLO_OBSERVATION, run=self.run_id,
                         cyl="serve", trace=self.trace, **obs)
        _metrics.REGISTRY.observe("slo_session_latency_s", total_s,
                                  sla=self.sla)
        self.bus.close()
        cb = self.on_terminal
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass   # a router hook must never block the delivery
        return True

    # -- rolling-horizon stream (ISSUE 19) --------------------------------
    @property
    def streaming(self) -> bool:
        """True for an MPC stream session (one solution line per step;
        reaped on per-step deadline misses, not session wall clock)."""
        return getattr(self.spec, "mpc_steps", 0) > 0

    def reset_step_anchor(self) -> None:
        """Re-arm the per-step deadline clock — called when the stream
        (re)enters RUNNING so queue/preemption time is never billed
        against the first step's deadline."""
        with self._lock:
            self._step_anchor = time.perf_counter()

    def note_step(self, step: int, **info) -> None:
        """One completed window: advance the resume cursor, re-arm the
        step deadline, charge the step through WFQ (on_step)."""
        with self._lock:
            self.mpc_step = int(step) + 1
            self._step_anchor = time.perf_counter()
        cb = self.on_step
        if cb is not None:
            try:
                cb(self)
            except Exception:
                pass   # accounting must never kill the stream

    def steps_overdue(self, now: float | None = None) -> int:
        """Whole per-step deadline windows elapsed since the last
        completed step — the reaper's consecutive-miss count.  0 when
        the session has no per-step deadline."""
        sd = getattr(self.spec, "step_deadline_s", None)
        if not sd:
            return 0
        if now is None:
            now = time.perf_counter()
        with self._lock:
            anchor = self._step_anchor
        return max(0, int((now - anchor) / float(sd)))

    def seconds(self) -> float | None:
        if self.t_finished is None:
            return None
        return self.t_finished - self.t_submit
