###############################################################################
# APL1P: two-generator capacity expansion under demand + availability
# uncertainty (ref:mpisppy/tests/examples/apl1p.py; costs follow Bailey,
# Jensen & Morton's response-surface study of the Infanger 1992 model).
#
# First stage: generator capacities Cap_g >= Cmin (continuous nonants).
# Second stage: operation levels Op_{g,dl} per demand level and unserved
# demand U_dl with penalty cost.  Per-scenario randomness (seeded
# exactly like the reference: RandomState(scennum).rand(6), indices 1-2
# for availability, 3-5 for demand):
#     Avail_g  ~ discrete({1,.9,.5,.1} / {1,.9,.7,.1,0})
#     Demand_dl ~ discrete({900,1000,1100,1200})
#
# Columns (n = 11): [Cap_1, Cap_2, Op_{1,1..3}, Op_{2,1..3}, U_{1..3}]
# Rows (m = 5): max-operating per g (sum_dl Op_gdl - Avail_g Cap_g <= 0)
#               demand per dl (sum_g Op_gdl + U_dl >= Demand_dl)
# (Cmin enters as the Cap box lower bound.)
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num

_AVAIL_OUTCOME = ([1.0, 0.9, 0.5, 0.1], [1.0, 0.9, 0.7, 0.1, 0.0])
_AVAIL_CUMPROB = (np.cumsum([0.2, 0.3, 0.4, 0.1]),
                  np.cumsum([0.1, 0.2, 0.5, 0.1, 0.1]))
_DEMAND_OUTCOME = [900.0, 1000.0, 1100.0, 1200.0]
_DEMAND_CUMPROB = np.cumsum([0.15, 0.45, 0.25, 0.15])
_INVEST = np.array([4.0, 2.5])
_OP_COST = np.array([[4.3, 2.0, 0.5], [8.7, 4.0, 1.0]])
_UNSERVED = 10.0
_CMIN = 1000.0


def sample(scennum: int):
    """(avail (2,), demand (3,)) drawn with the reference's stream."""
    rng = np.random.RandomState(scennum)
    r = rng.rand(6)
    avail = np.array([
        _AVAIL_OUTCOME[g][int(np.searchsorted(_AVAIL_CUMPROB[g], r[g + 1]))]
        for g in range(2)])
    demand = np.array([
        _DEMAND_OUTCOME[int(np.searchsorted(_DEMAND_CUMPROB, r[3 + dl]))]
        for dl in range(3)])
    return avail, demand


def scenario_creator(scenario_name: str, num_scens: int | None = None,
                     **_ignored) -> ScenarioSpec:
    scennum = extract_num(scenario_name)
    avail, demand = sample(scennum)
    n = 11
    c = np.concatenate([_INVEST, _OP_COST.reshape(-1),
                        np.full(3, _UNSERVED)])
    l = np.zeros(n)  # noqa: E741
    l[:2] = _CMIN
    u = np.full(n, np.inf)
    # generous finite caps keep every dual bound finite for the B&B path
    u[:2] = 10_000.0
    u[2:] = 5_000.0
    A = np.zeros((5, n))
    for g in range(2):
        A[g, 2 + 3 * g:5 + 3 * g] = 1.0
        A[g, g] = -avail[g]
    for dl in range(3):
        A[2 + dl, 2 + dl] = 1.0      # Op_{1,dl}
        A[2 + dl, 5 + dl] = 1.0      # Op_{2,dl}
        A[2 + dl, 8 + dl] = 1.0      # U_dl
    bl = np.concatenate([np.full(2, -np.inf), demand])
    bu = np.concatenate([np.zeros(2), np.full(3, np.inf)])
    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(2, dtype=np.int32),
        probability=None if num_scens is None else 1.0 / num_scens,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(cfg):
    return {"num_scens": cfg.get("num_scens")}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
