###############################################################################
# Hydro (elec3): the canonical 3-stage hydro-thermal scheduling problem,
# generated natively as BoxQP scenario specs (no Pyomo).  Matches the
# reference model's data and tree semantics
# (ref:examples/hydro/hydro.py:42-151,216-244 and the PySP node data
# ref:examples/hydro/PySP/nodedata/*.dat):
#
#   per stage t=1..3:  Pgt[t] thermal gen   in [0, 100]
#                      Pgh[t] hydro gen     in [0, 100]
#                      PDns[t] unserved     in [0, D_t]
#                      Vol[t] reservoir     in [0, 100]
#   plus sl >= 0 (future-cost slack at the last stage).
#   demand:   Pgt_t + Pgh_t + PDns_t = D_t
#   conserv:  Vol_t - Vol_{t-1} + u_t Pgh_t <= u_t A_t   (Vol_0 = V0)
#   fcfe:     sl + 4166.67 Vol_3 >= 4166.67 V0
#   obj:      sum_t r_t (betaGt Pgt_t + betaDns PDns_t) + sl,
#             r_t = (1/1.1)^(duracion_t / T)
#
#   randomness: inflow A_2 in {10,50,90} per stage-2 branch and
#               A_3 in {40,50,60} per leaf branch (9 scenarios, bf=(3,3));
#               A_1 = 50 deterministic.
#
# Nonant slots (stage-major, matching MakeNodesforScen
# ref:examples/hydro/hydro.py:185-216): stage-1 [Pgt1,Pgh1,PDns1,Vol1],
# stage-2 [Pgt2,Pgh2,PDns2,Vol2]; N = 8, tree bf = branching_factors.
#
# Larger trees (scaling studies): branching factors beyond (3,3) draw
# inflows from a seeded uniform range per node, keeping the reference
# values for the first three branches.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.core.tree import ScenarioTree
from mpisppy_tpu.utils.sputils import extract_num  # noqa: F401 (re-export)

_D = np.array([90.0, 160.0, 110.0])
_U = np.array([0.6048, 0.6048, 1.2096])
_DURACION = np.array([168.0, 168.0, 336.0])
_T = 8760.0
_V0 = 60.48
_VMAX = 100.0
_PMAX = 100.0
_BETA_GT = 1.0
_BETA_GH = 0.0
_BETA_DNS = 10.0
_FCFE = 4166.67
_A1 = 50.0
_A2_BASE = np.array([10.0, 50.0, 90.0])   # ref:PySP/nodedata/Node2_*.dat
_A3_BASE = np.array([40.0, 50.0, 60.0])   # ref:PySP/nodedata/Node3_*_*.dat


def _inflow(base: np.ndarray, branch: int, seed_tag: int) -> float:
    if branch < len(base):
        return float(base[branch])
    rng = np.random.RandomState(1_000_003 * seed_tag + branch)
    return float(rng.uniform(base.min(), base.max()))


def scenario_creator(scenario_name: str,
                     branching_factors=(3, 3)) -> ScenarioSpec:
    """One-based Scen<k> names (ref:examples/hydro/hydro.py:216-244)."""
    bfs = tuple(int(b) for b in branching_factors)
    if len(bfs) != 2:
        raise ValueError("hydro is a 3-stage problem: two branching factors")
    snum = extract_num(scenario_name)          # one-based
    b1 = (snum - 1) // bfs[1]
    b2 = (snum - 1) % bfs[1]
    A = np.array([_A1, _inflow(_A2_BASE, b1, 2),
                  _inflow(_A3_BASE, b2, 3)])

    r = (1.0 / 1.1) ** (_DURACION / _T)

    # columns: Pgt[0:3], Pgh[3:6], PDns[6:9], Vol[9:12], sl[12]
    n = 13
    PGT, PGH, PDNS, VOL, SL = 0, 3, 6, 9, 12
    c = np.zeros(n)
    c[PGT:PGT + 3] = r * _BETA_GT
    c[PGH:PGH + 3] = r * _BETA_GH
    c[PDNS:PDNS + 3] = r * _BETA_DNS
    c[SL] = 1.0

    # rows: demand (3 eq), conservation (3 ineq), fcfe (1 ineq)
    m = 7
    Am = np.zeros((m, n))
    bl = np.full(m, -np.inf)
    bu = np.full(m, np.inf)
    for t in range(3):
        Am[t, PGT + t] = 1.0
        Am[t, PGH + t] = 1.0
        Am[t, PDNS + t] = 1.0
        bl[t] = bu[t] = _D[t]
    for t in range(3):
        row = 3 + t
        Am[row, VOL + t] = 1.0
        if t > 0:
            Am[row, VOL + t - 1] = -1.0
        Am[row, PGH + t] = _U[t]
        bu[row] = _U[t] * A[t] + (_V0 if t == 0 else 0.0)
    Am[6, SL] = 1.0
    Am[6, VOL + 2] = _FCFE
    bl[6] = _FCFE * _V0

    l = np.zeros(n)  # noqa: E741
    u = np.concatenate([
        np.full(3, _PMAX),        # Pgt
        np.full(3, _PMAX),        # Pgh
        _D,                       # PDns
        np.full(3, _VMAX),        # Vol
        [np.inf],                 # sl
    ])

    # stage-major nonant slots: stage-1 then stage-2 variables
    nonant_idx = np.array([PGT, PGH, PDNS, VOL,
                           PGT + 1, PGH + 1, PDNS + 1, VOL + 1], np.int32)

    return ScenarioSpec(
        name=scenario_name, c=c, A=Am, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=nonant_idx,
        probability=1.0 / (bfs[0] * bfs[1]),
    )


def make_tree(branching_factors=(3, 3)) -> ScenarioTree:
    return ScenarioTree(branching_factors=tuple(branching_factors),
                        nonants_per_stage=(4, 4))


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 1 if start is None else start
    return [f"Scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.add_to_config("branching_factors",
                      description="two branching factors, e.g. 3 3",
                      domain=list, default=[3, 3])


def kw_creator(cfg):
    return {"branching_factors":
            tuple(cfg.get("branching_factors", (3, 3)))}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
