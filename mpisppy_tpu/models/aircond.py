###############################################################################
# aircond: the multistage air-conditioner production planning problem,
# generated natively as BoxQP scenario specs (no Pyomo).  Matches the
# reference model's semantics
# (ref:mpisppy/tests/examples/aircond.py:26-254):
#
#   per stage t=1..T:
#     Reg_t in [0, Capacity]   regular production  (cost 1.0)
#     OT_t  in [0, bigM]       overtime production (cost 3.0)
#     posI_t, negI_t >= 0      inventory split (Inventory = posI - negI)
#   balance:  (posI_{t-1} - negI_{t-1}) + Reg_t + OT_t
#                 - posI_t + negI_t = d_t        (I_0 = BeginInventory)
#   objective: sum_t RegCost*Reg + OTCost*OT + InvCost_t*posI
#                 + NegInvCost*negI,
#     with InvCost_t = 0.5 for t<T and LastInventoryCost = -0.8
#     (salvage) at t=T (ref:aircond.py:95-160 InvenCostExpr).
#
#   randomness (ref:aircond.py:44-75 _demands_creator): demand follows a
#   clipped random walk over the scenario tree — d_1 = starting_d, and
#   each stage-t tree node draws d_t = clip(d_{t-1} + N(mu_dev,
#   sigma_dev), min_d, max_d) from a stream seeded with start_seed +
#   node_idx(path), so all scenarios through a node share its demand
#   (the reference's node-keyed seeding, ref:sputils.py:508-536).
#
# Nonants per non-leaf stage (ref:aircond.py:256-268 MakeNodesforScen):
# [Reg_t, OT_t] — 2 slots per stage, stage-major.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.core.tree import ScenarioTree
from mpisppy_tpu.utils.sputils import extract_num

# defaults (ref:mpisppy/tests/examples/aircond.py:26-42 parms)
DEFAULTS = dict(
    mu_dev=0.0, sigma_dev=40.0, start_seed=1134,
    min_d=0.0, max_d=400.0, starting_d=200.0,
    BeginInventory=200.0, InventoryCost=0.5, LastInventoryCost=-0.8,
    Capacity=200.0, RegularProdCost=1.0, OvertimeProdCost=3.0,
    NegInventoryCost=5.0,
)
_MAX_T = 25
_BIGM_FACTOR = _MAX_T


def _node_idx(path: list[int], bfs: tuple[int, ...]) -> int:
    """Unique node id along a path (ref:sputils.py:508-536 node_idx)."""
    if not path:
        return 0
    stage_id = 0
    before = 1
    acc = 1
    for t in range(len(path) - 1):
        acc *= bfs[t]
        before += acc
    for t, b in enumerate(path):
        stage_id = path[t] + bfs[t] * stage_id
    return before + stage_id


def demands_for_scenario(scennum: int, bfs: tuple[int, ...],
                         **kw) -> np.ndarray:
    """Stage demands along scenario scennum's tree path
    (ref:aircond.py:44-75)."""
    p = {**DEFAULTS, **kw}
    prod = int(np.prod(bfs))
    s = scennum % prod
    path = []
    rem = prod
    for b in bfs:
        rem //= b
        path.append(s // rem)
        s %= rem
    d = p["starting_d"]
    demands = [d]
    for t in range(1, len(bfs) + 1):
        seed = p["start_seed"] + _node_idx(path[:t], bfs)
        rng = np.random.RandomState(seed)
        d = min(p["max_d"], max(p["min_d"],
                                d + rng.normal(p["mu_dev"],
                                               p["sigma_dev"])))
        demands.append(d)
    return np.array(demands)


def scenario_creator(scenario_name: str,
                     branching_factors=(3, 3, 2), **kw) -> ScenarioSpec:
    """Zero-based Scenario<k> names.  T = len(bfs) + 1 stages."""
    p = {**DEFAULTS, **kw}
    bfs = tuple(int(b) for b in branching_factors)
    T = len(bfs) + 1
    if T > _MAX_T:
        raise ValueError(f"at most {_MAX_T} stages (ref:aircond.py:103)")
    scennum = extract_num(scenario_name)
    d = demands_for_scenario(scennum, bfs, **kw)
    bigM = p["Capacity"] * _BIGM_FACTOR

    # columns: Reg[0:T], OT[T:2T], posI[2T:3T], negI[3T:4T]
    n = 4 * T
    REG, OT, PI, NI = 0, T, 2 * T, 3 * T
    c = np.zeros(n)
    c[REG:REG + T] = p["RegularProdCost"]
    c[OT:OT + T] = p["OvertimeProdCost"]
    c[PI:PI + T] = p["InventoryCost"]
    c[PI + T - 1] = p["LastInventoryCost"]
    c[NI:NI + T] = p["NegInventoryCost"]

    # balance rows
    A = np.zeros((T, n))
    bl = np.empty(T)
    for t in range(T):
        A[t, REG + t] = 1.0
        A[t, OT + t] = 1.0
        A[t, PI + t] = -1.0
        A[t, NI + t] = 1.0
        if t > 0:
            A[t, PI + t - 1] = 1.0
            A[t, NI + t - 1] = -1.0
        bl[t] = d[t] - (p["BeginInventory"] if t == 0 else 0.0)
    bu = bl.copy()

    l = np.zeros(n)  # noqa: E741
    u = np.full(n, bigM)
    u[REG:REG + T] = p["Capacity"]

    # nonants: [Reg_t, OT_t] per non-leaf stage, stage-major
    nonant_idx = np.array(
        [v for t in range(T - 1) for v in (REG + t, OT + t)], np.int32)

    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=nonant_idx,
        probability=1.0 / int(np.prod(bfs)),
    )


# --------------------------------------------------------------------------
# Seeded scenario synthesis (scengen branch; docs/scengen.md).
#
# aircond is the MULTISTAGE program of the family: demand follows a
# clipped random walk over the tree, with one draw per NON-ROOT tree
# node shared by every scenario through that node.  The scengen branch
# keeps exactly that node-keyed structure but folds the node id into
# the counter-based key — fold_in(base_key, node_idx(path)) — instead
# of seeding a RandomState per node, so nonanticipativity of the DATA
# is preserved by construction under any tiling or sharding.
# --------------------------------------------------------------------------
def scenario_program(num_scens: int, seed: int = 0, start: int = 0,
                     branching_factors=(3, 3, 2), **kw):
    """ScenarioProgram drawing the node demand walk through scengen
    keys.  num_scens must equal prod(branching_factors)."""
    import jax
    import jax.numpy as jnp
    from jax import random as jrandom

    from mpisppy_tpu.scengen.program import ScenarioProgram

    if int(start) != 0:
        # node keys derive from the WITHIN-TREE path (idx % prod), so a
        # start offset would silently replay the same tree — replicate
        # multistage samples by varying `seed` (one base key per tree,
        # the sample_tree convention), never by windowing indices
        raise ValueError("aircond program: replications vary `seed`, "
                         "not `start` (node-keyed draws)")
    kw.pop("start_seed", None)  # legacy RandomState knob; `seed` rules
    p = {**DEFAULTS, **kw}
    bfs = tuple(int(b) for b in branching_factors)
    prod = int(np.prod(bfs))
    if int(num_scens) != prod:
        raise ValueError(f"aircond program needs num_scens == "
                         f"prod(branching_factors) = {prod}")
    T = len(bfs) + 1
    bigM = p["Capacity"] * _BIGM_FACTOR

    n = 4 * T
    REG, OT, PI, NI = 0, T, 2 * T, 3 * T
    c = np.zeros(n)
    c[REG:REG + T] = p["RegularProdCost"]
    c[OT:OT + T] = p["OvertimeProdCost"]
    c[PI:PI + T] = p["InventoryCost"]
    c[PI + T - 1] = p["LastInventoryCost"]
    c[NI:NI + T] = p["NegInventoryCost"]
    A = np.zeros((T, n))
    for t in range(T):
        A[t, REG + t] = 1.0
        A[t, OT + t] = 1.0
        A[t, PI + t] = -1.0
        A[t, NI + t] = 1.0
        if t > 0:
            A[t, PI + t - 1] = 1.0
            A[t, NI + t - 1] = -1.0
    l = np.zeros(n)  # noqa: E741
    u = np.full(n, bigM)
    u[REG:REG + T] = p["Capacity"]
    bl0 = np.zeros(T)
    bl0[0] = p["starting_d"] - p["BeginInventory"]
    nonant_idx = np.array(
        [v for t in range(T - 1) for v in (REG + t, OT + t)], np.int32)

    # static node-id arithmetic of _node_idx, per path length
    before = []
    for L in range(1, T):
        b_, acc = 1, 1
        for t in range(L - 1):
            acc *= bfs[t]
            b_ += acc
        before.append(b_)
    mu, sigma = float(p["mu_dev"]), float(p["sigma_dev"])
    min_d, max_d = float(p["min_d"]), float(p["max_d"])
    start_d = float(p["starting_d"])
    begin_inv = float(p["BeginInventory"])

    def sampler(base_key, idx):
        # path digits of scenario idx (depth-first layout)
        s = idx % prod
        rem = prod
        digits = []
        for b in bfs:
            rem = rem // b
            digits.append(s // rem)
            s = s % rem
        d = jnp.asarray(start_d, jnp.float32)
        rows = [jnp.asarray(start_d - begin_inv, jnp.float32)]
        for t in range(1, T):
            sid = jnp.asarray(0, jnp.int32)
            for tt in range(t):
                sid = digits[tt] + bfs[tt] * sid
            node = before[t - 1] + sid
            z = jrandom.normal(jax.random.fold_in(base_key, node), (),
                               jnp.float32)
            d = jnp.clip(d + mu + sigma * z, min_d, max_d)
            rows.append(d)
        bl = jnp.stack(rows)
        return {"bl": bl, "bu": bl}

    return ScenarioProgram(
        name="aircond", num_scenarios=prod,
        base_seed=int(seed), start=int(start),
        template={"c": c, "A": A, "bl": bl0, "bu": bl0.copy(),
                  "l": l, "u": u},
        varying=("bl", "bu"), sampler=sampler,
        nonant_idx=nonant_idx,
        tree=make_tree(bfs),
    )


def make_tree(branching_factors=(3, 3, 2)) -> ScenarioTree:
    bfs = tuple(int(b) for b in branching_factors)
    return ScenarioTree(branching_factors=bfs,
                        nonants_per_stage=(2,) * len(bfs))


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.add_to_config("branching_factors",
                      "branching factors, e.g. 3 3 2", list, [3, 3, 2])
    for name, default in (("mu_dev", 0.0), ("sigma_dev", 40.0),
                          ("start_seed", 1134)):
        cfg.add_to_config(name, f"aircond {name}", type(default), default)


def kw_creator(cfg):
    kw = {"branching_factors":
          tuple(cfg.get("branching_factors", (3, 3, 2)))}
    for name in ("mu_dev", "sigma_dev", "start_seed"):
        if cfg.get(name) is not None:
            kw[name] = cfg[name]
    return kw


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
