###############################################################################
# ccopf: multistage (chance-constrained-style) optimal power flow on a
# scenario tree — the acopf3 family (ref:examples/acopf3/
# ccopf_multistage.py + ACtree.py + fourstage.py), in TWO fidelities:
#
# DC mode (default) — the LINEARIZED B-theta power-flow model, the
# compiler-friendly stand-in for the reference's egret AC formulation:
#   stage t in {1,2,3}: dispatch g_{t,i}, angles theta_{t,b}, shed
#   slack u_{t,b} >= 0
#   rows: bus balance  sum_{i at b} g - sum_l B_l inc(l,b) dtheta = d_b(t)
#         line limits  |B_l (theta_from - theta_to)| <= cap_l
#   cost: c2 g^2 + c1 g (QUADRATIC — exercises the q path) + shed
#   nonants: g at stages 1 and 2 (stage-major, hydro's tree layout).
#
# SOC mode (soc=True) — the branch-flow second-order-cone relaxation of
# AC power flow (Baran-Wu DistFlow + the Farivar-Low SOCP relaxation)
# on a radial feeder, exercising the conic kernel contract
# (ops/cones.py) end to end.  Per stage, per line l (parent i -> child
# j): active/reactive flows P_l, Q_l, squared current i_l, squared
# voltages v_b, and the relaxed physics
#     v_j = v_i - 2(r P + x Q) + (r^2 + x^2) i_l        (voltage drop)
#     ||(2P_l, 2Q_l, i_l - v_i)||_2 <= i_l + v_i        (SOC block:
#         the convex relaxation of i_l v_i = P^2 + Q^2)
# with DistFlow bus balances (losses r i / x i charged to the parent
# side), shed slacks on BOTH balances, and a loss cost on i_l that
# drives the relaxation toward tightness.  Nonants stay g at stages
# 1 and 2, so the SOC workload drops into the same tree/cylinder
# plumbing as the DC one.
#
# Demand at stages 2/3 scales by seeded per-branch multipliers
# (ref:ACtree.py's per-node random demand scaling) in both modes.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.core.tree import ScenarioTree
from mpisppy_tpu.utils.sputils import extract_num

_SHED = 500.0


def grid_instance(n_buses: int = 4, seed: int = 0) -> dict:
    """Small ring grid: one generator per bus except the last, lines
    ring-connected, quadratic gen costs."""
    rng = np.random.RandomState(seed)
    lines = [(b, (b + 1) % n_buses) for b in range(n_buses)]
    gens = list(range(max(1, n_buses - 1)))
    return {
        "n_buses": n_buses,
        "lines": lines,
        "B": rng.uniform(5.0, 15.0, size=len(lines)),
        "cap": rng.uniform(0.6, 1.2, size=len(lines)),
        "gens": gens,                      # bus index of each generator
        "gmax": rng.uniform(0.8, 1.6, size=len(gens)),
        "c1": rng.uniform(10.0, 30.0, size=len(gens)),
        "c2": rng.uniform(2.0, 6.0, size=len(gens)),
        "demand": rng.uniform(0.3, 0.7, size=n_buses),
    }


def feeder_instance(n_buses: int = 4, seed: int = 0) -> dict:
    """Radial feeder for the SOC (branch-flow) mode: a path of buses
    with line l feeding bus l+1 from bus l, per-line impedances r + jx,
    generators on the same buses as grid_instance."""
    rng = np.random.RandomState(seed)
    nl = n_buses - 1
    gens = list(range(max(1, n_buses - 1)))
    return {
        "n_buses": n_buses,
        "r": rng.uniform(0.01, 0.05, size=nl),
        "x": rng.uniform(0.02, 0.08, size=nl),
        "cap": rng.uniform(0.6, 1.2, size=nl),
        "gens": gens,
        "gmax": rng.uniform(0.8, 1.6, size=len(gens)),
        "c1": rng.uniform(10.0, 30.0, size=len(gens)),
        "c2": rng.uniform(2.0, 6.0, size=len(gens)),
        "demand": rng.uniform(0.15, 0.35, size=n_buses),
        "qfrac": 0.35,      # reactive demand fraction
        "loss_cost": 1.0,   # linear cost on i_l: drives the SOC tight
    }


def branch_multiplier(stage: int, branch: int, seed: int = 0) -> float:
    rng = np.random.RandomState(40_000 + 97 * stage + branch + seed)
    return float(rng.uniform(0.8, 1.25))


def _stage_multipliers(scenario_name: str, bfs, seed: int):
    if len(bfs) != 2:
        raise ValueError("ccopf is a 3-stage problem: two branching "
                         "factors (ref:examples/acopf3/fourstage.py is "
                         "the 4-stage variant of the same tree recipe)")
    snum = extract_num(scenario_name)
    b2, b3 = snum // bfs[1], snum % bfs[1]
    return {1: 1.0,
            2: branch_multiplier(2, b2, seed),
            3: branch_multiplier(3, b2 * bfs[1] + b3, seed)}


def _soc_scenario(scenario_name: str, inst: dict, mult: dict
                  ) -> ScenarioSpec:
    """Branch-flow SOC relaxation scenario (see the module header).
    Per-stage columns: [g, gq, P, Q, v, iL, up, uq]."""
    nb = inst["n_buses"]
    nl = nb - 1
    gens = inst["gens"]
    ng = len(gens)
    per = 2 * ng + 3 * nl + 3 * nb
    n = 3 * per

    def col(t, base, i):
        return (t - 1) * per + base + i

    off_g, off_gq = 0, ng
    off_P, off_Q = 2 * ng, 2 * ng + nl
    off_v = 2 * ng + 2 * nl
    off_i = off_v + nb
    off_up = off_i + nl
    off_uq = off_up + nb

    c = np.zeros(n)
    q = np.zeros(n)
    l = np.full(n, -np.inf)  # noqa: E741
    u = np.full(n, np.inf)
    for t in (1, 2, 3):
        for i in range(ng):
            c[col(t, off_g, i)] = inst["c1"][i]
            q[col(t, off_g, i)] = 2.0 * inst["c2"][i]
            l[col(t, off_g, i)] = 0.0
            u[col(t, off_g, i)] = inst["gmax"][i]
            l[col(t, off_gq, i)] = -inst["gmax"][i]
            u[col(t, off_gq, i)] = inst["gmax"][i]
        for li in range(nl):
            cap = inst["cap"][li]
            for off in (off_P, off_Q):
                l[col(t, off, li)] = -cap
                u[col(t, off, li)] = cap
            c[col(t, off_i, li)] = inst["loss_cost"]
            l[col(t, off_i, li)] = 0.0
            u[col(t, off_i, li)] = 8.0 * cap * cap
        l[col(t, off_v, 0)] = 1.0   # substation voltage (squared)
        u[col(t, off_v, 0)] = 1.0
        for b in range(1, nb):
            l[col(t, off_v, b)] = 0.81
            u[col(t, off_v, b)] = 1.21
        for b in range(nb):
            for off in (off_up, off_uq):
                c[col(t, off, b)] = _SHED
                l[col(t, off, b)] = 0.0
                u[col(t, off, b)] = 10.0

    rows, bl, bu, soc_blocks = [], [], [], []
    for t in (1, 2, 3):
        d = inst["demand"] * mult[t]
        dq = inst["qfrac"] * d
        # DistFlow balances: inflow (parent line minus its loss) + gen
        # + shed - outflow (child line) = demand; bus b's parent line is
        # b-1, its child line is b (path feeder)
        for kind, off_f, off_u_, loss, dem in (
                ("P", off_P, off_up, inst["r"], d),
                ("Q", off_Q, off_uq, inst["x"], dq)):
            for b in range(nb):
                r = np.zeros(n)
                for i, gb in enumerate(gens):
                    if gb == b:
                        r[col(t, off_g if kind == "P" else off_gq, i)] = 1.0
                if b > 0:
                    r[col(t, off_f, b - 1)] = 1.0
                    r[col(t, off_i, b - 1)] = -loss[b - 1]
                if b < nb - 1:
                    r[col(t, off_f, b)] = -1.0
                r[col(t, off_u_, b)] = 1.0
                rows.append(r)
                bl.append(float(dem[b]))
                bu.append(float(dem[b]))
        for li in range(nl):   # voltage drop (equality)
            rl, xl = inst["r"][li], inst["x"][li]
            r = np.zeros(n)
            r[col(t, off_v, li + 1)] = 1.0
            r[col(t, off_v, li)] = -1.0
            r[col(t, off_P, li)] = 2.0 * rl
            r[col(t, off_Q, li)] = 2.0 * xl
            r[col(t, off_i, li)] = -(rl * rl + xl * xl)
            rows.append(r)
            bl.append(0.0)
            bu.append(0.0)
        for li in range(nl):   # SOC block: ||(2P,2Q,i-v)|| <= i+v
            head = np.zeros(n)
            head[col(t, off_i, li)] = 1.0
            head[col(t, off_v, li)] = 1.0
            t1 = np.zeros(n)
            t1[col(t, off_P, li)] = 2.0
            t2 = np.zeros(n)
            t2[col(t, off_Q, li)] = 2.0
            t3 = np.zeros(n)
            t3[col(t, off_i, li)] = 1.0
            t3[col(t, off_v, li)] = -1.0
            r0 = len(rows)
            rows.extend([head, t1, t2, t3])
            bl.extend([0.0] * 4)
            bu.extend([0.0] * 4)
            soc_blocks.append(np.arange(r0, r0 + 4, dtype=np.int32))

    nonant_idx = np.concatenate([
        [col(1, off_g, i) for i in range(ng)],
        [col(2, off_g, i) for i in range(ng)]]).astype(np.int32)
    return ScenarioSpec(
        name=scenario_name, c=c, q=q, A=np.asarray(rows),
        bl=np.asarray(bl), bu=np.asarray(bu), l=l, u=u,
        nonant_idx=nonant_idx, soc_blocks=soc_blocks,
    )


def mpc_drift(demand: np.ndarray, step: int) -> np.ndarray:
    """Deterministic rolling-dispatch load drift for window `step`: a
    diurnal swing (period 24 decision epochs, ±20%) applied to the base
    demand — the ccopf analogue of uc's rolled profile (mpc/horizon.py).
    Pure in {demand, step}, so a resumed stream re-derives window k's
    data exactly."""
    return np.asarray(demand) * (
        1.0 + 0.2 * np.sin(2.0 * np.pi * step / 24.0))


def scenario_creator(scenario_name: str, instance: dict | None = None,
                     branching_factors=(3, 3), seed: int = 0,
                     soc: bool = False, mpc_step: int = -1,
                     **_ignored) -> ScenarioSpec:
    if mpc_step >= 0:
        # rolling window `mpc_step` (mpc/horizon.py): re-key the branch
        # multipliers per step (fresh uncertainty each epoch, still a
        # pure function of {seed, step}) and drift the load
        seed = seed + 7919 * int(mpc_step)
        inst = dict(instance) if instance is not None else \
            (feeder_instance() if soc else grid_instance())
        inst["demand"] = mpc_drift(inst["demand"], int(mpc_step))
        instance = inst
    bfs = tuple(int(b) for b in branching_factors)
    mult = _stage_multipliers(scenario_name, bfs, seed)
    if soc:
        return _soc_scenario(scenario_name,
                             instance or feeder_instance(), mult)
    inst = instance or grid_instance()

    nb = inst["n_buses"]
    lines = inst["lines"]
    gens = inst["gens"]
    nl, ng = len(lines), len(gens)
    # per-stage columns: [g (ng), theta (nb), shed (nb)]
    per = ng + nb + nb
    n = 3 * per

    def gcol(t, i):
        return (t - 1) * per + i

    def thcol(t, b):
        return (t - 1) * per + ng + b

    def ucol(t, b):
        return (t - 1) * per + ng + nb + b

    c = np.zeros(n)
    q = np.zeros(n)
    l = np.full(n, -np.inf)  # noqa: E741
    u = np.full(n, np.inf)
    for t in (1, 2, 3):
        for i in range(ng):
            c[gcol(t, i)] = inst["c1"][i]
            q[gcol(t, i)] = 2.0 * inst["c2"][i]  # q is the 1/2 x'Qx diag
            l[gcol(t, i)] = 0.0
            u[gcol(t, i)] = inst["gmax"][i]
        l[thcol(t, 0)] = 0.0     # reference bus
        u[thcol(t, 0)] = 0.0
        for b in range(1, nb):
            l[thcol(t, b)] = -np.pi
            u[thcol(t, b)] = np.pi
        for b in range(nb):
            c[ucol(t, b)] = _SHED
            l[ucol(t, b)] = 0.0
            u[ucol(t, b)] = 10.0

    rows, bl, bu = [], [], []
    for t in (1, 2, 3):
        d = inst["demand"] * mult[t]
        for b in range(nb):   # bus balance (equality)
            r = np.zeros(n)
            for i, gb in enumerate(gens):
                if gb == b:
                    r[gcol(t, i)] = 1.0
            for li, (f, to) in enumerate(lines):
                if f == b:
                    r[thcol(t, f)] -= inst["B"][li]
                    r[thcol(t, to)] += inst["B"][li]
                if to == b:
                    r[thcol(t, to)] -= inst["B"][li]
                    r[thcol(t, f)] += inst["B"][li]
            r[ucol(t, b)] = 1.0
            rows.append(r)
            bl.append(float(d[b]))
            bu.append(float(d[b]))
        for li, (f, to) in enumerate(lines):   # line limits
            r = np.zeros(n)
            r[thcol(t, f)] = inst["B"][li]
            r[thcol(t, to)] = -inst["B"][li]
            rows.append(r)
            bl.append(-float(inst["cap"][li]))
            bu.append(float(inst["cap"][li]))

    nonant_idx = np.concatenate([
        [gcol(1, i) for i in range(ng)],
        [gcol(2, i) for i in range(ng)]]).astype(np.int32)
    return ScenarioSpec(
        name=scenario_name, c=c, q=q, A=np.asarray(rows),
        bl=np.asarray(bl), bu=np.asarray(bu), l=l, u=u,
        nonant_idx=nonant_idx,
    )


def make_tree(branching_factors=(3, 3),
              instance: dict | None = None) -> ScenarioTree:
    # DC and SOC instances share the generator layout (feeder_instance
    # mirrors grid_instance's gens), so the tree — nonants are g at
    # stages 1 and 2 — is identical in both modes
    bfs = tuple(branching_factors)
    ng = len((instance or grid_instance())["gens"])
    return ScenarioTree(branching_factors=bfs,
                        nonants_per_stage=(ng, ng))


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("branching_factors",
                      description="two branching factors, e.g. 3 3",
                      domain=list, default=[3, 3])
    cfg.add_to_config("soc",
                      description="solve the branch-flow second-order-"
                      "cone (conic AC relaxation) workload instead of "
                      "the DC approximation",
                      domain=bool, default=False)
    cfg.add_to_config("ccopf_mpc_step",
                      description="rolling-horizon window index (mpc/):"
                      " >= 0 re-keys multipliers and drifts the load "
                      "per step; -1 = not a rolling window",
                      domain=int, default=-1)


def kw_creator(cfg):
    return {"branching_factors":
            tuple(cfg.get("branching_factors", (3, 3))),
            "soc": bool(cfg.get("soc", False)),
            "mpc_step": int(cfg.get("ccopf_mpc_step", -1))}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
