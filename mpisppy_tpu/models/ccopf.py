###############################################################################
# ccopf: multistage (chance-constrained-style) optimal power flow on a
# scenario tree — the acopf3 family (ref:examples/acopf3/
# ccopf_multistage.py + ACtree.py + fourstage.py), re-based on the
# LINEARIZED DC power-flow model (B-theta), the standard compiler-
# friendly stand-in for the reference's egret AC formulation: the AC
# physics live in an external nonlinear solver there, which has no
# TPU-native analog; the decision structure (multistage generation
# nonants over a tree of demand outcomes, line limits, shed penalties)
# is preserved.
#
# Per scenario (a leaf path of the (bf1, bf2) 3-stage tree):
#   stage t in {1,2,3}: dispatch g_{t,i}, angles theta_{t,b}, shed
#   slack u_{t,b} >= 0
#   rows: bus balance  sum_{i at b} g - sum_l B_l inc(l,b) dtheta = d_b(t)
#         line limits  |B_l (theta_from - theta_to)| <= cap_l
#   cost: c2 g^2 + c1 g (QUADRATIC — exercises the q path) + shed
#   nonants: g at stages 1 and 2 (stage-major, hydro's tree layout).
# Demand at stages 2/3 scales by seeded per-branch multipliers
# (ref:ACtree.py's per-node random demand scaling).
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.core.tree import ScenarioTree
from mpisppy_tpu.utils.sputils import extract_num

_SHED = 500.0


def grid_instance(n_buses: int = 4, seed: int = 0) -> dict:
    """Small ring grid: one generator per bus except the last, lines
    ring-connected, quadratic gen costs."""
    rng = np.random.RandomState(seed)
    lines = [(b, (b + 1) % n_buses) for b in range(n_buses)]
    gens = list(range(max(1, n_buses - 1)))
    return {
        "n_buses": n_buses,
        "lines": lines,
        "B": rng.uniform(5.0, 15.0, size=len(lines)),
        "cap": rng.uniform(0.6, 1.2, size=len(lines)),
        "gens": gens,                      # bus index of each generator
        "gmax": rng.uniform(0.8, 1.6, size=len(gens)),
        "c1": rng.uniform(10.0, 30.0, size=len(gens)),
        "c2": rng.uniform(2.0, 6.0, size=len(gens)),
        "demand": rng.uniform(0.3, 0.7, size=n_buses),
    }


def branch_multiplier(stage: int, branch: int, seed: int = 0) -> float:
    rng = np.random.RandomState(40_000 + 97 * stage + branch + seed)
    return float(rng.uniform(0.8, 1.25))


def scenario_creator(scenario_name: str, instance: dict | None = None,
                     branching_factors=(3, 3), seed: int = 0,
                     **_ignored) -> ScenarioSpec:
    inst = instance or grid_instance()
    bfs = tuple(int(b) for b in branching_factors)
    if len(bfs) != 2:
        raise ValueError("ccopf is a 3-stage problem: two branching "
                         "factors (ref:examples/acopf3/fourstage.py is "
                         "the 4-stage variant of the same tree recipe)")
    snum = extract_num(scenario_name)
    b2, b3 = snum // bfs[1], snum % bfs[1]
    mult = {1: 1.0,
            2: branch_multiplier(2, b2, seed),
            3: branch_multiplier(3, b2 * bfs[1] + b3, seed)}

    nb = inst["n_buses"]
    lines = inst["lines"]
    gens = inst["gens"]
    nl, ng = len(lines), len(gens)
    # per-stage columns: [g (ng), theta (nb), shed (nb)]
    per = ng + nb + nb
    n = 3 * per

    def gcol(t, i):
        return (t - 1) * per + i

    def thcol(t, b):
        return (t - 1) * per + ng + b

    def ucol(t, b):
        return (t - 1) * per + ng + nb + b

    c = np.zeros(n)
    q = np.zeros(n)
    l = np.full(n, -np.inf)  # noqa: E741
    u = np.full(n, np.inf)
    for t in (1, 2, 3):
        for i in range(ng):
            c[gcol(t, i)] = inst["c1"][i]
            q[gcol(t, i)] = 2.0 * inst["c2"][i]  # q is the 1/2 x'Qx diag
            l[gcol(t, i)] = 0.0
            u[gcol(t, i)] = inst["gmax"][i]
        l[thcol(t, 0)] = 0.0     # reference bus
        u[thcol(t, 0)] = 0.0
        for b in range(1, nb):
            l[thcol(t, b)] = -np.pi
            u[thcol(t, b)] = np.pi
        for b in range(nb):
            c[ucol(t, b)] = _SHED
            l[ucol(t, b)] = 0.0
            u[ucol(t, b)] = 10.0

    rows, bl, bu = [], [], []
    for t in (1, 2, 3):
        d = inst["demand"] * mult[t]
        for b in range(nb):   # bus balance (equality)
            r = np.zeros(n)
            for i, gb in enumerate(gens):
                if gb == b:
                    r[gcol(t, i)] = 1.0
            for li, (f, to) in enumerate(lines):
                if f == b:
                    r[thcol(t, f)] -= inst["B"][li]
                    r[thcol(t, to)] += inst["B"][li]
                if to == b:
                    r[thcol(t, to)] -= inst["B"][li]
                    r[thcol(t, f)] += inst["B"][li]
            r[ucol(t, b)] = 1.0
            rows.append(r)
            bl.append(float(d[b]))
            bu.append(float(d[b]))
        for li, (f, to) in enumerate(lines):   # line limits
            r = np.zeros(n)
            r[thcol(t, f)] = inst["B"][li]
            r[thcol(t, to)] = -inst["B"][li]
            rows.append(r)
            bl.append(-float(inst["cap"][li]))
            bu.append(float(inst["cap"][li]))

    nonant_idx = np.concatenate([
        [gcol(1, i) for i in range(ng)],
        [gcol(2, i) for i in range(ng)]]).astype(np.int32)
    return ScenarioSpec(
        name=scenario_name, c=c, q=q, A=np.asarray(rows),
        bl=np.asarray(bl), bu=np.asarray(bu), l=l, u=u,
        nonant_idx=nonant_idx,
    )


def make_tree(branching_factors=(3, 3),
              instance: dict | None = None) -> ScenarioTree:
    bfs = tuple(branching_factors)
    ng = len((instance or grid_instance())["gens"])
    return ScenarioTree(branching_factors=bfs,
                        nonants_per_stage=(ng, ng))


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(cfg):
    return {}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
