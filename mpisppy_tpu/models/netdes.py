###############################################################################
# netdes: stochastic fixed-charge network design, generated natively as
# sparse BoxQP scenario specs (no Pyomo).  Matches the reference model
# semantics (ref:examples/netdes/netdes.py:24-80):
#
#   first stage:   x_e in {0,1}  build arc e           (cost c_e)
#   second stage:  y_e >= 0      flow on arc e         (cost d_e)
#   vub:           y_e - u_e x_e <= 0                  per arc
#   balance:       sum_out y - sum_in y = b_i          per node
#   randomness:    (d, u, b) per scenario.
#
# Instances come from the reference's NETGEN-style .dat files
# (ref:examples/netdes/data/network-*.dat, parsed here natively) or from
# a seeded synthetic generator with the same structure.  Constraint
# matrices are scipy-sparse; the batch compiler lowers them to a
# shared-pattern batched ELL block (vub rows carry scenario-dependent
# u_e), so HBM holds O(S * nnz) instead of O(S * m * n).
###############################################################################
from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num


def parse_dat(path: str) -> dict:
    """Parse a reference-format netdes .dat instance
    (ref:examples/netdes/netdes.py uses the `parse` helper; the format is
    header comments, then n, density, fixed/variable ratio, adjacency,
    first-stage cost matrix, K, probabilities, then (d, u, b) per
    scenario)."""
    import re
    numline = re.compile(r"^[\s0-9eE+\-.,;]+$")
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and numline.match(line) and any(ch.isdigit()
                                                    for ch in line):
                rows.append(line)

    def mat(s):
        return np.array([[float(v) for v in r.split(",")]
                         for r in s.split(";")])

    n = int(float(rows[0]))
    adj = mat(rows[3])
    c = mat(rows[4])
    K = int(float(rows[5]))
    p = np.array([float(v) for v in rows[6].split(",")])
    scens = []
    for k in range(K):
        d = mat(rows[7 + 3 * k])
        u = mat(rows[8 + 3 * k])
        b = np.array([float(v) for v in rows[9 + 3 * k].split(",")])
        scens.append({"d": d, "u": u, "b": b})
    assert adj.shape == (n, n) and len(p) == K
    return {"n": n, "adj": adj, "c": c, "p": p, "scens": scens}


def synthetic_instance(n_nodes: int = 10, num_scens: int = 10,
                       density: float = 0.6, seed: int = 0) -> dict:
    """Seeded instance with the reference .dat structure: one source
    (node 0), one sink (node 1), random arc costs/capacities/demands."""
    rng = np.random.RandomState(seed)
    adj = (rng.rand(n_nodes, n_nodes) < density).astype(float)
    np.fill_diagonal(adj, 0.0)
    # guarantee connectivity source->sink through a random path
    perm = [0] + list(rng.permutation(np.arange(2, n_nodes))) + [1]
    for a, b in zip(perm[:-1], perm[1:]):
        adj[a, b] = 1.0
    c = np.where(adj > 0, rng.uniform(6000, 16000, adj.shape), 0.0)
    p = rng.dirichlet(np.ones(num_scens))
    flow = rng.uniform(10, 16)
    scens = []
    for _ in range(num_scens):
        d = np.where(adj > 0, rng.uniform(15, 80, adj.shape), 0.0)
        u = np.where(adj > 0, rng.uniform(2 * flow / 3, 6 * flow,
                                          adj.shape), 0.0)
        b = np.zeros(n_nodes)
        # balance is out - in == b_i: node 0 (source, start of the
        # forced 0->...->1 path) supplies +flow, node 1 (sink) -flow
        b[0], b[1] = flow, -flow
        scens.append({"d": d, "u": u, "b": b})
    return {"n": n_nodes, "adj": adj, "c": c, "p": p, "scens": scens}


def _edges(adj: np.ndarray) -> list[tuple[int, int]]:
    return [(i, j) for i in range(adj.shape[0])
            for j in range(adj.shape[1]) if adj[i, j] > 0]


def scenario_creator(scenario_name: str, path: str | None = None,
                     instance: dict | None = None,
                     lp_relax: bool = False, **_ignored) -> ScenarioSpec:
    """Zero-based Scenario<k> names (ref:examples/netdes/netdes.py:87-96).

    Columns: x[0:E] (build, binary), y[E:2E] (flow).  Rows: E vub rows
    then n balance rows, as scipy-sparse (shared pattern across
    scenarios; values vary with u)."""
    if instance is None:
        if path is None:
            raise RuntimeError(
                "netdes needs `path` (a reference-format .dat) or a "
                "prebuilt `instance` (ref:netdes.py:25-28 semantics)")
        cache_key = "_netdes_cache"
        instance = scenario_creator.__dict__.setdefault(
            cache_key, {})
        if path not in instance:
            scenario_creator.__dict__[cache_key][path] = parse_dat(path)
        instance = scenario_creator.__dict__[cache_key][path]
    k = extract_num(scenario_name)
    sc = instance["scens"][k]
    adj, cmat = instance["adj"], instance["c"]
    n_nodes = instance["n"]
    edges = _edges(adj)
    E = len(edges)
    n = 2 * E

    c = np.zeros(n)
    for e, (i, j) in enumerate(edges):
        c[e] = cmat[i, j]
        c[E + e] = sc["d"][i, j]

    rows, cols, vals = [], [], []
    bl = np.full(E + n_nodes, -np.inf)
    bu = np.full(E + n_nodes, np.inf)
    # vub rows: y_e - u_e x_e <= 0
    for e, (i, j) in enumerate(edges):
        rows += [e, e]
        cols += [E + e, e]
        vals += [1.0, -sc["u"][i, j]]
        bu[e] = 0.0
    # balance rows: out - in == b_i
    for e, (i, j) in enumerate(edges):
        rows += [E + i, E + j]
        cols += [E + e, E + e]
        vals += [1.0, -1.0]
    for i in range(n_nodes):
        bl[E + i] = bu[E + i] = sc["b"][i]
    A = sps.csr_matrix((vals, (rows, cols)), shape=(E + n_nodes, n))

    l = np.zeros(n)  # noqa: E741
    u = np.concatenate([np.ones(E),
                        np.array([max(s["u"][i, j] for s in
                                      instance["scens"])
                                  for (i, j) in edges])])
    integer = np.zeros(n, bool)
    if not lp_relax:
        integer[:E] = True

    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(E, dtype=np.int32),
        probability=float(instance["p"][k]),
        integer=integer,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.add_to_config("instance_name",
                      "netdes instance name (e.g. network-10-20-L-01)",
                      str, None)
    cfg.add_to_config("netdes_data_path", "path to netdes .dat data",
                      str, None)


def kw_creator(cfg):
    path = None
    if cfg.get("netdes_data_path") and cfg.get("instance_name"):
        path = f"{cfg['netdes_data_path']}/{cfg['instance_name']}.dat"
    kw = {"lp_relax": True}
    if path is not None:
        kw["path"] = path
        kw["num_scens"] = len(parse_dat(path)["scens"])
    else:
        num = cfg.get("num_scens") or 10
        kw["instance"] = synthetic_instance(num_scens=int(num))
        kw["num_scens"] = int(num)
    return kw


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
