###############################################################################
# sizes: the two-period SIZES product-sizing MIP (Løkketangen & Woodruff
# 1996), generated natively as BoxQP scenario specs (no Pyomo).  Matches
# the reference model semantics
# (ref:examples/sizes/models/ReferenceModel.py:32-176,
# ref:examples/sizes/sizes.py:13-33):
#
#   per stage s in {1,2}, sizes i=1..P (P=10):
#     z_i^s in {0,1}  produce any size i            (setup cost 453)
#     y_i^s >= 0      units produced                (unit cost ~0.75+)
#     w_ij^s >= 0     units of size i cut down to j<=i   (cut cost 0.008)
#   demand:     sum_{j>=i} w_ji^s >= D_i^s
#   setup:      y_i^s - Cap z_i^s <= 0
#   capacity:   sum_i y_i^s <= Cap            (Cap = 200,000)
#   inventory:  sum_{j<=i} w_ij^1 <= y_i^1
#               sum_{j<=i} (w_ij^1 + w_ij^2) <= y_i^1 + y_i^2
#
#   randomness: second-stage demands D^2 = mult_k * D^1 with
#   mult in {0.7, 1.0, 1.3} for 3 scenarios (the SIZES3 data,
#   ref:examples/sizes/SIZES3/Scenario*.dat), linearly spaced
#   0.7..1.3 for other scenario counts.
#
# Nonants (matching ref:sizes.py:29-30 varlist): the FIRST-STAGE
# continuous vars [NumProduced, NumUnitsCut] — the binary setup vars are
# deliberately NOT nonanticipative in the reference.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num

_P = 10
_CAP = 200000.0
_D1 = np.array([2500., 7500., 12500., 10000., 35000., 25000., 15000.,
                12500., 12500., 5000.])
_UNIT_COST = 0.748 + 0.0104 * np.arange(_P)   # 0.748 .. 0.8416
_SETUP = np.full(_P, 453.0)
_CUT_COST = 0.008

# (i, j) pairs with i >= j (cut size i down to size j), i-major
_PAIRS = [(i, j) for i in range(_P) for j in range(i + 1)]
_W = len(_PAIRS)


def demand_multiplier(scennum_1based: int, num_scens: int) -> float:
    """SIZES3: {0.7, 1.0, 1.3}; general: linspace(0.7, 1.3)."""
    if num_scens == 1:
        return 1.0
    return 0.7 + 0.6 * (scennum_1based - 1) / (num_scens - 1)


def scenario_creator(scenario_name: str, scenario_count: int = 3,
                     lp_relax: bool = False, **_ignored) -> ScenarioSpec:
    """One-based Scenario<k> names (ref:examples/sizes/sizes.py:41-46)."""
    k = extract_num(scenario_name)
    D2 = demand_multiplier(k, scenario_count) * _D1

    # columns per stage: z[0:P], y[P:2P], w[2P:2P+W]; stage2 offset nvs
    nvs = 2 * _P + _W
    n = 2 * nvs
    Z1, Y1, W1 = 0, _P, 2 * _P
    Z2, Y2, W2 = nvs, nvs + _P, nvs + 2 * _P

    c = np.zeros(n)
    for s0, (Z, Y, W) in enumerate(((Z1, Y1, W1), (Z2, Y2, W2))):
        c[Z:Z + _P] = _SETUP
        c[Y:Y + _P] = _UNIT_COST
        for w_ix, (i, j) in enumerate(_PAIRS):
            if i != j:
                c[W + w_ix] = _CUT_COST

    # rows: demand (2P), setup vub (2P), capacity (2), inventory (2P)
    m = 6 * _P + 2
    A = np.zeros((m, n))
    bl = np.full(m, -np.inf)
    bu = np.full(m, np.inf)
    r = 0
    # demand: sum_{j >= i} w_ji >= D_i   (w_ji = pair (j, i) with j >= i)
    for s0, (W, D) in enumerate(((W1, _D1), (W2, D2))):
        for i in range(_P):
            for w_ix, (jj, ii) in enumerate(_PAIRS):
                if ii == i and jj >= i:
                    A[r, W + w_ix] = 1.0
            bl[r] = D[i]
            r += 1
    # setup vub: y_i - Cap z_i <= 0
    for Z, Y in ((Z1, Y1), (Z2, Y2)):
        for i in range(_P):
            A[r, Y + i] = 1.0
            A[r, Z + i] = -_CAP
            bu[r] = 0.0
            r += 1
    # capacity: sum_i y_i <= Cap
    for Y in (Y1, Y2):
        A[r, Y:Y + _P] = 1.0
        bu[r] = _CAP
        r += 1
    # inventory stage 1: sum_{j <= i} w_ij^1 - y_i^1 <= 0
    for i in range(_P):
        for w_ix, (ii, jj) in enumerate(_PAIRS):
            if ii == i:
                A[r, W1 + w_ix] = 1.0
        A[r, Y1 + i] = -1.0
        bu[r] = 0.0
        r += 1
    # inventory cumulative: sum_{j<=i}(w^1+w^2) - y^1 - y^2 <= 0
    for i in range(_P):
        for w_ix, (ii, jj) in enumerate(_PAIRS):
            if ii == i:
                A[r, W1 + w_ix] = 1.0
                A[r, W2 + w_ix] = 1.0
        A[r, Y1 + i] = -1.0
        A[r, Y2 + i] = -1.0
        bu[r] = 0.0
        r += 1
    assert r == m

    l = np.zeros(n)  # noqa: E741
    u = np.full(n, _CAP)
    u[Z1:Z1 + _P] = 1.0
    u[Z2:Z2 + _P] = 1.0

    integer = np.zeros(n, bool)
    if not lp_relax:
        integer[Z1:Z1 + _P] = True
        integer[Z2:Z2 + _P] = True
        # NumProduced/NumUnitsCut are integers in the reference but
        # "implicitly integer ... with the PH cost objective this isn't
        # the case" (ref:ReferenceModel.py:83-85); we track only the
        # binaries, matching practical relaxations.

    # nonants = first-stage [y, w] (ref:sizes.py:29-30 varlist)
    nonant_idx = np.concatenate([np.arange(Y1, Y1 + _P),
                                 np.arange(W1, W1 + _W)]).astype(np.int32)

    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=nonant_idx,
        probability=1.0 / scenario_count,
        integer=integer,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"Scenario{i + 1}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(cfg):
    return {"scenario_count": int(cfg["num_scens"]), "lp_relax": True}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
