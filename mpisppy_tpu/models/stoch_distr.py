###############################################################################
# stoch_distr: STOCHASTIC inter-region distribution — scenario x region
# consensus ADMM through utils.stoch_admmWrapper
# (ref:examples/stoch_distr/stoch_distr.py + stoch_distr_admm_cylinders.py).
#
# The deterministic distr network (models/distr.py) gains:
#   * stochastic demand: each stochastic scenario scales every region's
#     demand by a seeded multiplier (the reference's stochastic
#     scenario axis, ref:stoch_distr.py scenario_creator);
#   * a GLOBAL first-stage decision z >= 0 — emergency production
#     capacity available to every region's factory — nonanticipative
#     across stochastic scenarios and shared by all regions (the
#     stage-1 slot block of utils.stoch_admmWrapper).
#
# Each (stoch scenario, region) pair model (min):
#     (cz/R) z + prod_cost g + intra costs + arc costs/2 + penalty unmet
#   s.t.  F:   g - f_FDC = 0
#         DC:  f_FDC + sum_in f - f_DCB - sum_out f = 0
#         B:   f_DCB + unmet = demand_r * mult_s
#         cap: g - z <= prod_cap_r
# (z's cost is split across the R regions because the stoch_admmWrapper
# expectation counts each pair's objective once per region.)
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.models import distr

_PENALTY = 1000.0
_Z_COST = 4.0
_Z_MAX = 200.0


def demand_multiplier(stoch_name: str, seed: int = 0) -> float:
    """Seeded per-scenario demand scaling (ref:stoch_distr.py's
    stochastic demand draw)."""
    from mpisppy_tpu.utils.sputils import extract_num
    rng = np.random.RandomState(20_000 + extract_num(stoch_name) + seed)
    return float(rng.uniform(0.7, 1.3))


def scenario_creator(stoch_name: str, region_name: str,
                     data: dict | None = None,
                     num_regions: int | None = None, seed: int = 0,
                     **_ignored):
    """(ScenarioSpec, var_names) for one (stoch scenario, region) pair —
    the utils.stoch_admmWrapper contract.  nonant_idx marks the ORIGINAL
    first-stage column (z)."""
    if data is None:
        data = distr.region_data(num_regions or 3, seed)
    R = len(data["regions"])
    rd = data["regions"][region_name]
    inc, out = distr._region_arcs(region_name, data)
    mult = demand_multiplier(stoch_name, seed)
    demand = rd["demand"] * mult

    # columns: z, g, f_FDC, f_DCB, unmet, then one per touching arc
    var_names = ["z", "g", "f_FDC", "f_DCB", "unmet"] \
        + [distr.arc_label(k) for k in inc + out]
    n = len(var_names)
    c = np.zeros(n)
    c[0] = _Z_COST / R
    c[1] = rd["prod_cost"]
    c[2] = rd["intra_cost"]
    c[3] = rd["intra_cost"]
    c[4] = _PENALTY
    l = np.zeros(n)  # noqa: E741
    u = np.empty(n)
    u[0] = _Z_MAX
    u[1] = rd["prod_cap"] + _Z_MAX
    u[2] = rd["intra_cap"]
    u[3] = rd["intra_cap"]
    u[4] = demand
    for j, k in enumerate(inc + out):
        c[5 + j] = data["inter"][k]["cost"] / 2.0
        u[5 + j] = data["inter"][k]["cap"]

    # rows: F balance, DC balance, B balance, capacity link
    A = np.zeros((4, n))
    A[0, 1] = 1.0
    A[0, 2] = -1.0
    A[1, 2] = 1.0
    A[1, 3] = -1.0
    for j, k in enumerate(inc):
        A[1, 5 + j] = 1.0
    for j, k in enumerate(out):
        A[1, 5 + len(inc) + j] = -1.0
    A[2, 3] = 1.0
    A[2, 4] = 1.0
    A[3, 1] = 1.0
    A[3, 0] = -1.0
    bl = np.array([0.0, 0.0, demand, -np.inf])
    bu = np.array([0.0, 0.0, demand, rd["prod_cap"]])

    spec = ScenarioSpec(
        name=f"{stoch_name}_{region_name}", c=c, A=A, bl=bl, bu=bu,
        l=l, u=u,
        nonant_idx=np.arange(1, dtype=np.int32),  # z is column 0
    )
    return spec, var_names


def consensus_vars_creator(num_regions: int, data: dict | None = None,
                           seed: int = 0) -> dict:
    """Same inter-arc consensus labels as deterministic distr
    (ref:stoch_distr.py:212-261 builds them from the inter-region
    dict)."""
    return distr.consensus_vars_creator(num_regions, data, seed)


def stoch_scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"StochScen{i}" for i in range(start, start + num_scens)]


def admm_subproblem_names_creator(num_regions: int):
    return distr.scenario_names_creator(num_regions)


def global_lp_oracle(data: dict, stoch_names: list[str],
                     seed: int = 0) -> float:
    """Merged two-stage LP optimum via scipy: shared z, per-(s, arc)
    flows, per-(s, region) recourse — the analog of
    ref:examples/stoch_distr/globalmodel.py."""
    from scipy.optimize import linprog

    regions = list(data["regions"])
    inter = list(data["inter"])
    R, S = len(regions), len(stoch_names)
    p_s = 1.0 / S
    # columns: z | for each s: per region (g, f1, f2, unmet) | arcs
    per_s = 4 * R + len(inter)
    n = 1 + S * per_s
    c = np.zeros(n)
    lb = np.zeros(n)
    ub = np.empty(n)
    c[0] = _Z_COST
    ub[0] = _Z_MAX
    A_ub, b_ub, A_eq, b_eq = [], [], [], []
    for si, snm in enumerate(stoch_names):
        mult = demand_multiplier(snm, seed)
        base = 1 + si * per_s
        for i, r in enumerate(regions):
            rd = data["regions"][r]
            j0 = base + 4 * i
            c[j0:j0 + 4] = p_s * np.array(
                [rd["prod_cost"], rd["intra_cost"], rd["intra_cost"],
                 _PENALTY])
            ub[j0:j0 + 4] = [rd["prod_cap"] + _Z_MAX, rd["intra_cap"],
                             rd["intra_cap"], rd["demand"] * mult]
            # capacity link g - z <= prod_cap
            row = np.zeros(n)
            row[j0] = 1.0
            row[0] = -1.0
            A_ub.append(row)
            b_ub.append(rd["prod_cap"])
            # F balance
            row = np.zeros(n)
            row[j0] = 1.0
            row[j0 + 1] = -1.0
            A_eq.append(row)
            b_eq.append(0.0)
            # DC balance
            row = np.zeros(n)
            row[j0 + 1] = 1.0
            row[j0 + 2] = -1.0
            for aj, k in enumerate(inter):
                if k[1] == r:
                    row[base + 4 * R + aj] = 1.0
                if k[0] == r:
                    row[base + 4 * R + aj] = -1.0
            A_eq.append(row)
            b_eq.append(0.0)
            # B balance
            row = np.zeros(n)
            row[j0 + 2] = 1.0
            row[j0 + 3] = 1.0
            A_eq.append(row)
            b_eq.append(rd["demand"] * mult)
        for aj, k in enumerate(inter):
            j = base + 4 * R + aj
            c[j] = p_s * data["inter"][k]["cost"]
            ub[j] = data["inter"][k]["cap"]
    res = linprog(c, A_ub=np.array(A_ub), b_ub=np.array(b_ub),
                  A_eq=np.array(A_eq), b_eq=np.array(b_eq),
                  bounds=list(zip(lb, ub)), method="highs")
    assert res.status == 0, res.message
    return float(res.fun)
