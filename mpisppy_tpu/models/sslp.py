###############################################################################
# SSLP: SIPLIB stochastic server location problem, generated natively as
# BoxQP scenario specs (no Pyomo).  Matches the reference model's
# semantics (ref:examples/sslp/model/ReferenceModel.py,
# ref:examples/sslp/sslp.py:27-60):
#
#   first stage:   FacilityOpen[j], j=1..n servers   (binary; the nonants)
#   second stage:  Allocation[i,j] (binary), Dummy[j] >= 0 (overflow)
#   constraints:   capacity:  sum_i Demand[i,j]*y_ij - d_j - Cap*x_j <= 0
#                  client:    sum_j y_ij == ClientPresent_i   (random RHS)
#   objective:     sum_j FixedCost_j x_j + Penalty*sum_j d_j
#                  - sum_ij Revenue_ij y_ij
#
# Randomness is RHS-only (ClientPresent), so the constraint matrix is
# DETERMINISTIC and shared across the whole batch — the batch compiler
# keeps one (m,n) `A` that broadcasts over scenarios, so HBM holds one
# copy of the matrix for any scenario count (the TPU answer to "sslp at
# 10k scenarios must fit").
#
# Data sources, in priority order:
#   * `data_dir`: a directory of SIPLIB `ScenarioK.dat` AMPL-format data
#     files (the reference's on-disk format,
#     ref:examples/sslp/data/sslp_*/scenariodata/) — parsed natively;
#   * `instance` params (n_servers, n_clients, seed): a seeded synthetic
#     instance following the SIPLIB generation scheme (Ntaimo & Sen):
#     integer revenues/demands U{0..25}, fixed costs U{40..70},
#     ClientPresent ~ Bernoulli(1/2).
#
# Integrality is carried as a mask and relaxed at solve time
# (LP relaxation), per the framework's kernel contract
# (ref:mpisppy/spopt.py:884 leans on MIP solvers; we use LP + rounding
# heuristics in the xhat plane).
###############################################################################
from __future__ import annotations

import os
import re

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num  # noqa: F401 (re-export)

DEFAULT_PENALTY = 1000.0

# data_dir -> the first parsed scenario's dict, reused as the shared
# deterministic-instance carrier for _build_spec's cache (see below)
_DATA_DIR_CACHE: dict[str, dict] = {}


# --------------------------------------------------------------------------
# AMPL .dat parsing (the subset SIPLIB sslp files use: scalar params,
# indexed-list params, and table params).
# --------------------------------------------------------------------------
def parse_dat(path: str) -> dict:
    """Parse an sslp AMPL-format .dat file into plain python/numpy data."""
    with open(path) as f:
        text = f.read()
    text = re.sub(r"#.*", "", text)
    out: dict = {}
    # Each statement ends with ';'
    for stmt in text.split(";"):
        stmt = stmt.strip()
        if not stmt.startswith("param"):
            continue
        body = stmt[len("param"):].strip()
        if ":=" in body and ":" in body.split(":=")[0]:
            # table form: "Name:\n  col1 col2 ... :=\n row v v v ..."
            name, rest = body.split(":", 1)
            name = name.strip()
            header, data = rest.split(":=", 1)
            cols = [int(tok) for tok in header.split()]
            rows: dict[int, list[float]] = {}
            toks = data.split()
            i = 0
            while i < len(toks):
                r = int(toks[i])
                vals = [float(v) for v in toks[i + 1:i + 1 + len(cols)]]
                rows[r] = vals
                i += 1 + len(cols)
            nr, nc = max(rows), max(cols)
            mat = np.zeros((nr, nc))
            for r, vals in rows.items():
                for cix, v in zip(cols, vals):
                    mat[r - 1, cix - 1] = v
            out[name] = mat
        else:
            name, data = body.split(":=", 1)
            name = name.strip()
            toks = data.split()
            if len(toks) == 1:
                out[name] = float(toks[0])
            else:
                idx = [int(t) for t in toks[0::2]]
                vals = [float(t) for t in toks[1::2]]
                vec = np.zeros(max(idx))
                for i_, v in zip(idx, vals):
                    vec[i_ - 1] = v
                out[name] = vec
    return out


# --------------------------------------------------------------------------
# Synthetic SIPLIB-style instances (seeded, reproducible).
# --------------------------------------------------------------------------
def synthetic_instance(n_servers: int, n_clients: int, seed: int = 0) -> dict:
    """Deterministic instance data following the SIPLIB generation ranges."""
    rng = np.random.RandomState(seed)
    demand = rng.randint(0, 26, size=(n_clients, n_servers)).astype(float)
    inst = {
        "NumServers": float(n_servers),
        "NumClients": float(n_clients),
        "FixedCost": rng.randint(40, 71, size=n_servers).astype(float),
        # SIPLIB instances use Revenue == Demand
        "Revenue": demand,
        "Demand": demand,
        # capacity sized so a handful of servers can cover expected demand
        "Capacity": float(
            np.ceil(1.5 * demand.mean() * n_clients / max(2, n_servers // 2))),
        "Penalty": DEFAULT_PENALTY,
    }
    return inst


def synthetic_client_present(n_clients: int, scennum: int,
                             seedoffset: int = 0) -> np.ndarray:
    """ClientPresent ~ Bernoulli(1/2) per client, seeded per scenario."""
    rng = np.random.RandomState(10_000 + scennum + seedoffset)
    return (rng.rand(n_clients) < 0.5).astype(float)


# --------------------------------------------------------------------------
# Scenario compiler: instance data + ClientPresent -> ScenarioSpec.
# Column layout (n = NumServers, m = NumClients):
#   [0:n)        x_j FacilityOpen     [0,1] int   <- nonants
#   [n:n+m*n)    y_ij Allocation      [0,1] int   (i-major: y[i,j])
#   [n+m*n: +n)  d_j Dummy            [0,inf)
# Row layout:
#   [0:n)        capacity rows:  sum_i D_ij y_ij - d_j - Cap x_j <= 0
#   [n:n+m)      client rows:    sum_j y_ij == h_i
# --------------------------------------------------------------------------
def _build_spec(inst: dict, client_present: np.ndarray,
                name: str, probability: float | None,
                strengthen: bool = False) -> ScenarioSpec:
    n = int(inst["NumServers"])
    m = int(inst["NumClients"])
    cache_key = "_spec_cache_vub" if strengthen else "_spec_cache"

    # The deterministic data (A, c, box, integrality) is identical for
    # every scenario of an instance — build it once and share the SAME
    # numpy objects across specs, so a 100k-scenario build costs O(m*n)
    # host memory, not O(S*m*n), and the batch compiler's shared-A
    # detection hits the identity fast path.
    cache = inst.get(cache_key)
    if cache is None:
        cap = float(inst["Capacity"])
        penalty = float(inst.get("Penalty", DEFAULT_PENALTY))
        D = np.asarray(inst["Demand"], float)        # (m, n)
        R = np.asarray(inst["Revenue"], float)       # (m, n)
        fc = np.asarray(inst["FixedCost"], float)    # (n,)

        ncols = n + m * n + n
        nrows = n + m

        c = np.concatenate([fc, -R.reshape(-1), np.full(n, penalty)])

        A = np.zeros((nrows, ncols))
        # capacity rows (one per server j)
        j = np.arange(n)
        A[j, j] = -cap                               # -Cap * x_j
        for jj in range(n):
            A[jj, n + jj:n + m * n:n] = D[:, jj]     # D_ij y_ij (i-major)
        A[j, n + m * n + j] = -1.0                   # -d_j

        l = np.zeros(ncols)  # noqa: E741
        # d_j only absorbs D·y_j - Cap x_j <= sum_i D_ij, so the natural
        # finite bound is the column demand sum; finite boxes everywhere
        # make every ops.boxqp.certified_dual_bound finite (the exact-MIP
        # branch-and-bound prunes on it)
        u = np.concatenate([np.ones(n + m * n), D.sum(axis=0)])

        # client rows (one per client i): sum_j y_ij == h_i
        for i in range(m):
            A[n + i, n + i * n:n + (i + 1) * n] = 1.0

        integer = np.zeros(ncols, bool)
        integer[:n + m * n] = True
        if strengthen:
            # variable-upper-bound strengthening y_ij <= x_j: valid for
            # every integer point (capacity already forces y=0 at x=0)
            # but cuts the fractional LP points where a barely-open
            # server serves clients — the standard SSLP tightening; it
            # lifts the LP relaxation toward the integer hull, so every
            # node LP in the exact-MIP plane (ops/bnb.py) prunes harder
            # and the integer-Lagrangian bound certifies tighter.  The
            # VUB rows have 2 nonzeros each, so the strengthened matrix
            # goes out SPARSE (ELL path: max row nnz ~ m+2 vs 705 dense
            # columns — the extra rows come nearly free).
            import scipy.sparse as sps
            V = np.zeros((m * n, ncols))
            rows = np.arange(m * n)
            V[rows, n + rows] = 1.0                  # +y_ij
            V[rows, np.tile(np.arange(n), m)] = -1.0  # -x_j (i-major y)
            A = sps.csr_matrix(np.vstack([A, V]))
        cache = inst[cache_key] = (A, c, l, u, integer)
    A, c, l, u, integer = cache

    nrows = A.shape[0]
    bl = np.full(nrows, -np.inf)
    bu = np.full(nrows, np.inf)
    bu[:n] = 0.0
    bl[n:n + m] = client_present
    bu[n:n + m] = client_present
    if strengthen:
        bu[n + m:] = 0.0  # y_ij - x_j <= 0

    return ScenarioSpec(
        name=name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(n, dtype=np.int32),
        probability=probability, integer=integer,
    )


def scenario_creator(scenario_name: str, data_dir: str | None = None,
                     instance: dict | None = None,
                     n_servers: int = 5, n_clients: int = 25,
                     num_scens: int | None = None,
                     seedoffset: int = 0, inst_seed: int = 0,
                     lp_relax: bool = False,
                     strengthen: bool = False) -> ScenarioSpec:
    """ref:examples/sslp/sslp.py:27-45 semantics: one spec per scenario;
    `data_dir` points at SIPLIB scenariodata; otherwise synthetic.
    `lp_relax` drops the integrality mask (the BASELINE 'sslp LP-relaxed'
    configs), so xhat heuristics do not round.  `strengthen` adds the
    y_ij <= x_j variable-upper-bound rows (tighter LP relaxation for
    the exact-MIP certification plane)."""
    if data_dir is not None:
        data = parse_dat(os.path.join(data_dir, scenario_name + ".dat"))
        h = np.zeros(int(data["NumClients"]))
        cp = data.get("ClientPresent")
        if cp is not None:
            cp = np.asarray(cp, float).reshape(-1)
            h[:cp.shape[0]] = cp
        else:
            h[:] = 1.0  # AMPL default=1 (ReferenceModel.py ClientPresent)
        # The deterministic data repeats in every ScenarioK.dat — route
        # all scenarios of a directory through ONE cached inst dict so
        # _build_spec's shared-(A,c,…) cache actually hits and the batch
        # compiler sees identical array objects (one (m,n) A on the host
        # regardless of scenario count).
        inst = _DATA_DIR_CACHE.setdefault(data_dir, data)
    else:
        if instance is None:
            instance = synthetic_instance(n_servers, n_clients, inst_seed)
        h = synthetic_client_present(int(instance["NumClients"]),
                                     extract_num(scenario_name), seedoffset)
    prob = None if num_scens is None else 1.0 / num_scens
    spec = _build_spec(inst if data_dir is not None else instance, h,
                       scenario_name, prob, strengthen=strengthen)
    if lp_relax:
        spec.integer = np.zeros_like(spec.integer)  # shared: don't mutate
    return spec


# --------------------------------------------------------------------------
# Seeded scenario synthesis (scengen branch; docs/scengen.md).
#
# sslp randomness is RHS-only (ClientPresent), so the program's varying
# fields are just (bl, bu): the dense constraint matrix, costs, and box
# stay one shared template for ANY scenario count — the ideal shape for
# on-device synthesis.  ClientPresent ~ Bernoulli(1/2) per client draws
# from threefry (uniform(scen_key(base_key, s)) < 0.5) instead of the
# legacy RandomState stream.
# --------------------------------------------------------------------------
def scenario_program(num_scens: int, seed: int = 0, start: int = 0,
                     n_servers: int = 5, n_clients: int = 25,
                     inst_seed: int = 0, lp_relax: bool = False,
                     instance: dict | None = None):
    """ScenarioProgram drawing ClientPresent through scengen keys."""
    import jax.numpy as jnp
    from jax import random as jrandom

    from mpisppy_tpu.scengen.program import ScenarioProgram, scen_key

    inst = instance if instance is not None \
        else synthetic_instance(n_servers, n_clients, inst_seed)
    n = int(inst["NumServers"])
    m = int(inst["NumClients"])
    # populate the deterministic-structure cache and reuse its arrays
    _build_spec(inst, np.zeros(m), "_scengen_template", None)
    A, c, l, u, integer = inst["_spec_cache"]  # noqa: E741
    nrows = A.shape[0]

    bl0 = np.full(nrows, -np.inf)
    bu0 = np.full(nrows, np.inf)
    bu0[:n] = 0.0

    bl0_f = jnp.asarray(bl0, jnp.float32)
    bu0_f = jnp.asarray(bu0, jnp.float32)

    def sampler(base_key, idx):
        h = (jrandom.uniform(scen_key(base_key, idx), (m,),
                             jnp.float32) < 0.5).astype(jnp.float32)
        return {"bl": bl0_f.at[n:n + m].set(h),
                "bu": bu0_f.at[n:n + m].set(h)}

    integer_eff = np.zeros_like(integer) if lp_relax else integer
    return ScenarioProgram(
        name="sslp", num_scenarios=int(num_scens),
        base_seed=int(seed), start=int(start),
        template={"c": c, "A": A, "bl": bl0, "bu": bu0, "l": l, "u": u},
        varying=("bl", "bu"), sampler=sampler,
        nonant_idx=np.arange(n, dtype=np.int32),
        integer=integer_eff,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    """One-based names (ref:examples/sslp/sslp.py:55-60)."""
    start = 1 if start is None else start
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.add_to_config("instance_name",
                      description="sslp instance name (e.g., sslp_15_45_10)",
                      domain=str, default=None)
    cfg.add_to_config("sslp_data_path",
                      description="path to sslp data (e.g., ./data)",
                      domain=str, default=None)
    cfg.add_to_config("n_servers", description="synthetic servers",
                      domain=int, default=5)
    cfg.add_to_config("n_clients", description="synthetic clients",
                      domain=int, default=25)
    cfg.add_to_config("sslp_lp_relax",
                      description="drop the integrality mask (the "
                      "BASELINE 'sslp LP-relaxed' configuration; serve "
                      "sessions use it for interactive-latency runs)",
                      domain=bool, default=False)


def kw_creator(cfg):
    lp_relax = bool(cfg.get("sslp_lp_relax", False))
    inst = cfg.get("instance_name")
    if inst is not None and cfg.get("sslp_data_path") is not None:
        ns = int(inst.split("_")[-1])
        data_dir = os.path.join(cfg["sslp_data_path"], inst, "scenariodata")
        return {"data_dir": data_dir, "num_scens": ns,
                "lp_relax": lp_relax}
    # build the synthetic instance ONCE and share it across every
    # scenario_creator call, so the dense constraint matrix exists once
    # on the host and the batch compiler's identity fast path fires
    return {"instance": synthetic_instance(cfg.get("n_servers", 5),
                                           cfg.get("n_clients", 25)),
            "num_scens": cfg.get("num_scens"),
            "lp_relax": lp_relax}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass


# --------------------------------------------------------------------------
# Exact integer recourse evaluation (the inner-bound evaluator).
#
# With the first stage FIXED, a scenario's recourse is an assignment
# with capacity-overflow penalties.  The batched B&B's generic dive
# rounds mid-face LP points and lands on poor assignments (the round-3
# documented blocker for the certified-gap inner side), so the sslp
# family carries its own exact evaluator: solve the recourse LP with
# the framework kernel, round each present client to its argmax server
# (the client rows are SOS1-like equalities), then 1-opt reassign until
# stable.  The returned value is the EXACT objective of an integral
# feasible recourse — a certified inner bound contribution — computed
# in closed form from the instance data.
# --------------------------------------------------------------------------
def exact_recourse_value(inst: dict, client_present: np.ndarray,
                         xhat: np.ndarray,
                         y_lp: np.ndarray | None = None) -> float:
    """One scenario's exact integer recourse value at first stage
    `xhat` ((n,) 0/1).  `y_lp` ((m, n) LP allocation, client-major)
    seeds the rounding; greedy best-revenue seeding is used without it.
    Serving from closed servers is allowed (original penalty-form
    semantics) but never chosen by the heuristic unless no server is
    open."""
    n = int(inst["NumServers"])
    m = int(inst["NumClients"])
    cap = float(inst["Capacity"])
    pen = float(inst.get("Penalty", DEFAULT_PENALTY))
    D = np.asarray(inst["Demand"], float)      # (m, n)
    R = np.asarray(inst["Revenue"], float)
    fc = np.asarray(inst["FixedCost"], float)
    x = np.round(np.asarray(xhat, float)[:n])
    open_j = np.nonzero(x > 0.5)[0]
    present = np.nonzero(np.asarray(client_present, float) > 0.5)[0]
    first = float(fc @ x)
    if present.size == 0:
        return first
    serve_set = open_j if open_j.size else np.arange(n)

    # seed assignment
    assign = np.empty(present.size, int)
    if y_lp is not None:
        for k, i in enumerate(present):
            assign[k] = serve_set[int(np.argmax(y_lp[i, serve_set]))]
    else:
        for k, i in enumerate(present):
            assign[k] = serve_set[int(np.argmax(R[i, serve_set]))]

    def value(assign):
        load = np.zeros(n)
        rev = 0.0
        for k, i in enumerate(present):
            j = assign[k]
            load[j] += D[i, j]
            rev += R[i, j]
        over = np.maximum(0.0, load - cap * x)
        return first - rev + pen * float(over.sum())

    best = value(assign)
    # 1-opt moves + pairwise swaps: single-client moves cannot fix
    # capacity packing (two clients on over-full servers may need to
    # trade places), so the sweep alternates move and swap passes
    improved = True
    sweeps = 0
    while improved and sweeps < 30:
        improved = False
        sweeps += 1
        for k in range(present.size):
            cur = assign[k]
            for j in serve_set:
                if j == cur:
                    continue
                trial = assign.copy()
                trial[k] = j
                v = value(trial)
                if v < best - 1e-9:
                    assign, best = trial, v
                    improved = True
        for k1 in range(present.size):
            for k2 in range(k1 + 1, present.size):
                if assign[k1] == assign[k2]:
                    continue
                trial = assign.copy()
                trial[k1], trial[k2] = assign[k2], assign[k1]
                v = value(trial)
                if v < best - 1e-9:
                    assign, best = trial, v
                    improved = True
    return best


def eval_candidates_exact(inst: dict, client_presents: "list[np.ndarray]",
                          xhats, probs=None,
                          lp_opts=None) -> "list[float]":
    """Exact integer inner-bound values E[f(xhat)] for several candidate
    first stages: one batched LP solve over (K*S) recourse problems via
    the framework kernel seeds per-client argmax rounding + 1-opt.
    Returns one expectation per candidate."""
    import dataclasses as _dc

    import jax.numpy as jnp

    from mpisppy_tpu.core import batch as batch_mod
    from mpisppy_tpu.ops import pdhg

    S = len(client_presents)
    K = len(xhats)
    n = int(inst["NumServers"])
    m = int(inst["NumClients"])
    if probs is None:
        probs = np.full(S, 1.0 / S)
    # one batched LP: scenarios repeat K times with different fixed x
    specs = [_build_spec(inst, client_presents[s], f"p{k}_{s}", None)
             for k in range(K) for s in range(S)]
    # uniform pair probabilities keep from_specs happy; expectations are
    # computed per candidate below
    for sp in specs:
        sp.probability = 1.0 / len(specs)
    b = batch_mod.from_specs(specs)
    xh = jnp.asarray(np.repeat(np.asarray(xhats, float), S, axis=0),
                     b.qp.c.dtype)  # (K*S, n)
    qp = b.with_fixed_nonants(xh)
    opts = lp_opts or pdhg.PDHGOptions(tol=1e-5, max_iters=20_000,
                                       restart_period=40, omega0=0.1)
    st = pdhg.solve(qp, opts, pdhg.init_state(qp, opts))
    # original-space allocation block, client-major (m, n) per problem
    x_orig = np.asarray(st.x) * np.broadcast_to(
        np.asarray(b.d_col), (K * S, b.qp.n))
    y_all = x_orig[:, n:n + m * n].reshape(K * S, m, n)
    out = []
    for k in range(K):
        tot = 0.0
        for s in range(S):
            tot += probs[s] * exact_recourse_value(
                inst, client_presents[s], np.asarray(xhats[k]),
                y_lp=y_all[k * S + s])
        out.append(float(tot))
    return out
