# models subpackage of mpisppy_tpu
