###############################################################################
# battery: hybrid solar-battery storage (Singh-Knueven Lagrangian
# relaxation), generated natively as BoxQP scenario specs
# (ref:examples/battery/battery.py:25-131).
#
#   first stage (nonants): y_t >= 0   hourly committed output, t=1..T
#   second stage:          p_t in [0,cMax] charge, q_t in [0,dMax]
#                          discharge, x_t in [eMin,eMax] storage,
#                          z in {0,1} chance-constraint indicator
#   storage balance:  x_{t+1} = x_t + eff p_t - (1/eff) q_t   (x_1 = x0)
#   big-M rows:       y_t - q_t + p_t - M_{s,t} z <= solar_{s,t}
#   objective:        -rev.y + char*sum p + disc*sum q + lam*z
#
# Randomness enters only through (solar, M) in the big-M RHS/column, so
# A is shared across the batch except the M column — the batch compiler
# keeps per-scenario A values with a shared ELL pattern.  `use_LP`
# relaxes z (the reference's LP mode); lam is the chance-constraint
# dual weight.  Data: the reference's published constants; solar from
# `solar_filename` (csv, scenarios x T) or a seeded synthetic profile.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num

_DATA = {
    "T": 24, "eff": 0.9, "eMax": 960.0, "eMin": 192.0,
    "char": 0.0256, "disc": 0.0256, "cMax": 480.0, "dMax": 480.0,
    "eps": 0.05, "x0": 480.0,
    "rev": np.array(
        [0.0189, 0.0172, 0.0155, 0.0148, 0.0146, 0.0151, 0.0173, 0.0219,
         0.0227, 0.0226, 0.0235, 0.0242, 0.0250, 0.0261, 0.0285, 0.0353,
         0.0531, 0.0671, 0.0438, 0.0333, 0.0287, 0.0268, 0.0240, 0.0211]),
}


def synthetic_solar(num_scens: int, T: int = 24, seed: int = 0) -> np.ndarray:
    """(num_scens, T) seeded diurnal solar output."""
    rng = np.random.RandomState(seed)
    t = np.arange(T)
    base = 400.0 * np.clip(np.sin(np.pi * (t - 6.0) / 12.0), 0.0, None)
    scale = rng.uniform(0.4, 1.1, size=(num_scens, 1))
    noise = rng.uniform(0.85, 1.15, size=(num_scens, T))
    return base[None, :] * scale * noise


def getData(solar_filename: str | None = None, num_scens: int = 10,
            seed: int = 0) -> dict:
    """ref:battery.py:98-122 (constants from the paper; big-M from its
    Corollary 1 with all-equally-likely scenarios)."""
    data = dict(_DATA)
    if solar_filename is not None:
        data["solar"] = np.loadtxt(solar_filename, delimiter=",")
    else:
        data["solar"] = synthetic_solar(num_scens, data["T"], seed)
    N = data["solar"].shape[0]
    data["N"] = N
    base = min(data["dMax"], data["eff"] * (data["eMax"] - data["eMin"]))
    M = base * np.ones((N, data["T"])) - data["solar"]
    ell = int(np.floor(N * data["eps"]) + 1)
    M += np.sort(data["solar"], axis=0)[-ell, :]
    data["M"] = M
    return data


def scenario_creator(scenario_name: str, solar_filename: str | None = None,
                     use_LP: bool = False, lam: float = 100.0,
                     data: dict | None = None, num_scens: int | None = None,
                     seed: int = 0, **_ignored) -> ScenarioSpec:
    """Column layout: [y (T) | p (T) | q (T) | x (T) | z].
    Row layout: [T-1 balance eq | T big-M rows]."""
    if data is None:
        data = getData(solar_filename, num_scens or 10, seed)
    s = extract_num(scenario_name)
    T = data["T"]
    eff = data["eff"]
    solar = np.asarray(data["solar"], float)
    M = np.asarray(data["M"], float)
    Y0, P0, Q0, X0, Z0 = 0, T, 2 * T, 3 * T, 4 * T
    n = 4 * T + 1
    m = (T - 1) + T

    cache = data.get("_spec_cache")
    if cache is None:
        # deterministic structure shared across scenarios except the
        # big-M column, which carries scenario values — build the shared
        # parts once
        rows, cols, vals = [], [], []
        r = 0
        # T-1 balance rows over t=0..T-2, leaving the final hour's p/q
        # outside the storage recursion — this mirrors the REFERENCE
        # formulation exactly (ref:battery.py:65-68 iterates Tm1 =
        # range(T-1)); the end-of-horizon artifact is the paper
        # model's, kept for parity
        for t in range(T - 1):
            rows += [r, r, r, r]
            cols += [X0 + t + 1, X0 + t, P0 + t, Q0 + t]
            vals += [1.0, -1.0, -eff, 1.0 / eff]
            r += 1
        bigm0 = r
        for t in range(T):
            rows += [r, r, r, r]
            cols += [Y0 + t, Q0 + t, P0 + t, Z0]
            vals += [1.0, -1.0, 1.0, 0.0]  # M value filled per scenario
            r += 1
        c = np.concatenate([-np.asarray(data["rev"], float),
                            np.full(T, data["char"]),
                            np.full(T, data["disc"]),
                            np.zeros(T), [0.0]])
        l = np.concatenate([np.zeros(T), np.zeros(T), np.zeros(T),  # noqa: E741
                            np.full(T, data["eMin"]), [0.0]])
        u = np.concatenate([
            np.full(T, solar.max() + M.max() + data["dMax"]),
            np.full(T, data["cMax"]), np.full(T, data["dMax"]),
            np.full(T, data["eMax"]), [1.0]])
        l[X0] = u[X0] = data["x0"]         # initial storage level
        integer = np.zeros(n, bool)
        integer[Z0] = True
        cache = data["_spec_cache"] = (
            np.asarray(rows), np.asarray(cols), np.asarray(vals, float),
            bigm0, c, l, u, integer)
    rows, cols, vals, bigm0, c, l, u, integer = cache

    import scipy.sparse as sps
    vals_s = vals.copy()
    # the z entry of big-M row t is the 4th entry of each group of 4
    z_slots = np.nonzero(np.asarray(cols) == Z0)[0]
    vals_s[z_slots] = -M[s]
    A = sps.csr_matrix((vals_s, (rows, cols)), shape=(m, n))
    bl = np.concatenate([np.zeros(T - 1), np.full(T, -np.inf)])
    bu = np.concatenate([np.zeros(T - 1), solar[s]])

    c_s = c.copy()
    c_s[Z0] = lam
    return ScenarioSpec(
        name=scenario_name, c=c_s, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(T, dtype=np.int32),
        probability=1.0 / data["N"],
        integer=np.zeros(n, bool) if use_LP else integer,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("solar_filename", "csv of solar scenarios", str,
                      None)
    cfg.add_to_config("battery_lam", "chance-constraint dual weight",
                      float, 100.0)
    cfg.add_to_config("battery_use_lp", "relax the indicator z", bool,
                      False)


def kw_creator(cfg):
    ns = int(cfg["num_scens"])
    return {
        "data": getData(cfg.get("solar_filename"), ns),
        "num_scens": ns,
        "lam": cfg.get("battery_lam", 100.0),
        "use_LP": cfg.get("battery_use_lp", False),
    }


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
