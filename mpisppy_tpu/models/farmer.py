###############################################################################
# Farmer: the canonical 2-stage scalable test problem, generated natively
# as BoxQP scenario specs (no Pyomo).  Matches the reference model's
# data, randomness, and scenario naming exactly
# (ref:examples/farmer/farmer.py:31-230):
#
#   first stage:   DevotedAcreage[crop]            (the nonants)
#   second stage:  QuantitySubQuotaSold, QuantitySuperQuotaSold,
#                  QuantityPurchased               (recourse)
#   constraints:   total acreage; cattle feed requirement; limit sold
#   randomness:    per-crop Yield — 3 base scenarios (below/avg/above),
#                  plus U[0,1) noise for scenario groups > 0 seeded with
#                  RandomState(scennum + seedoffset), one rand() per crop
#                  in WHEAT0,CORN0,SUGAR_BEETS0,WHEAT1,... order.
#
# Known answer for parity: 3-scenario EF objective = -108390
# (classic Birge & Louveaux farmer value used throughout the reference's
# examples/docs).
#
# Column layout per scenario (k = crops_multiplier, C = 3k crops):
#   [0:C)    acreage        bounds [0, 500k]          <- nonants
#   [C:2C)   sub-quota sold bounds [0, PriceQuota]
#   [2C:3C)  super-quota    bounds [0, inf)
#   [3C:4C)  purchased      bounds [0, inf)
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num  # noqa: F401 (re-export)

_BASE_YIELD = np.array([
    [2.0, 2.4, 16.0],   # BelowAverageScenario
    [2.5, 3.0, 20.0],   # AverageScenario
    [3.0, 3.6, 24.0],   # AboveAverageScenario
])
_PLANTING_COST = np.array([150.0, 230.0, 260.0])
_SUB_PRICE = np.array([170.0, 150.0, 36.0])
_SUPER_PRICE = np.array([0.0, 0.0, 10.0])
_PURCHASE_PRICE = np.array([238.0, 210.0, 100000.0])
_CATTLE_FEED = np.array([200.0, 240.0, 0.0])
_PRICE_QUOTA = np.array([100000.0, 100000.0, 6000.0])


def _yields(scennum: int, crops_multiplier: int, seedoffset: int) -> np.ndarray:
    base = _BASE_YIELD[scennum % 3]
    groupnum = scennum // 3
    y = np.tile(base, crops_multiplier).reshape(crops_multiplier, 3)
    if groupnum != 0:
        # one rand() per crop in CROPS order (WHEAT_i, CORN_i, SB_i for
        # each i) — ref:examples/farmer/farmer.py:157-163
        stream = np.random.RandomState(scennum + seedoffset)
        y = y + stream.rand(crops_multiplier, 3)
    return y.reshape(-1)  # (3k,)


def scenario_creator(scenario_name: str, use_integer: bool = False,
                     crops_multiplier: int = 1, num_scens: int | None = None,
                     seedoffset: int = 0) -> ScenarioSpec:
    scennum = extract_num(scenario_name)
    k = crops_multiplier
    C = 3 * k
    n = 4 * C
    total_acreage = 500.0 * k
    yields = _yields(scennum, k, seedoffset)

    tile = lambda v: np.tile(v, k)  # noqa: E731
    c = np.concatenate([
        tile(_PLANTING_COST),       # acreage
        -tile(_SUB_PRICE),          # sub-quota sales (revenue)
        -tile(_SUPER_PRICE),        # super-quota sales
        tile(_PURCHASE_PRICE),      # purchases
    ])

    # rows: [0] total acreage <= 500k
    #       [1:1+C] cattle feed: yield*acre + purch - sub - super >= CFR
    #       [1+C:1+2C] limit sold: sub + super - yield*acre <= 0
    m = 1 + 2 * C
    A = np.zeros((m, n))
    bl = np.full(m, -np.inf)
    bu = np.full(m, np.inf)

    A[0, :C] = 1.0
    bu[0] = total_acreage

    rows = 1 + np.arange(C)
    A[rows, np.arange(C)] = yields               # acre
    A[rows, 3 * C + np.arange(C)] = 1.0          # purchased
    A[rows, C + np.arange(C)] = -1.0             # sub sold
    A[rows, 2 * C + np.arange(C)] = -1.0         # super sold
    bl[rows] = tile(_CATTLE_FEED)

    rows = 1 + C + np.arange(C)
    A[rows, C + np.arange(C)] = 1.0
    A[rows, 2 * C + np.arange(C)] = 1.0
    A[rows, np.arange(C)] = -yields
    bu[rows] = 0.0

    l = np.zeros(n)
    u = np.concatenate([
        np.full(C, total_acreage),
        tile(_PRICE_QUOTA),
        np.full(C, np.inf),
        np.full(C, np.inf),
    ])

    integer = np.zeros(n, bool)
    if use_integer:
        integer[:C] = True

    return ScenarioSpec(
        name=scenario_name,
        c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(C, dtype=np.int32),
        probability=None if num_scens is None else 1.0 / num_scens,
        integer=integer,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    """ref:examples/farmer/farmer.py:235-240."""
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


# --------------------------------------------------------------------------
# Seeded scenario synthesis (scengen branch; docs/scengen.md).
#
# The same model with its randomness rebased onto counter-based keys:
# scenario s's yields are base[s % 3] plus U[0,1) noise per crop for
# scenario groups > 0, drawn from threefry via
# jax.random.uniform(scen_key(base_key, s)) instead of the legacy
# RandomState(scennum + seedoffset) Mersenne stream — the draws differ
# from the legacy branch by construction (different generator), but are
# identical between host materialization, vmapped device synthesis,
# tiled kernels, and any mesh sharding (the fold_in contract).
# Farmer's randomness enters the CONSTRAINT MATRIX (yields), so this is
# the per-scenario-A case of the program family.
# --------------------------------------------------------------------------
def scenario_program(num_scens: int, seed: int = 0, start: int = 0,
                     crops_multiplier: int = 1,
                     use_integer: bool = False):
    """ScenarioProgram drawing farmer yields through scengen keys."""
    import jax.numpy as jnp
    from jax import random as jrandom

    from mpisppy_tpu.scengen.program import ScenarioProgram, scen_key

    k = int(crops_multiplier)
    C = 3 * k
    n = 4 * C
    total_acreage = 500.0 * k
    tile = lambda v: np.tile(v, k)  # noqa: E731

    c = np.concatenate([
        tile(_PLANTING_COST), -tile(_SUB_PRICE),
        -tile(_SUPER_PRICE), tile(_PURCHASE_PRICE)])
    m = 1 + 2 * C
    # yield-free skeleton of the constraint matrix (scenario_creator's
    # layout with the yield coefficients zeroed; the sampler scatters
    # the drawn yields into rows [1, 1+C) and their negation into the
    # limit rows)
    A0 = np.zeros((m, n))
    A0[0, :C] = 1.0
    rows = 1 + np.arange(C)
    A0[rows, 3 * C + np.arange(C)] = 1.0
    A0[rows, C + np.arange(C)] = -1.0
    A0[rows, 2 * C + np.arange(C)] = -1.0
    rows2 = 1 + C + np.arange(C)
    A0[rows2, C + np.arange(C)] = 1.0
    A0[rows2, 2 * C + np.arange(C)] = 1.0
    bl = np.full(m, -np.inf)
    bu = np.full(m, np.inf)
    bu[0] = total_acreage
    bl[1:1 + C] = tile(_CATTLE_FEED)
    bu[1 + C:1 + 2 * C] = 0.0
    l = np.zeros(n)  # noqa: E741
    u = np.concatenate([np.full(C, total_acreage), tile(_PRICE_QUOTA),
                        np.full(C, np.inf), np.full(C, np.inf)])
    integer = np.zeros(n, bool)
    if use_integer:
        integer[:C] = True

    A0_f = jnp.asarray(A0, jnp.float32)
    base_f = jnp.asarray(_BASE_YIELD, jnp.float32)
    feed_rows = jnp.asarray(rows, jnp.int32)
    limit_rows = jnp.asarray(rows2, jnp.int32)
    acre_cols = jnp.arange(C, dtype=jnp.int32)

    def sampler(base_key, idx):
        base = jnp.tile(base_f[idx % 3], (k, 1))        # (k, 3)
        noise = jrandom.uniform(scen_key(base_key, idx), (k, 3),
                                jnp.float32)
        y = (base + jnp.where(idx // 3 > 0, noise, 0.0)).reshape(-1)
        A = A0_f.at[feed_rows, acre_cols].set(y)
        A = A.at[limit_rows, acre_cols].set(-y)
        return {"A": A}

    return ScenarioProgram(
        name="farmer", num_scenarios=int(num_scens),
        base_seed=int(seed), start=int(start),
        template={"c": c, "A": A0, "bl": bl, "bu": bu, "l": l, "u": u},
        varying=("A",), sampler=sampler,
        nonant_idx=np.arange(C, dtype=np.int32),
        integer=integer if use_integer else None,
    )


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("crops_multiplier",
                      description="number of crops will be three times this",
                      domain=int, default=1)
    cfg.add_to_config("farmer_with_integers",
                      description="integer acreage variant",
                      domain=bool, default=False)


def kw_creator(cfg):
    return {
        "use_integer": cfg.get("farmer_with_integers", False),
        "crops_multiplier": cfg.get("crops_multiplier", 1),
        "num_scens": cfg.get("num_scens", None),
    }


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
