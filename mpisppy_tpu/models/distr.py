###############################################################################
# distr: inter-region minimum-cost distribution via consensus ADMM
# (ref:examples/distr/distr.py + distr_data.py).  Regions are the admm
# "scenarios"; inter-region arc flows are the consensus variables, each
# arc's cost split half/half between its two regions
# (ref:distr.py:23-50 inter_arcs_adder).
#
# Synthetic seeded data in the reference's shape: each region has a
# factory node F (bounded production), a distribution center DC, and a
# buyer node B (fixed demand, slack with penalty so every region is
# feasible standalone); inter-region arcs form a ring DC_r -> DC_{r+1}.
#
# Region LP (min):  prod_cost*g + sum arc_cost*f + penalty*unmet
#   s.t.  F:  g - f_{F->DC} = 0
#         DC: f_{F->DC} + sum_in f_inter - f_{DC->B}
#             - sum_out f_inter = 0
#         B:  f_{DC->B} + unmet = demand
# with box capacities on every flow.  The consensus labels are the
# inter-arc flow names, shared by source and target region — exactly
# the reference's nonant choice.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec

_PENALTY = 1000.0


def region_data(num_regions: int, seed: int = 0) -> dict:
    """Seeded synthetic inter-region network (ref:distr_data.py shape)."""
    rng = np.random.RandomState(seed + 31 * num_regions)
    regions = {}
    for r in range(num_regions):
        regions[f"Region{r}"] = {
            "prod_cap": float(rng.uniform(80.0, 160.0)),
            "prod_cost": float(rng.uniform(2.0, 8.0)),
            "demand": float(rng.uniform(60.0, 120.0)),
            "intra_cost": float(rng.uniform(0.5, 2.0)),
            "intra_cap": 500.0,
        }
    inter = {}
    for r in range(num_regions):
        t = (r + 1) % num_regions
        if num_regions > 1:
            inter[(f"Region{r}", f"Region{t}")] = {
                "cap": float(rng.uniform(30.0, 80.0)),
                "cost": float(rng.uniform(1.0, 4.0)),
            }
    return {"regions": regions, "inter": inter}


def _region_arcs(region: str, data: dict):
    """(incoming, outgoing) inter-arc keys touching `region`."""
    inc = [k for k in data["inter"] if k[1] == region]
    out = [k for k in data["inter"] if k[0] == region]
    return inc, out


def arc_label(key) -> str:
    return f"flow_{key[0]}_{key[1]}"


def scenario_creator(scenario_name: str, data: dict | None = None,
                     num_regions: int | None = None, seed: int = 0,
                     **_ignored):
    """Returns (ScenarioSpec, var_names) — the admmWrapper contract
    (consensus labels resolved by name, ref:distr.py nonant choice)."""
    if data is None:
        data = region_data(num_regions or 3, seed)
    rd = data["regions"][scenario_name]
    inc, out = _region_arcs(scenario_name, data)

    # columns: g, f_FDC, f_DCB, unmet, then one per touching inter arc
    var_names = ["g", "f_FDC", "f_DCB", "unmet"] \
        + [arc_label(k) for k in inc + out]
    n = len(var_names)
    c = np.zeros(n)
    c[0] = rd["prod_cost"]
    c[1] = rd["intra_cost"]
    c[2] = rd["intra_cost"]
    c[3] = _PENALTY
    l = np.zeros(n)  # noqa: E741
    u = np.empty(n)
    u[0] = rd["prod_cap"]
    u[1] = rd["intra_cap"]
    u[2] = rd["intra_cap"]
    u[3] = rd["demand"]
    for j, k in enumerate(inc + out):
        # half the arc cost to each side (ref:distr.py:36 note)
        c[4 + j] = data["inter"][k]["cost"] / 2.0
        u[4 + j] = data["inter"][k]["cap"]

    # rows: F balance, DC balance, B balance
    A = np.zeros((3, n))
    A[0, 0] = 1.0
    A[0, 1] = -1.0
    A[1, 1] = 1.0
    A[1, 2] = -1.0
    for j, k in enumerate(inc):
        A[1, 4 + j] = 1.0
    for j, k in enumerate(out):
        A[1, 4 + len(inc) + j] = -1.0
    A[2, 2] = 1.0
    A[2, 3] = 1.0
    bl = np.array([0.0, 0.0, rd["demand"]])
    bu = bl.copy()

    spec = ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(0, dtype=np.int32),  # set by the wrapper
    )
    return spec, var_names


def consensus_vars_creator(num_regions: int, data: dict | None = None,
                           seed: int = 0) -> dict:
    """region -> list of consensus labels (both endpoint regions carry
    each inter arc, ref:distr_admm_cylinders.py consensus setup)."""
    if data is None:
        data = region_data(num_regions, seed)
    out: dict = {}
    for r in data["regions"]:
        inc, outg = _region_arcs(r, data)
        out[r] = [arc_label(k) for k in inc + outg]
    return out


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"Region{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(cfg):
    ns = int(cfg["num_scens"])
    return {"data": region_data(ns), "num_regions": ns}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass


def global_lp_oracle(data: dict):
    """The merged single-LP optimum via scipy (test oracle, the analog
    of ref:examples/distr/globalmodel.py)."""
    from scipy.optimize import linprog

    regions = list(data["regions"])
    inter = list(data["inter"])
    # columns: per region (g, f_FDC, f_DCB, unmet) then one per inter arc
    nr = len(regions)
    n = 4 * nr + len(inter)
    c = np.zeros(n)
    lb = np.zeros(n)
    ub = np.empty(n)
    for i, r in enumerate(regions):
        rd = data["regions"][r]
        c[4 * i:4 * i + 4] = [rd["prod_cost"], rd["intra_cost"],
                              rd["intra_cost"], _PENALTY]
        ub[4 * i:4 * i + 4] = [rd["prod_cap"], rd["intra_cap"],
                               rd["intra_cap"], rd["demand"]]
    for j, k in enumerate(inter):
        c[4 * nr + j] = data["inter"][k]["cost"]
        ub[4 * nr + j] = data["inter"][k]["cap"]
    A_eq, b_eq = [], []
    for i, r in enumerate(regions):
        rd = data["regions"][r]
        row = np.zeros(n)
        row[4 * i] = 1.0
        row[4 * i + 1] = -1.0
        A_eq.append(row); b_eq.append(0.0)
        row = np.zeros(n)
        row[4 * i + 1] = 1.0
        row[4 * i + 2] = -1.0
        for j, k in enumerate(inter):
            if k[1] == r:
                row[4 * nr + j] = 1.0
            if k[0] == r:
                row[4 * nr + j] = -1.0
        A_eq.append(row); b_eq.append(0.0)
        row = np.zeros(n)
        row[4 * i + 2] = 1.0
        row[4 * i + 3] = 1.0
        A_eq.append(row); b_eq.append(rd["demand"])
    res = linprog(c, A_eq=np.array(A_eq), b_eq=np.array(b_eq),
                  bounds=list(zip(lb, ub)), method="highs")
    assert res.success
    return float(res.fun)
