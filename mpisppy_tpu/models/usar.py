###############################################################################
# USAR: urban search and rescue team deployment under uncertainty
# (ref:examples/usar/abstract.py, the Chen & Miller-Hooks formulation;
# data generation follows ref:examples/usar/generate_data.py's shape:
# uniform coordinates, Poisson-ish household sizes, Pareto survival
# deadlines).
#
# Modeled here (the core decision structure):
#   * first stage: binary depot activation, sum_d active_d == K
#     (ref:abstract.py limit_num_active_depots) — the nonants;
#   * per scenario: timed departures depot_departures[t, d, s] (binary),
#     only from active depots (ref depart_only_active_depots), at most
#     depot_inflows[t] departures per period (ref limit_depot_outflow),
#     each site visited at most once (ref visit_only_once), and a
#     departure at t from d saves lives_to_be_saved[t + travel(d, s), s]
#     (deadline-limited: lives decay to 0 after the scenario's survival
#     horizon).
# Simplification vs the reference: teams return after one rescue —
# the inter-site chain variables (site_departures / stays_at_site /
# is_time_from_arrival) are folded into the single-hop arrival
# bookkeeping, keeping the same first-stage decision and the same
# deadline/capacity trade-offs while staying a compact batched spec.
#
# Columns: [active_d (D, int, nonants) | x_{t,d,s} (T*D*S, int)]
# Rows: activation equality, per-(t,d,s) linking x <= active_d,
#       per-t outflow caps, per-s visit-once.
###############################################################################
from __future__ import annotations

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num


def generate_instance(num_depots: int = 3, num_sites: int = 8,
                      time_horizon: int = 6, num_active_depots: int = 2,
                      seed: int = 0) -> dict:
    """Deterministic geometry (ref:generate_data.py generate_coords):
    uniform depot/site coordinates, travel times from scaled distances."""
    rng = np.random.RandomState(seed)
    depot_xy = rng.rand(num_depots, 2)
    site_xy = rng.rand(num_sites, 2)
    dist = np.linalg.norm(depot_xy[:, None, :] - site_xy[None, :, :],
                          axis=-1)
    travel = np.maximum(1, np.ceil(dist * (time_horizon / 2))).astype(int)
    return {
        "num_depots": num_depots,
        "num_sites": num_sites,
        "time_horizon": time_horizon,
        "num_active_depots": num_active_depots,
        "travel": travel,                      # (D, S) periods
        "depot_inflows": np.full(time_horizon, 2, int),
    }


def sample_scenario(inst: dict, scennum: int, seedoffset: int = 0):
    """(lives (T, S), deadline (S,)): household sizes ~ Poisson(2)+1,
    survival deadlines ~ scaled Pareto (ref:generate_data.py
    RESCUE_PARTY_SIZE / EMERGENCY_SUPPLIES_STOCK)."""
    T, S = inst["time_horizon"], inst["num_sites"]
    rng = np.random.RandomState(7_000 + scennum + seedoffset)
    sizes = rng.poisson(2.0, size=S) + 1
    deadline = np.minimum(T, np.ceil(
        (1.0 + rng.pareto(1.0, size=S)) * (T / 3.0))).astype(int)
    lives = np.zeros((T, S))
    for s in range(S):
        lives[:deadline[s], s] = sizes[s]
    return lives, deadline


def scenario_creator(scenario_name: str, instance: dict | None = None,
                     num_scens: int | None = None, seedoffset: int = 0,
                     lp_relax: bool = False, **_ignored) -> ScenarioSpec:
    inst = instance or generate_instance()
    scennum = extract_num(scenario_name)
    lives, _ = sample_scenario(inst, scennum, seedoffset)
    D, S, T = inst["num_depots"], inst["num_sites"], inst["time_horizon"]
    travel = inst["travel"]
    n = D + T * D * S

    def xcol(t, d, s):
        return D + (t * D + d) * S + s

    # objective: maximize saved lives -> minimize -lives at arrival time
    c = np.zeros(n)
    for t in range(T):
        for d in range(D):
            for s in range(S):
                ta = t + travel[d, s]
                if ta < T:
                    c[xcol(t, d, s)] = -lives[ta, s]
    l = np.zeros(n)  # noqa: E741
    u = np.ones(n)

    rows = []
    bl, bu = [], []
    # activation count (equality)
    r = np.zeros(n)
    r[:D] = 1.0
    rows.append(r)
    bl.append(float(inst["num_active_depots"]))
    bu.append(float(inst["num_active_depots"]))
    # linking: sum_t,s x_{t,d,s} <= T * inflow * active_d  (aggregated
    # big-M link; exact per-(t,d,s) links would be T*D*S rows — the
    # aggregate plus the outflow caps gives the same integer hull here
    # because inflow caps already bound per-period departures)
    for d in range(D):
        r = np.zeros(n)
        r[d] = -float(T * int(inst["depot_inflows"].max()))
        for t in range(T):
            for s in range(S):
                r[xcol(t, d, s)] = 1.0
        rows.append(r)
        bl.append(-np.inf)
        bu.append(0.0)
    # per-period outflow caps
    for t in range(T):
        r = np.zeros(n)
        for d in range(D):
            for s in range(S):
                r[xcol(t, d, s)] = 1.0
        rows.append(r)
        bl.append(-np.inf)
        bu.append(float(inst["depot_inflows"][t]))
    # visit each site at most once
    for s in range(S):
        r = np.zeros(n)
        for t in range(T):
            for d in range(D):
                r[xcol(t, d, s)] = 1.0
        rows.append(r)
        bl.append(-np.inf)
        bu.append(1.0)

    integer = np.ones(n, bool)
    if lp_relax:
        integer = np.zeros(n, bool)
    return ScenarioSpec(
        name=scenario_name, c=c, A=np.asarray(rows),
        bl=np.asarray(bl), bu=np.asarray(bu), l=l, u=u,
        nonant_idx=np.arange(D, dtype=np.int32),
        probability=None if num_scens is None else 1.0 / num_scens,
        integer=integer,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(cfg):
    return {"num_scens": cfg.get("num_scens")}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
