###############################################################################
# uc: stochastic unit commitment, generated natively as sparse BoxQP
# scenario specs (no Pyomo/egret).  The reference drives egret-built
# Pyomo UC models through PH/FWPH cylinders
# (ref:examples/uc/uc_funcs.py, paper runs
# ref:paperruns/larger_uc/uc_cylinders.py) with demand scenarios; this
# is a native generator with the same decision structure:
#
#   first stage  (nonant): commitment u_{g,t} in {0,1}, all hours
#   first stage  (implied): startup v_{g,t}, shutdown w_{g,t} in [0,1]
#                 (continuous: integral whenever u is — Rajan-Takriti)
#   second stage:          dispatch p_{g,t} >= 0, load shed s_t >= 0,
#                          reserve shortfall r_t >= 0
#   gen limits:  Pmin_g u_{g,t} <= p_{g,t} <= Pmax_g u_{g,t}
#   balance:     sum_g p_{g,t} + s_t = d_t^scen
#   ramping:     |p_{g,t} - p_{g,t-1}| <= R_g
#   state:       u_{g,t} - u_{g,t-1} - v_{g,t} + w_{g,t} = 0  (u_{g,-1}=0)
#   min-up:      sum_{tau in (t-UT_g, t]} v_{g,tau} <= u_{g,t}
#   min-down:    sum_{tau in (t-DT_g, t]} w_{g,tau} <= 1 - u_{g,t}
#   reserve:     sum_g Pmax_g u_{g,t} + r_t >= (1+rho_r) d_t^scen
#   objective:   sum cfix_g u + cstart_g v + cvar_g p
#                + VOLL * s + CRSV * r
#
# (min-up/down are the turn-on/turn-off inequalities of Rajan &
# Takriti's strong formulation — the same constraint family egret's UC
# uses, ref:examples/uc/uc_funcs.py params min_up_time/min_down_time;
# startup costs ref egret startup_cost; reserves ref uc_funcs
# reserve_factor.)
#
#   randomness: hourly demand d^scen = profile * seeded per-scenario
#   multiplicative AR(1) noise — only balance/reserve RHS vary, so the
#   sparse constraint matrix is SHARED across all scenarios (one ELL
#   block in HBM regardless of scenario count).
#
# Scales to the paper-run regime (10-100 units, 24-48 hours,
# 100-1000+ scenarios, ref:paperruns/larger_uc/quartz/100scen_fw).
###############################################################################
from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num

_VOLL = 5000.0    # $/MWh unserved energy
_CRSV = 1100.0    # $/MWh reserve shortfall (well below VOLL)


def synthetic_instance(n_gens: int = 10, n_hours: int = 24,
                       seed: int = 0) -> dict:
    """Seeded fleet + demand profile (deterministic given the seed)."""
    rng = np.random.RandomState(seed)
    pmax = rng.uniform(50.0, 300.0, n_gens)
    inst = {
        "n_gens": n_gens,
        "n_hours": n_hours,
        "pmax": pmax,
        "pmin": 0.3 * pmax,
        "ramp": 0.35 * pmax,
        "cvar": rng.uniform(10.0, 40.0, n_gens),     # $/MWh
        "cfix": rng.uniform(300.0, 1200.0, n_gens),  # $/h committed
        # startup costs scale with unit size (cold-start heuristic)
        "cstart": rng.uniform(2.0, 8.0, n_gens) * pmax,
        # bigger units cycle slower
        "min_up": np.clip((pmax / 80.0).astype(int) + 1, 1, 8),
        "min_down": np.clip((pmax / 100.0).astype(int) + 1, 1, 6),
        "reserve_frac": 0.1,
        # diurnal profile peaking at ~70% of fleet capacity
        "profile": 0.5 * pmax.sum()
        * (1.0 + 0.35 * np.sin(2.0 * np.pi
                               * (np.arange(n_hours) - 6.0) / 24.0)),
        "seed": seed,
    }
    return inst


def scenario_demand(inst: dict, scennum: int) -> np.ndarray:
    """Multiplicative AR(1) demand noise, seeded per scenario."""
    rng = np.random.RandomState(1_000_003 * (inst["seed"] + 1) + scennum)
    eps = np.zeros(inst["n_hours"])
    for t in range(inst["n_hours"]):
        eps[t] = (0.6 * eps[t - 1] if t else 0.0) + rng.normal(0.0, 0.05)
    return inst["profile"] * (1.0 + eps)


def mpc_instance(instance: dict, step: int, stride: int = 1) -> dict:
    """Window `step` of the rolling horizon (mpc/horizon.py): the SAME
    fleet with the demand profile advanced stride*step hours (periodic
    diurnal extension) and the step recorded so scenario_creator re-keys
    the AR(1) noise through fold_in(base, step).  The shared-structure
    cache is carried over: structure depends on the profile only through
    profile.max() (the shed bound), which a roll preserves — so every
    window of a stream shares one sparse A build."""
    inst = dict(instance)
    inst["profile"] = np.roll(instance["profile"],
                              -int(stride) * int(step))
    inst["mpc_step"] = int(step)
    inst["mpc_stride"] = int(stride)
    return inst


def _mpc_demand(inst: dict, scennum: int) -> np.ndarray:
    """Step-re-keyed demand: the scenario_program sampler's EXACT f32
    jnp ops (W_ar weight sum over threefry normals), eagerly, with the
    base key folded to the window's step first — so a serve stream's
    demand is bit-identical to ScenarioProgram.advance(step) synthesis,
    and eager dispatch (cached by shape, not by closure identity) keeps
    warm windows recompile-free."""
    import jax
    import jax.numpy as jnp
    from jax import random as jrandom

    from mpisppy_tpu.scengen.program import scen_key

    T = inst["n_hours"]
    key = jrandom.PRNGKey(inst["seed"])
    if inst["mpc_step"]:
        key = jax.random.fold_in(key, inst["mpc_step"])
    z = jrandom.normal(scen_key(key, scennum), (T,), jnp.float32) * 0.05
    t_ix = np.arange(T)
    W_ar = np.where(t_ix[None, :] <= t_ix[:, None],
                    0.6 ** (t_ix[:, None] - t_ix[None, :]), 0.0)
    eps = jnp.sum(jnp.asarray(W_ar, jnp.float32) * z[None, :], axis=-1)
    d = jnp.asarray(inst["profile"], jnp.float32) * (1.0 + eps)
    return np.asarray(d, np.float64)


def _shared_structure(inst: dict):
    """(A, c, l, u, integer, nonant_idx, row markers) —
    scenario-independent; cached on the instance dict so the batch
    compiler's shared-object fast path sees one sparse A for the whole
    batch.  Column layout (g-major time blocks):
      [0:nU)          u_{g,t} commitment        {0,1}   <- nonants
      [nU:2nU)        p_{g,t} dispatch          [0,Pmax]
      [2nU:2nU+T)     s_t load shed             [0,inf)
      [2nU+T:3nU+T)   v_{g,t} startup           [0,1]
      [3nU+T:4nU+T)   w_{g,t} shutdown          [0,1]
      [4nU+T:4nU+2T)  r_t reserve shortfall     [0,inf)
    """
    if "_spec_cache" in inst:
        return inst["_spec_cache"]
    G, T = inst["n_gens"], inst["n_hours"]
    nU = G * T
    U0, P0, S0 = 0, nU, 2 * nU
    V0, W0, R0 = 2 * nU + T, 3 * nU + T, 4 * nU + T
    n = 4 * nU + 2 * T

    rows, cols, vals = [], [], []
    r = 0
    # pmax: p - Pmax u <= 0 ; pmin: Pmin u - p <= 0
    for g in range(G):
        for t in range(T):
            rows += [r, r]
            cols += [P0 + g * T + t, U0 + g * T + t]
            vals += [1.0, -inst["pmax"][g]]
            r += 1
    for g in range(G):
        for t in range(T):
            rows += [r, r]
            cols += [U0 + g * T + t, P0 + g * T + t]
            vals += [inst["pmin"][g], -1.0]
            r += 1
    # balance rows (RHS varies per scenario)
    bal0 = r
    for t in range(T):
        for g in range(G):
            rows.append(r)
            cols.append(P0 + g * T + t)
            vals.append(1.0)
        rows.append(r)
        cols.append(S0 + t)
        vals.append(1.0)
        r += 1
    # ramping
    for g in range(G):
        for t in range(1, T):
            rows += [r, r]
            cols += [P0 + g * T + t, P0 + g * T + t - 1]
            vals += [1.0, -1.0]
            r += 1
            rows += [r, r]
            cols += [P0 + g * T + t - 1, P0 + g * T + t]
            vals += [1.0, -1.0]
            r += 1
    # commitment state logic: u_t - u_{t-1} - v_t + w_t = 0 (u_{-1} = 0)
    state0 = r
    for g in range(G):
        for t in range(T):
            rows.append(r)
            cols.append(U0 + g * T + t)
            vals.append(1.0)
            if t > 0:
                rows.append(r)
                cols.append(U0 + g * T + t - 1)
                vals.append(-1.0)
            rows += [r, r]
            cols += [V0 + g * T + t, W0 + g * T + t]
            vals += [-1.0, 1.0]
            r += 1
    # min-up:  sum_{tau=max(0,t-UT+1)..t} v_tau - u_t <= 0
    for g in range(G):
        UT = int(inst["min_up"][g])
        for t in range(T):
            for tau in range(max(0, t - UT + 1), t + 1):
                rows.append(r)
                cols.append(V0 + g * T + tau)
                vals.append(1.0)
            rows.append(r)
            cols.append(U0 + g * T + t)
            vals.append(-1.0)
            r += 1
    # min-down: sum_{tau=max(0,t-DT+1)..t} w_tau + u_t <= 1
    for g in range(G):
        DT = int(inst["min_down"][g])
        for t in range(T):
            for tau in range(max(0, t - DT + 1), t + 1):
                rows.append(r)
                cols.append(W0 + g * T + tau)
                vals.append(1.0)
            rows.append(r)
            cols.append(U0 + g * T + t)
            vals.append(1.0)
            r += 1
    # spinning reserve: -sum_g Pmax_g u_{g,t} - r_t <= -(1+rho) d_t
    rsv0 = r
    for t in range(T):
        for g in range(G):
            rows.append(r)
            cols.append(U0 + g * T + t)
            vals.append(-inst["pmax"][g])
        rows.append(r)
        cols.append(R0 + t)
        vals.append(-1.0)
        r += 1
    m = r
    A = sps.csr_matrix((vals, (rows, cols)), shape=(m, n))

    c = np.zeros(n)
    for g in range(G):
        c[U0 + g * T:U0 + (g + 1) * T] = inst["cfix"][g]
        c[P0 + g * T:P0 + (g + 1) * T] = inst["cvar"][g]
        c[V0 + g * T:V0 + (g + 1) * T] = inst["cstart"][g]
    c[S0:S0 + T] = _VOLL
    c[R0:R0 + T] = _CRSV

    l = np.zeros(n)  # noqa: E741
    u = np.ones(n)
    for g in range(G):
        u[P0 + g * T:P0 + (g + 1) * T] = inst["pmax"][g]
    u[S0:S0 + T] = inst["profile"].max() * 2.0   # shed <= any demand
    u[R0:R0 + T] = inst["pmax"].sum()            # shortfall <= requirement

    integer = np.zeros(n, bool)
    integer[U0:U0 + nU] = True
    nonant_idx = np.arange(nU, dtype=np.int32)
    inst["_spec_cache"] = (A, c, l, u, integer, nonant_idx, bal0, rsv0, m)
    return inst["_spec_cache"]


def scenario_creator(scenario_name: str, instance: dict | None = None,
                     num_scens: int | None = None, lp_relax: bool = True,
                     n_gens: int = 10, n_hours: int = 24, seed: int = 0,
                     **_ignored) -> ScenarioSpec:
    """Zero-based Scenario<k> names (ref:examples/uc convention)."""
    if instance is None:
        instance = synthetic_instance(n_gens, n_hours, seed)
    A, c, l, u, integer, nonant_idx, bal0, rsv0, m = \
        _shared_structure(instance)
    T = instance["n_hours"]
    k = extract_num(scenario_name)
    d = _mpc_demand(instance, k) if "mpc_step" in instance \
        else scenario_demand(instance, k)

    bl = np.full(m, -np.inf)
    bu = np.zeros(m)
    bl[bal0:bal0 + T] = d
    bu[bal0:bal0 + T] = d
    # ramp rows upper bounds
    G = instance["n_gens"]
    rr = bal0 + T
    for g in range(G):
        bu[rr:rr + 2 * (T - 1)] = instance["ramp"][g]
        rr += 2 * (T - 1)
    # state rows are equalities (== 0); they follow the ramp block
    nU = G * T
    bl[rr:rr + nU] = 0.0
    # min-up rows: <= 0 (already); min-down rows: <= 1
    md0 = rr + nU + nU
    bu[md0:md0 + nU] = 1.0
    # reserve rows: -cap - r <= -(1 + rho) d
    bu[rsv0:rsv0 + T] = -(1.0 + instance["reserve_frac"]) * d

    integer_eff = integer if not lp_relax else np.zeros_like(integer)
    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=nonant_idx,
        probability=None if num_scens is None else 1.0 / num_scens,
        integer=integer_eff,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


# --------------------------------------------------------------------------
# Seeded scenario synthesis (scengen branch; docs/scengen.md).
#
# uc randomness is RHS-only (hourly demand): the sparse shared A stays
# one ELL block for any scenario count and the program varies (bl, bu).
# The AR(1) demand noise eps_t = 0.6 eps_{t-1} + z_t is expressed in
# closed form as a lower-triangular weight sum over the i.i.d. normals
# (eps = sum_j 0.6^{t-j} z_j), drawn from threefry — elementwise ops
# only, so vmapped synthesis bit-matches the per-scenario host path.
# --------------------------------------------------------------------------
def scenario_program(num_scens: int, seed: int = 0, start: int = 0,
                     n_gens: int = 10, n_hours: int = 24,
                     inst_seed: int = 0, lp_relax: bool = True,
                     instance: dict | None = None):
    """ScenarioProgram drawing the demand path through scengen keys."""
    import jax.numpy as jnp
    from jax import random as jrandom

    from mpisppy_tpu.scengen.program import ScenarioProgram, scen_key

    inst = instance if instance is not None \
        else synthetic_instance(n_gens, n_hours, inst_seed)
    A, c, l, u, integer, nonant_idx, bal0, rsv0, m = \
        _shared_structure(inst)
    G, T = inst["n_gens"], inst["n_hours"]

    # deterministic bound skeleton (scenario_creator with the demand
    # rows left for the sampler)
    bl0 = np.full(m, -np.inf)
    bu0 = np.zeros(m)
    rr = bal0 + T
    for g in range(G):
        bu0[rr:rr + 2 * (T - 1)] = inst["ramp"][g]
        rr += 2 * (T - 1)
    nU = G * T
    bl0[rr:rr + nU] = 0.0
    md0 = rr + nU + nU
    bu0[md0:md0 + nU] = 1.0

    bl0_f = jnp.asarray(bl0, jnp.float32)
    bu0_f = jnp.asarray(bu0, jnp.float32)
    profile_f = jnp.asarray(inst["profile"], jnp.float32)
    # AR(1) unrolled: weights[t, j] = 0.6^(t-j) for j <= t
    t_ix = np.arange(T)
    W_ar = np.where(t_ix[None, :] <= t_ix[:, None],
                    0.6 ** (t_ix[:, None] - t_ix[None, :]), 0.0)
    W_ar_f = jnp.asarray(W_ar, jnp.float32)
    rsv_fac = float(1.0 + inst["reserve_frac"])

    def sampler(base_key, idx):
        z = jrandom.normal(scen_key(base_key, idx), (T,),
                           jnp.float32) * 0.05
        eps = jnp.sum(W_ar_f * z[None, :], axis=-1)
        d = profile_f * (1.0 + eps)
        bl = bl0_f.at[bal0:bal0 + T].set(d)
        bu = bu0_f.at[bal0:bal0 + T].set(d)
        bu = bu.at[rsv0:rsv0 + T].set(-rsv_fac * d)
        return {"bl": bl, "bu": bu}

    integer_eff = np.zeros_like(integer) if lp_relax else integer
    return ScenarioProgram(
        name="uc", num_scenarios=int(num_scens),
        base_seed=int(seed), start=int(start),
        template={"c": c, "A": A, "bl": bl0, "bu": bu0, "l": l, "u": u},
        varying=("bl", "bu"), sampler=sampler,
        nonant_idx=np.asarray(nonant_idx, np.int32),
        integer=integer_eff,
    )


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("uc_n_gens", "number of thermal units", int, 10)
    cfg.add_to_config("uc_n_hours", "scheduling horizon (hours)", int, 24)
    cfg.add_to_config("uc_seed", "instance seed", int, 0)
    cfg.add_to_config("uc_mpc_step",
                      "rolling-horizon window index (mpc/): >= 0 rolls "
                      "the profile and re-keys demand per step; -1 = "
                      "not a rolling window", int, -1)
    cfg.add_to_config("uc_mpc_stride",
                      "hours the rolling window advances per step",
                      int, 1)


def kw_creator(cfg):
    instance = synthetic_instance(cfg.get("uc_n_gens", 10),
                                  cfg.get("uc_n_hours", 24),
                                  cfg.get("uc_seed", 0))
    if cfg.get("uc_mpc_step", -1) >= 0:
        instance = mpc_instance(instance, cfg["uc_mpc_step"],
                                cfg.get("uc_mpc_stride", 1))
    return {
        "instance": instance,
        "num_scens": int(cfg["num_scens"]),
        "lp_relax": True,
    }


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
