###############################################################################
# uc: stochastic unit commitment, generated natively as sparse BoxQP
# scenario specs (no Pyomo/egret).  The reference drives egret-built
# Pyomo UC models through PH/FWPH cylinders
# (ref:examples/uc/uc_funcs.py, paper runs
# ref:paperruns/larger_uc/uc_cylinders.py) with demand scenarios; this
# is a native generator with the same decision structure:
#
#   first stage  (nonant): commitment u_{g,t} in {0,1}, all hours
#   second stage:          dispatch  p_{g,t} >= 0, load shed s_t >= 0
#   gen limits:  Pmin_g u_{g,t} <= p_{g,t} <= Pmax_g u_{g,t}
#   balance:     sum_g p_{g,t} + s_t = d_t^scen
#   ramping:     |p_{g,t} - p_{g,t-1}| <= R_g
#   objective:   sum fixed_g u + c_g p + VOLL * s
#
#   randomness: hourly demand d^scen = profile * seeded per-scenario
#   multiplicative AR(1) noise — only the balance RHS varies, so the
#   sparse constraint matrix is SHARED across all scenarios (one ELL
#   block in HBM regardless of scenario count).
#
# Scales to the paper-run regime (10-100 units, 24-48 hours,
# 100-1000+ scenarios, ref:paperruns/larger_uc/quartz/100scen_fw).
###############################################################################
from __future__ import annotations

import numpy as np
import scipy.sparse as sps

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num

_VOLL = 5000.0


def synthetic_instance(n_gens: int = 10, n_hours: int = 24,
                       seed: int = 0) -> dict:
    """Seeded fleet + demand profile (deterministic given the seed)."""
    rng = np.random.RandomState(seed)
    pmax = rng.uniform(50.0, 300.0, n_gens)
    inst = {
        "n_gens": n_gens,
        "n_hours": n_hours,
        "pmax": pmax,
        "pmin": 0.3 * pmax,
        "ramp": 0.35 * pmax,
        "cvar": rng.uniform(10.0, 40.0, n_gens),     # $/MWh
        "cfix": rng.uniform(300.0, 1200.0, n_gens),  # $/h committed
        # diurnal profile peaking at ~70% of fleet capacity
        "profile": 0.5 * pmax.sum()
        * (1.0 + 0.35 * np.sin(2.0 * np.pi
                               * (np.arange(n_hours) - 6.0) / 24.0)),
        "seed": seed,
    }
    return inst


def scenario_demand(inst: dict, scennum: int) -> np.ndarray:
    """Multiplicative AR(1) demand noise, seeded per scenario."""
    rng = np.random.RandomState(1_000_003 * (inst["seed"] + 1) + scennum)
    eps = np.zeros(inst["n_hours"])
    for t in range(inst["n_hours"]):
        eps[t] = (0.6 * eps[t - 1] if t else 0.0) + rng.normal(0.0, 0.05)
    return inst["profile"] * (1.0 + eps)


def _shared_structure(inst: dict):
    """(A, c, l, u, integer, nonant_idx) — scenario-independent; cached
    on the instance dict so the batch compiler's shared-object fast path
    sees one sparse A for the whole batch."""
    if "_spec_cache" in inst:
        return inst["_spec_cache"]
    G, T = inst["n_gens"], inst["n_hours"]
    nU = G * T
    U0, P0, S0 = 0, nU, 2 * nU      # u (g-major: g*T+t), p, shed
    n = 2 * nU + T

    rows, cols, vals = [], [], []
    r = 0
    # pmax: p - Pmax u <= 0 ; pmin: Pmin u - p <= 0
    for g in range(G):
        for t in range(T):
            rows += [r, r]
            cols += [P0 + g * T + t, U0 + g * T + t]
            vals += [1.0, -inst["pmax"][g]]
            r += 1
    for g in range(G):
        for t in range(T):
            rows += [r, r]
            cols += [U0 + g * T + t, P0 + g * T + t]
            vals += [inst["pmin"][g], -1.0]
            r += 1
    # balance rows (RHS varies per scenario)
    bal0 = r
    for t in range(T):
        for g in range(G):
            rows.append(r)
            cols.append(P0 + g * T + t)
            vals.append(1.0)
        rows.append(r)
        cols.append(S0 + t)
        vals.append(1.0)
        r += 1
    # ramping
    for g in range(G):
        for t in range(1, T):
            rows += [r, r]
            cols += [P0 + g * T + t, P0 + g * T + t - 1]
            vals += [1.0, -1.0]
            r += 1
            rows += [r, r]
            cols += [P0 + g * T + t - 1, P0 + g * T + t]
            vals += [1.0, -1.0]
            r += 1
    m = r
    A = sps.csr_matrix((vals, (rows, cols)), shape=(m, n))

    c = np.zeros(n)
    for g in range(G):
        c[U0 + g * T:U0 + (g + 1) * T] = inst["cfix"][g]
        c[P0 + g * T:P0 + (g + 1) * T] = inst["cvar"][g]
    c[S0:S0 + T] = _VOLL

    l = np.zeros(n)  # noqa: E741
    u = np.ones(n)
    for g in range(G):
        u[P0 + g * T:P0 + (g + 1) * T] = inst["pmax"][g]
    u[S0:S0 + T] = np.inf

    integer = np.zeros(n, bool)
    integer[U0:U0 + nU] = True
    nonant_idx = np.arange(nU, dtype=np.int32)
    inst["_spec_cache"] = (A, c, l, u, integer, nonant_idx, bal0, m)
    return inst["_spec_cache"]


def scenario_creator(scenario_name: str, instance: dict | None = None,
                     num_scens: int | None = None, lp_relax: bool = True,
                     n_gens: int = 10, n_hours: int = 24, seed: int = 0,
                     **_ignored) -> ScenarioSpec:
    """Zero-based Scenario<k> names (ref:examples/uc convention)."""
    if instance is None:
        instance = synthetic_instance(n_gens, n_hours, seed)
    A, c, l, u, integer, nonant_idx, bal0, m = _shared_structure(instance)
    T = instance["n_hours"]
    k = extract_num(scenario_name)
    d = scenario_demand(instance, k)

    bl = np.full(m, -np.inf)
    bu = np.zeros(m)
    bl[bal0:bal0 + T] = d
    bu[bal0:bal0 + T] = d
    # ramp rows upper bounds
    G = instance["n_gens"]
    rr = bal0 + T
    for g in range(G):
        bu[rr:rr + 2 * (T - 1)] = instance["ramp"][g]
        rr += 2 * (T - 1)

    integer_eff = integer if not lp_relax else np.zeros_like(integer)
    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=nonant_idx,
        probability=None if num_scens is None else 1.0 / num_scens,
        integer=integer_eff,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"Scenario{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()
    cfg.add_to_config("uc_n_gens", "number of thermal units", int, 10)
    cfg.add_to_config("uc_n_hours", "scheduling horizon (hours)", int, 24)
    cfg.add_to_config("uc_seed", "instance seed", int, 0)


def kw_creator(cfg):
    return {
        "instance": synthetic_instance(cfg.get("uc_n_gens", 10),
                                       cfg.get("uc_n_hours", 24),
                                       cfg.get("uc_seed", 0)),
        "num_scens": int(cfg["num_scens"]),
        "lp_relax": True,
    }


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
