###############################################################################
# GBD: Ferguson & Dantzig (1956) aircraft allocation under random route
# demand (ref:mpisppy/tests/examples/gbd/gbd.py; the extended demand
# distributions follow Bayraksan & Morton's sequential-sampling study).
#
# First stage: x_{a,r} aircraft of type a flown on route r (continuous
# nonants; three (a, r) pairs are forbidden and fixed to 0) with
# aircraft-inventory equalities via slack columns.
# Second stage: passenger surplus/deficit slack per route against the
# random demand; deficits cost the route's lost-revenue rate.
#
# Columns (n = 34): [x (20 a-major), acSlack (4), psPos (5), psNeg (5)]
# Rows (m = 9): 4 inventory equalities, 5 demand equalities.
###############################################################################
from __future__ import annotations

import json
import os

import numpy as np

from mpisppy_tpu.core.batch import ScenarioSpec
from mpisppy_tpu.utils.sputils import extract_num

_NUM_AIRCRAFT = np.array([10.0, 19.0, 25.0, 15.0])
# passengers/month (hundreds) per (type, route); row 5 = slack coeff
_P = np.array([
    [16.0, 15.0, 28.0, 23.0, 81.0],
    [0.0, 10.0, 14.0, 15.0, 57.0],
    [0.0, 5.0, 0.0, 7.0, 29.0],
    [9.0, 11.0, 22.0, 17.0, 55.0],
    [1.0, 1.0, 1.0, 1.0, 1.0],
])
# $k/month per (type, route); row 5 = lost revenue per deficit unit
_C = np.array([
    [18.0, 21.0, 18.0, 16.0, 10.0],
    [0.0, 15.0, 16.0, 14.0, 9.0],
    [0.0, 10.0, 0.0, 9.0, 6.0],
    [17.0, 16.0, 17.0, 15.0, 10.0],
    [13.0, 13.0, 7.0, 7.0, 1.0],
])
_FORBIDDEN = [(1, 0), (2, 0), (2, 2)]  # (type, route), 0-indexed

# Original 1956 route-demand distributions (public data; the reference's
# gbd_extended_data.json is used instead when available).
_DEMANDS_1956 = ([20, 22, 25, 27, 30], [5, 15], [14, 16, 18, 20, 22],
                 [1, 5, 8, 10, 34], [58, 60, 62])
_PROBS_1956 = ([.2, .05, .35, .2, .2], [.3, .7], [.1, .2, .4, .2, .1],
               [.2, .2, .3, .2, .1], [.1, .8, .1])

_EXT_PATH = ("/root/reference/mpisppy/tests/examples/gbd/gbd_data/"
             "gbd_extended_data.json")


def _distributions(data_path: str | None = None):
    path = data_path or _EXT_PATH
    if os.path.exists(path):
        with open(path) as f:
            d = json.load(f)
        dmds = tuple(np.asarray(d[f"r{i + 1}_dmds"], float)
                     for i in range(5))
        prbs = tuple(np.asarray(d[f"r{i + 1}_prbs"], float)
                     for i in range(5))
        return dmds, prbs
    return (tuple(np.asarray(v, float) for v in _DEMANDS_1956),
            tuple(np.asarray(v, float) for v in _PROBS_1956))


def sample(scennum: int, data_path: str | None = None) -> np.ndarray:
    """(5,) route demands drawn with the reference's stream (flipped
    cumulative trick included, ref:gbd.py demands_init)."""
    dmds, prbs = _distributions(data_path)
    rng = np.random.RandomState(scennum)
    r = rng.rand(5)
    out = np.empty(5)
    for g in range(5):
        cum = np.flip(np.cumsum(np.flip(prbs[g])))
        j = int(np.searchsorted(np.flip(cum), r[g]))
        out[g] = dmds[g][len(cum) - 1 - j]
    return out


def scenario_creator(scenario_name: str, num_scens: int | None = None,
                     data_path: str | None = None,
                     **_ignored) -> ScenarioSpec:
    scennum = extract_num(scenario_name)
    demand = sample(scennum, data_path)
    n = 20 + 4 + 5 + 5
    c = np.zeros(n)
    c[:20] = _C[:4].reshape(-1)          # a-major x costs
    c[24:29] = _C[4]                     # psPos: deficit lost revenue
    l = np.zeros(n)  # noqa: E741
    u = np.full(n, np.inf)
    u[:20] = np.repeat(_NUM_AIRCRAFT, 5)
    u[20:24] = _NUM_AIRCRAFT
    u[24:29] = 400.0    # deficit <= max demand (314 in the extended data)
    u[29:34] = 5000.0   # surplus bound: full fleet on one route
    for (a, r) in _FORBIDDEN:
        u[5 * a + r] = 0.0
    A = np.zeros((9, n))
    for a in range(4):
        A[a, 5 * a:5 * a + 5] = 1.0
        A[a, 20 + a] = 1.0
    for r in range(5):
        for a in range(4):
            A[4 + r, 5 * a + r] = _P[a, r]
        A[4 + r, 24 + r] = _P[4, r]      # psPos: fills a deficit (costed)
        A[4 + r, 29 + r] = -_P[4, r]     # psNeg: absorbs surplus (free)
    bl = np.concatenate([_NUM_AIRCRAFT, demand])
    bu = bl.copy()
    return ScenarioSpec(
        name=scenario_name, c=c, A=A, bl=bl, bu=bu, l=l, u=u,
        nonant_idx=np.arange(20, dtype=np.int32),
        probability=None if num_scens is None else 1.0 / num_scens,
    )


def scenario_names_creator(num_scens: int, start: int | None = None):
    start = 0 if start is None else start
    return [f"scen{i}" for i in range(start, start + num_scens)]


def inparser_adder(cfg):
    cfg.num_scens_required()


def kw_creator(cfg):
    return {"num_scens": cfg.get("num_scens")}


def scenario_denouement(rank, scenario_name, spec, x=None):
    pass
