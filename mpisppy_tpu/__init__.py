###############################################################################
# mpisppy-tpu: TPU-native stochastic programming (scenario decomposition)
#
# A from-scratch JAX/XLA re-design of the capabilities of mpi-sppy
# (Pyomo/mpi-sppy).  Scenario subproblems are batched into vmapped
# first-order LP/QP solves over an HBM-resident scenario tensor sharded
# across a TPU mesh; nonanticipativity reductions use XLA collectives
# instead of MPI allreduce.
#
# Reference parity notes cite files in the reference repo as
# ``ref:<path>:<lines>`` (e.g. ref:mpisppy/phbase.py:32-112).
###############################################################################
import time as _time

__version__ = "0.1.0"

_T0 = _time.time()


def global_toc(msg: str, cond: bool = True) -> None:
    """Timestamped progress logging (ref:mpisppy/__init__.py:16-22).

    The reference gates on ``rank == 0``; here there is a single
    controller process, so ``cond`` is caller-supplied (default True).
    Routed through the telemetry console (telemetry/console.py): with
    no telemetry configured the output format is unchanged; with a
    configured bus every line also lands in the JSONL trace.
    """
    if cond:
        from mpisppy_tpu.telemetry import console
        console.log(msg)
