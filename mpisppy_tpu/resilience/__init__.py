from mpisppy_tpu.resilience.faults import (  # noqa: F401
    CheckpointFault, FaultPlan, LaneFault, PreemptionError,
    SimulatedPreemption, SpokeBoundFault,
)
