from mpisppy_tpu.resilience.faults import (  # noqa: F401
    CheckpointFault, DispatchFault, DispatchPoison, FaultPlan, LaneFault,
    MeshFault, PreemptionError, ReplicaFault, ServeFault,
    SimulatedPreemption, SpokeBoundFault,
)
from mpisppy_tpu.resilience.watchdog import HubWatchdog  # noqa: F401
