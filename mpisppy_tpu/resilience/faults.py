###############################################################################
# Deterministic fault injection for the cylinder wheel.
#
# The reference wheel survives solver/license hiccups with per-scenario
# solve retries (ref:mpisppy/spopt.py:931-960) and tolerates slow or
# dead cylinders by never reading stale RMA windows.  The TPU wheel's
# failure modes are different — a NaN spoke bound, a diverged PDHG lane,
# a preemption mid-run (the dominant failure on real TPU pools, cf. the
# restarted-PDHG robustness discussion in MPAX, arXiv:2412.09734) — and
# a fault model you cannot *inject* is a fault model you cannot test.
#
# A FaultPlan arms named HOST-SIDE seams:
#
#   * spoke harvest   — poison a harvested bound (NaN / wrong-sense /
#                       stale) between `sp.harvest()` and the hub's
#                       bound bookkeeping (hub._harvest_all);
#   * PDHG lanes      — scale or NaN chosen scenario lanes of the hub
#                       solver state at a hub iteration, forcing the
#                       per-lane divergence guard in ops/pdhg.py to fire
#                       at the next restart boundary (hub.sync);
#   * checkpoint      — tear (truncate) or corrupt (bit-flip) a rotated
#                       checkpoint file right after it lands on disk
#                       (hub._write_checkpoint);
#   * preemption      — raise SimulatedPreemption at hub iteration k
#                       (hub.sync), exercising the emergency-save +
#                       restore-from-checkpoint path end to end;
#   * dispatch        — fault the solve-dispatch layer (ISSUE 9,
#                       docs/dispatch.md failure semantics): hang a
#                       megabatch dispatch, raise from it, poison a
#                       specific submitted request (raises every time
#                       its lanes are in the batch — the bisection
#                       quarantine's target), drop a ticket's result
#                       delivery, jitter the "device" with slow sleeps,
#                       or kill the dispatcher daemon thread
#                       (dispatch/scheduler.py seams).
#
# Every seam is a plain Python call on the host driver loop: NOTHING
# enters the jitted graph, so a disarmed (or absent) plan has zero
# overhead and zero trace impact — the jitted step HLO is byte-identical
# with and without the resilience layer (tests/test_chaos.py asserts
# this).  Injection is deterministic: seams fire at configured hub
# iterations / write indices, and any randomness (corruption offsets)
# comes from the plan's own seeded generator.
###############################################################################
from __future__ import annotations

import dataclasses

import numpy as np


class PreemptionError(RuntimeError):
    """The run must stop NOW and persist state (SIGTERM/SIGINT on a
    preemptible pool, or a simulated preemption from a FaultPlan).
    WheelSpinner.spin catches this, writes a synchronous emergency
    checkpoint, and re-raises so the caller can exit/restart."""


class SimulatedPreemption(PreemptionError):
    """Preemption injected by a FaultPlan (not a real signal)."""


@dataclasses.dataclass(frozen=True)
class SpokeBoundFault:
    """Poison a spoke's harvested bound at the hub harvest seam.

    kind: 'nan'          -> bound becomes NaN
          'wrong_sense'  -> outer bounds jump UP past the incumbent,
                            inner bounds jump DOWN past the outer bound
                            (sense-violating by `magnitude`)
          'stale'        -> re-deliver the first bound ever harvested
                            from this spoke (a slow cylinder's old
                            window content)
    spoke_index: which spoke (position in hub.spokes); None = every one.
    at_iters: hub iterations to fire on; empty = every iteration.
    """

    kind: str
    spoke_index: int | None = None
    at_iters: tuple[int, ...] = ()
    magnitude: float = 1e8

    def __post_init__(self):
        if self.kind not in ("nan", "wrong_sense", "stale"):
            raise ValueError(f"unknown spoke-bound fault {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class LaneFault:
    """Corrupt chosen scenario lanes of the hub's PDHG solver state at
    hub iteration `at_iter` (host-side, between jitted steps).

    mode: 'scale' multiplies x/y by `scale` (forces the magnitude
    branch of the lane guard); 'nan' sets them to NaN (forces the
    non-finite branch — NaN never self-heals, so recovery proves the
    quarantine reset works)."""

    at_iter: int
    lanes: tuple[int, ...]
    mode: str = "scale"
    scale: float = 1e25

    def __post_init__(self):
        if self.mode not in ("scale", "nan"):
            raise ValueError(f"unknown lane fault mode {self.mode!r}")


class DispatchPoison(RuntimeError):
    """Injected NaN-poisoned-batch analog: the dispatch raises whenever
    the poisoned submit's lanes ride in the megabatch, so retry never
    clears it and only bisection can isolate it (dispatch/scheduler.py
    _solve_recover)."""


@dataclasses.dataclass(frozen=True)
class DispatchFault:
    """One dispatch-layer fault (host-only seams inside
    dispatch/scheduler.py; zero jit-graph impact — the seams run on the
    host dispatch path around `solve_fn`, never inside it).

    kind: 'hang'            -> the dispatch blocks for hang_s seconds
                               (exercises the dispatch timeout + retry)
          'exception'       -> the dispatch raises RuntimeError
          'slow'            -> seeded jitter sleep in [0, jitter_s]
                               (a slow device, not a failure)
          'poison'          -> raise DispatchPoison whenever any submit
                               in `submits` rides in the batch — retry
                               cannot clear it; bisection isolates and
                               quarantines exactly those requests
          'drop_ticket'     -> complete the solve but never deliver the
                               result to the `submits` tickets (a lost
                               result; the ticket deadline converts the
                               would-be hang into a typed SolveFailed)
          'kill_dispatcher' -> raise inside the dispatcher daemon loop
                               (thread death; the supervisor must fail
                               queued tickets fast, once)

    at_dispatches: dispatch-attempt indices (0-based, counting every
    attempt including retries) that hang/exception/slow fire on; empty
    means every attempt.  submits: 0-based submit indices (the order
    requests entered `SolveScheduler.submit`) for poison/drop_ticket.
    """

    kind: str
    at_dispatches: tuple[int, ...] = ()
    submits: tuple[int, ...] = ()
    hang_s: float = 3600.0
    jitter_s: float = 0.05

    def __post_init__(self):
        if self.kind not in ("hang", "exception", "slow", "poison",
                             "drop_ticket", "kill_dispatcher"):
            raise ValueError(f"unknown dispatch fault {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class AsyncExchangeFault:
    """One async-exchange fault (ISSUE 11; docs/async_wheel.md): the
    host-side seams of the double-buffered exchange plane in
    algos/async_wheel.AsyncFusedPH + cylinders/hub.AsyncPHHub.

    kind: 'drop_plane_write' -> the due plane write is dropped (the
                                slot keeps its previous generation, so
                                observed staleness exceeds the bound —
                                validity must not depend on it)
          'torn_swap'        -> the slot gets a MIXED plane: duals and
                                primal iterates from the OLD
                                generation, averages from the new (a
                                torn pointer swap)
          'slow_harvest'     -> the host-complete half sleeps delay_s
                                seconds (a slow host; pushed past the
                                watchdog budget this is the wedged
                                exchange the hub watchdog must catch)

    at_iters: hub iterations to fire on; empty = every iteration."""

    kind: str
    at_iters: tuple[int, ...] = ()
    delay_s: float = 0.05

    def __post_init__(self):
        if self.kind not in ("drop_plane_write", "torn_swap",
                             "slow_harvest"):
            raise ValueError(f"unknown async-exchange fault {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class ServeFault:
    """One serve-layer fault (ISSUE 12; docs/serving.md): the
    host-only seams of the multi-tenant wheel server
    (mpisppy_tpu/serve/) and its load harness.

    kind: 'hang'       -> the session's solve blocks hang_s seconds
                          before starting (a wedged worker; the
                          session deadline must convert it to a typed
                          SolveFailed at the client, never a hang)
          'poison'     -> the session's solve raises (a poisoned
                          problem instance; the client observes a
                          typed failure, siblings proceed)
          'disconnect' -> the server drops the session's client
                          connection mid-run (the session must still
                          reach a terminal state and release its
                          tenant quota)
          'flood'      -> the load generator multiplies this tenant's
                          submit count by flood_factor (admission
                          backpressure must reject typed, and healthy
                          tenants' latency must hold — the isolation
                          acceptance line)

    tenant: which tenant's sessions the fault fires on ("" = every
    tenant).  at_sessions: per-tenant session ordinals (0-based, in
    admission order) for hang/poison/disconnect; empty = every
    session of the tenant."""

    kind: str
    tenant: str = ""
    at_sessions: tuple[int, ...] = ()
    hang_s: float = 3600.0
    flood_factor: int = 10

    def __post_init__(self):
        if self.kind not in ("hang", "poison", "disconnect", "flood"):
            raise ValueError(f"unknown serve fault {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class ReplicaFault:
    """One fleet-replica fault (ISSUE 16; docs/serving.md): the
    host-only seams of the fleet router's health plane
    (mpisppy_tpu/fleet/).

    kind: 'kill'           -> the replica dies at its at_beats[0]-th
                              heartbeat: the beat loop stops (the
                              router declares it dead after the miss
                              budget) and no new work is assigned;
                              in-flight sessions drain through the
                              SIGTERM-grace emergency-checkpoint path
                              and migrate to live replicas
          'partition'      -> heartbeats AND router status probes are
                              suppressed while the beat index is
                              inside the at_beats window; a window
                              longer than the miss budget migrates the
                              replica's sessions, and the replica
                              stays FENCED (dead to the router) even
                              after connectivity returns — no split
                              brain, the settle latch still guarantees
                              one terminal outcome if a partitioned
                              worker races a migrated copy
          'slow_heartbeat' -> every beat is delayed delay_s extra
                              (clock skew / an overloaded host; at
                              worst the replica turns SUSPECT, never
                              loses a session)

    replica: which replica id the fault fires on ("" = every
    replica).  at_beats: 0-based beat indices — the kill beat for
    'kill' (empty = beat 0), the suppressed window for 'partition'
    (empty = never)."""

    kind: str
    replica: str = ""
    at_beats: tuple[int, ...] = ()
    delay_s: float = 0.0

    def __post_init__(self):
        if self.kind not in ("kill", "partition", "slow_heartbeat"):
            raise ValueError(f"unknown replica fault {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class MeshFault:
    """One mesh-layer fault (ISSUE 17; docs/resilience.md): the
    host-only seams of the elastic mesh fault domain
    (mpisppy_tpu/parallel/elastic.py).

    kind: 'host_lost'    -> the named host drops out of the mesh at
                            hub iteration at_iters[0] (fires once):
                            membership marks it DEAD, the elastic
                            runner emergency-checkpoints the hub
                            plane and re-shards the wheel across the
                            survivors
          'partition'    -> the host's heartbeat beacons are
                            suppressed while the beat index is inside
                            the at_beats window; shorter than the
                            DEAD budget the host turns SUSPECT and
                            rejoins UP at the next epoch WITHOUT a
                            reshard (the partition-heals case)
          'straggler'    -> the hub-harvest device fetch is delayed
                            delay_s seconds at each of at_iters (a
                            slow collective; pushed past the harvest
                            deadline this trips a typed MeshDegraded,
                            never a hang)
          'torn_harvest' -> the harvested scalar vector is corrupted
                            to NaN at each of at_iters (fires once
                            per iteration): the caller must detect
                            the tear and synchronously re-fetch — the
                            device value is intact, only the transfer
                            tore

    host: which host index the fault names (host_lost/partition);
    at_iters: hub iterations (host_lost fires once at the first);
    at_beats: suppressed heartbeat window for 'partition'."""

    kind: str
    host: int = 1
    at_iters: tuple[int, ...] = ()
    at_beats: tuple[int, ...] = ()
    delay_s: float = 0.05

    def __post_init__(self):
        if self.kind not in ("host_lost", "partition", "straggler",
                             "torn_harvest"):
            raise ValueError(f"unknown mesh fault {self.kind!r}")


@dataclasses.dataclass(frozen=True)
class CheckpointFault:
    """Damage the `at_write`-th completed checkpoint file (0-based).

    kind: 'torn' truncates the file to half (a kill mid-write on a
    non-atomic filesystem); 'corrupt' flips bytes in the middle (bit
    rot — survives np.load, caught by the checksum)."""

    kind: str
    at_write: int = 0

    def __post_init__(self):
        if self.kind not in ("torn", "corrupt"):
            raise ValueError(f"unknown checkpoint fault {self.kind!r}")


class FaultPlan:
    """A seeded, deterministic schedule of faults for one wheel run.

    Build one, put it in the hub options as ``options['fault_plan']``,
    and spin.  The hub and WheelSpinner call the seam methods below at
    the named points; a plan with no faults armed (or no plan at all)
    never changes behavior.  ``plan.fired`` records every injection as
    ``(seam, detail)`` tuples so tests can assert the schedule ran.
    """

    def __init__(self, seed: int = 0, spoke_bounds=(), lanes=(),
                 checkpoints=(), preempt_at_iter: int | None = None,
                 dispatches=(), exchanges=(), serves=(), replicas=(),
                 meshes=()):
        self.rng = np.random.default_rng(seed)
        self.spoke_bounds = tuple(spoke_bounds)
        self.lanes = tuple(lanes)
        self.checkpoints = tuple(checkpoints)
        self.preempt_at_iter = preempt_at_iter
        self.dispatches = tuple(dispatches)
        self.exchanges = tuple(exchanges)
        self.serves = tuple(serves)
        self.replicas = tuple(replicas)
        self.meshes = tuple(meshes)
        self.fired: list[tuple[str, str]] = []
        self._writes = 0
        self._first_seen: dict[int, float] = {}
        self._preempted = False
        self._dropped: set[int] = set()
        self._killed_dispatcher = False
        self._served_disconnects: set[tuple[str, int]] = set()
        self._killed_replicas: set[str] = set()
        self._partitions_fired: set[tuple[str, int]] = set()
        self._slow_replicas: set[str] = set()
        self._lost_hosts: set[int] = set()
        self._mesh_partitions_fired: set[tuple[int, int]] = set()
        self._torn_harvests: set[int] = set()
        self._stragglers_fired: set[tuple[int, int]] = set()
        # set by the hub when the plan is armed in its options: every
        # injection also lands in the telemetry stream as a
        # fault-injected event (docs/telemetry.md), so a chaos run's
        # trace shows WHAT was injected next to what the guards did.
        # telemetry_iter is the hub-iteration stamp (-1 pre-wheel),
        # refreshed by the hub each sync AND by every seam that
        # receives the iteration directly, so the analyzer joins
        # injections to the timeline exactly (ISSUE 5 satellite).
        self.telemetry = None
        self.telemetry_run = ""
        self.telemetry_iter = -1

    def _fire(self, seam: str, detail: str) -> None:
        self.fired.append((seam, detail))
        if self.telemetry is not None:
            from mpisppy_tpu.telemetry import FAULT_INJECTED
            self.telemetry.emit(FAULT_INJECTED, run=self.telemetry_run,
                                cyl="fault-plan", seam=seam,
                                detail=detail,
                                hub_iter=self.telemetry_iter)

    @property
    def armed(self) -> bool:
        return bool(self.spoke_bounds or self.lanes or self.checkpoints
                    or self.dispatches or self.exchanges or self.serves
                    or self.replicas or self.meshes
                    or self.preempt_at_iter is not None)

    # -- seams: serve layer (mpisppy_tpu/serve; docs/serving.md) ----------
    def _serve_hits(self, kind: str, tenant: str, ordinal: int):
        for f in self.serves:
            if f.kind != kind:
                continue
            if f.tenant and f.tenant != tenant:
                continue
            if f.at_sessions and ordinal not in f.at_sessions:
                continue
            return f
        return None

    def serve_before_solve(self, tenant: str, ordinal: int) -> None:
        """Called by the serve engine right before a session's solve
        starts; may sleep (hang) or raise (poison) — both must surface
        at the client as a typed terminal outcome, never a hang."""
        import time as _time
        f = self._serve_hits("hang", tenant, ordinal)
        if f is not None:
            self._fire("serve", f"hang {tenant}#{ordinal}")
            _time.sleep(float(f.hang_s))
        f = self._serve_hits("poison", tenant, ordinal)
        if f is not None:
            self._fire("serve", f"poison {tenant}#{ordinal}")
            raise RuntimeError(
                f"injected serve poison ({tenant} session {ordinal})")

    def serve_drop_connection(self, tenant: str, ordinal: int) -> bool:
        """True when the server must drop this session's client
        connection now (fires once per (tenant, ordinal))."""
        f = self._serve_hits("disconnect", tenant, ordinal)
        if f is None or (tenant, ordinal) in self._served_disconnects:
            return False
        self._served_disconnects.add((tenant, ordinal))
        self._fire("serve", f"disconnect {tenant}#{ordinal}")
        return True

    def serve_flood_factor(self, tenant: str) -> int:
        """Submit-count multiplier the load generator applies to this
        tenant (1 = no flood armed)."""
        for f in self.serves:
            if f.kind == "flood" and (not f.tenant or f.tenant == tenant):
                self._fire("serve", f"flood {tenant} x{f.flood_factor}")
                return max(1, int(f.flood_factor))
        return 1

    # -- seams: fleet replicas (mpisppy_tpu/fleet; docs/serving.md) -------
    def _replica_hits(self, kind: str, rid: str):
        for f in self.replicas:
            if f.kind == kind and (not f.replica or f.replica == rid):
                return f
        return None

    def replica_kill(self, rid: str, beat: int) -> bool:
        """True when this replica must die NOW — called from the
        replica's heartbeat loop; fires once per replica."""
        f = self._replica_hits("kill", rid)
        if f is None or rid in self._killed_replicas:
            return False
        if beat < (f.at_beats[0] if f.at_beats else 0):
            return False
        self._killed_replicas.add(rid)
        self._fire("replica", f"kill {rid}@beat{beat}")
        return True

    def replica_partitioned(self, rid: str, beat: int) -> bool:
        """True while the replica's heartbeats and the router's status
        probes must be dropped (the partition window)."""
        f = self._replica_hits("partition", rid)
        if f is None or beat not in f.at_beats:
            return False
        if (rid, beat) not in self._partitions_fired:
            self._partitions_fired.add((rid, beat))
            self._fire("replica", f"partition {rid}@beat{beat}")
        return True

    def replica_beat_delay(self, rid: str) -> float:
        """Extra per-beat delay (slow_heartbeat); 0.0 unarmed.  Fires
        into the record once per replica, applies every beat."""
        f = self._replica_hits("slow_heartbeat", rid)
        if f is None:
            return 0.0
        if rid not in self._slow_replicas:
            self._slow_replicas.add(rid)
            self._fire("replica",
                       f"slow-heartbeat {rid} +{f.delay_s}s")
        return float(f.delay_s)

    # -- seams: elastic mesh (parallel/elastic.py; docs/resilience.md) ----
    def _mesh_hits(self, kind: str):
        return [f for f in self.meshes if f.kind == kind]

    def mesh_lost_host(self, hub_iter: int) -> int | None:
        """Host index that drops out of the mesh NOW, or None.  Fires
        once per host, at the first armed hub iteration reached."""
        self.telemetry_iter = hub_iter
        for f in self._mesh_hits("host_lost"):
            if f.host in self._lost_hosts:
                continue
            first = f.at_iters[0] if f.at_iters else 0
            if hub_iter < first:
                continue
            self._lost_hosts.add(f.host)
            self._fire("mesh", f"host_lost host{f.host} iter{hub_iter}")
            return f.host
        return None

    def mesh_partitioned(self, host: int, beat: int) -> bool:
        """True while the host's heartbeat beacons must be suppressed
        (the DCN partition window)."""
        for f in self._mesh_hits("partition"):
            if f.host != host or beat not in f.at_beats:
                continue
            if (host, beat) not in self._mesh_partitions_fired:
                self._mesh_partitions_fired.add((host, beat))
                self._fire("mesh", f"partition host{host}@beat{beat}")
            return True
        return False

    def mesh_harvest_delay(self, hub_iter: int) -> float:
        """Extra seconds the hub-harvest fetch must sleep this
        iteration (the straggler collective); 0.0 unarmed."""
        self.telemetry_iter = hub_iter
        delay = 0.0
        for i, f in enumerate(self._mesh_hits("straggler")):
            if f.at_iters and hub_iter not in f.at_iters:
                continue
            if (i, hub_iter) in self._stragglers_fired:
                # fires once per (fault, iteration): a resumed run that
                # re-executes the trip iteration must not re-straggle —
                # the injected collective was transiently slow, not
                # permanently wedged (a re-trip would livelock the
                # elastic runner into its max_reshards budget)
                continue
            self._stragglers_fired.add((i, hub_iter))
            self._fire("mesh", f"straggler +{f.delay_s}s iter{hub_iter}")
            delay += float(f.delay_s)
        return delay

    def mesh_torn_harvest(self, hub_iter: int) -> bool:
        """True when the fetched scalar vector must be torn (NaN) this
        iteration; fires once per iteration."""
        self.telemetry_iter = hub_iter
        for f in self._mesh_hits("torn_harvest"):
            if f.at_iters and hub_iter not in f.at_iters:
                continue
            if hub_iter in self._torn_harvests:
                return False
            self._torn_harvests.add(hub_iter)
            self._fire("mesh", f"torn_harvest iter{hub_iter}")
            return True
        return False

    # -- seams: async exchange (async_wheel.AsyncFusedPH / AsyncPHHub) ----
    def filter_plane_write(self, hub_iter: int, new_plane, old_plane):
        """Return the plane the slot should actually receive: the old
        one (dropped write), a torn old/new mix, or the new one
        untouched.  Host-side pointer surgery only — device arrays are
        immutable, so a torn swap is a REF mix, never a torn tensor."""
        for f in self.exchanges:
            if f.at_iters and hub_iter not in f.at_iters:
                continue
            if f.kind == "drop_plane_write":
                self._fire("exchange",
                           f"drop_plane_write iter{hub_iter}")
                return old_plane
            if f.kind == "torn_swap":
                self._fire("exchange", f"torn_swap iter{hub_iter}")
                return dataclasses.replace(
                    new_plane, W=old_plane.W, x=old_plane.x)
        return new_plane

    def before_harvest(self, hub_iter: int) -> None:
        """Called at the top of the host-complete half; may sleep."""
        import time as _time
        for f in self.exchanges:
            if f.kind != "slow_harvest":
                continue
            if f.at_iters and hub_iter not in f.at_iters:
                continue
            self._fire("exchange",
                       f"slow_harvest {f.delay_s}s iter{hub_iter}")
            _time.sleep(float(f.delay_s))

    # -- seam: spoke harvest (hub._harvest_all) ---------------------------
    def filter_bound(self, spoke_index: int, sense: str, bound: float,
                     hub_iter: int) -> float:
        """Return the (possibly poisoned) bound the hub should see."""
        self.telemetry_iter = hub_iter
        if spoke_index not in self._first_seen and np.isfinite(bound):
            self._first_seen[spoke_index] = bound
        for f in self.spoke_bounds:
            if f.spoke_index is not None and f.spoke_index != spoke_index:
                continue
            if f.at_iters and hub_iter not in f.at_iters:
                continue
            if f.kind == "nan":
                poisoned = float("nan")
            elif f.kind == "wrong_sense":
                poisoned = bound + f.magnitude if sense == "outer" \
                    else bound - f.magnitude
            else:  # stale
                poisoned = self._first_seen.get(spoke_index, bound)
            self._fire("spoke_bound",
                       f"{f.kind} spoke{spoke_index} iter{hub_iter}")
            return poisoned
        return bound

    # -- seam: PDHG lanes (hub.sync, host-side) ---------------------------
    def corrupt_lanes(self, hub_iter: int, opt) -> bool:
        """Scale/NaN the configured lanes of opt.state.solver.  Returns
        True when something was corrupted."""
        self.telemetry_iter = hub_iter
        todo = [f for f in self.lanes if f.at_iter == hub_iter]
        if not todo or getattr(opt, "state", None) is None:
            return False
        import jax.numpy as jnp
        st = opt.state
        solver = st.solver
        x, y = solver.x, solver.y
        for f in todo:
            lanes = np.asarray(f.lanes, np.int32)
            if f.mode == "scale":
                x = x.at[lanes].mul(f.scale)
                y = y.at[lanes].mul(f.scale)
            else:
                nan = jnp.asarray(np.nan, x.dtype)
                x = x.at[lanes].set(nan)
                y = y.at[lanes].set(nan)
            self._fire("lanes", f"{f.mode} lanes{f.lanes} iter{hub_iter}")
        opt.state = dataclasses.replace(
            st, solver=dataclasses.replace(solver, x=x, y=y))
        # FusedPH carries the authoritative state in wstate; keep the
        # two views consistent so the corruption is not silently dropped
        wstate = getattr(opt, "wstate", None)
        if wstate is not None and wstate.ph is st:
            opt.wstate = dataclasses.replace(wstate, ph=opt.state)
        return True

    # -- seam: checkpoint write (hub._write_checkpoint) -------------------
    def on_checkpoint_written(self, path: str) -> None:
        """Called after a checkpoint file fully lands (post-rename)."""
        idx = self._writes
        self._writes += 1
        for f in self.checkpoints:
            if f.at_write != idx:
                continue
            import os
            size = os.path.getsize(path)
            if f.kind == "torn":
                with open(path, "r+b") as fh:
                    fh.truncate(max(1, size // 2))
            else:  # corrupt: flip bytes in the middle of the file
                off = size // 3 + int(self.rng.integers(0, max(1, size // 3)))
                with open(path, "r+b") as fh:
                    fh.seek(off)
                    chunk = fh.read(8)
                    fh.seek(off)
                    fh.write(bytes(b ^ 0xFF for b in chunk))
            self._fire("checkpoint", f"{f.kind} write{idx} {path}")

    # -- seams: dispatch layer (dispatch/scheduler.py) --------------------
    # All three run on the host dispatch path — before_dispatch inside
    # the (possibly worker-threaded) solve attempt, drop_ticket at
    # result delivery, maybe_kill_dispatcher at the top of the daemon
    # loop.  The bus is thread-safe, so _fire from these threads is
    # safe; the seeded rng draws keep 'slow' jitter deterministic in
    # submission order under the scheduler's lock-serialized delivery.
    def before_dispatch(self, index: int, submit_ids) -> None:
        """Called with the dispatch-attempt index and the submit ids of
        every request riding this megabatch; may sleep or raise."""
        import time as _time
        for f in self.dispatches:
            if f.kind == "poison":
                hit = sorted(set(submit_ids) & set(f.submits))
                if hit:
                    self._fire("dispatch",
                               f"poison submits{hit} attempt{index}")
                    raise DispatchPoison(
                        f"injected poison in submits {hit}")
            elif f.kind in ("hang", "exception", "slow"):
                if f.at_dispatches and index not in f.at_dispatches:
                    continue
                if f.kind == "hang":
                    self._fire("dispatch", f"hang attempt{index}")
                    _time.sleep(f.hang_s)
                elif f.kind == "exception":
                    self._fire("dispatch", f"exception attempt{index}")
                    raise RuntimeError(
                        f"injected dispatch exception (attempt {index})")
                else:
                    self._fire("dispatch", f"slow attempt{index}")
                    _time.sleep(float(self.rng.uniform(0.0, f.jitter_s)))

    def drop_ticket(self, submit_id: int) -> bool:
        """True when this submit's completed result must be withheld
        from its ticket (a lost delivery; fires once per submit)."""
        for f in self.dispatches:
            if f.kind == "drop_ticket" and submit_id in f.submits \
                    and submit_id not in self._dropped:
                self._dropped.add(submit_id)
                self._fire("dispatch", f"drop_ticket submit{submit_id}")
                return True
        return False

    def maybe_kill_dispatcher(self) -> None:
        """Raise inside the dispatcher daemon loop, once."""
        if self._killed_dispatcher:
            return
        for f in self.dispatches:
            if f.kind == "kill_dispatcher":
                self._killed_dispatcher = True
                self._fire("dispatch", "kill_dispatcher")
                raise RuntimeError("injected dispatcher-thread death")

    # -- seam: preemption (hub.sync) --------------------------------------
    def maybe_preempt(self, hub_iter: int) -> None:
        self.telemetry_iter = hub_iter
        if (self.preempt_at_iter is not None and not self._preempted
                and hub_iter >= self.preempt_at_iter):
            self._preempted = True
            self._fire("preemption", f"iter{hub_iter}")
            raise SimulatedPreemption(
                f"simulated preemption at hub iteration {hub_iter}")
