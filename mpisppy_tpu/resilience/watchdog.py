###############################################################################
# Hub progress watchdog (ISSUE 9; docs/resilience.md fault domain).
#
# A long-lived serving wheel can wedge in ways no exception ever
# reports: a hung device dispatch, an XLA deadlock, a starved dispatcher
# — the hub loop simply stops advancing and the process sits there
# burning reservation.  The reference never needs this (a hung Gurobi
# rank trips MPI timeouts); a single-process TPU wheel must supervise
# itself.
#
# HubWatchdog is a daemon thread fed host-side progress beats from the
# hub (`beat(iter, outer, inner)` once per sync — progress = the hub
# iteration advanced OR a certified bound moved).  When no progress
# lands for `budget_s` wall seconds it TRIPS:
#
#   1. emit a `watchdog` telemetry event + bump watchdog_trips_total;
#   2. dump every flight recorder on the hub's bus (the black box shows
#      what the wheel was doing when it froze);
#   3. act, per `action`:
#        'degrade' — switch the process-default dispatch scheduler to
#                    direct un-coalesced dispatch (coalescing windows /
#                    admission timers out of the suspect path) and keep
#                    watching; a SECOND full budget with no progress
#                    escalates to the abort action below;
#        'abort'   — synchronous emergency checkpoint (when the hub has
#                    a checkpoint_path), then exit 75 (EX_TEMPFAIL, the
#                    same code a preemption exits with) so the pool
#                    scheduler restarts the run and --checkpoint-restore
#                    resumes it.
#
# Everything is host-side (nothing enters the jit graph) and the thread
# costs one monotonic-clock read per `interval_s` while healthy.  The
# abort path deliberately writes its last words straight to stderr: the
# telemetry console may be wedged inside the very stall being escaped
# (tools/lint_no_print.py allowlists this module for that reason).
###############################################################################
from __future__ import annotations

import os
import sys
import threading
import time


class HubWatchdog:
    """Supervise hub progress; see the module header.

    `hub` is duck-typed: telemetry (bus), run_id, options (dict),
    emergency_checkpoint(path).  `abort_fn` is injectable for tests
    (default os._exit — a hung process cannot be unwound politely)."""

    def __init__(self, hub, budget_s: float, action: str = "abort",
                 interval_s: float | None = None, abort_fn=None,
                 shrink_fn=None):
        if action not in ("abort", "degrade", "shrink"):
            raise ValueError(f"unknown watchdog action {action!r}")
        self.hub = hub
        self.budget_s = float(budget_s)
        self.action = action
        self.interval_s = max(0.01, float(interval_s)) \
            if interval_s is not None else max(0.05, self.budget_s / 4.0)
        self.abort_fn = abort_fn or os._exit
        # shrink_fn: the elastic-mesh escalation rung (ISSUE 17) —
        # called once between degrade and abort when action='shrink';
        # returns True when the wheel was re-homed onto a smaller
        # survivor mesh (parallel/elastic.py supplies it).  A missing
        # or failing shrink falls through to abort on the next trip.
        self.shrink_fn = shrink_fn
        # trips/degraded are touched only on the supervisor thread
        # (and read by tests after stop()); the beat path shares only
        # the two _lock-guarded fields below (lint-enforced:
        # tools/graftlint lock-discipline)
        self.trips = 0
        self.degraded = False
        self.shrunk = False
        self._shrink_attempted = False
        self._lock = threading.Lock()
        self._last_progress = time.perf_counter()  # guarded-by: _lock
        self._last = (None, None, None)            # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- the hub-facing surface -------------------------------------------
    def start(self) -> "HubWatchdog":
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="mpisppy-tpu-watchdog")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def beat(self, hub_iter: int, outer: float, inner: float) -> None:
        """One host-side progress report per hub sync.  Progress = the
        iteration advanced or either certified bound moved; a hung
        wheel simply stops calling this, and a wheel whose sync loop
        still spins without moving anything resets the budget via the
        advancing iteration count (stall-without-hang is the hub's own
        max_stalled_iters termination's job, not the watchdog's)."""
        cur = (hub_iter, outer, inner)
        with self._lock:
            if cur != self._last:
                self._last = cur
                self._last_progress = time.perf_counter()

    def stalled_s(self) -> float:
        with self._lock:
            return time.perf_counter() - self._last_progress

    # -- the supervisor loop ----------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            stalled = self.stalled_s()
            if stalled < self.budget_s:
                continue
            self._trip(stalled)
            if self._stop.is_set():
                return
            with self._lock:   # fresh budget after any surviving action
                self._last_progress = time.perf_counter()

    def _trip(self, stalled: float) -> None:
        # stop() racing an in-flight trip wins: the wheel is unwinding
        # or finalizing on purpose and must not be exited from under
        if self._stop.is_set():
            return
        self.trips += 1
        # escalation ladder per configured action (PR-8 semantics,
        # extended with the elastic rung): 'abort' goes straight there;
        # 'degrade' gives one degraded budget first; 'shrink' walks
        # degrade -> shrink (re-home onto the survivor mesh) -> abort,
        # each rung consuming one full stall budget
        if self.action == "abort":
            rung = "abort"
        elif self.action == "degrade":
            rung = "abort" if self.degraded else "degrade"
        elif not self.degraded:
            rung = "degrade"
        elif not self._shrink_attempted and self.shrink_fn is not None:
            rung = "shrink"
        else:
            rung = "abort"
        self._emit(action=rung, stalled_s=round(stalled, 3),
                   budget_s=self.budget_s, trips=self.trips)
        try:
            from mpisppy_tpu.telemetry import metrics as _metrics
            _metrics.REGISTRY.inc("watchdog_trips_total")
        except Exception:
            pass
        self._dump_flight(stalled)
        if rung == "abort":
            self._abort(stalled)
        elif rung == "shrink":
            self._shrink(stalled)
        else:
            self._degrade()

    def _emit(self, **data) -> None:
        bus = getattr(self.hub, "telemetry", None)
        if bus is None:
            return
        try:
            from mpisppy_tpu import telemetry as tel
            bus.emit(tel.WATCHDOG, run=getattr(self.hub, "run_id", ""),
                     cyl="watchdog", component="hub", **data)
        except Exception:
            pass

    def _dump_flight(self, stalled: float) -> None:
        try:
            from mpisppy_tpu.telemetry import flightrec
            bus = getattr(self.hub, "telemetry", None)
            flightrec.dump_all(
                bus, reason=f"watchdog: no hub progress for "
                            f"{stalled:.1f}s (budget {self.budget_s}s)")
        except Exception:
            pass

    def _degrade(self) -> None:
        """Switch the process-default dispatch scheduler to direct,
        un-coalesced dispatch — the admission/coalescing machinery is
        out of the suspect path, every later submit dispatches solo."""
        self.degraded = True
        try:
            from mpisppy_tpu import dispatch as _dispatch
            sched = _dispatch.get_scheduler(create=False)
            if sched is not None:
                sched.degrade()
        except Exception:
            pass
        try:
            from mpisppy_tpu.telemetry import console as _console
            _console.log("watchdog: hub stalled past budget — degraded "
                         "dispatch to direct un-coalesced mode")
        except Exception:
            pass

    def _shrink(self, stalled: float) -> None:
        """The elastic rung: ask parallel/elastic.py to emergency-
        checkpoint and re-home the wheel onto the surviving mesh.  A
        shrink that fails (or returns False) leaves `shrunk` unset so
        the NEXT trip escalates to abort — the ladder never wedges."""
        self._shrink_attempted = True
        try:
            self.shrunk = bool(self.shrink_fn(stalled))
        except Exception:
            self.shrunk = False
        try:
            from mpisppy_tpu.telemetry import console as _console
            _console.log(
                "watchdog: hub stalled past degraded budget — "
                + ("re-homed the wheel onto the survivor mesh"
                   if self.shrunk else
                   "shrink failed; next trip aborts (exit 75)"))
        except Exception:
            pass

    def _abort(self, stalled: float) -> None:
        """Checkpoint-and-abort: last-gasp save, then EX_TEMPFAIL so the
        pool scheduler restarts us and --checkpoint-restore resumes."""
        if self._stop.is_set():   # re-check: stop() may have landed
            return                # while the trip was dumping
        path = None
        try:
            path = (getattr(self.hub, "options", None) or {}).get(
                "checkpoint_path")
            if path:
                self.hub.emergency_checkpoint(path)
        except Exception:
            path = None
        # stderr on purpose: the console bus may be part of the wedge
        print(f"watchdog: ABORT — no hub progress for {stalled:.1f}s "
              f"(budget {self.budget_s}s); "
              f"{'checkpoint saved to ' + path if path else 'no checkpoint path'}"
              f"; exiting 75", file=sys.stderr, flush=True)
        self._stop.set()
        self.abort_fn(75)
