###############################################################################
# Kernel-tile synthesis: ScenarioProgram -> ops.pdhg_pallas.TileSynth.
#
# The Pallas double-buffered window engine streams each scenario tile's
# operands HBM->VMEM while the previous tile computes.  For a program-
# backed batch the data operands (c/q/l/u/bl/bu) need not exist in HBM
# at all: this builder closes the program's sampler + template scaling
# over the kernel and generates every tile's data IN the kernel — the
# "synthesize tile t+1 into the VMEM slot instead of DMA-ing it" half
# of ISSUE 14's tentpole.  Solver state (x/y/window sums, tau/sigma/
# done) still rides the DMA pipeline: it is genuine state.
#
# The produced values are KERNEL-READY: scaled by the shared template
# scaling (core.batch.scale_field — the same f32 arithmetic as realize
# and from_specs(scaling=...)), padded to the hardware tile widths with
# run_window's fill values, bound rows clipped to +-_BIG, and pad
# scenarios clamped to the last real index — so a synth window
# bit-matches a window over the materialized batch
# (tests/test_scengen.py, interpret mode).
###############################################################################
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.core.batch import scale_field
from mpisppy_tpu.ops.boxqp import BoxQP
from mpisppy_tpu.ops.pdhg_pallas import _BIG, _pad_last, _round_up, TileSynth
from mpisppy_tpu.scengen.virtual import VirtualBatch

_DATA_FIELDS = ("c", "q", "l", "u", "bl", "bu")
_FILL = {"c": 0.0, "q": 0.0, "l": 0.0, "u": 0.0, "bl": -_BIG, "bu": _BIG}


def window_inputs(vb: VirtualBatch, tile_s: int = 128):
    """(qp_proxy, TileSynth) for ops.pdhg_pallas.run_window.

    qp_proxy carries the REAL shared dense A (the kernel keeps it
    VMEM-resident) and (1, width) placeholders for every data field —
    their values are never read; the TileSynth generates all six data
    operands per tile (varying fields sampled through the program's
    counter-based keys, shared fields broadcast from the template), so
    nothing (S, ·)-shaped exists for the data plane.
    """
    prog = vb.program
    A = vb.shared.get("A")
    if A is None or hasattr(A, "vals") or getattr(A, "ndim", 0) != 2:
        raise ValueError(
            "window_inputs needs a shared dense constraint matrix "
            "(the Pallas window kernel's supported() shape); programs "
            "varying A or using ELL keep the XLA synthesis path")
    n = int(A.shape[1])
    m = int(A.shape[0])
    n_p = _round_up(n, 128)
    m_p = _round_up(m, 128)
    dt = prog.dtype
    widths = {"c": n_p, "q": n_p, "l": n_p, "u": n_p,
              "bl": m_p, "bu": m_p}

    shared_pad = {}
    for name in _DATA_FIELDS:
        if name in prog.varying:
            continue
        val = vb.shared[name]
        if name in ("bl", "bu"):
            val = jnp.clip(val, -_BIG, _BIG)
        shared_pad[name] = _pad_last(jnp.asarray(val, dt),
                                     widths[name], _FILL[name])
    base_key = vb.base_key
    d_row, d_col = vb.d_row, vb.d_col
    num_real, start = vb.num_real, prog.start
    varying = prog.varying

    def raw_fn(t):
        from mpisppy_tpu.scengen.program import sample_fields
        i = t * tile_s + jnp.arange(tile_s, dtype=jnp.int32)
        idx = jnp.minimum(i, num_real - 1) + start
        sampled = sample_fields(vb.program, idx, base_key=base_key)
        out = []
        for name in _DATA_FIELDS:
            if name in varying:
                val = scale_field(name, sampled[name], d_row, d_col)
                if name in ("bl", "bu"):
                    val = jnp.clip(val, -_BIG, _BIG)
                out.append(_pad_last(val, widths[name], _FILL[name]))
            else:
                out.append(jnp.broadcast_to(
                    shared_pad[name], (tile_s, widths[name])))
        return out

    # A Pallas kernel may not CAPTURE array constants (the base key,
    # scalings, padded template rows, and whatever the model sampler
    # itself closed over) — trace raw_fn once and hoist the jaxpr's
    # constvars into an explicit argument list, which TileSynth.consts
    # then passes as VMEM-resident kernel inputs
    # (jax.closure_convert does NOT hoist concrete arrays — it folds
    # them back in as jaxpr constants, re-creating the capture).
    closed = jax.make_jaxpr(raw_fn)(jnp.asarray(0, jnp.int32))
    consts = tuple(jnp.asarray(c) for c in closed.consts)

    def fn(t, *const_vals):
        vals = jax.core.eval_jaxpr(closed.jaxpr, const_vals, t)
        return dict(zip(_DATA_FIELDS, vals))

    def dummy(w):
        return jnp.zeros((1, w), dt)

    qp_proxy = BoxQP(
        c=dummy(n), q=dummy(n), A=A,
        bl=dummy(m), bu=dummy(m), l=dummy(n), u=dummy(n))
    return qp_proxy, TileSynth(names=_DATA_FIELDS, fn=fn,
                               consts=tuple(consts))
