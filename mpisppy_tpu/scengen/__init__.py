###############################################################################
# scengen: seeded on-device scenario synthesis (ROADMAP item 3a;
# docs/scengen.md).
#
# Public surface:
#   ScenarioProgram   declarative key -> scenario-data recipe
#   scen_key          fold_in(base_key, scenario_index) — the counter scheme
#   program_for       model-module bridge (models/{farmer,sslp,uc,aircond})
#   virtual_batch     program -> VirtualBatch (O(n+m+S) resident pytree)
#   materialize       program -> fully synthesized ScenarioBatch (device)
#   to_specs          program -> host ScenarioSpec list (from_specs bridge)
###############################################################################
from mpisppy_tpu.scengen.program import (  # noqa: F401
    FIELDS, ScenarioProgram, has_program, program_for, program_from_cfg,
    sample_fields, scen_key,
)
from mpisppy_tpu.scengen.virtual import (  # noqa: F401
    VirtualBatch, materialize, virtual_batch,
)
from mpisppy_tpu.scengen.tiles import window_inputs  # noqa: F401


def to_specs(program):
    """Host-materialize a program's sampled set as ScenarioSpecs."""
    return program.to_specs()
