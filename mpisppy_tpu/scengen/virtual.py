###############################################################################
# VirtualBatch: a ScenarioBatch whose scenario data does not exist.
#
# The pytree holds only O(n + m + S) state — the base PRNG key, the
# probabilities, the shared (pre-scaled) template fields, and the shared
# Ruiz scalings — plus the ScenarioProgram as static metadata.
# `realize()` synthesizes the full ScenarioBatch IN-TRACE: every jitted
# iteration kernel concretizes a VirtualBatch at entry
# (core.batch.concretize), so the (S, ...) scenario tensors exist only
# as transients inside one device program and nothing scenario-sized is
# ever built on the host or kept resident between steps.  That is what
# decouples scenario count from memory (ROADMAP item 3a): at S = 1M the
# persistent footprint is the solver state the algorithm inherently
# carries, not the data.
#
# Sharded synthesis: parallel.mesh.shard_batch shards `p` (and the
# multistage node map) over the scenario axis and replicates the key +
# template.  Inside a jitted step XLA's SPMD partitioner then partitions
# realize()'s iota/fold_in/sampler chain along the same axis — each
# device folds in only its shard's scenario indices and generates only
# its shard's data, while the counter-based key scheme guarantees the
# draws are the ones any other layout would have produced
# (__graft_entry__.dryrun_multichip holds the sharded case to this).
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.core.batch import ScenarioBatch, scale_field
from mpisppy_tpu.ops.boxqp import BoxQP
from mpisppy_tpu.scengen.program import (
    FIELDS, ScenarioProgram, estimate_materialized_bytes, sample_fields,
)

Array = jax.Array


class _VirtualQP:
    """Host-side shape/dtype view of the qp a VirtualBatch would
    realize — enough surface for eager driver code (rho init reads
    `batch.qp.c.dtype`, the bench flops model reads `A.shape`) without
    synthesizing anything.  Inside kernels the batch is concretized
    first, so traced code never sees this shim."""

    def __init__(self, vb: "VirtualBatch"):
        prog = vb.program
        S = vb.num_scenarios
        dt = prog.dtype
        n = int(np.asarray(prog.template["c"]).shape[-1])
        m = int(prog.template["A"].shape[0])
        self.c = jax.ShapeDtypeStruct((S, n), dt)
        self.q = jax.ShapeDtypeStruct((S, n), dt)
        for f, width in (("l", n), ("u", n), ("bl", m), ("bu", m)):
            shape = (S, width) if f in prog.varying else (width,)
            setattr(self, f, jax.ShapeDtypeStruct(shape, dt))
        self.A = vb.shared["A"] if "A" in vb.shared \
            else jax.ShapeDtypeStruct((S, m, n), dt)
        self.cones = None
        self.n = n
        self.m = m

    @property
    def batched(self) -> bool:
        return True


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["base_key", "p", "d_col", "d_row", "d_non",
                 "nonant_idx", "node_of_slot", "integer_slot",
                 "integer_full", "shared"],
    meta_fields=["program", "num_real"],
)
@dataclasses.dataclass(frozen=True)
class VirtualBatch:
    """The ScenarioBatch interface over synthesized scenarios.

    shared: pre-scaled f32 template fields for every NON-varying qp
    field (name -> array / EllMatrix), built once by virtual_batch().
    node_of_slot is None for two-stage programs (synthesized as zeros
    in realize()) and a stored (S, N) map for multistage trees.
    """

    base_key: Array
    p: Array
    d_col: Array
    d_row: Array
    d_non: Array
    nonant_idx: Array
    node_of_slot: Array | None
    integer_slot: Array
    integer_full: Array
    shared: dict
    program: ScenarioProgram
    num_real: int

    is_virtual = True

    # -- ScenarioBatch surface (host-safe) --------------------------------
    @property
    def num_scenarios(self) -> int:
        return int(self.p.shape[0])

    @property
    def num_nonants(self) -> int:
        return int(self.nonant_idx.shape[0])

    @property
    def tree(self):
        return self.program.tree

    @property
    def qp(self) -> _VirtualQP:
        return _VirtualQP(self)

    @property
    def var_prob(self):
        return None

    def expectation(self, vals: Array) -> Array:
        return jnp.sum(self.p * vals)

    def nonants(self, x_scaled: Array) -> Array:
        """Original-space nonants — d_non is SHARED by the template-
        scaling contract, so this never synthesizes (the hub's
        per-sync snapshot calls it eagerly)."""
        return self.d_non * x_scaled[..., self.nonant_idx]

    def nonant_box(self):
        """Exact when the box is deterministic (every shipped program:
        randomness lives in A or the row RHS, never in l/u)."""
        prog = self.program
        if "l" in prog.varying or "u" in prog.varying:
            raise NotImplementedError(
                "nonant_box over a program with a varying box would "
                "need a tiled scan; no shipped program varies l/u")
        nonant = np.asarray(self.nonant_idx)
        d = np.asarray(self.d_non)
        lb = np.asarray(self.shared["l"])[nonant] * d
        ub = np.asarray(self.shared["u"])[nonant] * d
        return lb, ub

    # -- synthesis --------------------------------------------------------
    def realize(self) -> ScenarioBatch:
        """Synthesize the full ScenarioBatch (trace-pure — this is the
        in-kernel materialization point).  Pad rows (p == 0) clone the
        last real scenario's index, mirroring pad_to_multiple."""
        prog = self.program
        S = self.num_scenarios
        i = jnp.arange(S, dtype=jnp.int32)
        idx = jnp.minimum(i, self.num_real - 1) + prog.start
        fields = sample_fields(prog, idx, base_key=self.base_key)

        vals = {}
        for name in FIELDS:
            if name in prog.varying:
                vals[name] = scale_field(name, fields[name],
                                         self.d_row, self.d_col)
            elif name in self.shared:
                vals[name] = self.shared[name]
        n = vals["c"].shape[-1]
        qp = BoxQP(
            c=jnp.broadcast_to(vals["c"], (S, n)),
            q=jnp.broadcast_to(vals["q"], (S, n)),
            A=vals["A"], bl=vals["bl"], bu=vals["bu"],
            l=vals["l"], u=vals["u"],
        )
        if self.node_of_slot is not None:
            nos = self.node_of_slot
        else:
            nos = jnp.zeros((S, self.num_nonants), jnp.int32)
        return ScenarioBatch(
            qp=qp, d_col=self.d_col, d_row=self.d_row, d_non=self.d_non,
            p=self.p, nonant_idx=self.nonant_idx, node_of_slot=nos,
            integer_slot=self.integer_slot,
            integer_full=self.integer_full,
            tree=prog.tree, num_real=self.num_real)

    def persistent_bytes(self) -> int:
        """Resident footprint of this pytree's DATA leaves — the
        synthesized-path term of the bench's HBM high-water estimate."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self):
            total += int(getattr(leaf, "nbytes", 0) or 0)
        return total

    def materialized_bytes(self) -> int:
        """What the host-materialized equivalent would keep resident."""
        return estimate_materialized_bytes(self.program)


def virtual_batch(program: ScenarioProgram, pad_to: int | None = None,
                  bus=None) -> VirtualBatch:
    """Build the VirtualBatch for a program (host; O(n + m + S) work).

    pad_to: pad the scenario axis to a multiple (mesh divisibility) —
    pad rows get probability 0 and clone the last real scenario, the
    pad_to_multiple contract.  Emits one `scengen` telemetry event on
    `bus` (when given) and mirrors the build into the metrics registry.
    """
    from mpisppy_tpu.core.batch import as_scaled_arrays

    prog = program
    S = prog.num_scenarios
    S_p = S if pad_to is None else S + ((-S) % int(pad_to))
    dt = prog.dtype

    d_row_j, d_col_j = as_scaled_arrays(prog.scaling, dt)
    shared = {}
    for name in FIELDS:
        if name in prog.varying:
            continue
        if name == "q":
            tpl = prog.template.get("q")
            if tpl is None:
                tpl = np.zeros_like(np.asarray(prog.template["c"]))
        else:
            tpl = prog.template[name]
        if name == "A":
            import scipy.sparse as sps
            if sps.issparse(tpl):
                from mpisppy_tpu.ops import sparse as sparse_mod
                tpl = sparse_mod.ell_from_scipy(tpl, dt)
            else:
                tpl = jnp.asarray(tpl, dt)
        else:
            tpl = jnp.asarray(tpl, dt)
        shared[name] = scale_field(name, tpl, d_row_j, d_col_j)

    probs = np.zeros(S_p, np.float64)
    probs[:S] = 1.0 / S
    nonant_idx = np.asarray(prog.nonant_idx, np.int32)
    n = int(np.asarray(prog.template["c"]).shape[-1])
    integer = prog.integer if prog.integer is not None \
        else np.zeros(n, bool)

    node_of_slot = None
    if prog.tree.num_nodes > 1:
        nos = prog.tree.node_of_slot()
        if S_p > S:
            nos = np.concatenate(
                [nos, np.repeat(nos[-1:], S_p - S, axis=0)], axis=0)
        node_of_slot = jnp.asarray(nos)

    vb = VirtualBatch(
        base_key=prog.base_key(),
        p=jnp.asarray(probs, dt),
        d_col=d_col_j, d_row=d_row_j,
        d_non=d_col_j[nonant_idx],
        nonant_idx=jnp.asarray(nonant_idx),
        node_of_slot=node_of_slot,
        integer_slot=jnp.asarray(integer[nonant_idx]),
        integer_full=jnp.asarray(integer),
        shared=shared,
        program=prog,
        num_real=S,
    )

    from mpisppy_tpu.telemetry import metrics as _metrics
    saved = max(vb.materialized_bytes() - vb.persistent_bytes(), 0)
    _metrics.REGISTRY.inc("scengen_virtual_batches_total")
    _metrics.REGISTRY.set_gauge("scengen_scenarios", float(S))
    _metrics.REGISTRY.set_gauge("scengen_data_bytes_saved", float(saved))
    if bus is not None:
        bus.emit("scengen", program=prog.name, num_scenarios=S,
                 padded_to=S_p, base_seed=prog.base_seed,
                 start=prog.start,
                 persistent_bytes=vb.persistent_bytes(),
                 materialized_bytes_est=vb.materialized_bytes())
    return vb


def repartition(vb: VirtualBatch, pad_to: int) -> VirtualBatch:
    """Re-derive the scenario-axis layout for a new device count — the
    elastic-reshard primitive (docs/resilience.md, docs/scengen.md
    reshard-invariance contract).

    Scenario data never moves: it is synthesized from fold_in(base_key,
    scenario_index), and the index range [start, start + num_real) is a
    property of the PROGRAM, not of the mesh layout.  Only the O(S)
    probability vector and the multistage node map carry the padded
    scenario axis, so re-sharding after a host loss rebuilds exactly
    those two: real probabilities keep their values, pad rows get
    probability ZERO (never a cloned real probability — every
    p-weighted reduction stays value-identical across layouts), and
    realize()'s index clamp makes the pad rows clone the last real
    scenario's data as before."""
    S = vb.num_real
    S_p = S + ((-S) % int(pad_to))
    p_real = np.asarray(vb.p)[:S]
    probs = np.zeros(S_p, p_real.dtype)
    probs[:S] = p_real
    nos = vb.node_of_slot
    if nos is not None:
        nos_np = np.asarray(nos)[:S]
        if S_p > S:
            nos_np = np.concatenate(
                [nos_np, np.repeat(nos_np[-1:], S_p - S, axis=0)], axis=0)
        nos = jnp.asarray(nos_np)
    return dataclasses.replace(vb, p=jnp.asarray(probs), node_of_slot=nos)


def materialize(program: ScenarioProgram) -> ScenarioBatch:
    """Device-synthesize the WHOLE batch in one jitted realize — the
    bit-identity counterpart of from_specs(program.to_specs(),
    scaling=program.scaling) (tests/test_scengen.py holds every model
    program to exact equality)."""
    return _realize_jit(virtual_batch(program))


@jax.jit
def _realize_jit(vb: VirtualBatch) -> ScenarioBatch:
    return vb.realize()
