###############################################################################
# Seeded scenario synthesis: the ScenarioProgram (ROADMAP item 3a).
#
# The survey's problem statement — min E_s[ f(x, y_s, xi_s) ] — treats
# xi_s as data DRAWN FROM A DISTRIBUTION, yet the whole framework so far
# materializes every draw on the host (one numpy ScenarioSpec per
# scenario) and keeps the stacked result HBM-resident for the life of
# the run.  That is the 100k-scenario ceiling.  A ScenarioProgram is the
# recompute-instead-of-store answer (the idiom of the TPU
# distributed-linear-algebra line, PAPERS.md arXiv 2112.09017): a
# declarative, trace-pure recipe mapping a counter-based PRNG key to one
# scenario's data, so xi_s can be synthesized *inside* the iteration
# kernels and scenario count decouples from memory entirely.
#
# Key/counter scheme (docs/scengen.md):
#
#     key_s = jax.random.fold_in(PRNGKey(base_seed), start + s)
#
# threefry is counter-based and stateless, so draw s depends only on
# (base_seed, start + s) — never on which tile, device shard, or
# replication batch evaluates it.  This is the determinism +
# resharding-invariance contract: a batch synthesized tile-by-tile in a
# Pallas kernel, vmapped whole on one chip, sharded over a mesh, or
# materialized scenario-by-scenario on the host produces bit-identical
# data (tests/test_scengen.py holds every model's program to it).
#
# Two consumers share one program:
#
#   * `to_specs(program)` — the HOST materialization path: evaluates the
#     sampler per scenario (same threefry bits) and emits ordinary
#     ScenarioSpec objects for core.batch.from_specs.  This is the
#     compatibility bridge: anything that wants specs (EF builds, the
#     confidence-interval estimators) can draw through scengen keys.
#   * `scengen.virtual_batch(program)` — the DEVICE synthesis path: a
#     VirtualBatch whose realize() vmaps the sampler over the scenario
#     axis in-trace (see scengen/virtual.py).
#
# Bit-identity between the two paths holds by construction: both apply
# the program's shared template Scaling with the same f32 arithmetic
# (core.batch.scale_qp / from_specs(scaling=...)), and both draw each
# scenario's fields from the same folded key.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.core.tree import ScenarioTree, two_stage_tree

Array = jax.Array

#: qp fields a sampler may produce (ScenarioSpec field names).
FIELDS = ("c", "q", "A", "bl", "bu", "l", "u")


def scen_key(base_key: Array, idx) -> Array:
    """The ONE key derivation of the subsystem: scenario `idx`'s
    counter-based key.  fold_in is threefry-backed and stateless, so
    this is invariant to tiling/sharding/replication order."""
    return jax.random.fold_in(base_key, idx)


@dataclasses.dataclass(frozen=True, eq=False)
class ScenarioProgram:
    """A declarative recipe: scenario index -> one scenario's data.

    template: f64 numpy (or scipy-sparse A) DETERMINISTIC skeleton of
        every qp field; varying fields hold the values the sampler
        overwrites (their deterministic entries must match what the
        sampler embeds, bit-for-bit after f64->f32 conversion).
    varying: which fields the sampler produces.
    sampler: trace-pure `(base_key, idx) -> {field: f32 array}` built
        from jnp + jax.random only — it runs vmapped inside jitted
        iteration kernels, per-scenario on the host (to_specs), and
        per-tile inside the Pallas window pipeline.  It receives the
        BASE key (not the folded one) so multistage models can fold
        per tree NODE (aircond) while two-stage models use
        scen_key(base_key, idx).
    start: index offset — replication r of a confidence-interval run
        draws scenarios [start, start+num_scenarios) of the same base
        key, the seed-provenance contract of docs/scengen.md.

    eq=False keeps the object hashable by identity so it can ride jit
    static args (VirtualBatch meta field) — build a program once and
    reuse it; a fresh identical program keys a fresh compile.
    """

    name: str
    num_scenarios: int
    base_seed: int
    template: dict
    varying: tuple
    sampler: Callable
    nonant_idx: np.ndarray
    tree: ScenarioTree | None = None
    integer: np.ndarray | None = None
    start: int = 0
    dtype: Any = jnp.float32
    #: rolling-horizon step (mpc/): step k re-keys EVERY draw through
    #: fold_in(PRNGKey(base_seed), k) BEFORE the per-scenario fold, so
    #: consecutive MPC steps resample independently while staying bit-
    #: reproducible from {base_seed, step} alone (resharding-invariant
    #: like the per-scenario fold — threefry is stateless).
    step: int = 0

    def __post_init__(self):
        unknown = set(self.varying) - set(FIELDS)
        if unknown:
            raise ValueError(f"unknown varying fields: {sorted(unknown)}")
        if self.tree is None:
            object.__setattr__(self, "tree", two_stage_tree(
                self.num_scenarios, len(self.nonant_idx)))
        if self.tree.num_scenarios != self.num_scenarios:
            raise ValueError(
                f"tree has {self.tree.num_scenarios} scenarios, program "
                f"declares {self.num_scenarios}")

    # -- keys -------------------------------------------------------------
    def base_key(self) -> Array:
        key = jax.random.PRNGKey(self.base_seed)
        if self.step:
            key = jax.random.fold_in(key, self.step)
        return key

    def advance(self, step: int) -> "ScenarioProgram":
        """The MPC step re-key helper (ISSUE 19): the SAME program with
        its base key folded to step `step` — every scenario draw of the
        advanced program is bit-identical to synthesizing directly from
        fold_in(PRNGKey(base_seed), step), under any sharding
        (tests/test_scengen.py pins this).  Absolute, not relative:
        advance(k).advance(j) samples step j, not k+j."""
        if step == self.step:
            return self
        return dataclasses.replace(self, step=int(step))

    def indices(self) -> np.ndarray:
        return np.arange(self.start, self.start + self.num_scenarios)

    def provenance(self) -> dict:
        """Seed-provenance record (confidence_intervals outputs carry
        it): everything needed to regenerate the exact draws."""
        prov = {"scheme": "threefry2x32/fold_in",
                "program": self.name,
                "base_seed": int(self.base_seed),
                "start": int(self.start),
                "num_scenarios": int(self.num_scenarios)}
        if self.step:
            prov["step"] = int(self.step)
        return prov

    # -- scaling ----------------------------------------------------------
    @property
    def scaling(self):
        """Template Ruiz Scaling, computed ONCE from scenario `start`'s
        realized spec and shared by every scenario — any positive
        scaling is a valid equilibration, and a SHARED one is what lets
        d_col/d_non stay (n,)/(N,) for any scenario count.  Cached on
        the instance (programs are identity-hashed, so this is safe)."""
        sc = self.__dict__.get("_scaling")
        if sc is None:
            from mpisppy_tpu.ops.boxqp import BoxQP, ruiz_scale
            sp = self.spec_at(self.start)
            qp = BoxQP(
                c=sp.c, q=np.zeros_like(sp.c), A=_as_ell_or_dense(sp.A),
                bl=sp.bl, bu=sp.bu, l=sp.l, u=sp.u)
            _, sc = ruiz_scale(qp)
            object.__setattr__(self, "_scaling", sc)
        return sc

    # -- host materialization ---------------------------------------------
    def _host_sampler(self):
        fn = self.__dict__.get("_host_jit")
        if fn is None:
            fn = jax.jit(partial(_sample_one, self))
            object.__setattr__(self, "_host_jit", fn)
        return fn

    def _spec_from_fields(self, idx: int, fields: dict):
        """ScenarioSpec assembly from one scenario's drawn varying
        fields: f32 values upcast to f64 (exact), deterministic fields
        the SHARED template objects — so from_specs' identity fast
        path fires and the stacked batch bit-matches device
        synthesis."""
        from mpisppy_tpu.core.batch import ScenarioSpec
        vals = dict(self.template)
        for k in self.varying:
            vals[k] = np.asarray(fields[k], np.float64)
        return ScenarioSpec(
            name=f"{self.name}_scengen{idx}",
            c=vals["c"], A=vals["A"], bl=vals["bl"], bu=vals["bu"],
            l=vals["l"], u=vals["u"],
            q=vals.get("q"),
            nonant_idx=np.asarray(self.nonant_idx, np.int32),
            probability=1.0 / self.num_scenarios,
            integer=self.integer,
        )

    def spec_at(self, idx: int):
        """One scenario's ScenarioSpec, drawn through the program's
        keys (one device dispatch; bulk consumers use to_specs)."""
        fields = jax.device_get(self._host_sampler()(jnp.asarray(
            idx, jnp.int32)))
        return self._spec_from_fields(idx, fields)

    def to_specs(self) -> list:
        """The whole sampled set as host ScenarioSpecs (the from_specs
        bridge; O(S) host memory — the path synthesis exists to avoid,
        kept for EF builds and the bit-identity contract test).  ONE
        vmapped device dispatch draws every varying field; the python
        loop only assembles host spec objects."""
        idx = self.indices()
        fields = jax.device_get(_sample_fields_jit(
            self, jnp.asarray(idx, jnp.int32)))
        return [self._spec_from_fields(
            i, {k: fields[k][row] for k in self.varying})
            for row, i in enumerate(idx)]


def _as_ell_or_dense(A):
    import scipy.sparse as sps
    if sps.issparse(A):
        from mpisppy_tpu.ops import sparse as sparse_mod
        return sparse_mod.ell_from_scipy(A, jnp.float32)
    return A


def _sample_one(program: ScenarioProgram, idx: Array) -> dict:
    return program.sampler(program.base_key(), idx)


def sample_fields(program: ScenarioProgram, idx: Array,
                  base_key: Array | None = None) -> dict:
    """Vmapped draw of the varying fields for an index vector — THE
    device synthesis primitive (trace-pure; VirtualBatch.realize and
    the Pallas tile synth route through it).  `base_key` lets callers
    supply an already-placed key array (a VirtualBatch's replicated
    data leaf) instead of rebuilding it from the seed."""
    base = program.base_key() if base_key is None else base_key
    return jax.vmap(lambda i: program.sampler(base, i))(idx)


@partial(jax.jit, static_argnames=("program",))
def _sample_fields_jit(program: ScenarioProgram, idx: Array) -> dict:
    return sample_fields(program, idx)


def program_for(module, num_scens: int, seed: int = 0, start: int = 0,
                **kw) -> ScenarioProgram | None:
    """The model-module bridge: modules that ship a scenario-synthesis
    branch expose `scenario_program(num_scens, seed=..., start=..., ...)`
    (models/farmer, sslp, uc, aircond).  Returns None when the module
    has no program — callers fall back to host materialization."""
    factory = getattr(module, "scenario_program", None)
    if factory is None:
        return None
    return factory(num_scens, seed=seed, start=start, **kw)


def has_program(module) -> bool:
    return getattr(module, "scenario_program", None) is not None


def program_from_cfg(module, cfg, num: int, start: int = 0,
                     seed: int | None = None, drop: tuple = (),
                     **overrides) -> ScenarioProgram | None:
    """THE cfg-gated resolver the confidence-interval layer shares
    (ciutils + sample_tree): honor the `use_scengen` opt-in, forward
    the cfg's MODEL kwargs (kw_creator) so the program samples the
    instance the legacy path would build, and fall back to None — with
    a console warning, never silently — when the program cannot cover
    this sample (multistage index windows, on-disk data kwargs).

    drop: kw_creator keys the caller supplies itself / that must not
    reach the factory; overrides: explicit factory kwargs."""
    if not bool(cfg.get("use_scengen", False)):
        return None
    if not has_program(module):
        return None
    kw = {}
    if hasattr(module, "kw_creator"):
        try:
            kw = dict(module.kw_creator(cfg))
        except Exception:
            kw = {}
    kw.pop("num_scens", None)
    for k in drop:
        kw.pop(k, None)
    kw.update(overrides)
    if seed is None:
        seed = int(cfg.get("scengen_seed", 0))
    try:
        return program_for(module, num, seed=int(seed), start=int(start),
                           **kw)
    except (TypeError, ValueError) as e:
        # an EXPLICIT opt-in that cannot be honored must be audible:
        # the caller falls back to the legacy host stream and the
        # output will carry no seed_provenance
        from mpisppy_tpu.telemetry import console
        console.log(
            f"scengen: use_scengen requested but "
            f"{getattr(module, '__name__', module)!s} has no program "
            f"covering this sample ({e}); drawing from the legacy "
            f"host stream instead", level=console.INFO)
        return None


def estimate_materialized_bytes(program: ScenarioProgram,
                                itemsize: int = 4) -> int:
    """What a host-materialized from_specs batch would keep resident
    for the qp DATA alone (c/q always stack batched; varying fields
    batched; shared fields counted once) — the HBM high-water term
    synthesis removes.  Analytic, never allocates."""
    S = program.num_scenarios
    n = int(np.asarray(program.template["c"]).shape[-1])
    A = program.template["A"]
    m = A.shape[0]
    total = 2 * S * n * itemsize                      # c, q stack batched
    for f in ("l", "u"):
        mult = S if f in program.varying else 1
        total += mult * n * itemsize
    for f in ("bl", "bu"):
        mult = S if f in program.varying else 1
        total += mult * m * itemsize
    import scipy.sparse as sps
    if sps.issparse(A):
        k = max(int(np.diff(A.tocsr().indptr).max()), 1)
        a_elems = m * k * 2                           # vals + cols
    else:
        a_elems = m * n
    total += (S if "A" in program.varying else 1) * a_elems * itemsize
    return total
