# Extension plane: hub plug-ins called at fixed PH callout points
# (ref:mpisppy/extensions/).
from mpisppy_tpu.extensions.extension import (  # noqa: F401
    Extension, MultiExtension,
)
