# Extension plane: hub plug-ins called at fixed PH callout points
# (ref:mpisppy/extensions/).
from mpisppy_tpu.extensions.extension import (  # noqa: F401
    Extension, MultiExtension,
)
from mpisppy_tpu.extensions.avgminmaxer import MinMaxAvg  # noqa: F401
from mpisppy_tpu.extensions.diagnoser import Diagnoser  # noqa: F401
from mpisppy_tpu.extensions.xhatclosest import XhatClosest  # noqa: F401
