###############################################################################
# Gapper (ref:mpisppy/extensions/mipgapper.py:16-62): per-iteration
# solver-effort schedule.  The reference tightens the subproblem MIP gap
# as PH progresses; the TPU analog of "solver effort" is the PDHG window
# budget per PH iteration, so the schedule maps PH iteration ->
# subproblem_windows.  Changing the (static) budget recompiles the PH
# step once per distinct value — schedules should use a handful of
# values, exactly like the reference's gap dictionaries.
###############################################################################
from __future__ import annotations

import dataclasses

from mpisppy_tpu.extensions.extension import Extension


class Gapper(Extension):
    """schedule: {iteration: subproblem_windows}; read from
    ph.options.mipgapdict when present."""

    def __init__(self, ph, schedule: dict | None = None):
        super().__init__(ph)
        self.schedule = dict(schedule
                             or getattr(ph.options, "mipgapdict", None)
                             or {})

    def miditer(self):
        k = self.opt._iter
        if k in self.schedule:
            self.opt.options = dataclasses.replace(
                self.opt.options,
                subproblem_windows=int(self.schedule[k]))
