###############################################################################
# XhatClosest (ref:mpisppy/extensions/xhatclosest.py:16-117): try the
# scenario whose nonant vector is closest to x̄ — distance is the
# truncated z-score sum_slots min(3, |x_s - x̄| / stdev) — as the
# incumbent candidate x̂.
#
# The reference scans local scenarios per rank and Allreduces the min
# distance + winner rank; here the distance is one vectorized (S,N)
# reduction on device and argmin picks the winner — no communication
# plane needed.  The variance statistic is the same xsqbar the Fixer
# uses; it is recomputed here directly from the current iterate so the
# extension works whether or not PHOptions.compute_xsqbar is on.
# Evaluation reuses algos.xhat.evaluate (the Xhat_Eval analog), which
# already carries the stalled-tail rescue pass.
###############################################################################
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.algos import xhat as xhat_mod
from mpisppy_tpu.extensions.extension import Extension
from mpisppy_tpu.ops import pdhg


class XhatClosest(Extension):
    """Closest-scenario-to-x̄ incumbent candidate.

    Options arrive via the constructor — wire with
    functools.partial(XhatClosest, options={"keep_solution": bool,
    "verbose": bool}); PHOptions is a frozen dataclass, so the kwarg IS
    the options channel (the ref reads ph.options["xhat_closest_options"]).
    On keep_solution=True (default) the winning x̂ and its objective stay
    on the driver as `_xhat_closest_xhat` / `_final_xhat_closest_obj`
    (ref keeps the solution in the Pyomo instances the same way).
    """

    def __init__(self, ph, options: dict | None = None):
        super().__init__(ph)
        self.options = dict(options or {})
        self.keep_solution = bool(self.options.get("keep_solution", True))
        self._final_xhat_closest_obj = None

    # -- the distance + pick (ref:xhatclosest.py:29-94) -------------------
    def closest_scenario(self) -> int:
        st = self.opt.state
        batch = self.opt.batch
        x_non = batch.nonants(st.solver.x)              # (S, N)
        xbar = st.xbar                                  # (S, N)
        xsqbar, _ = batch.node_average(x_non * x_non)
        var = xsqbar - xbar * xbar
        stdev = jnp.sqrt(jnp.maximum(var, 0.0))
        # slots with no spread contribute 0, matching the reference's
        # `if variance > 0` guard
        z = jnp.where(var > 1e-12,
                      jnp.minimum(3.0, jnp.abs(x_non - xbar)
                                  / jnp.maximum(stdev, 1e-12)),
                      0.0)
        dist = jnp.sum(z, axis=-1)                      # (S,)
        # padded (probability-0) scenarios can never win
        dist = jnp.where(batch.p > 0.0, dist, jnp.inf)
        return int(jnp.argmin(dist))

    def xhat_closest_to_xbar(self, verbose: bool = False):
        """Returns (obj or None if infeasible, winning scenario name) —
        the surface of ref:xhatclosest.py:29."""
        sidx = self.closest_scenario()
        batch = self.opt.batch
        x_non = batch.nonants(self.opt.state.solver.x)
        cand = xhat_mod.round_integers(batch, x_non[sidx])
        res = xhat_mod.evaluate(batch, cand,
                                getattr(self.opt.options, "pdhg",
                                        pdhg.PDHGOptions()))
        feasible = bool(res.feasible)
        obj = float(res.value) if feasible else None
        sname = self.opt.scenario_names[sidx] \
            if sidx < len(self.opt.scenario_names) else f"scen{sidx}"
        if verbose:
            global_toc(f"XhatClosest: scenario {sname} -> "
                       f"{obj if feasible else 'infeasible'}", True)
        if feasible and self.keep_solution:
            self.opt._xhat_closest_xhat = np.asarray(cand)
        return obj, {"ROOT": sname}

    # -- hooks (ref fires at post_everything) -----------------------------
    def post_everything(self):
        obj, _ = self.xhat_closest_to_xbar(
            verbose=bool(self.options.get("verbose", False)))
        self._final_xhat_closest_obj = obj
        self.opt._final_xhat_closest_obj = obj
