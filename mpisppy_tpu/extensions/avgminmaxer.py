###############################################################################
# MinMaxAvg (ref:mpisppy/extensions/avgminmaxer.py:16-44): print
# avg/min/max (and max-min) of a per-scenario component each iteration.
#
# The reference resolves options["avgminmax_name"] to a Pyomo component
# (e.g. "FirstStageCost") per local instance and MPI-reduces; here the
# component resolves to a per-scenario device vector and the three
# reductions fuse into one fetch.  Supported component names:
#   "objective"        — per-scenario objective at the current iterate
#   "nonant:<k>"       — nonant slot k's per-scenario value
# (the batched model has no named expression dictionary to look up).
###############################################################################
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.extensions.extension import Extension
from mpisppy_tpu.telemetry import console as _console


class MinMaxAvg(Extension):
    def __init__(self, ph, compstr: str | None = None):
        # the component name arrives via the constructor kwarg
        # (functools.partial(MinMaxAvg, compstr=...)); PHOptions is a
        # frozen dataclass, so there is no ph.options["avgminmax_name"]
        # channel to read
        super().__init__(ph)
        self.compstr = compstr or "objective"

    def _component(self):
        st = self.opt.state
        batch = self.opt.batch
        if self.compstr.startswith("nonant:"):
            k = int(self.compstr.split(":", 1)[1])
            vals = batch.nonants(st.solver.x)[:, k]
        else:
            vals = batch.objective(st.solver.x)
        return vals

    def avg_min_max(self):
        """(avg, min, max) over real scenarios — the surface of
        ref PHBase.avg_min_max (ref:phbase.py avg_min_max)."""
        batch = self.opt.batch
        vals = self._component()
        real = batch.p > 0.0
        avg = self.opt.batch.expectation(vals)
        vmin = jnp.min(jnp.where(real, vals, jnp.inf))
        vmax = jnp.max(jnp.where(real, vals, -jnp.inf))
        out = np.asarray(jnp.stack([avg, vmin, vmax]))  # one fetch
        return float(out[0]), float(out[1]), float(out[2])

    def _report(self):
        if self.opt.state is None:
            return
        avgv, minv, maxv = self.avg_min_max()
        _console.log(f"  ###  {self.compstr}: avg, min, max, max-min "
                     f"{avgv} {minv} {maxv} {maxv - minv}")

    def post_iter0(self):
        self._report()

    def enditer(self):
        self._report()
