###############################################################################
# WXBarWriter / WXBarReader extensions
# (ref:mpisppy/utils/wxbarwriter.py:41-100, wxbarreader.py:42-105).
#
# Writer: dumps W and/or x̄ csvs per iteration (or only at the end).
# Reader: loads W/x̄ right after Iter0 so PH warm-starts from saved
# duals.  Option names mirror the reference's Config group
# (wxbar_read_write_args, ref:config.py:950-975): W_fname, Xbar_fname,
# init_W_fname, init_Xbar_fname, separate_W_files.
###############################################################################
from __future__ import annotations

import os

from mpisppy_tpu.extensions.extension import Extension
from mpisppy_tpu.utils import wxbarutils


class WXBarWriter(Extension):
    def __init__(self, ph, W_fname: str | None = None,
                 Xbar_fname: str | None = None,
                 per_iteration: bool = False):
        super().__init__(ph)
        self.W_fname = W_fname
        self.Xbar_fname = Xbar_fname
        self.per_iteration = per_iteration

    def _emit(self, tag: str | None = None):
        def _name(base):
            if tag is None:
                return base
            root, ext = os.path.splitext(base)
            return f"{root}_{tag}{ext}"
        if self.W_fname:
            wxbarutils.write_W_to_file(self.opt, _name(self.W_fname))
        if self.Xbar_fname:
            wxbarutils.write_xbar_to_file(self.opt, _name(self.Xbar_fname))

    def enditer(self):
        if self.per_iteration:
            self._emit(tag=str(self.opt._iter))

    def post_everything(self):
        self._emit()


class WXBarReader(Extension):
    def __init__(self, ph, init_W_fname: str | None = None,
                 init_Xbar_fname: str | None = None,
                 disable_check: bool = False):
        super().__init__(ph)
        self.init_W_fname = init_W_fname
        self.init_Xbar_fname = init_Xbar_fname
        self.disable_check = disable_check

    def post_iter0(self):
        # after Iter0 the state exists; loaded values override the
        # fresh-start W/xbar (ref:wxbarreader.py:83-97)
        if self.init_W_fname:
            wxbarutils.set_W_from_file(self.init_W_fname, self.opt,
                                       disable_check=self.disable_check)
        if self.init_Xbar_fname:
            wxbarutils.set_xbar_from_file(self.init_Xbar_fname, self.opt)
