###############################################################################
# Diagnoser (ref:mpisppy/extensions/diagnoser.py:21-86): append one
# diagnostic line per scenario per iteration to
# `<diagnoser_outdir>/<scenario>.dag` — "iter,objective".
#
# The reference loops local Pyomo instances per rank; here the whole
# (S,) per-scenario objective vector comes back in ONE device fetch per
# iteration and the host fans it out to the files.  Same refusal to
# clobber an existing output directory as the reference (which quits);
# raising is friendlier than quit() for library use.
###############################################################################
from __future__ import annotations

import os

import numpy as np

from mpisppy_tpu.extensions.extension import Extension


class Diagnoser(Extension):
    """options arrive via the constructor (wire with
    functools.partial(Diagnoser, options={"diagnoser_outdir": path,
    "flush_period": N}) — the ref reads ph.options, but PHOptions is a
    frozen dataclass here, so the kwarg IS the options channel)."""

    def __init__(self, ph, options: dict | None = None):
        super().__init__(ph)
        opts = dict(options or {})
        self.dirname = opts.get("diagnoser_outdir", "diagnostics")
        self.flush_period = int(opts.get("flush_period", 20))
        self._since_flush = 0
        if os.path.exists(self.dirname):
            raise RuntimeError(
                f"Diagnoser: output directory exists: {self.dirname} "
                "(refusing to clobber, ref:diagnoser.py:29-34)")
        os.makedirs(self.dirname)
        self._rows: dict[str, list[str]] = {}

    def write_loop(self):
        st = self.opt.state
        if st is None:
            return
        batch = self.opt.batch
        objs = np.asarray(batch.objective(st.solver.x))  # (S,) one fetch
        it = self.opt._iter
        for i, name in enumerate(self.opt.scenario_names):
            # rows buffer in memory (one small string per scenario-iter)
            # and flush periodically — 10k scenarios x 100s of iterations
            # of per-iteration open/append/close triples would gate the
            # host loop, but never flushing would lose everything on a
            # crashed run (the run a diagnoser exists for)
            self._rows.setdefault(name, []).append(f"{it},{objs[i]}\n")
        self._since_flush += 1
        if self._since_flush >= self.flush_period:
            self._flush()

    def _flush(self):
        for name, rows in self._rows.items():
            with open(os.path.join(self.dirname, f"{name}.dag"), "a") as f:
                f.writelines(rows)
        self._rows.clear()
        self._since_flush = 0

    def post_iter0(self):
        self.write_loop()

    def enditer(self):
        self.write_loop()

    def post_everything(self):
        self._flush()
