###############################################################################
# Diagnoser (ref:mpisppy/extensions/diagnoser.py:21-86): append one
# diagnostic line per scenario per iteration to
# `<diagnoser_outdir>/<scenario>.dag` — "iter,objective".
#
# The reference loops local Pyomo instances per rank; here the whole
# (S,) per-scenario objective vector comes back in ONE device fetch per
# iteration and the host fans it out to the files.  Same refusal to
# clobber an existing output directory as the reference (which quits);
# raising is friendlier than quit() for library use.
###############################################################################
from __future__ import annotations

import os

import numpy as np

from mpisppy_tpu.extensions.extension import Extension


class Diagnoser(Extension):
    """options come from ph.options.diagnoser_options
    {"diagnoser_outdir": path} (ref:diagnoser.py:28-40)."""

    def __init__(self, ph, options: dict | None = None):
        super().__init__(ph)
        opts = dict(options
                    or getattr(ph.options, "diagnoser_options", None)
                    or {})
        self.dirname = opts.get("diagnoser_outdir", "diagnostics")
        if os.path.exists(self.dirname):
            raise RuntimeError(
                f"Diagnoser: output directory exists: {self.dirname} "
                "(refusing to clobber, ref:diagnoser.py:29-34)")
        os.makedirs(self.dirname)
        self._rows: dict[str, list[str]] = {}

    def write_loop(self):
        st = self.opt.state
        if st is None:
            return
        batch = self.opt.batch
        objs = np.asarray(batch.objective(st.solver.x))  # (S,) one fetch
        it = self.opt._iter
        for i, name in enumerate(self.opt.scenario_names):
            # rows buffer in memory (one small string per scenario-iter)
            # and flush once at post_everything — 10k scenarios x 100s of
            # iterations of open/append/close triples would gate the host
            # loop otherwise
            self._rows.setdefault(name, []).append(f"{it},{objs[i]}\n")

    def _flush(self):
        for name, rows in self._rows.items():
            with open(os.path.join(self.dirname, f"{name}.dag"), "a") as f:
                f.writelines(rows)
        self._rows.clear()

    def post_iter0(self):
        self.write_loop()

    def enditer(self):
        self.write_loop()

    def post_everything(self):
        self._flush()
