###############################################################################
# Hub side of cross-scenario cuts
# (ref:mpisppy/extensions/cross_scen_extension.py:22-433).
#
# At construction it swaps the PH driver's batch for the eta-augmented
# one (static preallocated cut buffer, algos.cross_scen.augment_batch);
# each iteration it installs any new cut package from the
# CrossScenarioCutSpoke (functional .at[] writes — no recompilation) and
# periodically solves the batched EF objective for a certified outer
# bound (char 'C', ref:cross_scen_extension.py:80-128 _check_bound),
# gated the same way: only when the inner bound has not improved for
# `check_bound_improve_iterations` hub iterations.
###############################################################################
from __future__ import annotations

import math

import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.algos import cross_scen
from mpisppy_tpu.extensions.extension import Extension
from mpisppy_tpu.ops import pdhg


class CrossScenarioExtension(Extension):
    def __init__(self, ph, check_bound_improve_iterations: int | None = 4,
                 max_rounds: int = 8,
                 pdhg_opts: pdhg.PDHGOptions | None = None):
        super().__init__(ph)
        if ph.batch.tree.num_nodes != 1:
            raise RuntimeError("CrossScenarioExtension only supports "
                               "two-stage models at this time "
                               "(ref:cross_scen_extension.py:26-28)")
        self.check_bound_iterations = check_bound_improve_iterations
        self.pdhg_opts = pdhg_opts or pdhg.PDHGOptions(tol=1e-7,
                                                       max_iters=100_000)
        # augment the driver's batch in place: preallocated cut rows
        # (the eta-column EF view lives only in the meta)
        ph._cross_scen_orig_batch = ph.batch
        eta_lb = cross_scen.eta_lower_bounds(ph.batch, self.pdhg_opts)
        self.meta = cross_scen.make_meta(ph.batch, eta_lb,
                                         max_rounds=max_rounds)
        ph.batch = self.meta.aug_ph
        self.any_cuts = False
        self.cur_ib = math.inf
        self.iter_at_cur_ib = 0
        self.iter_since_last_check = 0
        self._ef_warm = None

    # -- cut installation -------------------------------------------------
    def _spoke(self):
        from mpisppy_tpu.cylinders.spoke import CrossScenarioCutSpoke
        spcomm = self.opt.spcomm
        if spcomm is None:
            return None
        for sp in getattr(spcomm, "spokes", []):
            if isinstance(sp, CrossScenarioCutSpoke):
                return sp
        return None

    def _get_cuts(self):
        sp = self._spoke()
        if sp is None or not sp.new_cuts:
            return
        sp.new_cuts = False
        # other extensions (e.g. ReducedCostsFixer) may have tightened
        # or collapsed boxes on the live batch; sync them into the PH
        # view BEFORE installing cuts so they are never reverted
        import dataclasses as _dc
        live = self.opt.batch.qp
        self.meta.aug_ph = _dc.replace(
            self.meta.aug_ph,
            qp=_dc.replace(self.meta.aug_ph.qp, l=live.l, u=live.u))
        cross_scen.write_cuts(self.meta, sp.cut_package)
        self.opt.batch = self.meta.aug_ph
        self.any_cuts = True
        self._ef_warm = None   # shapes same, but cuts moved the problem

    # -- periodic EF-objective bound check --------------------------------
    def _check_bound(self):
        bound, st = cross_scen.ef_check_bound(
            self.meta, self.pdhg_opts, st0=self._ef_warm)
        self._ef_warm = st
        if bound is not None and self.opt.spcomm is not None:
            self.opt.spcomm.OuterBoundUpdate(bound, "C")
            global_toc(f"cross-scen EF bound: {bound:.6g}",
                       self.opt.options.display_progress)

    def sync_with_spokes(self):
        """Hub-driven exchange point (ref:cross_scen_extension.py via
        hub.py:517-532): pull any fresh cut package off the cut spoke
        and install it.  Idempotent with the miditer pull (gated on the
        spoke's new_cuts flag), so bare-PH runs without a hub-driven
        hook plane still work."""
        self._get_cuts()

    def miditer(self):
        self._get_cuts()
        if self.check_bound_iterations is None or not self.any_cuts:
            return
        spcomm = self.opt.spcomm
        ib = spcomm.BestInnerBound if spcomm is not None else math.inf
        if ib != self.cur_ib:
            self.cur_ib = ib
            self.iter_at_cur_ib = self.opt._iter
        self.iter_since_last_check += 1
        stalled = (self.opt._iter - self.iter_at_cur_ib
                   >= self.check_bound_iterations)
        if stalled and \
                self.iter_since_last_check >= self.check_bound_iterations:
            self.iter_since_last_check = 0
            self._check_bound()

    def enditer(self):
        pass

    def post_everything(self):
        # one final bound attempt so late cuts count (respecting the
        # None = bound-checking-disabled setting, as in miditer)
        self._get_cuts()
        if self.any_cuts and self.check_bound_iterations is not None:
            self._check_bound()

    # parity attribute used by hub traces
    @property
    def cuts_installed(self) -> int:
        return self.meta.rounds_used * self.meta.S
