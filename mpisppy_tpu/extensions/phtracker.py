###############################################################################
# PHTracker: per-iteration tracking of convergence, bounds, gaps,
# nonants, duals, xbars and per-scenario solve quality, with optional
# plots (ref:mpisppy/extensions/phtracker.py:22-580: TrackedData
# buffers + per-quantity csv + plot_* helpers, per-cylinder folders).
#
# TPU-native differences: quantities come off the batched device state
# in one host transfer per tracked tensor (no per-variable Pyomo
# iteration), and "scenario gap" is the per-scenario relative KKT score
# of the batched subproblem solve — the batched analog of the
# per-scenario solver gaps the reference reads off Gurobi.
#
# Options (ctor kwargs, or a ph.options.phtracker_options dict which
# overrides them, mirroring the reference's options plumbing):
#   track_{convergence,gaps,bounds,nonants,duals,xbars,scen_gaps}
#   plot_{...} (matching plot flag per quantity), plots (default all)
#   save_every, write_every, results_folder, cylinder_name
###############################################################################
from __future__ import annotations

import os

import numpy as np

from mpisppy_tpu.extensions.extension import Extension


class TrackedData:
    """Buffered rows -> csv (ref:phtracker.py:22-101 TrackedData).

    Flushes go through the shared atomic-write helpers
    (utils/atomic_io.py): the first flush lands header+rows atomically
    (tmp + rename — a reader can never see a half-created file), later
    flushes append each row batch in one write (a crash tears at most
    the final batch's tail line, and I/O stays O(rows) over the run).
    Every buffered row is guaranteed to land on the final flush
    regardless of where the iteration count stopped relative to the
    save_every*write_every cadence (ISSUE 3 satellite)."""

    def __init__(self, name: str, folder: str, plot: bool = False):
        self.name = name
        self.fname = os.path.join(folder, f"{name}.csv")
        self.plot_fname = os.path.join(folder, f"{name}.png")
        self.plot = plot
        self.columns: list[str] | None = None
        self.rows: list[list] = []          # buffered, not yet on disk
        self._wrote_header = False

    def initialize_df(self, columns):
        self.columns = list(columns)

    def add_row(self, row):
        self.rows.append(list(row))

    def write_out_data(self):
        if self.columns is None:
            return
        from mpisppy_tpu.utils import atomic_io
        lines = [",".join(repr(v) if isinstance(v, float) else str(v)
                          for v in r) for r in self.rows]
        self.rows.clear()
        if not self._wrote_header:
            header = ",".join(map(str, self.columns))
            atomic_io.atomic_write_text(
                self.fname, "\n".join([header] + lines) + "\n")
            self._wrote_header = True
        elif lines:
            atomic_io.append_text(self.fname, "\n".join(lines) + "\n")


class PHTracker(Extension):
    _TENSOR_TRACKS = ("nonants", "duals", "xbars", "scen_gaps")
    _SCALAR_TRACKS = ("convergence", "gaps", "bounds")

    def __init__(self, ph, folder: str | None = None, name: str = "hub",
                 track_nonants: bool = False, track_duals: bool = False,
                 track_xbars: bool = False, track_scen_gaps: bool = False,
                 track_convergence: bool = True, track_gaps: bool = True,
                 track_bounds: bool = True, save_every: int = 1,
                 write_every: int = 3, plots: bool = False):
        super().__init__(ph)
        opts = getattr(ph.options, "phtracker_options", None) or {}
        self.folder = opts.get("results_folder", folder) or "phtracker_out"
        self.name = opts.get("cylinder_name", name)
        self.save_every = max(1, int(opts.get("save_every", save_every)))
        self.write_every = max(1, int(opts.get("write_every",
                                               write_every)))
        cyl_folder = os.path.join(self.folder, self.name)
        os.makedirs(cyl_folder, exist_ok=True)
        flags = {
            "convergence": track_convergence, "gaps": track_gaps,
            "bounds": track_bounds, "nonants": track_nonants,
            "duals": track_duals, "xbars": track_xbars,
            "scen_gaps": track_scen_gaps,
        }
        self.track_dict: dict[str, TrackedData] = {}
        for t in self._SCALAR_TRACKS + self._TENSOR_TRACKS:
            if opts.get(f"track_{t}", flags[t]):
                self.track_dict[t] = TrackedData(
                    t, cyl_folder, plot=opts.get(f"plot_{t}", plots))
        S = ph.batch.num_scenarios
        N = ph.batch.num_nonants
        heads = {
            "convergence": ["iteration", "conv"],
            "gaps": ["iteration", "abs_gap", "rel_gap"],
            "bounds": ["iteration", "outer", "inner", "eobj", "trivial"],
            "nonants": ["iteration"] + [f"x{s}_{j}" for s in range(S)
                                        for j in range(N)],
            "duals": ["iteration"] + [f"W{s}_{j}" for s in range(S)
                                      for j in range(N)],
            "xbars": ["iteration"] + [f"xbar{j}" for j in range(N)],
            "scen_gaps": ["iteration"] + [f"scen{s}" for s in range(S)],
        }
        for t, td in self.track_dict.items():
            td.initialize_df(heads[t])
        self._hub_row: dict | None = None
        self._subscribed_bus = None

    # -- data pulls -------------------------------------------------------
    # Hub scalars come off the telemetry spine (docs/telemetry.md): the
    # tracker subscribes to the hub's event bus and its bounds/gaps
    # rows derive from the SAME hub-iteration events as the JSONL
    # trace, so the two artifacts cannot diverge.  Tensor tracks
    # (nonants/duals/xbars/scen_gaps) still pull the device state
    # directly — they are bulk data no event carries.
    def _ensure_subscribed(self, hub):
        bus = getattr(hub, "telemetry", None)
        if bus is None or bus is self._subscribed_bus:
            return
        from mpisppy_tpu import telemetry as tel

        tracker = self

        class _HubRowCache(tel.Sink):
            def handle(self, event):
                if event.kind == tel.HUB_ITERATION \
                        and event.run == hub.run_id:
                    tracker._hub_row = dict(event.data)

        bus.subscribe(_HubRowCache())
        self._subscribed_bus = bus

    def _bounds(self):
        sp = self.opt.spcomm
        if sp is None:
            return float("nan"), float("nan"), float("nan"), float("nan")
        self._ensure_subscribed(sp)
        row = self._hub_row
        if row is not None:
            return (row["outer"], row["inner"],
                    row["abs_gap"], row["rel_gap"])
        # no hub-iteration event yet (enditer precedes this
        # iteration's sync): read the bookkeeping directly
        abs_gap, rel_gap = sp.compute_gaps()
        return sp.BestOuterBound, sp.BestInnerBound, abs_gap, rel_gap

    def enditer(self):
        ph = self.opt
        k = ph._iter
        if k % self.save_every:
            return
        conv = ph._read_conv()
        outer, inner, abs_gap, rel_gap = self._bounds()
        td = self.track_dict
        if "convergence" in td:
            td["convergence"].add_row([k, conv])
        if "gaps" in td:
            td["gaps"].add_row([k, abs_gap, rel_gap])
        if "bounds" in td:
            tb = ph.trivial_bound
            td["bounds"].add_row([k, outer, inner, ph.Eobjective(),
                                  float("nan") if tb is None else tb])
        if "nonants" in td:
            x = np.asarray(ph.batch.nonants(ph.state.solver.x)).reshape(-1)
            td["nonants"].add_row([k] + x.tolist())
        if "duals" in td:
            td["duals"].add_row(
                [k] + np.asarray(ph.state.W).reshape(-1).tolist())
        if "xbars" in td:
            td["xbars"].add_row(
                [k] + np.asarray(ph.state.xbar_nodes)[0].tolist())
        if "scen_gaps" in td:
            td["scen_gaps"].add_row(
                [k] + np.asarray(ph.state.solver.score).tolist())
        if k % (self.save_every * self.write_every) == 0:
            for t in td.values():
                t.write_out_data()

    def post_everything(self):
        for td in self.track_dict.values():
            td.write_out_data()
            if td.plot:
                self._plot(td)

    # -- plots (ref:phtracker.py:452-530 plot_* helpers) ------------------
    def _plot(self, td: TrackedData):
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            import pandas as pd
        except Exception:
            return  # plotting is best-effort (csv is the artifact)
        if not os.path.exists(td.fname):
            return
        df = pd.read_csv(td.fname)
        if df.empty:
            return
        fig, ax = plt.subplots(figsize=(7, 4))
        x = df["iteration"]
        ycols = [c for c in df.columns if c != "iteration"]
        # tensor tracks plot a handful of series, scalar tracks all
        for c in ycols[: 12 if td.name in self._TENSOR_TRACKS else 6]:
            ax.plot(x, df[c], label=c, lw=1)
        ax.set_xlabel("PH iteration")
        ax.set_title(f"{self.name}: {td.name}")
        ax.legend(fontsize=6, ncol=2)
        fig.tight_layout()
        fig.savefig(td.plot_fname, dpi=110)
        plt.close(fig)
