###############################################################################
# PHTracker: per-iteration csv tracking of convergence, bounds, gaps and
# (optionally) nonants/Ws (ref:mpisppy/extensions/phtracker.py:22-580).
# One row per PH iteration into <folder>/<name>.csv; tensor dumps go to
# npz per iteration when track_nonants/track_duals is set.
###############################################################################
from __future__ import annotations

import os

import numpy as np

from mpisppy_tpu.extensions.extension import Extension


class PHTracker(Extension):
    def __init__(self, ph, folder: str | None = None, name: str = "hub",
                 track_nonants: bool = False, track_duals: bool = False):
        super().__init__(ph)
        self.folder = folder or getattr(ph.options, "tracking_folder",
                                        None) or "phtracker_out"
        self.name = name
        self.track_nonants = track_nonants
        self.track_duals = track_duals
        os.makedirs(self.folder, exist_ok=True)
        self._f = open(os.path.join(self.folder, f"{name}.csv"), "w")
        self._f.write("iteration,conv,eobj,outer,inner,rel_gap\n")

    def _bounds(self):
        sp = self.opt.spcomm
        if sp is None:
            return float("nan"), float("nan"), float("nan")
        abs_gap, rel_gap = sp.compute_gaps()
        return sp.BestOuterBound, sp.BestInnerBound, rel_gap

    def enditer(self):
        ph = self.opt
        k = ph._iter
        conv = float(ph.state.conv)
        eobj = ph.Eobjective()
        outer, inner, rel_gap = self._bounds()
        self._f.write(f"{k},{conv},{eobj},{outer},{inner},{rel_gap}\n")
        self._f.flush()
        if self.track_nonants or self.track_duals:
            payload = {}
            if self.track_nonants:
                payload["nonants"] = np.asarray(
                    ph.batch.nonants(ph.state.solver.x))
            if self.track_duals:
                payload["W"] = np.asarray(ph.state.W)
            np.savez(os.path.join(self.folder,
                                  f"{self.name}_iter{k}.npz"), **payload)

    def post_everything(self):
        self._f.close()
