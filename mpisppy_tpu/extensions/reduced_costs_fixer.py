###############################################################################
# ReducedCostsFixer: hub-side heuristic fixing + bound tightening from
# the ReducedCostsSpoke's expected reduced costs
# (ref:mpisppy/extensions/reduced_costs_fixer.py:16-323).
#
# Mechanics (minimization):
#   * fixing (ref:reduced_costs_fixer.py:222-310): take the
#     (1 - fix_fraction_target) quantile of nonzero |rc| as the cutoff;
#     slots with |rc| >= cutoff and xbar at the matching bound get their
#     box collapsed to that bound (rc > 0 -> lb, rc < 0 -> ub); slots
#     whose rc went NaN (scenario disagreement) or fell below the cutoff
#     are UNFIXED (box restored) — unlike the WW Fixer, rc fixing is
#     reversible.
#   * bound tightening (ref:reduced_costs_fixer.py:123-220): with a
#     finite gap (ib - ob), a slot at lb with rc > 0 satisfies
#     x <= lb + gap/rc in every optimal solution (floor for integers);
#     symmetrically for ub.  Applied to the batch's boxes, monotone.
###############################################################################
from __future__ import annotations

import dataclasses
import math

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.extensions.extension import Extension


class ReducedCostsFixer(Extension):
    def __init__(self, ph, fix_fraction_target_iter0: float = 0.0,
                 fix_fraction_target_iterK: float = 0.0,
                 zero_rc_tol: float = 1e-4, bound_tol: float = 1e-6,
                 use_rc_bt: bool = False, use_rc_fixer: bool = True,
                 rc_fixer_require_improving_lagrangian: bool = True,
                 verbose: bool = False):
        super().__init__(ph)
        if ph.batch.tree.num_nodes != 1:
            raise RuntimeError("ReducedCostsFixer supports two-stage "
                               "problems only (xbar/consensus are "
                               "root-node reductions)")
        for f in (fix_fraction_target_iter0, fix_fraction_target_iterK):
            if not 0.0 <= f <= 1.0:
                raise ValueError("fix fraction targets must be in [0,1]")
        self._f_iter0 = fix_fraction_target_iter0
        self._f_iterK = fix_fraction_target_iterK
        self.fix_fraction_target = fix_fraction_target_iter0
        self.zero_rc_tol = zero_rc_tol
        self.bound_tol = bound_tol
        self.use_rc_bt = use_rc_bt
        self.use_rc_fixer = use_rc_fixer
        self.require_improving = rc_fixer_require_improving_lagrangian
        self.verbose = verbose

        b = ph.batch
        self._lb0, self._ub0 = b.nonant_box()
        self._lb = self._lb0.copy()   # current (possibly tightened)
        self._ub = self._ub0.copy()
        N = b.num_nonants
        self.fixed_mask = np.zeros(N, bool)
        self._fix_val = np.zeros(N)
        self._best_ob = -math.inf
        self.n_tightened = 0

    def nfixed(self) -> int:
        return int(self.fixed_mask.sum())

    def post_iter0(self):
        self.fix_fraction_target = self._f_iterK

    # -- helpers ----------------------------------------------------------
    def _spoke(self):
        from mpisppy_tpu.cylinders.spoke import ReducedCostsSpoke
        spcomm = self.opt.spcomm
        if spcomm is None:
            return None
        for sp in getattr(spcomm, "spokes", []):
            if isinstance(sp, ReducedCostsSpoke):
                return sp
        return None

    def _apply_boxes(self):
        """Install current (lb, ub, fixed) into the batch (scaled)."""
        ph = self.opt
        batch = ph.batch
        qp = batch.qp
        nonant_idx = np.asarray(batch.nonant_idx)
        S, n = batch.qp.c.shape
        lb = np.where(self.fixed_mask, self._fix_val, self._lb)
        ub = np.where(self.fixed_mask, self._fix_val, self._ub)
        d = np.broadcast_to(np.asarray(batch.d_non), (S, len(nonant_idx)))
        l_full = jnp.broadcast_to(qp.l, (S, n))
        u_full = jnp.broadcast_to(qp.u, (S, n))
        ph.batch = dataclasses.replace(batch, qp=dataclasses.replace(
            qp,
            l=l_full.at[:, nonant_idx].set(jnp.asarray(lb / d, qp.l.dtype)),
            u=u_full.at[:, nonant_idx].set(jnp.asarray(ub / d, qp.u.dtype)),
        ))

    # -- the work ---------------------------------------------------------
    def sync_with_spokes(self):
        """Hub-driven exchange point (ref:reduced_costs_fixer via
        hub.py:517-532): consume fresh reduced costs as soon as the hub
        harvests them.  Idempotent with the miditer pull (gated on the
        spoke's new_rc flag)."""
        self.miditer()

    def miditer(self):
        sp = self._spoke()
        if sp is None or not sp.new_rc or sp.rc_global is None:
            return
        sp.new_rc = False
        rc = sp.rc_global
        spcomm = self.opt.spcomm
        ob = spcomm.BestOuterBound if spcomm is not None else -math.inf
        improving = ob > self._best_ob
        self._best_ob = max(self._best_ob, ob)

        changed = False
        if self.use_rc_bt:
            changed |= self._bounds_tightening(
                rc, getattr(sp, "last_lagrangian_bound", None))
        if self.use_rc_fixer and self.fix_fraction_target > 0.0:
            if improving or not self.require_improving:
                changed |= self._fixing(rc)
        if changed:
            self._apply_boxes()

    def _bounds_tightening(self, rc: np.ndarray,
                           lagrangian_bound: float | None) -> bool:
        spcomm = self.opt.spcomm
        if spcomm is None or lagrangian_bound is None:
            return False
        ib = spcomm.BestInnerBound
        # the rc theorem needs the gap against the bound of the SAME
        # dual solution the rcs came from — NOT the historical best
        # outer bound, which another spoke may have pushed higher and
        # would understate the gap (cutting off the optimum)
        ob = lagrangian_bound
        if not (math.isfinite(ib) and math.isfinite(ob)):
            return False
        gap = max(ib - ob, 0.0)
        is_int = np.asarray(self.opt.batch.integer_slot)
        ok = np.isfinite(rc)
        pos = ok & (rc > self.zero_rc_tol)
        neg = ok & (rc < -self.zero_rc_tol)
        new_ub = np.where(pos, self._lb + gap / np.where(pos, rc, 1.0),
                          np.inf)
        new_lb = np.where(neg, self._ub + gap / np.where(neg, rc, 1.0),
                          -np.inf)
        new_ub = np.where(is_int, np.floor(new_ub + 1e-9), new_ub)
        new_lb = np.where(is_int, np.ceil(new_lb - 1e-9), new_lb)
        tighter_u = new_ub < self._ub - 1e-12
        tighter_l = new_lb > self._lb + 1e-12
        self._ub = np.where(tighter_u, new_ub, self._ub)
        self._lb = np.where(tighter_l, new_lb, self._lb)
        cnt = int(tighter_u.sum() + tighter_l.sum())
        self.n_tightened += cnt
        if cnt and self.verbose:
            global_toc(f"rc bound tightening: {cnt} bounds", True)
        return cnt > 0

    def _fixing(self, rc: np.ndarray) -> bool:
        if np.all(np.isnan(rc)):
            return False
        abs_rc = np.abs(rc)
        nonzero = abs_rc[abs_rc > self.zero_rc_tol]
        if len(nonzero) == 0:
            target = self.zero_rc_tol
        else:
            target = np.nanquantile(nonzero,
                                    1.0 - self.fix_fraction_target,
                                    method="median_unbiased")
        target = max(target, self.zero_rc_tol)

        st = self.opt.state
        xbar = np.asarray(st.xbar_nodes)[0] if st is not None else None

        changed = False
        for i in range(len(rc)):
            if np.isnan(abs_rc[i]) or abs_rc[i] < target:
                if self.fixed_mask[i]:      # unfix (reversible)
                    self.fixed_mask[i] = False
                    changed = True
                continue
            if self.fixed_mask[i]:
                continue
            near_lb = xbar is None or \
                xbar[i] - self._lb[i] <= max(self.bound_tol, 1e-4)
            near_ub = xbar is None or \
                self._ub[i] - xbar[i] <= max(self.bound_tol, 1e-4)
            if rc[i] > self.zero_rc_tol and near_lb:
                self._fix_val[i] = self._lb[i]
            elif rc[i] < -self.zero_rc_tol and near_ub:
                self._fix_val[i] = self._ub[i]
            else:
                continue
            self.fixed_mask[i] = True
            changed = True
        if changed and self.verbose:
            global_toc(f"rc fixer: {self.nfixed()} fixed", True)
        return changed
