###############################################################################
# Extension ABC — the hub's callback plane
# (ref:mpisppy/extensions/extension.py:18-151).  The PH driver calls the
# hook methods at fixed points (algos/ph.py _ext); extensions read and
# mutate the driver (`self.opt`): its options, its device-resident
# PHState (via dataclasses.replace on host), or its batch (e.g. the
# Fixer collapses nonant boxes).  All 14 reference callout points exist;
# PH drives pre_iter0/iter0_post_solver_creation/post_iter0/
# post_iter0_after_sync/miditer/pre_solve_loop/post_solve_loop/enditer/
# enditer_after_sync/post_everything at the reference's callout points
# (ref:mpisppy/phbase.py:829-1061), and the cylinder layer drives
# setup_hub/sync_with_spokes.  pre_solve/post_solve (per-SUBPROBLEM
# hooks) have no per-scenario callout in the batched design — the whole
# solve loop is one program — so they fire only via MultiExtension
# users calling them explicitly.
###############################################################################
from __future__ import annotations


class Extension:
    """ref:mpisppy/extensions/extension.py:18."""

    def __init__(self, ph):
        self.opt = ph

    def pre_iter0(self):
        pass

    def iter0_post_solver_creation(self):
        pass

    def post_iter0(self):
        pass

    def post_iter0_after_sync(self):
        pass

    def miditer(self):
        pass

    def enditer(self):
        pass

    def enditer_after_sync(self):
        pass

    def post_everything(self):
        pass

    def pre_solve_loop(self):
        pass

    def post_solve_loop(self):
        pass

    def pre_solve(self, subproblem=None):
        pass

    def post_solve(self, subproblem=None, results=None):
        pass

    def setup_hub(self):
        pass

    def initialize_spoke_indices(self):
        pass

    def sync_with_spokes(self):
        pass


class MultiExtension(Extension):
    """Compose several extensions; each hook fans out in order
    (ref:mpisppy/extensions/extension.py:154-226)."""

    def __init__(self, ph, ext_classes):
        super().__init__(ph)
        self.extdict = {}
        for cls in ext_classes:
            # classes, factories, and functools.partial(s) all work
            name = getattr(cls, "__name__", None) \
                or getattr(getattr(cls, "func", None), "__name__", None) \
                or f"ext{len(self.extdict)}"
            self.extdict[name] = cls(ph)

    def _fan(self, hook, *args):
        for ext in self.extdict.values():
            getattr(ext, hook)(*args)


for _hook in ["pre_iter0", "iter0_post_solver_creation", "post_iter0",
              "post_iter0_after_sync", "miditer", "enditer",
              "enditer_after_sync", "post_everything", "pre_solve_loop",
              "post_solve_loop", "pre_solve", "post_solve", "setup_hub",
              "initialize_spoke_indices", "sync_with_spokes"]:
    def _make(h):
        def f(self, *args):
            self._fan(h, *args)
        f.__name__ = h
        return f
    setattr(MultiExtension, _hook, _make(_hook))
del _hook, _make
