# Scaffolding extension that records every callout in order — the
# analog of ref:mpisppy/extensions/test_extension.py:15, used by the
# test suite to prove the driver actually fires each hook at the
# documented point in the iteration sequence.
from mpisppy_tpu.extensions.extension import Extension


class TestExtension(Extension):
    """Appends each hook name to self.opt._TestExtension_who_is_called
    (a list on the driver, so MultiExtension composition and driver
    rebuilds both keep one shared trace)."""

    def __init__(self, ph):
        super().__init__(ph)
        if not hasattr(ph, "_TestExtension_who_is_called"):
            ph._TestExtension_who_is_called = []
        self.who_is_called = ph._TestExtension_who_is_called

    def _record(self, name):
        self.who_is_called.append(name)


def _make_hook(name):
    def hook(self, *args, **kwargs):
        self._record(name)
    hook.__name__ = name
    return hook


for _h in ("pre_iter0", "iter0_post_solver_creation", "post_iter0",
           "post_iter0_after_sync", "miditer", "enditer",
           "enditer_after_sync", "post_everything", "pre_solve_loop",
           "post_solve_loop", "pre_solve", "post_solve", "setup_hub",
           "initialize_spoke_indices", "sync_with_spokes"):
    setattr(TestExtension, _h, _make_hook(_h))
