# W-oscillation diagnostics as a PH extension — import-path parity with
# ref:mpisppy/extensions/wtracker_extension.py:15 (the implementation
# lives with its WTracker in utils/wtracker.py).
from mpisppy_tpu.utils.wtracker import WTracker, WTrackerExtension

__all__ = ["WTracker", "WTrackerExtension"]

Wtracker_extension = WTrackerExtension  # reference class-name spelling
