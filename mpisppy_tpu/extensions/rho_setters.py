###############################################################################
# Rho adaptation family (ref:mpisppy/extensions/norm_rho_updater.py:39,
# sep_rho.py:17, coeff_rho.py:15).
#
# All three mutate the (N,)-vector rho carried in the device PHState —
# a host-side dataclasses.replace between jitted steps, no recompile
# (rho is data, not a static).
###############################################################################
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.extensions.extension import Extension


def _set_rho(ph, rho_new) -> None:
    rho = jnp.asarray(rho_new, ph.batch.qp.c.dtype)
    ph.rho = rho
    if ph.state is not None:
        ph.state = dataclasses.replace(ph.state, rho=rho)


def _orig_cost_per_slot(batch) -> np.ndarray:
    """|c_i| of each nonant slot in ORIGINAL space, averaged over
    scenarios (scaled c absorbs d_col: c_orig = c_scaled / d_col)."""
    c = np.asarray(batch.qp.c)
    d_col = np.asarray(batch.d_col)
    idx = np.asarray(batch.nonant_idx)
    c_orig = c / d_col
    c_non = c_orig[..., idx]
    if c_non.ndim == 2:
        c_non = np.abs(c_non).mean(axis=0)
    return np.abs(c_non)


class NormRhoUpdater(Extension):
    """Residual balancing (ref:mpisppy/extensions/norm_rho_updater.py:39):
    grow rho when the primal nonanticipativity residual dominates the
    dual movement, shrink when the dual dominates (ADMM mu/tau rule)."""

    def __init__(self, ph, mu: float = 10.0, tau: float = 2.0):
        super().__init__(ph)
        self.mu = mu
        self.tau = tau
        self._prev_xbar = None

    def enditer(self):
        ph = self.opt
        st = ph.state
        batch = ph.batch
        x_non = batch.nonants(st.solver.x)
        primal = float(batch.expectation(
            jnp.sum(jnp.abs(x_non - st.xbar), axis=-1)))
        xbar_nodes = np.asarray(st.xbar_nodes)
        if self._prev_xbar is not None:
            rho = np.asarray(st.rho)
            dual = float(np.sum(np.abs(
                rho.mean() * (xbar_nodes - self._prev_xbar))))
            if dual > 0:
                if primal > self.mu * dual:
                    _set_rho(ph, np.asarray(st.rho) * self.tau)
                elif dual > self.mu * primal:
                    _set_rho(ph, np.asarray(st.rho) / self.tau)
        self._prev_xbar = xbar_nodes


class SepRho(Extension):
    """Watson-Woodruff per-variable rho (ref:mpisppy/extensions/
    sep_rho.py:17): rho_i = |c_i| / (max_s x_i - min_s x_i + 1), from
    the iter0 solutions."""

    def __init__(self, ph, multiplier: float = 1.0):
        super().__init__(ph)
        self.multiplier = float(
            getattr(ph.options, "sep_rho_multiplier", multiplier))

    def post_iter0(self):
        ph = self.opt
        batch = ph.batch
        x_non = np.asarray(batch.nonants(ph.state.solver.x))
        real = np.asarray(batch.p > 0.0)
        xr = x_non[real]
        spread = xr.max(axis=0) - xr.min(axis=0)
        cost = _orig_cost_per_slot(batch)
        _set_rho(ph, self.multiplier * cost / (spread + 1.0))


class CoeffRho(Extension):
    """rho_i = multiplier * |c_i|
    (ref:mpisppy/extensions/coeff_rho.py:15)."""

    def __init__(self, ph, multiplier: float = 0.1):
        super().__init__(ph)
        self.multiplier = float(
            getattr(ph.options, "coeff_rho_multiplier", multiplier))

    def post_iter0(self):
        batch = self.opt.batch
        cost = _orig_cost_per_slot(batch)
        _set_rho(self.opt, self.multiplier * np.maximum(cost, 1e-6))
