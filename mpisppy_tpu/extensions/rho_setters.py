###############################################################################
# Rho adaptation family (ref:mpisppy/extensions/norm_rho_updater.py:39,
# sep_rho.py:17, coeff_rho.py:15).
#
# All three mutate the (N,)-vector rho carried in the device PHState —
# a host-side dataclasses.replace between jitted steps, no recompile
# (rho is data, not a static).
###############################################################################
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.extensions.extension import Extension


def _set_rho(ph, rho_new) -> None:
    rho = jnp.asarray(rho_new, ph.batch.qp.c.dtype)
    ph.rho = rho
    if ph.state is not None:
        ph.state = dataclasses.replace(ph.state, rho=rho)


def _orig_cost_per_slot(batch) -> np.ndarray:
    """|c_i| of each nonant slot in ORIGINAL space, averaged over
    scenarios (scaled c absorbs d_col: c_orig = c_scaled / d_col)."""
    c = np.asarray(batch.qp.c)
    d_col = np.asarray(batch.d_col)
    idx = np.asarray(batch.nonant_idx)
    c_orig = c / d_col
    c_non = c_orig[..., idx]
    if c_non.ndim == 2:
        c_non = np.abs(c_non).mean(axis=0)
    return np.abs(c_non)


class NormRhoUpdater(Extension):
    """Residual balancing (ref:mpisppy/extensions/norm_rho_updater.py:39):
    grow rho when the primal nonanticipativity residual dominates the
    dual movement, shrink when the dual dominates (ADMM mu/tau rule)."""

    def __init__(self, ph, mu: float = 10.0, tau: float = 2.0):
        super().__init__(ph)
        self.mu = mu
        self.tau = tau
        self._prev_xbar = None

    def enditer(self):
        ph = self.opt
        st = ph.state
        batch = ph.batch
        x_non = batch.nonants(st.solver.x)
        primal = float(batch.expectation(
            jnp.sum(jnp.abs(x_non - st.xbar), axis=-1)))
        xbar_nodes = np.asarray(st.xbar_nodes)
        if self._prev_xbar is not None:
            rho = np.asarray(st.rho)
            dual = float(np.sum(np.abs(
                rho.mean() * (xbar_nodes - self._prev_xbar))))
            if dual > 0:
                if primal > self.mu * dual:
                    _set_rho(ph, np.asarray(st.rho) * self.tau)
                elif dual > self.mu * primal:
                    _set_rho(ph, np.asarray(st.rho) / self.tau)
        self._prev_xbar = xbar_nodes


class SepRho(Extension):
    """Watson-Woodruff per-variable rho (ref:mpisppy/extensions/
    sep_rho.py:17): rho_i = |c_i| / (max_s x_i - min_s x_i + 1), from
    the iter0 solutions."""

    def __init__(self, ph, multiplier: float = 1.0):
        super().__init__(ph)
        self.multiplier = float(
            getattr(ph.options, "sep_rho_multiplier", multiplier))

    def post_iter0(self):
        ph = self.opt
        batch = ph.batch
        x_non = np.asarray(batch.nonants(ph.state.solver.x))
        real = np.asarray(batch.p > 0.0)
        xr = x_non[real]
        spread = xr.max(axis=0) - xr.min(axis=0)
        cost = _orig_cost_per_slot(batch)
        rho = self.multiplier * cost / (spread + 1.0)
        # Zero-cost nonants (e.g. hydro's reservoir volumes: pure state
        # variables) would get rho = 0 and never reach consensus — PH's
        # W update is rho-scaled, so a zero stays zero forever and x̄
        # wanders on those slots (measured: hydro's inner bound never
        # published).  Floor them at a tenth of the mean positive rho.
        pos = rho[rho > 0.0]
        if pos.size:
            rho = np.maximum(rho, 0.1 * float(pos.mean()))
        else:
            rho = np.full_like(rho, self.multiplier)
        _set_rho(ph, rho)


class CoeffRho(Extension):
    """rho_i = multiplier * |c_i|
    (ref:mpisppy/extensions/coeff_rho.py:15)."""

    def __init__(self, ph, multiplier: float = 0.1):
        super().__init__(ph)
        self.multiplier = float(
            getattr(ph.options, "coeff_rho_multiplier", multiplier))

    def post_iter0(self):
        batch = self.opt.batch
        cost = _orig_cost_per_slot(batch)
        _set_rho(self.opt, self.multiplier * np.maximum(cost, 1e-6))


class MultRhoUpdater(Extension):
    """Multiplicative rho schedule
    (ref:mpisppy/extensions/mult_rho_updater.py:32): every
    `mult_rho_update_interval` iterations after `_first_iter`, rho *=
    `mult_rho_update_factor` (stopping after `_last_iter`)."""

    def __init__(self, ph, mult_rho_update_factor: float = 2.0,
                 mult_rho_update_interval: int = 2,
                 first_iter: int = 2, last_iter: int | None = None):
        super().__init__(ph)
        self.factor = mult_rho_update_factor
        self.interval = mult_rho_update_interval
        self.first_iter = first_iter
        # None = never stop (the reference default)
        self.last_iter = last_iter

    def miditer(self):
        ph = self.opt
        it = ph._iter
        if (self.first_iter <= it
                and (self.last_iter is None or it <= self.last_iter)
                and (it - self.first_iter) % self.interval == 0):
            _set_rho(ph, np.asarray(ph.state.rho) * self.factor)


class SensiRho(Extension):
    """KKT-sensitivity-based rho
    (ref:mpisppy/extensions/sensi_rho.py:15,75): per-slot rho from the
    order-stat aggregation of per-scenario |nonant sensitivities| at
    the iter0 solves, scaled by `sensi_rho_multiplier`."""

    def __init__(self, ph, sensi_rho_multiplier: float = 1.0,
                 order_stat: float = 0.5):
        super().__init__(ph)
        self.multiplier = sensi_rho_multiplier
        self.order_stat = order_stat

    def post_iter0(self):
        from mpisppy_tpu.utils.gradient import order_stat_aggregate
        from mpisppy_tpu.utils.nonant_sensitivities import (
            nonant_sensitivities,
        )
        ph = self.opt
        sens = np.abs(nonant_sensitivities(ph.batch, ph.state.solver))
        p = np.asarray(ph.batch.p, np.float64)
        rho = order_stat_aggregate(sens, p, self.order_stat)
        rho = np.maximum(rho, 1e-6) * self.multiplier
        _set_rho(ph, rho)


class ReducedCostsRho(Extension):
    """rho from expected |reduced costs| of the LP-LR solve
    (ref:mpisppy/extensions/reduced_costs_rho.py:15) — identical
    machinery to SensiRho here (both read the solve's reduced costs),
    kept as its own class for the reference's option surface with its
    own multiplier."""

    def __init__(self, ph, rc_rho_multiplier: float = 1.0):
        super().__init__(ph)
        self._inner = SensiRho(ph, sensi_rho_multiplier=rc_rho_multiplier)

    def post_iter0(self):
        self._inner.post_iter0()


class Gradient_extension(Extension):
    """Dynamic gradient-based rho
    (ref:mpisppy/extensions/gradient_extension.py:18, base
    ref:dyn_rho_base.py:22): recompute the WW-heuristic rho every
    `grad_rho_update_interval` iterations from the current iterates
    (Find_Rho with fresh gradient costs), gated after iter 1."""

    def __init__(self, ph, grad_order_stat: float = 0.5,
                 grad_rho_update_interval: int = 5,
                 indep_denom: bool = False,
                 grad_rho_relative_bound: float = 1e3):
        super().__init__(ph)
        from mpisppy_tpu.utils.gradient import Find_Rho
        self.interval = grad_rho_update_interval
        self.indep_denom = indep_denom
        self._finder = Find_Rho(ph, {
            "grad_order_stat": grad_order_stat,
            "grad_rho_relative_bound": grad_rho_relative_bound})

    def miditer(self):
        ph = self.opt
        if ph._iter < 2 or (ph._iter - 2) % self.interval != 0:
            return
        self._finder.c = None  # refresh gradient costs at the iterates
        rho = self._finder.compute_rho(indep_denom=self.indep_denom)
        rho = np.maximum(rho, 1e-6)
        _set_rho(ph, rho)
