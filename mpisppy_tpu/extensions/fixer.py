###############################################################################
# Fixer: WW-style fixing of (near-)converged nonants
# (ref:mpisppy/extensions/fixer.py:27-335).
#
# The reference watches per-variable convergence (xbar/xsqbar variance
# plus iteration-count lags from a user Fixer_tuple) and fixes Pyomo
# vars in every scenario.  Here the per-slot statistic is the
# cross-scenario spread |x_s,i - xbar_i| reduced on device; a slot that
# stays converged for `lag` consecutive iterations is fixed by
# collapsing its box in the batch's qp to the (rounded, for integer
# slots) node average — after which every subsequent batched solve
# treats it as a constant.  Fixing is monotone (never unfixed), matching
# the reference default.
###############################################################################
from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.extensions.extension import Extension


class Fixer(Extension):
    """options read from ph.options when present: fixer_lag (default 5),
    fixer_tol (1e-4), fixer_integer_only (True)."""

    def __init__(self, ph):
        super().__init__(ph)
        opt = ph.options
        self.lag = int(getattr(opt, "fixer_lag", 5))
        self.tol = float(getattr(opt, "fixer_tol", 1e-4))
        self.integer_only = bool(getattr(opt, "fixer_integer_only", True))
        N = ph.batch.num_nonants
        self._streak = np.zeros(N, np.int64)
        self.fixed_mask = np.zeros(N, bool)

    def nfixed(self) -> int:
        return int(self.fixed_mask.sum())

    def enditer(self):
        ph = self.opt
        batch = ph.batch
        st = ph.state
        x_non = batch.nonants(st.solver.x)
        real = (batch.p > 0.0)[:, None]
        spread = np.asarray(jnp.max(
            jnp.where(real, jnp.abs(x_non - st.xbar), 0.0), axis=0))
        conv = spread <= self.tol
        self._streak = np.where(conv, self._streak + 1, 0)

        eligible = ~self.fixed_mask & (self._streak >= self.lag)
        if self.integer_only:
            eligible &= np.asarray(batch.integer_slot)
        if not eligible.any():
            return

        idx = np.nonzero(eligible)[0]
        # per-scenario fix values: each scenario's slot is pinned to ITS
        # owning tree node's average (multistage-correct; for two-stage
        # every row reads the ROOT average)
        node_of_slot = np.asarray(batch.node_of_slot)          # (S, N)
        xbar_nodes = np.asarray(st.xbar_nodes)                 # (nodes, N)
        vals = xbar_nodes[node_of_slot[:, idx], idx]           # (S, k)
        is_int = np.asarray(batch.integer_slot)[idx]
        vals = np.where(is_int, np.round(vals), vals)

        # collapse the box at the fixed slots (scaled space, per scenario)
        qp = batch.qp
        d_non = np.asarray(batch.d_non)
        d = d_non[idx] if d_non.ndim == 1 else d_non[:, idx]
        cols = np.asarray(batch.nonant_idx)[idx]
        xs = jnp.asarray(vals / d, qp.l.dtype)                 # (S, k)
        S, n = batch.qp.c.shape
        l_full = jnp.broadcast_to(qp.l, (S, n))
        u_full = jnp.broadcast_to(qp.u, (S, n))
        ph.batch = dataclasses.replace(batch, qp=dataclasses.replace(
            qp, l=l_full.at[:, cols].set(xs),
            u=u_full.at[:, cols].set(xs)))
        self.fixed_mask[idx] = True
