###############################################################################
# Spoke taxonomy (ref:mpisppy/cylinders/spoke.py:21-380) and the concrete
# bound spokes, TPU-native.
#
# A spoke consumes the hub's latest (W, nonants, xbar) snapshot and
# produces a bound.  In the reference each spoke is an MPI cylinder
# re-solving its own copy of every scenario with a CPU solver; here each
# spoke is a *batched device computation over the same HBM-resident
# ScenarioBatch*, launched without blocking (XLA async dispatch) so hub
# iterations overlap spoke solves — the TPU answer to the reference's
# asynchronous cylinders.  The hub reads `bound` later, blocking only on
# the scalar.
#
# Spoke map (ref file -> class here):
#   lagrangian_bounder.py:53-98  -> LagrangianOuterBound  (consumes W)
#   lagranger_bounder.py:18+     -> LagrangerOuterBound   (consumes x, own W)
#   subgradient_bounder.py:12-54 -> SubgradientOuterBound (self-contained)
#   xhatxbar_bounder.py:37       -> XhatXbarInnerBound
#   xhatshufflelooper_bounder.py -> XhatShuffleInnerBound
#   slam_heuristic.py:25-129     -> SlamMaxHeuristic/SlamMinHeuristic
###############################################################################
from __future__ import annotations

import enum
import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.algos import lagrangian as lag_mod
from mpisppy_tpu.algos import xhat as xhat_mod
from mpisppy_tpu.cylinders.spcommunicator import SPCommunicator
from mpisppy_tpu.ops import pdhg


class ConvergerSpokeType(enum.Enum):
    """ref:mpisppy/cylinders/spoke.py:21-25."""

    OUTER_BOUND = 1
    INNER_BOUND = 2
    W_GETTER = 3
    NONANT_GETTER = 4


class Spoke(SPCommunicator):
    """Base spoke: runs against the hub's ScenarioBatch snapshot."""

    converger_spoke_types: tuple[ConvergerSpokeType, ...] = ()

    def __init__(self, opt, options: dict | None = None):
        super().__init__(opt, options)
        self.batch = opt.batch
        self.pdhg_opts = self.options.get(
            "pdhg_opts", pdhg.PDHGOptions(tol=1e-6))
        self.bound: float | None = None
        self._pending = None  # un-read device results (async dispatch)
        self.trace: list[tuple[int, float]] = []  # (hub_iter, bound)
        # resilience bookkeeping (docs/resilience.md): the hub counts a
        # strike per rejected bound and flips `disabled` after K — a
        # disabled spoke is neither updated nor harvested again
        self.strikes = 0
        self.disabled = False

    def update(self, hub_payload: dict):
        """Launch this spoke's computation for the hub snapshot.  Must
        not block on device results."""
        raise NotImplementedError

    def harvest(self) -> float | None:
        """Read the last launched result (blocks on the scalar only),
        update self.bound, return it."""
        raise NotImplementedError

    def main(self):  # spokes are driven by the wheel, not self-running
        pass


class OuterBoundSpoke(Spoke):
    """Outer (lower, for min) bounds — only CERTIFIED results accepted
    (ref:mpisppy/cylinders/spoke.py:250-275).  Subclasses leave a
    LagrangianResult-like object (bound, certified) in self._pending."""

    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,)
    bound_sense = "outer"

    def harvest(self):
        if self._pending is None:
            return None
        res = self._pending
        if bool(res.certified):
            b = float(res.bound)
            # a non-finite bound must not become the cached best: every
            # later `b > NaN` comparison is False, so one poisoned solve
            # would pin the spoke at NaN forever (quarantine-at-source;
            # the hub additionally validates + strikes, hub.py)
            if math.isfinite(b) and (self.bound is None
                                     or b > self.bound):
                self.bound = b
        return self.bound


class InnerBoundSpoke(Spoke):
    """Incumbent finders; keeps the best (xhat, value) pair so the
    winning solution can be written out (ref:mpisppy/cylinders/
    spoke.py:242-248,325-367 update_if_improving + best cache).

    Publication is gated on BOTH feasibility and comp-tightness
    (xhat.comp_tight): the evaluators' first-order infeasibility
    compensation makes values only approximately certified, so a value
    whose compensation is a material fraction of the bound stays
    unpublished — the same gate the fused planes (_eval_step) and
    EFXhatInnerBound enforce."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,)
    bound_sense = "inner"

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        self.best_xhat = None  # (num_nodes, N) or (N,) candidate
        self.comp_tol = float(self.options.get(
            "comp_tol", xhat_mod.DEFAULT_COMP_TOL))

    def _offer(self, value: float, xhat) -> None:
        if not math.isfinite(value):
            return  # never cache a poisoned incumbent (see OuterBound)
        if self.bound is None or value < self.bound:
            self.bound = value
            self.best_xhat = np.asarray(xhat)

    def _finalize(self, res, xhat):
        """Hook applied at HARVEST (blocking is fine here): subclasses
        run the stalled-tail rescue so Spoke.update stays async."""
        return res

    def harvest(self):
        if self._pending is None:
            return None
        res, xhat = self._pending
        res = self._finalize(res, xhat)
        if bool(res.feasible) and xhat_mod.comp_tight(self.batch, res,
                                                      self.comp_tol):
            self._offer(float(res.value), xhat)
        return self.bound


# ---------------------------------------------------------------------------
# Fused spokes (pair with algos.fused_wheel.FusedPH): the device work
# lives INSIDE the hub's jitted step; these objects only read the
# resulting scalars at harvest.  `fused = True` makes the hub
# harvest them every iteration (they are free) regardless of
# spoke_sync_period.
# ---------------------------------------------------------------------------
class FusedLagrangianOuterBound(OuterBoundSpoke):
    """Reads the in-step Lagrangian bound off FusedWheelState — the
    fused analog of LagrangianOuterBound (same certificate gating)."""

    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,
                             ConvergerSpokeType.W_GETTER)
    converger_spoke_char = "L"
    fused = True

    def update(self, hub_payload):
        pass  # computation rides inside FusedPH's jitted step

    def harvest(self):
        sc = getattr(self.opt, "scalar_cache", None)
        if sc is None:
            return self.bound
        if sc["lag_certified"] > 0.5:
            b = sc["lag_bound"]
            # same non-finite cache refusal as OuterBoundSpoke.harvest
            if math.isfinite(b) and (self.bound is None
                                     or b > self.bound):
                self.bound = b
        return self.bound


class FusedXhatXbarInnerBound(InnerBoundSpoke):
    """Reads the in-step x̂ = round(x̄) recourse value off
    FusedWheelState — the fused analog of XhatXbarInnerBound.

    Fallback: if the truncated in-loop evaluation has not produced a
    feasible value for `rescue_after` consecutive harvests (a stalled
    recourse tail), one blocking full evaluation with the rescue tiers
    runs at harvest — bounded, and amortized to once per stall."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)
    converger_spoke_char = "X"
    fused = True

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        self.rescue_after = int(self.options.get("rescue_after", 40))
        self._dry_harvests = 0

    def update(self, hub_payload):
        pass

    def harvest(self):
        sc = getattr(self.opt, "scalar_cache", None)
        if sc is None:
            return self.bound
        if sc["xhat_feasible"] > 0.5:
            self._dry_harvests = 0
            # cand_cache rides the same pipeline as scalar_cache, so the
            # value is always paired with the candidate it was evaluated
            # at; the tensor transfers only on an actual offer
            if self.bound is None or sc["xhat_value"] < self.bound:
                self._offer(sc["xhat_value"],
                            np.asarray(self.opt.cand_cache["xhat"]))
            return self.bound
        self._dry_harvests += 1
        if self._dry_harvests >= self.rescue_after:
            self._dry_harvests = 0
            if sc.get("xhat_dead", 0.0) > 0.5:
                # the candidate is CERTIFIED recourse-infeasible — a
                # blocking to-convergence rescue would spend ~a minute
                # re-proving it (observed); the plane is already
                # rotating to a new candidate
                return self.bound
            cand = jnp.asarray(self.opt.cand_cache["xhat"])
            # warm rescue: start from the in-loop plane's solver state
            # (it has been tracking this candidate for many exchanges)
            # instead of a cold to-convergence solve, and fold the
            # polished state back so the plane keeps the benefit
            wstate = getattr(self.opt, "wstate", None)
            if wstate is not None:
                res, st = xhat_mod.evaluate_warm(
                    self.batch, cand, wstate.xhat_solver, self.pdhg_opts)
                import dataclasses as _dc
                self.opt.wstate = _dc.replace(wstate, xhat_solver=st)
            else:
                res = xhat_mod.evaluate(self.batch, cand, self.pdhg_opts)
            if bool(res.feasible) and xhat_mod.comp_tight(
                    self.batch, res, self.comp_tol):
                self._offer(float(res.value), np.asarray(cand))
        return self.bound


class FusedXhatShuffleInnerBound(InnerBoundSpoke):
    """Reads the in-step rotating-scenario candidate value off
    FusedWheelState (enable with FusedWheelOptions.shuffle_windows > 0)
    — the fused analog of XhatShuffleInnerBound: one shuffled scenario's
    own first stage per wheel iteration instead of k per sync."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)
    converger_spoke_char = "F"
    fused = True

    def update(self, hub_payload):
        pass

    def harvest(self):
        sc = getattr(self.opt, "scalar_cache", None)
        if sc is None:
            return self.bound
        if sc["shuf_feasible"] > 0.5 and (self.bound is None
                                          or sc["shuf_value"] < self.bound):
            self._offer(sc["shuf_value"],
                        np.asarray(self.opt.cand_cache["shuf"]))
        return self.bound


class FusedSlamHeuristic(InnerBoundSpoke):
    """Reads the in-step slam-candidate recourse value off
    FusedWheelState (enable with FusedWheelOptions.slam_windows > 0) —
    the fused analog of SlamMaxHeuristic/SlamMinHeuristic."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)
    converger_spoke_char = "S"
    fused = True

    def update(self, hub_payload):
        pass

    def harvest(self):
        sc = getattr(self.opt, "scalar_cache", None)
        if sc is None:
            return self.bound
        if sc["slam_feasible"] > 0.5 and (self.bound is None
                                          or sc["slam_value"] < self.bound):
            self._offer(sc["slam_value"],
                        np.asarray(self.opt.cand_cache["slam"]))
        return self.bound


# ---------------------------------------------------------------------------
# Outer bounds
# ---------------------------------------------------------------------------
class LagrangianOuterBound(OuterBoundSpoke):
    """L(W) at the hub's W (ref:cylinders/lagrangian_bounder.py:53-98)."""

    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,
                             ConvergerSpokeType.W_GETTER)

    def update(self, hub_payload):
        W = hub_payload["W"]
        self._pending = lag_mod.lagrangian_bound(
            self.batch, W, self.pdhg_opts,
            self._pending.solver if self._pending is not None else None)


class LagrangerOuterBound(OuterBoundSpoke):
    """Takes hub *x* and maintains its own W from a rho schedule
    (ref:cylinders/lagranger_bounder.py:18+).  rho_rescale_factors:
    {iter: factor} applied multiplicatively when the hub iter passes."""

    converger_spoke_types = (ConvergerSpokeType.OUTER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        self.rho = float(self.options.get("rho", 1.0))
        self.rescale = dict(self.options.get("rho_rescale_factors", {}))
        self._W = None

    def update(self, hub_payload):
        x_non = hub_payload["nonants"]
        xbar = hub_payload["xbar_scen"]
        it = hub_payload.get("iter", 0)
        if it in self.rescale:
            self.rho *= float(self.rescale.pop(it))
        dW = self.rho * (x_non - xbar)
        self._W = dW if self._W is None else self._W + dW
        self._pending = lag_mod.lagrangian_bound(
            self.batch, self._W, self.pdhg_opts)


class SubgradientOuterBound(OuterBoundSpoke):
    """Self-contained subgradient loop advancing one step per hub sync
    (ref:cylinders/subgradient_bounder.py:12-54).  best_bound already
    folds only certified bounds (algos/lagrangian.subgradient_step)."""

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        self.rho = jnp.asarray(float(self.options.get("rho", 1.0)),
                               self.batch.qp.c.dtype)
        self.n_windows = int(self.options.get("n_windows", 20))
        self._st = lag_mod.subgradient_init(self.batch, self.pdhg_opts)

    def update(self, hub_payload):
        self._st = lag_mod.subgradient_step(
            self.batch, self._st, self.rho, self.pdhg_opts, self.n_windows)
        self._pending = self._st

    def harvest(self):
        if self._pending is None:
            return None
        b = float(self._pending.best_bound)
        if np.isfinite(b) and (self.bound is None or b > self.bound):
            self.bound = b
        return self.bound


class EFOuterBound(OuterBoundSpoke):
    """Warm PDHG solve of the ASSEMBLED extensive form, publishing its
    Fenchel-dual value under a dual-residual certificate — an exact
    outer bound for LP problems where PH's W converges too slowly for
    the Lagrangian plane (measured on hydro: L(W) plateaus ~3.5% below
    the LP optimum while the EF dual closes it).  No direct reference
    analog: the reference gets the equivalent effect from exact solver
    bestbounds; the EF-as-a-cylinder configuration mirrors its
    fix-and-solve EF utilities (ref:mpisppy/opt/ef.py:16-155).

    options: 'ef_problem' (algos.ef.EFProblem, required) or
    'specs' + 'tree' to build one; 'n_windows' per sync (default 20)."""

    converger_spoke_char = "E"

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        efp = self.options.get("ef_problem")
        if efp is None:
            from mpisppy_tpu.algos.ef import build_ef
            efp = build_ef(self.options["specs"],
                           tree=self.options.get("tree"))
        self.efp = efp
        self.n_windows = int(self.options.get("n_windows", 20))
        self._st = pdhg.init_state(efp.qp, self.pdhg_opts)

    def update(self, hub_payload):
        self._st = pdhg.solve_fixed(self.efp.qp, self.n_windows,
                                    self.pdhg_opts, self._st)
        self._pending = self._st

    def harvest(self):
        from mpisppy_tpu.ops import boxqp
        if self._pending is None:
            return self.bound
        st = self._pending
        qp = self.efp.qp
        dual = float(boxqp.dual_objective(qp, st.x, st.y))
        _, rd, _ = boxqp.kkt_residuals(qp, st.x, st.y)
        tol = max(self.pdhg_opts.tol, 5.0e-7)
        if float(rd) <= 10.0 * tol and (self.bound is None
                                        or dual > self.bound):
            self.bound = dual
        return self.bound


@partial(jax.jit, static_argnames=("windows", "opts"))
def _ef_root_fixed_solve(qp, cols, xs, st, windows, opts):
    import dataclasses as _dc

    from mpisppy_tpu.ops import boxqp
    l = qp.l.at[cols].set(xs)          # noqa: E741
    u = qp.u.at[cols].set(xs)
    qp2 = _dc.replace(qp, l=l, u=u)
    st = _dc.replace(st, x=jnp.clip(st.x, l, u))
    st = pdhg.solve_fixed(qp2, windows, opts, st)
    obj = jnp.sum(qp2.c * st.x + 0.5 * qp2.q * st.x * st.x)
    viol = boxqp.primal_residual(qp2, st.x)
    # safety-scaled first-order compensation (xhat.COMP_SAFETY): the
    # dual iterate is truncated, so the published obj + comp is
    # APPROXIMATELY certified, error O(rp * |y - y*|)
    comp = xhat_mod.COMP_SAFETY * jnp.sum(jnp.abs(st.y) * viol)
    rp, _, _ = boxqp.kkt_residuals(qp2, st.x, st.y)
    dead = (st.status == pdhg.INFEASIBLE) | (st.status == pdhg.UNBOUNDED)
    return st, obj, comp, rp, dead


class EFXhatInnerBound(InnerBoundSpoke):
    """Multistage-correct x̂ inner bound: fix only the ROOT-stage
    nonants at the candidate and solve the extensive form over the
    remaining stages — inner-node decisions re-optimize subject to the
    EF's nonanticipativity rows.  The analog of the reference's
    xhatlooper `stage2ef` option (ref:examples/hydro/hydro_cylinders.py:35),
    which exists for exactly this reason: a candidate that fixes EVERY
    stage's nonants is structurally infeasible whenever a later-stage
    equality couples nonants with stage randomness (hydro's reservoir
    balance: Vol2 = Vol1 + inflow - Pgh2 with all three decision terms
    fixed — measured recourse duals ~1e6 and a +37% first-order
    compensation; no valid tight bound exists at such points).

    Publication: obj + COMP_SAFETY*|y|'viol (safety-scaled first-order
    infeasibility compensation, EF duals are bounded here) once the
    primal residual clears feas_tol AND the compensation is below
    comp_tol*|obj| — published values are APPROXIMATELY certified
    (error O(rp * |y - y*|), see xhat.COMP_SAFETY) and tight.  The candidate root stays
    FROZEN across syncs until it publishes, letting the warm EF solve
    accumulate.  Use for multistage batches; two-stage recourse is
    better served by the batched XhatXbar/Fused planes."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)
    converger_spoke_char = "I"

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        efp = self.options.get("ef_problem")
        if efp is None:
            from mpisppy_tpu.algos.ef import build_ef
            efp = build_ef(self.options["specs"],
                           tree=self.options.get("tree"))
        self.efp = efp
        self.n_windows = int(self.options.get("n_windows", 20))
        # rp gates how far the first-order compensation can be trusted,
        # not validity (the published value already carries +|y|'viol);
        # 1e-3 matches the batched per-scenario evaluators' gate — the
        # REAL tightness gate is comp_tol (measured under SepRho-driven
        # candidates: rp plateaued at 8e-4 with comp at 0.15% of the
        # objective, and a 1e-4 rp gate starved the wheel of any inner)
        self.feas_tol = float(self.options.get("feas_tol", 1e-3))
        self.comp_tol = float(self.options.get("comp_tol", 2e-3))
        # adopt a fresh candidate after this many syncs without a
        # publication — a root for which the root-fixed EF is
        # infeasible/degenerate must not pin the spoke forever
        self.give_up = int(self.options.get("give_up", 15))
        from mpisppy_tpu.algos.ef import root_fix_columns
        self._root_slots, flat, d_flat = root_fix_columns(efp)
        self._cols = jnp.asarray(flat, jnp.int32)
        self._dcols = jnp.asarray(d_flat, efp.qp.c.dtype)
        import dataclasses as _dc
        self.pdhg_opts = _dc.replace(self.pdhg_opts, detect_infeas=True)
        self._st = pdhg.init_state(efp.qp, self.pdhg_opts)
        self._frozen = None
        self._published = False
        self._dry_syncs = 0

    def update(self, hub_payload):
        cand_nodes = xhat_mod.round_integers(
            self.batch, hub_payload["xbar_nodes"])
        root = jnp.asarray(cand_nodes)[0, self._root_slots]
        if (self._frozen is None or self._published
                or self._dry_syncs >= self.give_up):
            self._frozen = root
            self._published = False
            self._dry_syncs = 0
        else:
            self._dry_syncs += 1
        S = len(self.efp.probs)
        xs = jnp.tile(self._frozen, S) / self._dcols
        self._st, obj, comp, rp, dead = _ef_root_fixed_solve(
            self.efp.qp, self._cols, xs, self._st, self.n_windows,
            self.pdhg_opts)
        self._pending = (obj, comp, rp, dead)

    def _policy_nodes(self) -> np.ndarray:
        """(num_nodes, N) nonanticipative policy from the EF solution:
        per-node probability-weighted averages, root pinned at the
        frozen candidate."""
        efp = self.efp
        x = np.asarray(self._st.x) * np.asarray(efp.scaling.d_col)
        S, n = len(efp.probs), efp.n_per_scen
        xs = x.reshape(S, n)[:, np.asarray(efp.nonant_idx)]  # (S, N)
        tree = efp.tree
        nos = tree.node_of_slot()                            # (S, N)
        p = np.asarray(efp.probs)
        N = xs.shape[1]
        nodes = np.zeros((tree.num_nodes, N))
        wsum = np.zeros((tree.num_nodes, N))
        colix = np.broadcast_to(np.arange(N)[None, :], (S, N))
        np.add.at(nodes, (nos, colix), p[:, None] * xs)
        np.add.at(wsum, (nos, colix), np.broadcast_to(p[:, None], (S, N)))
        nodes = nodes / np.maximum(wsum, 1e-30)
        nodes[0, self._root_slots] = np.asarray(self._frozen)
        return nodes

    def harvest(self):
        if self._pending is None:
            return self.bound
        obj, comp, rp, dead = (float(np.asarray(v))
                               for v in self._pending)
        if dead > 0.5:
            # root-fixed EF certified infeasible/unbounded at this
            # candidate — drop it immediately, don't wait for give_up
            self._dry_syncs = self.give_up
            return self.bound
        if rp <= self.feas_tol and comp <= self.comp_tol * max(1.0,
                                                               abs(obj)):
            self._published = True
            self._offer(obj + comp, self._policy_nodes())
        return self.bound


class FWPHOuterBound(OuterBoundSpoke):
    """FWPH as an outer-bound spoke (ref:cylinders/fwph_spoke.py:11-39):
    self-contained — advances one FWPH outer iteration per hub sync and
    publishes the certified dual bound (`opt._local_bound` analog)."""

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        from mpisppy_tpu.algos import fwph as fwph_mod
        self._fwph_mod = fwph_mod
        self.fw_opts = self.options.get("fw_opts", fwph_mod.FWPHOptions())
        rho = jnp.broadcast_to(
            jnp.asarray(float(self.options.get("rho",
                                               self.fw_opts.default_rho)),
                        self.batch.qp.c.dtype),
            (self.batch.num_nonants,))
        self._st, _, _ = fwph_mod.fwph_init(self.batch, rho, self.fw_opts)

    def update(self, hub_payload):
        self._st = self._fwph_mod.fwph_iter(self.batch, self._st,
                                            self.fw_opts)
        self._pending = self._st

    def harvest(self):
        if self._pending is None:
            return None
        st = self._pending
        b = float(st.best_bound)
        if np.isfinite(b) and (self.bound is None or b > self.bound):
            self.bound = b
        return self.bound


# ---------------------------------------------------------------------------
# Inner bounds (incumbent finders)
# ---------------------------------------------------------------------------
class XhatXbarInnerBound(InnerBoundSpoke):
    """x̂ = rounded x̄ (ref:cylinders/xhatxbar_bounder.py:37).

    Carries warm PDHG state across syncs: consecutive x̄ candidates
    differ little, so each sync's recourse solve starts from the last
    one's iterates (round-2 review weakness #7)."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        self._solver = None

    def update(self, hub_payload):
        xbar_nodes = hub_payload["xbar_nodes"]
        # cache the ROUNDED candidate: the bound is evaluated at it, so
        # the incumbent written out must be the same point
        cand = xhat_mod.round_integers(self.batch, xbar_nodes)
        if self._solver is None:
            import dataclasses as _dc
            qp = self.batch.with_fixed_nonants(cand)
            self._solver = pdhg.init_state(
                qp, _dc.replace(self.pdhg_opts, detect_infeas=True))
        # async core solve only; the stalled-tail rescue happens in
        # _finalize at harvest so update never blocks on device results
        res, self._solver = xhat_mod._evaluate_warm_core(
            self.batch, cand, self._solver, self.pdhg_opts)
        self._pending = (res, cand)

    def _finalize(self, res, xhat):
        return xhat_mod._rescue_merge(self.batch, jnp.asarray(xhat), res,
                                      self.pdhg_opts, 1e-3)


class XhatShuffleInnerBound(InnerBoundSpoke):
    """Deterministic shared shuffle of candidate scenarios, k tried per
    sync as ONE (k,S)-batched program
    (ref:cylinders/xhatshufflelooper_bounder.py:23-157; seed 42 at :74)."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        self.k = int(self.options.get("k", 4))
        # reverse epochs: walk the shuffle backwards every other pass
        # (ref:xhatshufflelooper_bounder.py ScenarioCycler reverse mode)
        self.add_reversed = bool(self.options.get("add_reversed", False))
        rng = np.random.default_rng(self.options.get("seed", 42))
        self._order = rng.permutation(self.batch.num_real)
        self._cursor = 0
        self._reversed_epoch = False

    def _next_ids(self):
        S = self.batch.num_real
        order = self._order[::-1] if self._reversed_epoch else self._order
        ids = [int(order[(self._cursor + j) % S]) for j in range(self.k)]
        cursor = self._cursor + self.k
        if cursor >= S and self.add_reversed:
            self._reversed_epoch = not self._reversed_epoch
        self._cursor = cursor % S
        return jnp.asarray(ids)

    def update(self, hub_payload):
        x_non = hub_payload["nonants"]
        ids = self._next_ids()
        self._pending = xhat_mod.xhat_shuffle(
            self.batch, x_non, ids, self.k, self.pdhg_opts)

    def harvest(self):
        if self._pending is None:
            return None
        vals, feas, cands, comps = self._pending
        vals = np.asarray(vals)
        feas = np.asarray(feas)
        # comp-tightness gate, batched (see InnerBoundSpoke.harvest)
        feas = feas & xhat_mod.comp_tight_mask(vals, comps, self.comp_tol)
        if feas.any():
            j = int(np.argmin(np.where(feas, vals, np.inf)))
            self._offer(float(vals[j]), np.asarray(cands)[j])
        else:
            # every candidate failed the batched core evaluation — at
            # scale that is usually the stalled-tail artifact, not true
            # infeasibility (all `vals` are +inf, so there is no rank to
            # pick by); rescue-evaluate candidates in order until one
            # lands, capped at 2 per sync (host level: blocking is fine
            # at harvest)
            for j in range(min(2, len(vals))):
                res = xhat_mod.evaluate(self.batch,
                                        jnp.asarray(np.asarray(cands)[j]),
                                        self.pdhg_opts)
                if bool(res.feasible) and xhat_mod.comp_tight(
                        self.batch, res, self.comp_tol):
                    self._offer(float(res.value), np.asarray(cands)[j])
                    break
        return self.bound


class XhatLooperInnerBound(XhatShuffleInnerBound):
    """Fixed-order looper: tries the first `scen_limit` scenarios per
    sync in SCENARIO ORDER, no shuffle
    (ref:mpisppy/cylinders/xhatlooper_bounder.py:23 — the pre-shuffle
    looper; same batched (k,S) evaluation here, identity permutation)."""

    def __init__(self, opt, options=None):
        options = dict(options or {})
        options.setdefault("k", int(options.pop("scen_limit", 3)))
        super().__init__(opt, options)
        self._order = np.arange(self.batch.num_real)  # identity, no rng


class XhatSpecificInnerBound(InnerBoundSpoke):
    """Evaluates USER-NAMED candidate scenarios' first stages
    (ref:mpisppy/cylinders/xhatspecific_bounder.py:25; the reference
    takes a {node: scenario_name} dict via 'xhat_specific_dict').
    options: 'scenario_names' (list of names) or 'scenario_ids'."""

    converger_spoke_types = (ConvergerSpokeType.INNER_BOUND,
                             ConvergerSpokeType.NONANT_GETTER)

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        ids = self.options.get("scenario_ids")
        if ids is None:
            names = self.options.get("scenario_names")
            if names is None:
                raise ValueError("XhatSpecificInnerBound needs "
                                 "'scenario_ids' or 'scenario_names'")
            lookup = {nm: i for i, nm in enumerate(
                getattr(opt, "scenario_names", []))}
            ids = [lookup[nm] for nm in names]
        self._ids = jnp.asarray(list(ids))

    def update(self, hub_payload):
        x_non = hub_payload["nonants"]
        self._pending = xhat_mod.xhat_shuffle(
            self.batch, x_non, self._ids, int(self._ids.shape[0]),
            self.pdhg_opts)

    harvest = XhatShuffleInnerBound.harvest


class XhatLShapedInnerBound(XhatXbarInnerBound):
    """Evaluates the L-shaped master's candidate x̂ as an inner bound
    (ref:mpisppy/cylinders/lshaped_bounder.py:14 XhatLShapedInnerBound —
    identical mechanics to xhat-xbar: the hub's published nonant point is
    fixed and the recourse evaluated)."""


class _SlamHeuristic(InnerBoundSpoke):
    sense_max = True

    def update(self, hub_payload):
        x_non = hub_payload["nonants"]
        xhat = xhat_mod.slam_candidate(self.batch, x_non, self.sense_max)
        self._pending = (
            xhat_mod._evaluate_core(self.batch, xhat, self.pdhg_opts),
            xhat)

    def _finalize(self, res, xhat):
        return xhat_mod._rescue_merge(self.batch, xhat, res,
                                      self.pdhg_opts, 1e-3)


class SlamMaxHeuristic(_SlamHeuristic):
    """ref:cylinders/slam_heuristic.py:111."""

    sense_max = True


class SlamMinHeuristic(_SlamHeuristic):
    """ref:cylinders/slam_heuristic.py:121."""

    sense_max = False


# ---------------------------------------------------------------------------
# Cut generation (pairs with extensions.cross_scen_extension on the hub)
# ---------------------------------------------------------------------------
class CrossScenarioCutSpoke(Spoke):
    """Cross-scenario L-shaped cut generator
    (ref:mpisppy/cylinders/cross_scen_spoke.py:17-303).  Consumes the
    hub's nonants, picks the scenario-x farthest from xbar, solves every
    scenario's recourse there in ONE batched PDHG call, and leaves a cut
    package (dual-certified optimality cuts + Farkas feasibility cuts)
    for the hub's CrossScenarioExtension to install.  Produces no bound
    itself — the hub extension's periodic EF-objective check does
    (ref:extensions/cross_scen_extension.py:80-128)."""

    converger_spoke_types = ()  # neither bound type: a cut provider

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        import dataclasses as _dc
        # cuts are generated on the ORIGINAL (un-augmented) batch
        self.orig_batch = getattr(opt, "_cross_scen_orig_batch", opt.batch)
        # cut solves need infeasibility detection and a full-convergence
        # budget (never LOWER than configured)
        self.cut_opts = _dc.replace(
            self.pdhg_opts, detect_infeas=True,
            max_iters=max(self.pdhg_opts.max_iters, 100_000))
        self.cut_package: dict | None = None
        self.new_cuts = False

    def update(self, hub_payload):
        from mpisppy_tpu.algos import cross_scen
        self._pending = cross_scen.launch_cuts(
            self.orig_batch, hub_payload["nonants"],
            hub_payload["xbar_scen"], self.cut_opts)

    def harvest(self):
        from mpisppy_tpu.algos import cross_scen
        if self._pending is None:
            return None
        self.cut_package = cross_scen.package_cuts(self._pending,
                                                   self.cut_opts)
        self.new_cuts = True
        self._pending = None
        return None  # no bound




class ReducedCostsSpoke(LagrangianOuterBound):
    """Lagrangian bound spoke that also extracts nonant reduced costs
    for the hub's ReducedCostsFixer
    (ref:mpisppy/cylinders/reduced_costs_spoke.py:16-175).

    Publishes, besides the bound: `rc_global` (N,) expected reduced
    costs — NaN where the scenarios disagree (xbar variance above
    sqrt(bound_tol), ref:reduced_costs_spoke.py:139-143) or where xbar
    sits away from both bounds — and `rc_scenario` (S, N) raw
    per-scenario values."""

    converger_spoke_char = "R"

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        if self.batch.tree.num_nodes != 1:
            # xbar/consensus below are root-node reductions; per-node
            # variants would be needed first (mirrors the reference's
            # two-stage-only usage of rc fixing)
            raise RuntimeError("ReducedCostsSpoke supports two-stage "
                               "problems only")
        self.bound_tol = float(self.options.get("rc_bound_tol", 1e-6))
        self.consensus_threshold = float(np.sqrt(self.bound_tol))
        self.rc_global: np.ndarray | None = None
        self.rc_scenario: np.ndarray | None = None
        self.new_rc = False
        # original-space nonant box (static: hoisted from the harvest
        # path so no per-iteration (S, n) device pulls)
        self._nonant_lb, self._nonant_ub = self.batch.nonant_box()

    def update(self, hub_payload):
        super().update(hub_payload)
        res = self._pending
        self._rc_dev = lag_mod.nonant_reduced_costs(
            self.batch, hub_payload["W"], res.solver)
        self._x_dev = self.batch.nonants(res.solver.x)

    def harvest(self):
        b = super().harvest()
        if self._pending is None:
            return b
        if not bool(self._pending.certified):
            # an unconverged Lagrangian solve has arbitrary-sign reduced
            # costs; publishing them would let the fixer pin variables
            # to the wrong bound
            return b
        # record the certified Lagrangian bound of the SAME solve the
        # rcs come from — the fixer's bound-tightening gap needs it
        self.last_lagrangian_bound = float(self._pending.bound)
        rc = np.asarray(self._rc_dev, np.float64)       # (S, N)
        x = np.asarray(self._x_dev, np.float64)
        p = np.asarray(self.batch.p, np.float64)
        xbar = (p[:, None] * x).sum(0)
        var = (p[:, None] * x * x).sum(0) - xbar * xbar
        self.rc_scenario = rc
        exp_rc = (p[:, None] * rc).sum(0)
        at_bound = (xbar - self._nonant_lb <= self.bound_tol) \
            | (self._nonant_ub - xbar <= self.bound_tol)
        consensus = var <= self.consensus_threshold ** 2
        exp_rc = np.where(consensus & at_bound, exp_rc, np.nan)
        self.rc_global = exp_rc
        self.new_rc = True
        return b


class PhOuterBound(OuterBoundSpoke):
    """PH itself as an outer-bound engine (ref:mpisppy/cylinders/
    ph_ob.py:21-175): runs its OWN PH iterations with rescaled
    (typically much smaller) rho, and after each iteration evaluates the
    Lagrangian bound at its own W — valid because PH's W update keeps
    the p-weighted node mean of W at zero (ref:phbase.py:114-179)."""

    converger_spoke_char = "P"

    def __init__(self, opt, options=None):
        super().__init__(opt, options)
        from mpisppy_tpu.algos import ph as ph_mod
        self._ph_mod = ph_mod
        rescale = float(self.options.get("ph_ob_rho_rescale", 0.1))
        base_rho = float(self.options.get("rho", 1.0))
        self._ph_opts = ph_mod.PHOptions(
            default_rho=base_rho * rescale,
            subproblem_windows=int(self.options.get("n_windows", 8)),
            pdhg=self.pdhg_opts)
        self._rho = jnp.broadcast_to(
            jnp.asarray(base_rho * rescale, self.batch.qp.c.dtype),
            (self.batch.num_nonants,))
        self._st = None

    def update(self, hub_payload):
        if self._st is None:
            self._st, _, _ = self._ph_mod.ph_iter0(
                self.batch, self._rho, self._ph_opts)
        else:
            self._st = self._ph_mod.ph_iterk(self.batch, self._st,
                                             self._ph_opts)
        self._pending = lag_mod.lagrangian_bound(
            self.batch, self._st.W, self.pdhg_opts,
            self._pending.solver if self._pending is not None else None)
