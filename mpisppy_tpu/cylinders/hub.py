###############################################################################
# Hub: runs the hub algorithm (PH), feeds spokes, tracks bounds, decides
# termination (ref:mpisppy/cylinders/hub.py:28-724).
#
# The reference hub Puts W/nonants into RMA windows and Gets bounds back,
# with write-id consensus; here `sync()` hands the spokes a host-side
# snapshot dict (device arrays — zero-copy) and harvests their previous
# results.  On ONE chip, classic spokes' separate device dispatches
# SERIALIZE against the hub (round-3 measured 642x bare PH per
# iteration for a 4-spoke wheel — async dispatch does NOT overlap work
# on a single queue); the production answer is algos/fused_wheel.py,
# which carries the bound planes INSIDE the hub's jitted step
# (measured <=4.5x bare PH for the same 4 bound planes).  Classic
# spokes remain for cut/rc providers and multi-process deployments.
#
# Termination semantics match ref:mpisppy/cylinders/hub.py:82-166:
#   * rel_gap  <= options['rel_gap']   (gap = (inner-outer)/|inner|;
#     when |inner| ~ 0 the denominator widens to max(|inner|,|outer|)
#     so shifted-objective models can still terminate — see compute_gaps)
#   * abs_gap  <= options['abs_gap']
#   * inner bounds stalled for 'max_stalled_iters' hub iterations
###############################################################################
from __future__ import annotations

import contextlib
import functools
import math
import time

import numpy as np

from mpisppy_tpu import global_toc, telemetry as tel
from mpisppy_tpu.cylinders.spcommunicator import SPCommunicator
from mpisppy_tpu.cylinders.spoke import ConvergerSpokeType
from mpisppy_tpu.telemetry import profiler as _prof


def _checkpoint_crc(data: dict) -> np.ndarray:
    """CRC32 over every array in key order — the checkpoint integrity
    stamp (docs/resilience.md).  Deterministic: keys sorted, raw bytes.
    Zero-copy: crc32 reads the array buffers directly (tobytes() would
    duplicate the full ~460 MB snapshot inside the time-critical
    emergency-save path)."""
    import zlib
    crc = 0
    for k in sorted(data):
        crc = zlib.crc32(k.encode(), crc)
        arr = np.ascontiguousarray(data[k])
        crc = zlib.crc32(memoryview(arr).cast("B"), crc)
    return np.asarray(crc, np.uint32)


def _identity(a):
    return a


@functools.lru_cache(maxsize=8)
def _replicated_gather(mesh):
    """Jitted identity with fully-replicated output on `mesh` — the
    cross-host allgather that makes a scenario-sharded leaf fetchable
    on every process (multi-process checkpointing, ISSUE 17).  Cached
    per mesh so repeated saves reuse one executable."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.jit(_identity,
                   out_shardings=NamedSharding(mesh, PartitionSpec()))


def _fetch_leaf(x, timeout_s: float | None = None) -> np.ndarray:
    """Fetch one state leaf to host.  Single-process (every shard
    addressable) this is a plain np.asarray.  On a multi-process mesh a
    scenario-sharded leaf spans NON-addressable devices, so it is first
    replicated through a jitted identity collective — which every
    process must enter (save_checkpoint runs at a deterministic
    iteration cadence there, options['checkpoint_every_iters']).  The
    gather is bounded by timeout_s: with a peer host dead the
    collective never completes, and a last-gasp emergency save must
    skip (and fall back to the last rotated snapshot) rather than hang
    the survivor (docs/resilience.md failure-semantics table)."""
    if getattr(x, "is_fully_addressable", True) \
            or getattr(x, "is_fully_replicated", False):
        return np.asarray(x)
    gather = _replicated_gather(x.sharding.mesh)
    if timeout_s is None:
        return np.asarray(gather(x))
    import threading
    box: list = []

    def run():
        box.append(np.asarray(gather(x)))

    t = threading.Thread(target=run, daemon=True,
                         name="mpisppy-tpu-ckpt-gather")
    t.start()
    t.join(float(timeout_s))
    if not box:
        raise TimeoutError(
            f"cross-host checkpoint gather exceeded {timeout_s}s "
            "(peer host unreachable?)")
    return box[0]


class Hub(SPCommunicator):
    """Bound bookkeeping + termination (ref:cylinders/hub.py:28-243)."""

    def __init__(self, opt, options: dict | None = None, spokes=None):
        super().__init__(opt, options)
        self.spokes = spokes or []
        self.BestOuterBound = -math.inf  # min problems: lower bound
        self.BestInnerBound = math.inf
        self.latest_ib_char = ""
        self.latest_ob_char = ""
        self._inner_bound_update_iter = 0
        self._iter = 0
        # telemetry spine (docs/telemetry.md): every hub observation —
        # iterations, harvests, bound decisions, checkpoints — is
        # EMITTED through the event bus; the legacy `trace` list here
        # and each spoke's `(iter, bound)` trace are subscriber views
        # (telemetry/views.py), so existing consumers read them
        # unchanged.  A bus arrives via options['telemetry_bus'] (the
        # CLI's --trace-jsonl / --metrics-snapshot wiring); otherwise
        # the hub gets a private sink-less bus whose only subscriber is
        # the view.
        self.trace: list[dict] = []
        self.telemetry = self.options.get("telemetry_bus") \
            or tel.EventBus()
        # a serve session passes its own id so the session's lifecycle
        # events and its wheel's events share ONE run in the per-session
        # trace (docs/serving.md); standalone wheels mint a fresh one
        self.run_id = self.options.get("run_id") or tel.new_run_id()
        # causal trace (ISSUE 20): a serve session's bus arrives
        # already scoped to the session's segment span — adopt it; a
        # standalone wheel mints a fresh root so even a bare CLI run
        # is one complete trace
        if getattr(self.telemetry, "trace", None) is None \
                and hasattr(self.telemetry, "set_trace"):
            self.telemetry.set_trace(tel.TraceContext.mint())
        self._trace_view = tel.WheelTraceView(self)
        self.telemetry.subscribe(self._trace_view)
        self._last_guard_total = 0
        plan = self.options.get("fault_plan")
        if plan is not None:
            # fault injections report through the same spine
            plan.telemetry = self.telemetry
            plan.telemetry_run = self.run_id
        self._last_dispatch_batches = 0
        # adopt the process-default dispatch scheduler into this run:
        # its megabatch events then carry this hub's run id and join
        # the trace exactly (the scheduler is configured by the CLI
        # before any hub exists, so it cannot know the id itself) —
        # and arm the run's fault plan on its dispatch seams so chaos
        # runs fault the dispatch layer through the same plan object
        try:
            from mpisppy_tpu import dispatch as _dispatch
            sched = _dispatch.get_scheduler(create=False)
            if sched is not None and not sched.run:
                sched.run = self.run_id
            if sched is not None and plan is not None \
                    and sched.fault_plan is None:
                sched.fault_plan = plan
            # per-session context token (ISSUE 12 satellite): a SERVE
            # session's hub (marked by the injected run_id) stamps its
            # driver thread — including pre-wheel iter0 oracle work —
            # with THIS run's id, so concurrent sessions sharing one
            # scheduler stay joinable per session.  Standalone wheels
            # keep the process-global stamp untouched (their run
            # already matches the scheduler's).
            if self.options.get("run_id"):
                _dispatch.set_session_context(
                    self.run_id, -1, **self._trace_token())
        except Exception:
            pass
        # hub progress watchdog (docs/resilience.md): no hub iteration
        # or certified-bound movement for watchdog_budget_s wall
        # seconds -> flight-recorder dump + the configured action
        # (checkpoint-and-abort exit 75, or degrade the dispatch
        # scheduler to direct un-coalesced mode)
        self._watchdog = None
        budget = self.options.get("watchdog_budget_s")
        if budget:
            from mpisppy_tpu.resilience.watchdog import HubWatchdog
            self._watchdog = HubWatchdog(
                self, float(budget),
                action=self.options.get("watchdog_action", "abort"),
                interval_s=self.options.get("watchdog_interval_s"),
                shrink_fn=self.options.get("watchdog_shrink_fn"),
            ).start()
        self._profiler = None
        if self.options.get("profile_dir"):
            self._profiler = _prof.ProfilerSession(
                self.options["profile_dir"],
                num_iters=int(self.options.get("profile_iters", 5)),
                bus=self.telemetry, run=self.run_id)
        self._emit(tel.RUN_START, hub_class=type(self).__name__,
                   num_spokes=len(self.spokes))
        # sense-contradiction bookkeeping (docs/resilience.md): a
        # rejected bound is ambiguous evidence — EITHER the incoming
        # value or the standing opposite-sense incumbent is garbage.
        # _contra[side] records the DISTINCT spokes whose bounds
        # contradicted the CURRENT incumbent of `side`; enough of them
        # evict it (see _note_contradiction).
        self._contra: dict[str, list] = {"outer": [], "inner": []}

    def _emit(self, kind: str, _cyl: str = "hub", **data):
        """Publish one event for this hub's run (no-op without sinks)."""
        self.telemetry.emit(kind, run=self.run_id, cyl=_cyl,
                            hub_iter=self._iter, **data)

    def _trace_token(self) -> dict:
        """The bus's current trace/span ids as set_session_context
        kwargs — how `options['run_id']` hands the causal context to
        the thread-local DispatchContext (ISSUE 20)."""
        ctx = getattr(self.telemetry, "trace", None)
        if ctx is None:
            return {}
        return {"trace_id": ctx.trace_id, "span_id": ctx.span_id}

    def emit_span(self, name: str, dur_s: float):
        """One timed wheel phase (host wall seconds) onto the stream —
        the analyzer's per-phase breakdown input.  Host-side semantics:
        a span covers dispatch + any blocking reads inside it, so with
        async XLA dispatch the device wait lands in whichever span
        first reads a result (docs/telemetry.md)."""
        self._emit(tel.SPAN, name=name, dur_s=dur_s)

    @contextlib.contextmanager
    def _span(self, name: str):
        """Profiler annotation + SPAN event for one wheel phase."""
        with _prof.annotate(f"wheel/{name}"):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                self.emit_span(name, time.perf_counter() - t0)

    def emit_run_end(self, reason: str, **extra):
        """Emit the run-end record (exit reason + final gap) exactly
        once — the normal path reaches here via finalize(), a dying
        wheel via WheelSpinner.spin's unwind (reason "preemption" /
        "exception"), so a trace always ends with an explicit verdict
        instead of run termination being inferred from stream
        truncation (ISSUE 5 satellite)."""
        if getattr(self, "_run_ended", False):
            return
        self._run_ended = True
        abs_gap, rel_gap = self.compute_gaps()
        self._emit(tel.RUN_END, reason=reason,
                   outer=self.BestOuterBound, inner=self.BestInnerBound,
                   abs_gap=abs_gap, rel_gap=rel_gap,
                   iterations=self._iter, **extra)

    # -- bound bookkeeping (ref:hub.py:207-243) ---------------------------
    # Non-finite values never enter the bookkeeping: a NaN outer bound
    # would poison every later max() comparison silently, and a +inf
    # outer (or -inf inner) would fire gap termination on garbage.
    # Sense CROSS-validation (outer vs inner) lives in _harvest_all where
    # the per-spoke strike counters are (docs/resilience.md).
    def OuterBoundUpdate(self, new_bound: float, char: str = "*"):
        if math.isfinite(new_bound) and new_bound > self.BestOuterBound:
            self.BestOuterBound = new_bound
            self.latest_ob_char = char
        return self.BestOuterBound

    def InnerBoundUpdate(self, new_bound: float, char: str = "*"):
        if math.isfinite(new_bound) and new_bound < self.BestInnerBound:
            self.BestInnerBound = new_bound
            self.latest_ib_char = char
            self._inner_bound_update_iter = self._iter
        return self.BestInnerBound

    def _validate_bound(self, sense: str, b: float) -> str | None:
        """None when `b` is acceptable, else a rejection reason.

        A bound is rejected when non-finite or SENSE-VIOLATING: an
        outer (lower) bound above the incumbent, or an inner bound
        below the certified outer bound, by more than `bound_slack`
        relative (default 5e-3 — legitimate f32 crossings measured up
        to ~2e-3 on the farmer wheel must pass)."""
        if not math.isfinite(b):
            return f"non-finite {sense} bound {b!r}"
        slack = float(self.options.get("bound_slack", 5e-3))
        if sense == "outer" and math.isfinite(self.BestInnerBound):
            lim = self.BestInnerBound \
                + slack * max(1.0, abs(self.BestInnerBound))
            if b > lim:
                return (f"sense-violating outer bound {b:.6g} > "
                        f"inner {self.BestInnerBound:.6g} + slack")
        if sense == "inner" and math.isfinite(self.BestOuterBound):
            lim = self.BestOuterBound \
                - slack * max(1.0, abs(self.BestOuterBound))
            if b < lim:
                return (f"sense-violating inner bound {b:.6g} < "
                        f"outer {self.BestOuterBound:.6g} - slack")
        return None

    # -- gaps + termination (ref:hub.py:82-166) ---------------------------
    def compute_gaps(self) -> tuple[float, float]:
        abs_gap = self.BestInnerBound - self.BestOuterBound
        nano = 1e-10
        if self.BestInnerBound in (math.inf, -math.inf):
            rel_gap = math.inf
        else:
            # Reference semantics: divide by |inner| (ref:hub.py:96-101).
            # That blows up when the optimal objective is near zero
            # (legit for shifted models) and rel_gap termination can then
            # never fire — ONLY in that degenerate case fall back to the
            # larger bound magnitude, so every normal run keeps the exact
            # reference gap convention (the one BENCH numbers use).
            denom = abs(self.BestInnerBound)
            ob = abs(self.BestOuterBound)
            near_zero = denom < 1e-6 * max(1.0, ob if math.isfinite(ob)
                                           else 0.0)
            if near_zero and math.isfinite(ob):
                denom = max(denom, ob)
            rel_gap = abs_gap / max(nano, denom)
        return abs_gap, rel_gap

    def determine_termination(self) -> bool:
        abs_gap, rel_gap = self.compute_gaps()
        opt = self.options
        if "rel_gap" in opt and rel_gap <= opt["rel_gap"]:
            global_toc(f"Terminating: rel_gap {rel_gap:.4e} <= "
                       f"{opt['rel_gap']}", True)
            self._term_reason = "converged"
            return True
        if "abs_gap" in opt and abs_gap <= opt["abs_gap"]:
            global_toc(f"Terminating: abs_gap {abs_gap:.4e} <= "
                       f"{opt['abs_gap']}", True)
            self._term_reason = "converged"
            return True
        if "max_stalled_iters" in opt:
            # spokes only produce results on exchange iterations, so the
            # stall budget counts in EXCHANGE rounds (with
            # spoke_sync_period=k, intermediate iterations cannot update
            # the inner bound and must not count as stalled)
            period = max(1, int(opt.get("spoke_sync_period", 1)))
            if (self._iter - self._inner_bound_update_iter
                    >= opt["max_stalled_iters"] * period
                    and self.BestInnerBound < math.inf):
                global_toc("Terminating: inner bound stalled", True)
                self._term_reason = "stalled"
                return True
        return False

    def is_converged(self) -> bool:
        return self.determine_termination()


class PHHub(Hub):
    """PH as the hub algorithm (ref:cylinders/hub.py:462-573).

    `opt` is an algos.ph.PH driver; the hub installs itself as
    `opt.spcomm` so the PH loop calls sync()/is_converged() each
    iteration (the cylinder seam, ref:phbase.py:1040-1056).
    """

    def setup_hub(self):
        self.opt.spcomm = self
        for sp in self.spokes:
            sp.make_windows()
        # hub-side extension hooks (ref:mpisppy/cylinders/hub.py:476-516
        # setup_hub drives the extension's setup + spoke-index wiring)
        ext = getattr(self.opt, "extobject", None)
        if ext is not None:
            if hasattr(ext, "setup_hub"):
                ext.setup_hub()
            if hasattr(ext, "initialize_spoke_indices"):
                ext.initialize_spoke_indices()

    def _snapshot(self) -> dict:
        """Device-array snapshot for spokes (ref:hub.py:517-532 sends
        Ws + nonants + bounds).  xbar views are reused from the PH state
        — ph_iterk already reduced them."""
        st = self.opt.state
        batch = self.opt.batch
        return {
            "W": st.W,
            "nonants": batch.nonants(st.solver.x),
            "xbar_scen": st.xbar,
            "xbar_nodes": st.xbar_nodes,
            "iter": self._iter,
            "bounds": (self.BestOuterBound, self.BestInnerBound),
        }

    def _harvest_all(self, only=None):
        """Fold every spoke's latest result into the bound bookkeeping.

        Harvested bounds are VALIDATED before they can move
        BestOuterBound/BestInnerBound.  Non-finite values (unambiguous
        garbage) count a strike against the producing spoke; after
        `spoke_max_strikes` the spoke is auto-disabled (skipped by
        harvest AND update) and the wheel continues on the remaining
        spokes — the analog of the reference simply not reading a dead
        cylinder's window.  Sense-violating values are rejected without
        blame and recorded as contradictions against the standing
        opposite incumbent (_note_contradiction).  The optional
        options['fault_plan'] harvest seam injects poisoned bounds
        HERE, between the spoke and the validation (resilience/faults)."""
        plan = self.options.get("fault_plan")
        max_strikes = int(self.options.get("spoke_max_strikes", 3))
        for j, sp in enumerate(self.spokes):
            if only is not None and sp not in only:
                continue
            if getattr(sp, "disabled", False):
                continue
            b = sp.harvest()
            if b is None:
                continue
            types = sp.converger_spoke_types
            if ConvergerSpokeType.OUTER_BOUND in types:
                sense = "outer"
            elif ConvergerSpokeType.INNER_BOUND in types:
                sense = "inner"
            else:
                continue  # cut/rc providers publish no bound
            self._emit(tel.SPOKE_HARVEST, spoke=j,
                       spoke_class=type(sp).__name__, sense=sense,
                       bound=float(b))
            if plan is not None:
                b = plan.filter_bound(j, sense, float(b), self._iter)
            reason = self._validate_bound(sense, b)
            if reason is not None:
                self._emit(tel.BOUND_REJECT, spoke=j, sense=sense,
                           bound=float(b), reason=reason)
                # scrub the offending value from the spoke's monotone
                # cache: harvests re-return the cache even with no new
                # result, so one transient spike would otherwise
                # re-offer itself every sync forever
                if getattr(sp, "bound", None) is not None:
                    sp.bound = None
                if reason.startswith("sense-violating"):
                    # ambiguous evidence (either the incoming value or
                    # the standing opposite incumbent is garbage):
                    # never a strike — blame needs corroboration
                    self._note_contradiction(sense, sp, reason)
                else:
                    self._strike(j, sp, reason, max_strikes)
                continue
            # spokes may declare their trace char (ref spoke classes'
            # converger_spoke_char); default to the class initial
            ch = getattr(sp, "converger_spoke_char",
                         type(sp).__name__[0])
            if sense == "outer":
                before = self.BestOuterBound
                self.OuterBoundUpdate(b, ch)
                improved = self.BestOuterBound > before
            else:
                before = self.BestInnerBound
                self.InnerBoundUpdate(b, ch)
                improved = self.BestInnerBound < before
                # hub-side incumbent cache: BestInnerBound must always
                # have a backing solution, even after the producing
                # spoke's cache is later scrubbed or the spoke disabled
                # (best_nonants falls back to this before xbar)
                if (self.BestInnerBound < before
                        and getattr(sp, "best_xhat", None) is not None):
                    self._best_inner_xhat = np.asarray(sp.best_xhat)
            # an accepted bound is CONSISTENT with the opposite-sense
            # incumbent: clear the suspicion that had built against it
            other = "inner" if sense == "outer" else "outer"
            self._contra[other] = []
            # the view appends (iter, bound) to sp.trace (views.py)
            self._emit(tel.BOUND_ACCEPT, spoke=j, sense=sense,
                       bound=float(b), char=ch, improved=bool(improved))

    def _strike(self, j: int, sp, reason: str, max_strikes: int):
        """One unambiguously-garbage (non-finite) bound = one strike; K
        strikes disable the spoke (ref analog: a misbehaving cylinder's
        window is never read again).  Counters survive on the spoke
        object so finalize() and tests can inspect them.  Only fresh
        invalid results accumulate strikes — the caller scrubs rejected
        values from the spoke cache, and the hub's own Best*Bound keeps
        every previously accepted value."""
        sp.strikes = getattr(sp, "strikes", 0) + 1
        self._emit(tel.SPOKE_STRIKE, spoke=j,
                   spoke_class=type(sp).__name__, reason=reason,
                   strikes=sp.strikes, max_strikes=max_strikes)
        global_toc(f"hub: rejected bound from spoke {j} "
                   f"({type(sp).__name__}): {reason} "
                   f"[strike {sp.strikes}/{max_strikes}]",
                   self.options.get("display_progress", False))
        if sp.strikes >= max_strikes and not getattr(sp, "disabled",
                                                     False):
            sp.disabled = True
            self._emit(tel.SPOKE_DISABLE, spoke=j,
                       spoke_class=type(sp).__name__, strikes=sp.strikes)
            global_toc(f"hub: DISABLED spoke {j} ({type(sp).__name__}) "
                       f"after {sp.strikes} strikes; continuing with "
                       f"the remaining spokes", True)

    def _note_contradiction(self, sense: str, sp, reason: str):
        """A finite sense-violating bound is ambiguous: EITHER the
        incoming value or the standing opposite incumbent is garbage —
        e.g. a wrong-sense outer bound accepted at iter 1 (before any
        inner existed to validate against) would poison the monotone
        BestOuterBound forever.  Contradictions from enough DISTINCT
        spokes flip the verdict and evict the incumbent.  Distinctness
        matters: one persistently rogue spoke repeating garbage every
        sync must never out-vote a repeatedly-confirmed incumbent (a
        count-based trigger let exactly that happen), so a lone
        contradictor can only ever log its dissent — in a two-spoke
        wheel a poisoned early incumbent stands, the wheel honestly
        never certifies, and the report shows the missing side as null
        rather than lying."""
        global_toc(f"hub: rejected {reason}",
                   self.options.get("display_progress", False))
        other = "outer" if sense == "inner" else "inner"
        rec = self._contra[other]
        if sp not in rec:
            rec.append(sp)
        limit = int(self.options.get("bound_evict_contras", 3))
        if len(rec) >= limit:
            self._evict_incumbent(other, rec)

    def _evict_incumbent(self, side: str, contradictors: list):
        """Reset a contradicted incumbent — no strikes, no blame: the
        evidence stays ambiguous, so nothing is charged to anyone and
        the surviving producers simply re-establish the bound on the
        next exchange."""
        val = self.BestOuterBound if side == "outer" \
            else self.BestInnerBound
        self._emit(tel.BOUND_EVICT, side=side, value=float(val),
                   contradictors=len(contradictors))
        global_toc(f"hub: EVICTING the {side} incumbent ({val:.6g}) — "
                   f"contradicted by {len(contradictors)} distinct "
                   f"spokes", True)
        if side == "outer":
            self.BestOuterBound = -math.inf
            self.latest_ob_char = ""
            # re-fold the hub's own certified trivial bound: it never
            # came from a spoke and is the one outer value we trust
            if (getattr(self, "_trivial_bound_folded", False)
                    and getattr(self.opt, "trivial_bound_certified",
                                False)
                    and self.opt.trivial_bound is not None):
                self.OuterBoundUpdate(self.opt.trivial_bound, "T")
        else:
            self.BestInnerBound = math.inf
            self.latest_ib_char = ""
            # the solution backing the evicted (distrusted) incumbent
            # goes with it — best_nonants must never write it out
            self._best_inner_xhat = None
            # don't let the eviction read as an instant stall
            self._inner_bound_update_iter = self._iter
        self._contra[side] = []

    def _fold_own_bounds(self):
        """Fold bounds the hub algorithm itself produces (PH: none —
        the trivial bound enters via is_converged)."""

    def _trace_extra(self) -> dict:
        return {"conv": self.opt._read_conv()}

    def _apply_warm_plane(self, plane: dict):
        """Seed a rolling-horizon stream's shifted W/x̄ plane
        (mpc/shift.py) into the PH state at the FIRST sync — the
        WXBarReader.post_iter0 timing (iter0 has run, so the seeded
        duals price iteration 1 onward) without the file round-trip:
        mpc/driver.py threads the plane through options['warm_plane'].
        Mirrors _restore_from_arrays' fused-state pattern so a fused
        wheel's wstate stays consistent with opt.state."""
        import dataclasses

        import jax.numpy as jnp
        opt = self.opt
        st = getattr(opt, "state", None)
        if st is None:
            return
        batch = opt.batch
        dt = st.W.dtype
        kw = {}
        if plane.get("W") is not None:
            kw["W"] = jnp.asarray(np.asarray(plane["W"]), dt)
        xbj = plane.get("xbar_nodes")
        if xbj is not None:
            xbj = jnp.asarray(np.asarray(xbj), dt)
            kw["xbar_nodes"] = xbj
            kw["xbar"] = (
                jnp.take_along_axis(xbj, batch.node_of_slot, axis=0)
                if batch.tree.num_nodes > 1
                else jnp.broadcast_to(xbj[0], st.xbar.shape))
        if not kw:
            return
        new = dataclasses.replace(st, **kw)
        wstate = getattr(opt, "wstate", None)
        if wstate is not None:
            opt.wstate = dataclasses.replace(wstate, ph=new)
        opt.state = new

    def sync(self):
        """One hub<->spoke exchange: harvest the spokes' previous async
        results, then launch their next round on a fresh snapshot.

        options['spoke_sync_period'] = k exchanges with the spokes only
        every k-th sync: their device work launched at the previous
        exchange keeps running across the intervening hub iterations
        (XLA async dispatch), which is exactly the reference's
        slower-cylinder overlap (ref:hub.py write-id freshness checks —
        a spoke that hasn't produced a new result simply isn't read).

        Telemetry (docs/telemetry.md): the wheel phases are bracketed
        with profiler spans, the --profile-dir session is advanced, and
        the per-iteration trace row is EMITTED as a hub-iteration event
        (the legacy self.trace list is a subscriber view)."""
        self._iter += 1
        if self._iter == 1 and self.options.get("warm_plane") is not None:
            self._apply_warm_plane(self.options["warm_plane"])
        if self._profiler is not None:
            self._profiler.on_sync(self._iter)
        with _prof.step("wheel_sync", self._iter):
            self._sync_body()

    def _sync_body(self):
        self._sync_prologue()
        self._sync_exchange()
        self._sync_epilogue()

    def _sync_prologue(self):
        # stamp the current hub iteration onto the out-of-band emitters
        # (dispatch megabatches, fault seams) so their events join the
        # iteration timeline exactly, not by seq-window heuristics
        # (ISSUE 5 satellite); -1 remains the pre-wheel stamp.  A
        # serve session's hub additionally carries a per-THREAD token
        # (run, iter) so concurrent sessions never clobber each
        # other's stamp (see __init__)
        from mpisppy_tpu import dispatch as _dispatch
        if self.options.get("run_id"):
            _dispatch.set_session_context(
                self.run_id, self._iter, **self._trace_token())
        _dispatch.set_hub_iter(self._iter)
        # live-migration drain (ISSUE 16): the fleet router sets the
        # session's preempt_event to move this wheel; raising here
        # lands the emergency checkpoint at a consistent sync boundary
        # (WheelSpinner.spin's preemption path), after which the
        # session restores on another replica via load_checkpoint
        drain = self.options.get("preempt_event")
        if drain is not None and drain.is_set():
            from mpisppy_tpu.resilience.faults import PreemptionError
            raise PreemptionError(
                f"migration drain requested at iter {self._iter}")
        plan = self.options.get("fault_plan")
        if plan is not None:
            plan.telemetry_iter = self._iter
            # chaos seams (resilience/faults): a simulated preemption
            # unwinds to WheelSpinner.spin's emergency save; lane
            # corruption mutates the solver state host-side so the
            # pdhg lane guard has something real to catch
            plan.maybe_preempt(self._iter)
            plan.corrupt_lanes(self._iter, self.opt)

    def _sync_exchange(self):
        """The host exchange: harvest -> validate -> publish ->
        checkpoint.  The async hub runs this as its host-complete half
        while the next device step is already in flight."""
        period = max(1, int(self.options.get("spoke_sync_period", 1)))
        do_spokes = (self._iter <= 2) or (self._iter % period == 0)
        # fused spokes (algos.fused_wheel) compute inside the hub's own
        # jitted step — harvesting them is a scalar read, so they fold
        # EVERY iteration; classic spokes keep the sync period
        fused = [sp for sp in self.spokes if getattr(sp, "fused", False)]
        classic = [sp for sp in self.spokes if not getattr(sp, "fused",
                                                           False)]
        with self._span("harvest"):
            self._harvest_all(only=fused)
            if do_spokes:
                self._harvest_all(only=classic)
        if do_spokes:
            # extension exchange with the spokes it cares about
            # (ref:mpisppy/cylinders/hub.py:517-532 drives the
            # extension's sync_with_spokes every sync)
            ext = getattr(self.opt, "extobject", None)
            if ext is not None and hasattr(ext, "sync_with_spokes"):
                ext.sync_with_spokes()
        self._fold_own_bounds()
        # building the snapshot dispatches a (small) device gather; with
        # an all-fused wheel no consumer exists, so skip it off-sync
        if (do_spokes and classic) or self.options.get("publish_snapshots"):
            with self._span("hub_sync"):
                payload = self._snapshot()
                self.from_hub.put(payload)  # for API parity / inspection
            if do_spokes:
                with self._span("spoke_update"):
                    for sp in classic:
                        if not getattr(sp, "disabled", False):
                            sp.update(payload)
        with self._span("checkpoint"):
            self._maybe_checkpoint()

    def _sync_epilogue(self):
        """Off-critical-path bookkeeping: the pipelined kernel-counter
        harvest, dispatch stats, watchdog beat, and the per-iteration
        trace row."""
        self._harvest_kernel_counters()
        self._harvest_dispatch_stats()
        abs_gap, rel_gap = self.compute_gaps()
        if self._watchdog is not None:
            self._watchdog.beat(self._iter, self.BestOuterBound,
                                self.BestInnerBound)
        extra = self._trace_extra()
        self._emit(tel.HUB_ITERATION, **{
            "iter": self._iter, **extra,
            "outer": self.BestOuterBound, "inner": self.BestInnerBound,
            "abs_gap": abs_gap, "rel_gap": rel_gap,
            "ob_char": self.latest_ob_char, "ib_char": self.latest_ib_char,
        })
        if self.options.get("display_progress"):
            conv_str = (f" conv {extra['conv']:9.3e}"
                        if "conv" in extra else "")
            global_toc(
                f"iter {self._iter:4d}{conv_str}"
                f" outer {self.BestOuterBound:12.5g}"
                f" inner {self.BestInnerBound:12.5g} rel_gap {rel_gap:8.3e}"
                f" ({self.latest_ob_char}/{self.latest_ib_char})", True)

    # -- on-device kernel counter harvest (docs/telemetry.md) -------------
    def _counter_solvers(self):
        """(label, PDHGState) pairs carrying kernel counters: the hub's
        subproblem solver plus any fused bound planes' warm solvers
        (--kernel-counters arms them all via _fuse_wheel, so they must
        all be harvested or the exported totals silently undercount)."""
        out = []
        st = getattr(self.opt, "state", None)
        solver = getattr(st, "solver", None) if st is not None else None
        if solver is not None:
            out.append(("hub", solver))
        wstate = getattr(self.opt, "wstate", None)
        wopts = getattr(self.opt, "wheel_options", None)
        if wstate is not None and wopts is not None:
            # gate each plane on ITS options' telemetry flag: plane
            # states warm-start from the hub's iter0 solver and can
            # carry a counters pytree their own solve never updates —
            # harvesting that would report stale iter0 numbers forever
            plane_on = {
                "lag": wopts.lag_pdhg.telemetry and wopts.lag_windows,
                "xhat": wopts.xhat_pdhg.telemetry and wopts.xhat_windows,
                "slam": wopts.xhat_pdhg.telemetry and wopts.slam_windows,
                "shuf": wopts.xhat_pdhg.telemetry
                and wopts.shuffle_windows,
            }
            for name, on in plane_on.items():
                s = getattr(wstate, f"{name}_solver", None)
                if on and s is not None:
                    out.append((name, s))
        return [(cyl, s) for cyl, s in out
                if getattr(s, "counters", None) is not None]

    def _harvest_kernel_counters(self, flush: bool = False):
        """Mirror cumulative on-device counters into the metrics
        registry and the event stream — one small transfer per solver
        per sync (the ring stays in HBM), and a strict no-op unless the
        kernels were built with telemetry=True (counters None
        otherwise).

        PIPELINED off the hub critical path (ISSUE 11 satellite): each
        sync COMPLETES the harvest begun the previous sync (its async
        host copies have long landed — no block on the in-flight step)
        and BEGINS a fresh one on the current state.  Totals therefore
        lag one sync in the stream; they are cumulative mirrors
        (set_counter, monotone), and finalize calls with flush=True —
        DISCARDING the pending one-sync-stale snapshot and taking one
        synchronous harvest of the final state instead (folding both
        would stamp duplicate kernel-counters rows on the final sync)
        — so exported totals can never undercount the run
        (regression-tested in tests/test_async_wheel.py)."""
        from mpisppy_tpu.telemetry import counters as kcounters
        solvers = self._counter_solvers()
        pending = getattr(self, "_counters_pending", None)
        if pending and not flush:
            for cyl, handle in pending:
                self._fold_counter_harvest(
                    cyl, kcounters.complete_harvest(handle))
        # on flush the pending one-sync-stale snapshot is discarded:
        # the fresh synchronous harvest below supersedes it (totals are
        # cumulative set_counter mirrors), and folding both would stamp
        # two kernel-counters rows with different totals on the same
        # final sync
        self._counters_pending = [
            (cyl, kcounters.begin_harvest(s, include_ring=False))
            for cyl, s in solvers]
        if flush:
            for cyl, handle in self._counters_pending:
                self._fold_counter_harvest(
                    cyl, kcounters.complete_harvest(handle))
            self._counters_pending = []

    def _fold_counter_harvest(self, cyl: str, h: dict | None):
        if h is None:
            return
        from mpisppy_tpu.telemetry import counters as kcounters
        from mpisppy_tpu.telemetry import metrics as metrics_mod
        kcounters.fold_into_registry(metrics_mod.REGISTRY, h, cyl=cyl)
        if cyl != "hub":
            return
        guard_total = h["pdhg_guard_resets_total"]
        if guard_total > self._last_guard_total:
            self._emit(tel.LANE_QUARANTINE,
                       resets=guard_total - self._last_guard_total,
                       total=guard_total)
        self._last_guard_total = guard_total
        self._emit(tel.KERNEL_COUNTERS, **h)

    # -- dispatch-scheduler stats harvest (docs/dispatch.md) --------------
    def _harvest_dispatch_stats(self):
        """One per-sync snapshot of the solve-dispatch scheduler
        (queue depth, batch occupancy, in-flight, compile counts) onto
        the event stream.  The scheduler mirrors its gauges into the
        metrics registry itself; this only adds the per-iteration
        trace row, and only when dispatches actually happened since
        the last sync — a wheel that never touches the MIP oracle pays
        one dict lookup."""
        from mpisppy_tpu import dispatch as _dispatch
        stats = _dispatch.scheduler_stats()
        if not stats or stats["batches"] == self._last_dispatch_batches:
            return
        self._last_dispatch_batches = stats["batches"]
        self._emit(tel.DISPATCH, **stats)

    # -- crash-resilient checkpointing (VERDICT r3 #2; the analog of the
    # reference surviving solver/license hiccups, ref:spopt.py:931-960) --
    def _maybe_checkpoint(self):
        import time as _time
        path = self.options.get("checkpoint_path")
        if not path:
            return
        every_it = self.options.get("checkpoint_every_iters")
        if every_it:
            # deterministic iteration cadence, SYNCHRONOUS save: the
            # multi-process mesh path (ISSUE 17).  The leaf fetch is a
            # cross-host collective there, so every process must enter
            # it at the same point in program order — wall-clock
            # cadence and background writer threads both desync the
            # collective streams and deadlock gloo.
            if self._iter > 0 and self._iter % int(every_it) == 0 \
                    and self._iter != getattr(self, "_last_ckpt_iter", -1):
                if self.save_checkpoint(path):
                    self._last_ckpt_iter = self._iter
            return
        every = float(self.options.get("checkpoint_every_s", 60.0))
        now = _time.perf_counter()
        last = getattr(self, "_last_ckpt_t", None)
        if last is None:
            # first sync: start the clock, don't save yet
            self._last_ckpt_t = now
            return
        if now - last < every:
            return
        # only consume the cadence slot when a save actually LAUNCHES:
        # a skipped save (previous write thread still alive) must retry
        # next sync, or a slow write silently halves the checkpoint
        # frequency
        if self.save_checkpoint(path, background=True):
            self._last_ckpt_t = now

    def save_checkpoint(self, path: str, background: bool = False,
                        tmp_tag: str = ".tmp"):
        """Atomic npz snapshot of the full wheel: solver state (wstate
        for FusedPH, else PHState), hub bound bookkeeping, spoke bests,
        and caller extras (options['checkpoint_extra'] -> dict).

        background=True writes from a daemon thread: a full-wheel
        snapshot at 10k scenarios is ~460 MB, and fetching it through
        the device tunnel synchronously (~50 s measured) would gate the
        hub loop.  The state pytree is immutable and device_get is
        thread-safe, so the transfer overlaps compute; at most one save
        is in flight (later requests are skipped, not queued).

        Returns True when a write launched (or completed, for
        synchronous saves), False when it was skipped — the cadence
        bookkeeping in _maybe_checkpoint depends on this."""
        import threading

        import jax
        st = getattr(self.opt, "wstate", None)
        which = "wstate" if st is not None else "state"
        if st is None:
            st = self.opt.state
        if st is None:
            return False  # preempted before Iter0: nothing to persist
        # created here (always the main thread) so the two possible
        # writers — the background daemon and a later emergency save —
        # share one lock without a creation race
        if not hasattr(self, "_ckpt_lock"):
            self._ckpt_lock = threading.Lock()
        leaves, _ = jax.tree.flatten(st)
        if background:
            prev = getattr(self, "_ckpt_thread", None)
            if prev is not None and prev.is_alive():
                return False
            host_meta = self._checkpoint_meta(which)
            t = threading.Thread(
                target=self._write_checkpoint,
                args=(path, leaves, host_meta, tmp_tag), daemon=True)
            self._ckpt_thread = t
            t.start()
            return True
        self._write_checkpoint(path, leaves, self._checkpoint_meta(which),
                               tmp_tag)
        return True

    def emergency_checkpoint(self, path: str) -> bool:
        """Synchronous last-gasp save for SIGTERM/SIGINT/preemption.

        Deliberately does NOT wait for an in-flight background write: at
        10k scenarios a snapshot write is ~50 s (see save_checkpoint),
        longer than the eviction grace window, so joining would forfeit
        the save.  A distinct tmp name keeps the two writers from
        clobbering each other's staging file; if the slow background
        write lands after us its (older) snapshot becomes `path` and
        ours rotates to path.1 — load_checkpoint validates and falls
        back, so a complete snapshot survives either ordering.  Returns
        True when a snapshot landed.

        Best effort BY CONTRACT: on a multi-process mesh whose peer
        just died, the leaf-fetch gather cannot complete (bounded by
        checkpoint_gather_timeout_s, _fetch_leaf) — the save is
        reported skipped and the restore path falls back to the last
        rotated periodic snapshot instead of hanging the survivor."""
        try:
            return self.save_checkpoint(path, background=False,
                                        tmp_tag=".emergency.tmp")
        except Exception as e:  # noqa: BLE001 — last-gasp, logged
            global_toc(f"emergency checkpoint failed ({e}); "
                       "falling back to last rotated snapshot", True)
            return False

    def _checkpoint_meta(self, which: str) -> dict:
        """Host-side bookkeeping captured SYNCHRONOUSLY (the mutable
        bits; device leaves are immutable and can transfer later)."""
        data = {}
        data["which"] = np.frombuffer(which.encode(), np.uint8)
        data["hub_iter"] = np.asarray(self._iter)
        data["opt_iter"] = np.asarray(self.opt._iter)
        data["bounds"] = np.asarray([self.BestOuterBound,
                                     self.BestInnerBound])
        data["ib_update_iter"] = np.asarray(self._inner_bound_update_iter)
        tb = self.opt.trivial_bound
        data["trivial"] = np.asarray([
            np.nan if tb is None else tb,
            1.0 if self.opt.trivial_bound_certified else 0.0,
            1.0 if getattr(self, "_trivial_bound_folded", False) else 0.0])
        for j, sp in enumerate(self.spokes):
            if sp.bound is not None:
                data[f"spoke{j}_bound"] = np.asarray(sp.bound)
                bx = getattr(sp, "best_xhat", None)
                if bx is not None:
                    data[f"spoke{j}_xhat"] = np.asarray(bx)
        bx = getattr(self, "_best_inner_xhat", None)
        if bx is not None:
            data["hub_best_xhat"] = np.asarray(bx)
        extra = self.options.get("checkpoint_extra")
        if callable(extra):
            for k, v in extra().items():
                data[f"extra_{k}"] = np.asarray(v)
        return data

    def _write_checkpoint(self, path: str, leaves, data: dict,
                          tmp_tag: str = ".tmp"):
        """Atomic rotated write: tmp -> rotate path->path.1->... ->
        rename tmp to path.  The meta carries a CRC32 over every array
        so load_checkpoint can reject silent corruption (a torn zip
        already fails np.load; bit rot inside a member does not)."""
        import os
        gather_timeout = self.options.get("checkpoint_gather_timeout_s")
        for i, x in enumerate(leaves):
            data[f"leaf{i}"] = _fetch_leaf(x, gather_timeout)
        data["crc"] = _checkpoint_crc(data)
        tmp = path + tmp_tag
        with open(tmp, "wb") as f:
            np.savez(f, **data)
        # rotate + final rename under the shared writer lock: without
        # it the background daemon could rename its OLDER tmp over a
        # just-landed emergency snapshot without rotating it aside,
        # destroying the newest state outright (distinct tmp names only
        # protect the staging files, not this sequence)
        import threading
        lock = getattr(self, "_ckpt_lock", None) or threading.Lock()
        with lock:
            # keep floor of 2: with a single slot a slow background
            # write finishing after an emergency save would still
            # CLOBBER it — the both-orderings survival guarantee
            # (emergency_checkpoint) needs >= 2 slots
            keep = max(2, int(self.options.get("checkpoint_keep", 2)))
            for i in range(keep - 1, 0, -1):
                src = path if i == 1 else f"{path}.{i - 1}"
                try:
                    if os.path.exists(src):
                        os.replace(src, f"{path}.{i}")
                except OSError:
                    # a stolen rotation slot is harmless — every
                    # completed snapshot is self-validating; only
                    # losing a WRITE would matter
                    pass
            os.replace(tmp, path)
            # durability: flush the directory inode so a host crash
            # right after this rename cannot roll the entry back and
            # lose the newest snapshot (utils/atomic_io.fsync_dir;
            # tests/test_chaos.py crash-ordering test)
            from mpisppy_tpu.utils.atomic_io import fsync_dir
            fsync_dir(path)
        # may run on the background writer daemon: the bus is
        # thread-safe, and the snapshot's own hub_iter (not the
        # possibly-advanced live self._iter) stamps the event
        self.telemetry.emit(
            tel.CHECKPOINT_WRITE, run=self.run_id, cyl="hub",
            hub_iter=int(data["hub_iter"]), path=path,
            bytes=os.path.getsize(path))
        from mpisppy_tpu.telemetry import metrics as metrics_mod
        metrics_mod.REGISTRY.inc("checkpoint_writes_total")
        plan = self.options.get("fault_plan")
        if plan is not None:
            plan.on_checkpoint_written(path)

    def _checkpoint_candidates(self, path: str) -> list[str]:
        """Existing snapshots, newest first: path, path.1, path.2, ..."""
        import os
        out = [path] if os.path.exists(path) else []
        i = 1
        while os.path.exists(f"{path}.{i}"):
            out.append(f"{path}.{i}")
            i += 1
        return out

    def load_checkpoint(self, path: str, transform=None) -> dict:
        """Restore a save_checkpoint snapshot into the built (unspun)
        wheel; ph_main then skips Iter0 and resumes the loop.  Returns
        the extras dict.

        transform: optional arrays-dict -> arrays-dict hook applied
        after integrity checks and before shape validation — the
        elastic-reshard seam (parallel/elastic.adapt_checkpoint_arrays
        re-partitions scenario-major leaves onto a shrunk mesh).

        Falls back through the rotated candidates (path, path.1, ...)
        on a torn/corrupt/incompatible file instead of crashing — the
        preemption-tolerance contract: the newest VALID snapshot wins.
        "Newest" is decided by the hub_iter stored in each snapshot's
        meta, not by filename: an emergency save racing a slow
        background write can leave the OLDER snapshot at `path` (the
        background writer's rotation lands last), and filename order
        would silently discard the iterations the emergency save
        preserved."""
        cands = self._checkpoint_candidates(path)
        order = []
        for i, cand in enumerate(cands):
            try:  # cheap lazy read of one meta scalar, no validation
                with np.load(cand) as d:
                    it = int(d["hub_iter"])
            except Exception:
                it = -1  # unreadable here: full validation gets it last
            order.append((it, -i, cand))
        order.sort(reverse=True)
        errors = []
        for _, _, cand in order:
            try:
                arrays = self._read_checkpoint_arrays(cand)
            except Exception as e:  # torn zip, bad crc, IO error, ...
                errors.append(f"{cand}: {type(e).__name__}: {e}")
                continue
            try:
                if transform is not None:
                    arrays = transform(arrays)
                extras = self._restore_from_arrays(arrays)
            except ValueError as e:  # wrong shapes/dtypes/leaf count
                errors.append(f"{cand}: {e}")
                continue
            if cand != path:
                global_toc(f"checkpoint: {path} invalid, restored the "
                           f"older rotated snapshot {cand}", True)
            self._emit(tel.CHECKPOINT_RESTORE, path=cand,
                       fallback=cand != path)
            return extras
        detail = "; ".join(errors) if errors else "no snapshot files"
        raise FileNotFoundError(
            f"no valid checkpoint under {path!r}: {detail}")

    def _read_checkpoint_arrays(self, path: str) -> dict:
        """Load + integrity-check one snapshot file (no state mutation).
        The NpzFile is a context manager — it holds an open zip handle
        that was previously never closed."""
        with np.load(path) as data:
            arrays = {k: np.asarray(data[k]) for k in data.files}
        if "crc" in arrays:
            stored = int(arrays.pop("crc"))
            actual = int(_checkpoint_crc(arrays))
            if actual != stored:
                raise ValueError(
                    f"checksum mismatch (stored {stored:#x}, "
                    f"recomputed {actual:#x})")
        if "which" not in arrays:
            raise ValueError("not a wheel checkpoint (missing 'which')")
        return arrays

    def _restore_from_arrays(self, data: dict) -> dict:
        import jax
        import jax.numpy as jnp
        from mpisppy_tpu.utils.wxbarutils import validate_state_leaves
        which = bytes(data["which"]).decode()
        template = self.opt.state_template()
        leaves, treedef = jax.tree.flatten(template)
        validate_state_leaves(data, leaves)
        new = [jnp.asarray(data[f"leaf{i}"]) for i in range(len(leaves))]
        st = jax.tree.unflatten(treedef, new)
        if which == "wstate":
            self.opt.wstate = st
            self.opt.state = st.ph
        else:
            self.opt.state = st
        self._iter = int(data["hub_iter"])
        self.opt._iter = int(data["opt_iter"])
        ob, ib = [float(v) for v in data["bounds"]]
        self.BestOuterBound, self.BestInnerBound = ob, ib
        self._inner_bound_update_iter = int(data["ib_update_iter"])
        tb, cert, folded = [float(v) for v in data["trivial"]]
        self.opt.trivial_bound = None if math.isnan(tb) else tb
        self.opt.trivial_bound_certified = bool(cert)
        self._trivial_bound_folded = bool(folded)
        if "hub_best_xhat" in data:
            self._best_inner_xhat = np.asarray(data["hub_best_xhat"])
        # re-baseline the quarantine delta tracker: the restored solver
        # carries its historical cumulative guard_resets, and without
        # this the first post-restore sync would emit a spurious
        # lane-quarantine event re-reporting all past resets as fresh
        solver = getattr(self.opt.state, "solver", None)
        if solver is not None:
            self._last_guard_total = int(
                np.asarray(solver.guard_resets).sum())
        for j, sp in enumerate(self.spokes):
            key = f"spoke{j}_bound"
            if key in data:
                sp.bound = float(data[key])
                if f"spoke{j}_xhat" in data:
                    sp.best_xhat = np.asarray(data[f"spoke{j}_xhat"])
        return {k[len("extra_"):]: data[k] for k in data
                if k.startswith("extra_")}

    def is_converged(self) -> bool:
        # use the PH trivial bound as the initial outer bound
        # (ref:hub.py:544) — but only when its dual-residual certificate
        # held: a truncated iter0 primal value can exceed the optimum,
        # and an invalid outer bound here would fire the "certified" gap
        # termination wrongly.  A once-flag, not an iteration-count gate:
        # the driver also syncs after Iter0 (ref:phbase.py:905-910), so
        # by the first is_converged call _iter is already 2.
        if (self.opt.trivial_bound is not None
                and not getattr(self, "_trivial_bound_folded", False)
                and getattr(self.opt, "trivial_bound_certified", False)):
            self._trivial_bound_folded = True
            self.OuterBoundUpdate(self.opt.trivial_bound, "T")
        return self.determine_termination()

    def main(self):
        """ref:cylinders/hub.py:571-573."""
        return self.opt.ph_main()

    def finalize(self):
        # the run is terminating on purpose: the watchdog must not
        # trip on the (possibly long) finalization work
        if self._watchdog is not None:
            self._watchdog.stop()
        # one last harvest so late async results count; fused drivers
        # first sync their pipelined scalar cache to the final iterate
        if hasattr(self.opt, "flush_scalars"):
            self.opt.flush_scalars()
        self._harvest_all()
        # settle any in-flight background checkpoint write so the file
        # on disk is complete before the caller inspects/deletes it
        t = getattr(self, "_ckpt_thread", None)
        if t is not None and t.is_alive():
            t.join()
        if self._profiler is not None:
            self._profiler.close()
        # final totals after the last iterk: complete the pipelined
        # pending harvest AND take one synchronous final one, so the
        # exported totals exactly match the device state
        self._harvest_kernel_counters(flush=True)
        self.emit_run_end(getattr(self, "_term_reason", None)
                          or "max-iter")
        return self.BestInnerBound

    def hub_finalize(self):
        abs_gap, rel_gap = self.compute_gaps()
        global_toc(f"Final bounds: outer {self.BestOuterBound:.6g} "
                   f"inner {self.BestInnerBound:.6g} rel_gap {rel_gap:.3e}",
                   self.options.get("display_progress", False))

    # -- solution access --------------------------------------------------
    def best_nonants(self) -> np.ndarray:
        """(num_nodes, N) nonants of the solution that achieved
        BestInnerBound — the inner-bound winner's cached x̂
        (ref:spin_the_wheel.py:171-195 _determine_innerbound_winner);
        falls back to the final xbar when no incumbent exists."""
        winner, best = None, math.inf
        for sp in self.spokes:
            # a NaN cached bound must never enter the winner scan (every
            # NaN comparison is False, so depending on spoke order it
            # could silently shadow — or be shadowed by — a real
            # incumbent), and neither may a disabled spoke's cache or a
            # value the hub's validation would reject: the written
            # solution must be consistent with the reported bounds
            if (ConvergerSpokeType.INNER_BOUND in sp.converger_spoke_types
                    and not getattr(sp, "disabled", False)
                    and sp.bound is not None and math.isfinite(sp.bound)
                    and sp.bound < best
                    and self._validate_bound("inner", sp.bound) is None
                    and getattr(sp, "best_xhat", None) is not None):
                winner, best = sp, sp.bound
        xhat = None
        if winner is not None:
            xhat = np.asarray(winner.best_xhat)
        elif getattr(self, "_best_inner_xhat", None) is not None:
            # the spoke that produced BestInnerBound was scrubbed or
            # disabled since: the hub-side cache (stored the moment the
            # bound was ACCEPTED, _harvest_all) still backs the
            # reported bound with its actual solution
            xhat = self._best_inner_xhat
        if xhat is not None:
            if xhat.ndim == 1:
                num_nodes = self.opt.batch.tree.num_nodes
                return np.broadcast_to(xhat, (num_nodes, xhat.shape[0]))
            return xhat
        return self._fallback_nonants()

    def _fallback_nonants(self) -> np.ndarray:
        return np.asarray(self.opt.state.xbar_nodes)


class AsyncPHHub(PHHub):
    """Asynchronous exchange hub (ISSUE 11 tentpole;
    docs/async_wheel.md).  Pair with algos.async_wheel.AsyncFusedPH.

    options['async_staleness'] = s >= 1 splits every sync into a
    device-issue half (iteration stamping, fault seams, the driver's
    plane write — all while the just-dispatched step runs) and a
    host-complete half (harvest -> validate -> publish -> checkpoint,
    all against information the depth-2 scalar pipeline already
    landed), so the host exchange overlaps device iterations instead
    of serializing between dispatches.  The kernel-counter harvest is
    pipelined in the base hub already (begin now / complete next sync);
    here the plane-write and overlap attribution additionally land in
    the trace (`plane-write`, `exchange-overlap` events).

    s = 0 routes every sync through the synchronous PHHub body —
    trajectories, trace events and checkpoints are bit-identical to a
    plain PHHub wheel by construction (tested)."""

    def _async_staleness(self) -> int:
        """The ONE staleness source of truth is the driver's
        AsyncWheelOptions (it owns the delay line and decides between
        the sync and stale iteration paths); options['async_staleness']
        is only the CLI mirror.  Deriving the hub's routing from the
        driver — and refusing a contradictory mirror — means an
        AsyncFusedPH paired with this hub can never silently run the
        synchronous body while the driver queues plane tickets and
        events nobody drains."""
        aopts = getattr(self.opt, "async_options", None)
        drv = None if aopts is None else int(aopts.staleness)
        mirror = self.options.get("async_staleness")
        if drv is not None and mirror is not None and int(mirror) != drv:
            raise ValueError(
                f"async_staleness mismatch: hub options carry "
                f"{int(mirror)} but the driver's AsyncWheelOptions "
                f"carry {drv} — set one (the driver's is "
                f"authoritative)")
        if drv is not None:
            return drv
        return int(mirror or 0)

    def _exchange_gate(self):
        """Context guarding the host-complete half.  The default is a
        no-op; the serve layer's multiplexer (serve/multiplex.py)
        overrides it with a token ring so only one session at a time
        runs its host exchange while every other session's device
        issue half keeps feeding the wheel — one device stream
        advances several tenants between host exchanges."""
        return contextlib.nullcontext()

    def _sync_body(self):
        staleness = self._async_staleness()
        if staleness <= 0:
            return super()._sync_body()
        from mpisppy_tpu.telemetry import metrics as metrics_mod
        t0 = time.perf_counter()
        with self._span("exchange_issue"):
            self._sync_prologue()
            plan = self.options.get("fault_plan")
            # the driver recorded its plane writes while dispatching
            # this iteration; stamp them onto the stream here (the
            # driver has no bus)
            for evd in getattr(self.opt, "take_plane_events",
                               lambda: [])():
                self._emit(tel.PLANE_WRITE, **evd)
                metrics_mod.REGISTRY.inc("async_plane_writes_total")
                metrics_mod.REGISTRY.set_gauge(
                    "async_plane_staleness",
                    float(evd.get("staleness", 0)))
        t1 = time.perf_counter()
        with self._span("exchange_complete"), self._exchange_gate():
            if plan is not None:
                # chaos seam: a slow host harvest (resilience/faults
                # AsyncExchangeFault) — the wedged-exchange case the
                # hub watchdog must still catch
                plan.before_harvest(self._iter)
            # settle the PREVIOUS iteration's plane tickets with the
            # PR-8 bounded-wait semantics (a wedged exchange surfaces
            # as SolveFailed('deadline'), never a silent hang)
            if hasattr(self.opt, "result_exchange"):
                self.opt.result_exchange()
            self._sync_exchange()
        t2 = time.perf_counter()
        self._sync_epilogue()
        theta = getattr(self.opt, "last_theta", None)
        self._emit(tel.EXCHANGE_OVERLAP,
                   staleness=staleness,
                   issue_s=round(t1 - t0, 6),
                   complete_s=round(t2 - t1, 6),
                   **({} if theta is None else {"theta": float(theta)}))


class APHHub(PHHub):
    """APH as the hub algorithm (ref:mpisppy/cylinders/hub.py:712-724
    APHHub): identical exchange surface to PHHub — Ws and nonants out,
    bounds in — minus the barrier-synchronized write-id protocol the
    reference skips for APH (ref:hub.py:396,420,427-431), which has no
    analog here anyway."""

    def _trace_extra(self) -> dict:
        return {"conv": float(self.opt.state.conv),
                "theta": float(self.opt.state.theta)}

    def main(self):
        """ref:cylinders/hub.py:722-724."""
        return self.opt.APH_main()


class LShapedHub(PHHub):
    """L-shaped (Benders) as the hub algorithm
    (ref:mpisppy/cylinders/hub.py:618-710 LShapedHub): sends only
    NONANTS (the master's current candidate) to spokes — no W exists —
    and folds the Benders lb/ub into the bound bookkeeping."""

    def setup_hub(self):
        self.opt.spcomm = self
        for sp in self.spokes:
            types = sp.converger_spoke_types
            if ConvergerSpokeType.W_GETTER in types:
                raise RuntimeError(
                    "LShapedHub cannot feed W-getter spokes "
                    "(ref:hub.py:618-710 sends nonants only)")
            sp.make_windows()

    def _snapshot(self) -> dict:
        ls = self.opt  # an algos.lshaped.LShapedMethod
        batch = ls.batch
        xhat = np.asarray(ls.xhat)
        S = batch.num_scenarios
        return {
            "nonants": np.broadcast_to(xhat, (S, xhat.shape[0])),
            "xbar_scen": np.broadcast_to(xhat, (S, xhat.shape[0])),
            "xbar_nodes": xhat[None, :],
            "iter": self._iter,
            "bounds": (self.BestOuterBound, self.BestInnerBound),
        }

    def _fold_own_bounds(self):
        # the hub algorithm itself produces both bounds
        self.OuterBoundUpdate(self.opt.lb, "B")
        if np.isfinite(self.opt.ub):
            self.InnerBoundUpdate(self.opt.ub, "B")

    def _trace_extra(self) -> dict:
        return {}

    def is_converged(self) -> bool:
        return self.determine_termination()

    def main(self):
        return self.opt.lshaped_algorithm()

    def _fallback_nonants(self) -> np.ndarray:
        return np.asarray(self.opt.xhat)[None, :]
