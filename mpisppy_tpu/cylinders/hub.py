###############################################################################
# Hub: runs the hub algorithm (PH), feeds spokes, tracks bounds, decides
# termination (ref:mpisppy/cylinders/hub.py:28-724).
#
# The reference hub Puts W/nonants into RMA windows and Gets bounds back,
# with write-id consensus; here `sync()` hands the spokes a host-side
# snapshot dict (device arrays — zero-copy) and harvests their previous
# results.  On ONE chip, classic spokes' separate device dispatches
# SERIALIZE against the hub (round-3 measured 642x bare PH per
# iteration for a 4-spoke wheel — async dispatch does NOT overlap work
# on a single queue); the production answer is algos/fused_wheel.py,
# which carries the bound planes INSIDE the hub's jitted step
# (measured <=4.5x bare PH for the same 4 bound planes).  Classic
# spokes remain for cut/rc providers and multi-process deployments.
#
# Termination semantics match ref:mpisppy/cylinders/hub.py:82-166:
#   * rel_gap  <= options['rel_gap']   (gap = (inner-outer)/|inner|;
#     when |inner| ~ 0 the denominator widens to max(|inner|,|outer|)
#     so shifted-objective models can still terminate — see compute_gaps)
#   * abs_gap  <= options['abs_gap']
#   * inner bounds stalled for 'max_stalled_iters' hub iterations
###############################################################################
from __future__ import annotations

import math

import numpy as np

from mpisppy_tpu import global_toc
from mpisppy_tpu.cylinders.spcommunicator import SPCommunicator
from mpisppy_tpu.cylinders.spoke import ConvergerSpokeType


class Hub(SPCommunicator):
    """Bound bookkeeping + termination (ref:cylinders/hub.py:28-243)."""

    def __init__(self, opt, options: dict | None = None, spokes=None):
        super().__init__(opt, options)
        self.spokes = spokes or []
        self.BestOuterBound = -math.inf  # min problems: lower bound
        self.BestInnerBound = math.inf
        self.latest_ib_char = ""
        self.latest_ob_char = ""
        self._inner_bound_update_iter = 0
        self._iter = 0
        self.trace: list[dict] = []

    # -- bound bookkeeping (ref:hub.py:207-243) ---------------------------
    def OuterBoundUpdate(self, new_bound: float, char: str = "*"):
        if new_bound > self.BestOuterBound:
            self.BestOuterBound = new_bound
            self.latest_ob_char = char
        return self.BestOuterBound

    def InnerBoundUpdate(self, new_bound: float, char: str = "*"):
        if new_bound < self.BestInnerBound:
            self.BestInnerBound = new_bound
            self.latest_ib_char = char
            self._inner_bound_update_iter = self._iter
        return self.BestInnerBound

    # -- gaps + termination (ref:hub.py:82-166) ---------------------------
    def compute_gaps(self) -> tuple[float, float]:
        abs_gap = self.BestInnerBound - self.BestOuterBound
        nano = 1e-10
        if self.BestInnerBound in (math.inf, -math.inf):
            rel_gap = math.inf
        else:
            # Reference semantics: divide by |inner| (ref:hub.py:96-101).
            # That blows up when the optimal objective is near zero
            # (legit for shifted models) and rel_gap termination can then
            # never fire — ONLY in that degenerate case fall back to the
            # larger bound magnitude, so every normal run keeps the exact
            # reference gap convention (the one BENCH numbers use).
            denom = abs(self.BestInnerBound)
            ob = abs(self.BestOuterBound)
            near_zero = denom < 1e-6 * max(1.0, ob if math.isfinite(ob)
                                           else 0.0)
            if near_zero and math.isfinite(ob):
                denom = max(denom, ob)
            rel_gap = abs_gap / max(nano, denom)
        return abs_gap, rel_gap

    def determine_termination(self) -> bool:
        abs_gap, rel_gap = self.compute_gaps()
        opt = self.options
        if "rel_gap" in opt and rel_gap <= opt["rel_gap"]:
            global_toc(f"Terminating: rel_gap {rel_gap:.4e} <= "
                       f"{opt['rel_gap']}", True)
            return True
        if "abs_gap" in opt and abs_gap <= opt["abs_gap"]:
            global_toc(f"Terminating: abs_gap {abs_gap:.4e} <= "
                       f"{opt['abs_gap']}", True)
            return True
        if "max_stalled_iters" in opt:
            # spokes only produce results on exchange iterations, so the
            # stall budget counts in EXCHANGE rounds (with
            # spoke_sync_period=k, intermediate iterations cannot update
            # the inner bound and must not count as stalled)
            period = max(1, int(opt.get("spoke_sync_period", 1)))
            if (self._iter - self._inner_bound_update_iter
                    >= opt["max_stalled_iters"] * period
                    and self.BestInnerBound < math.inf):
                global_toc("Terminating: inner bound stalled", True)
                return True
        return False

    def is_converged(self) -> bool:
        return self.determine_termination()


class PHHub(Hub):
    """PH as the hub algorithm (ref:cylinders/hub.py:462-573).

    `opt` is an algos.ph.PH driver; the hub installs itself as
    `opt.spcomm` so the PH loop calls sync()/is_converged() each
    iteration (the cylinder seam, ref:phbase.py:1040-1056).
    """

    def setup_hub(self):
        self.opt.spcomm = self
        for sp in self.spokes:
            sp.make_windows()
        # hub-side extension hooks (ref:mpisppy/cylinders/hub.py:476-516
        # setup_hub drives the extension's setup + spoke-index wiring)
        ext = getattr(self.opt, "extobject", None)
        if ext is not None:
            if hasattr(ext, "setup_hub"):
                ext.setup_hub()
            if hasattr(ext, "initialize_spoke_indices"):
                ext.initialize_spoke_indices()

    def _snapshot(self) -> dict:
        """Device-array snapshot for spokes (ref:hub.py:517-532 sends
        Ws + nonants + bounds).  xbar views are reused from the PH state
        — ph_iterk already reduced them."""
        st = self.opt.state
        batch = self.opt.batch
        return {
            "W": st.W,
            "nonants": batch.nonants(st.solver.x),
            "xbar_scen": st.xbar,
            "xbar_nodes": st.xbar_nodes,
            "iter": self._iter,
            "bounds": (self.BestOuterBound, self.BestInnerBound),
        }

    def _harvest_all(self, only=None):
        """Fold every spoke's latest result into the bound bookkeeping."""
        for sp in (self.spokes if only is None else only):
            b = sp.harvest()
            if b is None:
                continue
            # spokes may declare their trace char (ref spoke classes'
            # converger_spoke_char); default to the class initial
            ch = getattr(sp, "converger_spoke_char",
                         type(sp).__name__[0])
            if ConvergerSpokeType.OUTER_BOUND in sp.converger_spoke_types:
                self.OuterBoundUpdate(b, ch)
            elif ConvergerSpokeType.INNER_BOUND in sp.converger_spoke_types:
                self.InnerBoundUpdate(b, ch)
            sp.trace.append((self._iter, b))

    def _fold_own_bounds(self):
        """Fold bounds the hub algorithm itself produces (PH: none —
        the trivial bound enters via is_converged)."""

    def _trace_extra(self) -> dict:
        return {"conv": self.opt._read_conv()}

    def sync(self):
        """One hub<->spoke exchange: harvest the spokes' previous async
        results, then launch their next round on a fresh snapshot.

        options['spoke_sync_period'] = k exchanges with the spokes only
        every k-th sync: their device work launched at the previous
        exchange keeps running across the intervening hub iterations
        (XLA async dispatch), which is exactly the reference's
        slower-cylinder overlap (ref:hub.py write-id freshness checks —
        a spoke that hasn't produced a new result simply isn't read)."""
        self._iter += 1
        period = max(1, int(self.options.get("spoke_sync_period", 1)))
        do_spokes = (self._iter <= 2) or (self._iter % period == 0)
        # fused spokes (algos.fused_wheel) compute inside the hub's own
        # jitted step — harvesting them is a scalar read, so they fold
        # EVERY iteration; classic spokes keep the sync period
        fused = [sp for sp in self.spokes if getattr(sp, "fused", False)]
        classic = [sp for sp in self.spokes if not getattr(sp, "fused",
                                                           False)]
        self._harvest_all(only=fused)
        if do_spokes:
            self._harvest_all(only=classic)
            # extension exchange with the spokes it cares about
            # (ref:mpisppy/cylinders/hub.py:517-532 drives the
            # extension's sync_with_spokes every sync)
            ext = getattr(self.opt, "extobject", None)
            if ext is not None and hasattr(ext, "sync_with_spokes"):
                ext.sync_with_spokes()
        self._fold_own_bounds()
        # building the snapshot dispatches a (small) device gather; with
        # an all-fused wheel no consumer exists, so skip it off-sync
        if (do_spokes and classic) or self.options.get("publish_snapshots"):
            payload = self._snapshot()
            self.from_hub.put(payload)  # for API parity / inspection
            if do_spokes:
                for sp in classic:
                    sp.update(payload)
        self._maybe_checkpoint()
        abs_gap, rel_gap = self.compute_gaps()
        extra = self._trace_extra()
        import time as _time
        self.trace.append({
            "iter": self._iter, **extra, "t": _time.perf_counter(),
            "outer": self.BestOuterBound, "inner": self.BestInnerBound,
            "abs_gap": abs_gap, "rel_gap": rel_gap,
            "ob_char": self.latest_ob_char, "ib_char": self.latest_ib_char,
        })
        if self.options.get("display_progress"):
            conv_str = (f" conv {extra['conv']:9.3e}"
                        if "conv" in extra else "")
            global_toc(
                f"iter {self._iter:4d}{conv_str}"
                f" outer {self.BestOuterBound:12.5g}"
                f" inner {self.BestInnerBound:12.5g} rel_gap {rel_gap:8.3e}"
                f" ({self.latest_ob_char}/{self.latest_ib_char})", True)

    # -- crash-resilient checkpointing (VERDICT r3 #2; the analog of the
    # reference surviving solver/license hiccups, ref:spopt.py:931-960) --
    def _maybe_checkpoint(self):
        import time as _time
        path = self.options.get("checkpoint_path")
        if not path:
            return
        every = float(self.options.get("checkpoint_every_s", 60.0))
        now = _time.perf_counter()
        last = getattr(self, "_last_ckpt_t", None)
        if last is None:
            # first sync: start the clock, don't save yet
            self._last_ckpt_t = now
            return
        if now - last < every:
            return
        self._last_ckpt_t = now
        self.save_checkpoint(path, background=True)

    def save_checkpoint(self, path: str, background: bool = False):
        """Atomic npz snapshot of the full wheel: solver state (wstate
        for FusedPH, else PHState), hub bound bookkeeping, spoke bests,
        and caller extras (options['checkpoint_extra'] -> dict).

        background=True writes from a daemon thread: a full-wheel
        snapshot at 10k scenarios is ~460 MB, and fetching it through
        the device tunnel synchronously (~50 s measured) would gate the
        hub loop.  The state pytree is immutable and device_get is
        thread-safe, so the transfer overlaps compute; at most one save
        is in flight (later requests are skipped, not queued)."""
        import os
        import threading

        import jax
        st = getattr(self.opt, "wstate", None)
        which = "wstate" if st is not None else "state"
        if st is None:
            st = self.opt.state
        leaves, _ = jax.tree.flatten(st)
        if background:
            prev = getattr(self, "_ckpt_thread", None)
            if prev is not None and prev.is_alive():
                return
            host_meta = self._checkpoint_meta(which)
            t = threading.Thread(
                target=self._write_checkpoint,
                args=(path, leaves, host_meta), daemon=True)
            self._ckpt_thread = t
            t.start()
            return
        self._write_checkpoint(path, leaves, self._checkpoint_meta(which))

    def _checkpoint_meta(self, which: str) -> dict:
        """Host-side bookkeeping captured SYNCHRONOUSLY (the mutable
        bits; device leaves are immutable and can transfer later)."""
        data = {}
        data["which"] = np.frombuffer(which.encode(), np.uint8)
        data["hub_iter"] = np.asarray(self._iter)
        data["opt_iter"] = np.asarray(self.opt._iter)
        data["bounds"] = np.asarray([self.BestOuterBound,
                                     self.BestInnerBound])
        data["ib_update_iter"] = np.asarray(self._inner_bound_update_iter)
        tb = self.opt.trivial_bound
        data["trivial"] = np.asarray([
            np.nan if tb is None else tb,
            1.0 if self.opt.trivial_bound_certified else 0.0,
            1.0 if getattr(self, "_trivial_bound_folded", False) else 0.0])
        for j, sp in enumerate(self.spokes):
            if sp.bound is not None:
                data[f"spoke{j}_bound"] = np.asarray(sp.bound)
                bx = getattr(sp, "best_xhat", None)
                if bx is not None:
                    data[f"spoke{j}_xhat"] = np.asarray(bx)
        extra = self.options.get("checkpoint_extra")
        if callable(extra):
            for k, v in extra().items():
                data[f"extra_{k}"] = np.asarray(v)
        return data

    def _write_checkpoint(self, path: str, leaves, data: dict):
        import os
        for i, x in enumerate(leaves):
            data[f"leaf{i}"] = np.asarray(x)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **data)
        os.replace(tmp, path)

    def load_checkpoint(self, path: str) -> dict:
        """Restore a save_checkpoint snapshot into the built (unspun)
        wheel; ph_main then skips Iter0 and resumes the loop.  Returns
        the extras dict."""
        import jax
        import jax.numpy as jnp
        data = np.load(path)
        which = bytes(data["which"]).decode()
        template = self.opt.state_template()
        leaves, treedef = jax.tree.flatten(template)
        new = [jnp.asarray(data[f"leaf{i}"]) for i in range(len(leaves))]
        for i, (a, b) in enumerate(zip(new, leaves)):
            if tuple(a.shape) != tuple(b.shape):
                raise ValueError(
                    f"checkpoint leaf {i} shape {a.shape} != expected "
                    f"{b.shape} (different problem/options?)")
        st = jax.tree.unflatten(treedef, new)
        if which == "wstate":
            self.opt.wstate = st
            self.opt.state = st.ph
        else:
            self.opt.state = st
        self._iter = int(data["hub_iter"])
        self.opt._iter = int(data["opt_iter"])
        ob, ib = [float(v) for v in data["bounds"]]
        self.BestOuterBound, self.BestInnerBound = ob, ib
        self._inner_bound_update_iter = int(data["ib_update_iter"])
        tb, cert, folded = [float(v) for v in data["trivial"]]
        self.opt.trivial_bound = None if math.isnan(tb) else tb
        self.opt.trivial_bound_certified = bool(cert)
        self._trivial_bound_folded = bool(folded)
        for j, sp in enumerate(self.spokes):
            key = f"spoke{j}_bound"
            if key in data:
                sp.bound = float(data[key])
                if f"spoke{j}_xhat" in data:
                    sp.best_xhat = np.asarray(data[f"spoke{j}_xhat"])
        return {k[len("extra_"):]: data[k] for k in data.files
                if k.startswith("extra_")}

    def is_converged(self) -> bool:
        # use the PH trivial bound as the initial outer bound
        # (ref:hub.py:544) — but only when its dual-residual certificate
        # held: a truncated iter0 primal value can exceed the optimum,
        # and an invalid outer bound here would fire the "certified" gap
        # termination wrongly.  A once-flag, not an iteration-count gate:
        # the driver also syncs after Iter0 (ref:phbase.py:905-910), so
        # by the first is_converged call _iter is already 2.
        if (self.opt.trivial_bound is not None
                and not getattr(self, "_trivial_bound_folded", False)
                and getattr(self.opt, "trivial_bound_certified", False)):
            self._trivial_bound_folded = True
            self.OuterBoundUpdate(self.opt.trivial_bound, "T")
        return self.determine_termination()

    def main(self):
        """ref:cylinders/hub.py:571-573."""
        return self.opt.ph_main()

    def finalize(self):
        # one last harvest so late async results count; fused drivers
        # first sync their pipelined scalar cache to the final iterate
        if hasattr(self.opt, "flush_scalars"):
            self.opt.flush_scalars()
        self._harvest_all()
        # settle any in-flight background checkpoint write so the file
        # on disk is complete before the caller inspects/deletes it
        t = getattr(self, "_ckpt_thread", None)
        if t is not None and t.is_alive():
            t.join()
        return self.BestInnerBound

    def hub_finalize(self):
        abs_gap, rel_gap = self.compute_gaps()
        global_toc(f"Final bounds: outer {self.BestOuterBound:.6g} "
                   f"inner {self.BestInnerBound:.6g} rel_gap {rel_gap:.3e}",
                   self.options.get("display_progress", False))

    # -- solution access --------------------------------------------------
    def best_nonants(self) -> np.ndarray:
        """(num_nodes, N) nonants of the solution that achieved
        BestInnerBound — the inner-bound winner's cached x̂
        (ref:spin_the_wheel.py:171-195 _determine_innerbound_winner);
        falls back to the final xbar when no incumbent exists."""
        winner, best = None, math.inf
        for sp in self.spokes:
            if (ConvergerSpokeType.INNER_BOUND in sp.converger_spoke_types
                    and sp.bound is not None and sp.bound < best
                    and getattr(sp, "best_xhat", None) is not None):
                winner, best = sp, sp.bound
        if winner is not None:
            xhat = np.asarray(winner.best_xhat)
            if xhat.ndim == 1:
                num_nodes = self.opt.batch.tree.num_nodes
                return np.broadcast_to(xhat, (num_nodes, xhat.shape[0]))
            return xhat
        return self._fallback_nonants()

    def _fallback_nonants(self) -> np.ndarray:
        return np.asarray(self.opt.state.xbar_nodes)


class APHHub(PHHub):
    """APH as the hub algorithm (ref:mpisppy/cylinders/hub.py:712-724
    APHHub): identical exchange surface to PHHub — Ws and nonants out,
    bounds in — minus the barrier-synchronized write-id protocol the
    reference skips for APH (ref:hub.py:396,420,427-431), which has no
    analog here anyway."""

    def _trace_extra(self) -> dict:
        return {"conv": float(self.opt.state.conv),
                "theta": float(self.opt.state.theta)}

    def main(self):
        """ref:cylinders/hub.py:722-724."""
        return self.opt.APH_main()


class LShapedHub(PHHub):
    """L-shaped (Benders) as the hub algorithm
    (ref:mpisppy/cylinders/hub.py:618-710 LShapedHub): sends only
    NONANTS (the master's current candidate) to spokes — no W exists —
    and folds the Benders lb/ub into the bound bookkeeping."""

    def setup_hub(self):
        self.opt.spcomm = self
        for sp in self.spokes:
            types = sp.converger_spoke_types
            if ConvergerSpokeType.W_GETTER in types:
                raise RuntimeError(
                    "LShapedHub cannot feed W-getter spokes "
                    "(ref:hub.py:618-710 sends nonants only)")
            sp.make_windows()

    def _snapshot(self) -> dict:
        ls = self.opt  # an algos.lshaped.LShapedMethod
        batch = ls.batch
        xhat = np.asarray(ls.xhat)
        S = batch.num_scenarios
        return {
            "nonants": np.broadcast_to(xhat, (S, xhat.shape[0])),
            "xbar_scen": np.broadcast_to(xhat, (S, xhat.shape[0])),
            "xbar_nodes": xhat[None, :],
            "iter": self._iter,
            "bounds": (self.BestOuterBound, self.BestInnerBound),
        }

    def _fold_own_bounds(self):
        # the hub algorithm itself produces both bounds
        self.OuterBoundUpdate(self.opt.lb, "B")
        if np.isfinite(self.opt.ub):
            self.InnerBoundUpdate(self.opt.ub, "B")

    def _trace_extra(self) -> dict:
        return {}

    def is_converged(self) -> bool:
        return self.determine_termination()

    def main(self):
        return self.opt.lshaped_algorithm()

    def _fallback_nonants(self) -> np.ndarray:
        return np.asarray(self.opt.xhat)[None, :]
