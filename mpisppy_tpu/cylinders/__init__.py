from mpisppy_tpu.cylinders.spcommunicator import SPCommunicator  # noqa: F401
from mpisppy_tpu.cylinders.hub import (  # noqa: F401
    APHHub, AsyncPHHub, Hub, LShapedHub, PHHub,
)
from mpisppy_tpu.cylinders.spoke import (  # noqa: F401
    ConvergerSpokeType, Spoke, OuterBoundSpoke, InnerBoundSpoke,
    LagrangianOuterBound, SubgradientOuterBound, XhatXbarInnerBound,
    XhatLShapedInnerBound, XhatShuffleInnerBound, SlamMaxHeuristic,
    SlamMinHeuristic,
)
