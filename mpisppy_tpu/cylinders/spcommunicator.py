###############################################################################
# SPCommunicator: the hub<->spoke data plane, TPU-native.
#
# The reference allocates MPI one-sided RMA windows of doubles with a
# write-id tail and a consensus Allreduce to detect fresh messages
# (ref:mpisppy/cylinders/spcommunicator.py:34-128,
# ref:mpisppy/cylinders/hub.py:379-445, spoke.py:63-122).  All of that
# machinery exists to move small dense vectors (W, nonants, scalar
# bounds, a kill flag) between PROCESSES.
#
# Here hub and spokes live in ONE process driving one device mesh, so the
# "window" is a plain host-side mailbox of jax Arrays with a write
# counter.  The asynchrony the reference gets from RMA windows we get
# from XLA's async dispatch: a spoke's `update` launches device work and
# returns immediately; its arrays are futures the hub only blocks on
# when it reads the bound.  Freshness = compare write ids — same
# semantics, no locks, no consensus protocol needed (single host thread).
#
# Wire format parity (ref:mpisppy/cylinders/hub.py:586-616): hub
# publishes {"W": (S,N), "nonants": (S,N), "xbar": (nodes,N), "bounds":
# (outer, inner)}; spokes publish {"bound": scalar} or {"nonants": ...}.
###############################################################################
from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class Mailbox:
    """One-directional message slot with freshness tracking
    (the RMA window + write_id analog, ref:spcommunicator.py:100-128)."""

    payload: Any = None
    write_id: int = 0

    def put(self, payload: Any):
        self.payload = payload
        self.write_id += 1

    def fresh_for(self, last_seen: int) -> bool:
        return self.write_id > last_seen


class SPCommunicator:
    """Base for hub and spoke communicators
    (ref:mpisppy/cylinders/spcommunicator.py:34).

    Lifecycle hooks mirror the reference: make_windows() allocates the
    mailboxes, main() runs the algorithm, sync() exchanges data,
    is_converged() decides termination, finalize() returns the last
    result.
    """

    def __init__(self, opt, options: dict | None = None):
        self.opt = opt
        self.options = options or {}
        self.to_hub = Mailbox()
        self.from_hub = Mailbox()
        self._last_seen_hub = 0
        self._kill = False
        # back-pointer set by WheelSpinner
        self.strata_rank = 0

    # -- window lifecycle (no-ops kept for API parity) --------------------
    def make_windows(self):
        pass

    def free_windows(self):
        pass

    # -- messaging --------------------------------------------------------
    def got_kill_signal(self) -> bool:
        """ref:mpisppy/cylinders/spoke.py:124-128 (write_id == -1)."""
        return self._kill

    def send_terminate(self):
        """ref:mpisppy/cylinders/hub.py:447-459."""
        self._kill = True

    def hub_update(self) -> Any | None:
        """Fresh hub payload or None (spoke_from_hub analog)."""
        if self.from_hub.fresh_for(self._last_seen_hub):
            self._last_seen_hub = self.from_hub.write_id
            return self.from_hub.payload
        return None

    # -- hooks ------------------------------------------------------------
    def main(self):
        raise NotImplementedError

    def sync(self):
        pass

    def is_converged(self) -> bool:
        return False

    def finalize(self):
        return None

    def hub_finalize(self):
        pass
