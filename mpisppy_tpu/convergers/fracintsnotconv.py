###############################################################################
# FractionalConverger: fraction of integer nonants not yet converged
# across scenarios (ref:mpisppy/convergers/fracintsnotconv.py:19).
# "Converged" for an integer slot means every scenario in its tree node
# agrees with the (rounded) node average to within `ratio_tol`.
###############################################################################
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.convergers.converger import Converger


class FractionalConverger(Converger):
    """ref:mpisppy/convergers/fracintsnotconv.py:19."""

    def __init__(self, opt):
        super().__init__(opt)
        options = getattr(opt, "options", None)
        odict = getattr(options, "__dict__", {}) if options else {}
        self.fracthresh = float(
            getattr(opt, "frac_thresh", odict.get("frac_thresh", 0.05)))
        self.ratio_tol = 1e-4

    def is_converged(self) -> bool:
        batch = self.opt.batch
        mask = np.asarray(batch.integer_slot)
        if not mask.any():
            self.conv_value = 0.0
            return True
        st = self.opt.state
        x_non = batch.nonants(st.solver.x)
        xbar = st.xbar
        real = (batch.p > 0.0)[:, None]
        dev = jnp.where(real, jnp.abs(x_non - jnp.round(xbar)), 0.0)
        slot_conv = jnp.max(dev, axis=0) <= self.ratio_tol   # (N,)
        notconv = np.asarray(~slot_conv) & mask
        self.conv_value = float(notconv.sum() / mask.sum())
        return self.conv_value < self.fracthresh
