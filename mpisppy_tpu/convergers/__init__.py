# Convergers: hub-side intra-algorithm termination
# (ref:mpisppy/convergers/).
from mpisppy_tpu.convergers.converger import Converger  # noqa: F401
from mpisppy_tpu.convergers.fracintsnotconv import (  # noqa: F401
    FractionalConverger,
)
from mpisppy_tpu.convergers.norm_rho_converger import (  # noqa: F401
    NormRhoConverger,
)
from mpisppy_tpu.convergers.primal_dual_converger import (  # noqa: F401
    PrimalDualConverger,
)
