###############################################################################
# NormRhoConverger (ref:mpisppy/convergers/norm_rho_converger.py:18):
# terminate when the rho-weighted primal metric
#   sum_s p_s || rho * (x_s - xbar) ||_1
# falls below a threshold — the same quantity NormRhoUpdater adapts on.
###############################################################################
from __future__ import annotations

import jax.numpy as jnp

from mpisppy_tpu.convergers.converger import Converger


class NormRhoConverger(Converger):
    """ref:mpisppy/convergers/norm_rho_converger.py:18."""

    def __init__(self, opt):
        super().__init__(opt)
        self.tol = float(getattr(opt, "norm_rho_tol", 1e-4))

    def is_converged(self) -> bool:
        batch = self.opt.batch
        st = self.opt.state
        x_non = batch.nonants(st.solver.x)
        metric = batch.expectation(
            jnp.sum(jnp.abs(st.rho * (x_non - st.xbar)), axis=-1))
        self.conv_value = float(metric)
        return self.conv_value < self.tol
