###############################################################################
# PrimalDualConverger (ref:mpisppy/convergers/primal_dual_converger.py:
# 17,66-120): terminate when BOTH
#   primal: sum_s p_s ||x_s - xbar||_1          (nonanticipativity gap)
#   dual:   ||rho * (xbar_t - xbar_{t-1})||_1   (dual movement)
# fall below `tol`.  The reference computes each with an Allreduce; here
# both are reductions over the device-resident state, and the previous
# xbar is carried host-side between calls.
###############################################################################
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.convergers.converger import Converger


class PrimalDualConverger(Converger):
    """ref:mpisppy/convergers/primal_dual_converger.py:17."""

    def __init__(self, opt, tol: float = 1e-2):
        super().__init__(opt)
        self.tol = float(tol)
        self._prev_xbar = None
        self.trace: list[tuple[float, float]] = []

    def is_converged(self) -> bool:
        batch = self.opt.batch
        st = self.opt.state
        x_non = batch.nonants(st.solver.x)
        primal = float(batch.expectation(
            jnp.sum(jnp.abs(x_non - st.xbar), axis=-1)))
        xbar_nodes = np.asarray(st.xbar_nodes)
        if self._prev_xbar is None:
            dual = np.inf
        else:
            rho = np.asarray(st.rho)
            dual = float(np.sum(np.abs(rho * (xbar_nodes
                                              - self._prev_xbar))))
        self._prev_xbar = xbar_nodes
        self.conv_value = max(primal, dual)
        self.trace.append((primal, dual))
        return primal < self.tol and dual < self.tol
