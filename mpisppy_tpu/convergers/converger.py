###############################################################################
# Converger ABC (ref:mpisppy/convergers/converger.py:24-47): a hub-side
# object asked `is_converged()` once per PH iteration, with access to the
# PH driver (`self.opt`) and thus the device-resident PHState.
###############################################################################
from __future__ import annotations

import abc


class Converger(abc.ABC):
    """ref:mpisppy/convergers/converger.py:24."""

    def __init__(self, opt):
        self.opt = opt
        self.conv_value: float | None = None

    @abc.abstractmethod
    def is_converged(self) -> bool:
        ...
