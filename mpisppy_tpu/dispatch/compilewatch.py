###############################################################################
# CompileWatch: the process-wide backend-compile counter.
#
# The compile-cache discipline (docs/dispatch.md) is only enforceable
# if compiles are OBSERVABLE: jax.monitoring emits a
# '/jax/core/compile/backend_compile_duration' sample every time XLA
# actually lowers+compiles an executable (cache hits emit nothing), so
# one registered listener turns the silent recompile storm into a
# counter the scheduler can attribute to buckets and tests can assert
# on.  Listener registration is process-global and permanent (JAX has
# no unregister), so exactly one is ever installed here, guarded by a
# lock; everything downstream reads deltas of the monotone count.
###############################################################################
from __future__ import annotations

import threading

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_installed = False
_count = 0
_seconds = 0.0


def _listener(name: str, duration: float, **kw) -> None:
    global _count, _seconds
    if name == _COMPILE_EVENT:
        with _lock:
            _count += 1
            _seconds += float(duration)


def install() -> None:
    """Idempotently register the one process listener."""
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True
    import jax.monitoring
    jax.monitoring.register_event_duration_secs_listener(_listener)


class CompileWatch:
    """Delta view over the global counter: `with CompileWatch() as w`
    or manual mark()/delta().  Creating one installs the listener."""

    def __init__(self):
        install()
        self._mark = 0
        self.mark()

    @staticmethod
    def total() -> int:
        with _lock:
            return _count

    @staticmethod
    def total_seconds() -> float:
        with _lock:
            return _seconds

    def mark(self) -> None:
        self._mark = self.total()

    def delta(self) -> int:
        """Backend compiles since the last mark()."""
        return self.total() - self._mark

    def __enter__(self):
        self.mark()
        return self

    def __exit__(self, *exc):
        return False
