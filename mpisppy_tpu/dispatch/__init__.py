###############################################################################
# Dispatch subsystem: the one gate between host-driven solve loops and
# the device tunnel (docs/dispatch.md).
#
# The round-5 verdict's top item: sslp_15_45 re-certification runs never
# completed because per-step solve_mip calls from the Lagrangian-oracle
# loops (algos/mip.py) wedged the TPU tunnel with thousands of tiny,
# variably-shaped dispatches.  The reference never faces this — each
# scenario subproblem is one opaque Gurobi call on its own rank
# (ref:mpisppy/spopt.py:884) — but a TPU-native wheel needs the
# inference-serving shape instead: coalesce many small requests into
# fixed-shape batched solves (MPAX, arXiv:2412.09734) and keep
# utilization high with a bounded pipeline of large dispatches (Large
# Scale Distributed Linear Algebra With TPUs, arXiv:2112.09017).
#
# Three pieces (one module each):
#   * buckets.py      — the shape-bucket ladder + batch-axis padding:
#     every dispatch shape is rounded up a small geometric ladder so the
#     jit cache stays bounded and a recompile is a counted event;
#   * compilewatch.py — process-wide backend-compile counter riding
#     jax.monitoring, the evidence behind the compile-cache discipline;
#   * scheduler.py    — the coalescing queue (max-wait/max-batch
#     admission), the bounded in-flight semaphore (backpressure), and
#     the process-default scheduler every oracle loop routes through.
###############################################################################
from mpisppy_tpu.dispatch.buckets import (   # noqa: F401
    BucketLadder,
    default_ladder,
    pad_qp_batch,
    slice_result,
)
from mpisppy_tpu.dispatch.compilewatch import CompileWatch  # noqa: F401
from mpisppy_tpu.dispatch.scheduler import (  # noqa: F401
    DispatchContext,
    DispatchOptions,
    SolveFailed,
    SolveScheduler,
    clear_session_context,
    configure,
    current_context,
    current_hub_iter,
    from_cfg,
    get_scheduler,
    scheduler_stats,
    set_hub_iter,
    set_session_context,
    solve_mip,
)
