###############################################################################
# SolveScheduler: coalescing queue + bounded in-flight dispatch.
#
# Every host-driven MIP solve (algos/mip.py oracle loops, ops/bnb.py
# megabatches, decomposition-B&B node re-solves) routes through one of
# these instead of calling ops.bnb.solve_mip directly.  Three layers:
#
#   * ADMISSION (coalescing windows).  Requests are keyed by their
#     mergeable identity — (n, m), dtype, A storage/identity, integer
#     signature, BnBOptions — and same-key requests land in one open
#     WINDOW.  A window dispatches when it reaches max_batch lanes,
#     when max_wait_ms passes, or the moment a caller blocks on one of
#     its tickets (a sync caller never waits out the admission timer
#     for coalescence that cannot arrive).  Dispatch concatenates the
#     window's requests along the batch axis into one MEGABATCH solve
#     and splits the result back per ticket.
#   * BACKPRESSURE (bounded in-flight).  A semaphore of max_inflight
#     outstanding dispatches gates every window: when the device
#     pipeline is full, dispatching threads queue on the semaphore and
#     their windows KEEP ACCUMULATING requests while they wait — load
#     turns into batch occupancy instead of tunnel depth, which is the
#     whole point.  max_inflight=2 is the classic double buffer: one
#     dispatch executing, one staged.
#   * SHAPE DISCIPLINE (buckets + compile watch).  Megabatches pad up
#     the geometric ladder (buckets.py) before dispatch, the padded
#     shape signature is recorded in the bucket registry, and a
#     CompileWatch attributes backend compiles: a compile against an
#     already-warm bucket increments dispatch_unexpected_recompiles
#     (and raises under --dispatch-compile-guard) instead of silently
#     storming.
#   * FAULT DOMAIN (deadlines / retry / bisection quarantine;
#     ISSUE 9, docs/dispatch.md failure semantics).  Every ticket may
#     carry a deadline (per-submit or the options default) and
#     result() takes a timeout — a caller can NEVER block past the
#     earlier of the two; expiry raises a typed SolveFailed instead of
#     hanging.  Every megabatch dispatch may carry a timeout
#     (dispatch_timeout_s): a hung or raising dispatch (XLA
#     RuntimeError, OOM, a NaN-poisoned batch) is retried with
#     exponential backoff up to retry_max; a window still failing after
#     its budget is BISECTED — split in request halves and re-solved —
#     until the poison request(s) are isolated and QUARANTINED (their
#     tickets resolve with SolveFailed, the healthy halves proceed).
#     The dispatcher daemon is supervised: if the thread dies, every
#     queued ticket fails fast with SolveFailed("dispatcher-died")
#     instead of waiting forever, and the next submit restarts it.
#     PreemptionError and AssertionError (the compile guard) are never
#     retried — they must stay loud.
#
# Everything is recorded in the process metrics REGISTRY (gauges:
# queue depth, in-flight, occupancy; counters: batches, lanes, pad
# lanes, compiles, retries, quarantined lanes) and, when a bus is
# attached, emitted as "dispatch" / "dispatch-retry" /
# "dispatch-quarantine" / "watchdog" events — see docs/dispatch.md for
# the field tables.
###############################################################################
from __future__ import annotations

import dataclasses
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.dispatch import buckets as _buckets
from mpisppy_tpu.dispatch import compilewatch as _cw
from mpisppy_tpu.telemetry import metrics as _metrics


# -- hub-iteration stamp (ISSUE 5 satellite), generalized to a
# per-session context token (ISSUE 12 satellite) ----------------------------
# Single-wheel processes: the hub calls set_hub_iter at the top of every
# sync and every dispatch event carries the value, so the analyzer joins
# megabatches to the iteration timeline exactly.  -1 = pre-wheel
# (warm-up compiles, iter0 oracle work).  A plain int write/read — no
# lock needed for a monotone diagnostic stamp.
#
# Multi-session processes (the serve layer, docs/serving.md): several
# concurrent wheels share one scheduler, so a single global stamp would
# be whichever hub wrote last — garbage joins.  Each session's hub
# instead installs a THREAD-LOCAL DispatchContext (run id + hub iter)
# on its driver thread; submit() captures the submitting thread's token
# per request, and the megabatch event carries a per-session breakdown
# (`sessions`) so the analyzer joins every dispatch to the right
# session exactly — no seq heuristics (telemetry/analyze.py keeps a
# dispatch row whenever its sessions mention the analyzed run).
_hub_iter = -1
_ctx_local = threading.local()


@dataclasses.dataclass(frozen=True)
class DispatchContext:
    """One session's dispatch stamp: the run id of the hub driving this
    thread, its current hub iteration (-1 pre-wheel), and — ISSUE 20 —
    the causal trace/span ids of the session's current segment, so a
    MIXED megabatch's event row attributes every lane to the right
    trace, not just the right run."""

    run: str = ""
    hub_iter: int = -1
    trace_id: str = ""
    span_id: str = ""


def set_session_context(run: str, hub_iter: int = -1,
                        trace_id: str = "", span_id: str = "") -> None:
    """Install the calling thread's session token (the hub calls this
    each sync on its driver thread; the serve engine calls it before
    iter0 so warm-up dispatches already join the session)."""
    _ctx_local.ctx = DispatchContext(run=str(run), hub_iter=int(hub_iter),
                                     trace_id=str(trace_id or ""),
                                     span_id=str(span_id or ""))


def clear_session_context() -> None:
    _ctx_local.ctx = None


def current_context() -> DispatchContext:
    """The submitting thread's token; falls back to the process-global
    hub-iteration stamp (run resolved by the scheduler's own run id)."""
    ctx = getattr(_ctx_local, "ctx", None)
    return ctx if ctx is not None else DispatchContext(hub_iter=_hub_iter)


def set_hub_iter(it: int) -> None:
    global _hub_iter
    _hub_iter = int(it)
    # a thread that carries a session token advances it in lockstep so
    # the two stamps can never disagree on the same thread
    ctx = getattr(_ctx_local, "ctx", None)
    if ctx is not None:
        _ctx_local.ctx = dataclasses.replace(ctx, hub_iter=int(it))


def current_hub_iter() -> int:
    return _hub_iter


@dataclasses.dataclass(frozen=True)
class DispatchOptions:
    """Scheduler knobs (CLI: the --dispatch-* group, utils/config.py)."""

    coalesce: bool = True        # merge same-key requests into megabatches
    max_batch: int = 4096        # lane cap per megabatch dispatch
    max_wait_ms: float = 2.0     # admission window for async submits
    max_inflight: int = 2        # outstanding dispatches (double buffer)
    pad_batch: bool = True       # pad megabatches up the bucket ladder
    bucket_growth: float = 2.0   # geometric ladder growth factor
    compile_guard: bool = False  # raise on a warm-bucket recompile
    # -- fault domain (ISSUE 9; docs/dispatch.md failure semantics) ------
    dispatch_timeout_s: float | None = None  # per-attempt solve timeout
    retry_max: int = 2           # retries per request set before bisecting
    retry_backoff_s: float = 0.05  # base backoff, doubled per retry
    deadline_s: float | None = None  # default per-ticket deadline


class SolveFailed(RuntimeError):
    """Typed terminal outcome of a failed solve request — what a
    `solve_mip`/`result()` caller observes instead of a hang
    (docs/dispatch.md failure-semantics table).

    reason: 'deadline'         ticket deadline / result(timeout) expired
            'timeout'          every dispatch attempt hit
                               dispatch_timeout_s (retries exhausted)
            'exception'        every dispatch attempt raised (retries
                               exhausted; `detail` holds the last error)
            'dispatcher-died'  the dispatcher daemon died with this
                               request queued (fail fast, not forever)
    attempts counts the solve attempts this request rode in (0 for
    deadline/dispatcher failures); lanes is the request's batch size —
    the quarantine accounting unit."""

    def __init__(self, reason: str, detail: str = "", attempts: int = 0,
                 lanes: int = 0):
        self.reason = reason
        self.detail = detail
        self.attempts = attempts
        self.lanes = lanes
        super().__init__(
            f"solve failed ({reason}"
            + (f" after {attempts} attempt(s)" if attempts else "")
            + (f"): {detail}" if detail else ")"))


class _DispatchTimeout(RuntimeError):
    """Internal: one dispatch attempt exceeded dispatch_timeout_s."""


class SolveTicket:
    """Future for one submitted solve; result() blocks (and, when the
    owning window is still open, dispatches it — inline on the caller's
    thread for unbounded waits, via the dispatcher daemon when a
    deadline/timeout bounds the wait so the caller can never be pinned
    inside a hung solve)."""

    def __init__(self, scheduler, window, lanes: int = 0,
                 deadline: float | None = None, sid: int = -1):
        self._scheduler = scheduler
        self._window = window
        self._event = threading.Event()
        self._result = None
        self._exc = None
        self._lanes = lanes
        self._deadline = deadline     # absolute perf_counter stamp
        self.sid = sid                # scheduler-assigned submit id
                                      # (joins quarantine events and
                                      # FaultPlan dispatch seams)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """Block for the result.  A LIVE ticket deadline and `timeout`
        (seconds) each bound the wait — past the earlier one this
        raises SolveFailed('deadline'): a ticket can never hang its
        caller.  After the deadline has expired, a bare result() keeps
        raising, but an EXPLICIT timeout grants a fresh recovery wait
        (the solve may still land late) and a call after it lands
        returns the result.  A quarantined/failed request raises its
        SolveFailed."""
        if not self._event.is_set():
            now = time.perf_counter()
            expired = self._deadline is not None \
                and self._deadline <= now
            if expired and timeout is None:
                raise SolveFailed(
                    "deadline", lanes=self._lanes,
                    detail="ticket deadline expired with the solve "
                           "still outstanding")
            bound = None if timeout is None else now + timeout
            if self._deadline is not None and not expired:
                bound = self._deadline if bound is None \
                    else min(bound, self._deadline)
            if bound is None:
                self._scheduler._drive(self._window, cause="inline")
                self._event.wait()
            else:
                # bounded wait: hand the window to the dispatcher (a
                # caller driving inline would sit inside solve_fn past
                # its own deadline) and wait out the bound
                self._scheduler._expedite(self._window)
                if not self._event.wait(
                        max(0.0, bound - time.perf_counter())):
                    raise SolveFailed(
                        "deadline", lanes=self._lanes,
                        detail="ticket deadline/timeout expired with "
                               "the solve still outstanding")
        if self._exc is not None:
            raise self._exc
        return self._result


class PlaneTicket:
    """Fire-and-forget future over one async XLA plane dispatch — the
    async wheel's exchange tickets (ISSUE 11; docs/async_wheel.md).

    Unlike SolveTicket there is no queue to drive: XLA dispatch is
    already asynchronous, so the dispatch ran inline at submit_plane
    and `value` holds the (future-valued) device arrays immediately —
    the caller threads them into later dispatches without waiting.
    The ticket exists for the PR-8 failure semantics: result(timeout=)
    is a BOUNDED readiness wait — past the earlier of the ticket
    deadline and the explicit timeout it raises SolveFailed('deadline')
    instead of pinning the caller inside a wedged device queue.  The
    abandoned waiter thread keeps blocking until XLA returns (the same
    'wait out the budget, then surface a typed failure' contract the
    dispatch timeout documents; docs/dispatch.md)."""

    def __init__(self, scheduler, value, label: str = "plane",
                 deadline: float | None = None):
        self._scheduler = scheduler
        self.value = value
        self.label = label
        self._deadline = deadline     # absolute perf_counter stamp

    def done(self) -> bool:
        """Best-effort readiness probe (no blocking)."""
        leaves = jax.tree_util.tree_leaves(self.value)
        try:
            return all(bool(x.is_ready()) for x in leaves
                       if hasattr(x, "is_ready"))
        except RuntimeError:
            # an errored/deleted buffer: nothing left to WAIT on —
            # result()'s landing check types the failure
            return True

    def _landed(self):
        """The one observation point for a ready value: a dispatch
        whose async computation ERRORED (or whose buffers died) must
        surface here as a typed SolveFailed — never be handed back as
        success to poison an arbitrary later use of the plane."""
        try:
            jax.block_until_ready(self.value)
        except Exception as e:
            raise SolveFailed(
                "exception",
                detail=f"plane ticket {self.label!r} dispatch "
                       f"failed: {e!r}") from e
        return self.value

    def result(self, timeout: float | None = None):
        """Block until the dispatched arrays are ready, bounded by the
        earlier of the LIVE ticket deadline and `timeout` — expiry
        raises SolveFailed('deadline') (and counts a plane deadline
        miss).  After the deadline has expired, a bare result() keeps
        raising (unless the arrays already landed), but an EXPLICIT
        timeout grants a fresh recovery wait — exactly SolveTicket's
        expired-deadline semantics, so a slow iteration can never
        convert a healthy exchange into a spurious miss."""
        now = time.perf_counter()
        expired = self._deadline is not None and self._deadline <= now
        bound = None if timeout is None else now + float(timeout)
        if self._deadline is not None and not expired:
            bound = self._deadline if bound is None \
                else min(bound, self._deadline)
        if bound is None and not expired:
            return self._landed()
        if self.done():
            # fast path: the dispatch landed a full iteration ago in
            # the steady state — no waiter thread, no handshake
            return self._landed()
        if bound is None:
            # expired deadline, no explicit timeout, not ready
            self._scheduler._note_plane_miss(self.label)
            raise SolveFailed(
                "deadline",
                detail=f"plane ticket {self.label!r} deadline expired "
                       f"with the dispatch still outstanding")
        done = threading.Event()
        err: list = []

        def waiter():
            try:
                jax.block_until_ready(self.value)
            except Exception as e:   # typed below, on the caller thread
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=waiter, daemon=True,
                             name="mpisppy-tpu-plane-wait")
        t.start()
        if not done.wait(max(0.0, bound - time.perf_counter())):
            # expired bound: one readiness re-check before declaring a
            # miss — a result that LANDED before the caller got here
            # must never read as a wedged exchange (the SolveTicket
            # expired-deadline recovery semantics, PR-8; with an
            # already-past deadline the 0 ms wait above loses the race
            # against the just-started waiter thread every time)
            if not self.done():
                self._scheduler._note_plane_miss(self.label)
                raise SolveFailed(
                    "deadline",
                    detail=f"plane ticket {self.label!r} still not "
                           f"ready at its deadline (wedged exchange)")
            return self._landed()
        if err:
            raise SolveFailed(
                "exception",
                detail=f"plane ticket {self.label!r} dispatch "
                       f"failed: {err[0]!r}") from err[0]
        return self.value


class _Window:
    """One open coalescing window for a key: requests accumulate until
    the window is claimed by a dispatching thread and frozen."""

    __slots__ = ("key", "reqs", "tickets", "t0", "claimed", "frozen",
                 "due", "cause")

    def __init__(self, key):
        self.key = key
        # (qp, d_col, int_cols, opts, kwargs, sid, ctx) per request —
        # ctx is the submitting thread's DispatchContext token
        self.reqs: list = []
        self.tickets: list = []
        self.t0 = time.perf_counter()
        self.claimed = False
        self.frozen = False
        self.due = False          # a bounded result() wait expedites
        self.cause = "timer"      # why the window dispatched (stats)


class SolveScheduler:
    """See the module header.  `solve_fn` is injectable for tests (a
    synthetic storm needs to observe concurrency without paying for
    real branch-and-bound); the default is ops.bnb.solve_mip."""

    def __init__(self, options: DispatchOptions = DispatchOptions(),
                 solve_fn=None, bus=None, run: str = "",
                 fault_plan=None):
        if solve_fn is None:
            from mpisppy_tpu.ops import bnb as _bnb
            solve_fn = _bnb.solve_mip
        self.options = options
        self.solve_fn = solve_fn
        self.bus = bus
        self.run = run
        # chaos seams (resilience/faults.DispatchFault; armed by tests
        # and by the hub when its options carry a fault_plan) — host
        # dispatch path only, zero jit-graph impact
        self.fault_plan = fault_plan
        self.ladder = _buckets.BucketLadder(options.bucket_growth)
        # Lock discipline is lint-enforced (tools/graftlint
        # lock-discipline pass, docs/static_analysis.md): every field
        # below annotated `# guarded-by: _lock` may only be touched
        # inside `with self._lock` (or `with self._wake` — a Condition
        # over the same lock), or in a method marked
        # `# holds-lock: _lock` whose caller holds it.  Deliberately
        # UNannotated shared state: `options` (immutable dataclass,
        # swapped atomically under the lock by degrade(); bare reads
        # see either complete value), the sync primitives themselves,
        # and init-frozen handles (ladder/_watch/solve_fn/bus/run).
        self._lock = threading.Lock()
        self._sem = threading.Semaphore(max(1, options.max_inflight))
        self._pending: dict = {}          # guarded-by: _lock
        self._watch = _cw.CompileWatch()
        self._dispatcher = None           # guarded-by: _lock
        self._wake = threading.Condition(self._lock)
        self._closed = False              # guarded-by: _lock
        self._degraded = False            # guarded-by: _lock
        self._next_sid = 0                # guarded-by: _lock
        self._attempts = 0                # guarded-by: _lock
        # -- stats (all also mirrored into the metrics REGISTRY) ----------
        self._buckets: dict = {}          # guarded-by: _lock
        self._inflight = 0                # guarded-by: _lock
        self._inflight_max = 0            # guarded-by: _lock
        self._batches = 0                 # guarded-by: _lock
        self._lanes = 0                   # guarded-by: _lock
        self._pad_lanes = 0               # guarded-by: _lock
        self._coalesced_lanes = 0         # guarded-by: _lock
        self._unexpected_recompiles = 0   # guarded-by: _lock
        self._dispatch_compiles = 0       # guarded-by: _lock
        self._retries = 0                 # guarded-by: _lock
        self._quarantined_lanes = 0       # guarded-by: _lock
        self._quarantined_requests = 0    # guarded-by: _lock
        self._dispatcher_deaths = 0       # guarded-by: _lock
        # async-wheel exchange tickets (ISSUE 11): counted here, missed
        # deadlines noted from whichever thread timed the wait out
        self._plane_tickets = 0           # guarded-by: _lock
        self._plane_deadline_misses = 0   # guarded-by: _lock
        # why windows dispatched: timer (admission deadline expiry),
        # size (max_batch reached), inline (a caller's unbounded
        # result()), expedite (a deadline-bounded result()), overflow
        # (displaced by the lane cap), close (scheduler flush) — the
        # stats() split that lets the analyzer attribute occupancy loss
        # to admission timeouts vs size-forced dispatch (ISSUE 9
        # satellite)
        self._by_cause: dict = {}         # guarded-by: _lock
        # per-coalesce-key occupancy breakdown (ISSUE 12 satellite):
        # which mergeable identities actually shared megabatches, and
        # how many distinct sessions rode each one — the attribution
        # behind cross-session megabatch sharing in `telemetry
        # analyze`'s dispatch audit (docs/serving.md)
        self._by_key: dict = {}           # guarded-by: _lock

    # -- public API -------------------------------------------------------
    def solve_mip(self, qp, d_col, int_cols, opts=None, **kwargs):
        """Synchronous solve through the scheduler: bucket-padded, and
        coalesced with whatever compatible requests are already queued
        (a lone caller dispatches immediately — the admission timer
        only ever delays fire-and-forget submits)."""
        return self.submit(qp, d_col, int_cols, opts, **kwargs).result()

    def submit(self, qp, d_col, int_cols, opts=None,
               deadline_s: float | None = None, **kwargs) -> SolveTicket:
        """Enqueue one solve; returns a ticket.  Same-key submits
        coalesce into one megabatch dispatch.  The caller may submit
        many and then collect results — the first result() call drives
        the (single, coalesced) dispatch.  `deadline_s` (default:
        options.deadline_s) bounds how long result() may ever block on
        this ticket; expiry raises SolveFailed('deadline')."""
        if opts is None:
            from mpisppy_tpu.ops.bnb import BnBOptions
            opts = BnBOptions()
        S = int(qp.c.shape[0])
        key = self._request_key(qp, d_col, int_cols, opts, kwargs)
        if deadline_s is None:
            deadline_s = self.options.deadline_s
        deadline = None if deadline_s is None \
            else time.perf_counter() + float(deadline_s)
        overflow = None
        with self._lock:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            win = self._pending.get(key)
            lanes = sum(r[0].c.shape[0] for r in win.reqs) if win else 0
            if (win is None or win.frozen
                    or not self.options.coalesce
                    or lanes + S > self.options.max_batch):
                # a frozen predecessor is already owned by a dispatching
                # thread; an OPEN one displaced by the lane cap would be
                # orphaned (the dispatcher only scans _pending) — this
                # thread dispatches it below, after the lock drops
                if win is not None and not win.frozen \
                        and not win.claimed:
                    overflow = win
                win = _Window(key)
                self._pending[key] = win
            sid = self._next_sid
            self._next_sid += 1
            ticket = SolveTicket(self, win, lanes=S, deadline=deadline,
                                 sid=sid)
            win.reqs.append((qp, d_col, int_cols, opts, kwargs, sid,
                             current_context()))
            win.tickets.append(ticket)
            full = (sum(r[0].c.shape[0] for r in win.reqs)
                    >= self.options.max_batch)
            if not full:
                # the admission-timer daemon covers fire-and-forget
                # submits whether or not coalescing is on
                self._ensure_dispatcher()
            self._wake.notify_all()
        # full/overflow windows normally dispatch on THIS thread (the
        # submitting threads are what provide dispatch concurrency up
        # to max_inflight) — but a deadline-carrying submit with NO
        # dispatch timeout would then sit inside an unbounded solve_fn
        # before ever reaching result(), wedging past its own deadline;
        # in that mode hand the window to the dispatcher instead
        inline_ok = deadline is None \
            or self.options.dispatch_timeout_s is not None
        if overflow is not None:
            if inline_ok:
                self._drive(overflow, cause="overflow")
            else:
                self._expedite(overflow)
        if full:
            if inline_ok:
                self._drive(win, cause="size")
            else:
                self._expedite(win)
        return ticket

    def submit_plane(self, fn, *args, label: str = "plane",
                     deadline_s: float | None = None,
                     **kwargs) -> PlaneTicket:
        """Fire-and-forget ticket over one async XLA plane dispatch
        (the async wheel's exchange programs; ISSUE 11).  `fn` is
        called INLINE — XLA dispatch is already asynchronous, so this
        returns immediately with the future-valued arrays in
        ticket.value; `deadline_s` bounds any later result() wait with
        the PR-8 typed-failure semantics."""
        value = fn(*args, **kwargs)
        deadline = None if deadline_s is None \
            else time.perf_counter() + float(deadline_s)
        with self._lock:
            self._plane_tickets += 1
        _metrics.REGISTRY.inc("dispatch_plane_tickets_total")
        return PlaneTicket(self, value, label=label, deadline=deadline)

    def _note_plane_miss(self, label: str) -> None:
        """A plane ticket's bounded wait expired (PlaneTicket.result —
        may run on any caller thread)."""
        with self._lock:
            self._plane_deadline_misses += 1
        _metrics.REGISTRY.inc("dispatch_plane_deadline_misses_total")
        self._emit_event("watchdog", component="exchange",
                         action="deadline", label=label)

    def stats(self) -> dict:
        """Point-in-time snapshot for bench artifacts and the hub's
        per-sync telemetry (docs/dispatch.md field table)."""
        with self._lock:
            lanes = max(1, self._lanes + self._pad_lanes)
            return {
                "batches": self._batches,
                "lanes": self._lanes,
                "pad_lanes": self._pad_lanes,
                "coalesced_lanes": self._coalesced_lanes,
                "occupancy": self._lanes / lanes,
                "buckets": len(self._buckets),
                # compiles observed WHILE a dispatch executed — the
                # dispatch-attributable count (other threads' compiles
                # can land in the window; see _solve_merged's caveat).
                # The raw process total is CompileWatch.total().
                "backend_compiles": self._dispatch_compiles,
                "unexpected_recompiles": self._unexpected_recompiles,
                "inflight_max": self._inflight_max,
                "queue_depth": sum(len(w.reqs)
                                   for w in self._pending.values()),
                # -- fault domain (ISSUE 9) -------------------------------
                "retries_total": self._retries,
                "quarantined_lanes": self._quarantined_lanes,
                "quarantined_requests": self._quarantined_requests,
                "dispatcher_deaths": self._dispatcher_deaths,
                "plane_tickets": self._plane_tickets,
                "plane_deadline_misses": self._plane_deadline_misses,
                "degraded": self._degraded,
                # why windows dispatched (timer = admission deadline
                # expiry, size = lane cap, inline/expedite = a blocking
                # caller, overflow, close) — the occupancy-attribution
                # split (a timer-heavy mix under load means the window
                # never fills before its admission deadline)
                "by_cause": dict(self._by_cause),
                # per-coalesce-key occupancy: which mergeable
                # identities shared megabatches, across how many
                # distinct sessions (ISSUE 12 satellite)
                "by_key": {
                    label: {
                        "batches": a["batches"],
                        "lanes": a["lanes"],
                        "pad_lanes": a["pad_lanes"],
                        "coalesced_lanes": a["coalesced_lanes"],
                        "occupancy": round(
                            a["lanes"] / max(1, a["lanes"]
                                             + a["pad_lanes"]), 4),
                        "sessions": len(a["runs"]),
                    } for label, a in self._by_key.items()},
            }

    def degrade(self) -> None:
        """Watchdog action (resilience/watchdog.py): drop to direct,
        un-coalesced dispatch — every later submit dispatches solo, the
        coalescing/admission machinery leaves the suspect path.  Shape
        padding stays on (the jit cache must stay bounded even in the
        degraded mode)."""
        with self._lock:
            self.options = dataclasses.replace(self.options,
                                               coalesce=False)
            self._degraded = True
        # observability rides the tripping watchdog's own event plus
        # the degraded flag in stats()/the hub's per-sync stats row —
        # a synthetic megabatch row here would pollute the audit

    def close(self):
        """Flush every open window and stop the dispatcher thread."""
        with self._lock:
            self._closed = True
            wins = [w for w in self._pending.values() if not w.claimed]
            self._wake.notify_all()
        for w in wins:
            self._drive(w, cause="close")
        with self._lock:
            t = self._dispatcher
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    # -- request identity -------------------------------------------------
    def _request_key(self, qp, d_col, int_cols, opts, kwargs) -> tuple:
        """Mergeable identity.  Batched per-lane fields concatenate
        freely; SHARED structure (a broadcast A, the ELL column index
        array, a ConeSpec) must be the same object across a window —
        object identity is exact for the oracle loops, which rebuild
        c/l/u per call but thread the same A through (see
        mip.lagrangian_mip_bound), and a miss only costs coalescence,
        never correctness.  Requests with kwargs never coalesce (a
        warm-start array is per-request state)."""
        A = qp.A
        if hasattr(A, "vals"):
            a_id = ("ell", id(A.cols),
                    None if A.vals.ndim == 3 else id(A.vals))
        else:
            a_id = ("dense", None if A.ndim == 3 else id(A))
        cones = getattr(qp, "cones", None)
        shared = tuple(
            None if getattr(f, "ndim", 0) == nd else id(f)
            for f, nd in ((qp.c, 2), (qp.q, 2), (qp.bl, 2), (qp.bu, 2),
                          (qp.l, 2), (qp.u, 2), (d_col, 2)))
        ints = np.asarray(int_cols)
        return (qp.n, qp.m, str(qp.c.dtype), a_id, shared,
                None if cones is None else id(cones),
                ints.shape, hash(ints.tobytes()), opts,
                ("solo", id(kwargs)) if kwargs else ())

    # -- dispatch machinery -----------------------------------------------
    def _ensure_dispatcher(self):        # holds-lock: _lock
        """Lazy daemon that fires windows whose admission timer lapsed
        (callers that block in result() drive their own windows; this
        thread only covers fire-and-forget submits).  Caller holds the
        lock."""
        if self._dispatcher is not None and self._dispatcher.is_alive():
            return
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name="mpisppy-tpu-dispatch")
        self._dispatcher.start()

    def _dispatch_loop(self):
        """Supervised daemon body: the inner loop fires due windows;
        ANY escape — including an injected dispatcher kill — fails
        every queued ticket fast (SolveFailed('dispatcher-died'))
        instead of leaving them to wait on a dead thread.  The next
        submit restarts the daemon (see _ensure_dispatcher)."""
        try:
            self._dispatch_loop_inner()
        except BaseException as e:  # noqa: BLE001 — the supervisor seam
            self._on_dispatcher_death(e)

    def _dispatch_loop_inner(self):
        wait_s = max(self.options.max_wait_ms, 0.1) / 1e3
        while True:
            plan = self.fault_plan
            if plan is not None:
                plan.maybe_kill_dispatcher()
            with self._lock:
                now = time.perf_counter()
                open_w = [w for w in self._pending.values()
                          if not w.claimed]
                due = [w for w in open_w
                       if w.due or now - w.t0 >= wait_s]
                if not due:
                    if self._closed:
                        return
                    if open_w:
                        # sleep exactly to the earliest admission
                        # deadline
                        deadline = min(w.t0 + wait_s for w in open_w)
                        self._wake.wait(timeout=max(deadline - now,
                                                    1e-4))
                    else:
                        # idle: block until a submit (or close)
                        # notifies — no polling
                        self._wake.wait()
                    continue
            for w in due:
                self._drive(w, cause="expedite" if w.due else "timer")

    def _on_dispatcher_death(self, exc: BaseException):
        """Fail-fast fan-out for a dead dispatcher thread: every ticket
        still queued in an unclaimed window resolves with a typed
        SolveFailed NOW (never a hang), the queue empties, and a
        watchdog event records the death."""
        with self._lock:
            wins = [w for w in self._pending.values() if not w.claimed]
            for w in wins:
                w.claimed = True
                w.frozen = True
            self._pending = {}
            self._dispatcher_deaths += 1
        failed = 0
        for w in wins:
            for t in w.tickets:
                if not t.done():
                    t._exc = SolveFailed(
                        "dispatcher-died", lanes=t._lanes,
                        detail=f"{type(exc).__name__}: {exc}")
                    t._event.set()
                    failed += 1
        _metrics.REGISTRY.inc("dispatch_dispatcher_deaths_total")
        self._emit_event(
            "watchdog", component="dispatcher", action="fail-fast",
            failed_tickets=failed,
            error=f"{type(exc).__name__}: {exc}")

    def _expedite(self, win: _Window):
        """A deadline-bounded result() wait: mark the window due and
        wake the dispatcher so it fires without the caller having to
        sit inside solve_fn itself."""
        with self._lock:
            if win.claimed:
                return
            win.due = True
            self._ensure_dispatcher()
            self._wake.notify_all()

    def _drive(self, win: _Window, cause: str = "inline"):
        """Claim-and-run a window; loses the race gracefully when
        another thread (or the dispatcher) got there first."""
        with self._lock:
            if win.claimed:
                return
            win.claimed = True
            win.cause = cause
        try:
            self._run_window(win)
        except BaseException as e:  # noqa: BLE001 — fanned out below
            with self._lock:
                win.frozen = True
                if self._pending.get(win.key) is win:
                    del self._pending[win.key]
            for t in win.tickets:
                if not t.done():
                    t._exc = e
                    t._event.set()
            raise

    def _run_window(self, win: _Window):
        # backpressure FIRST: while this thread queues on the in-flight
        # semaphore the window is still open, so a storm accumulates
        # into occupancy rather than tunnel depth
        self._sem.acquire()
        try:
            with self._lock:
                win.frozen = True
                if self._pending.get(win.key) is win:
                    del self._pending[win.key]
                reqs = list(win.reqs)
                tickets = list(win.tickets)
                self._inflight += 1
                self._inflight_max = max(self._inflight_max,
                                         self._inflight)
                _metrics.REGISTRY.set_gauge("dispatch_inflight",
                                            self._inflight)
            t_launch = time.perf_counter()
            self._solve_recover(win, reqs, tickets, t_launch)
        finally:
            with self._lock:
                self._inflight -= 1
                _metrics.REGISTRY.set_gauge("dispatch_inflight",
                                            self._inflight)
            self._sem.release()

    def _solve_recover(self, win: _Window, reqs, tickets,
                       t_launch: float, bisected: bool = False):
        """The fault-domain driver (ISSUE 9): solve this request set
        with retry + exponential backoff; a set still failing after its
        budget BISECTS into request halves (each with a fresh budget —
        recursion depth is log2(requests), total attempts bounded by
        (retry_max+1) * (2*requests - 1)); a single request that still
        fails is QUARANTINED — its ticket resolves with a typed
        SolveFailed and every healthy sibling proceeds.  Non-retryable
        escapes (preemption, the compile guard's AssertionError,
        KeyboardInterrupt/SystemExit) propagate immediately to _drive's
        fan-out."""
        from mpisppy_tpu.resilience.faults import PreemptionError
        last: BaseException | None = None
        attempts = 0
        for attempt in range(max(0, self.options.retry_max) + 1):
            if attempt:
                backoff = self.options.retry_backoff_s * (2 ** (attempt - 1))
                self._retry_note(win, reqs, attempt, last, backoff)
                time.sleep(backoff)
            attempts += 1
            try:
                res, sizes, S_pad, sig = self._solve_merged(reqs)
            except (PreemptionError, AssertionError):
                raise          # must stay loud: shutdown / compile guard
            except Exception as e:  # noqa: BLE001 — the retryable class
                last = e
                continue
            self._deliver(win, reqs, tickets, res, sizes)
            self._record(win, reqs, sizes, S_pad, sig, t_launch)
            return
        if len(reqs) > 1:
            # the poison is somewhere in this set: isolate by
            # lane-balanced halves (buckets.balanced_split)
            mid = _buckets.balanced_split(
                [int(r[0].c.shape[0]) for r in reqs])
            self._solve_recover(win, reqs[:mid], tickets[:mid],
                                t_launch, bisected=True)
            self._solve_recover(win, reqs[mid:], tickets[mid:],
                                t_launch, bisected=True)
            return
        self._quarantine(win, reqs[0], tickets[0], attempts, last,
                         bisected)

    def _solve_attempt(self, reqs, qp, d_col, int_cols, opts, kwargs):
        """One bounded solve attempt.  With dispatch_timeout_s set the
        solve runs on a worker thread and a hang becomes a typed
        _DispatchTimeout after the budget (the abandoned worker keeps
        the device busy until XLA returns — retry semantics on real
        hardware are therefore 'wait out the budget, then re-enqueue',
        not a device-side cancel; docs/dispatch.md).  The chaos seam
        runs INSIDE the attempt so injected hangs consume the timeout
        exactly like real ones."""
        with self._lock:      # concurrent dispatch threads share the
            idx = self._attempts          # attempt index sequence
            self._attempts += 1
        plan = self.fault_plan

        def run():
            if plan is not None:
                plan.before_dispatch(idx, [r[5] for r in reqs])
            return self.solve_fn(qp, d_col, int_cols, opts, **kwargs)

        timeout = self.options.dispatch_timeout_s
        if timeout is None:
            return run()
        box: dict = {}
        done = threading.Event()

        def worker():
            try:
                box["res"] = run()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["exc"] = e
            finally:
                done.set()

        t = threading.Thread(target=worker, daemon=True,
                             name="mpisppy-tpu-dispatch-solve")
        t.start()
        if not done.wait(float(timeout)):
            raise _DispatchTimeout(
                f"dispatch exceeded its {timeout}s timeout "
                f"(attempt {idx})")
        if "exc" in box:
            raise box["exc"]
        return box["res"]

    def _deliver(self, win: _Window, reqs, tickets, res, sizes):
        off = 0
        plan = self.fault_plan
        for t, S, r in zip(tickets, sizes, reqs):
            if plan is not None and plan.drop_ticket(r[5]):
                # injected result loss: the ticket stays unresolved and
                # its deadline converts the would-be hang into a typed
                # SolveFailed at the caller
                off += S
                continue
            # per-request slices exclude the pad lanes automatically
            # (pads sit past the last real lane)
            t._result = jax.tree_util.tree_map(
                lambda a, o=off, s=S: a[o:o + s]
                if getattr(a, "ndim", 0) >= 1 else a, res)
            t._event.set()
            off += S

    def _retry_note(self, win: _Window, reqs, attempt: int,
                    exc: BaseException | None, backoff_s: float):
        with self._lock:
            self._retries += 1
        _metrics.REGISTRY.inc("dispatch_retries_total")
        self._emit_event(
            "dispatch-retry", attempt=attempt,
            requests=len(reqs),
            lanes=sum(int(r[0].c.shape[0]) for r in reqs),
            backoff_s=backoff_s,
            error="" if exc is None else f"{type(exc).__name__}: {exc}")

    def _quarantine(self, win: _Window, req, ticket, attempts: int,
                    exc: BaseException | None, bisected: bool):
        """Terminal isolation of one poisoned request: the ticket
        resolves with SolveFailed (reason timeout/exception), the lanes
        are accounted, and the quarantine is observable."""
        lanes = int(req[0].c.shape[0])
        reason = "timeout" if isinstance(exc, _DispatchTimeout) \
            else "exception"
        detail = "" if exc is None else f"{type(exc).__name__}: {exc}"
        with self._lock:
            self._quarantined_lanes += lanes
            self._quarantined_requests += 1
        _metrics.REGISTRY.inc("dispatch_quarantined_lanes_total", lanes)
        _metrics.REGISTRY.inc("dispatch_quarantined_requests_total")
        self._emit_event(
            "dispatch-quarantine", submit=req[5], lanes=lanes,
            attempts=attempts, reason=reason, bisected=bisected,
            error=detail)
        if not ticket.done():
            ticket._exc = SolveFailed(reason, detail=detail,
                                      attempts=attempts, lanes=lanes)
            ticket._event.set()

    def _emit_event(self, kind: str, **data):
        if self.bus is None:
            return
        self.bus.emit(kind, run=self.run, cyl="dispatch",
                      hub_iter=_hub_iter, **data)

    def _solve_merged(self, reqs):
        """Concatenate the window's requests, pad up the ladder, solve.
        Returns (result, per-request sizes, padded lane count, shape
        signature)."""
        qps = [r[0] for r in reqs]
        sizes = [int(q.c.shape[0]) for q in qps]
        S_tot = sum(sizes)
        qp, d_col = self._merge(reqs) if len(reqs) > 1 \
            else (reqs[0][0], reqs[0][1])
        int_cols, opts, kwargs = reqs[0][2], reqs[0][3], reqs[0][4]
        S_pad = self.ladder.bucket(S_tot) if self.options.pad_batch \
            else S_tot
        S_pad = max(S_pad, S_tot)
        qp, d_col = _buckets.pad_qp_batch(qp, d_col, S_pad)
        if S_pad > S_tot and kwargs:
            # per-lane kwargs (x_warm/y_warm) must ride the same
            # padding or their lane count no longer matches the qp's
            kwargs = {
                k: _buckets.pad_leading_rows(v, S_tot, S_pad)
                for k, v in kwargs.items()}
        sig = _buckets.shape_signature(qp, d_col) + (opts,)
        with self._lock:
            warm = sig in self._buckets
        before = self._watch.total()
        res = self._solve_attempt(reqs, qp, d_col, int_cols, opts,
                                  kwargs)
        compiled = self._watch.total() - before
        with self._lock:
            # += on a counter from concurrent dispatch threads is a
            # lost-update race without the lock — found by the
            # lock-discipline lint when the guarded-by audit landed
            # (ISSUE 10); same for the warm-bucket read above and the
            # solo-inflight read below
            self._dispatch_compiles += compiled
            solo = self._inflight == 1
        if warm and compiled and solo:
            # ADVISORY attribution: the counter is only read with one
            # dispatch in flight, but compiles from OTHER threads (a
            # hub step compiling a wheel kernel) and legitimately
            # value-derived shapes inside a bucket (detect_sos1_groups'
            # (G, L) arrays follow A's VALUES, not its shape) can still
            # land in the window.  That is why the default only counts;
            # compile_guard is the strict dev/test mode that turns the
            # count into an assertion on workloads known to be clean.
            with self._lock:
                self._unexpected_recompiles += compiled
            _metrics.REGISTRY.inc("dispatch_unexpected_recompiles_total",
                                  compiled)
            if self.options.compile_guard:
                raise AssertionError(
                    f"compile-cache discipline violated: {compiled} "
                    f"backend compile(s) against warm bucket {sig[:3]} "
                    "(if this workload legitimately varies value-"
                    "derived kernel shapes inside a bucket, run "
                    "without --dispatch-compile-guard)")
        with self._lock:
            self._buckets[sig] = self._buckets.get(sig, 0) + 1
        return res, sizes, S_pad, sig

    def _merge(self, reqs):
        """One megabatch BoxQP from same-key requests: batched fields
        concatenate along the lane axis, shared fields (same object by
        key construction) pass through; a field shared in one request
        but batched in another broadcasts before the concat."""
        qps = [r[0] for r in reqs]
        d_cols = [r[1] for r in reqs]
        sizes = [int(q.c.shape[0]) for q in qps]

        def cat(fields, batched_ndim):
            if all(getattr(f, "ndim", 0) < batched_ndim
                   for f in fields) and \
                    all(f is fields[0] for f in fields):
                return fields[0]
            return jnp.concatenate(
                [jnp.broadcast_to(f, (s,) + f.shape[-(batched_ndim - 1):])
                 if f.ndim < batched_ndim else f
                 for f, s in zip(fields, sizes)], axis=0)

        A0 = qps[0].A
        if hasattr(A0, "vals"):
            if A0.vals.ndim == 3:
                A = dataclasses.replace(
                    A0, vals=jnp.concatenate([q.A.vals for q in qps],
                                             axis=0))
            else:
                A = A0  # shared vals/cols: key guarantees identity
        elif A0.ndim == 3:
            A = jnp.concatenate([q.A for q in qps], axis=0)
        else:
            A = A0      # shared dense A: key guarantees identity
        qp = dataclasses.replace(
            qps[0],
            c=cat([q.c for q in qps], 2), q=cat([q.q for q in qps], 2),
            A=A,
            bl=cat([q.bl for q in qps], 2), bu=cat([q.bu for q in qps], 2),
            l=cat([q.l for q in qps], 2), u=cat([q.u for q in qps], 2))
        return qp, cat(d_cols, 2)

    def _key_label(self, win: _Window) -> str:
        """Compact stable-within-a-run render of a coalesce key for the
        by_key stats breakdown: the human-meaningful shape/dtype parts
        plus a short digest separating keys that only differ in shared
        structure identity (two tenants with same-shape but different
        shared-A problems must not fold into one row)."""
        n, m, dtype = win.key[0], win.key[1], win.key[2]
        digest = abs(hash(win.key)) & 0xFFFF
        return f"n{n}m{m}:{dtype}:k{digest:04x}"

    def _session_breakdown(self, reqs, sizes) -> list[dict]:
        """Per-session (run, iter, lanes) aggregation of a megabatch's
        requests from their captured DispatchContext tokens — the exact
        join the analyzer uses for concurrent sessions."""
        agg: dict[tuple, dict] = {}
        for r, S in zip(reqs, sizes):
            ctx = r[6]
            a = agg.setdefault((ctx.run, ctx.hub_iter),
                               {"run": ctx.run, "iter": ctx.hub_iter,
                                "lanes": 0, "requests": 0})
            a["lanes"] += S
            a["requests"] += 1
            # per-trace attribution for mixed megabatches (ISSUE 20):
            # the session token carries its segment's trace/span ids
            if ctx.trace_id and "trace_id" not in a:
                a["trace_id"] = ctx.trace_id
                a["span_id"] = ctx.span_id
        return list(agg.values())

    def _record(self, win: _Window, reqs, sizes, S_pad: int, sig,
                t_launch: float):
        real = sum(sizes)
        occ = real / max(1, S_pad)
        sessions = self._session_breakdown(reqs, sizes)
        key_label = self._key_label(win)
        with self._lock:
            self._batches += 1
            self._lanes += real
            self._pad_lanes += S_pad - real
            if len(sizes) > 1:
                self._coalesced_lanes += real
            self._by_cause[win.cause] = \
                self._by_cause.get(win.cause, 0) + 1
            bk = self._by_key.setdefault(
                key_label, {"batches": 0, "lanes": 0, "pad_lanes": 0,
                            "coalesced_lanes": 0, "runs": set()})
            bk["batches"] += 1
            bk["lanes"] += real
            bk["pad_lanes"] += S_pad - real
            if len(sizes) > 1:
                bk["coalesced_lanes"] += real
            bk["runs"].update(s["run"] for s in sessions)
            queue_depth = sum(len(w.reqs) for w in self._pending.values())
            # snapshot everything the unlocked metric/event writes
            # below read — the renders must see one consistent point
            # in time (lock-discipline lint, ISSUE 10)
            n_buckets = len(self._buckets)
            dispatch_compiles = self._dispatch_compiles
            inflight_max = self._inflight_max
        R = _metrics.REGISTRY
        R.inc("dispatch_batches_total")
        R.inc("dispatch_lanes_total", real)
        R.inc("dispatch_pad_lanes_total", S_pad - real)
        R.set_gauge("dispatch_batch_occupancy", occ)
        R.set_gauge("dispatch_queue_depth", queue_depth)
        R.set_gauge("dispatch_buckets_active", n_buckets)
        R.set_counter("dispatch_backend_compiles_total",
                      dispatch_compiles)
        if self.bus is not None:
            from mpisppy_tpu import telemetry as tel
            # the megabatch's run/iter stamp: when every riding request
            # carries ONE session token, the event joins that session's
            # timeline directly; a mixed (cross-tenant) batch keeps the
            # scheduler's own run with the per-session breakdown
            # carrying the exact attribution (ISSUE 12 satellite)
            runs = {s["run"] for s in sessions}
            ev_run, ev_iter, ev_trace = self.run, _hub_iter, None
            if len(sessions) == 1 and sessions[0]["run"]:
                ev_run = sessions[0]["run"]
                ev_iter = sessions[0]["iter"]
                # single-session batch: stamp the row with that
                # session's segment span (a DispatchContext quacks
                # like a TraceContext for make_event)
                ctx0 = reqs[0][6]
                ev_trace = ctx0 if ctx0.trace_id else None
            self.bus.emit(
                tel.DISPATCH, run=ev_run, cyl="dispatch",
                hub_iter=ev_iter, trace=ev_trace,
                requests=len(sizes), lanes=real, padded_to=S_pad,
                occupancy=occ, bucket=list(sig[:3]), key=key_label,
                wait_ms=1e3 * (t_launch - win.t0),
                queue_depth=queue_depth, cause=win.cause,
                inflight_max=inflight_max,
                **({"sessions": sessions}
                   if any(s["run"] for s in sessions)
                   and (len(runs) > 1 or runs != {self.run}) else {}))


# -- the process-default scheduler (prometheus_client-style global) ---------
_default_lock = threading.Lock()
_default: SolveScheduler | None = None


def get_scheduler(create: bool = True) -> SolveScheduler | None:
    """The process-default scheduler every library call site routes
    through; created lazily with default options on first use."""
    global _default
    with _default_lock:
        if _default is None and create:
            _default = SolveScheduler()
        return _default


def configure(options: DispatchOptions | None = None, bus=None,
              run: str = "") -> SolveScheduler:
    """(Re)build the process-default scheduler — the CLI wiring seam
    (generic_cylinders calls this off the --dispatch-* group).  Any
    previous default is flushed first."""
    global _default
    with _default_lock:
        old, _default = _default, None
    if old is not None:
        old.close()
    # a fresh scheduler means a fresh run: drop the previous wheel's
    # final hub-iteration stamp (and the calling thread's stale session
    # token) or the new run's warm-up dispatches would join a bogus old
    # iteration instead of reading pre-wheel
    clear_session_context()
    set_hub_iter(-1)
    sched = SolveScheduler(options or DispatchOptions(), bus=bus, run=run)
    with _default_lock:
        _default = sched
    return sched


def from_cfg(cfg, bus=None, run: str = "") -> SolveScheduler:
    """Build + install the default scheduler from the dispatch_args
    Config group (utils/config.py)."""
    timeout = cfg.get("dispatch_timeout_s")
    deadline = cfg.get("dispatch_deadline_s")
    return configure(DispatchOptions(
        coalesce=bool(cfg.get("dispatch_coalesce", True)),
        max_batch=int(cfg.get("dispatch_max_batch", 4096)),
        max_wait_ms=float(cfg.get("dispatch_max_wait_ms", 2.0)),
        max_inflight=int(cfg.get("dispatch_max_inflight", 2)),
        pad_batch=bool(cfg.get("dispatch_pad", True)),
        bucket_growth=float(cfg.get("dispatch_bucket_growth", 2.0)),
        compile_guard=bool(cfg.get("dispatch_compile_guard", False)),
        dispatch_timeout_s=None if timeout is None else float(timeout),
        retry_max=int(cfg.get("dispatch_retry_max", 2)),
        retry_backoff_s=float(cfg.get("dispatch_retry_backoff_s", 0.05)),
        deadline_s=None if deadline is None else float(deadline),
    ), bus=bus, run=run)


def solve_mip(qp, d_col, int_cols, opts=None, **kwargs):
    """Module-level convenience: one solve through the process-default
    scheduler (the drop-in for ops.bnb.solve_mip at every oracle call
    site — algos/mip.py routes here)."""
    return get_scheduler().solve_mip(qp, d_col, int_cols, opts, **kwargs)


def scheduler_stats() -> dict | None:
    """stats() of the default scheduler, None when none exists yet —
    bench.py embeds this in its artifact entries."""
    sched = get_scheduler(create=False)
    return None if sched is None else sched.stats()
