###############################################################################
# Shape buckets: the geometric ladder + batch-axis padding.
#
# Every jitted kernel under ops/bnb.py and ops/pdhg.py specializes on
# the array shapes it is traced with, so a host loop that feeds the
# device (batch, n, m) triples drawn from a continuum — K*S candidate
# tilings, k_ws wait-and-see slices, tail-rescue gathers — compiles one
# executable per distinct triple: a silent recompile storm.  The ladder
# quantizes the BATCH axis to a small geometric set of rungs; (n, m)
# stay exact (they are fixed per model family within a run — padding
# columns/rows would perturb the solve itself).  The number of live
# executables per kernel is then bounded by
#     #rungs touched  x  #(n, m) families  x  #option sets,
# and tests/test_dispatch.py asserts exactly that with a compile
# counter (compilewatch.py).
#
# Padding contract — THE invariant everything downstream leans on: pad
# lanes are copies of lane 0, and every per-lane computation in the
# bnb/pdhg stack is independent and deterministic, so a pad lane
# reproduces lane 0's trajectory and host-side control flow over the
# whole batch (np.all(done), fixed-count stalls, cycle detection) sees
# the same truth values padded or not.  In exact arithmetic the
# sliced-back result would be bit-identical to the unpadded solve; in
# practice XLA lowers different batch shapes to different (equally
# valid) instruction schedules, so values match at the ulp level per
# op — which the B&B's value-driven host heuristics can amplify into
# small, still-certified value differences (measured ~1e-5 relative on
# random MIPs; tests/test_dispatch.py pins the band).  Two things are
# exact either way: every reported bound keeps its certificate, and
# BnBOptions.jitter > 0 additionally draws shape-keyed randoms (padded
# solves then take different — equally valid — tie-breaks).  Padded
# lanes do cost device FLOPs; the ladder keeps that waste under the
# growth factor.
###############################################################################
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

Array = jax.Array


class BucketLadder:
    """Geometric batch-size rungs: 1, ceil(g), ceil(g^2), ... (strictly
    increasing; growth g < 2 still steps by at least 1)."""

    def __init__(self, growth: float = 2.0, min_bucket: int = 1):
        if growth <= 1.0:
            raise ValueError(f"bucket growth must exceed 1 ({growth})")
        self.growth = float(growth)
        self.min_bucket = max(1, int(min_bucket))

    def rungs(self, up_to: int):
        """All rungs <= max(up_to, first rung), ascending."""
        out = [self.min_bucket]
        while out[-1] < up_to:
            out.append(max(out[-1] + 1, int(-(-out[-1] * self.growth
                                              // 1))))
        return out

    def bucket(self, size: int) -> int:
        """Smallest rung >= size (the padding target)."""
        if size <= 0:
            raise ValueError(f"bucket size must be positive ({size})")
        r = self.min_bucket
        while r < size:
            r = max(r + 1, int(-(-r * self.growth // 1)))
        return r

    def bucket_floor(self, size: int) -> int:
        """Largest rung <= size (for sub-batch gathers that must not
        exceed the source batch)."""
        if size <= 0:
            raise ValueError(f"bucket size must be positive ({size})")
        r = prev = self.min_bucket
        while r <= size:
            prev = r
            r = max(r + 1, int(-(-r * self.growth // 1)))
        return prev


_DEFAULT_LADDER = BucketLadder()


def default_ladder() -> BucketLadder:
    return _DEFAULT_LADDER


def _pad_leading(x, batched_ndim: int, pad: int):
    """Append `pad` copies of row 0 along the leading axis of a field
    whose batched rank is `batched_ndim`; shared (lower-rank) fields
    pass through untouched."""
    if getattr(x, "ndim", 0) != batched_ndim:
        return x
    rep = jnp.broadcast_to(x[:1], (pad,) + x.shape[1:])
    return jnp.concatenate([x, rep], axis=0)


def pad_qp_batch(qp, d_col, S_to: int):
    """Pad a batched BoxQP (and its column scaling) to S_to lanes with
    copies of lane 0 (see the padding contract in the module header).
    Returns (qp_padded, d_col_padded); a no-op when already at S_to."""
    S = qp.c.shape[0]
    if S_to < S:
        raise ValueError(f"cannot pad {S} lanes down to {S_to}")
    if S_to == S:
        return qp, d_col
    pad = S_to - S
    A = qp.A
    if hasattr(A, "vals"):  # EllMatrix: only a batched vals pads
        if A.vals.ndim == 3:
            A = dataclasses.replace(A, vals=_pad_leading(A.vals, 3, pad))
    else:
        A = _pad_leading(A, 3, pad)
    qp2 = dataclasses.replace(
        qp,
        c=_pad_leading(qp.c, 2, pad), q=_pad_leading(qp.q, 2, pad),
        A=A,
        bl=_pad_leading(qp.bl, 2, pad), bu=_pad_leading(qp.bu, 2, pad),
        l=_pad_leading(qp.l, 2, pad), u=_pad_leading(qp.u, 2, pad))
    return qp2, _pad_leading(d_col, 2, pad)


def pad_leading_rows(v, S: int, S_to: int):
    """Pad an auxiliary per-lane array (warm starts etc.) from S to
    S_to lanes with copies of row 0; non-arrays and arrays without an
    S-long leading axis pass through untouched."""
    if getattr(v, "ndim", 0) >= 1 and v.shape[0] == S:
        rep = jnp.broadcast_to(v[:1], (S_to - S,) + v.shape[1:])
        return jnp.concatenate([jnp.asarray(v), rep], axis=0)
    return v


def slice_result(res, S: int):
    """Strip the pad lanes off a result pytree: every leaf with a
    leading batch axis longer than S is cut back to its first S rows
    (BnBResult fields are all (S_pad, ...), scalars pass through)."""
    return jax.tree_util.tree_map(
        lambda a: a[:S] if (getattr(a, "ndim", 0) >= 1
                            and a.shape[0] > S) else a, res)


def balanced_split(sizes) -> int:
    """Bisection point for a failing megabatch's request list
    (scheduler._solve_recover): the request index that best halves the
    LANE count, clamped to keep both halves non-empty.  Splitting by
    lanes (not request count) keeps the bisection's isolation depth
    log2(lanes-weighted) when one request dwarfs the rest — and both
    halves land closer to a shared ladder rung."""
    sizes = list(sizes)
    if len(sizes) < 2:
        raise ValueError("need at least two requests to split")
    half = sum(sizes) / 2.0
    acc, best_mid, best_err = 0, 1, float("inf")
    for i, s in enumerate(sizes[:-1]):
        acc += s
        err = abs(acc - half)
        if err < best_err:
            best_err, best_mid = err, i + 1
    return best_mid


def shape_signature(qp, d_col) -> tuple:
    """The registry key of a dispatch's DEVICE-FACING shape: batch
    rung, (n, m), dtype, the A storage kind, and which fields carry a
    batch axis (shared-vs-batched changes the traced program)."""
    A = qp.A
    if hasattr(A, "vals"):
        akind = ("ell", A.k, A.vals.ndim)
    else:
        akind = ("dense", A.ndim)
    batched = tuple(getattr(f, "ndim", 0)
                    for f in (qp.c, qp.q, qp.bl, qp.bu, qp.l, qp.u,
                              d_col))
    return (qp.c.shape[0], qp.n, qp.m, str(qp.c.dtype), akind, batched)
