###############################################################################
# Generic driver CLI — the flagship entry point
# (ref:mpisppy/generic_cylinders.py:32-312,396-457):
#
#   python -m mpisppy_tpu --module-name mpisppy_tpu.models.farmer \
#          --num-scens 3 --default-rho 1.0 --lagrangian --xhatxbar \
#          --rel-gap 0.01 [--EF] [--solution-base-name out]
#
# The model module supplies the reference's 5-function API
# (ref:mpisppy/generic_cylinders.py:43-52): scenario_creator,
# scenario_names_creator, inparser_adder, kw_creator,
# scenario_denouement — returning ScenarioSpec instead of Pyomo models.
# Multistage modules additionally provide make_tree(branching_factors).
###############################################################################
from __future__ import annotations

import importlib
import json
import sys

from mpisppy_tpu import global_toc, telemetry
from mpisppy_tpu.core import batch as batch_mod
from mpisppy_tpu.resilience.faults import PreemptionError
from mpisppy_tpu.spin_the_wheel import WheelSpinner
from mpisppy_tpu.utils import cfg_vanilla as vanilla
from mpisppy_tpu.utils.config import Config


def _parse_args(module, args=None):
    """ref:generic_cylinders.py:32-80."""
    cfg = Config()
    cfg.add_to_config("module_name", "model module to import", str, None)
    cfg.add_to_config("EF", "solve the extensive form directly", bool,
                      False)
    cfg.add_to_config("solution_base_name",
                      "write first-stage solution files with this base",
                      str, None)
    # the model module declares its flags FIRST: add_to_config ignores
    # re-declaration, so a module's defaults (e.g. hydro's
    # branching_factors=[3,3]) win over the canned groups' None defaults
    module.inparser_adder(cfg)
    cfg.num_scens_optional()
    cfg.popular_args()
    cfg.ph_args()
    cfg.aph_args()
    cfg.two_sided_args()
    cfg.fwph_args()
    cfg.lagrangian_args()
    cfg.lagranger_args()
    cfg.subgradient_args()
    cfg.xhatxbar_args()
    cfg.fused_wheel_args()
    cfg.xhatshuffle_args()
    cfg.slama_args()
    cfg.gradient_args()
    cfg.dynamic_rho_args()
    cfg.reduced_costs_args()
    cfg.ph_ob_args()
    cfg.cross_scenario_cuts_args()
    cfg.lshaped_args()
    cfg.converger_args()
    cfg.presolve_args()
    cfg.resilience_args()
    cfg.telemetry_args()
    cfg.dispatch_args()
    cfg.wxbar_read_write_args()
    cfg.proper_bundle_config()
    cfg.multistage()
    cfg.parse_command_line("mpisppy_tpu.generic_cylinders", args)
    cfg.checker()
    return cfg


def _model_plumbing(cfg, module):
    """Names, creator kwargs, and tree — the scenario count may come
    from --num-scens, the instance (e.g. sslp_15_45_10), or the
    branching factors (multistage)."""
    num_scens = cfg.get("num_scens")
    kwargs = module.kw_creator(cfg)
    if num_scens is None:
        num_scens = kwargs.get("num_scens")
    if num_scens is None and cfg.get("branching_factors"):
        import math
        num_scens = math.prod(cfg["branching_factors"])
    if num_scens is None:
        raise SystemExit("need --num-scens (or an instance implying it)")
    names = module.scenario_names_creator(int(num_scens))
    tree = None
    if hasattr(module, "make_tree") and cfg.get("branching_factors"):
        tree = module.make_tree(tuple(cfg["branching_factors"]))
    elif hasattr(module, "make_tree"):
        tree = module.make_tree()
    return names, kwargs, tree


def _presolve_maybe(cfg, batch):
    if not cfg.get("presolve"):
        return batch
    from mpisppy_tpu.ops.fbbt import presolve_batch
    try:
        batch, info = presolve_batch(
            batch, n_sweeps=cfg.get("presolve_sweeps", 3))
    except ValueError as e:
        raise SystemExit(f"presolve: {e}")
    global_toc(f"presolve: tightened {info['tightened_bounds']} bounds",
               cfg.get("display_progress", False))
    return batch


def _build_batch(cfg, module):
    names, kwargs, tree = _model_plumbing(cfg, module)
    if cfg.get("scenarios_per_bundle"):
        # proper bundles: PH runs over bundle-EF subproblems
        # (ref:generic_cylinders.py:316-393 bundle paths)
        from mpisppy_tpu.utils.pickle_bundle import check_args
        from mpisppy_tpu.utils.proper_bundler import ProperBundler
        if tree is not None:
            raise SystemExit("proper bundles are two-stage only "
                             "(ref:proper_bundler.py:22); drop "
                             "--scenarios-per-bundle or the "
                             "branching factors")
        check_args(cfg)
        if cfg.get("num_scens") is None:
            cfg.quick_assign("num_scens", int, len(names))
        pb = ProperBundler(module)
        num_buns = len(names) // int(cfg["scenarios_per_bundle"])
        kwargs = pb.kw_creator(cfg)
        names = pb.bundle_names_creator(num_buns, cfg=cfg)
        specs = [pb.scenario_creator(nm, **kwargs) for nm in names]
        return _presolve_maybe(cfg, batch_mod.from_specs(specs)), \
            names, specs
    specs = [module.scenario_creator(nm, **kwargs) for nm in names]
    batch = _presolve_maybe(cfg, batch_mod.from_specs(specs, tree=tree))
    return batch, names, specs


def _do_EF(cfg, module):
    """ref:generic_cylinders.py:396-457."""
    from mpisppy_tpu.algos import ef as ef_mod
    # EF runs have no hub to emit wheel events, but --trace-jsonl /
    # --metrics-snapshot must not be silently ignored: the bus still
    # captures the console stream and writes a final metrics snapshot
    tel_bus = telemetry.from_cfg(cfg)
    try:
        names, kwargs, tree = _model_plumbing(cfg, module)
        ef = ef_mod.ExtensiveForm({"tol": cfg.get("pdhg_tol", 1e-6)},
                                  names, module.scenario_creator, kwargs,
                                  tree=tree)
        st = ef.solve_extensive_form()
        obj = ef.get_objective_value()
        global_toc(f"EF objective: {obj:.6g} "
                   f"(converged={bool(st.done.all())})", True)
        if cfg.get("solution_base_name"):
            import numpy as np
            np.save(cfg["solution_base_name"] + ".npy",
                    np.asarray(list(ef.get_root_solution().values())))
    finally:
        telemetry.close_bus(tel_bus)
    print(json.dumps({"EF_objective": obj,  # telemetry: allow-print
                      "converged": bool(st.done.all())}))
    return ef


def _fuse_wheel(cfg, hub, spokes, specs=None, tree=None):
    """Swap the PH hub for FusedPH and the fusable bound spokes
    (lagrangian / xhatxbar / slam / xhatshuffle) for their fused
    classes; everything else (cut providers, FWPH, reduced costs, ...)
    stays a classic spoke on the hub's sync period.

    MULTISTAGE: the x̄ recourse planes fix EVERY stage's nonants, which
    is structurally infeasible whenever a later-stage equality couples
    nonants with stage randomness (hydro's reservoir balance — measured
    recourse duals ~1e6); on trees deeper than two stages the x̄ spoke
    maps to EFXhatInnerBound (root-fixed EF with intra-tree
    nonanticipativity, the reference's xhatlooper stage2ef analog)
    instead of the fused all-stage-fixed plane."""
    from mpisppy_tpu.algos import fused_wheel as fw
    from mpisppy_tpu.cylinders import spoke as spoke_mod

    multistage = tree is not None and tree.num_stages > 2
    fusable = {
        spoke_mod.LagrangianOuterBound: spoke_mod.FusedLagrangianOuterBound,
        spoke_mod.XhatXbarInnerBound: spoke_mod.FusedXhatXbarInnerBound,
        spoke_mod.XhatShuffleInnerBound:
            spoke_mod.FusedXhatShuffleInnerBound,
        spoke_mod.SlamMaxHeuristic: spoke_mod.FusedSlamHeuristic,
        spoke_mod.SlamMinHeuristic: spoke_mod.FusedSlamHeuristic,
    }
    present = set()
    out_spokes = []
    for sd in spokes:
        cls = sd["spoke_class"]
        if cls is spoke_mod.XhatXbarInnerBound and multistage \
                and specs is not None:
            out_spokes.append({
                "spoke_class": spoke_mod.EFXhatInnerBound,
                "opt_kwargs": {"options": {"specs": specs,
                                           "tree": tree}}})
        elif cls in fusable:
            present.add(cls)
            out_spokes.append({"spoke_class": fusable[cls],
                               "opt_kwargs": {"options": {}}})
        else:
            out_spokes.append(sd)
    # --lane-guard and --kernel-counters must reach the fused planes'
    # PDHG options too, or the CLI knobs would silently cover only the
    # hub's subproblems
    import dataclasses as _dc
    _defaults = fw.FusedWheelOptions()
    _guard = {"lane_guard": bool(cfg.get("lane_guard", False)),
              "guard_max_resets": int(cfg.get("guard_max_resets", 3)),
              "telemetry": bool(cfg.get("kernel_counters", False))}
    wopts = fw.FusedWheelOptions(
        lag_pdhg=_dc.replace(_defaults.lag_pdhg, **_guard),
        xhat_pdhg=_dc.replace(_defaults.xhat_pdhg, **_guard),
        lag_windows=8 if spoke_mod.LagrangianOuterBound in present else 0,
        xhat_windows=4 if spoke_mod.XhatXbarInnerBound in present else 0,
        slam_windows=2 if (spoke_mod.SlamMaxHeuristic in present
                           or spoke_mod.SlamMinHeuristic in present)
        else 0,
        slam_sense_max=spoke_mod.SlamMinHeuristic not in present,
        shuffle_windows=4 if spoke_mod.XhatShuffleInnerBound in present
        else 0,
        spoke_period=max(1, int(cfg.get("fused_spoke_period", 1) or 1)))
    hub = dict(hub)
    hub["opt_class"] = fw.FusedPH
    hub["opt_kwargs"] = dict(hub.get("opt_kwargs", {}))
    hub["opt_kwargs"]["wheel_options"] = wopts
    # --async-staleness s >= 1: swap in the async exchange hub/driver
    # (ISSUE 11; docs/async_wheel.md).  0 keeps the synchronous pair —
    # AsyncPHHub/AsyncFusedPH at staleness 0 would be bit-identical
    # anyway, but the plain classes keep the common path untouched.
    staleness = max(0, int(cfg.get("async_staleness", 0) or 0))
    if staleness > 0:
        from mpisppy_tpu.algos import async_wheel as aw
        from mpisppy_tpu.cylinders import hub as hub_mod
        hub["hub_class"] = hub_mod.AsyncPHHub
        hub["opt_class"] = aw.AsyncFusedPH
        ddl = float(cfg.get("async_exchange_deadline_s", 0.0) or 0.0)
        hub["opt_kwargs"]["async_options"] = aw.AsyncWheelOptions(
            staleness=staleness,
            exchange_deadline_s=ddl if ddl > 0 else None)
        hub["hub_kwargs"] = dict(hub.get("hub_kwargs", {}))
        hub_opts = dict(hub["hub_kwargs"].get("options", {}))
        hub_opts["async_staleness"] = staleness
        hub["hub_kwargs"]["options"] = hub_opts
    return hub, out_spokes


def build_wheel(cfg, module):
    """Assemble (hub, spokes, names, specs, batch) from a parsed Config
    — the cylinder-construction half of the decomp driver, split out so
    other drivers (the multi-tenant serve engine, serve/engine.py)
    build sessions through the exact CLI recipe surface instead of a
    parallel hand-rolled one."""
    batch, names, specs = _build_batch(cfg, module)
    converger = None
    if cfg.get("use_primal_dual_converger"):
        import functools
        from mpisppy_tpu.convergers.primal_dual_converger import (
            PrimalDualConverger,
        )
        converger = functools.partial(
            PrimalDualConverger,
            tol=cfg.get("primal_dual_converger_tol", 1e-2))
    if cfg.get("lshaped_hub"):
        if converger is not None:
            global_toc("WARNING: converger options are ignored with "
                       "--lshaped-hub (Benders has its own termination)",
                       True)
        if cfg.get("aph_hub"):
            global_toc("WARNING: --aph-hub is ignored because "
                       "--lshaped-hub is also set", True)
        hub = vanilla.lshaped_hub(cfg, batch, scenario_names=names)
    elif cfg.get("aph_hub"):
        hub = vanilla.aph_hub(cfg, batch, scenario_names=names,
                              converger=converger)
    else:
        extensions = None
        ext_factories = []
        if cfg.get("cross_scenario_cuts"):
            ext_factories.append(vanilla.cross_scenario_extension(cfg))
        if cfg.get("reduced_costs"):
            ext_factories.append(vanilla.reduced_costs_fixer(cfg))
        if cfg.get("grad_rho"):
            import functools
            from mpisppy_tpu.extensions.rho_setters import (
                Gradient_extension,
            )
            ext_factories.append(functools.partial(
                Gradient_extension,
                grad_order_stat=cfg.get("grad_order_stat", 0.5),
                grad_rho_update_interval=cfg.get(
                    "grad_rho_update_interval", 5),
                indep_denom=cfg.get("grad_rho_indep_denom", False),
                grad_rho_relative_bound=cfg.get(
                    "grad_rho_relative_bound", 1e3)))
        if cfg.get("sensi_rho"):
            import functools
            from mpisppy_tpu.extensions.rho_setters import SensiRho
            ext_factories.append(functools.partial(
                SensiRho,
                sensi_rho_multiplier=cfg.get("sensi_rho_multiplier",
                                             1.0)))
        if cfg.get("mult_rho"):
            import functools
            from mpisppy_tpu.extensions.rho_setters import MultRhoUpdater
            ext_factories.append(functools.partial(
                MultRhoUpdater,
                mult_rho_update_factor=cfg.get("mult_rho_update_factor",
                                               2.0),
                mult_rho_update_interval=cfg.get(
                    "mult_rho_update_interval", 2)))
        if cfg.get("W_fname") or cfg.get("Xbar_fname"):
            import functools
            from mpisppy_tpu.extensions.wxbar_io import WXBarWriter
            ext_factories.append(functools.partial(
                WXBarWriter, W_fname=cfg.get("W_fname"),
                Xbar_fname=cfg.get("Xbar_fname")))
        if cfg.get("init_W_fname") or cfg.get("init_Xbar_fname"):
            import functools
            from mpisppy_tpu.extensions.wxbar_io import WXBarReader
            ext_factories.append(functools.partial(
                WXBarReader, init_W_fname=cfg.get("init_W_fname"),
                init_Xbar_fname=cfg.get("init_Xbar_fname")))
        if len(ext_factories) == 1:
            extensions = ext_factories[0]
        elif ext_factories:
            from mpisppy_tpu.extensions.extension import MultiExtension
            import functools
            extensions = functools.partial(MultiExtension,
                                           ext_classes=ext_factories)
        rho_setter = None
        if cfg.get("rho_file_in"):
            from mpisppy_tpu.utils.gradient import Set_Rho
            rho_setter = Set_Rho(cfg).rho_setter
        hub = vanilla.ph_hub(cfg, batch, scenario_names=names,
                             converger=converger, extensions=extensions,
                             rho_setter=rho_setter)
    spokes = []
    if not cfg.get("lshaped_hub") and not cfg.get("aph_hub"):
        if cfg.get("cross_scenario_cuts"):
            spokes.append(vanilla.cross_scenario_cuts_spoke(cfg))
        if cfg.get("reduced_costs"):
            spokes.append(vanilla.reduced_costs_spoke(cfg))
    if cfg.get("ph_ob"):
        spokes.append(vanilla.ph_ob_spoke(cfg))
    if cfg.get("xhatlshaped"):
        spokes.append(vanilla.xhatlshaped_spoke(cfg))
    if cfg.get("fwph"):
        spokes.append(vanilla.fwph_spoke(cfg))
    if cfg.get("lagrangian"):
        spokes.append(vanilla.lagrangian_spoke(cfg))
    if cfg.get("lagranger"):
        spokes.append(vanilla.lagranger_spoke(cfg))
    if cfg.get("subgradient"):
        spokes.append(vanilla.subgradient_spoke(cfg))
    if cfg.get("xhatxbar"):
        spokes.append(vanilla.xhatxbar_spoke(cfg))
    if cfg.get("xhatshuffle"):
        spokes.append(vanilla.xhatshuffle_spoke(cfg))
    if cfg.get("slammax"):
        spokes.append(vanilla.slammax_spoke(cfg))
    if cfg.get("slammin"):
        spokes.append(vanilla.slammin_spoke(cfg))

    if cfg.get("fused_wheel") and not cfg.get("lshaped_hub") \
            and not cfg.get("aph_hub"):
        hub, spokes = _fuse_wheel(cfg, hub, spokes, specs=specs,
                                  tree=batch.tree)
    elif int(cfg.get("async_staleness", 0) or 0) > 0:
        why = ("--fused-wheel is vetoed by --aph-hub/--lshaped-hub here"
               if cfg.get("fused_wheel") else "requires --fused-wheel")
        global_toc(f"WARNING: --async-staleness {why} "
                   "(the async exchange plane is the fused wheel's); "
                   "running synchronous", True)
    return hub, spokes, names, specs, batch


def _do_decomp(cfg, module):
    """ref:generic_cylinders.py:109-312."""
    hub, spokes, names, specs, batch = build_wheel(cfg, module)

    # telemetry spine (docs/telemetry.md): --trace-jsonl /
    # --metrics-snapshot build the run's event bus; the hub emits into
    # it and the finally below flushes the sinks even on preemption
    tel_bus = telemetry.from_cfg(cfg)
    # crash flight recorder (docs/telemetry.md): an always-on bounded
    # ring of the last ~512 events, even with --trace-jsonl OFF —
    # WheelSpinner.spin dumps it to flight-<runid>.jsonl when the wheel
    # dies, so every crash leaves a black box.  When no trace/metrics
    # bus exists, a private bus carries just the recorder (and the
    # console stream, so the black box holds the final log lines too —
    # stdout rendering is unchanged: the private bus has no ConsoleSink)
    wheel_bus, own_bus = tel_bus, False
    if cfg.get("flight_recorder", True):
        from mpisppy_tpu.telemetry import flightrec
        if wheel_bus is None:
            wheel_bus = telemetry.EventBus()
            telemetry.console.attach(wheel_bus)
            own_bus = True
        wheel_bus.subscribe(flightrec.FlightRecorder(
            capacity=int(cfg.get("flight_capacity", 512)),
            dump_dir=cfg.get("flight_dir", ".")))
    # dispatch scheduler (docs/dispatch.md): the --dispatch-* group
    # configures the process-default scheduler every MIP-oracle solve
    # routes through; with a bus attached each megabatch dispatch also
    # lands in the JSONL trace (and the flight recorder's ring)
    from mpisppy_tpu import dispatch as _dispatch
    _dispatch.from_cfg(cfg, bus=wheel_bus)
    if wheel_bus is not None:
        hub = dict(hub)
        hub["hub_kwargs"] = dict(hub.get("hub_kwargs", {}))
        hub_opts = dict(hub["hub_kwargs"].get("options", {}))
        hub_opts["telemetry_bus"] = wheel_bus
        hub["hub_kwargs"]["options"] = hub_opts
    try:
        return _spin_and_report(cfg, module, hub, spokes, names, specs)
    finally:
        if own_bus:
            telemetry.console.detach(wheel_bus)
            wheel_bus.close()
        telemetry.close_bus(tel_bus)


def _report_device_profile(profile_dir: str) -> None:
    """A --profile-dir run closes the loop itself (ISSUE 7): parse the
    capture the ProfilerSession just wrote, print the headline device
    numbers, and leave the full roofline report next to the capture as
    device_profile.json — the committed-artifact form the README lint
    and `telemetry gate` consume."""
    import os

    from mpisppy_tpu.telemetry import deviceprof, roofline
    try:
        cap = deviceprof.newest_capture(profile_dir)
        if cap is None:
            return
        rep = roofline.roofline(deviceprof.build_timeline(cap))
    except (OSError, ValueError) as e:
        global_toc(f"device profile unreadable under {profile_dir}: {e}",
                   True)
        return
    out_path = os.path.join(profile_dir, "device_profile.json")
    try:
        from mpisppy_tpu.utils.atomic_io import atomic_write_text
        atomic_write_text(out_path, json.dumps(rep, indent=1) + "\n")
    except OSError:
        out_path = "(unwritable)"
    def _g(v):
        return "-" if v is None else format(v, ".4g")
    global_toc(
        f"device profile: sec/iter {_g(rep.get('device_sec_per_iter'))}"
        f"  stream {_g(rep.get('measured_stream_gbps'))} GB/s"
        f"  hbm {_g(rep.get('achieved_hbm_gbps'))}/"
        f"{_g(rep.get('peak_hbm_gbps'))} GB/s"
        f"  overlap {_g(rep.get('overlap_frac'))}  -> {out_path}", True)


def _spin_and_report(cfg, module, hub, spokes, names, specs):
    wheel = WheelSpinner(hub, spokes)
    ckpt = cfg.get("checkpoint_path")
    if ckpt and cfg.get("checkpoint_restore"):
        wheel.build()
        if wheel.spcomm._checkpoint_candidates(ckpt):
            try:
                wheel.spcomm.load_checkpoint(ckpt)
                global_toc(f"restored checkpoint {ckpt} at hub iter "
                           f"{wheel.spcomm._iter}; resuming", True)
            except FileNotFoundError as e:
                # snapshots exist but NONE validates (bit rot, torn on
                # a non-atomic fs): a crash here would restart-storm
                # the pool scheduler against the same dead files —
                # degrade to a fresh run instead, loudly
                global_toc(f"WARNING: no valid checkpoint to restore "
                           f"({e}); starting fresh", True)
    try:
        wheel.spin()
    except PreemptionError as e:
        # state was already emergency-saved by WheelSpinner.spin; report
        # and exit with EX_TEMPFAIL so the pool scheduler restarts us
        # (--checkpoint-restore picks the run back up)
        global_toc(f"run preempted ({e}); restart with "
                   f"--checkpoint-restore to resume", True)
        print(json.dumps({"preempted": True,  # telemetry: allow-print
                          "checkpoint_path": ckpt,
                          "iterations": wheel.spcomm._iter}))
        raise SystemExit(75)
    abs_gap, rel_gap = wheel.spcomm.compute_gaps()
    global_toc(
        f"outer {wheel.BestOuterBound:.6g} inner {wheel.BestInnerBound:.6g}"
        f" rel_gap {rel_gap:.3e}", True)
    if cfg.get("profile_dir"):
        _report_device_profile(cfg["profile_dir"])
    if cfg.get("solution_base_name"):
        wheel.write_first_stage_solution(
            cfg["solution_base_name"] + ".csv")
    if cfg.get("rho_file_out") \
            and getattr(wheel.opt, "state", None) is not None \
            and hasattr(wheel.opt.state, "rho"):
        import numpy as _np
        from mpisppy_tpu.utils.rho_utils import rhos_to_csv
        rhos_to_csv(_np.asarray(wheel.opt.state.rho),
                    cfg["rho_file_out"])
    for rank0, nm in enumerate(names):
        module.scenario_denouement(0, nm, specs[rank0])

    def _finite(v):  # strict-JSON safe: a bound that never landed -> null
        import math
        return v if isinstance(v, (int, float)) and math.isfinite(v) \
            else None
    # fault-domain accounting (docs/resilience.md): a run that leaned
    # on dispatch retries/quarantine or tripped the watchdog says so in
    # its machine-readable result line, not only in the trace
    from mpisppy_tpu import dispatch as _dispatch
    dstats = _dispatch.scheduler_stats() or {}
    wd = getattr(wheel.spcomm, "_watchdog", None)
    print(json.dumps({  # telemetry: allow-print
        "outer_bound": _finite(wheel.BestOuterBound),
        "inner_bound": _finite(wheel.BestInnerBound),
        "abs_gap": _finite(abs_gap), "rel_gap": _finite(rel_gap),
        "iterations": wheel.spcomm._iter,
        "dispatch_retries": dstats.get("retries_total", 0),
        "dispatch_quarantined_lanes": dstats.get("quarantined_lanes", 0),
        "watchdog_trips": 0 if wd is None else wd.trips,
    }))
    return wheel


def main(args=None):
    argv = list(sys.argv[1:] if args is None else args)
    module_name = None
    for i, a in enumerate(argv):
        if a == "--module-name":
            module_name = argv[i + 1]
        elif a.startswith("--module-name="):
            module_name = a.split("=", 1)[1]
    if module_name is None:
        raise SystemExit(
            "usage: python -m mpisppy_tpu --module-name <module> ...")
    sys.path.insert(0, ".")
    module = importlib.import_module(module_name)
    cfg = _parse_args(module, argv)
    if cfg.get("EF"):
        return _do_EF(cfg, module)
    return _do_decomp(cfg, module)


if __name__ == "__main__":
    main()
