###############################################################################
# ELL sparse constraint matrices for the BoxQP kernel.
#
# sslp/netdes/uc-class constraint matrices are sparse (flow balance,
# set-cover, ramp rows touch a handful of columns); at 10^4-10^5
# scenarios a dense per-scenario (S, m, n) A tensor cannot fit HBM
# (VERDICT round-1 weakness #3).  The reference never faces this — each
# Pyomo model hands a scipy-sparse matrix to Gurobi
# (ref:mpisppy/spopt.py:99-247) — so the TPU design needs its own answer.
#
# Format choice: ELLPACK, not BCOO.  Unstructured COO gathers defeat the
# TPU's vector units and XLA's static-shape tiling; ELL stores a fixed
# `k = max nonzeros per row` block (vals (..., m, k), cols (m, k)), so
#   A @ x   = sum_k vals * x[cols]          (one gather + multiply-add)
#   A.T @ y = scatter-add of vals * y       (one segment reduction)
# — both static-shape, fully vectorized, batched over scenarios by a
# leading axis on `vals` alone (the sparsity PATTERN is shared across
# the batch; only values vary, which is exactly the structure of
# scenario families where randomness enters the data, not the model).
#
# Padding entries point at column 0 with value 0, so no masks are needed
# anywhere in the hot path.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["vals", "cols"],
    meta_fields=["n"],
)
@dataclasses.dataclass(frozen=True)
class EllMatrix:
    """ELLPACK matrix: logical shape (..., m, n).

    vals: (..., m, k) nonzero values (leading batch axis optional).
    cols: (m, k) int32 column indices, shared across the batch.
    n:    number of columns (static).
    """

    vals: Array
    cols: Array
    n: int

    # -- dense-array interface shims (BoxQP treats A generically) ---------
    @property
    def ndim(self) -> int:
        """Rank of the LOGICAL matrix: vals (m,k) -> 2; (S,m,k) -> 3."""
        return self.vals.ndim

    @property
    def shape(self) -> tuple:
        return self.vals.shape[:-1] + (self.n,)

    @property
    def dtype(self):
        return self.vals.dtype

    @property
    def m(self) -> int:
        return self.vals.shape[-2]

    @property
    def k(self) -> int:
        return self.vals.shape[-1]

    # -- products ---------------------------------------------------------
    def matvec(self, x: Array) -> Array:
        """A @ x: gather + multiply-add (no MXU involvement, so no
        bf16-precision caveat — f32 FMAs throughout)."""
        flat = self.cols.reshape(-1)
        g = jnp.take(x, flat, axis=-1).reshape(
            x.shape[:-1] + self.cols.shape)
        return jnp.sum(self.vals * g, axis=-1)

    def rmatvec(self, y: Array) -> Array:
        """A.T @ y via scatter-add over the shared column index."""
        contrib = self.vals * y[..., None]           # (..., m, k)
        flat = self.cols.reshape(-1)
        cflat = contrib.reshape(contrib.shape[:-2] + (-1,))
        z = jnp.zeros(cflat.shape[:-1] + (self.n,), cflat.dtype)
        return z.at[..., flat].add(cflat)

    def toarray(self) -> np.ndarray:
        """Dense (..., m, n) numpy copy — oracle/debug use only."""
        vals = np.asarray(self.vals)
        cols = np.asarray(self.cols)
        out = np.zeros(vals.shape[:-2] + (self.m, self.n), vals.dtype)
        rows = np.broadcast_to(np.arange(self.m)[:, None], cols.shape)
        # scatter-ADD duplicates (padding slots add 0 at column 0)
        np.add.at(out, (..., rows, cols), vals)
        return out

    # -- norms (estimate_norm lower bounds, Ruiz) -------------------------
    def row_sqnorms(self) -> Array:
        return jnp.sum(self.vals * self.vals, axis=-1)

    def col_sqnorms(self) -> Array:
        sq = self.vals * self.vals
        flat = self.cols.reshape(-1)
        sflat = sq.reshape(sq.shape[:-2] + (-1,))
        z = jnp.zeros(sflat.shape[:-1] + (self.n,), sflat.dtype)
        return z.at[..., flat].add(sflat)


def _slot_map(csr) -> tuple[np.ndarray, np.ndarray, int]:
    """Vectorized nonzero -> (row, within-row position) map for a sorted
    CSR matrix, shared by all ELL constructors."""
    m = csr.shape[0]
    nnz_per_row = np.diff(csr.indptr)
    k = max(1, int(nnz_per_row.max()) if m else 1)
    slot_row = np.repeat(np.arange(m), nnz_per_row)
    slot_pos = np.arange(csr.nnz) - np.repeat(csr.indptr[:-1], nnz_per_row)
    return slot_row, slot_pos, k


def from_scipy(A, dtype=np.float64) -> tuple[np.ndarray, np.ndarray]:
    """(vals, cols) ELL arrays from a scipy.sparse matrix (host-side)."""
    import scipy.sparse as sps
    csr = sps.csr_matrix(A)
    csr.sort_indices()
    m, n = csr.shape
    slot_row, slot_pos, k = _slot_map(csr)
    vals = np.zeros((m, k), dtype)
    cols = np.zeros((m, k), np.int32)
    vals[slot_row, slot_pos] = csr.data
    cols[slot_row, slot_pos] = csr.indices
    return vals, cols


def ell_from_scipy(A, dtype=jnp.float32) -> EllMatrix:
    """Device EllMatrix from one scipy.sparse matrix."""
    vals, cols = from_scipy(A)
    return EllMatrix(vals=jnp.asarray(vals, dtype), cols=jnp.asarray(cols),
                     n=int(A.shape[1]))


def ell_from_scipy_batch(mats, dtype=jnp.float32) -> EllMatrix:
    """Batched EllMatrix from scipy matrices (vals get a leading
    scenario axis; cols are shared).

    Scenario matrices with DIFFERING sparsity patterns are padded onto
    the pattern UNION (absent entries hold value 0) — the heterogeneous-
    region case of the admm wrappers; matrices sharing a pattern skip
    the union work.  Collapses to a SHARED (unbatched) EllMatrix when
    all values are equal too — mirroring the dense stack()'s
    value-equality fallback so rebuilt-per-scenario deterministic
    matrices don't duplicate S-fold.  Vectorized fill: one
    (nnz,) -> (m, k) slot map shared by the batch, no per-row loop."""
    import scipy.sparse as sps
    csrs = []
    for M in mats:
        csr = sps.csr_matrix(M)
        csr.sort_indices()
        csrs.append(csr)
    first = csrs[0]
    m, n = first.shape
    for s, c in enumerate(csrs[1:], start=1):
        if c.shape != (m, n):
            raise ValueError(
                f"scenario {s}: matrix shape {c.shape} differs from "
                f"scenario 0's {(m, n)} (a batch shares one row/column "
                "layout; pad on the host first)")
    shared_pattern = all(
        np.array_equal(c.indptr, first.indptr)
        and np.array_equal(c.indices, first.indices) for c in csrs[1:])
    if not shared_pattern:
        # pattern union: mark every position present anywhere, then
        # read each scenario's values at the union coordinates
        pat = sps.csr_matrix(
            (np.ones_like(first.data), first.indices, first.indptr),
            shape=(m, n))
        for c in csrs[1:]:
            pat = pat + sps.csr_matrix(
                (np.ones_like(c.data), c.indices, c.indptr), shape=(m, n))
        pat = sps.csr_matrix(pat)
        pat.sort_indices()
        pat.data[:] = 1.0
        urows = np.repeat(np.arange(m), np.diff(pat.indptr))
        ucols = pat.indices
        data = np.empty((len(csrs), pat.nnz))
        for s, c in enumerate(csrs):
            data[s] = np.asarray(c[urows, ucols]).reshape(-1)
        slot_row, slot_pos, k = _slot_map(pat)
        cols = np.zeros((m, k), np.int32)
        cols[slot_row, slot_pos] = pat.indices
    else:
        slot_row, slot_pos, k = _slot_map(first)
        cols = np.zeros((m, k), np.int32)
        cols[slot_row, slot_pos] = first.indices
        data = np.empty((len(csrs), first.nnz))
        for s, csr in enumerate(csrs):
            data[s] = csr.data

    if (data[1:] == data[0]).all():
        vals = np.zeros((m, k))
        vals[slot_row, slot_pos] = data[0]
    else:
        vals = np.zeros((len(mats), m, k))
        vals[:, slot_row, slot_pos] = data
    return EllMatrix(vals=jnp.asarray(vals, dtype), cols=jnp.asarray(cols),
                     n=n)


def ruiz_scale_ell(vals: np.ndarray, cols: np.ndarray, n: int,
                   iters: int = 10, cones=None) -> tuple[np.ndarray,
                                                         np.ndarray,
                                                         np.ndarray]:
    """Host-side Ruiz equilibration in ELL form (the sparse analog of
    ops.boxqp.ruiz_scale's loop).  Returns (scaled_vals, d_row, d_col);
    batched vals get per-batch scalings.

    `cones` (an ops.cones.ConeSpec) forces block-UNIFORM row scales on
    SOC blocks — per-row scaling would break ||z|| <= t — exactly like
    the dense path (boxqp.group_row_scales); the ELL assembly otherwise
    carries SOC metadata untouched (the cone partition lives on the
    BoxQP, the sparsity pattern here)."""
    vals = np.asarray(vals, np.float64).copy()
    bshape = vals.shape[:-2]
    m = vals.shape[-2]
    dr = np.ones(bshape + (m,))
    dc = np.ones(bshape + (n,))
    flat_cols = cols.reshape(-1)
    for _ in range(iters):
        rmax = np.max(np.abs(vals), axis=-1)
        # empty rows/columns keep scale 1 (a 1e-12 floor like the dense
        # path would compound to overflow across iterations here, since
        # ELL problems legitimately have columns absent from A)
        rmax = np.where(rmax <= 1e-12, 1.0, rmax)
        if cones is not None:
            from mpisppy_tpu.ops.boxqp import group_row_scales
            rmax = group_row_scales(rmax, cones)
        vals /= np.sqrt(rmax)[..., None]
        dr /= np.sqrt(rmax)
        # one flattened scatter-max for the whole batch: index
        # b * n + col — no per-scenario Python loop at 1e5 scenarios
        B = int(np.prod(bshape)) if bshape else 1
        av = np.abs(vals).reshape(B, -1)
        offs = (np.arange(B)[:, None] * n + flat_cols[None, :]).reshape(-1)
        cflat = np.zeros(B * n)
        np.maximum.at(cflat, offs, av.reshape(-1))
        cmax = cflat.reshape(bshape + (n,))
        cmax = np.where(cmax <= 1e-12, 1.0, cmax)
        sq = np.sqrt(cmax)
        vals /= sq[..., flat_cols].reshape(vals.shape)
        dc /= sq
    return vals, dr, dc
