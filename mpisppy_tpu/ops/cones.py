###############################################################################
# Second-order-cone rows for the BoxQP kernel.
#
# The README's documented upgrade path (ccopf scope decision): SOC rows
# are the natural convex relaxation of AC power flow, and supporting
# them generalizes the subproblem class from box-LP/QP to conic — the
# same kernel-generalization move MPAX makes for JAX-native mathematical
# programming (PAPERS.md, arXiv:2412.09734), inheriting restarted-PDHG
# convergence for conic feasible sets from the PDLP line of work the
# kernel already follows.
#
# Contract (the ConeSpec contract, see docs/cones.md):
#
#   * A ConeSpec PARTITIONS the m constraint rows of a BoxQP into box
#     rows and disjoint SOC blocks.  A block is a set of rows
#     (head; tail_1..tail_d) whose constraint is
#
#         (A x - b)_block  in  K_soc   i.e.
#         a_head'x - b_head  >=  || (A x - b)_tail ||_2
#
#     with the per-row shifts b stored in BOTH bl and bu of the block's
#     rows (bl == bu == b).  That storage convention is load-bearing:
#     dual_objective's box accounting where(y>0, bu*y, bl*y) collapses
#     to b'y on SOC rows — exactly -g*(y) for y in the polar cone — so
#     the Fenchel machinery needs no special case, and Ruiz row scaling
#     of bl/bu scales the shift consistently with the block (row scales
#     are forced UNIFORM within a block; see boxqp.ruiz_scale).
#   * Blocks are ragged; the per-row segment encoding (`seg`) pads them
#     onto a shared (num_cones + 1)-segment axis so every blockwise
#     reduction is ONE fused scatter-add/gather pair over the row axis —
#     static shapes, batched over scenarios by broadcasting, no masks in
#     the hot path (box rows land in the sentinel segment, which is
#     never read back).
#   * The dual prox of the row indicator becomes, via Moreau and the
#     positive homogeneity of cone projections (no division by sigma):
#         box rows:  y1 = w - clip(w, sigma*bl, sigma*bu)
#         SOC rows:  y1 = Proj_polar(w - sigma*b)
#     so dual ITERATES always lie in the polar cone -K (SOC is
#     self-dual) and the conic dual-feasibility residual below is the
#     certificate that warm starts / window averages have not left it.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

_TINY = 1e-30


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["is_soc", "is_head", "seg"],
    meta_fields=["num_cones", "max_dim", "head_rows"],
)
@dataclasses.dataclass(frozen=True)
class ConeSpec:
    """Static partition of a BoxQP's m rows into box rows + SOC blocks.

    is_soc:    (m,) bool — row belongs to some SOC block.
    is_head:   (m,) bool — row is its block's head (the t component).
    seg:       (m,) int32 — block id for SOC rows; `num_cones` (the
               sentinel segment) for box rows.
    num_cones: static block count.
    max_dim:   static max block dimension (head + tails) — the padding
               width downstream fixed-shape consumers (the Pallas
               membership matrices) size against.
    head_rows: static (num_cones,) tuple — block b's head row index.
               STATIC (a meta field) so consumers needing per-block
               row gathers (FBBT's head-activity bound) can slice A
               at trace time instead of reducing over all m rows.
    """

    is_soc: Array
    is_head: Array
    seg: Array
    num_cones: int
    max_dim: int
    head_rows: tuple = ()

    @property
    def m(self) -> int:
        return self.is_soc.shape[0]


def cone_spec(m: int, blocks) -> ConeSpec:
    """Build a ConeSpec from `blocks`: a list of int row-index arrays,
    HEAD FIRST, each of length >= 2, pairwise disjoint."""
    is_soc = np.zeros(m, bool)
    is_head = np.zeros(m, bool)
    seg = np.full(m, len(blocks), np.int32)
    max_dim = 0
    heads = []
    for b, rows in enumerate(blocks):
        rows = np.asarray(rows, np.int64)
        if rows.ndim != 1 or len(rows) < 2:
            raise ValueError(f"SOC block {b}: need head + >=1 tail rows")
        if len(np.unique(rows)) != len(rows):
            # duplicates collapse in the fancy assignments below and
            # would silently build a LOOSER cone than specified
            raise ValueError(f"SOC block {b}: duplicate row indices")
        if is_soc[rows].any():
            raise ValueError(f"SOC block {b}: overlaps another block")
        is_soc[rows] = True
        is_head[rows[0]] = True
        heads.append(int(rows[0]))
        seg[rows] = b
        max_dim = max(max_dim, len(rows))
    return ConeSpec(
        is_soc=jnp.asarray(is_soc), is_head=jnp.asarray(is_head),
        seg=jnp.asarray(seg), num_cones=len(blocks), max_dim=max_dim,
        head_rows=tuple(heads))


def _blockwise(spec: ConeSpec, v: Array):
    """(t, znorm) per segment: head values and tail 2-norms, (..., C+1)."""
    C = spec.num_cones + 1
    tail = jnp.where(spec.is_soc & ~spec.is_head, v, 0.0)
    base = jnp.zeros(v.shape[:-1] + (C,), v.dtype)
    zsq = base.at[..., spec.seg].add(tail * tail)
    t = base.at[..., spec.seg].add(jnp.where(spec.is_head, v, 0.0))
    return t, jnp.sqrt(zsq)


def project_soc_rows(spec: ConeSpec, v: Array) -> Array:
    """Rowwise Euclidean projection of each SOC block of `v` onto the
    second-order cone {(t, z): ||z|| <= t}; box rows pass through.

    Cases (per block): interior/boundary (||z|| <= t) identity; polar
    (||z|| <= -t) zero; else the reflection case
    proj = (alpha, alpha z/||z||), alpha = (t + ||z||)/2.
    """
    t, znorm = _blockwise(spec, v)
    inside = znorm <= t
    polar = znorm <= -t
    alpha = 0.5 * (t + znorm)
    scale = jnp.where(inside, 1.0,
                      jnp.where(polar, 0.0,
                                alpha / jnp.maximum(znorm, _TINY)))
    t_new = jnp.where(inside, t, jnp.where(polar, 0.0, alpha))
    row_scale = scale[..., spec.seg]
    row_t = t_new[..., spec.seg]
    proj = jnp.where(spec.is_head, row_t, v * row_scale)
    return jnp.where(spec.is_soc, proj, v)


def project_polar_rows(spec: ConeSpec, v: Array) -> Array:
    """Rowwise projection of SOC blocks onto the POLAR cone -K (SOC is
    self-dual: -K* = -K); box rows pass through.  By Moreau,
    Proj_{-K}(v) = v - Proj_K(v)."""
    return jnp.where(spec.is_soc, v - project_soc_rows(spec, v), v)


def dual_prox(spec: ConeSpec, w: Array, sigma: Array,
              bl: Array, bu: Array) -> Array:
    """Generalized PDHG dual prox: y1 = w - sigma * Proj_set(w / sigma)
    with the row set = [bl, bu] on box rows and b + K on SOC blocks
    (shift b read off bl; bl == bu == b by the ConeSpec contract).

    Division-free via positive homogeneity:
        box:  y1 = w - clip(w, sigma*bl, sigma*bu)
        SOC:  y1 = (w - sigma*b) - Proj_K(w - sigma*b)
            = Proj_polar(w - sigma*b).
    `sigma` broadcasts over the row axis ((..., 1) from callers)."""
    box = w - jnp.clip(w, sigma * bl, sigma * bu)
    shift = jnp.where(spec.is_soc, bl, 0.0)
    wsh = w - sigma * shift
    soc = wsh - project_soc_rows(spec, wsh)
    return jnp.where(spec.is_soc, soc, box)


def primal_violation_rows(spec: ConeSpec, ax: Array, bl: Array) -> Array:
    """Rowwise |ax - Proj_{b+K}(ax)| on SOC rows, 0 on box rows — the
    conic analog of the box row residual max(ax-bu,0)+max(bl-ax,0)."""
    shift = jnp.where(spec.is_soc, bl, 0.0)
    v = ax - shift
    proj = project_soc_rows(spec, v)
    return jnp.where(spec.is_soc, jnp.abs(v - proj), 0.0)


def dual_cone_residual_rows(spec: ConeSpec, y: Array) -> Array:
    """Rowwise conic dual-feasibility residual |y - Proj_{-K}(y)| on SOC
    rows (0 on box rows): the distance of each dual block to the polar
    cone.  Zero at every PDHG iterate (the prox lands in -K) and at
    window averages (-K is convex); nonzero flags a warm start or
    hand-built y whose conic Fenchel accounting is not yet valid, so
    kkt_residuals folds the max into the dual residual and every
    bound-publication gate (lagrangian / xhat / fused planes) inherits
    the check."""
    return jnp.where(spec.is_soc, jnp.abs(y - project_polar_rows(spec, y)),
                     0.0)


def head_membership(spec: ConeSpec, num_segments: int | None = None):
    """(C, m) f32 head/tail membership matrices (Mhead, Mtail) — the
    matmul form of the segment maps, for consumers that cannot scatter
    (the Pallas VMEM window kernel does blockwise reductions as two
    small MXU dots against these)."""
    C = spec.num_cones if num_segments is None else num_segments
    m = spec.m
    base = jnp.zeros((C, m), jnp.float32)
    rows = jnp.arange(m)
    seg = jnp.clip(spec.seg, 0, C - 1)
    head = base.at[seg, rows].add(
        jnp.where(spec.is_soc & spec.is_head, 1.0, 0.0))
    tail = base.at[seg, rows].add(
        jnp.where(spec.is_soc & ~spec.is_head, 1.0, 0.0))
    return head, tail


def validate_against_bounds(spec: ConeSpec, bl, bu,
                            atol: float = 0.0) -> None:
    """Host-side check of the ConeSpec contract: every SOC row must
    carry bl == bu (the shift).  Call at build time, not in hot paths."""
    bl = np.asarray(bl)
    bu = np.asarray(bu)
    soc = np.asarray(spec.is_soc)
    bad = soc & ~(np.abs(bl - bu) <= atol)
    if bad.reshape(-1, bad.shape[-1]).any():
        rows = np.nonzero(bad.reshape(-1, bad.shape[-1]).any(0))[0]
        raise ValueError(
            f"SOC rows {rows.tolist()} must store their shift in both "
            "bl and bu (bl == bu); got differing bounds")
