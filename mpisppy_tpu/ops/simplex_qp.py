###############################################################################
# Batched QP over the probability simplex (the FWPH inner "SDM" QP).
#
# The reference's FWPH builds one Pyomo QP per scenario over convex-
# combination weights of its column set and dispatches each to a
# persistent Gurobi instance (ref:mpisppy/fwph/fwph.py:688-775,214-307).
# On TPU the natural shape is ONE batched dense QP
#
#     min_{lam in Delta_K}  1/2 lam' H lam + g' lam
#
# with H = (S, K, K) PSD Gram matrices (K = column-buffer size, small)
# and a per-scenario validity mask on the columns.  K x K matmuls over a
# scenario batch are exactly MXU food, so accelerated projected gradient
# (FISTA with adaptive restart) beats shipping S tiny QPs to a host
# solver by orders of magnitude.  Everything is fixed-shape and
# jit-compatible: masked columns are excluded by forcing their weight to
# zero through the projection, not by changing shapes.
###############################################################################
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

Array = jax.Array


def project_simplex(v: Array, valid: Array) -> Array:
    """Euclidean projection of each row of v onto the simplex restricted
    to `valid` columns (invalid coordinates project to exactly 0).

    Standard sort-and-threshold algorithm, batched.  Masking trick:
    invalid coordinates are sent to -inf before the sort, so they can
    never exceed the threshold theta and come out as max(v-theta,0)=0.
    """
    dt = v.dtype
    neg = jnp.asarray(-1e30, dt)
    vm = jnp.where(valid, v, neg)
    u = jnp.sort(vm, axis=-1)[..., ::-1]  # descending
    css = jnp.cumsum(u, axis=-1) - 1.0
    k = jnp.arange(1, v.shape[-1] + 1, dtype=dt)
    cond = u - css / k > 0
    # rho = number of active coordinates (>=1 whenever any column valid)
    rho = jnp.maximum(jnp.sum(cond, axis=-1), 1)
    theta = jnp.take_along_axis(css, rho[..., None] - 1, axis=-1) \
        / rho[..., None].astype(dt)
    return jnp.where(valid, jnp.maximum(vm - theta, 0.0), 0.0)


def _estimate_L(H: Array, valid: Array, iters: int = 12) -> Array:
    """Power-iteration estimate of lambda_max(H) per batch element,
    restricted to valid columns; floored by max |H_ii| (a guaranteed
    lower bound for PSD H) so a degenerate iterate cannot underestimate.
    Seeded with a fixed PRNG vector (never all-ones; see
    ops/pdhg.py:estimate_norm for the degeneracy rationale)."""
    bshape = H.shape[:-1]
    v = jax.random.normal(jax.random.PRNGKey(3), bshape, H.dtype)
    v = jnp.where(valid, v, 0.0)
    v = v / jnp.maximum(jnp.linalg.norm(v, axis=-1, keepdims=True), 1e-30)

    def body(_, carry):
        v, _ = carry
        w = jnp.einsum("...kj,...j->...k", H, v)
        w = jnp.where(valid, w, 0.0)
        nrm = jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True), 1e-30)
        return w / nrm, nrm[..., 0]

    _, lam = jax.lax.fori_loop(
        0, iters, body, (v, jnp.ones(H.shape[:-2] or (), H.dtype)))
    diag_lb = jnp.max(jnp.where(valid, jnp.abs(
        jnp.diagonal(H, axis1=-2, axis2=-1)), 0.0), axis=-1)
    return jnp.maximum(jnp.maximum(lam, diag_lb), 1e-12)


@partial(jax.jit, static_argnames=("iters",))
def solve_simplex_qp(H: Array, g: Array, valid: Array,
                     lam0: Array | None = None, iters: int = 200) -> Array:
    """FISTA with adaptive (function-free, gradient-scheme) restart.

    H: (..., K, K) PSD, g: (..., K), valid: (..., K) bool mask of usable
    columns, lam0: optional feasible warm start.  Returns (..., K)
    weights on the simplex with zeros at invalid columns.
    """
    L = _estimate_L(H, valid)[..., None]
    if lam0 is None:
        # uniform over valid columns
        nv = jnp.maximum(jnp.sum(valid, axis=-1, keepdims=True), 1)
        lam0 = jnp.where(valid, 1.0 / nv, 0.0).astype(g.dtype)
    else:
        lam0 = project_simplex(lam0, valid)

    def grad(lam):
        return jnp.einsum("...kj,...j->...k", H, lam) + g

    def body(_, carry):
        lam, z, t = carry
        lam_new = project_simplex(z - grad(z) / L, valid)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t * t))
        # gradient-scheme restart: if momentum points uphill, reset t
        uphill = jnp.sum((z - lam_new) * (lam_new - lam), axis=-1,
                         keepdims=True) > 0
        t_eff = jnp.where(uphill[..., 0], 1.0, t_new)
        beta = jnp.where(uphill, 0.0, ((t - 1.0) / t_new)[..., None])
        z_new = lam_new + beta * (lam_new - lam)
        return lam_new, z_new, t_eff

    t0 = jnp.ones(g.shape[:-1], g.dtype)
    lam, _, _ = jax.lax.fori_loop(0, iters, body, (lam0, lam0, t0))
    return lam
