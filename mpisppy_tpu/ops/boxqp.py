###############################################################################
# BoxQP: the canonical subproblem form of the TPU framework.
#
# Every scenario subproblem the framework solves (PH prox subproblems,
# Lagrangian bound solves, xhat recourse evaluations, extensive forms) is
# an instance of
#
#     min   c'x + 1/2 x' diag(q) x
#     s.t.  bl <= A x <= bu          (two-sided row constraints)
#           l  <=   x <= u           (box)
#
# This replaces the role Pyomo ConcreteModel + Gurobi play in the
# reference (ref:mpisppy/spopt.py:99-247 dispatches each scenario model
# to a CPU solver).  Here a scenario is a pytree of dense arrays so that
# thousands of scenarios batch into one XLA program: vmap over the
# leading axis maps subproblems onto the MXU, and `q` being diagonal
# makes the PH prox term (rho/2)||x - xbar||^2 an O(n) exact prox.
#
# Equality rows are bl == bu; one-sided rows use +/-inf.  Integrality is
# carried as a mask (`integer`) but relaxed at solve time — the
# reference leans on MIP solvers for exactness (ref:mpisppy/spopt.py:884);
# we use LP relaxation + fix/round heuristics (see algos/xhat*).
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# MXU pass count for solver matvecs.  f32 inputs on TPU decompose into
# bf16 passes: DEFAULT=1 (too coarse for PDHG — stalls ~1e-2 KKT),
# HIGH=3 (bf16x3, relative error ~4e-6 per matvec, measured on v5e),
# HIGHEST=6 (bf16x6, full f32).  Read at trace time; set BEFORE
# building jitted programs via set_matvec_precision().
MATVEC_PRECISION = jax.lax.Precision.HIGHEST

#: The precision-alias registry — the ONE table every knob resolves
#: through (module default, PDHGOptions.iter_precision, the Pallas
#: kernel, the --iter-precision CLI flag).  Pass-count names (bf16x3 /
#: bf16x6) are the preferred spelling in configs and artifacts; the
#: jax.lax.Precision names remain accepted for back-compat.
PRECISION_ALIASES = {
    "bf16": jax.lax.Precision.DEFAULT,
    "default": jax.lax.Precision.DEFAULT,
    "bf16x3": jax.lax.Precision.HIGH,    # 3-pass: halves HBM+MXU work,
    "high": jax.lax.Precision.HIGH,      #   ~4e-6 rel error per matvec
    "bf16x6": jax.lax.Precision.HIGHEST,  # 6-pass: full f32 accuracy
    "highest": jax.lax.Precision.HIGHEST,
    "f32": jax.lax.Precision.HIGHEST,
}


def as_precision(p):
    """Alias / jax.lax.Precision / None -> Precision|None.

    The single parser for every precision knob so aliases/validation
    live in one place.  Unknown strings raise with the full alias list
    — a typo'd --iter-precision must fail at config time, not silently
    trace at the module default."""
    if p is None or isinstance(p, jax.lax.Precision):
        return p
    if not isinstance(p, str):
        raise TypeError(
            f"precision must be None, a jax.lax.Precision, or one of "
            f"{sorted(PRECISION_ALIASES)}; got {p!r}")
    try:
        return PRECISION_ALIASES[p.lower()]
    except KeyError:
        raise ValueError(
            f"unknown precision alias {p!r}; valid aliases: "
            f"{', '.join(sorted(PRECISION_ALIASES))} "
            f"(bf16x3 = 3-pass bf16 iteration matvecs, ~4e-6 relative "
            f"error per matvec; bf16x6 = full-f32 6-pass)") from None


def set_matvec_precision(p) -> None:
    """Set the matvec MXU precision ('high' / 'highest' or a
    jax.lax.Precision).  Captured at trace time by every solver program;
    call before the first jit of the run (changing it later leaves
    already-compiled programs at the old setting).

    WARNING: this default governs EVERYTHING, including KKT residual
    scoring and convergence tests.  Lowering it below HIGHEST lowers the
    achievable KKT floor (HIGH floors at ~1e-5..1e-6 relative, measured
    on sslp-family LPs), so solves with a tighter `tol` will burn
    max_iters without ever certifying done.  To speed up ONLY the
    iteration matvecs while keeping scoring exact — the safe choice —
    use PDHGOptions.iter_precision instead of this setter."""
    global MATVEC_PRECISION
    MATVEC_PRECISION = as_precision(p)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["c", "q", "A", "bl", "bu", "l", "u", "cones"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class BoxQP:
    """One (or, with a leading batch axis, many) box-constrained QP(s).

    Shapes (unbatched): c,q,l,u: (n,); A: (m,n); bl,bu: (m,).
    A batch of S scenarios adds a leading S axis to every field, or — for
    scenario families whose constraint matrix is deterministic (e.g. sslp,
    where only the RHS is random) — `A` may stay (m,n) and broadcast.

    cones: optional ops.cones.ConeSpec partitioning the rows into box
    rows and second-order-cone blocks (shared across the batch — the
    cone PATTERN is deterministic like the ELL sparsity pattern).  SOC
    block rows store their shift b in BOTH bl and bu; see ops/cones.py
    for the full contract.  None (the default) is the pure box problem
    and keeps every hot path on the specialized clip kernels.
    """

    c: Array
    q: Array
    A: Array
    bl: Array
    bu: Array
    l: Array  # noqa: E741
    u: Array
    cones: "object | None" = None

    @property
    def n(self) -> int:
        return self.c.shape[-1]

    @property
    def m(self) -> int:
        return self.A.shape[-2]

    @property
    def batched(self) -> bool:
        return self.c.ndim == 2

    @property
    def nbatch(self) -> int:
        return self.c.shape[0] if self.batched else 1

    def matvec(self, x: Array, precision=None) -> Array:
        """A @ x, batch-aware (A may be shared across the batch, and may
        be an ops.sparse.EllMatrix for sparse constraint matrices).

        Precision: TPU matmuls default to single-pass bf16, whose ~8-bit
        mantissa stalls PDHG around 1e-2 relative KKT residual — verified
        on-chip.  `precision=None` uses the module default
        MATVEC_PRECISION (see set_matvec_precision), a multi-pass bf16
        scheme that restores near-f32 accumulation on the MXU; hot loops
        may pass a cheaper explicit precision (the PDHG iteration body
        runs 3-pass HIGH while restart scoring stays at the default —
        see PDHGOptions.iter_precision).

        The sparse (EllMatrix) path ignores `precision` by design: its
        gather-based matvec runs exact f32 FMAs on the VPU — already
        more accurate than any MXU bf16 pass scheme."""
        prec = MATVEC_PRECISION if precision is None else precision
        if hasattr(self.A, "matvec"):
            return self.A.matvec(x)
        if self.A.ndim == x.ndim + 1:
            return jnp.einsum("...mn,...n->...m", self.A, x,
                              precision=prec)
        # shared A with batched x
        return jnp.einsum("mn,...n->...m", self.A, x, precision=prec)

    def rmatvec(self, y: Array, precision=None) -> Array:
        """A.T @ y, batch-aware (precision: see matvec)."""
        prec = MATVEC_PRECISION if precision is None else precision
        if hasattr(self.A, "rmatvec"):
            return self.A.rmatvec(y)
        if self.A.ndim == y.ndim + 1:
            return jnp.einsum("...mn,...m->...n", self.A, y,
                              precision=prec)
        return jnp.einsum("mn,...m->...n", self.A, y, precision=prec)


def make_boxqp(c, A, bl, bu, l, u, q=None, dtype=jnp.float32,  # noqa: E741
               cones=None) -> BoxQP:
    """Build a BoxQP from numpy-ish inputs, defaulting q to zeros."""
    c = jnp.asarray(c, dtype)
    if q is None:
        q = jnp.zeros_like(c)
    if cones is not None:
        from mpisppy_tpu.ops import cones as cones_mod
        cones_mod.validate_against_bounds(cones, bl, bu)
    return BoxQP(
        c=c,
        q=jnp.asarray(q, dtype),
        A=jnp.asarray(A, dtype),
        bl=jnp.asarray(bl, dtype),
        bu=jnp.asarray(bu, dtype),
        l=jnp.asarray(l, dtype),
        u=jnp.asarray(u, dtype),
        cones=cones,
    )


def objective(p: BoxQP, x: Array) -> Array:
    """c'x + 1/2 x'diag(q)x (sums over the trailing axis only)."""
    return jnp.sum(p.c * x + 0.5 * p.q * x * x, axis=-1)


def dual_objective(p: BoxQP, x: Array, y: Array) -> Array:
    """Fenchel dual value at (y, reduced costs), using x for the Q term.

    For min c'x + 1/2 x'Qx + I_[l,u](x) + I_[bl,bu](Ax) the dual is
        max  -1/2 x'Qx - g*(y) - sup_{l<=z<=u} (-(c+Qx+A'y))'z
    Contributions from infinite bounds against adverse reduced-cost signs
    are excluded here; they show up in the dual residual instead
    (PDLP-style accounting).
    """
    rc = p.c + p.q * x + p.rmatvec(y)
    # -g*(y): y>0 pairs with bu, y<0 with bl (our sign convention:
    # y in dsubgradient of I_[bl,bu] at Ax).  SOC rows need NO special
    # case: they store their shift b in both bl and bu, so this
    # collapses to b*y — exactly -g*(y) for y in the polar cone, which
    # every PDHG iterate satisfies by construction (cones.dual_prox);
    # any distance to the polar cone is charged to the dual residual
    # (kkt_residuals), PDLP-style.
    ycontrib = jnp.where(y > 0.0, p.bu * y, p.bl * y)
    ycontrib = jnp.where(jnp.isfinite(ycontrib), ycontrib, 0.0)
    # reduced-cost bound contribution: rc>0 pairs with l, rc<0 with u.
    rccontrib = jnp.where(rc > 0.0, p.l * rc, p.u * rc)
    rccontrib = jnp.where(jnp.isfinite(rccontrib), rccontrib, 0.0)
    quad = 0.5 * jnp.sum(p.q * x * x, axis=-1)
    return -quad - jnp.sum(ycontrib, axis=-1) + jnp.sum(rccontrib, axis=-1)


def certified_dual_bound(p: BoxQP, x: Array, y: Array) -> Array:
    """A VALID lower bound on the optimal value from ANY iterates (x, y).

    `dual_objective` follows the PDLP accounting convention: adverse
    pairings of a multiplier/reduced cost with an infinite bound are
    zeroed and charged to the dual residual — fine for progress metrics,
    but NOT a bound until the residual clears tolerance.  Branch-and-bound
    pruning (ops/bnb.py) needs a bound that is valid unconditionally (the
    role Gurobi's "bestbound" plays, ref:mpisppy/spopt.py:413-436), so:

      * y is first PROJECTED onto the dual-sign cone implied by one-sided
        rows (y_i >= 0 where bl_i = -inf, y_i <= 0 where bu_i = +inf) —
        any y gives a valid bound, so projecting is free;
      * reduced costs pairing adversely with an infinite box bound send
        the bound to -inf (the honest value of the inner inf), instead of
        being zeroed.

    For convex QPs the bound is the gradient-linearization dual
        f(z) >= -1/2 x'Qx - g*(y) + inf_{l<=z<=u} (c + Qx + A'y)'z ,
    valid for every feasible z by convexity + weak duality.
    """
    if p.cones is not None:
        # SOC blocks: g*(y) = b'y requires y in the polar cone -K;
        # projecting there first is free (any y in the dual domain
        # yields a valid bound) and the bl==bu==b storage then makes
        # the box accounting below exact for these rows (box rows pass
        # through project_polar_rows unchanged).
        from mpisppy_tpu.ops import cones as cones_mod
        y = cones_mod.project_polar_rows(p.cones, y)
    yp = jnp.where(jnp.isfinite(p.bu), y, jnp.minimum(y, 0.0))
    yp = jnp.where(jnp.isfinite(p.bl), yp, jnp.maximum(yp, 0.0))
    gstar = jnp.where(yp > 0.0, p.bu * yp, p.bl * yp)
    gstar = jnp.where(yp == 0.0, 0.0, gstar)  # guard 0 * inf
    rc = p.c + p.q * x + p.rmatvec(yp)
    inf_j = jnp.where(rc > 0.0, p.l * rc, p.u * rc)
    inf_j = jnp.where(rc == 0.0, 0.0, inf_j)  # guard 0 * inf
    quad = 0.5 * jnp.sum(p.q * x * x, axis=-1)
    return -quad - jnp.sum(gstar, axis=-1) + jnp.sum(inf_j, axis=-1)


def primal_residual(p: BoxQP, x: Array) -> Array:
    """Per-row distance of Ax from the row feasible set: [bl, bu] on box
    rows, the shifted second-order cone b + K on SOC blocks (rowwise
    |ax - Proj(ax)|, so the inf-norm reductions downstream are
    uniform).  0 when feasible."""
    ax = p.matvec(x)
    r = jnp.maximum(ax - p.bu, 0.0) + jnp.maximum(p.bl - ax, 0.0)
    if p.cones is not None:
        from mpisppy_tpu.ops import cones as cones_mod
        soc = cones_mod.primal_violation_rows(p.cones, ax, p.bl)
        r = jnp.where(p.cones.is_soc, soc, r)
    return r


def dual_residual(p: BoxQP, x: Array, y: Array) -> Array:
    """Per-column dual infeasibility.

    rc_i > 0 is certified by a finite lower bound, rc_i < 0 by a finite
    upper bound; anything else is residual (PDLP convention).
    """
    rc = p.c + p.q * x + p.rmatvec(y)
    pos_ok = jnp.isfinite(p.l)
    neg_ok = jnp.isfinite(p.u)
    res_pos = jnp.where(pos_ok, 0.0, jnp.maximum(rc, 0.0))
    res_neg = jnp.where(neg_ok, 0.0, jnp.maximum(-rc, 0.0))
    return res_pos + res_neg


def kkt_residuals(p: BoxQP, x: Array, y: Array):
    """(rel_primal, rel_dual, rel_gap) — relative inf-norm KKT residuals.

    Conic problems fold the conic dual-feasibility residual (distance of
    each dual SOC block to the polar cone) into rel_dual, so every
    certificate gate downstream (lagrangian_bound's `certified`, the
    fused planes' dual-residual check, xhat feasibility) automatically
    refuses bounds whose conic Fenchel accounting has not converged."""
    rp = jnp.max(jnp.abs(primal_residual(p, x)), axis=-1)
    rd = jnp.max(jnp.abs(dual_residual(p, x, y)), axis=-1)
    if p.cones is not None:
        from mpisppy_tpu.ops import cones as cones_mod
        rd = jnp.maximum(
            rd, jnp.max(cones_mod.dual_cone_residual_rows(p.cones, y),
                        axis=-1))
    b_scale = jnp.where(jnp.isfinite(p.bl), jnp.abs(p.bl), 0.0)
    b_scale = jnp.maximum(b_scale, jnp.where(jnp.isfinite(p.bu), jnp.abs(p.bu), 0.0))
    c_scale = jnp.max(jnp.abs(p.c), axis=-1, initial=0.0)
    pobj = objective(p, x)
    dobj = dual_objective(p, x, y)
    rel_p = rp / (1.0 + jnp.max(b_scale, axis=-1, initial=0.0))
    rel_d = rd / (1.0 + c_scale)
    rel_g = jnp.abs(pobj - dobj) / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))
    return rel_p, rel_d, rel_g


# --------------------------------------------------------------------------
# Infeasibility / unboundedness certificates.  The reference reads solver
# statuses off Gurobi and aborts or marks subproblems
# (ref:mpisppy/spopt.py:76-96,194-231); a first-order kernel must certify
# these itself from (approximate) rays, per-batch-element.
# --------------------------------------------------------------------------
def infeasibility_certificate(p: BoxQP, y: Array, tol: float = 1e-6) -> Array:
    """True where `y` certifies primal infeasibility (Farkas).

    {bl<=Ax<=bu, l<=x<=u} is infeasible iff some y has
        q(y) = inf_{l<=x<=u} (A'y)'x - sup_{bl<=v<=bu} y'v  >  0.
    Components pairing a nonzero multiplier with an infinite bound drive
    q to -inf (no certificate).  `y` is normalized here; the test is
    q(y)/||y||_1 > tol.
    """
    if p.cones is not None:
        # On SOC blocks sup_{v in b+K} y'v is b'y only for y in the
        # polar cone (else +inf); treating the bl==bu storage as an
        # equality row would UNDERSTATE the sup and could fabricate a
        # Farkas certificate for a feasible conic problem.  Projecting
        # y onto the polar cone first keeps the test exact (any polar
        # y is a legitimate Farkas candidate; box rows pass through).
        from mpisppy_tpu.ops import cones as cones_mod
        y = cones_mod.project_polar_rows(p.cones, y)
    nrm = jnp.sum(jnp.abs(y), axis=-1, keepdims=True)
    yn = y / jnp.maximum(nrm, 1e-30)
    z = p.rmatvec(yn)
    # Entries of z below the f32 rounding floor of A'y are treated as
    # zero so huge-but-irrelevant box bounds don't kill the certificate;
    # the potential contribution of every dropped FINITE-bound column is
    # added back into the acceptance threshold below, so dropping cannot
    # manufacture a certificate.  (Columns with an infinite bound and a
    # true |z_j| <= ztol remain a ztol-level approximation — inherent to
    # certifying from approximate rays.)
    ztol = 32.0 * jnp.finfo(z.dtype).eps
    drop = jnp.abs(z) <= ztol
    z = jnp.where(drop, 0.0, z)
    inf_j = jnp.where(z > 0.0, z * p.l, z * p.u)
    inf_j = jnp.where(z == 0.0, 0.0, inf_j)
    sup_i = jnp.where(yn > 0.0, yn * p.bu, yn * p.bl)
    sup_i = jnp.where(yn == 0.0, 0.0, sup_i)
    bad = (~jnp.isfinite(inf_j)).any(axis=-1) | (~jnp.isfinite(sup_i)).any(axis=-1)
    qval = jnp.sum(inf_j, axis=-1) - jnp.sum(sup_i, axis=-1)
    absl = jnp.where(jnp.isfinite(p.l), jnp.abs(p.l), 0.0)
    absu = jnp.where(jnp.isfinite(p.u), jnp.abs(p.u), 0.0)
    dropped_err = jnp.sum(
        jnp.where(drop, ztol * jnp.maximum(absl, absu), 0.0), axis=-1)
    # scale-aware threshold: q is a difference of potentially large
    # cancelling sums, so floating-point noise is O(eps * sum|terms|) —
    # an absolute test would false-positive on problems with big bounds
    scale = 1.0 + jnp.sum(jnp.abs(inf_j), axis=-1) \
        + jnp.sum(jnp.abs(sup_i), axis=-1)
    return ~bad & (qval > tol * scale + dropped_err) & (nrm[..., 0] > 1e-30)


def unboundedness_certificate(p: BoxQP, d: Array, tol: float = 1e-6) -> Array:
    """True where direction `d` certifies an unbounded objective:
    d is a recession direction of the feasible set with c'd < 0 (and no
    quadratic curvature along d)."""
    nrm = jnp.sum(jnp.abs(d), axis=-1, keepdims=True)
    dn = d / jnp.maximum(nrm, 1e-30)
    ad = p.matvec(dn)
    row_ok = jnp.where(jnp.isfinite(p.bu), ad <= tol, True) \
        & jnp.where(jnp.isfinite(p.bl), ad >= -tol, True)
    if p.cones is not None:
        # recession cone of b + K is K itself: the direction's block
        # must (approximately) lie in the cone, not vanish (the bl==bu
        # box test would demand |ad| <= tol — a strict subset of K that
        # misses genuine conic recession rays)
        from mpisppy_tpu.ops import cones as cones_mod
        soc_dist = jnp.abs(ad - cones_mod.project_soc_rows(p.cones, ad))
        row_ok = jnp.where(p.cones.is_soc, soc_dist <= tol, row_ok)
    ok_rows = jnp.all(row_ok, axis=-1)
    ok_box = jnp.all(
        jnp.where(jnp.isfinite(p.u), dn <= tol, True)
        & jnp.where(jnp.isfinite(p.l), dn >= -tol, True), axis=-1)
    no_curv = jnp.sum(p.q * dn * dn, axis=-1) <= tol
    # Descent threshold is COST-SCALE relative: with large |c|, stray
    # ray components of size ~tol (which ok_box/ok_rows tolerate) times
    # big coefficients would fake a descent direction on a bounded
    # problem (observed: a zero-cost free column plus tol-sized noise
    # certified "unbounded").  A true recession ray's descent rate is
    # proportional to the cost scale, so nothing real is lost.
    cscale = 1.0 + jnp.max(jnp.abs(p.c), axis=-1)
    descent = jnp.sum(p.c * dn, axis=-1) < -tol * cscale
    return ok_rows & ok_box & no_curv & descent & (nrm[..., 0] > 1e-30)


# --------------------------------------------------------------------------
# Ruiz equilibration.  The reference delegates conditioning to Gurobi;
# first-order methods need it done explicitly (cf. PDLP).  Performed in
# numpy at problem-build time (not traced).
# --------------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Scaling:
    """x_orig = d_col * x_scaled ; y_orig = d_row * y_scaled."""

    d_row: np.ndarray
    d_col: np.ndarray


def group_row_scales(rmax: np.ndarray, cones) -> np.ndarray:
    """Force row scale factors UNIFORM within each SOC block (the block
    max): per-row scaling D v of a block breaks ||z|| <= t unless D is
    a positive multiple of the identity on the block, while a shared
    scale maps b + K to (d b) + K exactly.  Box rows keep their own
    scale.  rmax: (..., m) positive row maxima."""
    if cones is None:
        return rmax
    seg = np.asarray(cones.seg)
    is_soc = np.asarray(cones.is_soc)
    C = cones.num_cones + 1
    m = rmax.shape[-1]
    bshape = rmax.shape[:-1]
    B = int(np.prod(bshape)) if bshape else 1
    flat = rmax.reshape(B, m)
    blk = np.zeros((B, C), flat.dtype)
    np.maximum.at(blk, (np.repeat(np.arange(B), m), np.tile(seg, B)),
                  flat.reshape(-1))
    grouped = np.where(is_soc[None, :], blk[:, seg], flat)
    return grouped.reshape(rmax.shape)


def ruiz_scale(p: BoxQP, iters: int = 10) -> tuple[BoxQP, Scaling]:
    """Iterative row/col inf-norm equilibration of A, applied to the
    whole problem.  Batched A gets per-batch scalings.  Dispatches to
    the ELL-form loop for sparse A (ops.sparse.ruiz_scale_ell).  SOC
    blocks get block-uniform row scales (see group_row_scales)."""
    from mpisppy_tpu.ops import sparse as sparse_mod
    dt = p.c.dtype
    if isinstance(p.A, sparse_mod.EllMatrix):
        vals, dr, dc = sparse_mod.ruiz_scale_ell(
            np.asarray(p.A.vals), np.asarray(p.A.cols), p.A.n, iters,
            cones=p.cones)
        A_scaled = dataclasses.replace(p.A, vals=jnp.asarray(vals, dt))
    else:
        A = np.asarray(p.A, np.float64)
        dr = np.ones(A.shape[:-1], A.dtype)
        dc = np.ones(A.shape[:-2] + (A.shape[-1],), A.dtype)
        for _ in range(iters):
            # all-zero rows/cols (e.g. a variable absent from every
            # constraint in some scenario) keep scale 1: flooring at a
            # tiny epsilon instead would compound 1/sqrt(eps) per sweep
            # into an inf scaling
            rmax = np.max(np.abs(A), axis=-1)
            rmax = np.where(rmax <= 0.0, 1.0, rmax)
            rmax = group_row_scales(rmax, p.cones)
            A = A / np.sqrt(rmax)[..., None]
            dr = dr / np.sqrt(rmax)
            cmax = np.max(np.abs(A), axis=-2)
            cmax = np.where(cmax <= 0.0, 1.0, cmax)
            A = A / np.sqrt(cmax)[..., None, :]
            dc = dc / np.sqrt(cmax)
        A_scaled = jnp.asarray(A, dt)
    scaled = BoxQP(
        cones=p.cones,
        c=jnp.asarray(np.asarray(p.c, np.float64) * dc, dt),
        q=jnp.asarray(np.asarray(p.q, np.float64) * dc * dc, dt),
        A=A_scaled,
        bl=jnp.asarray(np.asarray(p.bl, np.float64) * dr, dt),
        bu=jnp.asarray(np.asarray(p.bu, np.float64) * dr, dt),
        l=jnp.asarray(np.asarray(p.l, np.float64) / dc, dt),
        u=jnp.asarray(np.asarray(p.u, np.float64) / dc, dt),
    )
    return scaled, Scaling(d_row=dr, d_col=dc)
