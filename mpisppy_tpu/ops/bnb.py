###############################################################################
# Batched branch-and-bound on the PDHG LP/QP kernel: the exact-MIP path.
#
# The reference gets exact integer solves from Gurobi/CPLEX per scenario
# subproblem (ref:mpisppy/spopt.py:99-247,884) — sslp/sizes/netdes/uc are
# MIPs, and PH/Lagrangian/xhat all lean on those exact solves.  A TPU
# framework has no MIP solver to call, so this module IS one, built
# TPU-first:
#
#   * The batch axis is scenarios: every round pops the best-first open
#     node of EVERY scenario's tree and solves all of those LP
#     relaxations as ONE batched PDHG call.  S scenario MIPs advance in
#     lockstep as a single tensor program — the analog of the
#     reference's per-rank sequential Gurobi loop is a (S,)-shaped
#     best-first step.
#   * All control flow is masked tensor math over a fixed-size node pool
#     (static shapes; no per-scenario Python).  The host only runs the
#     outer round loop and checks the (S,) done mask.
#   * Pruning uses ops.boxqp.certified_dual_bound — valid for ANY
#     iterates by weak duality — so inexact first-order LP solves can
#     never fathom the true optimum.  The reported outer bound folds in
#     every fathomed/dropped subtree's bound, making the final
#     (inner, outer) bracket a certificate, not a heuristic.
#   * Incumbents come from an integer-feasible leaf (all-integral LP
#     vertex, or a dive node with every integer column fixed), accepted
#     only when the LP's primal residual clears `feas_tol` — the same
#     standard any LP-based MIP solver certifies feasibility to.
#
# The dive heuristic (`dive`) is the cheaper fix-and-round path: rounds
# of "fix all near-integral columns (+ the most integral fractional
# one), re-solve" until everything integer is pinned — one incumbent in
# O(tens) of batched LP solves.  solve_mip() runs it first so
# branch-and-bound starts with a finite incumbent.
#
# Node state per (scenario, pool slot): ORIGINAL-space lower/upper
# bounds of the integer columns only (the continuous box never changes),
# plus the subtree's certified bound.  Ruiz column scalings map the
# integral branching values into the scaled space the kernel solves in.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.ops import boxqp, pdhg
from mpisppy_tpu.telemetry import console as _console
from mpisppy_tpu.ops.boxqp import BoxQP

Array = jax.Array

# swap_rounds the POLISH entry points (mip.evaluate_mip_polished,
# final-candidate certification like sslp_cert) enable explicitly —
# the round-5-measured budget that was briefly the global default
# before the hot Lagrangian-oracle cost moved it here.
POLISH_SWAP_ROUNDS = 24


@dataclasses.dataclass(frozen=True)
class BnBOptions:
    """Static branch-and-bound options (hashable: jit-static).

    The node-LP defaults are LOOSER than the standalone kernel's
    (tol 1e-5, 8k iters): certified_dual_bound stays valid at any
    tolerance, so inexact node solves only weaken pruning, never
    correctness — and warm-started children rarely need more.  feas_tol
    and int_tol sit an order above the LP tol so incumbents found at
    that tolerance are actually accepted."""

    gap_tol: float = 1e-3       # terminate at (inner-outer) <= gap_tol*scale
    int_tol: float = 1e-4       # max |x - round(x)| to accept integrality
    feas_tol: float = 1e-4      # relative primal residual for incumbents
    pool_size: int = 64         # open-node slots per scenario
    max_rounds: int = 400       # outer (host) round budget
    dive_rounds: int = 16       # confident-wave rounds in the dive pass
    dive_tol: float = 0.1       # "near-integral" fixing threshold
    dive_tail: int = 96         # one-at-a-time rounds for ambiguous cols
    # nearly-integral branched nodes (maxfrac <= pin_frac_tol) ALSO
    # enqueue a "pin" probe with all integer columns fixed at their
    # (half-up) rounding: its solve yields an EXACT incumbent.  Keep the
    # gate tight: probing every node (1.0) burns ~half the plunge
    # rounds on infeasible roundings of mid-face iterates.
    pin_frac_tol: float = 0.05
    # plunge tie tolerance (relative): nodes within this of the best
    # bound count as tied, and the DEEPEST tied node is popped — turning
    # degenerate tied regions into a dive (see bnb_round selection).
    # Only the SEARCH ORDER is affected (fathoming uses exact bounds),
    # so this is safe to loosen on heavily degenerate problems.
    plunge_tol: float = 1e-3
    # objective-feasibility-pump rounds run after the dive for
    # incumbents (0 disables); the pump handles the capacity-coupled
    # degenerate structures where rounding-based dives stall
    pump_rounds: int = 25
    # dual-guided SOS1 swap-repair rounds on integral incumbents
    # (0 disables): each round proposes ONE winner swap per scenario —
    # the group move with the most negative reduced-cost delta read off
    # the all-fixed LP's duals — evaluates it exactly with a warm
    # re-solve, and keeps it only where the true objective improved.
    # This closes the assignment-quality gap dive/B&B incumbents leave
    # on SOS1-structured recourse (sslp_15_45_5 at the optimal first
    # stage: -255.8 -> toward the true -262.4, measured round 5).
    # Default 0 = AUTO: on SOS1-structured models the repair costs up
    # to ~2*swap_rounds warm node re-solves per solve_mip call, which
    # the hot Lagrangian-oracle loops (mip.lagrangian_mip_bound /
    # mip_dual_bundle) pay every step for a polish aimed at final
    # candidates — so auto means OFF everywhere except the polish
    # entry points (mip.evaluate_mip / evaluate_mip_polished), which
    # promote it to POLISH_SWAP_ROUNDS.  An explicit POSITIVE value is
    # honored verbatim everywhere; a NEGATIVE value forces the repair
    # off even in polish contexts (sos1_swap_repair no-ops on <= 0).
    swap_rounds: int = 0
    # deterministic relative objective jitter for the NODE SOLVES ONLY:
    # breaks degenerate ties so the kernel's face-point iterates move
    # toward a unique vertex.  Bounds and objectives are always
    # evaluated against the TRUE costs, so correctness is unaffected.
    # Default OFF: at jitter below the LP tolerance the solver cannot
    # resolve the perturbation anyway (measured on sslp), and larger
    # jitters distort the search.
    jitter: float = 0.0
    lp: pdhg.PDHGOptions = pdhg.PDHGOptions(tol=1e-5, max_iters=8_000)


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["pool_lo", "pool_hi", "pool_bound", "pool_active",
                 "pool_depth",
                 "incumbent", "x_inc", "fathom_floor", "lost_bound",
                 "x_warm", "y_warm", "omega_warm", "Lnorm",
                 "outer", "done", "nodes_solved"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class BnBState:
    pool_lo: Array       # (S, P, nI) original-space int lower bounds
    pool_hi: Array       # (S, P, nI)
    pool_bound: Array    # (S, P) certified subtree lower bound (+inf empty)
    pool_active: Array   # (S, P) bool
    pool_depth: Array    # (S, P) int32 tree depth (plunge tie-break)
    incumbent: Array     # (S,) best integer-feasible objective (+inf none)
    x_inc: Array         # (S, n) incumbent solution, ORIGINAL space
    fathom_floor: Array  # (S,) min bound over fathomed subtrees (+inf)
    lost_bound: Array    # (S,) min bound over pool-overflow drops (+inf)
    x_warm: Array        # (S, n) scaled-space warm start
    y_warm: Array        # (S, m)
    omega_warm: Array    # (S,) adapted PDHG primal weight, carried over
    Lnorm: Array         # (S,) ||A||_2 (bounds never change A: computed once)
    outer: Array         # (S,) certified global lower bound
    done: Array          # (S,) bool
    nodes_solved: Array  # (S,) int32


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["x", "inner", "outer", "gap", "feasible", "nodes_solved"],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class BnBResult:
    x: Array            # (S, n) best integer solution, ORIGINAL space
    inner: Array        # (S,) its objective (+inf if none found)
    outer: Array        # (S,) certified lower bound
    gap: Array          # (S,) relative certified gap
    feasible: Array     # (S,) bool — an integer-feasible point was found
    nodes_solved: Array  # (S,) int32


def _node_qp(qp: BoxQP, d_col: Array, int_cols: Array,
             lo: Array, hi: Array) -> BoxQP:
    """Base qp with the integer columns' box replaced by the node's
    ORIGINAL-space [lo, hi] (mapped through the column scaling)."""
    S, n = qp.c.shape
    l_full = jnp.broadcast_to(qp.l, (S, n))
    u_full = jnp.broadcast_to(qp.u, (S, n))
    d_int = jnp.broadcast_to(d_col, (S, n))[:, int_cols]
    return dataclasses.replace(
        qp,
        l=l_full.at[:, int_cols].set(lo / d_int),
        u=u_full.at[:, int_cols].set(hi / d_int),
    )


def _solve_node(qp_node: BoxQP, x_warm: Array, y_warm: Array,
                lp_opts: pdhg.PDHGOptions,
                omega: Array | None = None, Lnorm: Array | None = None,
                jitter: float = 0.0):
    """Batched LP solve of the current nodes, warm-started (iterates AND
    step-size machinery: omega adaptation + the one-time ||A|| estimate
    carry across nodes, since branching only moves bounds, never A).

    `jitter` perturbs the SOLVE's costs by a fixed pseudorandom relative
    amount to break degeneracy (vertex-steering, see BnBOptions.jitter);
    the returned objective, certified bound, and residuals are all
    evaluated against the TRUE qp_node, so every number downstream
    remains exact.
    Returns (solver_state, objective, certified_lb, primal_residual)."""
    lp = dataclasses.replace(lp_opts, detect_infeas=True)
    if jitter > 0.0:
        # PER-ROW draws: tiled multistart copies of the same scenario
        # (dive_multistart) get different tie-breaks from the same key
        u = jax.random.uniform(jax.random.PRNGKey(17),
                               qp_node.c.shape, qp_node.c.dtype)
        cscale = jnp.maximum(jnp.mean(jnp.abs(qp_node.c), axis=-1,
                                      keepdims=True), 1.0)
        qp_solve = dataclasses.replace(
            qp_node, c=qp_node.c + jitter * cscale * (u - 0.5))
    else:
        qp_solve = qp_node
    x0 = jnp.clip(x_warm, qp_node.l, qp_node.u)
    if omega is None or Lnorm is None:
        st0 = pdhg.init_state(qp_solve, lp, x0=x0, y0=y_warm)
    else:
        bs = qp_node.c.shape[:-1]
        dt = qp_node.c.dtype
        st0 = pdhg.PDHGState(
            x=x0, y=y_warm,
            x_sum=jnp.zeros_like(x0), y_sum=jnp.zeros_like(y_warm),
            x_anchor=x0, y_anchor=y_warm,
            omega=omega, Lnorm=Lnorm,
            k=jnp.zeros((), jnp.int32), nwin=jnp.zeros(bs, jnp.int32),
            restart_score=jnp.full(bs, jnp.inf, dt),
            score=jnp.full(bs, jnp.inf, dt),
            done=jnp.zeros(bs, bool), status=jnp.zeros(bs, jnp.int32),
            guard_resets=jnp.zeros(bs, jnp.int32))
    sol = pdhg.solve(qp_solve, lp, st0)
    obj = jnp.sum(qp_node.c * sol.x + 0.5 * qp_node.q * sol.x * sol.x,
                  axis=-1)
    lb = boxqp.certified_dual_bound(qp_node, sol.x, sol.y)
    rp, _, _ = boxqp.kkt_residuals(qp_node, sol.x, sol.y)
    return sol, obj, lb, rp


# _solve_node for HOST-LOOP call sites (feasibility_pump's root/pin
# evaluations, sos1_swap_repair's baseline solve).  Called eagerly, the
# pdhg while_loop would close over the QP's VALUES as jaxpr constants
# and XLA would compile a fresh `while` executable per call — ~2 silent
# recompiles per pump round, found by the dispatch compile guard
# (docs/dispatch.md).  The jit keys on shapes + the static opts instead.
_solve_node_jit = partial(jax.jit,
                          static_argnames=("lp_opts", "jitter"))(_solve_node)


@partial(jax.jit, static_argnames=("opts",))
def bnb_round(qp: BoxQP, d_col: Array, int_cols: Array, st: BnBState,
              opts: BnBOptions) -> BnBState:
    """One best-first round: pop each scenario's lowest-bound open node,
    solve the batch of LP relaxations, then fathom/branch per scenario."""
    S, P, nI = st.pool_lo.shape
    dt = qp.c.dtype
    inf = jnp.asarray(jnp.inf, dt)

    # PLUNGING selection: among active nodes whose bound ties the best
    # (within a relative epsilon), pop the DEEPEST.  Pure best-first
    # wanders across the many equal-bound siblings a degenerate LP
    # produces and can burn its whole round budget without ever
    # reaching an integral leaf (observed on sslp recourse MIPs); the
    # depth bias turns tied regions into a dive while keeping exact
    # best-first behavior across genuinely different bounds.
    key = jnp.where(st.pool_active, st.pool_bound, inf)
    bmin = jnp.min(key, axis=1, keepdims=True)
    tie_eps = opts.plunge_tol * jnp.maximum(1.0, jnp.abs(bmin))
    thresh = jnp.where(jnp.isfinite(bmin), bmin + tie_eps, inf)
    tied = st.pool_active & (key <= thresh)
    sel = jnp.argmax(jnp.where(tied, st.pool_depth, -1), axis=1)  # (S,)
    has = jnp.any(st.pool_active, axis=1) & ~st.done    # (S,)
    sel_oh = jax.nn.one_hot(sel, P, dtype=bool)         # (S, P)

    def take2(a):  # (S, P, nI) -> (S, nI)
        return jnp.take_along_axis(a, sel[:, None, None], axis=1)[:, 0]

    lo = take2(st.pool_lo)
    hi = take2(st.pool_hi)
    parent = jnp.take_along_axis(st.pool_bound, sel[:, None], axis=1)[:, 0]

    qpn = _node_qp(qp, d_col, int_cols, lo, hi)
    sol, obj, lb, rp = _solve_node(qpn, st.x_warm, st.y_warm, opts.lp,
                                   st.omega_warm, st.Lnorm,
                                   jitter=opts.jitter)
    box_ok = jnp.all(lo <= hi, axis=1)
    infeas = (sol.status == pdhg.INFEASIBLE) | ~box_ok
    lb = jnp.where(infeas, inf, jnp.maximum(lb, parent))

    x_orig = sol.x * jnp.broadcast_to(d_col, sol.x.shape)
    xi = x_orig[:, int_cols]
    frac = jnp.abs(xi - jnp.round(xi))
    maxfrac = jnp.max(frac, axis=1)
    feas = rp <= opts.feas_tol
    is_int = has & (maxfrac <= opts.int_tol) & feas & ~infeas

    # -- incumbent ---------------------------------------------------------
    better = is_int & (obj < st.incumbent)
    incumbent = jnp.where(better, obj, st.incumbent)
    x_inc = jnp.where(better[:, None], x_orig, st.x_inc)

    # -- fathoming ---------------------------------------------------------
    scale = jnp.maximum(1.0, jnp.abs(incumbent))
    thresh = jnp.where(jnp.isfinite(incumbent),
                       incumbent - opts.gap_tol * scale, inf)
    prune = has & ~is_int & ~infeas & (lb >= thresh)
    fathomed = has & (is_int | prune)           # subtree closed with bound lb
    fathom_floor = jnp.where(fathomed, jnp.minimum(st.fathom_floor, lb),
                             st.fathom_floor)
    branch = has & ~is_int & ~prune & ~infeas

    # -- branch: child_down replaces the popped slot, child_up goes to a
    #    free slot (or evicts the worst open node, logging its bound) ------
    jstar = jnp.argmax(frac, axis=1)                    # (S,)
    j_oh = jax.nn.one_hot(jstar, nI, dtype=bool)
    v = jnp.take_along_axis(xi, jstar[:, None], axis=1)[:, 0]
    fl = jnp.floor(v)
    hi_down = jnp.where(j_oh, fl[:, None], hi)
    lo_up = jnp.where(j_oh, fl[:, None] + 1.0, lo)
    # plunge ordering: the popped slot inherits the ROUNDED side (the
    # depth tie-break prefers lower slot indices, so the sel slot leads
    # the dive) — branching toward the iterate's rounding is the dive
    # direction that tends to stay feasible
    round_up = (v - fl) >= 0.5
    sel_lo = jnp.where(round_up[:, None], lo_up, lo)
    sel_hi = jnp.where(round_up[:, None], hi, hi_down)
    oth_lo = jnp.where(round_up[:, None], lo, lo_up)
    oth_hi = jnp.where(round_up[:, None], hi_down, hi)

    # write child_down into slot `sel` (or deactivate it when fathomed)
    depth = jnp.take_along_axis(st.pool_depth, sel[:, None], axis=1)[:, 0]
    child_depth = depth + 1
    m_sel = sel_oh & branch[:, None]
    pool_hi = jnp.where(m_sel[:, :, None], sel_hi[:, None, :], st.pool_hi)
    pool_lo = jnp.where(m_sel[:, :, None], sel_lo[:, None, :], st.pool_lo)
    pool_bound = jnp.where(m_sel, lb[:, None], st.pool_bound)
    pool_depth = jnp.where(m_sel, child_depth[:, None], st.pool_depth)
    closed = sel_oh & (has & ~branch)[:, None]
    pool_active = st.pool_active & ~closed

    # free slot for child_up: first inactive, else evict worst open node
    any_free = jnp.any(~pool_active, axis=1)
    first_free = jnp.argmin(pool_active, axis=1)        # first False
    worst = jnp.argmax(jnp.where(pool_active, pool_bound, -inf), axis=1)
    slot_up = jnp.where(any_free, first_free, worst)
    up_oh = jax.nn.one_hot(slot_up, P, dtype=bool) & branch[:, None]
    evict = branch & ~any_free
    evicted_bound = jnp.take_along_axis(pool_bound, worst[:, None],
                                        axis=1)[:, 0]
    lost_bound = jnp.where(evict, jnp.minimum(st.lost_bound, evicted_bound),
                           st.lost_bound)
    pool_lo = jnp.where(up_oh[:, :, None], oth_lo[:, None, :], pool_lo)
    pool_hi = jnp.where(up_oh[:, :, None], oth_hi[:, None, :], pool_hi)
    pool_bound = jnp.where(up_oh, lb[:, None], pool_bound)
    pool_depth = jnp.where(up_oh, child_depth[:, None], pool_depth)
    pool_active = pool_active | up_oh

    # -- pin probe: near-integral branched nodes also enqueue the fully
    #    rounded assignment (exact incumbent when popped); only into a
    #    genuinely free slot — probes never evict real nodes ---------------
    want_pin = branch & (maxfrac <= opts.pin_frac_tol)
    free_pin = jnp.any(~pool_active, axis=1)
    slot_pin = jnp.argmin(pool_active, axis=1)
    pin_oh = jax.nn.one_hot(slot_pin, P, dtype=bool) \
        & (want_pin & free_pin)[:, None]
    r_pin = jnp.clip(jnp.floor(xi + 0.5), lo, hi)
    pool_lo = jnp.where(pin_oh[:, :, None], r_pin[:, None, :], pool_lo)
    pool_hi = jnp.where(pin_oh[:, :, None], r_pin[:, None, :], pool_hi)
    pool_bound = jnp.where(pin_oh, lb[:, None], pool_bound)
    # probes outrank both children in the plunge order
    pool_depth = jnp.where(pin_oh, child_depth[:, None] + 1, pool_depth)
    pool_active = pool_active | pin_oh

    # -- certified global outer bound + termination ------------------------
    open_min = jnp.min(jnp.where(pool_active, pool_bound, inf), axis=1)
    outer = jnp.minimum(jnp.minimum(open_min, fathom_floor),
                        jnp.minimum(lost_bound, incumbent))
    gap_ok = (incumbent - outer) <= opts.gap_tol \
        * jnp.maximum(1.0, jnp.abs(incumbent))
    done = st.done | ~jnp.any(pool_active, axis=1) \
        | (jnp.isfinite(incumbent) & gap_ok)

    return BnBState(
        pool_lo=pool_lo, pool_hi=pool_hi, pool_bound=pool_bound,
        pool_active=pool_active, pool_depth=pool_depth,
        incumbent=incumbent, x_inc=x_inc,
        fathom_floor=fathom_floor, lost_bound=lost_bound,
        x_warm=sol.x, y_warm=sol.y, omega_warm=sol.omega, Lnorm=st.Lnorm,
        outer=outer, done=done,
        nodes_solved=st.nodes_solved + has.astype(jnp.int32),
    )


# --------------------------------------------------------------------------
# Objective feasibility pump (Fischetti-Glover-Lodi; objective variant of
# Achterberg-Berthold — implemented from the papers' math).  Diving fails
# on problems whose LP keeps a ~constant pool of fractional ties no
# matter how many columns are pinned (sslp's capacity-coupled assignment
# rows); the pump instead alternates
#     x_lp  = argmin  (alpha) c'x + (1-alpha) dist(x, x_int)
#     x_int = round(x_lp)                 (half-up)
# with alpha decaying, where dist is the linear L1-to-rounding over the
# integer columns (exact for binaries).  Cycles break by flipping the
# most fractional entries.  Every iteration is ONE batched warm LP solve.
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("opts",))
def pump_round(qp: BoxQP, d_col: Array, int_cols: Array, xint: Array,
               alpha: Array, x_warm: Array, y_warm: Array,
               omega: Array, Lnorm: Array, opts: BnBOptions):
    """One pump iteration at mixing weight alpha ((S,) in [0,1]).
    Returns (xi, frac, x, y, omega) where xi is the new LP's integer
    columns in original space."""
    S, n = qp.c.shape
    d_int = jnp.broadcast_to(d_col, (S, n))[:, int_cols]
    # distance objective in SCALED space: d/dx |d*x - xint| = ±d
    lo_side = xint <= jnp.broadcast_to(
        jnp.ceil(jnp.broadcast_to(qp.l, (S, n))[:, int_cols]
                 * d_int - 1e-6), xint.shape)
    sgn = jnp.where(lo_side, 1.0, -1.0)                 # binaries: exact
    c_dist = jnp.zeros((S, n), qp.c.dtype).at[
        :, int_cols].set(sgn * d_int)
    cn = qp.c / jnp.maximum(
        jnp.linalg.norm(qp.c, axis=-1, keepdims=True), 1e-12)
    dn = c_dist / jnp.maximum(
        jnp.linalg.norm(c_dist, axis=-1, keepdims=True), 1e-12)
    a = alpha[:, None]
    qp_pump = dataclasses.replace(qp, c=a * cn + (1.0 - a) * dn)
    sol, _, _, _ = _solve_node(qp_pump, x_warm, y_warm, opts.lp,
                               omega, Lnorm)
    x_orig = sol.x * jnp.broadcast_to(d_col, sol.x.shape)
    xi = x_orig[:, int_cols]
    frac = jnp.abs(xi - jnp.round(xi))
    return xi, frac, sol.x, sol.y, sol.omega


def feasibility_pump(qp: BoxQP, d_col: Array, int_cols: Array,
                     opts: BnBOptions = BnBOptions(),
                     rounds: int = 40, alpha_decay: float = 0.85,
                     x_warm: Array | None = None,
                     y_warm: Array | None = None,
                     omega: Array | None = None,
                     Lnorm: Array | None = None):
    """Batched objective feasibility pump.  Returns (value (S,),
    x (S, n) original space, feasible (S,)) for the BEST integer point
    each scenario's pump visited (evaluated by pinning the rounding and
    solving the true-objective LP — certified like any incumbent)."""
    int_cols = jnp.asarray(int_cols, jnp.int32)
    S, n = qp.c.shape
    dt = qp.c.dtype
    if x_warm is None:
        x_warm = jnp.clip(jnp.zeros((S, n), dt), qp.l, qp.u)
    if y_warm is None:
        y_warm = jnp.zeros((S, qp.m), dt)
    if omega is None:
        omega = jnp.full((S,), opts.lp.omega0, dt)
    if Lnorm is None:
        Lnorm = pdhg.estimate_norm(qp, opts.lp.power_iters).astype(dt)

    lo0, hi0 = _root_bounds(qp, d_col, np.asarray(int_cols))
    lo0 = jnp.asarray(lo0, dt)
    hi0 = jnp.asarray(hi0, dt)
    # root LP under the true objective seeds the rounding
    qpr = _node_qp(qp, d_col, int_cols, lo0, hi0)
    sol, _, _, _ = _solve_node_jit(qpr, x_warm, y_warm, opts.lp, omega,
                                   Lnorm)
    x_warm, y_warm, omega = sol.x, sol.y, sol.omega
    xi = (sol.x * jnp.broadcast_to(d_col, sol.x.shape))[:, int_cols]
    xint = jnp.clip(jnp.floor(xi + 0.5), lo0, hi0)

    best_val = jnp.full((S,), jnp.inf, dt)
    best_x = jnp.zeros((S, n), dt)
    alpha = jnp.ones((S,), dt)
    prev_key = None
    rng = np.random.RandomState(23)
    for r in range(rounds):
        alpha = alpha * alpha_decay
        xi, frac, x_warm, y_warm, omega = pump_round(
            qp, d_col, int_cols, xint, alpha, x_warm, y_warm, omega,
            Lnorm, opts)
        new_xint = jnp.clip(jnp.floor(xi + 0.5), lo0, hi0)
        # evaluate the CURRENT rounding: ONE true-objective solve of the
        # fully pinned LP
        qp_pin = _node_qp(qp, d_col, int_cols, new_xint, new_xint)
        psol, pobj, _, prp = _solve_node_jit(qp_pin, x_warm, y_warm,
                                             opts.lp, omega, Lnorm)
        p_feas = (prp <= opts.feas_tol) \
            & (psol.status != pdhg.INFEASIBLE) \
            & (psol.status != pdhg.UNBOUNDED)
        val = jnp.where(p_feas, pobj, jnp.inf)
        x_f = psol.x * jnp.broadcast_to(d_col, psol.x.shape)
        better = val < best_val
        best_val = jnp.where(better, val, best_val)
        best_x = jnp.where(better[:, None], x_f, best_x)
        # cycle break: if the rounding did not change, flip the most
        # fractional entries (deterministic count, seeded)
        key_now = np.asarray(new_xint)
        if prev_key is not None and np.array_equal(key_now, prev_key):
            nflip = 1 + rng.randint(0, 4)
            fr = np.asarray(frac)
            idx = np.argsort(-fr, axis=1)[:, :nflip]
            flip = np.array(key_now)
            for s in range(S):
                cols = idx[s]
                lo_s = np.asarray(lo0)[s, cols]
                hi_s = np.asarray(hi0)[s, cols]
                flip[s, cols] = np.where(flip[s, cols] <= lo_s,
                                         np.minimum(lo_s + 1, hi_s),
                                         np.maximum(flip[s, cols] - 1,
                                                    lo_s))
            new_xint = jnp.asarray(flip, dt)
        prev_key = np.asarray(new_xint)
        xint = new_xint
        if bool(np.all(np.isfinite(np.asarray(best_val)))) \
                and bool(np.all(np.asarray(frac).max(axis=1) < 1e-3)):
            break
    return best_val, best_x, jnp.isfinite(best_val)


# --------------------------------------------------------------------------
# Dive heuristic: fix-and-round to a full integer assignment.
# --------------------------------------------------------------------------
def detect_sos1_groups(qp: BoxQP, d_col: Array, int_cols: Array):
    """Host-side detection of SOS1-like equality rows: bl == bu, every
    nonzero coefficient on an INTEGER column, and (in ORIGINAL space)
    each coefficient equal to the row rhs — i.e. rows of the shape
    sum_j y_j = h with y binary, h in {0, 1}.  The assignment rows of
    sslp-type models are exactly this, and independent per-column
    rounding provably wrecks them (a 0.5/0.5 split client rounds both
    ways); the dive projects such groups winner-take-all instead.

    Returns (groups (G, L) int32 positions into int_cols padded with
    -1, active (S, G) bool: rhs ~= coefficient for that scenario) or
    (None, None) when no groups exist."""
    A = qp.A
    if hasattr(A, "vals"):  # ELL: reconstruct rows over int cols
        vals = np.asarray(A.vals)
        if vals.ndim == 3:
            vals = vals[0]
        cols = np.asarray(A.cols)
        m, n = A.m, A.n
        dense = np.zeros((m, n))
        rows = np.repeat(np.arange(m), cols.shape[1])
        dense[rows, cols.reshape(-1)] = vals.reshape(-1)
        A2 = dense
    else:
        A2 = np.asarray(A)
        if A2.ndim == 3:
            A2 = A2[0]
    S = qp.c.shape[0]
    n = qp.c.shape[-1]
    dcol = np.broadcast_to(np.asarray(d_col), (S, n))[0]
    bl = np.broadcast_to(np.asarray(qp.bl), (S, qp.m))
    bu = np.broadcast_to(np.asarray(qp.bu), (S, qp.m))
    int_cols_np = np.asarray(int_cols)
    is_int = np.zeros(n, bool)
    is_int[int_cols_np] = True
    pos_of = np.full(n, -1, np.int64)
    pos_of[int_cols_np] = np.arange(len(int_cols_np))
    eq = np.all(np.abs(bl - bu) <= 1e-9, axis=0)  # equality in every scen
    groups, actives = [], []
    # original-space coefficients: A_orig[i, j] * d_row_i = A2[i, j] /
    # d_col_j ... the row scaling cancels against the scaled rhs, so
    # compare A2[i, j] / d_col_j (== d_row_i * orig coef) with bl[s, i]
    # (== d_row_i * orig rhs): equality <=> orig coef == orig rhs.
    for i in range(qp.m):
        if not eq[i]:
            continue
        nz = np.nonzero(np.abs(A2[i]) > 1e-12)[0]
        if nz.size < 2 or not np.all(is_int[nz]):
            continue
        coefs = A2[i, nz] / dcol[nz]
        if np.abs(coefs - coefs[0]).max() > 1e-6 * max(
                1.0, abs(coefs[0])):
            continue
        # active where scaled rhs == the common scaled coefficient
        act = np.abs(bl[:, i] - coefs[0]) <= 1e-6 * max(1.0,
                                                        abs(coefs[0]))
        if not act.any():
            continue
        groups.append(pos_of[nz])
        actives.append(act)
    if not groups:
        return None, None
    L = max(len(g) for g in groups)
    G = len(groups)
    gm = np.full((G, L), -1, np.int32)
    for gi, g in enumerate(groups):
        gm[gi, :len(g)] = g
    return jnp.asarray(gm), jnp.asarray(np.asarray(actives).T)  # (S, G)


def _sos1_project(r: Array, xi: Array, lo: Array, hi: Array,
                  groups: Array, active: Array) -> Array:
    """Winner-take-all rounding targets on SOS1 groups: the member with
    the largest LP value gets 1, the rest 0 (fixed-at-1 members win
    outright).  r/xi/lo/hi: (S, nI); groups (G, L) padded -1;
    active (S, G)."""
    S = r.shape[0]
    gidx = jnp.where(groups < 0, 0, groups)          # (G, L) safe gather
    valid = (groups >= 0)[None, :, :]                # (1, G, L)
    xi_g = xi[:, gidx]                               # (S, G, L)
    lo_g = lo[:, gidx]
    hi_g = hi[:, gidx]
    fixed1 = (lo_g == hi_g) & (lo_g > 0.5) & valid
    score = jnp.where(valid, xi_g, -jnp.inf)
    score = jnp.where(fixed1, jnp.inf, score)        # fixed-at-1 wins
    winner = jnp.argmax(score, axis=-1)              # (S, G)
    onehot = jax.nn.one_hot(winner, groups.shape[1],
                            dtype=r.dtype)           # (S, G, L)
    apply = valid & active[:, :, None]
    target = jnp.where(apply, onehot, 0.0)
    # scatter: only APPLIED positions overwrite r
    r2 = r.at[jnp.arange(S)[:, None, None], gidx].set(
        jnp.where(apply, target, r[:, gidx]))
    return r2


@partial(jax.jit, static_argnames=("opts", "mode"))
def dive_round(qp: BoxQP, d_col: Array, int_cols: Array,
               lo: Array, hi: Array, x_warm: Array, y_warm: Array,
               omega: Array, Lnorm: Array,
               opts: BnBOptions, mode: str = "wave",
               sos1=None):
    """Solve the current partially-fixed LP, then pin integer columns.

    mode="wave":   pin up to ~nI/8 CONFIDENT columns (frac <= dive_tol)
                   — bulk progress while the re-solve can still repair
                   the coupling the pins break;
    mode="single": pin exactly the most integral unfixed column — the
                   ambiguous tail, where pinning a coin-flip without a
                   re-solve in between wrecks coupled rows (observed on
                   sslp: one wave-pinned ambiguous batch cost +36k);
    mode="final":  pin everything remaining (the closing solve).

    Returns updated (lo, hi, x, y, omega, obj, feasible)."""
    qpn = _node_qp(qp, d_col, int_cols, lo, hi)
    sol, obj, _, rp = _solve_node(qpn, x_warm, y_warm, opts.lp,
                                  omega, Lnorm, jitter=opts.jitter)
    x_orig = sol.x * jnp.broadcast_to(d_col, sol.x.shape)
    xi = x_orig[:, int_cols]
    frac = jnp.abs(xi - jnp.round(xi))
    fixed = lo == hi
    nI = frac.shape[1]
    S = frac.shape[0]
    # members of ACTIVE SOS1 groups are resolved ONLY by group mode
    # (waves confidently pin 0.95-fraction members one by one and the
    # accumulated picks overload servers — measured +8k objective
    # blowups on sslp recourse); per-scenario mask since a row can be
    # active (rhs 1) in one scenario and inactive (rhs 0) in another
    sos_member = None
    if sos1 is not None and sos1[0] is not None:
        groups_, active_ = sos1
        G_, L_ = groups_.shape
        gidx_ = jnp.where(groups_ < 0, 0, groups_)
        valid_ = (groups_ >= 0)
        membership_ = jnp.zeros((G_, nI), frac.dtype).at[
            jnp.arange(G_)[:, None], gidx_].max(
            valid_.astype(frac.dtype))
        sos_member = (active_.astype(frac.dtype) @ membership_) > 0.5

    if mode == "final":
        newfix = ~fixed
    elif mode == "group":
        # pin ONE whole SOS1 group per re-solve (the one with the
        # clearest winner): mass-pinning all groups at their argmax
        # stacks correlated winners onto the same attractive server
        # (measured +16k objective blowups); pin-then-resolve lets the
        # LP steer the remaining clients around the filled capacity
        groups, active = sos1
        G, L = groups.shape
        gidx = jnp.where(groups < 0, 0, groups)
        valid = (groups >= 0)
        fixed_g = fixed[:, gidx] & valid[None]
        unresolved = jnp.any(~fixed_g & valid[None], axis=-1) & active
        xi_g = jnp.where(valid[None], xi[:, gidx], -jnp.inf)
        conf = jnp.max(jnp.where(fixed_g, -jnp.inf, xi_g), axis=-1)
        conf = jnp.where(unresolved, conf, -jnp.inf)
        gstar = jnp.argmax(conf, axis=-1)                  # (S,)
        has = jnp.any(unresolved, axis=-1)
        membership = jnp.zeros((G, nI), bool).at[
            jnp.arange(G)[:, None], gidx].max(valid)
        sel = jax.nn.one_hot(gstar, G, dtype=frac.dtype)   # (S, G)
        mem = (sel @ membership.astype(frac.dtype)) > 0.5  # (S, nI)
        newfix = mem & ~fixed & has[:, None]
    elif mode == "single":
        blocked = fixed if sos_member is None else (fixed | sos_member)
        jstar = jnp.argmin(jnp.where(blocked, jnp.inf, frac), axis=1)
        has_unfixed = ~jnp.all(blocked, axis=1)
        newfix = jax.nn.one_hot(jstar, nI, dtype=bool) \
            & has_unfixed[:, None] & ~fixed
    else:
        K = max(1, nI // 8)
        blocked = fixed if sos_member is None else (fixed | sos_member)
        score = jnp.where(blocked, -jnp.inf, -frac)     # bigger = better
        vals, idx = jax.lax.top_k(score, K)             # K smallest fracs
        take = vals > -opts.dive_tol                    # confident only
        newfix = jnp.zeros_like(fixed)
        newfix = newfix.at[jnp.arange(S)[:, None], idx].set(take)
        newfix = newfix & ~fixed
    r = jnp.clip(jnp.floor(xi + 0.5), lo, hi)
    if sos1 is not None and sos1[0] is not None:
        groups, active = sos1
        r = jnp.clip(_sos1_project(r, xi, lo, hi, groups, active), lo, hi)
    lo2 = jnp.where(newfix, r, lo)
    hi2 = jnp.where(newfix, r, hi)
    feasible = (rp <= opts.feas_tol) & (sol.status != pdhg.INFEASIBLE) \
        & (sol.status != pdhg.UNBOUNDED)
    return lo2, hi2, sol.x, sol.y, sol.omega, obj, feasible


def _root_bounds(qp: BoxQP, d_col: Array, int_cols: np.ndarray):
    """ORIGINAL-space integral root box of the integer columns."""
    S, n = qp.c.shape
    l_orig = np.broadcast_to(np.asarray(qp.l), (S, n)) \
        * np.broadcast_to(np.asarray(d_col), (S, n))
    u_orig = np.broadcast_to(np.asarray(qp.u), (S, n)) \
        * np.broadcast_to(np.asarray(d_col), (S, n))
    lo = np.ceil(l_orig[:, int_cols] - 1e-6)
    hi = np.floor(u_orig[:, int_cols] + 1e-6)
    return lo, hi


def dive(qp: BoxQP, d_col: Array, int_cols: Array,
         opts: BnBOptions = BnBOptions(),
         lo: Array | None = None, hi: Array | None = None,
         x_warm: Array | None = None, y_warm: Array | None = None,
         omega: Array | None = None, Lnorm: Array | None = None,
         sos1=None):
    """Fix-and-round dive to one integer-feasible point per scenario
    (host loop over jitted rounds).  Returns (value (S,), x (S,n) orig,
    feasible (S,), warm) where warm = (x, y, omega, Lnorm) for reuse;
    value is +inf where the dive's end point is infeasible.  This is the
    cheap certified-incumbent path the round-2 review asked for before
    full branch-and-bound."""
    int_cols = jnp.asarray(int_cols)
    if lo is None or hi is None:
        lo_np, hi_np = _root_bounds(qp, d_col, np.asarray(int_cols))
        lo = jnp.asarray(lo_np, qp.c.dtype)
        hi = jnp.asarray(hi_np, qp.c.dtype)
    S, n = qp.c.shape
    dt = qp.c.dtype
    if x_warm is None:
        x_warm = jnp.clip(jnp.zeros((S, n), dt), qp.l, qp.u)
    if y_warm is None:
        y_warm = jnp.zeros((S, qp.m), dt)
    if omega is None:
        omega = jnp.full((S,), opts.lp.omega0, dt)
    if Lnorm is None:
        Lnorm = pdhg.estimate_norm(qp, opts.lp.power_iters).astype(dt)
    def all_fixed():
        return bool(np.all(np.asarray(lo) == np.asarray(hi)))

    # SOS1-like assignment rows round winner-take-all (detected once;
    # repeated callers — lns_repair — pass the cached detection in)
    if sos1 is None:
        sos1 = detect_sos1_groups(qp, d_col, int_cols)

    prev_fixed = -1
    for _ in range(max(1, opts.dive_rounds)):
        lo, hi, x_warm, y_warm, omega, obj, feas = dive_round(
            qp, d_col, int_cols, lo, hi, x_warm, y_warm, omega, Lnorm,
            opts, "wave", sos1=sos1)
        nfixed = int((np.asarray(lo) == np.asarray(hi)).sum())
        if all_fixed() or nfixed == prev_fixed:  # no confident cols left
            break
        prev_fixed = nfixed
    # SOS1 groups: one whole group per re-solve, clearest winner first
    if sos1[0] is not None:
        for _ in range(int(sos1[0].shape[0])):
            if all_fixed():
                break
            lo, hi, x_warm, y_warm, omega, obj, feas = dive_round(
                qp, d_col, int_cols, lo, hi, x_warm, y_warm, omega,
                Lnorm, opts, "group", sos1=sos1)
    # ambiguous tail: one pin per re-solve
    for _ in range(opts.dive_tail):
        if all_fixed():
            break
        lo, hi, x_warm, y_warm, omega, obj, feas = dive_round(
            qp, d_col, int_cols, lo, hi, x_warm, y_warm, omega, Lnorm,
            opts, "single", sos1=sos1)
    # pin any remainder, then one last solve of the fully fixed LP
    lo, hi, x_warm, y_warm, omega, obj, feas = dive_round(
        qp, d_col, int_cols, lo, hi, x_warm, y_warm, omega, Lnorm,
        opts, "final", sos1=sos1)
    lo, hi, x_warm, y_warm, omega, obj, feas = dive_round(
        qp, d_col, int_cols, lo, hi, x_warm, y_warm, omega, Lnorm,
        opts, "final", sos1=sos1)
    value = jnp.where(feas, obj, jnp.inf)
    x_orig = x_warm * jnp.broadcast_to(d_col, x_warm.shape)
    return value, x_orig, feas, (x_warm, y_warm, omega, Lnorm)


@partial(jax.jit, static_argnames=("opts",))
def _swap_round(qp: BoxQP, d_col: Array, int_cols: Array,
                xi: Array, hi_root: Array, groups: Array, active: Array,
                obj_cur: Array, feas_cur: Array,
                x_cur: Array, y_cur: Array, omega: Array, Lnorm: Array,
                opts: BnBOptions):
    """One dual-guided SOS1 swap per scenario (see
    BnBOptions.swap_rounds).  `xi` is the (S, nI) integral point in
    ORIGINAL space, `x_cur`/`y_cur` the scaled primal-dual pair of its
    all-fixed LP solve; accepted moves replace the state, rejected
    moves leave it bit-identical."""
    S = xi.shape[0]
    d_full = jnp.broadcast_to(d_col, x_cur.shape)
    # per-unit-original reduced costs off the CURRENT duals: moving a
    # one-hot winner from column w to column m changes the objective by
    # ~ rc[m]/d[m] - rc[w]/d[w] (the group's own equality-row dual
    # contributes equally to every member, so comparisons are clean)
    rc = qp.c + qp.q * x_cur + qp.rmatvec(y_cur)
    score = (rc / d_full)[:, int_cols]                    # (S, nI)
    G, L = groups.shape
    gidx = jnp.where(groups < 0, 0, groups)               # (G, L)
    valid = (groups >= 0)[None]                           # (1, G, L)
    srange = jnp.arange(S)
    xg = jnp.where(valid, xi[:, gidx], 0.0)               # (S, G, L)
    sg = jnp.where(valid, score[:, gidx], jnp.inf)
    allowed = valid & (hi_root[:, gidx] > 0.5)
    is_winner = xg > 0.5
    win_score = jnp.sum(jnp.where(is_winner, sg, 0.0), axis=-1)
    alt = jnp.where(is_winner | ~allowed, jnp.inf, sg)    # (S, G, L)
    alt_best = jnp.min(alt, axis=-1)
    has_winner = jnp.any(is_winner & valid, axis=-1)      # (S, G)
    delta = jnp.where(active & has_winner & jnp.isfinite(alt_best),
                      alt_best - win_score, jnp.inf)
    gstar = jnp.argmin(delta, axis=-1)                    # (S,)
    can = jnp.isfinite(jnp.min(delta, axis=-1)) & feas_cur
    gsel = gidx[gstar]                                    # (S, L)
    vsel = (groups >= 0)[gstar]
    xg_sel = jnp.where(vsel, xi[srange[:, None], gsel], 0.0)
    win_col = jnp.take_along_axis(
        gsel, jnp.argmax(xg_sel, axis=-1)[:, None], axis=-1)[:, 0]
    alt_sel = alt[srange, gstar]
    alt_col = jnp.take_along_axis(
        gsel, jnp.argmin(alt_sel, axis=-1)[:, None], axis=-1)[:, 0]
    step = jnp.where(can, 1.0, 0.0)
    xi_try = xi.at[srange, win_col].add(-step)
    xi_try = xi_try.at[srange, alt_col].add(step)

    qpt = _node_qp(qp, d_col, int_cols, xi_try, xi_try)
    sol2, obj2, _, rp2 = _solve_node(qpt, x_cur, y_cur, opts.lp,
                                     omega, Lnorm)
    feas2 = (rp2 <= opts.feas_tol) & (sol2.status != pdhg.INFEASIBLE) \
        & (sol2.status != pdhg.UNBOUNDED)
    eps = 1e-6 * jnp.maximum(1.0, jnp.abs(obj_cur))
    improve = can & feas2 & (obj2 < obj_cur - eps)
    imp_c = improve[:, None]
    return (jnp.where(imp_c, xi_try, xi),
            jnp.where(improve, obj2, obj_cur),
            feas_cur | improve,
            jnp.where(imp_c, sol2.x, x_cur),
            jnp.where(imp_c, sol2.y, y_cur),
            jnp.where(improve, sol2.omega, omega),
            improve)


def sos1_swap_repair(qp: BoxQP, d_col: Array, int_cols: Array,
                     x_inc_orig: Array, feas: Array,
                     opts: BnBOptions,
                     warm=None, sos1=None, verbose: bool = False):
    """Polish integral incumbents by dual-guided SOS1 winner swaps.

    x_inc_orig: (S, n) incumbent points in ORIGINAL space (integer
    columns integral where `feas`).  Returns (value (S,), x_orig,
    feasible) with per-scenario improvements only (never regressions).
    No-op (returns None) when the problem has no SOS1 groups or
    swap_rounds == 0."""
    if opts.swap_rounds <= 0:
        return None
    if sos1 is None:
        sos1 = detect_sos1_groups(qp, d_col, int_cols)
    groups, active = sos1
    if groups is None:
        return None
    int_np = np.asarray(int_cols)
    _, hi_root = _root_bounds(qp, d_col, int_np)
    hi_root = jnp.asarray(hi_root, qp.c.dtype)
    xi = jnp.round(jnp.asarray(x_inc_orig)[:, int_np])
    S, n = qp.c.shape
    dt = qp.c.dtype
    d_full = jnp.broadcast_to(d_col, (S, n))
    if warm is not None:
        x_w, y_w, omega, Lnorm = warm
    else:
        x_w = jnp.asarray(x_inc_orig, dt) / d_full
        y_w = jnp.zeros((S, qp.m), dt)
        omega = Lnorm = None
    # evaluate the incumbents once (all integers fixed) for the
    # baseline objective and duals the first proposals read
    qpn = _node_qp(qp, d_col, int_cols, xi, xi)
    sol, obj, _, rp = _solve_node_jit(qpn, x_w, y_w, opts.lp, omega,
                                      Lnorm)
    feas_cur = jnp.asarray(feas) & (rp <= opts.feas_tol) \
        & (sol.status != pdhg.INFEASIBLE) \
        & (sol.status != pdhg.UNBOUNDED)
    x_cur, y_cur, om = sol.x, sol.y, sol.omega
    Ln = sol.Lnorm
    for r in range(opts.swap_rounds):
        xi, obj, feas_cur, x_cur, y_cur, om, moved = _swap_round(
            qp, d_col, int_cols, xi, hi_root, groups, active,
            obj, feas_cur, x_cur, y_cur, om, Ln, opts)
        if not bool(np.any(np.asarray(moved))):
            break
        if verbose and (r + 1) % 8 == 0:
            _console.log(f"[swap] round {r + 1}: obj={np.asarray(obj)}",
                         level=_console.DEBUG)
    x_orig = x_cur * d_full
    x_orig = x_orig.at[:, int_np].set(xi)
    return (jnp.where(feas_cur, obj, jnp.inf), x_orig, feas_cur)


def merge_incumbents(inc, x_inc, feas, cand_val, cand_x, cand_feas):
    """Accept-only-improvements merge of candidate incumbents into the
    running best: the single place the invariant lives (a candidate
    counts only where IT is feasible and strictly better than the
    current FEASIBLE value, infeasible current = +inf)."""
    better = jnp.where(cand_feas, cand_val, jnp.inf) \
        < jnp.where(feas, inc, jnp.inf)
    return (jnp.where(better, cand_val, inc),
            jnp.where(better[:, None], cand_x, x_inc),
            feas | (cand_feas & better))


def dive_multistart(qp: BoxQP, d_col: Array, int_cols: Array,
                    opts: BnBOptions = BnBOptions(), K: int = 16,
                    sos1=None):
    """K jitter-diversified dives per scenario in ONE batched program —
    batching the restarts is the TPU answer to a MIP heuristic's
    random-restart loop.  Each copy solves the SAME scenario with a
    different deterministic objective perturbation (vertex
    tie-breaking only; values are always evaluated against the true
    costs), and the per-scenario best integral point wins.  Returns
    (value (S,), x (S, n) orig, feasible (S,))."""
    S, n = qp.c.shape

    def tile(x, nd):
        if hasattr(x, "vals"):  # EllMatrix (same convention as
            # mip.evaluate_mip_many's tileS)
            return dataclasses.replace(x, vals=tile(x.vals, nd))
        if getattr(x, "ndim", 0) != nd:
            return x
        return jnp.tile(x, (K,) + (1,) * (nd - 1))

    qpK = dataclasses.replace(
        qp, c=tile(qp.c, 2), q=tile(qp.q, 2), A=tile(qp.A, 3),
        bl=tile(qp.bl, 2), bu=tile(qp.bu, 2), l=tile(qp.l, 2),
        u=tile(qp.u, 2))
    dK = d_col
    if getattr(d_col, "ndim", 1) == 2:
        dK = jnp.tile(d_col, (K, 1))
    o2 = dataclasses.replace(opts, jitter=max(opts.jitter, 1e-3))
    if sos1 is not None and sos1[0] is not None:
        groups, active = sos1
        sos1K = (groups, jnp.tile(active, (K, 1)))
    else:
        sos1K = sos1
    val, x, feas, _ = dive(qpK, dK, int_cols, o2, sos1=sos1K)
    val = jnp.where(feas, val, jnp.inf).reshape(K, S)
    x = x.reshape(K, S, n)
    k_best = jnp.argmin(val, axis=0)                      # (S,)
    srange = jnp.arange(S)
    return (val[k_best, srange], x[k_best, srange],
            jnp.isfinite(val[k_best, srange]))


def lns_repair(qp: BoxQP, d_col: Array, int_cols: Array,
               x_inc_orig: Array, value0: Array, feas0: Array,
               opts: BnBOptions = BnBOptions(),
               rounds: int = 16, destroy_frac: float = 0.25,
               seed: int = 7, sos1=None, verbose: bool = False):
    """Large-neighborhood polish of integral incumbents: per round,
    UNFIX a random per-scenario subset of SOS1 groups (the rest stay
    pinned at the incumbent) and re-dive warm, accepting per-scenario
    strict improvements only.

    Single dual-guided swaps (sos1_swap_repair) stall on
    capacity-coupled assignment structure — improving moves need
    chains (client A leaves server j so client B fits), which the
    destroy-and-re-dive neighborhood reaches.  Deterministic via
    `seed`.  Meant for FINAL-candidate certification polish, not the
    per-node hot path (each round costs a partial dive).  Returns
    (value, x_orig, feasible) or None when structureless."""
    if sos1 is None:
        sos1 = detect_sos1_groups(qp, d_col, int_cols)
    groups, active = sos1
    if groups is None or rounds <= 0:
        return None
    int_np = np.asarray(int_cols)
    lo_root, hi_root = _root_bounds(qp, d_col, int_np)
    xi = np.round(np.asarray(x_inc_orig)[:, int_np])
    best_val = np.array(np.asarray(value0), np.float64)
    best_x = np.array(np.asarray(x_inc_orig), np.float64)
    feas = np.array(np.asarray(feas0), bool)
    groups_np = np.asarray(groups)
    active_np = np.asarray(active)
    G, L = groups_np.shape
    S, nI = xi.shape
    membership = np.zeros((G, nI), bool)
    for g in range(G):
        membership[g, groups_np[g][groups_np[g] >= 0]] = True
    rng = np.random.default_rng(seed)
    dt = qp.c.dtype
    warm_omega = warm_L = None   # captured from the first dive
    for r in range(rounds):
        destroyed = (rng.random((S, G)) < destroy_frac) & active_np
        unfix = destroyed @ membership                    # (S, nI) bool
        cur = np.where(feas[:, None], xi, lo_root)        # infeasible:
        lo = np.where(unfix | ~feas[:, None], lo_root, cur)  # full re-dive
        hi = np.where(unfix | ~feas[:, None], hi_root, cur)
        val, x_new, f_new, warm = dive(
            qp, d_col, int_cols, opts,
            lo=jnp.asarray(lo, dt), hi=jnp.asarray(hi, dt),
            omega=warm_omega, Lnorm=warm_L, sos1=sos1)
        if warm_L is None:
            warm_omega, warm_L = warm[2], warm[3]
        val = np.asarray(val)
        x_new = np.asarray(x_new)
        f_new = np.asarray(f_new)
        eps = 1e-6 * np.maximum(1.0, np.abs(best_val))
        better = f_new & (val < np.where(feas, best_val - eps, np.inf))
        if np.any(better):
            best_val = np.where(better, val, best_val)
            best_x = np.where(better[:, None], x_new, best_x)
            feas = feas | better
            xi = np.round(best_x[:, int_np])
        if verbose and (r + 1) % 4 == 0:
            _console.log(f"[lns] round {r + 1}: {best_val}",
                         level=_console.DEBUG)
    return (jnp.asarray(np.where(feas, best_val, np.inf), dt),
            jnp.asarray(best_x, dt), jnp.asarray(feas))


def root_state(qp: BoxQP, d_col: Array, int_cols: Array,
               opts: BnBOptions = BnBOptions(),
               incumbent: Array | None = None,
               x_inc: Array | None = None,
               warm: "tuple | None" = None) -> BnBState:
    """Root-node BnBState: the open pool seeded with the integer root
    box, everything else at its no-information sentinel.  THE one
    construction shared by solve_mip (which seeds incumbent/warm from
    its dive/pump passes) and the multichip dry run (cold defaults) —
    the pool-seeding convention must never fork between the real
    solver and the coverage probe.

    warm: optional (x, y, omega, Lnorm); cold defaults otherwise.
    """
    S, n = qp.c.shape
    dt = qp.c.dtype
    int_cols_np = np.asarray(int_cols)
    nI = int(int_cols_np.shape[0])
    P = opts.pool_size
    lo0, hi0 = _root_bounds(qp, d_col, int_cols_np)
    if warm is None:
        from mpisppy_tpu.ops import pdhg as _pdhg
        x_w = jnp.clip(jnp.zeros_like(qp.c), qp.l, qp.u)
        y_w = jnp.zeros((S, qp.m), dt)
        omega = jnp.ones((S,), dt)
        Lnorm = _pdhg.estimate_norm(qp).astype(dt)
    else:
        x_w, y_w, omega, Lnorm = warm
    return BnBState(
        pool_lo=jnp.zeros((S, P, nI), dt).at[:, 0, :].set(
            jnp.asarray(lo0, dt)),
        pool_hi=jnp.zeros((S, P, nI), dt).at[:, 0, :].set(
            jnp.asarray(hi0, dt)),
        pool_bound=jnp.full((S, P), jnp.inf, dt).at[:, 0].set(-jnp.inf),
        pool_active=jnp.zeros((S, P), bool).at[:, 0].set(True),
        pool_depth=jnp.zeros((S, P), jnp.int32),
        incumbent=(jnp.full((S,), jnp.inf, dt) if incumbent is None
                   else incumbent),
        x_inc=(jnp.zeros((S, n), dt) if x_inc is None else x_inc),
        fathom_floor=jnp.full((S,), jnp.inf, dt),
        lost_bound=jnp.full((S,), jnp.inf, dt),
        x_warm=x_w, y_warm=y_w, omega_warm=omega, Lnorm=Lnorm,
        outer=jnp.full((S,), -jnp.inf, dt),
        done=jnp.zeros((S,), bool),
        nodes_solved=jnp.zeros((S,), jnp.int32),
    )


def solve_mip(qp: BoxQP, d_col: Array, int_cols: Array,
              opts: BnBOptions = BnBOptions(),
              x_warm: Array | None = None, y_warm: Array | None = None,
              verbose: bool = False) -> BnBResult:
    """Batched exact MIP solve: dive for an incumbent, then best-first
    branch-and-bound until every scenario's certified gap closes (or the
    round budget runs out — the bracket stays valid either way).

    qp:       scaled batched BoxQP ((S, n) fields; A may broadcast).
    d_col:    Ruiz column scaling ((n,) or (S, n)); x_orig = d_col * x.
    int_cols: int32 indices of integer columns (shared across batch).
    """
    int_cols = jnp.asarray(int_cols, jnp.int32)
    dt = qp.c.dtype

    sos1 = detect_sos1_groups(qp, d_col, int_cols)
    inc, x_inc, feas, warm = dive(qp, d_col, int_cols, opts,
                                  x_warm=x_warm, y_warm=y_warm,
                                  sos1=sos1)
    dive_x, dive_y, omega, Lnorm = warm
    if verbose and bool(np.any(np.asarray(feas))):
        v = np.asarray(inc)
        _console.log(f"[bnb] dive incumbents: {v}")
    if opts.pump_rounds > 0:
        p_val, p_x, p_feas = feasibility_pump(
            qp, d_col, int_cols, opts, rounds=opts.pump_rounds,
            x_warm=dive_x, y_warm=dive_y, omega=omega, Lnorm=Lnorm)
        inc, x_inc, feas = merge_incumbents(inc, x_inc, feas,
                                            p_val, p_x, p_feas)
        if verbose:
            _console.log(f"[bnb] pump incumbents: {np.asarray(p_val)}")

    rep = sos1_swap_repair(qp, d_col, int_cols, x_inc, feas, opts,
                           warm=(dive_x, dive_y, omega, Lnorm),
                           sos1=sos1, verbose=verbose)
    if rep is not None:
        inc, x_inc, feas = merge_incumbents(inc, x_inc, feas, *rep)
        if verbose:
            _console.log(f"[bnb] swap-repaired incumbents: {np.asarray(inc)}")

    st = root_state(qp, d_col, int_cols, opts,
                    incumbent=jnp.where(feas, inc, jnp.inf).astype(dt),
                    x_inc=x_inc.astype(dt),
                    warm=(dive_x, dive_y, omega, Lnorm))
    for r in range(opts.max_rounds):
        st = bnb_round(qp, d_col, int_cols, st, opts)
        if bool(np.all(np.asarray(st.done))):
            break
        if verbose and (r + 1) % 25 == 0:
            _console.log(f"[bnb] round {r + 1}: "
                         f"inc={np.asarray(st.incumbent)} "
                         f"outer={np.asarray(st.outer)}",
                         level=_console.DEBUG)

    # final polish: B&B rounds may have found new incumbents the
    # swap-repair has not seen yet
    rep = sos1_swap_repair(
        qp, d_col, int_cols, st.x_inc, jnp.isfinite(st.incumbent), opts,
        warm=(st.x_warm, st.y_warm, st.omega_warm, st.Lnorm),
        sos1=sos1, verbose=verbose)
    if rep is not None:
        new_inc, new_x, _ = merge_incumbents(
            st.incumbent, st.x_inc, jnp.isfinite(st.incumbent), *rep)
        st = dataclasses.replace(st, incumbent=new_inc, x_inc=new_x)

    # BnB loop telemetry (docs/telemetry.md): the loop already counts
    # nodes per lane on device (BnBState.nodes_solved); fold this
    # solve's totals into the process metrics registry so MIP runs
    # report next to the PDHG counters.  inc (not set): each solve_mip
    # call contributes its delta to the monotone process total.
    from mpisppy_tpu.telemetry import metrics as _metrics
    _metrics.REGISTRY.inc("bnb_nodes_solved_total",
                          int(np.sum(np.asarray(st.nodes_solved))))
    _metrics.REGISTRY.inc("bnb_lanes_closed_total",
                          int(np.sum(np.asarray(st.done))))

    inner = st.incumbent
    # A scenario that exhausted its pool with no incumbent and no open
    # nodes has outer = min(fathom_floor, lost) — report as-is.
    scale = jnp.maximum(1.0, jnp.abs(inner))
    gap = jnp.where(jnp.isfinite(inner), (inner - st.outer) / scale, jnp.inf)
    return BnBResult(x=st.x_inc, inner=inner, outer=st.outer, gap=gap,
                     feasible=jnp.isfinite(inner),
                     nodes_solved=st.nodes_solved)
