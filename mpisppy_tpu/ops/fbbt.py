###############################################################################
# Batched feasibility-based bound tightening (FBBT / presolve).
#
# The reference's SPPresolve wraps Pyomo APPSI's compiled C interval
# tightener per subproblem, then Allreduces the nonant bounds across
# ranks (MAX on lb, MIN on ub) so tightening is consistent scenario-wide
# (ref:mpisppy/opt/presolve.py:25,61-180,183-260).  TPU-native, a sweep
# of interval arithmetic over every row of EVERY scenario is one tensor
# program:
#
#   row activity bounds     Lmin_i = sum_j min(a_ij l_j, a_ij u_j)
#                           Lmax_i = sum_j max(a_ij l_j, a_ij u_j)
#   per-(row, col) implied  a_ij x_j <= bu_i - (Lmin_i - min-term_ij)
#   bounds                  a_ij x_j >= bl_i - (Lmax_i - max-term_ij)
#   column tightening       u_j <- min over rows, l_j <- max over rows
#   integer rounding        l_j <- ceil(l_j), u_j <- floor(u_j)
#
# Dense A uses (m, n) elementwise products; ELL A computes the same
# quantities on the (m, k) slot arrays with one gather and one
# scatter-min/max — both static-shape, batched over scenarios on the
# leading axis, and jit-compiled as a lax.fori_loop over sweeps.
#
# The payoff is dual (round-2 review, missing #2): reference parity
# (consistent nonant bounds), and PDHG conditioning — a smaller feasible
# box directly shrinks the primal diameter the first-order kernel has to
# traverse, and tighter integer boxes shrink the branch-and-bound tree.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from mpisppy_tpu.ops.boxqp import BoxQP

Array = jax.Array

_BIG = 1e30  # stand-in for inf inside interval arithmetic (avoids inf-inf)


def _clean(lo: Array, hi: Array):
    """Map +-inf to +-_BIG so activity PRODUCTS never produce NaN
    (0 * inf); infinite contributions are tracked symbolically by the
    sweeps, never through these clipped magnitudes."""
    lo = jnp.clip(lo, -_BIG, _BIG)
    hi = jnp.clip(hi, -_BIG, _BIG)
    return lo, hi


def _rooms(t_min, t_max, min_inf, max_inf, bl, bu, dtype):
    """Per-(row, col) slack on each row side with EXACT handling of
    infinite activity terms (ADVICE r3 medium: summing clipped 1e30
    magnitudes absorbs the finite terms below the ulp and fabricates
    invalid tightenings).  Infinite min/max-terms contribute ZERO to the
    finite sums and are COUNTED; column j may only be tightened from a
    side whose infinite-term count, excluding j's own term exactly, is
    zero."""
    t_min_f = jnp.where(min_inf, 0.0, t_min)
    t_max_f = jnp.where(max_inf, 0.0, t_max)
    n_min_inf = jnp.sum(min_inf, axis=-1, keepdims=True)
    n_max_inf = jnp.sum(max_inf, axis=-1, keepdims=True)
    Lmin_f = jnp.sum(t_min_f, axis=-1, keepdims=True)
    Lmax_f = jnp.sum(t_max_f, axis=-1, keepdims=True)
    # residual activity over k != j: j's own term is excluded exactly
    # (subtracted when finite, contributed 0 when infinite)
    resid_min = Lmin_f - t_min_f
    resid_max = Lmax_f - t_max_f
    ok_min = (n_min_inf - min_inf.astype(n_min_inf.dtype)) == 0
    ok_max = (n_max_inf - max_inf.astype(n_max_inf.dtype)) == 0
    inf_room = jnp.asarray(jnp.inf, dtype)
    bl_b = jnp.clip(bl, -_BIG, _BIG)[..., :, None]
    bu_b = jnp.clip(bu, -_BIG, _BIG)[..., :, None]
    up_room = jnp.where(jnp.isfinite(bu)[..., :, None] & ok_min,
                        bu_b - resid_min, inf_room)
    lo_room = jnp.where(jnp.isfinite(bl)[..., :, None] & ok_max,
                        bl_b - resid_max, -inf_room)
    return up_room, lo_room


def _sweep_dense(A: Array, bl: Array, bu: Array, l: Array, u: Array):
    """One FBBT sweep, dense A ((m, n) or (S, m, n); l,u (..., n))."""
    lo, hi = _clean(l, u)
    lo_b = lo[..., None, :]
    hi_b = hi[..., None, :]
    t_min = jnp.minimum(A * lo_b, A * hi_b)       # (..., m, n)
    t_max = jnp.maximum(A * lo_b, A * hi_b)
    pos = A > 0.0
    neg = A < 0.0
    # symbolic infinity tracking off the RAW bounds (|b| >= _BIG counts
    # as infinite so user-supplied 1e30 sentinels behave like inf)
    lo_inf = ~(jnp.abs(l) < _BIG)[..., None, :]
    hi_inf = ~(jnp.abs(u) < _BIG)[..., None, :]
    min_inf = (pos & lo_inf) | (neg & hi_inf)
    max_inf = (pos & hi_inf) | (neg & lo_inf)
    up_room, lo_room = _rooms(t_min, t_max, min_inf, max_inf, bl, bu,
                              l.dtype)
    inf = jnp.asarray(jnp.inf, l.dtype)
    Asafe = jnp.where(A == 0.0, 1.0, A)
    ub_from_up = jnp.where(pos, up_room / Asafe, inf)
    ub_from_lo = jnp.where(neg, lo_room / Asafe, inf)
    lb_from_lo = jnp.where(pos, lo_room / Asafe, -inf)
    lb_from_up = jnp.where(neg, up_room / Asafe, -inf)
    new_u = jnp.min(jnp.minimum(ub_from_up, ub_from_lo), axis=-2)
    new_l = jnp.max(jnp.maximum(lb_from_lo, lb_from_up), axis=-2)
    l2 = jnp.maximum(l, new_l)
    u2 = jnp.minimum(u, new_u)
    return l2, u2


def _sweep_ell(ell, bl: Array, bu: Array, l: Array, u: Array):
    """One FBBT sweep on an ops.sparse.EllMatrix (vals (..., m, k),
    cols (m, k) shared).  Gather column boxes to slots, reduce rows,
    scatter implied bounds back with segment-min/max."""
    vals, cols, n = ell.vals, ell.cols, ell.n
    lo, hi = _clean(l, u)
    flat = cols.reshape(-1)
    gl = jnp.take(lo, flat, axis=-1).reshape(lo.shape[:-1] + cols.shape)
    gu = jnp.take(hi, flat, axis=-1).reshape(hi.shape[:-1] + cols.shape)
    t_min = jnp.minimum(vals * gl, vals * gu)     # (..., m, k)
    t_max = jnp.maximum(vals * gl, vals * gu)
    pos = vals > 0.0
    neg = vals < 0.0
    # symbolic infinity tracking off the RAW bounds (see _rooms)
    raw_l = jnp.take(l, flat, axis=-1).reshape(lo.shape[:-1] + cols.shape)
    raw_u = jnp.take(u, flat, axis=-1).reshape(hi.shape[:-1] + cols.shape)
    lo_inf = ~(jnp.abs(raw_l) < _BIG)
    hi_inf = ~(jnp.abs(raw_u) < _BIG)
    min_inf = (pos & lo_inf) | (neg & hi_inf)
    max_inf = (pos & hi_inf) | (neg & lo_inf)
    up_room, lo_room = _rooms(t_min, t_max, min_inf, max_inf, bl, bu,
                              l.dtype)
    inf = jnp.asarray(jnp.inf, l.dtype)
    vsafe = jnp.where(vals == 0.0, 1.0, vals)
    slot_ub = jnp.minimum(jnp.where(pos, up_room / vsafe, inf),
                          jnp.where(neg, lo_room / vsafe, inf))
    slot_lb = jnp.maximum(jnp.where(pos, lo_room / vsafe, -inf),
                          jnp.where(neg, up_room / vsafe, -inf))
    # scatter-min/max to columns (padding slots carry +-inf: no-ops)
    bshape = vals.shape[:-2]
    ub_flat = slot_ub.reshape(bshape + (-1,))
    lb_flat = slot_lb.reshape(bshape + (-1,))
    base_u = jnp.full(bshape + (n,), inf, l.dtype)
    base_l = jnp.full(bshape + (n,), -inf, l.dtype)
    new_u = base_u.at[..., flat].min(ub_flat)
    new_l = base_l.at[..., flat].max(lb_flat)
    l2 = jnp.maximum(l, new_l)
    u2 = jnp.minimum(u, new_u)
    return l2, u2


def _head_activity_max(qp: BoxQP, l: Array, u: Array):  # noqa: E741
    """(Lmax_finite, has_inf) for the SOC HEAD rows only, (..., C):
    the finite part of the interval activity upper bound
    sum_j max(a_ij l_j, a_ij u_j) and whether any term is
    (symbolically) infinite — same conventions as the sweeps.  Head
    row indices are STATIC (ConeSpec.head_rows meta), so A is sliced
    to C rows at trace time instead of reducing over all m rows (the
    sweeps already pay the full (m, n) pass; the SOC relaxation only
    needs the heads)."""
    hr = np.asarray(qp.cones.head_rows, np.int64)
    lo, hi = _clean(l, u)
    if hasattr(qp.A, "vals"):
        vals, cols = qp.A.vals[..., hr, :], qp.A.cols[hr]
        flat = cols.reshape(-1)
        gl = jnp.take(lo, flat, axis=-1).reshape(lo.shape[:-1] + cols.shape)
        gu = jnp.take(hi, flat, axis=-1).reshape(hi.shape[:-1] + cols.shape)
        t_max = jnp.maximum(vals * gl, vals * gu)
        pos = vals > 0.0
        neg = vals < 0.0
        raw_l = jnp.take(l, flat, axis=-1).reshape(
            lo.shape[:-1] + cols.shape)
        raw_u = jnp.take(u, flat, axis=-1).reshape(
            hi.shape[:-1] + cols.shape)
        lo_inf = ~(jnp.abs(raw_l) < _BIG)
        hi_inf = ~(jnp.abs(raw_u) < _BIG)
    else:
        A = qp.A[..., hr, :]
        lo_b = lo[..., None, :]
        hi_b = hi[..., None, :]
        t_max = jnp.maximum(A * lo_b, A * hi_b)
        pos = A > 0.0
        neg = A < 0.0
        lo_inf = ~(jnp.abs(l) < _BIG)[..., None, :]
        hi_inf = ~(jnp.abs(u) < _BIG)[..., None, :]
    max_inf = (pos & hi_inf) | (neg & lo_inf)
    Lmax_f = jnp.sum(jnp.where(max_inf, 0.0, t_max), axis=-1)
    return Lmax_f, jnp.any(max_inf, axis=-1)


def _soc_effective_bounds(qp: BoxQP, l: Array, u: Array):  # noqa: E741
    """CONSERVATIVE row-interval relaxation of SOC blocks (norm-ball
    bounds) for the sweeps.  The block stores its shift b in bl == bu;
    treating that as an equality row would be an INVALID tightening
    (it cuts the cone down to its apex).  Valid implications instead:

      head:  a_h'x - b_h = t >= ||z|| >= 0      ->  row in [b_h, +inf)
      tails: |a_i'x - b_i| = |z_i| <= ||z|| <= t <= t_ub
                                               ->  row in [b_i -+ t_ub]

    with t_ub the interval activity upper bound of the head row minus
    b_h (infinite head activity -> tails stay untightened).  Box rows
    keep their bounds."""
    spec = qp.cones
    hr = np.asarray(spec.head_rows, np.int64)
    Lmax_h, has_inf_h = _head_activity_max(qp, l, u)   # (..., C)
    # block b's head is head_rows[b] (cone_spec order), so the head
    # activities ARE the per-block values — no segment scatter needed;
    # a zero sentinel column serves the box rows' seg gather below
    room = Lmax_h - qp.bl[..., hr]
    bshape = Lmax_h.shape[:-1]
    pad = jnp.zeros(bshape + (1,), Lmax_h.dtype)
    blk = jnp.concatenate(
        [jnp.broadcast_to(room, bshape + room.shape[-1:]), pad], axis=-1)
    blk_inf = jnp.concatenate(
        [jnp.broadcast_to(has_inf_h.astype(Lmax_h.dtype),
                          bshape + (spec.num_cones,)), pad], axis=-1)
    inf = jnp.asarray(jnp.inf, Lmax_h.dtype)
    t_ub = jnp.where(blk_inf[..., spec.seg] > 0.0, inf,
                     jnp.maximum(blk[..., spec.seg], 0.0))
    bl_eff = jnp.where(spec.is_soc & ~spec.is_head, qp.bl - t_ub, qp.bl)
    bu_eff = jnp.where(spec.is_soc,
                       jnp.where(spec.is_head, inf, qp.bu + t_ub), qp.bu)
    return bl_eff, bu_eff


@partial(jax.jit, static_argnames=("n_sweeps",))
def fbbt(qp: BoxQP, n_sweeps: int = 3,
         d_col: Array | None = None,
         integer: Array | None = None):
    """`n_sweeps` of interval tightening over a (possibly batched,
    possibly Ruiz-scaled) BoxQP.  Returns (l, u) — tightened scaled-space
    boxes, never looser than the input.

    d_col + integer: when both given, integer columns are rounded to
    integral ORIGINAL-space bounds each sweep (x_orig = d_col * x), the
    compiled analog of APPSI's integer handling
    (ref:mpisppy/opt/presolve.py:61-180).
    """
    S_shape = qp.c.shape
    l0 = jnp.broadcast_to(qp.l, S_shape)
    u0 = jnp.broadcast_to(qp.u, S_shape)
    eps = 1e-6

    def round_int(l, u):  # noqa: E741
        if integer is None or d_col is None:
            return l, u
        d = jnp.broadcast_to(d_col, l.shape)
        l_orig = jnp.ceil(l * d - eps)
        u_orig = jnp.floor(u * d + eps)
        return (jnp.where(integer, l_orig / d, l),
                jnp.where(integer, u_orig / d, u))

    def body(_, lu):
        l, u = lu  # noqa: E741
        if qp.cones is None:
            bl, bu = qp.bl, qp.bu
        else:
            # re-relaxed EVERY sweep: the norm-ball widths shrink as the
            # head rows' activity bounds tighten
            bl, bu = _soc_effective_bounds(qp, l, u)
        if hasattr(qp.A, "vals"):
            l, u = _sweep_ell(qp.A, bl, bu, l, u)  # noqa: E741
        else:
            l, u = _sweep_dense(qp.A, bl, bu, l, u)  # noqa: E741
        return round_int(l, u)

    l, u = jax.lax.fori_loop(0, n_sweeps, body, round_int(l0, u0))  # noqa: E741
    return l, u


def presolve_batch(batch, n_sweeps: int = 3, feas_tol: float = 1e-6,
                   raise_on_infeasible: bool = True):
    """Presolve a core.batch.ScenarioBatch: FBBT sweeps on every
    scenario, then the cross-scenario nonant-bound intersection the
    reference does with MIN/MAX Allreduces
    (ref:mpisppy/opt/presolve.py:183-260) — valid because nonanticipative
    variables are equal across their node's scenarios, so every
    scenario's implied bound applies to all of them.

    Returns (new_batch, info) where info has 'tightened_bounds' (count
    of bounds that moved) and 'infeasible' ((S,) bool — empty box
    detected, the analog of presolve detecting infeasibility).  A
    provably-infeasible scenario raises ValueError by default: the
    returned batch clamps empty boxes to a point to stay solvable, and a
    caller ignoring info['infeasible'] must not mistake that fabricated
    problem for the real one.  Pass raise_on_infeasible=False to inspect
    the mask instead."""
    import numpy as np

    qp = batch.qp
    S_all = batch.num_scenarios
    # dense A: the sweep materializes (S, m, n) intermediates, so chunk
    # the scenario axis to bound device memory at ~2e7 elements (the
    # ELL path is (S, m, k) and never needs this)
    if not hasattr(qp.A, "vals") and S_all * qp.m * qp.n > 2e7:
        chunk = max(1, int(2e7 / (qp.m * qp.n)))
        ls, us = [], []
        for s0 in range(0, S_all, chunk):
            sl = slice(s0, min(s0 + chunk, S_all))

            def cut(x, batched_ndim):
                return x[sl] if x.ndim == batched_ndim else x

            qp_c = dataclasses.replace(
                qp, c=cut(qp.c, 2), q=cut(qp.q, 2), A=cut(qp.A, 3),
                bl=cut(qp.bl, 2), bu=cut(qp.bu, 2),
                l=cut(qp.l, 2), u=cut(qp.u, 2))
            lc, uc = fbbt(qp_c, n_sweeps=n_sweeps,
                          d_col=cut(batch.d_col, 2),
                          integer=batch.integer_full)
            ls.append(lc)
            us.append(uc)
        l1 = jnp.concatenate(ls, axis=0)
        u1 = jnp.concatenate(us, axis=0)
    else:
        l1, u1 = fbbt(qp, n_sweeps=n_sweeps, d_col=batch.d_col,
                      integer=batch.integer_full)

    # cross-scenario nonant intersection, in ORIGINAL space, per node
    S, n = l1.shape
    d_non = jnp.broadcast_to(batch.d_non, (S, batch.num_nonants))
    l_non = l1[:, batch.nonant_idx] * d_non
    u_non = u1[:, batch.nonant_idx] * d_non
    real = (batch.p > 0.0)[:, None]
    # per-node max of lower bounds / min of upper bounds over members
    N = batch.num_nonants
    nseg = batch.tree.num_nodes * N
    key = (batch.node_of_slot * N + jnp.arange(N)[None, :]).reshape(-1)
    big = jnp.asarray(_BIG, l1.dtype)
    lmax = jax.ops.segment_max(
        jnp.where(real, l_non, -big).reshape(-1), key, num_segments=nseg
    ).reshape(batch.tree.num_nodes, N)
    umin = jax.ops.segment_min(
        jnp.where(real, u_non, big).reshape(-1), key, num_segments=nseg
    ).reshape(batch.tree.num_nodes, N)
    l_non2 = jnp.take_along_axis(lmax, batch.node_of_slot, axis=0)
    u_non2 = jnp.take_along_axis(umin, batch.node_of_slot, axis=0)
    l2 = l1.at[:, batch.nonant_idx].max(l_non2 / d_non)
    u2 = u1.at[:, batch.nonant_idx].min(u_non2 / d_non)

    infeasible = np.asarray(jnp.any(l2 > u2 + feas_tol, axis=1)
                            & (batch.p > 0.0))
    if raise_on_infeasible and infeasible.any():
        raise ValueError(
            f"FBBT proved scenario(s) {np.nonzero(infeasible)[0].tolist()} "
            "infeasible (empty variable box after tightening)")

    l0 = np.broadcast_to(np.asarray(qp.l), (S, n))
    u0 = np.broadcast_to(np.asarray(qp.u), (S, n))
    moved = (np.asarray(l2) > l0 + 1e-9).sum() \
        + (np.asarray(u2) < u0 - 1e-9).sum()
    info = {
        "tightened_bounds": int(moved),
        "infeasible": infeasible,
    }
    new_qp = dataclasses.replace(qp, l=l2, u=jnp.maximum(l2, u2))
    return dataclasses.replace(batch, qp=new_qp), info
