###############################################################################
# Pallas TPU kernel: a full PDHG restart window in VMEM.
#
# The profiled 100k-scenario cliff (VERDICT r3 weak #5) is HBM
# bandwidth: one PDHG iteration is ~12 passes over (S, n)/(S, m) arrays
# (x, y, window sums, c, q, l, u, …), and at 100k scenarios nothing fits
# on-chip, so XLA's fori_loop body streams ~3 GB per iteration — the
# measured 1.6 s/PH-iteration matches the 819 GB/s v5e roofline almost
# exactly, while at 10k partial VMEM residency hides much of it.
#
# The fix is the classic TPU move: tile the scenario axis, park one
# tile's entire solver state in VMEM, and run ALL `restart_period`
# iterations on it in one kernel invocation.  HBM traffic per window
# drops from O(restart_period * state) to O(state) — a ~40x reduction —
# and the two matvecs per iteration ride the MXU against the SHARED
# dense (m, n) constraint matrix kept resident in VMEM.
#
# Scope: dense SHARED-A batches (the sslp/uc/netdes shape: deterministic
# constraint matrix, scenario-varying c/q/rhs).  ELL and per-scenario-A
# batches keep the XLA path (ops/pdhg.py _window falls back
# automatically).  Matmuls run at HIGHEST precision: default bf16 MXU
# passes stall PDHG at ~1e-2 KKT residual on-chip (measured round 1).
#
# There is no reference analog to cite: mpi-sppy delegates subproblem
# solves to Gurobi (ref:mpisppy/spopt.py:884); this kernel is part of
# the TPU-native replacement for that solver, like ops/pdhg.py itself.
###############################################################################
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_BIG = 1e30  # finite stand-in for +-inf row bounds (avoids inf-inf = nan)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pad_last(x: Array, size: int, value: float) -> Array:
    pad = size - x.shape[-1]
    if pad == 0:
        return x
    cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, cfg, constant_values=value)


def _window_kernel(n_iters: int,
                   tau_ref, sigma_ref, done_ref,
                   c_ref, q_ref, l_ref, u_ref, bl_ref, bu_ref,
                   A_ref, AT_ref,
                   x0_ref, y0_ref, xs0_ref, ys0_ref,
                   x_ref, y_ref, xs_ref, ys_ref):
    """All n_iters PDHG iterations for one scenario tile, VMEM-resident.

    Math is bit-for-bit the XLA path (ops/pdhg.py _pdhg_iter):
        v  = x - tau * A'y
        x1 = clip((v - tau c) / (1 + tau q), l, u)
        w  = y + sigma * A (2 x1 - x)
        y1 = w - sigma * clip(w / sigma, bl, bu)
    with `done` scenarios frozen and window sums accumulated.
    """
    hp = jax.lax.Precision.HIGHEST
    tau = tau_ref[:]          # (T, 1)
    sigma = sigma_ref[:]
    live = 1.0 - done_ref[:]  # (T, 1) 1.0 = still running
    c = c_ref[:]
    q = q_ref[:]
    l = l_ref[:]              # noqa: E741  (T|1, n)
    u = u_ref[:]
    bl = bl_ref[:]
    bu = bu_ref[:]
    A = A_ref[:]              # (m, n)
    AT = AT_ref[:]            # (n, m)

    def body(_, carry):
        x, y, xs, ys = carry
        aty = jax.lax.dot_general(
            y, A, (((1,), (0,)), ((), ())),
            precision=hp, preferred_element_type=jnp.float32)
        v = x - tau * aty
        x1 = jnp.clip((v - tau * c) / (1.0 + tau * q), l, u)
        ax = jax.lax.dot_general(
            2.0 * x1 - x, AT, (((1,), (0,)), ((), ())),
            precision=hp, preferred_element_type=jnp.float32)
        w = y + sigma * ax
        y1 = w - sigma * jnp.clip(w / sigma, bl, bu)
        x1 = x + live * (x1 - x)
        y1 = y + live * (y1 - y)
        # frozen scenarios keep accumulating their (frozen) iterate,
        # matching the XLA path exactly (ops/pdhg.py _pdhg_iter)
        return x1, y1, xs + x1, ys + y1

    x, y, xs, ys = jax.lax.fori_loop(
        0, n_iters, body,
        (x0_ref[:], y0_ref[:], xs0_ref[:], ys0_ref[:]))
    x_ref[:] = x
    y_ref[:] = y
    xs_ref[:] = xs
    ys_ref[:] = ys


def supported(p) -> bool:
    """Dense SHARED constraint matrix with a (S,)-batched problem."""
    A = p.A
    return (isinstance(A, jax.Array) or isinstance(A, np.ndarray)) \
        and getattr(A, "ndim", 0) == 2 and p.c.ndim == 2


@partial(jax.jit, static_argnames=("n_iters", "tile_s", "interpret"))
def run_window(p, x: Array, y: Array, x_sum: Array, y_sum: Array,
               tau: Array, sigma: Array, done: Array,
               n_iters: int, tile_s: int = 128, interpret: bool = False):
    """n_iters PDHG iterations over the whole scenario batch via the
    tiled Pallas kernel.  Returns (x, y, x_sum, y_sum).

    Shapes: x,c,q (S, n); y (S, m); tau/sigma/done (S,); A (m, n)
    shared.  l/u/bl/bu may be shared (1 leading dim after broadcast
    handling) or per-scenario.  Scenario/column/row axes are padded to
    hardware tiles; pad columns get l=u=0 (iterates pinned at 0), pad
    rows get free bounds (dual pinned at 0), pad scenarios are marked
    done — all three are exact no-ops on the real problem.
    """
    S, n = x.shape
    m = y.shape[-1]
    n_p = _round_up(n, 128)
    m_p = _round_up(m, 128)
    S_p = _round_up(S, tile_s)
    dt = x.dtype

    A = jnp.asarray(p.A, dt)
    A_pad = jnp.pad(A, ((0, m_p - m), (0, n_p - n)))
    AT_pad = A_pad.T

    def prep(arr, last, fill, batched_fill=None):
        """Pad last dim; pad/keep the scenario dim (shared stays (1,.))."""
        arr = jnp.asarray(arr, dt)
        if arr.ndim == 1:
            return _pad_last(arr, last, fill)[None, :]
        arr = _pad_last(arr, last, fill)
        pad_s = S_p - arr.shape[0]
        if pad_s:
            arr = jnp.concatenate(
                [arr, jnp.broadcast_to(arr[-1:], (pad_s, last))], axis=0)
        return arr

    c = prep(jnp.broadcast_to(p.c, (S, n)), n_p, 0.0)
    q = prep(jnp.broadcast_to(p.q, (S, n)), n_p, 0.0)
    l = prep(p.l, n_p, 0.0)   # noqa: E741
    u = prep(p.u, n_p, 0.0)
    bl = prep(jnp.clip(p.bl, -_BIG, _BIG), m_p, -_BIG)
    bu = prep(jnp.clip(p.bu, -_BIG, _BIG), m_p, _BIG)
    x_p = prep(x, n_p, 0.0)
    y_p = prep(y, m_p, 0.0)
    xs_p = prep(x_sum, n_p, 0.0)
    ys_p = prep(y_sum, m_p, 0.0)

    def prep_s(v, fill):
        v = jnp.asarray(v, dt)
        pad = S_p - v.shape[0]
        if pad:
            v = jnp.concatenate([v, jnp.full((pad,), fill, dt)])
        return v[:, None]

    tau_p = prep_s(tau, 1.0)
    sigma_p = prep_s(sigma, 1.0)
    done_p = prep_s(done.astype(dt), 1.0)  # pad scenarios frozen

    grid = (S_p // tile_s,)

    def vspec(arr, width):
        if arr.shape[0] == 1:
            return pl.BlockSpec((1, width), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)
        return pl.BlockSpec((tile_s, width), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    sspec = pl.BlockSpec((tile_s, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    aspec = pl.BlockSpec((m_p, n_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    atspec = pl.BlockSpec((n_p, m_p), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    out_shapes = [
        jax.ShapeDtypeStruct((S_p, n_p), dt),
        jax.ShapeDtypeStruct((S_p, m_p), dt),
        jax.ShapeDtypeStruct((S_p, n_p), dt),
        jax.ShapeDtypeStruct((S_p, m_p), dt),
    ]

    def ospec(width):
        return pl.BlockSpec((tile_s, width), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    out_specs = [ospec(n_p), ospec(m_p), ospec(n_p), ospec(m_p)]

    xo, yo, xso, yso = pl.pallas_call(
        partial(_window_kernel, n_iters),
        grid=grid,
        in_specs=[sspec, sspec, sspec,
                  vspec(c, n_p), vspec(q, n_p), vspec(l, n_p), vspec(u, n_p),
                  vspec(bl, m_p), vspec(bu, m_p), aspec, atspec,
                  vspec(x_p, n_p), vspec(y_p, m_p),
                  vspec(xs_p, n_p), vspec(ys_p, m_p)],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(tau_p, sigma_p, done_p, c, q, l, u, bl, bu, A_pad, AT_pad,
      x_p, y_p, xs_p, ys_p)

    return (xo[:S, :n], yo[:S, :m], xso[:S, :n], yso[:S, :m])
