###############################################################################
# Pallas TPU kernel: a full PDHG restart window in VMEM.
#
# The profiled 100k-scenario cliff (VERDICT r3 weak #5) is HBM
# bandwidth: one PDHG iteration is ~12 passes over (S, n)/(S, m) arrays
# (x, y, window sums, c, q, l, u, …), and at 100k scenarios nothing fits
# on-chip, so XLA's fori_loop body streams ~3 GB per iteration — the
# measured 1.6 s/PH-iteration matches the 819 GB/s v5e roofline almost
# exactly, while at 10k partial VMEM residency hides much of it.
#
# The fix is the classic TPU move: tile the scenario axis, park one
# tile's entire solver state in VMEM, and run ALL `restart_period`
# iterations on it in one kernel invocation.  HBM traffic per window
# drops from O(restart_period * state) to O(state) — a ~40x reduction —
# and the two matvecs per iteration ride the MXU against the SHARED
# dense (m, n) constraint matrix kept resident in VMEM.
#
# Scope: dense SHARED-A batches (the sslp/uc/netdes shape: deterministic
# constraint matrix, scenario-varying c/q/rhs).  ELL and per-scenario-A
# batches keep the XLA path (ops/pdhg.py _window falls back
# automatically).  Matmuls run at HIGHEST precision by default: single-
# pass bf16 MXU passes stall PDHG at ~1e-2 KKT residual on-chip
# (measured round 1); the opt-in bf16x3 iteration mode
# (PDHGOptions.iter_precision="bf16x3") halves bytes and passes while
# restart scoring stays exact.
#
# Two tile engines share one iteration trace (_tile_math):
#   * single-buffer grid kernel (pipeline=False): one tile per grid
#     step, operands staged by the BlockSpec pipeline;
#   * double-buffered pipeline (pipeline=True, default): one invocation
#     loops over tiles with manual async copies and two VMEM slots per
#     per-scenario operand, so the next tile's HBM->VMEM stream (and
#     the previous tile's write-back) overlaps the current tile's
#     compute — the fix for the S=100k profile where tile DMA was
#     serialized with compute (485 of 819 GB/s streamed).
#
# There is no reference analog to cite: mpi-sppy delegates subproblem
# solves to Gurobi (ref:mpisppy/spopt.py:884); this kernel is part of
# the TPU-native replacement for that solver, like ops/pdhg.py itself.
###############################################################################
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

_BIG = 1e30  # finite stand-in for +-inf row bounds (avoids inf-inf = nan)


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _pad_last(x: Array, size: int, value: float) -> Array:
    pad = size - x.shape[-1]
    if pad == 0:
        return x
    cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
    return jnp.pad(x, cfg, constant_values=value)


def _split_bf16(v):
    """Error-free-ish split v ~= hi + lo with hi, lo in bf16.

    The rounded value is materialized via lax.reduce_precision, NOT an
    astype round-trip: XLA's simplifier folds convert(convert(v, bf16),
    f32) back to v, which silently zeroes the lo term (measured on v5e:
    the 3-pass product degraded to 1-pass accuracy).  reduce_precision
    is the documented escape hatch the simplifier must honor."""
    rounded = jax.lax.reduce_precision(v, exponent_bits=8, mantissa_bits=7)
    hi = rounded.astype(jnp.bfloat16)
    lo = (v - rounded).astype(jnp.bfloat16)
    return hi, lo


def _split_bf16_kernel(v):
    """In-kernel variant of _split_bf16: Mosaic has no reduce_precision
    lowering, but it also lowers convert ops literally (no XLA-style
    algebraic folding of the f32->bf16->f32 round trip — verified on
    v5e by comparing one-iteration kernel output against the exact
    path), so the plain astype round trip is safe HERE and only here."""
    hi = v.astype(jnp.bfloat16)
    lo = (v - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


def _dot3(v_split, M_hi, M_lo):
    """bf16x3 matmul: 3 single-pass bf16 MXU dots with f32 accumulation
    (hi*hi + hi*lo + lo*hi), matching jax.lax.Precision.HIGH semantics —
    which Mosaic does not accept natively ("Unsupported dot precision:
    HIGH", measured on v5e), hence the manual decomposition.  Half the
    MXU passes of HIGHEST; accuracy suffices for INEXACT hot-loop
    windows only (restart scoring outside the kernel stays exact).

    `v_split` is a (hi, lo) pair from _split_bf16 (XLA callers) or
    _split_bf16_kernel (inside the Mosaic kernel) — the split must
    happen at the call site because the two compilers need different
    round-trip idioms (see those docstrings)."""
    dims = (((1,), (0,)), ((), ()))
    v_hi, v_lo = v_split
    acc = jax.lax.dot_general(v_hi, M_hi, dims,
                              preferred_element_type=jnp.float32)
    acc += jax.lax.dot_general(v_hi, M_lo, dims,
                               preferred_element_type=jnp.float32)
    acc += jax.lax.dot_general(v_lo, M_hi, dims,
                               preferred_element_type=jnp.float32)
    return acc


def _tile_math(n_iters: int, precision, has_cones: bool,
               tau0, sigma0, done, c, q, l, u, bl, bu,  # noqa: E741
               mats, cone_vals, x0, y0, xs0, ys0):
    """All n_iters PDHG iterations for one scenario tile, on VALUES.

    Shared by the single-buffer grid kernel (_window_kernel) and the
    double-buffered pipeline kernel (see run_window) — both engines run
    this exact trace on their VMEM-resident tile, so their outputs are
    bit-identical by construction (tests/test_pdhg_pallas.py).

    Math matches the XLA path (ops/pdhg.py _pdhg_iter) up to float
    reassociation (loop invariants are hoisted here, see below):
        v  = x - tau * A'y
        x1 = clip((v - tau c) / (1 + tau q), l, u)
        w  = y + sigma * A (2 x1 - x)
        y1 = w - sigma * clip(w / sigma, bl, bu)   (box rows)
        y1 = Proj_polar(w - sigma*b)               (SOC rows)
    with `done` scenarios frozen and window sums accumulated.

    SOC blockwise reductions (per-block head value / tail norm and the
    scatter back to rows) run as small MXU dots against 0/1 membership
    matrices (ops.cones.head_membership) — Mosaic has no scatter, but a
    (T, m) x (m, C) dot IS a segment sum with static shapes.
    """
    three_pass = precision == jax.lax.Precision.HIGH
    live = 1.0 - done         # (T, 1) 1.0 = still running
    # Done-masking folds into the step sizes: with tau = sigma = 0 the
    # iteration is an exact no-op (x1 = clip(x, l, u) = x since every
    # iterate is box-feasible; y1 = w - clip(w, 0, 0) = y), so frozen
    # scenarios need no blend passes — they still accumulate their
    # frozen iterate into the window sums, matching the XLA path.
    tau = tau0 * live         # (T, 1)
    sigma = sigma0 * live
    # Loop-invariant precomputes: the VPU, not the MXU, bounds this
    # kernel at bench shapes (measured: 6->3 MXU passes bought only
    # ~15%), and per-element divides are its costliest ops.  Hoisting
    # removes both in-loop divides and two multiplies per element.
    tc = tau * c              # (T, n)
    pre = 1.0 / (1.0 + tau * q)
    sbl = sigma * bl          # (T, m): sigma*clip(w/sigma,bl,bu)
    sbu = sigma * bu          # == clip(w, sigma*bl, sigma*bu)
    # rmv/mv follow BoxQP naming: rmv(y) = A'y (BoxQP.rmatvec),
    # mv(v) = A v (BoxQP.matvec) — contracting with the (m, n) block
    # computes A'y, with the (n, m) block computes A v.
    if three_pass:
        A_hi, AT_hi, A_lo, AT_lo = mats   # (m, n)/(n, m) bf16 splits

        def rmv(v, _A=A_hi, _Al=A_lo):
            return _dot3(_split_bf16_kernel(v), _A, _Al)

        def mv(v, _AT=AT_hi, _ATl=AT_lo):
            return _dot3(_split_bf16_kernel(v), _AT, _ATl)
    else:
        hp = precision if precision is not None else jax.lax.Precision.HIGHEST
        A, AT = mats              # (m, n), (n, m)

        def rmv(v, _A=A):
            return jax.lax.dot_general(
                v, _A, (((1,), (0,)), ((), ())),
                precision=hp, preferred_element_type=jnp.float32)

        def mv(v, _AT=AT):
            return jax.lax.dot_general(
                v, _AT, (((1,), (0,)), ((), ())),
                precision=hp, preferred_element_type=jnp.float32)

    if has_cones:
        (shift, socm, headm, Mhead, MheadT, Mtail, MtailT) = cone_vals
        tailm = socm - headm
        dims = (((1,), (0,)), ((), ()))

        def xdot(a, b):
            return jax.lax.dot_general(
                a, b, dims, precision=jax.lax.Precision.HIGHEST,
                preferred_element_type=jnp.float32)

        def soc_prox(w, y):
            """Proj_polar(w - sigma*shift) on SOC rows, frozen-exact:
            the tau=sigma=0 freeze trick does NOT make the cone branch
            a no-op (Proj_polar(y) != y in general), so frozen
            scenarios blend back to y explicitly via `live`."""
            wsh = w - sigma * shift
            blk = xdot(wsh * wsh * tailm, MtailT)      # (T, C) sum z^2
            tvals = xdot(wsh * headm, MheadT)          # (T, C) head t
            znorm = jnp.sqrt(blk)
            inside = znorm <= tvals
            pol = znorm <= -tvals
            alpha = 0.5 * (tvals + znorm)
            scale = jnp.where(inside, 1.0,
                              jnp.where(pol, 0.0,
                                        alpha / jnp.maximum(znorm, 1e-30)))
            tnew = jnp.where(inside, tvals, jnp.where(pol, 0.0, alpha))
            proj = headm * xdot(tnew, Mhead) \
                + tailm * (wsh * xdot(scale, Mtail))
            y_soc = wsh - proj
            return y + live * (y_soc - y)

    def body(_, carry):
        x, y, xs, ys = carry
        aty = rmv(y)            # A'y -> (T, n)
        x1 = jnp.clip((x - tau * aty - tc) * pre, l, u)
        ax = mv(2.0 * x1 - x)   # A(2x1 - x) -> (T, m)
        w = y + sigma * ax
        y1 = w - jnp.clip(w, sbl, sbu)
        if has_cones:
            y1 = jnp.where(socm > 0.0, soc_prox(w, y), y1)
        return x1, y1, xs + x1, ys + y1

    return jax.lax.fori_loop(0, n_iters, body, (x0, y0, xs0, ys0))


def _window_kernel(n_iters: int, precision, has_cones: bool, *refs):
    """Single-buffer grid kernel: one scenario tile per grid step, tile
    operands staged into VMEM by the BlockSpec pipeline, iteration math
    in _tile_math."""
    three_pass = precision == jax.lax.Precision.HIGH
    # matrix refs are present only for the precision mode in use (2 for
    # a single-dot mode, 4 for the bf16x3 split) — dead operands would
    # cost a DMA + VMEM residency per grid step
    nmat = 4 if three_pass else 2
    (tau_ref, sigma_ref, done_ref,
     c_ref, q_ref, l_ref, u_ref, bl_ref, bu_ref) = refs[:9]
    mat_refs = refs[9:9 + nmat]
    k = 9 + nmat
    cone_refs = refs[k:k + 7] if has_cones else ()
    k += 7 if has_cones else 0
    (x0_ref, y0_ref, xs0_ref, ys0_ref,
     x_ref, y_ref, xs_ref, ys_ref) = refs[k:]

    x, y, xs, ys = _tile_math(
        n_iters, precision, has_cones,
        tau_ref[:], sigma_ref[:], done_ref[:],
        c_ref[:], q_ref[:], l_ref[:], u_ref[:], bl_ref[:], bu_ref[:],
        tuple(r[:] for r in mat_refs),
        tuple(r[:] for r in cone_refs),
        x0_ref[:], y0_ref[:], xs0_ref[:], ys0_ref[:])
    x_ref[:] = x
    y_ref[:] = y
    xs_ref[:] = xs
    ys_ref[:] = ys


def _membership_padded(spec, m: int, m_p: int, dt):
    """(Mhead, MheadT, Mtail, MtailT) padded to (C_p, m_p).  Built
    inline per trace: run_window is jitted, so this runs once per
    compilation (not once per window) and XLA's compilation cache
    amortizes it — do NOT add a host-side cache here, the spec is a
    freshly-unflattened tracer pytree on every trace (unhashable,
    fresh id()), so caching can only leak tracers, never hit."""
    from mpisppy_tpu.ops import cones as cones_mod
    C_p = _round_up(max(spec.num_cones, 1), 128)
    Mhead, Mtail = cones_mod.head_membership(spec)
    Mhead = jnp.pad(Mhead.astype(dt),
                    ((0, C_p - spec.num_cones), (0, m_p - m)))
    Mtail = jnp.pad(Mtail.astype(dt),
                    ((0, C_p - spec.num_cones), (0, m_p - m)))
    return (Mhead, Mhead.T, Mtail, Mtail.T)


import dataclasses as _dc


@_dc.dataclass(frozen=True, eq=False)
class TileSynth:
    """In-kernel tile synthesis (scengen, docs/scengen.md): instead of
    DMA-ing a per-scenario data operand HBM->VMEM, the pipelined window
    engine calls `fn(tile_index)` INSIDE the kernel and writes the
    result straight into the VMEM working set — the DMA/compute overlap
    machinery becomes synth/compute for those operands, and the (S, ·)
    arrays never exist anywhere.

    names: which data operands fn produces (subset of c/q/l/u/bl/bu).
    fn:    trace-pure (tile_index, *const_values) ->
           {name: (tile_s, padded_width)} KERNEL-READY values — already
           scaled, padded, and bound-clipped exactly as run_window's
           prep() would have produced for that tile slice
           (scengen.tiles builds fn from a VirtualBatch and owns that
           contract).
    consts: arrays fn needs (base key, scalings, shared template rows)
           — Pallas kernels cannot capture array constants, so these
           ride as extra VMEM-resident kernel inputs and are handed to
           fn as values.

    Solver STATE (x/y/sums) and tau/sigma/done still stream via the
    double-buffered DMA pipeline — they are genuine state, not
    recomputable data.  eq=False keeps the object identity-hashable as
    a jit static argument.

    Portability: fn runs under the Pallas kernel compiler.  The XLA
    interpret path (CPU tests) accepts any jnp/jax.random sampler;
    Mosaic on real TPUs supports a narrower op set, so TPU deployments
    should keep samplers to elementwise/integer ops (threefry's ARX
    core lowers; exotic transcendentals may not) — the engine is
    opt-in (`synth=`), never auto-selected.
    """

    names: tuple
    fn: object
    consts: tuple = ()


def supported(p) -> bool:
    """Dense SHARED constraint matrix with a (S,)-batched problem.
    Conic problems (p.cones set) are supported: the kernel runs the SOC
    dual prox via membership-matrix dots (see _window_kernel)."""
    A = p.A
    return (isinstance(A, jax.Array) or isinstance(A, np.ndarray)) \
        and getattr(A, "ndim", 0) == 2 and p.c.ndim == 2


@partial(jax.jit,
         static_argnames=("n_iters", "tile_s", "precision", "pipeline",
                          "interpret", "synth"))
def run_window(p, x: Array, y: Array, x_sum: Array, y_sum: Array,
               tau: Array, sigma: Array, done: Array,
               n_iters: int, tile_s: int = 128,
               precision: str | None = None, pipeline: bool = True,
               interpret: bool = False, synth: "TileSynth | None" = None):
    """n_iters PDHG iterations over the whole scenario batch via the
    tiled Pallas kernel.  Returns (x, y, x_sum, y_sum).

    Shapes: x,c,q (S, n); y (S, m); tau/sigma/done (S,); A (m, n)
    shared.  l/u/bl/bu may be shared (1 leading dim after broadcast
    handling) or per-scenario.  Scenario/column/row axes are padded to
    hardware tiles; pad columns get l=u=0 (iterates pinned at 0), pad
    rows get free bounds (dual pinned at 0), pad scenarios are marked
    done — all three are exact no-ops on the real problem.

    `synth` (pipeline mode only): a TileSynth generating the named data
    operands in-kernel instead of streaming them — callers pass
    (1, width) placeholders for those fields in `p` (their values are
    never read), so nothing (S, ·)-shaped is materialized for them.

    `pipeline=True` (default) runs the DOUBLE-BUFFERED engine: one
    kernel invocation loops over scenario tiles, async-copying the next
    tile's solver state HBM->VMEM (and the previous tile's results
    VMEM->HBM) while the current tile runs its restart window — the
    S=100k fix for tile DMA serialized with compute (measured 485 of
    819 GB/s streamed before; ROADMAP item 2).  The shared dense A
    stays VMEM-resident either way.  False keeps the single-buffer
    grid kernel; both engines run the same _tile_math trace per tile,
    so their outputs are bit-identical (tests/test_pdhg_pallas.py).
    """
    S, n = x.shape
    m = y.shape[-1]
    n_p = _round_up(n, 128)
    m_p = _round_up(m, 128)
    S_p = _round_up(S, tile_s)
    dt = x.dtype

    from mpisppy_tpu.ops import boxqp
    # Resolve the module default HERE (trace time) so both engines honor
    # set_matvec_precision identically; a default of HIGH routes to the
    # manual three-pass decomposition (Mosaic rejects Precision.HIGH in
    # dot_general, so passing it through would crash the kernel).
    prec = boxqp.as_precision(precision)
    if prec is None:
        prec = boxqp.MATVEC_PRECISION
    three_pass = prec == jax.lax.Precision.HIGH

    A = jnp.asarray(p.A, dt)
    A_pad = jnp.pad(A, ((0, m_p - m), (0, n_p - n)))
    AT_pad = A_pad.T
    if three_pass:
        # hi/lo bf16 split of the shared matrix, done once per call —
        # the kernel runs 3 single-pass bf16 dots per matvec (see
        # _dot3).  MUST go through _split_bf16 (reduce_precision):
        # run_window is jitted XLA code, so an astype round trip here
        # would be simplifier-folded and zero the lo matrix.
        A_hi, A_lo = _split_bf16(A_pad)
        mats = (A_hi, A_hi.T, A_lo, A_lo.T)
    else:
        mats = (A_pad, AT_pad)

    def prep(arr, last, fill, batched_fill=None):
        """Pad last dim; pad/keep the scenario dim (shared stays (1,.))."""
        arr = jnp.asarray(arr, dt)
        if arr.ndim == 1:
            return _pad_last(arr, last, fill)[None, :]
        arr = _pad_last(arr, last, fill)
        pad_s = S_p - arr.shape[0]
        if pad_s:
            arr = jnp.concatenate(
                [arr, jnp.broadcast_to(arr[-1:], (pad_s, last))], axis=0)
        return arr

    c = prep(jnp.broadcast_to(p.c, (S, n)), n_p, 0.0)
    q = prep(jnp.broadcast_to(p.q, (S, n)), n_p, 0.0)
    l = prep(p.l, n_p, 0.0)   # noqa: E741
    u = prep(p.u, n_p, 0.0)
    bl = prep(jnp.clip(p.bl, -_BIG, _BIG), m_p, -_BIG)
    bu = prep(jnp.clip(p.bu, -_BIG, _BIG), m_p, _BIG)
    x_p = prep(x, n_p, 0.0)
    y_p = prep(y, m_p, 0.0)
    xs_p = prep(x_sum, n_p, 0.0)
    ys_p = prep(y_sum, m_p, 0.0)

    def prep_s(v, fill):
        v = jnp.asarray(v, dt)
        pad = S_p - v.shape[0]
        if pad:
            v = jnp.concatenate([v, jnp.full((pad,), fill, dt)])
        return v[:, None]

    tau_p = prep_s(tau, 1.0)
    sigma_p = prep_s(sigma, 1.0)
    done_p = prep_s(done.astype(dt), 1.0)  # pad scenarios frozen

    has_cones = p.cones is not None
    cone_ops = ()
    if has_cones:
        spec = p.cones
        # shift: bl on SOC rows (bl == bu == b by the ConeSpec contract),
        # 0 elsewhere; may be shared (m,) or per-scenario (S, m)
        shift = jnp.where(spec.is_soc, jnp.asarray(p.bl, dt), 0.0)
        shift_p = prep(shift, m_p, 0.0)
        socm = prep(spec.is_soc.astype(dt), m_p, 0.0)
        headm = prep(spec.is_head.astype(dt), m_p, 0.0)
        cone_ops = (shift_p, socm, headm) \
            + _membership_padded(spec, m, m_p, dt)

    if synth is not None:
        if not pipeline:
            raise ValueError("TileSynth requires the pipelined engine "
                             "(pipeline=True)")
        if has_cones:
            raise ValueError("TileSynth does not support conic batches")
        bad = set(synth.names) - {"c", "q", "l", "u", "bl", "bu"}
        if bad:
            raise ValueError(f"TileSynth cannot produce {sorted(bad)}")

    if pipeline:
        xo, yo, xso, yso = _run_window_pipelined(
            n_iters, prec, has_cones, tile_s, S_p, n_p, m_p, dt,
            mats, (tau_p, sigma_p, done_p, c, q, l, u, bl, bu),
            cone_ops, (x_p, y_p, xs_p, ys_p), interpret, synth)
        return (xo[:S, :n], yo[:S, :m], xso[:S, :n], yso[:S, :m])

    grid = (S_p // tile_s,)

    def vspec(arr, width):
        if arr.shape[0] == 1:
            return pl.BlockSpec((1, width), lambda i: (0, 0),
                                memory_space=pltpu.VMEM)
        return pl.BlockSpec((tile_s, width), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    sspec = pl.BlockSpec((tile_s, 1), lambda i: (i, 0),
                         memory_space=pltpu.VMEM)
    aspec = pl.BlockSpec((m_p, n_p), lambda i: (0, 0),
                         memory_space=pltpu.VMEM)
    atspec = pl.BlockSpec((n_p, m_p), lambda i: (0, 0),
                          memory_space=pltpu.VMEM)
    out_shapes = [
        jax.ShapeDtypeStruct((S_p, n_p), dt),
        jax.ShapeDtypeStruct((S_p, m_p), dt),
        jax.ShapeDtypeStruct((S_p, n_p), dt),
        jax.ShapeDtypeStruct((S_p, m_p), dt),
    ]

    def ospec(width):
        return pl.BlockSpec((tile_s, width), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)

    out_specs = [ospec(n_p), ospec(m_p), ospec(n_p), ospec(m_p)]

    mat_specs = [aspec, atspec] * (len(mats) // 2)
    cone_specs = []
    if has_cones:
        mspec = pl.BlockSpec((cone_ops[3].shape[0], m_p), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)
        mtspec = pl.BlockSpec((m_p, cone_ops[3].shape[0]), lambda i: (0, 0),
                              memory_space=pltpu.VMEM)
        cone_specs = [vspec(cone_ops[0], m_p), vspec(cone_ops[1], m_p),
                      vspec(cone_ops[2], m_p), mspec, mtspec, mspec, mtspec]
    xo, yo, xso, yso = pl.pallas_call(
        partial(_window_kernel, n_iters, prec, has_cones),
        grid=grid,
        in_specs=[sspec, sspec, sspec,
                  vspec(c, n_p), vspec(q, n_p), vspec(l, n_p), vspec(u, n_p),
                  vspec(bl, m_p), vspec(bu, m_p),
                  *mat_specs, *cone_specs,
                  vspec(x_p, n_p), vspec(y_p, m_p),
                  vspec(xs_p, n_p), vspec(ys_p, m_p)],
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(tau_p, sigma_p, done_p, c, q, l, u, bl, bu, *mats, *cone_ops,
      x_p, y_p, xs_p, ys_p)

    return (xo[:S, :n], yo[:S, :m], xso[:S, :n], yso[:S, :m])


def _run_window_pipelined(n_iters, prec, has_cones, tile_s, S_p, n_p, m_p,
                          dt, mats, params, cone_ops, state, interpret,
                          synth=None):
    """The double-buffered window engine (ROADMAP item 2 / ISSUE 8).

    One kernel invocation owns the whole scenario batch: per-scenario
    operands stay in HBM (memory_space=ANY) and a manual async-copy
    pipeline with TWO VMEM slots per operand prefetches tile t+1 while
    tile t computes, and drains tile t's results back to HBM while tile
    t+1 computes — the "prefetch-next-while-computing" discipline of
    the TPU distributed-linear-algebra line (PAPERS.md: arXiv
    2112.09017) applied to the solver-state stream that the profiler
    showed serialized with compute at S=100k (485 of 819 GB/s;
    telemetry/roofline.py overlap_frac / dma.exposed_s are the
    acceptance instruments).

    The shared dense A (and its bf16 hi/lo split under bf16x3), shared
    bound rows, and the SOC membership matrices remain VMEM-resident
    for the whole invocation, exactly like the single-buffer grid
    kernel.  Write-back slot reuse is fenced: before tile t overwrites
    output slot t%2, it waits on the DMA it issued for tile t-2 from
    that slot; the final two write-backs drain after the tile loop.

    Layout bookkeeping is static python: `dma_names` fixes the operand
    order (always-batched tau/sigma/done + solver state, plus whichever
    of c/q/l/u/bl/bu[/shift] are per-scenario); shared operands bypass
    the pipeline entirely.
    """
    n_tiles = S_p // tile_s
    tau_p, sigma_p, done_p, c, q, l, u, bl, bu = params  # noqa: E741
    named = [("tau", tau_p, 1), ("sigma", sigma_p, 1), ("done", done_p, 1),
             ("c", c, n_p), ("q", q, n_p), ("l", l, n_p), ("u", u, n_p),
             ("bl", bl, m_p), ("bu", bu, m_p)]
    cone_shared = ()
    if has_cones:
        named.append(("shift", cone_ops[0], m_p))
        cone_shared = tuple(cone_ops[1:])
    x_p, y_p, xs_p, ys_p = state
    named += [("x", x_p, n_p), ("y", y_p, m_p),
              ("xs", xs_p, n_p), ("ys", ys_p, m_p)]

    synth_names = () if synth is None else tuple(synth.names)
    synth_consts = () if synth is None else tuple(synth.consts)
    dma_names, dma_arrs, dma_widths = [], [], []
    shared_names, shared_arrs = [], []
    for nm, arr, w in named:
        if nm in synth_names:
            continue  # generated in-kernel by synth.fn — no operand
        if arr.shape[0] == 1:      # shared across the batch: no DMA
            shared_names.append(nm)
            shared_arrs.append(arr)
        else:
            dma_names.append(nm)
            dma_arrs.append(arr)
            dma_widths.append(w)
    n_in = len(dma_arrs)
    out_widths = (n_p, m_p, n_p, m_p)   # x, y, xs, ys

    def kernel(*refs):
        k = len(mats)
        mat_vals = tuple(r[:] for r in refs[:k])
        shared_vals = {nm: r[:] for nm, r
                       in zip(shared_names, refs[k:k + len(shared_arrs)])}
        k += len(shared_arrs)
        cone_shared_vals = tuple(r[:] for r
                                 in refs[k:k + len(cone_shared)])
        k += len(cone_shared)
        synth_const_vals = tuple(r[:] for r
                                 in refs[k:k + len(synth_consts)])
        k += len(synth_consts)
        in_refs = refs[k:k + n_in]
        k += n_in
        out_refs = refs[k:k + 4]
        k += 4
        scr_in = refs[k:k + n_in]
        k += n_in
        scr_out = refs[k:k + 4]
        k += 4
        insem, outsem = refs[k], refs[k + 1]

        def in_dma(j, slot, t):
            return pltpu.make_async_copy(
                in_refs[j].at[pl.ds(t * tile_s, tile_s)],
                scr_in[j].at[slot],
                insem.at[slot, j])

        def out_dma(j, slot, t):
            return pltpu.make_async_copy(
                scr_out[j].at[slot],
                out_refs[j].at[pl.ds(t * tile_s, tile_s)],
                outsem.at[slot, j])

        # warm-up: tile 0's state starts streaming before any compute
        for j in range(n_in):
            in_dma(j, 0, 0).start()

        def tile_body(t, carry):
            cur = jax.lax.rem(t, 2)
            nxt = jax.lax.rem(t + 1, 2)

            # prefetch tile t+1 into the other slot while t computes.
            # Its previous occupant (tile t-1's inputs) was fully
            # consumed during iteration t-1 — loads happen before this
            # point in program order — so the slot is free.
            @pl.when(t + 1 < n_tiles)
            def _():
                for j in range(n_in):
                    in_dma(j, nxt, t + 1).start()

            for j in range(n_in):
                in_dma(j, cur, t).wait()

            v = dict(shared_vals)
            for nm, scr in zip(dma_names, scr_in):
                v[nm] = scr[cur]
            if synth is not None:
                # scengen: this tile's data operands are COMPUTED in
                # the kernel (counter-based draws keyed by scenario
                # index) — the synth/compute analog of the prefetch
                # overlap; there is no HBM stream to hide for them
                v.update(synth.fn(t, *synth_const_vals))
            cone_vals = ()
            if has_cones:
                cone_vals = (v["shift"],) + cone_shared_vals
            x1, y1, xs1, ys1 = _tile_math(
                n_iters, prec, has_cones,
                v["tau"], v["sigma"], v["done"],
                v["c"], v["q"], v["l"], v["u"], v["bl"], v["bu"],
                mat_vals, cone_vals, v["x"], v["y"], v["xs"], v["ys"])

            # fence: the write-back issued from this slot two tiles ago
            # must land before the slot is overwritten
            @pl.when(t >= 2)
            def _():
                for j in range(4):
                    out_dma(j, cur, t - 2).wait()
            for j, val in enumerate((x1, y1, xs1, ys1)):
                scr_out[j][cur] = val
            for j in range(4):
                out_dma(j, cur, t).start()
            return carry

        jax.lax.fori_loop(0, n_tiles, tile_body, 0)
        # drain the last (up to) two in-flight write-backs
        if n_tiles >= 2:
            for j in range(4):
                out_dma(j, (n_tiles - 2) % 2, n_tiles - 2).wait()
        for j in range(4):
            out_dma(j, (n_tiles - 1) % 2, n_tiles - 1).wait()

    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    hbm = pl.BlockSpec(memory_space=pltpu.ANY)
    n_resident = len(mats) + len(shared_arrs) + len(cone_shared) \
        + len(synth_consts)
    return pl.pallas_call(
        kernel,
        in_specs=[vmem] * n_resident + [hbm] * n_in,
        out_specs=[hbm] * 4,
        out_shape=[jax.ShapeDtypeStruct((S_p, w), dt) for w in out_widths],
        scratch_shapes=(
            [pltpu.VMEM((2, tile_s, w), dt) for w in dma_widths]
            + [pltpu.VMEM((2, tile_s, w), dt) for w in out_widths]
            + [pltpu.SemaphoreType.DMA((2, n_in)),
               pltpu.SemaphoreType.DMA((2, 4))]),
        interpret=interpret,
    )(*mats, *shared_arrs, *cone_shared, *synth_consts, *dma_arrs)
