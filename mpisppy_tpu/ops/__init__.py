from mpisppy_tpu.ops.boxqp import BoxQP, kkt_residuals, objective  # noqa: F401
from mpisppy_tpu.ops.pdhg import PDHGOptions, PDHGState, solve, solve_batch  # noqa: F401
