###############################################################################
# Restarted PDHG (PDLP-style) for batched BoxQPs.
#
# This kernel plays the role Gurobi/CPLEX play in the reference
# (ref:mpisppy/spopt.py:99-247, spopt.py:876-960): it is THE subproblem
# solver.  Design points, all TPU-driven:
#
#   * One batched tensor program: every field carries an optional leading
#     scenario axis; matvecs become (S,m,n)x(S,n) einsums that XLA tiles
#     onto the MXU.  A thousand scenario LPs are one program, not a
#     thousand solver calls (contrast ref:mpisppy/spopt.py:250-341, a
#     sequential Python loop over per-scenario solver plugins).
#   * No data-dependent Python control flow: the solve is a
#     lax.while_loop over restart windows, each window a lax.fori_loop of
#     PDHG iterations.  Per-problem termination is a `done` mask, not an
#     early exit, so the batch stays rectangular for XLA.
#   * Warm starts are first-class: PH re-solves the same constraint data
#     with updated linear/diagonal-quadratic terms every iteration, so
#     PDHGState (iterates + step-size machinery) is carried across calls.
#
# Algorithm: Chambolle-Pock primal-dual hybrid gradient with
#   - exact prox of c'x + 1/2 q x^2 over [l,u] (diagonal q),
#   - dual prox of the [bl,bu] row-indicator via Moreau,
#   - ADAPTIVE restart-to-average: candidates (better of {current,
#     window average} by relative KKT score) are evaluated every
#     `restart_period` iterations, but a restart fires only on
#     sufficient score decay (or at a forced window cap) — per batch
#     element.  A fixed short restart cadence stalls on degenerate LPs
#     (observed on the sslp extensive form: 200k iters stuck at 1.7e-2
#     primal residual vs 1.6k iters with longer windows),
#   - adaptive primal weight omega rebalancing primal/dual step sizes
#     (tau = omega/||A||, sigma = 1/(omega ||A||)), updated at restarts,
# following the PDLP recipe (Applegate et al.; see also MPAX in
# PAPERS.md) re-implemented from the math, not from any codebase.
###############################################################################
from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from mpisppy_tpu.ops.boxqp import (
    BoxQP, infeasibility_certificate, kkt_residuals,
    unboundedness_certificate,
)

Array = jax.Array

# Per-problem statuses (ref:mpisppy/spopt.py:76-96,194-231 reads these
# off Gurobi; here the kernel certifies them itself).
RUNNING = 0       # not terminated (hit max_iters => unconverged)
OPTIMAL = 1
INFEASIBLE = 2    # certified by a Farkas ray
UNBOUNDED = 3     # certified by a recession direction with c'd < 0


@dataclasses.dataclass(frozen=True)
class PDHGOptions:
    """Static solver options (hashable: safe as a jit static arg)."""

    tol: float = 1e-6  # floored at 5*eps of the working dtype at solve time
    max_iters: int = 20_000
    # Auto-chunking: a single XLA dispatch whose while_loop can run more
    # than this many iterations is split into multiple capped host
    # dispatches (the axon TPU worker dies on ~100k-iteration single
    # dispatches — a library user must not need bench-harness chunking
    # to be safe).  Only applies to HOST-LEVEL solve() calls; inside a
    # jit trace the caller owns the budget.  0 disables.
    dispatch_cap: int = 60_000
    restart_period: int = 40   # candidate-check cadence (iterations)
    omega0: float = 1.0
    power_iters: int = 30
    omega_min: float = 1e-4
    omega_max: float = 1e4
    step_margin: float = 0.99  # tau*sigma*||A||^2 = step_margin^2 < 1
    restart_decay: float = 0.5  # restart on score <= decay * score@restart
    max_window: int = 16        # forced restart after this many periods
    detect_infeas: bool = False  # per-problem Farkas/recession certificates
    certificate_tol: float = 1e-4
    # window-iteration engine: None = auto (the Pallas VMEM-resident
    # window kernel on TPU for dense shared-A batches at scale — the
    # 100k-scenario HBM-bandwidth fix, ops/pdhg_pallas.py — else the
    # XLA fori_loop); True/False forces.
    use_pallas: bool | None = None
    # scenario-tile height for the Pallas window kernel; larger tiles
    # lift MXU utilization (bigger GEMM M dim, fewer grid steps) until
    # the tile's solver state outgrows VMEM
    pallas_tile_s: int = 128
    # Double-buffer the Pallas window kernel's scenario tiles: the next
    # tile's solver state is async-copied HBM->VMEM while the current
    # tile runs its restart window, and finished tiles write back
    # asynchronously — the S=100k fix for tile DMA serialized with
    # compute (ops/pdhg_pallas.py; measured 485 of 819 GB/s before).
    # False keeps the single-buffer grid kernel (same math bit-for-bit,
    # tests/test_pdhg_pallas.py).
    pallas_pipeline: bool = True
    # MXU precision for the ITERATION matvecs only (restart candidate
    # scoring and convergence tests always run at the boxqp module
    # default, HIGHEST = bf16x6, so a cheaper iteration precision can
    # never mis-certify a solution).  None = module default; "bf16x3"
    # (alias "high") = 3-pass bf16 — half the HBM bytes and MXU passes
    # per matvec, ~4e-6 relative error per matvec, measured on-chip to
    # reach ~1e-6 relative KKT on sslp-family LPs when scoring stays
    # exact.  Aliases resolve through ops/boxqp.py PRECISION_ALIASES;
    # unknown strings raise at trace time with the valid list.
    iter_precision: str | None = None
    # Per-lane divergence guard (resilience subsystem, docs/resilience.md):
    # at each restart boundary, lanes whose iterates are non-finite or
    # exceed guard_threshold in magnitude are QUARANTINE-RESET — primal
    # re-clipped from zero, dual zeroed, window sums/anchors cleared,
    # omega halved (a too-aggressive primal weight is the usual
    # divergence driver) — at most guard_max_resets times per lane,
    # after which the lane is frozen `done` with status RUNNING so it
    # can never certify a bound.  Healthy lanes are untouched and a
    # False flag compiles the exact pre-guard program.
    lane_guard: bool = False
    guard_threshold: float = 1e12
    guard_max_resets: int = 3
    # On-device kernel counters (telemetry subsystem, docs/telemetry.md):
    # accumulate per-lane iteration/restart/omega-adaptation counts plus
    # a small KKT-score ring at each restart boundary, inside the jit
    # graph, harvested host-side in ONE transfer
    # (telemetry.counters.harvest_state).  False leaves PDHGState.counters
    # None — zero extra leaves, and the lowered program is byte-identical
    # to a build that never imported telemetry (tests/test_telemetry.py).
    telemetry: bool = False
    telemetry_ring: int = 8   # score samples kept per lane


@partial(
    jax.tree_util.register_dataclass,
    data_fields=[
        "x", "y", "x_sum", "y_sum", "x_anchor", "y_anchor",
        "omega", "Lnorm", "k", "nwin", "restart_score", "score", "done",
        "status", "guard_resets", "counters",
    ],
    meta_fields=[],
)
@dataclasses.dataclass(frozen=True)
class PDHGState:
    x: Array        # (..., n) primal iterate
    y: Array        # (..., m) dual iterate
    x_sum: Array    # running window sums for restart-to-average
    y_sum: Array
    x_anchor: Array  # iterate at last restart (for omega adaptation)
    y_anchor: Array
    omega: Array    # (...,) primal weight
    Lnorm: Array    # (...,) ||A||_2 estimate
    k: Array        # () global iteration counter
    nwin: Array     # (...,) iterations since this problem's last restart
    restart_score: Array  # (...,) candidate score at last restart
    score: Array    # (...,) last max relative KKT residual
    done: Array     # (...,) bool
    status: Array   # (...,) int32 RUNNING/OPTIMAL/INFEASIBLE/UNBOUNDED
    guard_resets: Array   # (...,) int32 cumulative lane-guard quarantines
    # telemetry.counters.KernelCounters when opts.telemetry, else None
    # (None flattens to zero leaves: the off path's pytree and program
    # are exactly the pre-telemetry ones)
    counters: object = None


def _bshape(p: BoxQP):
    """Batch shape of a problem: () or (S,)."""
    return p.c.shape[:-1]


@partial(jax.jit, static_argnames=("iters",))
def estimate_norm(p: BoxQP, iters: int = 30) -> Array:
    """Power iteration for ||A||_2, batch-aware.

    Seeded with a fixed PRNG vector (an all-ones seed lies in null(A'A)
    for difference-row matrices — exactly the shape of nonanticipativity
    rows — and collapses the iterate to zero).  The result is floored by
    the max row/column 2-norms, both guaranteed lower bounds on ||A||_2,
    so a degenerate iterate can never produce an underestimate that makes
    tau explode.

    Jitted (shape-keyed): called eagerly, the fori_loop would otherwise
    close over p's VALUES as jaxpr constants and XLA would compile a
    fresh scan executable for every distinct QP — one silent recompile
    per solve_mip/dive call (found by the dispatch compile guard,
    docs/dispatch.md)."""
    v = jax.random.normal(jax.random.PRNGKey(7), p.c.shape, p.c.dtype)
    v = v / jnp.linalg.norm(v, axis=-1, keepdims=True)

    def body(_, carry):
        v, _ = carry
        w = p.rmatvec(p.matvec(v))
        nrm = jnp.linalg.norm(w, axis=-1, keepdims=True)
        nrm = jnp.maximum(nrm, 1e-30)
        return w / nrm, nrm[..., 0]

    _, lam = jax.lax.fori_loop(0, iters, body, (v, jnp.ones(_bshape(p), p.c.dtype)))
    if hasattr(p.A, "row_sqnorms"):   # ops.sparse.EllMatrix
        row_lb = jnp.sqrt(jnp.max(p.A.row_sqnorms(), axis=-1))
        col_lb = jnp.sqrt(jnp.max(p.A.col_sqnorms(), axis=-1))
    else:
        row_lb = jnp.sqrt(jnp.max(jnp.sum(p.A * p.A, axis=-1), axis=-1))
        col_lb = jnp.sqrt(jnp.max(jnp.sum(p.A * p.A, axis=-2), axis=-1))
    lb = jnp.maximum(jnp.maximum(row_lb, col_lb), 1e-12)
    # lb broadcasts when A is shared across a batched c
    return jnp.maximum(jnp.sqrt(lam), lb)


def init_state(p: BoxQP, opts: PDHGOptions = PDHGOptions(),
               x0: Array | None = None, y0: Array | None = None) -> PDHGState:
    bs = _bshape(p)
    dt = p.c.dtype
    if x0 is None:
        x0 = jnp.clip(jnp.zeros_like(p.c), p.l, p.u)
    if y0 is None:
        y0 = jnp.zeros(bs + (p.m,), dt)
    L = estimate_norm(p, opts.power_iters)
    return PDHGState(
        x=x0, y=y0,
        x_sum=jnp.zeros_like(x0), y_sum=jnp.zeros_like(y0),
        x_anchor=x0, y_anchor=y0,
        omega=jnp.full(bs, opts.omega0, dt),
        Lnorm=L.astype(dt),
        k=jnp.zeros((), jnp.int32),
        nwin=jnp.zeros(bs, jnp.int32),
        restart_score=jnp.full(bs, jnp.inf, dt),
        score=jnp.full(bs, jnp.inf, dt),
        done=jnp.zeros(bs, bool),
        status=jnp.zeros(bs, jnp.int32),
        guard_resets=jnp.zeros(bs, jnp.int32),
        counters=_init_counters(bs, dt, opts),
    )


def _init_counters(bs, dt, opts: PDHGOptions):
    if not opts.telemetry:
        return None
    from mpisppy_tpu.telemetry import counters as kcounters
    return kcounters.init_counters(bs, dt, ring_size=opts.telemetry_ring)


def _iter_precision(opts: PDHGOptions):
    from mpisppy_tpu.ops.boxqp import as_precision
    return as_precision(opts.iter_precision)


def _pdhg_iter(p: BoxQP, st: PDHGState, tau: Array, sigma: Array,
               precision=None) -> PDHGState:
    """One PDHG step; frozen for problems already `done`.

    The dual prox dispatches per row at TRACE time: pure box problems
    (p.cones is None) keep the two-sided clip; conic problems route
    through ops.cones.dual_prox, which clips box rows and applies the
    Moreau second-order-cone projection blockwise on SOC rows."""
    t = tau[..., None]
    s = sigma[..., None]
    v = st.x - t * p.rmatvec(st.y, precision=precision)
    x1 = jnp.clip((v - t * p.c) / (1.0 + t * p.q), p.l, p.u)
    w = st.y + s * p.matvec(2.0 * x1 - st.x, precision=precision)
    if p.cones is None:
        y1 = w - s * jnp.clip(w / s, p.bl, p.bu)
    else:
        from mpisppy_tpu.ops import cones as cones_mod
        y1 = cones_mod.dual_prox(p.cones, w, s, p.bl, p.bu)
    keep = st.done[..., None]
    x1 = jnp.where(keep, st.x, x1)
    y1 = jnp.where(keep, st.y, y1)
    return dataclasses.replace(
        st, x=x1, y=y1, x_sum=st.x_sum + x1, y_sum=st.y_sum + y1,
    )


def _restart(p: BoxQP, st: PDHGState, opts: PDHGOptions) -> PDHGState:
    """Adaptive restart-to-average + omega adaptation + convergence check.

    Every call evaluates the restart candidate (the better of the
    current iterate and the window average by relative KKT score), but
    the restart — adopt candidate, clear the window, adapt omega — only
    fires per batch element when the candidate score has decayed to
    `restart_decay` of the score at that element's last restart, or the
    window hits `max_window` periods (PDLP's artificial restart).  A
    short fixed cadence provably stalls on degenerate LPs; an
    ever-growing window goes stale — this is the standard middle ground.
    """
    navg = jnp.maximum(st.nwin, 1).astype(st.x.dtype)[..., None]
    xa, ya = st.x_sum / navg, st.y_sum / navg

    rp_c, rd_c, rg_c = kkt_residuals(p, st.x, st.y)
    rp_a, rd_a, rg_a = kkt_residuals(p, xa, ya)
    score_c = jnp.maximum(jnp.maximum(rp_c, rd_c), rg_c)
    score_a = jnp.maximum(jnp.maximum(rp_a, rd_a), rg_a)

    take_avg = (score_a < score_c)[..., None]
    xr = jnp.where(take_avg, xa, st.x)
    yr = jnp.where(take_avg, ya, st.y)
    score = jnp.minimum(score_a, score_c)

    # Dtype-aware tolerance floor: relative KKT residuals near eps are
    # unreachable in the working precision; without a floor a too-tight
    # `tol` silently burns max_iters with done=False.  5*eps (~6e-7 in
    # f32) sits below the 1e-6 default so ordinary tolerances are
    # honored exactly.
    tol = jnp.maximum(opts.tol, 5.0 * jnp.finfo(st.x.dtype).eps)
    newly_done = score <= tol

    fire = (score <= opts.restart_decay * st.restart_score) \
        | (st.nwin >= opts.max_window * opts.restart_period) \
        | newly_done

    # Primal-weight adaptation (theta = 0.5 log-space smoothing),
    # applied only at restarts.  Balance criterion: with tau = omega/L
    # and sigma = 1/(omega*L), equalizing per-window travel
    # |dx|/tau = |dy|/sigma gives omega ~ |dx|/|dy| — i.e. a fast-moving
    # DUAL shrinks omega (bigger dual steps).  The inverted ratio
    # (dy/dx) is a positive feedback loop that blew omega up to O(100)
    # and stalled fixed-nonant recourse solves.
    dx = jnp.linalg.norm(xr - st.x_anchor, axis=-1)
    dy = jnp.linalg.norm(yr - st.y_anchor, axis=-1)
    valid = fire & (dx > 1e-12) & (dy > 1e-12)
    omega_new = jnp.exp(0.5 * jnp.log(jnp.where(valid, dx / jnp.maximum(dy, 1e-30), 1.0))
                        + 0.5 * jnp.log(st.omega))
    omega = jnp.clip(jnp.where(valid, omega_new, st.omega),
                     opts.omega_min, opts.omega_max)

    status = jnp.where(~st.done & newly_done, OPTIMAL, st.status)
    if opts.detect_infeas:
        # Approximate rays: the per-window displacement converges to the
        # infimal displacement vector — nonzero dual part certifies
        # primal infeasibility, nonzero primal part + descent certifies
        # unboundedness (PDLP's detection recipe, from the math).
        # Detection is gated on the solve being far from converged: near
        # optimality q(y*) can round to +O(eps) in f32 and the iterate
        # test would false-positive; an infeasible/unbounded problem
        # never gets a small KKT score, so nothing real is lost.
        ctol = opts.certificate_tol
        far = score > jnp.maximum(1e-3, 10.0 * tol)
        infeas = far & (infeasibility_certificate(p, yr - st.y_anchor, ctol)
                        | infeasibility_certificate(p, yr, ctol))
        unbd = far & unboundedness_certificate(p, xr - st.x_anchor, ctol)
        status = jnp.where(~st.done & ~newly_done & infeas, INFEASIBLE,
                           status)
        status = jnp.where((status == RUNNING) & unbd, UNBOUNDED, status)
        newly_done = newly_done | ((status != RUNNING) & ~st.done)

    act = fire & ~st.done           # restart these elements
    actx = act[..., None]
    return dataclasses.replace(
        st,
        x=jnp.where(actx, xr, st.x),
        y=jnp.where(actx, yr, st.y),
        x_sum=jnp.where(actx, 0.0, st.x_sum),
        y_sum=jnp.where(actx, 0.0, st.y_sum),
        x_anchor=jnp.where(actx, xr, st.x_anchor),
        y_anchor=jnp.where(actx, yr, st.y_anchor),
        omega=jnp.where(st.done, st.omega, omega),
        nwin=jnp.where(act, 0, st.nwin),
        restart_score=jnp.where(act, score, st.restart_score),
        score=jnp.where(st.done, st.score, score),
        done=st.done | newly_done,
        status=status,
    )


def _lane_guard(p: BoxQP, st: PDHGState, opts: PDHGOptions) -> PDHGState:
    """Quarantine-reset diverged lanes (resilience subsystem).

    A lane (batch element) is DIVERGED when its iterates are non-finite
    or exceed guard_threshold in magnitude — the signature of a badly
    conditioned scenario, a poisoned warm start, or an omega runaway.
    Such a lane never converges on its own (NaN propagates; the done
    mask keeps the rest of the batch correct but the while_loop burns
    max_iters on the dead lane), so the guard re-initializes ONLY the
    bad lanes from scratch with halved omega, up to guard_max_resets
    times; past the budget the lane is frozen `done` with status
    RUNNING, which no certificate path ever accepts — the batch
    completes and the wheel degrades gracefully instead of stalling.
    Counters are surfaced in PDHGState.guard_resets (cumulative)."""
    mag = jnp.maximum(jnp.max(jnp.abs(st.x), axis=-1),
                      jnp.max(jnp.abs(st.y), axis=-1))
    bad = ~st.done & (~jnp.isfinite(mag) | (mag > opts.guard_threshold))
    give_up = bad & (st.guard_resets >= opts.guard_max_resets)
    # EVERY bad lane gets its iterates scrubbed — a frozen lane's x
    # feeds downstream consumers (PH's xbar/W node averages have no
    # NaN masking), so give-up must freeze a CLEAN unconverged point,
    # never the poisoned one
    rx = bad[..., None]
    x0 = jnp.clip(jnp.zeros_like(st.x), p.l, p.u)
    return dataclasses.replace(
        st,
        x=jnp.where(rx, x0, st.x),
        y=jnp.where(rx, 0.0, st.y),
        x_sum=jnp.where(rx, 0.0, st.x_sum),
        y_sum=jnp.where(rx, 0.0, st.y_sum),
        x_anchor=jnp.where(rx, x0, st.x_anchor),
        y_anchor=jnp.where(rx, 0.0, st.y_anchor),
        omega=jnp.where(bad,
                        jnp.maximum(jnp.where(jnp.isfinite(st.omega),
                                              0.5 * st.omega, opts.omega0),
                                    opts.omega_min),
                        st.omega),
        nwin=jnp.where(bad, 0, st.nwin),
        restart_score=jnp.where(bad, jnp.inf, st.restart_score),
        score=jnp.where(bad, jnp.inf, st.score),
        guard_resets=st.guard_resets + bad.astype(jnp.int32),
        done=st.done | give_up,
    )


def _use_pallas_window(p: BoxQP, st: PDHGState, opts: PDHGOptions) -> bool:
    """Engine choice, resolved at TRACE time (all inputs static)."""
    if opts.use_pallas is not None:
        # static options field, not a device value
        return bool(opts.use_pallas)      # graftlint: allow-host-sync
    from mpisppy_tpu.ops import pdhg_pallas
    # measured crossover on v5e (sslp shapes): XLA wins to ~10k
    # scenarios (partial VMEM residency), the kernel wins at ~100k
    # (1.45 vs 0.62 it/s) where the XLA loop is HBM-bandwidth-bound
    return (jax.default_backend() == "tpu"
            and pdhg_pallas.supported(p)
            and st.x.ndim == 2 and st.x.shape[0] >= 32768)


def _window(p: BoxQP, st: PDHGState, opts: PDHGOptions) -> PDHGState:
    tau = opts.step_margin * st.omega / st.Lnorm
    sigma = opts.step_margin / (st.omega * st.Lnorm)
    pre_done, pre_omega = st.done, st.omega
    if _use_pallas_window(p, st, opts):
        from mpisppy_tpu.ops import pdhg_pallas
        interp = jax.default_backend() != "tpu"
        x, y, xs, ys = pdhg_pallas.run_window(
            p, st.x, st.y, st.x_sum, st.y_sum, tau, sigma, st.done,
            opts.restart_period, tile_s=opts.pallas_tile_s,
            precision=opts.iter_precision,
            pipeline=opts.pallas_pipeline, interpret=interp)
        st = dataclasses.replace(st, x=x, y=y, x_sum=xs, y_sum=ys)
    else:
        prec = _iter_precision(opts)
        st = jax.lax.fori_loop(
            0, opts.restart_period,
            lambda _, s: _pdhg_iter(p, s, tau, sigma, prec), st)
    st = dataclasses.replace(st, nwin=st.nwin + opts.restart_period)
    st = _restart(p, st, opts)
    if opts.telemetry:
        # the restart boundary is the harvest point (MPAX, PAPERS.md):
        # nwin was just incremented by restart_period, so a zero here
        # means _restart's act mask fired for that lane.  Recorded
        # BEFORE the lane guard (a quarantine also clears nwin, and is
        # already counted separately in guard_resets).
        from mpisppy_tpu.telemetry import counters as kcounters
        st = dataclasses.replace(st, counters=kcounters.record_window(
            st.counters, active=~pre_done,
            restarted=st.nwin == 0,
            omega_moved=st.omega != pre_omega,
            score=st.score, period=opts.restart_period))
    if opts.lane_guard:
        st = _lane_guard(p, st, opts)
    return dataclasses.replace(st, k=st.k + opts.restart_period)


def will_chunk(opts: PDHGOptions) -> bool:
    """True when a host-level solve() with these options auto-chunks.
    Shared predicate so wrappers that pick a jitted fast path (e.g.
    lagrangian_bound) can never disagree with solve() about chunk
    eligibility — disagreement would reintroduce the oversized single
    dispatch the cap exists to prevent."""
    return 0 < opts.dispatch_cap < opts.max_iters


def solve(p: BoxQP, opts: PDHGOptions = PDHGOptions(),
          state: PDHGState | None = None) -> PDHGState:
    """Solve to tolerance (batch-aware).  Jit-friendly:
    ``jax.jit(solve, static_argnames='opts')``.

    Host-level calls with max_iters > dispatch_cap are automatically
    split into multiple capped dispatches (see PDHGOptions.dispatch_cap);
    traced calls keep the single while_loop — a jit caller owns its
    budget.
    """
    if state is None:
        st = init_state(p, opts)
    else:
        # Reuse iterates + step machinery; reset bookkeeping.
        st = dataclasses.replace(
            state,
            x_sum=jnp.zeros_like(state.x), y_sum=jnp.zeros_like(state.y),
            x_anchor=state.x, y_anchor=state.y,
            k=jnp.zeros((), jnp.int32),
            nwin=jnp.zeros_like(state.nwin),
            restart_score=jnp.full(state.omega.shape, jnp.inf, state.x.dtype),
            score=jnp.full(state.omega.shape, jnp.inf, state.x.dtype),
            done=jnp.zeros(state.omega.shape, bool),
            status=jnp.zeros_like(state.status),
        )
        if opts.telemetry and st.counters is None:
            # warm state built under telemetry-off options: counters
            # start at zero from here (totals are per solve lineage)
            st = dataclasses.replace(
                st, counters=_init_counters(st.omega.shape, st.x.dtype,
                                            opts))

    # a call is host-level only when NOTHING is traced — a concrete qp
    # with a traced state (vmap/jit over state with a captured problem)
    # must keep the in-trace while_loop
    traced = any(isinstance(leaf, jax.core.Tracer)
                 for leaf in jax.tree_util.tree_leaves((p, st)))
    if not traced and will_chunk(opts):
        while True:
            st = _dispatch_capped(p, opts, st)
            # the documented host seam of the auto-chunk loop: one
            # scalar read between capped dispatches decides whether to
            # re-dispatch                       # graftlint: allow-host-sync
            if int(st.k) >= opts.max_iters or bool(jnp.all(st.done)):
                return st

    # ALWAYS through the jitted, shape-keyed loop.  Called eagerly the
    # while_loop would close over p's VALUES as jaxpr constants — one
    # silent XLA compile per distinct QP per call, the same leak class
    # the dispatch compile guard caught in estimate_norm after PR 4
    # (now also flagged at lint time: tools/graftlint trace-purity).
    # Inside an outer trace the nested jit inlines, so traced callers
    # compile exactly what they did before.
    return _solve_loop_jit(p, opts, st)


@partial(jax.jit, static_argnames=("opts",))
def _solve_loop_jit(p: BoxQP, opts: PDHGOptions,
                    st: PDHGState) -> PDHGState:
    """The run-to-tolerance while_loop, jitted so host-level solve()
    calls key the compile cache on shapes+opts, never on QP values."""
    def cond(s):
        return (s.k < opts.max_iters) & ~jnp.all(s.done)

    return jax.lax.while_loop(cond, lambda s: _window(p, s, opts), st)


@partial(jax.jit, static_argnames=("opts",))
def _solve_capped_jit(p: BoxQP, opts: PDHGOptions,
                      st: PDHGState) -> PDHGState:
    """One capped dispatch: at most dispatch_cap MORE iterations past the
    entry count st.k (which persists across chunks, so restart windows
    and omega adaptation carry over seamlessly)."""
    k0 = st.k

    def cond(s):
        return (s.k < opts.max_iters) & ((s.k - k0) < opts.dispatch_cap) \
            & ~jnp.all(s.done)

    return jax.lax.while_loop(cond, lambda s: _window(p, s, opts), st)


def _dispatch_capped(p, opts, st):
    """Host seam for the auto-chunk loop (monkeypatchable in tests to
    observe dispatch granularity)."""
    return _solve_capped_jit(p, opts, st)


def solve_fixed(p: BoxQP, n_windows: int, opts: PDHGOptions,
                state: PDHGState) -> PDHGState:
    """Fixed budget: n_windows restart windows, no early exit.  This is
    the inner solver for PH hot loops (inexact subproblem solves with
    warm starts), where a static iteration count keeps the whole PH step
    a single compiled program."""
    st = dataclasses.replace(
        state,
        x_sum=jnp.zeros_like(state.x), y_sum=jnp.zeros_like(state.y),
        x_anchor=state.x, y_anchor=state.y,
        nwin=jnp.zeros_like(state.nwin),
        restart_score=jnp.full(state.omega.shape, jnp.inf, state.x.dtype),
        done=jnp.zeros(state.omega.shape, bool),
        status=jnp.zeros_like(state.status),
    )
    if opts.telemetry and st.counters is None:
        st = dataclasses.replace(
            st, counters=_init_counters(st.omega.shape, st.x.dtype, opts))
    return _solve_fixed_jit(p, n_windows, opts, st)


@partial(jax.jit, static_argnames=("n_windows", "opts"))
def _solve_fixed_jit(p: BoxQP, n_windows: int, opts: PDHGOptions,
                     st: PDHGState) -> PDHGState:
    """Fixed-budget window loop, jitted for the same reason as
    _solve_loop_jit: an eager fori_loop bakes QP values into the jaxpr
    and recompiles per call (PH hot loops call this inside their own
    jit, where the nested jit inlines — but host-level callers, e.g. a
    spoke's first warm-up solve, used to pay one silent backend
    compile per distinct QP)."""
    return jax.lax.fori_loop(0, n_windows,
                             lambda _, s: _window(p, s, opts), st)


solve_batch = solve  # batching is implicit via leading axes
