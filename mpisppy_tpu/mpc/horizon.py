###############################################################################
# Declarative rolling horizons (ISSUE 19 tentpole, piece 1; docs/mpc.md).
#
# A HorizonSpec is the WHOLE receding-horizon contract as data: how wide
# the decision window is, how far it advances per step, how the previous
# step's warm plane rolls forward (a ShiftPlan), and which argv solves
# one window — so RollingDriver (driver.py) and the serve stream
# (stream.py) share one definition instead of two hand-rolled loops.
#
# Per-step DATA shift is the model's job, keyed by one extra CLI flag
# (`--uc-mpc-step k` / `--ccopf-mpc-step k`): the model hooks re-key
# every stochastic draw through fold_in(base, step) (uc AR(1) demand,
# scengen's step re-key; ccopf branch multipliers) and roll the
# deterministic data (uc demand profile; ccopf load drift) by
# stride*step, so step k's window is bit-reproducible from
# {base_seed, k} alone — the property stream.py's preemption resume
# leans on.
###############################################################################
from __future__ import annotations

import dataclasses

from mpisppy_tpu.mpc.shift import ShiftPlan, ccopf_plan, uc_plan


@dataclasses.dataclass(frozen=True)
class HorizonSpec:
    """One rolling horizon, declaratively.

    window:     decision slots per solve along the rolled axis (hours
                for uc, stages for ccopf).
    stride:     slots the window advances per step.
    plan:       how W/x̄/x roll forward between steps (shift.py).
    base_argv:  the generic_cylinders argv solving ONE window (module,
                scale, recipe, rho policy — everything but the step).
    step_flag:  the model's step flag; step_argv(k) appends it, and the
                model hook shifts data + re-keys sampling from k.
    """

    name: str
    model: str
    window: int
    stride: int
    plan: ShiftPlan
    base_argv: tuple
    step_flag: str
    gap_target: float = 0.01
    max_step_iterations: int = 200

    def __post_init__(self):
        if self.window < 1 or not (0 < self.stride <= self.window):
            raise ValueError(
                f"bad horizon: window={self.window} stride={self.stride}")
        object.__setattr__(self, "base_argv", tuple(self.base_argv))

    def step_argv(self, step: int) -> list:
        """The argv solving window `step` (absolute, 0-based)."""
        if step < 0:
            raise ValueError(f"step {step} must be >= 0")
        return list(self.base_argv) + [self.step_flag, str(step)]


def _recipe_argv(module: str, num_scens: int, gap_target: float,
                 max_iterations: int) -> list:
    """The shared per-window solve recipe — the serve session recipe
    (serve/engine.session_argv) minus the model args."""
    return ["--module-name", module,
            "--num-scens", str(num_scens),
            "--fused-wheel", "--lagrangian", "--xhatxbar",
            "--rel-gap", str(gap_target),
            "--max-iterations", str(max_iterations),
            "--flight-recorder", "false"]


def uc_horizon(n_gens: int = 3, n_hours: int = 24, stride: int = 1,
               num_scens: int = 3, gap_target: float = 0.01,
               max_step_iterations: int = 200,
               extra_args: tuple = ()) -> HorizonSpec:
    """The flagship rolling horizon (ROADMAP item 3): a `n_hours`-hour
    unit-commitment window advancing `stride` hour(s) per step, AR(1)
    demand re-keyed per step via fold_in(base, step) (models/uc.py
    mpc_instance / _mpc_demand)."""
    argv = _recipe_argv("mpisppy_tpu.models.uc", num_scens, gap_target,
                        max_step_iterations)
    argv += ["--uc-n-gens", str(n_gens), "--uc-n-hours", str(n_hours),
             "--slammax", "--sensi-rho",
             "--uc-mpc-stride", str(stride)]
    argv += list(extra_args)
    return HorizonSpec(
        name=f"uc-{n_gens}g{n_hours}h-s{stride}", model="uc",
        window=int(n_hours), stride=int(stride),
        plan=uc_plan(n_gens, n_hours, stride),
        base_argv=tuple(argv), step_flag="--uc-mpc-step",
        gap_target=float(gap_target),
        max_step_iterations=int(max_step_iterations))


def ccopf_horizon(soc: bool = True, gap_target: float = 0.01,
                  max_step_iterations: int = 200,
                  extra_args: tuple = ()) -> HorizonSpec:
    """Rolling dispatch on the 3-stage OPF tree (--soc by default: the
    conic branch-flow relaxation): each step promotes the old stage-2
    setpoints to stage 1 and re-keys the branch multipliers + drifts
    the load (models/ccopf.py mpc hooks).  The window is the 2 nonant
    stages; the stride is one decision epoch."""
    from mpisppy_tpu.models import ccopf as ccopf_mod
    ng = len(ccopf_mod.grid_instance()["gens"])
    # 9 scenarios = the default (3, 3) tree's leaves
    argv = _recipe_argv("mpisppy_tpu.models.ccopf", 9, gap_target,
                        max_step_iterations)
    if soc:
        argv += ["--soc"]
    argv += list(extra_args)
    return HorizonSpec(
        name=f"ccopf-{'soc' if soc else 'dc'}", model="ccopf",
        window=2, stride=1, plan=ccopf_plan(ng),
        base_argv=tuple(argv), step_flag="--ccopf-mpc-step",
        gap_target=float(gap_target),
        max_step_iterations=int(max_step_iterations))


def horizon_for(spec) -> HorizonSpec:
    """The serve bridge: a streaming SubmitRequest (spec.mpc_steps > 0)
    to its HorizonSpec.  The session's model args ride along as
    extra_args so clients tune scale the same way non-streaming
    sessions do; uc window geometry is read back out of them because
    the ShiftPlan must match the solved window exactly."""
    args = list(spec.args)

    def _flag(name: str, default: int) -> int:
        val = default
        for i, a in enumerate(args):
            if a == name and i + 1 < len(args):
                val = int(args[i + 1])
            elif a.startswith(name + "="):
                val = int(a.split("=", 1)[1])
        return val

    def _without(name: str) -> tuple:
        """args minus a value-taking flag (both spellings) — the
        driver owns the step counter; a stray client copy would
        shadow every step with one frozen window."""
        out, skip = [], False
        for i, a in enumerate(args):
            if skip:
                skip = False
                continue
            if a == name:
                skip = i + 1 < len(args)
                continue
            if a.startswith(name + "="):
                continue
            out.append(a)
        return tuple(out)

    if spec.model == "uc":
        # serve default stays interactive-sized (the _MODEL_ARGS 3g/6h
        # session scale); a client asking for the flagship 24 h horizon
        # passes --uc-n-hours 24 in spec.args
        return uc_horizon(
            n_gens=_flag("--uc-n-gens", 3),
            n_hours=_flag("--uc-n-hours", 6),
            stride=_flag("--uc-mpc-stride", 1),
            num_scens=spec.num_scens, gap_target=spec.gap_target,
            max_step_iterations=spec.max_iterations,
            extra_args=_without("--uc-mpc-step"))
    if spec.model == "ccopf":
        return ccopf_horizon(
            soc="--soc" in args, gap_target=spec.gap_target,
            max_step_iterations=spec.max_iterations,
            extra_args=tuple(a for a in _without("--ccopf-mpc-step")
                             if a != "--soc"))
    raise ValueError(
        f"model {spec.model!r} has no rolling-horizon hook "
        "(want uc or ccopf)")
